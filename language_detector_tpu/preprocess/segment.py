"""Host-side script segmentation: UTF-8 text -> per-script letter spans.

TPU-first split of responsibilities: everything byte-level and inherently
sequential (codepoint decode, letters-vs-rest classification, script runs,
lowercasing, whitespace collapsing) runs on the host; the output spans are
clean " letters letters " byte buffers ready for vectorized n-gram hashing
and device scoring.

Behavioral contract follows the reference scanner
(getonescriptspan.cc:799 GetOneScriptSpan / :1033 LowerScriptSpan /
:1059 GetOneScriptSpanLower): spans contain lowercased letters/marks of a
single script, non-letter runs collapsed to one space, with a leading space
and trailing "   \\0"; spans are capped at ~40KB.

Classification and lowercasing use the per-codepoint tables extracted from
the reference's UTF-8 DFAs (utf8prop_lettermarkscriptnum.h,
utf8repl_lettermarklower.h), so letter/script/case decisions are identical.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache

import numpy as np

from ..tables import ScoringTables, load_tables

# kMaxScriptBytes = kMaxScriptBuffer - 32 = 40928 (getonescriptspan.h:29-32);
# the letter loop hard-stops there, the outer loop soft-stops a word earlier.
MAX_SPAN_PUT_BYTES = 40960 - 32
SOFT_SPAN_PUT_BYTES = MAX_SPAN_PUT_BYTES - 32  # kWithinScriptTail
# Buffer tail: " \x20\x20\x20\x00" plus slack so 32-bit gram loads with up to
# 20-byte group offsets never run off the end (hashing.py contract).
_TAIL_PAD = 32


def utf8_len_of_cps(cps) -> np.ndarray:
    """UTF-8 encoded byte length per codepoint (shared across preprocess)."""
    cps = np.asarray(cps)
    return np.where(cps < 0x80, 1,
                    np.where(cps < 0x800, 2,
                             np.where(cps < 0x10000, 3, 4)))


@dataclasses.dataclass
class ScriptSpan:
    """One same-script letters-only span (reference LangSpan, langspan.h)."""

    buf: np.ndarray        # uint8 bytes: b' ' + text + b'   \0' + pad
    text_bytes: int        # length counted like the reference: 1 + letters
    ulscript: int          # ULScript id
    cps: np.ndarray        # decoded codepoints of buf[:text_bytes+1]
    # source char index per buffer byte [text_bytes + 1]: maps span-buffer
    # offsets back to the segment_text input (the per-range results
    # equivalent of the reference's composed OffsetMaps, offsetmap.cc)
    src_idx: np.ndarray | None = None

    @property
    def text(self) -> bytes:
        return self.buf[:self.text_bytes].tobytes()


@lru_cache(maxsize=1)
def _lower_table() -> np.ndarray:
    """Full codepoint -> lowercase-codepoint map (identity unless mapped)."""
    t = load_tables()
    lower = np.arange(0x110000, dtype=np.uint32)
    lower[t.lower_pairs[:, 0]] = t.lower_pairs[:, 1]
    return lower


def _decode_utf32(text: str) -> np.ndarray:
    # surrogatepass: lone surrogates (e.g. from surrogatepass-decoded
    # byte input) must detect as non-letters, not crash — the native
    # packer round-trips them through UTF-8 the same way
    return np.frombuffer(text.encode("utf-32-le", "surrogatepass"),
                         dtype=np.uint32)


def segment_text(text: str,
                 tables: ScoringTables | None = None,
                 is_plain_text: bool = True) -> list[ScriptSpan]:
    """Split text into per-script spans of lowercased letters.

    is_plain_text=False first strips HTML tags and expands entities
    (preprocess/html.py), the separated-concerns equivalent of the
    reference scanner's inline tag state machine (getonescriptspan.cc
    :150-196, :393-480).

    (The reference computes a 160KB textlimit, compact_lang_det_impl.cc:1811,
    but never consults it in this version; the whole document is scanned.)
    """
    tables = tables or load_tables()
    if not is_plain_text:
        from .html import clean_html
        text, _ = clean_html(text, tables)
    cps = _decode_utf32(text)
    if len(cps) == 0:
        return []

    ULSCRIPT_INHERITED = 40
    capped = np.minimum(cps, 0x10FFFF)
    script = tables.script_of_cp[capped].tolist()
    lower_cps = _lower_table()[capped].tolist()
    # Original-case UTF-8 byte length per codepoint: the reference scanner's
    # buffer-size accounting runs before lowercasing.
    u8len = utf8_len_of_cps(capped).tolist()
    n = len(cps)

    # Cumulative raw byte offsets, for the near-end soft-limit rule
    byte_before = [0]
    for l in u8len:
        byte_before.append(byte_before[-1] + l)
    total_bytes = byte_before[-1]

    spans: list[ScriptSpan] = []
    i = 0
    while i < n:
        # Near the end of input, split the last two fragments evenly instead
        # of leaving a runt (getonescriptspan.cc:814-819).
        remaining = total_bytes - byte_before[i]
        soft_limit = SOFT_SPAN_PUT_BYTES
        if MAX_SPAN_PUT_BYTES <= remaining < 2 * MAX_SPAN_PUT_BYTES:
            soft_limit = remaining // 2
        # SkipToFrontOfSpan: advance to the first letter; its script (even
        # Inherited) names the span (getonescriptspan.cc:592-642, :855).
        while i < n and script[i] == 0:
            i += 1
        if i >= n:
            break
        spanscript = script[i]
        cur: list[int] = []
        cur_src: list[int] = []
        put = 1  # leading space, counted like the reference's put cursor

        # Alternate letter runs and non-letter runs (single space each)
        # until a letter of a genuinely different script, a full buffer, or
        # end of input (getonescriptspan.cc:858-1000).
        while i < n:
            # --- letter run ---
            while i < n:
                sc = script[i]
                if sc == 0:
                    break  # non-letter ends the run
                if sc != spanscript and sc != ULSCRIPT_INHERITED:
                    # Allow one embedded foreign letter when the following
                    # character is Common or back in-script
                    # (getonescriptspan.cc:900-930).
                    sc2 = script[i + 1] if i + 1 < n else 0
                    if sc2 != 0 and sc2 != spanscript:
                        break  # genuine script change: span ends here
                cur.append(lower_cps[i])
                cur_src.append(i)
                put += u8len[i]
                i += 1
                if put >= MAX_SPAN_PUT_BYTES:
                    break  # buffer full (truncated span)
            # --- non-letter run -> single separating space ---
            cur.append(0x20)
            cur_src.append(min(i, n - 1))
            put += 1
            while i < n and script[i] == 0:
                i += 1
            if i >= n:
                break
            if script[i] != spanscript and script[i] != ULSCRIPT_INHERITED:
                break  # next letter belongs to a different span
            if put >= soft_limit:
                break  # almost-full buffer: stop at this word boundary

        if len(cur) > 1:
            spans.append(_build_span(cur, spanscript, cur_src))
    return spans


def _build_span(span_cps: list[int], ulscript: int,
                src: list[int] | None = None) -> ScriptSpan:
    cps = np.array([0x20] + span_cps, dtype=np.uint32)
    text = cps.tobytes().decode("utf-32-le", "surrogatepass") \
        .encode("utf-8", "surrogatepass")
    buf = np.zeros(len(text) + _TAIL_PAD, dtype=np.uint8)
    buf[:len(text)] = np.frombuffer(text, dtype=np.uint8)
    buf[len(text):len(text) + 3] = 0x20  # trailing "   " then NULs
    src_idx = None
    if src is not None:
        # span-buffer byte -> source char: repeat each cp's source index
        # by its encoded length (leading space inherits the first letter)
        lens = utf8_len_of_cps(cps).astype(np.int64)
        per_cp = np.array([src[0] if src else 0] + src, dtype=np.int32)
        src_idx = np.repeat(per_cp, lens)
        src_idx = np.concatenate([src_idx, src_idx[-1:]])
    # text_bytes counts the leading space + letters (reference convention:
    # scriptspan.text[0]==' ' and text[text_bytes]==' ').
    return ScriptSpan(buf=buf, text_bytes=len(text), ulscript=int(ulscript),
                      cps=np.concatenate([cps, [0x20]]).astype(np.uint32),
                      src_idx=src_idx)
