"""Lock-discipline analyzer: owned attributes stay under their lock.

Consumes the declared ownership map (ownership.py). For each mapped
class, every `self.<attr>` touch of an owned attribute must happen
lexically inside `with self.<lock>:` — except in `__init__` (no other
thread can hold a reference yet), in declared held_methods (private
helpers of locked sections), or for attributes documented lock-free.
Cross-object reads through declared aliases (`self.ladder.level` from
AdmissionController) are checked against the aliased class's map — that
shape is exactly the torn-read bug class stats() used to have.

The map itself is verified against the code: a declared lock, owned
attribute, held method, alias, or lock-free entry that no longer exists
in the class is a violation (stale documentation fails, both
directions).
"""
from __future__ import annotations

import ast
from pathlib import Path

from .base import Violation, apply_suppressions, load_source, repo_root
from .ownership import LOCK_OWNERSHIP

RULE = "lock-discipline"


def _is_self_attr(node, attr: str | None = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking `with self.<lock>` nesting."""

    def __init__(self, cls_name, spec, alias_specs, rel, out,
                 assume_locked: bool):
        self.cls = cls_name
        self.spec = spec
        self.alias_specs = alias_specs  # attr -> ClassLocks of aliased
        self.rel = rel
        self.out = out
        self.depth = 1 if assume_locked else 0
        self.seen_attrs: set = set()

    def visit_With(self, node):
        locked = any(
            _is_self_attr(item.context_expr, self.spec.lock)
            for item in node.items) if self.spec.lock else False
        if locked:
            self.depth += 1
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self.depth -= 1

    def visit_Attribute(self, node):
        if _is_self_attr(node):
            self.seen_attrs.add(node.attr)
            if node.attr in self.spec.attrs and self.depth == 0 \
                    and node.attr not in self.spec.lockfree:
                self.out.append(Violation(
                    RULE, self.rel, node.lineno,
                    f"{self.cls}.{node.attr} is owned by "
                    f"{self.cls}.{self.spec.lock} but touched outside "
                    f"`with self.{self.spec.lock}`"))
        # cross-object: self.<alias>.<owned attr of aliased class>
        if isinstance(node.value, ast.Attribute) \
                and _is_self_attr(node.value) \
                and node.value.attr in self.alias_specs:
            other = self.alias_specs[node.value.attr]
            if node.attr in other.attrs \
                    and node.attr not in other.lockfree:
                self.out.append(Violation(
                    RULE, self.rel, node.lineno,
                    f"self.{node.value.attr}.{node.attr} reads state "
                    f"owned by the aliased object's own lock — use a "
                    f"locked accessor (e.g. snapshot()) instead"))
        self.generic_visit(node)


def _check_class(cls: ast.ClassDef, spec, all_specs, rel,
                 violations) -> None:
    alias_specs = {attr: all_specs[cname]
                   for attr, cname in spec.aliases.items()
                   if cname in all_specs}
    seen: set = set()
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name == "__init__":
            # collect attribute existence only; writes are exempt
            for node in ast.walk(item):
                if isinstance(node, ast.Attribute) \
                        and _is_self_attr(node):
                    seen.add(node.attr)
            continue
        mc = _MethodChecker(
            cls.name, spec, alias_specs, rel, violations,
            assume_locked=item.name in spec.held_methods)
        for stmt in item.body:
            mc.visit(stmt)
        seen |= mc.seen_attrs
    # stale-map detection: every declared name must still exist
    line = cls.lineno
    if spec.lock and spec.lock not in seen:
        violations.append(Violation(
            RULE, rel, line,
            f"ownership map declares lock {cls.name}.{spec.lock} "
            f"which the class never defines (stale map entry)"))
    method_names = {i.name for i in cls.body
                    if isinstance(i, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
    for a in sorted(spec.attrs):
        if a not in seen:
            violations.append(Violation(
                RULE, rel, line,
                f"ownership map declares owned attribute "
                f"{cls.name}.{a} which the class never touches "
                f"(stale map entry)"))
    for a in sorted(spec.lockfree):
        if a not in seen:
            violations.append(Violation(
                RULE, rel, line,
                f"ownership map documents lock-free attribute "
                f"{cls.name}.{a} which the class never touches "
                f"(stale map entry)"))
    for m in sorted(spec.held_methods):
        if m not in method_names:
            violations.append(Violation(
                RULE, rel, line,
                f"ownership map declares held method {cls.name}.{m} "
                f"which does not exist (stale map entry)"))
    for a in sorted(spec.aliases):
        if a not in seen:
            violations.append(Violation(
                RULE, rel, line,
                f"ownership map declares alias {cls.name}.{a} "
                f"which the class never touches (stale map entry)"))


def check(root: Path | None = None, ownership: dict | None = None):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    ownership = LOCK_OWNERSHIP if ownership is None else ownership
    violations: list = []
    n_suppressed = 0
    for rel, classes in sorted(ownership.items()):
        path = root / rel
        if not path.exists():
            violations.append(Violation(
                RULE, rel, 1, "ownership map names a file that does "
                              "not exist (stale map entry)"))
            continue
        sf = load_source(path, root)
        file_violations: list = []
        found: set = set()
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef) and node.name in classes:
                found.add(node.name)
                _check_class(node, classes[node.name], classes,
                             sf.rel, file_violations)
        for cname in sorted(set(classes) - found):
            file_violations.append(Violation(
                RULE, sf.rel, 1,
                f"ownership map names class {cname} which does not "
                f"exist in this file (stale map entry)"))
        kept, ns = apply_suppressions(sf, file_violations)
        violations.extend(kept)
        n_suppressed += ns
    return violations, n_suppressed
