"""Fixture: legal knob usage — a declared knob through the registry,
and a reasoned suppression for an environment passthrough."""
import os


def f():
    return knobs.get_int("LDT_SLOW_TRACE_RING")


def use_time_mutable_read():
    # mutable knobs are fine when read inside a function body: every
    # call observes the current override generation
    return knobs.get_int("LDT_MAX_INFLIGHT")


def passthrough():
    return {**os.environ}  # ldt-lint: disable=knob-direct-env -- fixture: whole-environment passthrough, not a config read
