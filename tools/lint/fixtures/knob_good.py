"""Fixture: legal knob usage — a declared knob through the registry,
and a reasoned suppression for an environment passthrough."""
import os


def f():
    return knobs.get_int("LDT_SLOW_TRACE_RING")


def passthrough():
    return {**os.environ}  # ldt-lint: disable=knob-direct-env -- fixture: whole-environment passthrough, not a config read
