"""C ABI seam: detect_language() / ldt_detect_batch_codes().

The reference's cgo boundary is one C function (wrapper.h:8,
wrapper.cc:7-16): `const char* detect_language(const char*)` returning a
static ISO-code string. A Go host links the library and calls it with no
Python in the loop. These tests call the exported symbols through a raw
ctypes handle — exactly the cgo calling convention — and assert the
C-side pipeline (pack -> C chunk scorer -> epilogue -> recursion) agrees
with the engine's device path on every document.
"""
from __future__ import annotations

import ctypes
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent))
from golden_data import golden_pairs  # noqa: E402

from language_detector_tpu import native  # noqa: E402
from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import load_tables  # noqa: E402

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


@pytest.fixture(scope="module")
def clib():
    """Raw CDLL handle, as a cgo host would hold it (tables initialized
    through the public init seam first)."""
    tables = load_tables()
    native.ensure_init(tables, registry)
    lib = ctypes.CDLL(str(Path(native.__file__).parent / "libldtpack.so"))
    lib.detect_language.restype = ctypes.c_char_p
    lib.detect_language.argtypes = [ctypes.c_char_p]
    lib.detect_language_n.restype = ctypes.c_char_p
    lib.detect_language_n.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    lib.ldt_detect_one_full.restype = ctypes.c_int32
    return lib


def test_detect_language_known_scripts(clib):
    cases = [
        ("Le gouvernement a annoncé de nouvelles mesures pour aider "
         "les familles", b"fr"),
        ("こんにちは世界。今日はとても良い天気ですね。散歩に行きましょう。",
         b"ja"),
        ("ภาษาไทยเป็นภาษาที่สวยงามและมีประวัติศาสตร์", b"th"),
        ("Η γρήγορη καφέ αλεπού πηδά πάνω από το τεμπέλικο σκυλί σήμερα "
         "το πρωί στον κήπο", b"el"),
        ("", b"un"),
    ]
    for text, want in cases:
        assert clib.detect_language(text.encode()) == want, text[:40]


def test_detect_language_matches_engine(clib):
    """C-side detection == the engine's device path on the golden suite
    plus squeeze/retry/edge constructions (the pipelines share the
    packer and epilogue; this pins the C chunk scorer against the device
    scorer)."""
    from language_detector_tpu.models.ngram import NgramBatchEngine
    pairs = golden_pairs()
    if not pairs:
        pytest.skip("reference snapshot unavailable")
    texts = [raw.decode("utf-8", errors="replace")
             for _, _, raw in pairs][::4]
    texts += [
        "buy cheap now " * 400,                  # squeeze pass
        "word " * 600,                           # squeeze + repeats
        texts[0][:150] + " " + texts[-1][:150],  # gate-failure retry
        "", "a", "123 !!!", "🎉🎊",
    ]
    eng = NgramBatchEngine()
    # force the device path: detect_codes routes tiny batches through
    # the very C pipeline under test (TINY_BATCH_C_PATH)
    assert len(texts) > eng.TINY_BATCH_C_PATH  # want == device, not C
    want = eng.detect_codes(texts)

    # single-doc entries: the NUL-terminated seam for clean docs, the
    # length-taking twin for docs carrying embedded NULs (wrapper.h:8
    # cannot represent those; detect_language_n can)
    for t, w in zip(texts, want):
        enc = t.encode("utf-8", "surrogatepass")
        if "\x00" in t:
            got = clib.detect_language_n(enc, len(enc))
        else:
            got = clib.detect_language(enc)
        assert got.decode() == w, t[:50]

    # batched entry
    enc = [t.encode("utf-8", "surrogatepass") for t in texts]
    bounds = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=bounds[1:])
    blob = np.frombuffer(b"".join(enc), np.uint8) if bounds[-1] \
        else np.zeros(1, np.uint8)
    blob = np.ascontiguousarray(blob)
    out = np.zeros(len(enc), np.int32)
    clib.ldt_detect_batch_codes(
        blob.ctypes.data_as(ctypes.c_void_p),
        bounds.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(len(enc)), ctypes.c_int32(4),
        out.ctypes.data_as(ctypes.c_void_p))
    got_codes = [registry.code(int(i)) for i in out]
    assert got_codes == want


def test_detect_language_n_embedded_nul(clib):
    """The length-taking entry scores PAST an embedded NUL; the
    NUL-terminated seam by definition truncates there. Both answers
    must match the scalar engine over the bytes each one sees."""
    from language_detector_tpu.engine_scalar import detect_scalar
    tables = load_tables()
    prefix = "こんにちは世界。"
    suffix = "今日はとても良い天気ですね。散歩に行きましょう。"
    text = prefix + "\x00" + suffix
    enc = text.encode()
    want_full = registry.code(detect_scalar(
        text, tables, registry, 0).summary_lang)
    want_prefix = registry.code(detect_scalar(
        prefix, tables, registry, 0).summary_lang)
    assert clib.detect_language_n(enc, len(enc)).decode() == want_full
    assert clib.detect_language(enc).decode() == want_prefix


def test_budget_overflow_doc_still_detects(clib):
    """A document overflowing the default per-doc budgets (here: >64
    direct-add spans from alternating scripts) must detect via the
    large budget tier, not answer "un" — the reference's wrapper never
    gives up for size (wrapper.cc:7-16). Parity against the scalar
    engine, which has no budgets at all."""
    from language_detector_tpu.engine_scalar import detect_scalar
    tables = load_tables()
    # 200 Greek spans split by Han spans: every span flips scripts, so
    # direct adds / chunks blow the tier-1 caps deterministically —
    # proven by the engine's packer marking the doc fallback under the
    # same default budgets
    text = ("καλημέρα κόσμε 世界 " * 200).strip()
    cb = native.pack_chunks_native([text], tables, registry,
                                   max_direct=64)
    assert cb.fallback[0], "doc no longer overflows tier-1 budgets; " \
                           "pick a harder construction"
    want = registry.code(detect_scalar(text, tables, registry,
                                       0).summary_lang)
    got = clib.detect_language(text.encode()).decode()
    assert got == want
    assert got != "un" or want == "un"

    # and through the full-row entry (the public detect() fast path)
    enc = text.encode()
    out = (ctypes.c_int64 * 14)()
    ok = clib.ldt_detect_one_full(enc, len(enc), out)
    assert ok == 1
    assert registry.code(int(out[0])) == want
