"""Admission control & graceful degradation for the serving stack.

PR 1 built batching, PR 2 built telemetry; this is the third subsystem
production TPU serving treats as first-class: deciding what work to
ACCEPT. Without it both fronts enqueue unboundedly — a traffic spike or
a slow XLA recompile turns into queue bloat, client-side timeouts, and
RSS-driven recycles instead of fast, explicit 429/503s. Four pieces,
shared by the sync and asyncio fronts through one controller per
DetectorService:

  bounded queues    per-request cost accounting (docs + byte-weighted
                    slot-demand estimate from the pack tier ladder)
                    against LDT_MAX_QUEUE_DOCS / LDT_MAX_QUEUE_BYTES /
                    LDT_MAX_INFLIGHT; past a bound the request sheds
                    with 429 and a Retry-After derived from the
                    telemetry registry's recent flush p95
  deadlines         X-LDT-Deadline-Ms (default LDT_DEFAULT_DEADLINE_MS)
                    rides the request trace into the batcher and the
                    engine scheduler; work already expired at dequeue
                    fails with DeadlineExceeded (the front answers 504)
                    instead of burning a flush, and near-deadline
                    batches skip the pipelined retry lane
  brownout ladder   a smoothed load signal (queue occupancy, optionally
                    flush p95) walks four levels with hysteresis:
                    0 healthy -> 1 skip-retry-lane -> 2 cache+scalar
                    only -> 3 shed all non-priority (X-LDT-Priority
                    requests keep being served)
  circuit breaker   consecutive device-flush failures or a stalled
                    dispatch (watchdog vs a multiple of compile-aware
                    expected latency) trip open and route detection to
                    the scalar engine; after a cooldown, half-open
                    probes recover

Everything exports through the PR 2 registry: ldt_admission_queue_docs
/ _queue_bytes / _inflight, ldt_brownout_level, ldt_breaker_state
(gauges in Metrics.render), ldt_shed_total{reason} and
ldt_deadline_expired_total (counters here), all surfaced in
/debug/vars. With no LDT_* overrides every limit is off and the
subsystem is a per-request constant-time no-op.
"""
from __future__ import annotations

import math
import time
from collections import deque

from .. import flightrec, knobs, telemetry
from ..locks import make_lock
from ..preprocess.pack import est_slot_demand

_mono = time.monotonic

# shed reasons, in the order they are checked; pre-touched as counter
# label values so every ldt_shed_total series renders from scrape one
SHED_REASONS = ("brownout", "tenant_docs", "tenant_bytes",
                "queue_docs", "queue_bytes", "inflight")

# tenant attributed to requests that carry no X-LDT-Tenant header; the
# per-tenant quotas and the WFQ scheduler treat it as a normal tenant
DEFAULT_TENANT = "default"

BROWNOUT_LEVEL_NAMES = ("healthy", "skip_retry", "degraded", "shed")

BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN = 0, 1, 2
BREAKER_STATE_NAMES = ("closed", "half_open", "open")

# prior for expected flush latency before the stage histograms have any
# observations: the tunneled backend's fixed dispatch cost (docs/PERF.md)
DEFAULT_FLUSH_MS = 95.0


class DeadlineExceeded(Exception):
    """The request's deadline passed before its batch dispatched."""


class Deadline:
    """One request's absolute deadline on the monotonic clock. Carried
    on telemetry.Trace.deadline through the batcher into the engine
    scheduler — a plain float wrapper so every layer shares one clock
    and one expiry rule."""

    __slots__ = ("t_end",)

    def __init__(self, budget_ms: float, now: float | None = None):
        self.t_end = (now if now is not None else _mono()) \
            + budget_ms / 1e3

    def remaining_ms(self, now: float | None = None) -> float:
        return (self.t_end - (now if now is not None else _mono())) * 1e3

    def expired(self, now: float | None = None) -> bool:
        return (now if now is not None else _mono()) >= self.t_end


def note_deadline_expired(n: int = 1):
    """Both batchers report dequeue-time expiries here (the controller
    is not plumbed into the batcher; the shared registry is)."""
    telemetry.REGISTRY.counter_inc("ldt_deadline_expired_total", n)


def expected_flush_ms(include_compiles: bool = False,
                      default: float = DEFAULT_FLUSH_MS) -> float:
    """Recent p95 of one engine flush, read from the stage histograms
    (dispatch on the device path, scalar_detect / c_path otherwise).
    include_compiles folds in the compile-time p95 for watchdog use —
    a dispatch that recompiles legitimately takes many times the warm
    latency and must not read as a stall. Peeks only: estimating load
    must not create empty histogram series in the exposition."""
    reg = telemetry.REGISTRY
    p95 = None
    for stage in ("dispatch", "scalar_detect", "c_path"):
        h = reg.histogram_peek("ldt_stage_latency_ms", stage=stage)
        if h is not None:
            p = h.percentile(95)
            if p:
                p95 = p
                break
    if p95 is None:
        p95 = default
    if include_compiles:
        c = reg.percentile_across("ldt_xla_compile_ms", 95)
        if c:
            p95 = max(p95, c)
    return p95


def request_cost(texts: list) -> int:
    """Byte-weighted admission cost of a request: 4 bytes per estimated
    packer slot (the tier ladder's est_slot_demand is ~len/4 plus a
    fixed per-doc overhead, so this tracks text bytes plus a constant
    per document — cheap, monotone, and the same signal the scheduler
    buckets on)."""
    return 4 * sum(est_slot_demand(t) for t in texts)


def retry_after_sec(queue_docs: int, flush_docs: int = 16384,
                    cap_sec: int = 30) -> int:
    """Retry-After for a shed response: how long until the backlog in
    front of the caller likely drains — (flushes queued + 1) x recent
    flush p95, clamped to [1, cap]."""
    flushes = 1 + queue_docs // max(flush_docs, 1)
    sec = math.ceil(flushes * expected_flush_ms() / 1e3)
    return max(1, min(int(sec), cap_sec))


class AdmissionConfig:
    """Env-derived knobs, all optional (docs/OBSERVABILITY.md table).
    Bounds are None when off; with everything off the controller admits
    unconditionally and the ladder never leaves healthy."""

    def __init__(self, max_queue_docs: int | None = None,
                 max_queue_bytes: int | None = None,
                 max_inflight: int | None = None,
                 default_deadline_ms: float | None = None,
                 flush_docs: int = 16384,
                 brownout_alpha: float = 0.3,
                 brownout_enter: tuple = (0.60, 0.80, 0.95),
                 brownout_exit: tuple = (0.45, 0.65, 0.80),
                 brownout_p95_ms: float | None = None,
                 breaker_failures: int = 5,
                 breaker_cooldown_sec: float = 10.0,
                 breaker_stall_factor: float = 10.0,
                 breaker_stall_min_ms: float = 2000.0,
                 tenant_quota_docs: int | None = None,
                 tenant_quota_bytes: int | None = None):
        self.max_queue_docs = max_queue_docs
        self.max_queue_bytes = max_queue_bytes
        self.max_inflight = max_inflight
        self.tenant_quota_docs = tenant_quota_docs
        self.tenant_quota_bytes = tenant_quota_bytes
        self.default_deadline_ms = default_deadline_ms
        self.flush_docs = flush_docs
        self.brownout_alpha = brownout_alpha
        self.brownout_enter = brownout_enter
        self.brownout_exit = brownout_exit
        self.brownout_p95_ms = brownout_p95_ms
        self.breaker_failures = breaker_failures
        self.breaker_cooldown_sec = breaker_cooldown_sec
        self.breaker_stall_factor = breaker_stall_factor
        self.breaker_stall_min_ms = breaker_stall_min_ms

    @classmethod
    def from_env(cls) -> "AdmissionConfig":
        """All knobs through the central registry (knobs.py): bound
        knobs answer None for unset/non-positive (feature off), scalar
        knobs fall back to their declared defaults on mistype."""
        return cls(
            max_queue_docs=knobs.get_int("LDT_MAX_QUEUE_DOCS"),
            max_queue_bytes=knobs.get_int("LDT_MAX_QUEUE_BYTES"),
            max_inflight=knobs.get_int("LDT_MAX_INFLIGHT"),
            default_deadline_ms=knobs.get_float(
                "LDT_DEFAULT_DEADLINE_MS"),
            brownout_alpha=knobs.get_float("LDT_BROWNOUT_ALPHA"),
            brownout_enter=knobs.get_levels("LDT_BROWNOUT_ENTER"),
            brownout_exit=knobs.get_levels("LDT_BROWNOUT_EXIT"),
            brownout_p95_ms=knobs.get_float("LDT_BROWNOUT_P95_MS"),
            breaker_failures=knobs.get_int("LDT_BREAKER_FAILURES"),
            breaker_cooldown_sec=knobs.get_float(
                "LDT_BREAKER_COOLDOWN_SEC"),
            breaker_stall_factor=knobs.get_float(
                "LDT_BREAKER_STALL_FACTOR"),
            breaker_stall_min_ms=knobs.get_float(
                "LDT_BREAKER_STALL_MIN_MS"),
            tenant_quota_docs=knobs.get_int("LDT_TENANT_QUOTA_DOCS"),
            tenant_quota_bytes=knobs.get_int("LDT_TENANT_QUOTA_BYTES"),
        )


class BrownoutLadder:
    """Hysteretic degradation levels over an EWMA'd load signal.

    Ascend from level L when the smoothed load reaches enter[L];
    descend from L when it falls below exit[L-1]. exit thresholds sit
    strictly below their enter twins, so a load hovering at a boundary
    cannot flap the service between serving modes — it has to genuinely
    recede before the ladder steps back down."""

    def __init__(self, enter: tuple = (0.60, 0.80, 0.95),
                 exit: tuple = (0.45, 0.65, 0.80),
                 alpha: float = 0.3):
        n = len(BROWNOUT_LEVEL_NAMES) - 1
        if len(enter) != n or len(exit) != n:
            raise ValueError(f"need {n} enter and exit thresholds")
        if any(x >= e for x, e in zip(exit, enter)):
            raise ValueError("exit thresholds must sit below enter "
                             "thresholds (hysteresis)")
        self.enter = tuple(enter)
        self.exit = tuple(exit)
        self.alpha = alpha
        self.ema = 0.0
        self.level = 0
        self._lock = make_lock("admission.ladder")

    def observe(self, load: float) -> int:
        """Fold one load sample in and return the (possibly stepped)
        level. Called on every admit/release, so single samples move the
        EMA by alpha — spikes must persist to climb the ladder."""
        with self._lock:
            prev = self.level
            self.ema += self.alpha * (load - self.ema)
            top = len(self.enter)
            while self.level < top and \
                    self.ema >= self.enter[self.level]:
                self.level += 1
            while self.level > 0 and \
                    self.ema < self.exit[self.level - 1]:
                self.level -= 1
            level = self.level
        if level != prev:  # recorder event outside the hot-path lock
            flightrec.emit_event("brownout_level", level=level,
                                 prev=prev)
        return level

    def retune(self, alpha: float | None = None) -> None:
        """Runtime retune from the config plane (LDT_BROWNOUT_ALPHA is
        a mutable knob); the level and EMA carry over so a retune never
        resets an in-progress brownout."""
        with self._lock:
            if alpha is not None and 0.0 < alpha <= 1.0:
                self.alpha = alpha

    def snapshot(self) -> tuple:
        """(level, ema) read under the ladder's own lock — stats
        reporters must not read the raw attributes (lock-discipline
        analyzer ownership: BrownoutLadder._lock owns level/ema)."""
        with self._lock:
            return self.level, self.ema


class CircuitBreaker:
    """Trip the device detect path to scalar on consecutive failures or
    stalls; recover through half-open probes.

    States: closed (all traffic to the device), open (all traffic to
    the scalar engine until the cooldown elapses), half-open (ONE probe
    allowed through; success closes, failure re-opens). A success whose
    wall time exceeds the stall watchdog counts as a failure — a device
    that answers in 30x its expected latency is down for serving
    purposes even if it eventually returns. The watchdog threshold is
    compile-aware: it reads the compile-time p95 so a legitimate
    recompile is not mistaken for a stall. clock is injectable for
    tests."""

    def __init__(self, failures: int = 5, cooldown_sec: float = 10.0,
                 stall_factor: float = 10.0,
                 stall_min_ms: float = 2000.0, clock=None):
        self.failures = max(int(failures), 1)
        self.cooldown_sec = cooldown_sec
        self.stall_factor = stall_factor
        self.stall_min_ms = stall_min_ms
        self._clock = clock or _mono
        self._lock = make_lock("admission.breaker")
        self._state = BREAKER_CLOSED
        self._consec = 0
        self._opened_at = 0.0
        self._probe_at: float | None = None
        self.trips = 0
        self.probes = 0
        self.failures_total = 0
        self.stalls_total = 0

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def stall_ms(self) -> float:
        """Current watchdog threshold: a flush slower than this counts
        as a failure."""
        return max(self.stall_min_ms,
                   self.stall_factor *
                   expected_flush_ms(include_compiles=True))

    def allow_device(self) -> bool:
        """Gate one detect call. closed: yes. open: no, until the
        cooldown converts to half-open and admits a probe. half-open:
        only if no probe is pending (or the pending probe itself looks
        wedged past the watchdog, in which case a fresh probe goes)."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            now = self._clock()
            if self._state == BREAKER_OPEN:
                if now - self._opened_at < self.cooldown_sec:
                    return False
                self._state = BREAKER_HALF_OPEN
                self._probe_at = now
                self.probes += 1
                flightrec.emit_event("breaker_state",
                                     state="half_open")
                return True
            # half-open with a probe already in flight
            if self._probe_at is not None and \
                    (now - self._probe_at) * 1e3 < self.stall_ms():
                return False
            self._probe_at = now
            self.probes += 1
            return True

    def record_success(self, elapsed_ms: float | None = None):
        if elapsed_ms is not None and elapsed_ms >= self.stall_ms():
            self.record_failure(stalled=True)
            return
        with self._lock:
            if self._state == BREAKER_OPEN:
                # a straggler success from a flush dispatched before the
                # trip must not close the breaker: OPEN only recovers
                # through the cooldown -> half-open probe path
                return
            self._consec = 0
            reclosed = self._state != BREAKER_CLOSED
            self._state = BREAKER_CLOSED
            self._probe_at = None
        if reclosed:
            flightrec.emit_event("breaker_state", state="closed")

    def record_failure(self, stalled: bool = False):
        with self._lock:
            self.failures_total += 1
            if stalled:
                self.stalls_total += 1
            self._consec += 1
            tripped = False
            if self._state == BREAKER_HALF_OPEN or \
                    self._consec >= self.failures:
                if self._state != BREAKER_OPEN:
                    self.trips += 1
                    tripped = True
                self._state = BREAKER_OPEN
                self._opened_at = self._clock()
                self._probe_at = None
                self._consec = 0
        if tripped:
            flightrec.emit_event("breaker_state", state="open",
                                 stalled=bool(stalled))

    def stats(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "state_name": BREAKER_STATE_NAMES[self._state],
                    "consecutive_failures": self._consec,
                    "failures_total": self.failures_total,
                    "stalls_total": self.stalls_total,
                    "trips": self.trips,
                    "probes": self.probes}


class Admit:
    """One try_admit verdict. shed=False tickets MUST be released (the
    fronts do it in a finally); shed=True carries the response the
    front should send."""

    __slots__ = ("shed", "status", "reason", "message", "retry_after",
                 "level", "degrade", "docs", "cost", "tenant", "probe")

    def __init__(self, shed, status, reason, message, retry_after,
                 level, degrade, docs, cost,
                 tenant: str = DEFAULT_TENANT, probe: bool = False):
        self.shed = shed
        self.status = status
        self.reason = reason
        self.message = message
        self.retry_after = retry_after
        self.level = level
        self.degrade = degrade
        self.docs = docs
        self.cost = cost
        self.tenant = tenant
        # probe vehicle (pool half-open probe through a full-shed
        # brownout): the fronts must serve it on the FULL device path —
        # degraded mode and no_retry would defeat the probe
        self.probe = probe


_SHED_MESSAGES = {
    "brownout": "server overloaded, shedding non-priority traffic",
    "tenant_docs": "tenant over quota: document quota exhausted",
    "tenant_bytes": "tenant over quota: byte quota exhausted",
    "queue_docs": "server overloaded: document queue full",
    "queue_bytes": "server overloaded: byte queue full",
    "inflight": "server overloaded: too many requests in flight",
}


class AdmissionController:
    """Per-service front door: cost-accounted bounds, the brownout
    ladder, and the device circuit breaker behind one try_admit/release
    pair. Thread-safe; the asyncio front calls it from the event loop
    (every operation is a few arithmetic ops under a lock)."""

    def __init__(self, config: AdmissionConfig | None = None):
        self.config = config or AdmissionConfig.from_env()
        # runtime-config staleness marker: the admission bounds are
        # mutable knobs (POST /configz), so try_admit re-derives the
        # config whenever the override version moved — one int compare
        # per admit while nothing changes
        self._config_version = knobs.overrides_version() \
            if config is None else None
        c = self.config
        self.ladder = BrownoutLadder(enter=c.brownout_enter,
                                     exit=c.brownout_exit,
                                     alpha=c.brownout_alpha)
        self.breaker = CircuitBreaker(
            failures=c.breaker_failures,
            cooldown_sec=c.breaker_cooldown_sec,
            stall_factor=c.breaker_stall_factor,
            stall_min_ms=c.breaker_stall_min_ms)
        self._lock = make_lock("admission.controller")
        # zero-arg provider returning the engine's DevicePool (or None);
        # a provider (not the pool itself) so a zero-downtime artifact
        # swap that rebuilds the engine is picked up automatically
        self.pool = None
        self.queue_docs = 0
        self.queue_bytes = 0
        self.inflight = 0
        # tenant -> [queued docs, queued byte cost]; entries drop when
        # a tenant fully drains, so the dict stays bounded by the set
        # of tenants with live work
        self.tenants: dict = {}
        self._shed = dict.fromkeys(SHED_REASONS, 0)
        # pre-touch the counter series so a scrape shows them at 0
        # before the first shed/expiry, not only after trouble starts
        for reason in SHED_REASONS:
            telemetry.REGISTRY.counter_inc("ldt_shed_total", 0,
                                           reason=reason)
        telemetry.REGISTRY.counter_inc("ldt_deadline_expired_total", 0)
        telemetry.REGISTRY.counter_inc("ldt_pool_probe_admits_total", 0)

    @classmethod
    def from_env(cls) -> "AdmissionController":
        # config=None, NOT cls(AdmissionConfig.from_env()): passing the
        # config explicitly marks it injected (tests), which pins
        # _config_version to None and detaches the controller from
        # runtime /configz overrides
        return cls()

    def attach_pool(self, provider) -> None:
        """Wire the device pool's capacity into the brownout ladder.
        provider: zero-arg callable returning the current DevicePool or
        None (pool disabled / scalar engine). Called once at service
        build; reads happen inside _occupancy under the controller
        lock."""
        self.pool = provider

    def _occupancy(self, docs: int = 0, nbytes: int = 0,
                   inflight: int = 0) -> float:
        """Load in [0, 1+]: the worst occupancy across the configured
        bounds, counting the candidate request, optionally maxed with
        flush p95 against its target. Unbounded axes contribute 0."""
        c = self.config
        occ = 0.0
        if c.max_queue_docs:
            occ = max(occ, (self.queue_docs + docs) / c.max_queue_docs)
        if c.max_queue_bytes:
            occ = max(occ,
                      (self.queue_bytes + nbytes) / c.max_queue_bytes)
        if c.max_inflight:
            occ = max(occ, (self.inflight + inflight) / c.max_inflight)
        if c.brownout_p95_ms:
            occ = max(occ, expected_flush_ms() / c.brownout_p95_ms)
        if self.pool is not None:
            pool = self.pool()
            if pool is not None:
                # lost dispatch capacity IS load: half the lanes
                # evicted reads as 0.6 (brownout level 1), a fully
                # evicted pool as 1.2 (level 3) — the ladder sheds
                # what the surviving lanes cannot carry
                occ = max(occ, pool.capacity_load())
        return occ

    def _pool_probe_due(self) -> bool:
        """Caller holds self._lock (same discipline as _occupancy's
        pool read)."""
        if self.pool is None:
            return False
        pool = self.pool()
        return pool is not None and pool.wants_probe()

    def _shed_out(self, reason: str, status: int, level: int,
                  docs: int, cost: int, tenant: str) -> Admit:
        self._shed[reason] += 1
        telemetry.REGISTRY.counter_inc("ldt_shed_total", reason=reason)
        telemetry.REGISTRY.counter_inc("ldt_tenant_shed_total",
                                       tenant=tenant, reason=reason)
        ra = retry_after_sec(self.queue_docs, self.config.flush_docs)
        return Admit(True, status, reason, _SHED_MESSAGES[reason], ra,
                     level, False, docs, cost, tenant)

    def _refresh_config(self) -> None:
        """Pick up runtime overrides of the mutable admission knobs
        (POST /configz): when the knobs override version moved, the
        config re-derives from the registry and the ladder retunes its
        alpha. Controllers built from an explicitly injected config
        (tests) never refresh."""
        v = self._config_version
        if v is None:
            return
        nv = knobs.overrides_version()
        if nv == v:
            return
        c = AdmissionConfig.from_env()
        with self._lock:
            self.config = c
            self._config_version = nv
        self.ladder.retune(alpha=c.brownout_alpha)

    def try_admit(self, texts: list, priority: bool = False,
                  tenant: str | None = None) -> Admit:
        """Admit or shed one request. Order: the brownout ladder sheds
        non-priority traffic first (503 — the service is degrading by
        policy), then the caller's per-tenant quota (429 — a hot tenant
        sheds on its own budget before it can fill the global queue),
        then the hard bounds shed anything over capacity (429 —
        priority included; a bound is a bound)."""
        self._refresh_config()
        docs = len(texts)
        cost = request_cost(texts)
        tenant = tenant or DEFAULT_TENANT
        c = self.config
        probe_vehicle = False
        with self._lock:
            level = self.ladder.observe(
                self._occupancy(docs, cost, 1))
            if level >= 3 and not priority:
                # full-shed exception: when the device pool owes a
                # half-open probe, this request is admitted as the
                # probe vehicle — probes are traffic-driven, so a
                # blanket shed would leave a fully evicted pool (load
                # 1.2 -> level 3) down forever (parallel/pool.py
                # wants_probe)
                if self._pool_probe_due():
                    probe_vehicle = True
                    telemetry.REGISTRY.counter_inc(
                        "ldt_pool_probe_admits_total")
                else:
                    return self._shed_out("brownout", 503, level, docs,
                                          cost, tenant)
            t_docs, t_bytes = self.tenants.get(tenant, (0, 0))
            if c.tenant_quota_docs is not None and \
                    t_docs + docs > c.tenant_quota_docs:
                return self._shed_out("tenant_docs", 429, level, docs,
                                      cost, tenant)
            if c.tenant_quota_bytes is not None and \
                    t_bytes + cost > c.tenant_quota_bytes:
                return self._shed_out("tenant_bytes", 429, level, docs,
                                      cost, tenant)
            if c.max_queue_docs is not None and \
                    self.queue_docs + docs > c.max_queue_docs:
                return self._shed_out("queue_docs", 429, level, docs,
                                      cost, tenant)
            if c.max_queue_bytes is not None and \
                    self.queue_bytes + cost > c.max_queue_bytes:
                return self._shed_out("queue_bytes", 429, level, docs,
                                      cost, tenant)
            if c.max_inflight is not None and \
                    self.inflight + 1 > c.max_inflight:
                return self._shed_out("inflight", 429, level, docs,
                                      cost, tenant)
            self.queue_docs += docs
            self.queue_bytes += cost
            self.inflight += 1
            self.tenants[tenant] = [t_docs + docs, t_bytes + cost]
            return Admit(False, 200, None, None, 0, level,
                         level >= 2 and not probe_vehicle, docs, cost,
                         tenant, probe=probe_vehicle)

    def release(self, admit: Admit):
        """Return an admitted request's cost (fronts call from a
        finally, so shed/error/success all balance). Feeds the ladder a
        decay sample so it steps back down as load drains."""
        if admit.shed:
            return
        with self._lock:
            self.queue_docs = max(self.queue_docs - admit.docs, 0)
            self.queue_bytes = max(self.queue_bytes - admit.cost, 0)
            self.inflight = max(self.inflight - 1, 0)
            entry = self.tenants.get(admit.tenant)
            if entry is not None:
                entry[0] = max(entry[0] - admit.docs, 0)
                entry[1] = max(entry[1] - admit.cost, 0)
                if entry[0] == 0 and entry[1] == 0:
                    del self.tenants[admit.tenant]
            self.ladder.observe(self._occupancy())

    def deadline_from_header(self, value) -> Deadline | None:
        """X-LDT-Deadline-Ms header (str/bytes/None) -> Deadline, using
        the configured default when absent/unparseable. None when no
        deadline applies. A non-positive budget is honored literally
        (already expired: the batcher sheds it at dequeue, 504)."""
        ms = None
        if value is not None:
            if isinstance(value, bytes):
                value = value.decode("latin-1", "replace")
            try:
                ms = float(value)
            except (TypeError, ValueError):
                ms = None
        if ms is None:
            ms = self.config.default_deadline_ms
        return None if ms is None else Deadline(ms)

    def stats(self) -> dict:
        """Live snapshot for Metrics.render gauges and /debug/vars."""
        c = self.config
        with self._lock:
            d = {"queue_docs": self.queue_docs,
                 "queue_bytes": self.queue_bytes,
                 "inflight": self.inflight,
                 "shed": dict(self._shed),
                 "tenants": {t: {"queue_docs": v[0],
                                 "queue_bytes": v[1]}
                             for t, v in self.tenants.items()}}
        # snapshot() reads under the LADDER's lock: the raw level/ema
        # attributes are owned by it, and an unlocked cross-object read
        # here could see a torn (level, ema) pair mid-observe
        level, ema = self.ladder.snapshot()
        d["brownout_level"] = level
        d["brownout_ema"] = round(ema, 4)
        d["breaker_state"] = self.breaker.state
        d["breaker"] = self.breaker.stats()
        d["deadline_expired"] = telemetry.REGISTRY.counter_value(
            "ldt_deadline_expired_total")
        d["limits"] = {"max_queue_docs": c.max_queue_docs,
                       "max_queue_bytes": c.max_queue_bytes,
                       "max_inflight": c.max_inflight,
                       "default_deadline_ms": c.default_deadline_ms,
                       "tenant_quota_docs": c.tenant_quota_docs,
                       "tenant_quota_bytes": c.tenant_quota_bytes}
        return d


def degraded_detect(texts: list, scalar_fn, cache=None, hints_key=None,
                    trace=None) -> list:
    """Brownout level-2 serving path: answer from the result cache
    where possible, run everything else through the scalar engine, and
    keep filling the cache — exact results (the scalar engine is the
    repo-wide equivalence oracle), bounded cost, zero batcher/device
    involvement. scalar_fn: texts -> codes (DetectorService.scalar_codes
    or the scalar detect closure)."""
    from .batcher import _MISS
    if cache is None:
        return scalar_fn(texts, trace=trace)
    vals = [cache.get((hints_key, t)) for t in texts]
    miss = [i for i, v in enumerate(vals) if v is _MISS]
    if miss:
        fresh = scalar_fn([texts[i] for i in miss], trace=trace)
        for i, v in zip(miss, fresh):
            vals[i] = v
            cache.put((hints_key, texts[i]), v, texts[i])
    return vals


def parse_tenant_weights(spec: str | None) -> dict:
    """LDT_TENANT_WEIGHTS "tenantA=4,tenantB=1" -> {tenant: weight}.
    Malformed or non-positive entries are dropped with a loud warning
    (the knobs.py mistype rule); unlisted tenants weigh 1."""
    import logging
    out: dict = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.partition("=")
        try:
            w = float(val) if sep else 1.0
        except ValueError:
            w = -1.0
        if not name.strip() or w <= 0:
            logging.getLogger(__name__).warning(
                "ignoring malformed LDT_TENANT_WEIGHTS entry %r", part)
            continue
        out[name.strip()] = w
    return out


class FairScheduler:
    """Deficit-weighted round robin over per-tenant FIFO lanes.

    The transport queue (queue.Queue / asyncio.Queue) stays the
    cross-task handoff; this is a dequeue-side stash owned by exactly
    one batcher collector (thread or task), so it needs no lock. Each
    scheduler round credits a tenant `quantum * weight` bytes of
    deficit; items pop while their byte cost fits, so when the backlog
    exceeds one flush a saturating tenant waits its turn instead of
    starving everyone else. Work within a lane stays FIFO."""

    def __init__(self, weights: dict, quantum: int = 65536):
        self.weights = dict(weights)
        self.quantum = max(int(quantum), 1)
        self._lanes: dict = {}          # tenant -> deque of items
        self._ring: deque = deque()     # active tenants, visit order
        self._deficit: dict = {}        # tenant -> accumulated bytes
        self.backlog = 0                # stashed docs across all lanes

    @classmethod
    def from_env(cls) -> "FairScheduler | None":
        """A scheduler when LDT_TENANT_WEIGHTS is set, else None (both
        batchers keep their strict-FIFO dequeue)."""
        weights = parse_tenant_weights(
            knobs.get_str("LDT_TENANT_WEIGHTS"))
        if not weights:
            return None
        return cls(weights,
                   knobs.get_int("LDT_WFQ_QUANTUM_BYTES") or 65536)

    @staticmethod
    def _tenant(item) -> str:
        # both batchers' items end (..., trace, future)
        return getattr(item[-2], "tenant", None) or DEFAULT_TENANT

    @staticmethod
    def _cost(item) -> int:
        return sum(len(t) for t in item[0]) + 1

    def push(self, item):
        tenant = self._tenant(item)
        lane = self._lanes.get(tenant)
        if lane is None:
            lane = self._lanes[tenant] = deque()
            self._ring.append(tenant)
            self._deficit.setdefault(tenant, 0.0)
        lane.append(item)
        self.backlog += len(item[0])

    def pop_batch(self, max_docs: int) -> list:
        """Dequeue up to max_docs documents' worth of items in DRR
        order. Always makes progress when lanes are non-empty: each
        ring visit adds a quantum, so any head item eventually fits."""
        out: list = []
        docs = 0
        while self._ring and docs < max_docs:
            tenant = self._ring[0]
            lane = self._lanes[tenant]
            self._deficit[tenant] += \
                self.quantum * self.weights.get(tenant, 1.0)
            while lane and docs < max_docs:
                cost = self._cost(lane[0])
                if cost > self._deficit[tenant] and out:
                    break
                item = lane.popleft()
                self._deficit[tenant] -= cost
                out.append(item)
                docs += len(item[0])
                self.backlog -= len(item[0])
            if not lane:
                del self._lanes[tenant]
                del self._deficit[tenant]
                self._ring.popleft()
            else:
                self._ring.rotate(-1)
        return out

    def drain_all(self) -> list:
        """Every stashed item, in lane order — close() uses this to
        fail stranded work instead of leaking its futures."""
        items = [it for lane in self._lanes.values() for it in lane]
        self._lanes.clear()
        self._ring.clear()
        self._deficit.clear()
        self.backlog = 0
        return items
