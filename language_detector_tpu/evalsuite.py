"""Vectorized on-device eval scorecard + the device reduction's oracle.

Three pieces of the accuracy plane live here (docs/ACCURACY.md):

  1. A bundled WiLI-style labeled corpus: a handcrafted multi-script
     seed bank (SEED_BANK) expanded deterministically into ~120 labeled
     documents by `generate_corpus` and checked in as
     data/eval_corpus.tsv ("code<TAB>text" lines, the same shape
     tools/eval_corpus.py streams). `corpus_pairs` loads the checked-in
     TSV when present and regenerates it bit-identically when not, so
     the corpus is reproducible from source alone.

  2. `oracle_score_chunks`: a pure-numpy, op-for-op mirror of the
     device chunk reduction (ops/score.py score_chunks_impl), INCLUDING
     the LDT_HINTS per-doc prior term — the "scalar-oracle extension"
     the hint-prior feature is pinned bit-exact against
     (tests/test_hints_parity.py runs every LDT_KERNEL mode against
     this function on the same wire).

  3. `run_eval`: batch the corpus through the engine, then compute the
     scorecard as vectorized array ops over int result planes — top-1 /
     top-3 agreement against the scalar oracle (detect_scalar), label
     accuracy, per-script confusion rows, and reliability calibration
     buckets. bench.py --eval publishes the dict as ACC_rNN.json;
     ci.sh's accuracy smoke fails the build when top-1 agreement drops
     below the pinned floor.
"""
from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from . import telemetry
from .engine_scalar import detect_scalar
from .registry import registry as default_registry
from .tables import load_tables

CORPUS_PATH = Path(__file__).resolve().parent / "data" / "eval_corpus.tsv"

# device-vs-scalar-oracle agreement floor the published scorecard (and
# the ci accuracy smoke) must clear — the engines are bit-exact by
# construction, so anything below 1.0 means a real divergence; the
# floor leaves headroom only for corpus edits landing mid-review
AGREEMENT_FLOOR = 0.99

# -- bundled corpus ---------------------------------------------------------
#
# code -> (ISO 15924 script of the language, seed sentences). The codes
# are the registry's own ISO-639 codes (asserted at generation time);
# sentences are handcrafted to be unambiguous for their language, and
# the generator expands each language into 5 deterministic variants
# (pairs, full joins, repeats, truncations) so length and structure
# vary without any RNG.

SEED_BANK: dict = {
    "en": ("Latn", [
        "The committee reviewed the proposal carefully and decided to "
        "postpone the final vote until the next quarterly meeting.",
        "She walked through the quiet village early in the morning "
        "while the shops were still closed and the streets empty.",
        "Scientists have discovered that the weather patterns over the "
        "northern ocean are changing faster than anyone expected.",
    ]),
    "fr": ("Latn", [
        "Le comité a examiné la proposition avec soin et a décidé de "
        "reporter le vote final à la prochaine réunion trimestrielle.",
        "Elle marchait dans le village tranquille tôt le matin alors "
        "que les boutiques étaient encore fermées et les rues vides.",
        "Les chercheurs ont découvert que les régimes climatiques au "
        "dessus de l'océan changent plus vite que prévu.",
    ]),
    "de": ("Latn", [
        "Der Ausschuss prüfte den Vorschlag sorgfältig und beschloss, "
        "die endgültige Abstimmung auf die nächste Sitzung zu "
        "verschieben.",
        "Sie ging früh am Morgen durch das ruhige Dorf, während die "
        "Geschäfte noch geschlossen und die Straßen leer waren.",
        "Wissenschaftler haben entdeckt, dass sich die Wettermuster "
        "über dem nördlichen Ozean schneller ändern als erwartet.",
    ]),
    "es": ("Latn", [
        "El comité examinó la propuesta cuidadosamente y decidió "
        "aplazar la votación final hasta la próxima reunión "
        "trimestral.",
        "Ella caminaba por el pueblo tranquilo temprano en la mañana "
        "mientras las tiendas seguían cerradas y las calles vacías.",
        "Los científicos han descubierto que los patrones del clima "
        "sobre el océano del norte cambian más rápido de lo esperado.",
    ]),
    "it": ("Latn", [
        "Il comitato ha esaminato attentamente la proposta e ha "
        "deciso di rinviare la votazione finale alla prossima "
        "riunione trimestrale.",
        "Camminava per il paese tranquillo la mattina presto mentre i "
        "negozi erano ancora chiusi e le strade vuote.",
        "Gli scienziati hanno scoperto che i modelli del tempo "
        "sull'oceano settentrionale cambiano più velocemente del "
        "previsto.",
    ]),
    "pt": ("Latn", [
        "O comitê examinou a proposta cuidadosamente e decidiu adiar "
        "a votação final até a próxima reunião trimestral.",
        "Ela caminhava pela aldeia tranquila de manhã cedo enquanto "
        "as lojas ainda estavam fechadas e as ruas vazias.",
        "Os cientistas descobriram que os padrões do clima sobre o "
        "oceano do norte estão mudando mais rápido do que o esperado.",
    ]),
    "nl": ("Latn", [
        "De commissie heeft het voorstel zorgvuldig bekeken en "
        "besloten de eindstemming uit te stellen tot de volgende "
        "vergadering.",
        "Ze liep vroeg in de ochtend door het rustige dorp terwijl de "
        "winkels nog gesloten waren en de straten leeg.",
        "Wetenschappers hebben ontdekt dat de weerpatronen boven de "
        "noordelijke oceaan sneller veranderen dan verwacht.",
    ]),
    "id": ("Latn", [
        "Panitia memeriksa usulan itu dengan cermat dan memutuskan "
        "untuk menunda pemungutan suara sampai rapat berikutnya.",
        "Dia berjalan melewati desa yang tenang pagi-pagi sekali "
        "ketika toko-toko masih tutup dan jalanan masih sepi.",
        "Para ilmuwan menemukan bahwa pola cuaca di atas samudra "
        "utara berubah lebih cepat daripada yang diperkirakan.",
    ]),
    "sv": ("Latn", [
        "Kommittén granskade förslaget noggrant och beslutade att "
        "skjuta upp den slutliga omröstningen till nästa möte.",
        "Hon gick genom den tysta byn tidigt på morgonen medan "
        "butikerna fortfarande var stängda och gatorna tomma.",
        "Forskare har upptäckt att vädermönstren över norra havet "
        "förändras snabbare än någon väntat sig.",
    ]),
    "tr": ("Latn", [
        "Komite öneriyi dikkatle inceledi ve nihai oylamayı bir "
        "sonraki üç aylık toplantıya ertelemeye karar verdi.",
        "Sabahın erken saatlerinde dükkanlar hâlâ kapalıyken ve "
        "sokaklar boşken sessiz köyün içinden yürüdü.",
        "Bilim insanları kuzey okyanusu üzerindeki hava düzenlerinin "
        "beklenenden daha hızlı değiştiğini keşfetti.",
    ]),
    "pl": ("Latn", [
        "Komisja dokładnie przeanalizowała propozycję i postanowiła "
        "odłożyć ostateczne głosowanie do następnego posiedzenia.",
        "Szła przez spokojną wieś wcześnie rano, gdy sklepy były "
        "jeszcze zamknięte, a ulice puste.",
        "Naukowcy odkryli, że wzorce pogodowe nad północnym oceanem "
        "zmieniają się szybciej, niż ktokolwiek się spodziewał.",
    ]),
    "vi": ("Latn", [
        "Ủy ban đã xem xét đề xuất một cách cẩn thận và quyết định "
        "hoãn cuộc bỏ phiếu cuối cùng đến cuộc họp quý sau.",
        "Cô đi bộ qua ngôi làng yên tĩnh vào sáng sớm khi các cửa "
        "hàng vẫn đóng cửa và đường phố vắng vẻ.",
        "Các nhà khoa học phát hiện rằng các hình thái thời tiết trên "
        "đại dương phía bắc đang thay đổi nhanh hơn dự kiến.",
    ]),
    "fi": ("Latn", [
        "Valiokunta tarkasteli ehdotusta huolellisesti ja päätti "
        "lykätä lopullista äänestystä seuraavaan kokoukseen.",
        "Hän käveli hiljaisen kylän läpi varhain aamulla, kun kaupat "
        "olivat vielä kiinni ja kadut tyhjiä.",
        "Tutkijat ovat havainneet, että pohjoisen valtameren "
        "sääilmiöt muuttuvat odotettua nopeammin.",
    ]),
    "da": ("Latn", [
        "Udvalget gennemgik forslaget omhyggeligt og besluttede at "
        "udskyde den endelige afstemning til det næste møde.",
        "Hun gik gennem den stille landsby tidligt om morgenen, mens "
        "butikkerne stadig var lukkede og gaderne tomme.",
        "Forskere har opdaget, at vejrmønstrene over det nordlige "
        "ocean ændrer sig hurtigere end nogen havde ventet.",
    ]),
    "ru": ("Cyrl", [
        "Комитет внимательно рассмотрел предложение и решил отложить "
        "окончательное голосование до следующего заседания.",
        "Она шла через тихую деревню рано утром, когда магазины были "
        "еще закрыты, а улицы пусты.",
        "Ученые обнаружили, что погодные условия над северным "
        "океаном меняются быстрее, чем кто-либо ожидал.",
    ]),
    "uk": ("Cyrl", [
        "Комітет уважно розглянув пропозицію і вирішив відкласти "
        "остаточне голосування до наступного засідання.",
        "Вона йшла через тихе село рано вранці, коли крамниці були "
        "ще зачинені, а вулиці порожні.",
        "Вчені виявили, що погодні умови над північним океаном "
        "змінюються швидше, ніж будь-хто очікував.",
    ]),
    "bg": ("Cyrl", [
        "Комитетът разгледа внимателно предложението и реши да "
        "отложи окончателното гласуване за следващото заседание.",
        "Тя вървеше през тихото село рано сутринта, докато "
        "магазините бяха още затворени, а улиците празни.",
        "Учените откриха, че метеорологичните условия над северния "
        "океан се променят по-бързо от очакваното.",
    ]),
    "el": ("Grek", [
        "Η επιτροπή εξέτασε προσεκτικά την πρόταση και αποφάσισε να "
        "αναβάλει την τελική ψηφοφορία για την επόμενη συνεδρίαση.",
        "Περπατούσε μέσα στο ήσυχο χωριό νωρίς το πρωί, ενώ τα "
        "μαγαζιά ήταν ακόμη κλειστά και οι δρόμοι άδειοι.",
        "Οι επιστήμονες ανακάλυψαν ότι τα καιρικά μοτίβα πάνω από "
        "τον βόρειο ωκεανό αλλάζουν ταχύτερα από το αναμενόμενο.",
    ]),
    "iw": ("Hebr", [
        "הוועדה בחנה את ההצעה בקפידה והחליטה לדחות את ההצבעה "
        "הסופית לישיבה הרבעונית הבאה.",
        "היא הלכה בכפר השקט מוקדם בבוקר כשהחנויות היו עדיין "
        "סגורות והרחובות ריקים.",
        "מדענים גילו שדפוסי מזג האוויר מעל האוקיינוס הצפוני "
        "משתנים מהר יותר מכפי שציפו.",
    ]),
    "ar": ("Arab", [
        "راجعت اللجنة الاقتراح بعناية وقررت تأجيل التصويت النهائي "
        "إلى الاجتماع الفصلي القادم.",
        "مشت عبر القرية الهادئة في الصباح الباكر بينما كانت المتاجر "
        "لا تزال مغلقة والشوارع فارغة.",
        "اكتشف العلماء أن أنماط الطقس فوق المحيط الشمالي تتغير "
        "أسرع مما توقعه أي شخص.",
    ]),
    "fa": ("Arab", [
        "کمیته پیشنهاد را با دقت بررسی کرد و تصمیم گرفت رأی گیری "
        "نهایی را به جلسه بعدی موکول کند.",
        "او صبح زود از میان روستای آرام می گذشت در حالی که مغازه ها "
        "هنوز بسته بودند و خیابان ها خالی.",
        "دانشمندان دریافته اند که الگوهای آب و هوا بر فراز اقیانوس "
        "شمالی سریعتر از انتظار تغییر می کنند.",
    ]),
    "ja": ("Jpan", [
        "委員会は提案を慎重に検討し、最終投票を次回の四半期会議まで"
        "延期することを決定しました。",
        "彼女は朝早く静かな村を歩いていたが、店はまだ閉まっており、"
        "通りには人がいなかった。",
        "科学者たちは、北の海の上の気象パターンが予想よりも速く"
        "変化していることを発見した。",
    ]),
    "zh": ("Hans", [
        "委员会仔细审查了这项提案,并决定将最终表决推迟到下一次"
        "季度会议。",
        "清晨她走过安静的村庄,商店还没有开门,街道上空无一人。",
        "科学家们发现,北方海洋上空的天气模式变化得比任何人预期的"
        "都要快。",
    ]),
    "ko": ("Kore", [
        "위원회는 제안을 신중하게 검토했으며 최종 투표를 다음 분기 "
        "회의까지 연기하기로 결정했다.",
        "그녀는 이른 아침 조용한 마을을 걸었고 가게들은 아직 닫혀 "
        "있었으며 거리는 비어 있었다.",
        "과학자들은 북쪽 바다 위의 날씨 패턴이 예상보다 빠르게 "
        "변하고 있다는 것을 발견했다.",
    ]),
    "th": ("Thai", [
        "คณะกรรมการพิจารณาข้อเสนออย่างรอบคอบและตัดสินใจเลื่อนการ"
        "ลงคะแนนเสียงครั้งสุดท้ายไปยังการประชุมครั้งถัดไป",
        "เธอเดินผ่านหมู่บ้านที่เงียบสงบในตอนเช้าตรู่ขณะที่ร้านค้ายังปิดอยู่"
        "และถนนก็ว่างเปล่า",
        "นักวิทยาศาสตร์ค้นพบว่ารูปแบบสภาพอากาศเหนือมหาสมุทรทางเหนือ"
        "กำลังเปลี่ยนแปลงเร็วกว่าที่ใครคาดไว้",
    ]),
    "hi": ("Deva", [
        "समिति ने प्रस्ताव की सावधानीपूर्वक समीक्षा की और अंतिम मतदान "
        "को अगली तिमाही बैठक तक स्थगित करने का निर्णय लिया।",
        "वह सुबह-सुबह शांत गांव से गुजर रही थी जबकि दुकानें अभी भी "
        "बंद थीं और सड़कें खाली थीं।",
        "वैज्ञानिकों ने पाया है कि उत्तरी महासागर के ऊपर मौसम के "
        "पैटर्न अपेक्षा से अधिक तेजी से बदल रहे हैं।",
    ]),
}

# the documented ambiguous-document hint demo (docs/ACCURACY.md): short
# English-function-word text whose unhinted verdict is unreliable; a
# content-language "id" prior flips it (run_eval records before/after,
# tests/test_hints_parity.py pins that the flip happens and that
# hint-off results stay byte-identical)
HINT_DEMO_TEXT = ("the quick brown fox jumps over the lazy dog near "
                  "the river bank")
HINT_DEMO_HINT = "id"


def generate_corpus(reg=None) -> list:
    """Deterministic (code, text) expansion of SEED_BANK: 5 structural
    variants per language — no RNG, so regenerating always reproduces
    the checked-in TSV byte for byte."""
    reg = reg or default_registry
    pairs: list = []
    for code, (_script, sents) in SEED_BANK.items():
        if code not in reg.code_to_lang:
            raise ValueError(f"eval corpus label {code!r} not in the "
                             "registry; fix SEED_BANK")
        s0, s1, s2 = sents[0], sents[1], sents[2]
        variants = [
            s0 + " " + s1,
            s1 + " " + s2,
            " ".join(sents),
            (s2 + " ") * 3,
            s0[:80] + " " + s2,
        ]
        for v in variants:
            pairs.append((code, v.replace("\t", " ").replace("\n", " ")))
    return pairs


def write_corpus(path: Path | None = None) -> Path:
    """Render the generated corpus as the checked-in TSV."""
    path = Path(path) if path else CORPUS_PATH
    lines = [f"{code}\t{text}" for code, text in generate_corpus()]
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    return path


def corpus_pairs(path: Path | None = None) -> list:
    """Load the bundled labeled corpus: the checked-in TSV when
    present, else the bit-identical in-memory regeneration."""
    path = Path(path) if path else CORPUS_PATH
    if path.is_file():
        pairs = []
        for line in path.read_text(encoding="utf-8").splitlines():
            if "\t" not in line:
                continue
            code, text = line.split("\t", 1)
            pairs.append((code, text))
        if pairs:
            return pairs
    return generate_corpus()


# -- numpy oracle of the device chunk reduction -----------------------------


def _oracle_reliability_expected(actual, expected):
    """f32 ratio math, op-for-op with ops/score.py
    _reliability_expected (float32 intermediates, so the int cast
    truncates identically)."""
    actual = np.asarray(actual, np.int64)
    expected = np.asarray(expected, np.int64)
    hi = np.maximum(actual, expected).astype(np.float32)
    lo = np.minimum(actual, expected).astype(np.float32)
    ratio = hi / np.maximum(lo, np.float32(1.0))
    pct = (np.float32(100.0) * (np.float32(4.0) - ratio)
           / np.float32(2.5)).astype(np.int32)
    pct = np.where(ratio <= 1.5, 100, np.where(ratio > 4.0, 0, pct))
    pct = np.where(expected == 0, 100, pct)
    return np.where(actual == 0, np.where(expected == 0, 100, 0), pct)


def oracle_score_chunks(tables, reg, wire: dict) -> np.ndarray:
    """Pure-numpy mirror of ops/score.py score_chunks_impl: flat wire
    dict (numpy arrays, exactly what pack_chunks_native built) ->
    packed [G] u32 chunk words. Every stage — slot gather, langprob
    decode, chunk totes, whacks, the LDT_HINTS prior term, group-in-use
    top-2, reliability, word packing — follows the device program
    op-for-op, so `oracle_score_chunks(t, r, cb.wire) ==
    np.asarray(score_chunks(dt, cb.wire))` bit-for-bit under every
    kernel mode (tests/test_hints_parity.py pins this, priors
    included). This is the scalar-oracle extension the device prior
    algebra is defined against."""
    from .ops.device_tables import host_tables
    from .ops.score import HINT_BASE

    ht = host_tables(tables, reg)
    cat_ind2 = ht.cat_ind2.astype(np.int64)
    lg3 = np.asarray(tables.lg_prob[:, 5:8], np.uint8)
    plang_to_lang = np.stack(
        [reg.plang_to_lang_latn, reg.plang_to_lang_othr]).astype(np.int64)
    expected = tables.avg_delta_octa_score.astype(np.int64)
    close = np.array([reg.close_set(lang)
                      for lang in range(reg.num_languages)], np.int64)

    idxf = np.asarray(wire["idx"]).reshape(-1).astype(np.int64)
    N = idxf.shape[0]
    cnsl2 = np.asarray(wire["cnsl"]).astype(np.int64)
    cstart = (np.cumsum(cnsl2, axis=-1) - cnsl2).reshape(-1)
    cnsl = cnsl2.reshape(-1)
    cmeta = np.asarray(wire["cmeta"]).reshape(-1).astype(np.uint32)
    G = cstart.shape[0]
    K = np.asarray(wire["k_iota"]).shape[0]

    ki = np.arange(K, dtype=np.int64)
    valid = ki[None, :] < cnsl[:, None]
    gidx = np.clip(cstart[:, None] + ki[None, :], 0, N - 1)
    raw = idxf[gidx]
    hint_lp = np.asarray(wire["hint_lp"]).astype(np.int64)
    H = hint_lp.shape[0]
    lp_tbl = cat_ind2[np.clip(raw, 0, len(cat_ind2) - 1)]
    lp_hint = hint_lp[np.clip(raw - HINT_BASE, 0, H - 1)]
    lp = np.where(valid, np.where(raw >= HINT_BASE, lp_hint, lp_tbl), 0)

    ps = np.stack([(lp >> 8) & 0xFF, (lp >> 16) & 0xFF,
                   (lp >> 24) & 0xFF], axis=-1)        # [G, K, 3]
    row = lp & 0xFF
    # the device gather clamps out-of-range rows (XLA semantics);
    # numpy fancy indexing must clamp explicitly to match
    q = lg3[np.minimum(row, len(lg3) - 1)].astype(np.int64)
    contrib = np.where(valid[..., None] & (ps > 0), q, 0)

    scores = np.zeros((G, 256), np.int64)
    gi = np.repeat(np.arange(G), K * 3)
    np.add.at(scores, (gi, ps.reshape(-1)), contrib.reshape(-1))
    # ps == 0 slots contributed 0 into plang 0 — identical to the
    # device's (ps > 0) mask zeroing their contribution

    cbytes = (cmeta & np.uint32(0xFFFF)).astype(np.int64)
    grams = ((cmeta >> 16) & np.uint32(0xFFF)).astype(np.int64)
    side = ((cmeta >> 28) & np.uint32(1)).astype(np.int64)
    real = ((cmeta >> 29) & np.uint32(1)).astype(np.int64)
    script = np.asarray(wire["cscript"]).reshape(-1).astype(np.int64)

    group_scores = scores
    if np.asarray(wire["cwhack"]).shape[-1] != 1:
        cwhack = np.asarray(wire["cwhack"]).reshape(-1).astype(np.int64)
        wtbl = np.asarray(wire["whack_tbl"])
        wmask = wtbl[np.clip(cwhack, 0, wtbl.shape[0] - 1), side]
        scores = np.where(wmask > 0, 0, scores)
    if "cprior" in wire:
        cprior = np.asarray(wire["cprior"]).reshape(-1).astype(np.int64)
        ptbl = np.asarray(wire["prior_tbl"])
        prior = ptbl[np.clip(cprior, 0, ptbl.shape[0] - 1),
                     side].astype(np.int64)
        scores = np.where(scores > 0, scores + prior, scores)

    iota256 = np.arange(256, dtype=np.int64)
    groups = (group_scores.reshape(G, 64, 4) > 0).any(axis=-1)
    slot_in_use = np.repeat(groups, 4, axis=-1)
    sortkey = np.where(slot_in_use, scores * 256 + (255 - iota256), -1)
    k1 = np.argmax(sortkey, axis=-1)
    top1 = np.take_along_axis(sortkey, k1[:, None], axis=-1)[:, 0]
    sortkey2 = np.where(iota256 == k1[:, None], -1, sortkey)
    k2 = np.argmax(sortkey2, axis=-1)
    top2 = np.take_along_axis(sortkey2, k2[:, None], axis=-1)[:, 0]
    s1 = np.where(top1 >= 0, top1 >> 8, 0)
    s2 = np.where(top2 >= 0, top2 >> 8, 0)
    k1 = np.where(top1 >= 0, k1, 0)
    k2 = np.where(top2 >= 0, k2, 0)

    lang1 = plang_to_lang[side, k1]
    lang2 = plang_to_lang[side, k2]

    actual_kb = np.where(cbytes > 0,
                         (s1 << 10) // np.maximum(cbytes, 1), 0)
    lscript4 = np.where(script == 1, 0,
                        np.where(script == 3, 1,
                                 np.where(script == 6, 2, 3)))
    expected_kb = expected[lang1, lscript4]

    maxp = np.where(grams < 8, 12 * grams, 100)
    thresh = np.clip((grams * 5) >> 3, 3, 16)
    delta = s1 - s2
    rd = np.where(delta >= thresh, maxp,
                  np.where(delta <= 0, 0,
                           np.minimum(maxp, (100 * delta) // thresh)))
    same_set = (close[lang1] != 0) & (close[lang1] == close[lang2])
    rd = np.where(same_set, 100, rd)
    rs = _oracle_reliability_expected(actual_kb, expected_kb)
    crel = np.minimum(rd, rs)

    word = (lang1.astype(np.uint32) |
            (np.clip(s1, 0, 0x3FFF).astype(np.uint32) << 10) |
            (np.clip(crel, 0, 127).astype(np.uint32) << 24) |
            (real.astype(np.uint32) << 31))
    return word


# -- hint-flip demo ---------------------------------------------------------


def hint_flip_demo(tables=None, reg=None) -> dict:
    """Pack HINT_DEMO_TEXT with and without the LDT_HINTS prior and
    report the before/after verdicts at the epilogue level — the
    documented ambiguous-document flip the acceptance gate pins. Runs
    entirely through the oracle (no jax needed)."""
    from . import native
    from .hints import CLDHints, apply_hints, prior_vector
    from .ops.score import unpack_chunks_out

    tables = tables or load_tables()
    reg = reg or default_registry
    hb = apply_hints(HINT_DEMO_TEXT, True,
                     CLDHints(content_language_hint=HINT_DEMO_HINT),
                     tables, reg)
    pv = prior_vector(hb, tables)
    cb0 = native.pack_chunks_native([HINT_DEMO_TEXT], tables, reg,
                                    hint_boosts=[hb])
    cb1 = native.pack_chunks_native([HINT_DEMO_TEXT], tables, reg,
                                    hint_boosts=[hb], hint_priors=[pv])
    out = {}
    for name, cb in (("before", cb0), ("after", cb1)):
        rows = unpack_chunks_out(oracle_score_chunks(tables, reg,
                                                     cb.wire),
                                 cb.wire["cmeta"])
        ep = native.epilogue_flat_native(rows, cb, 0, reg)
        out[name] = reg.code(int(ep[0][0]))
    return {"text": HINT_DEMO_TEXT,
            "hint": f"content-language: {HINT_DEMO_HINT}",
            "before": out["before"], "after": out["after"],
            "flipped": out["before"] != out["after"]}


# -- scorecard --------------------------------------------------------------


def _result_planes(results, reg) -> dict:
    """Result objects -> int planes for the vectorized scorecard."""
    n = len(results)
    top3 = np.zeros((n, 3), np.int64)
    pct1 = np.zeros(n, np.int64)
    rel = np.zeros(n, bool)
    lang1 = np.zeros(n, np.int64)
    for i, r in enumerate(results):
        top3[i] = list(r.language3)
        pct1[i] = int(r.percent3[0])
        rel[i] = bool(r.is_reliable)
        lang1[i] = int(r.summary_lang)
    return {"lang1": lang1, "top3": top3, "pct1": pct1, "rel": rel}


def run_eval(engine=None, quick: bool = False, pairs=None,
             tables=None, reg=None) -> dict:
    """Batch the bundled corpus through the engine (or the scalar
    engine when none is available) and compute the scorecard. The
    agreement block compares the engine's verdicts against the scalar
    oracle doc-for-doc; the accuracy/confusion/calibration blocks
    compare against the corpus labels. All tallies are vectorized
    numpy over the int result planes — no per-doc Python in the
    scoring passes."""
    reg = reg or (engine.reg if engine is not None else default_registry)
    tables = tables or (engine.tables if engine is not None
                        else load_tables())
    pairs = list(pairs if pairs is not None else corpus_pairs())
    if quick:
        pairs = pairs[::3]
    labels = [c for c, _ in pairs]
    texts = [t for _, t in pairs]
    telemetry.REGISTRY.counter_inc("ldt_eval_docs_total", len(texts))

    oracle = [detect_scalar(t, tables, reg) for t in texts]
    if engine is not None:
        got = engine.detect_batch(texts)
        engine_kind = "device"
    else:
        got = oracle
        engine_kind = "scalar"

    gp = _result_planes(got, reg)
    op = _result_planes(oracle, reg)
    label_ids = np.array([reg.code_to_lang.get(c, -1) for c in labels],
                         np.int64)
    scripts = np.array([SEED_BANK.get(c, ("??",))[0] if c in SEED_BANK
                        else "??" for c in labels])

    n = len(texts)
    top1_agree = float((gp["lang1"] == op["lang1"]).mean())
    top3_agree = float((op["lang1"][:, None]
                        == gp["top3"]).any(axis=1).mean())
    label_top1 = float((gp["lang1"] == label_ids).mean())
    label_top3 = float((label_ids[:, None]
                        == gp["top3"]).any(axis=1).mean())

    # per-script rows: accuracy + confusion pairs, via np.unique over
    # combined (label, got) keys — no per-doc python in the tally
    per_script: dict = {}
    for s in np.unique(scripts):
        m = scripts == s
        hits = gp["lang1"][m] == label_ids[m]
        keys = label_ids[m] * 100000 + gp["lang1"][m]
        uk, counts = np.unique(keys[~hits], return_counts=True)
        confusions = [[reg.code(int(k // 100000)),
                       reg.code(int(k % 100000)), int(c)]
                      for k, c in zip(uk, counts)]
        confusions.sort(key=lambda r: -r[2])
        per_script[str(s)] = {
            "docs": int(m.sum()),
            "label_top1": float(hits.mean()),
            "confusions": confusions[:8],
        }

    # reliability calibration: bucket the engine's top percent and
    # compare claimed reliability against label accuracy per bucket
    edges = np.array([0, 20, 40, 60, 80, 101])
    bucket = np.digitize(gp["pct1"], edges) - 1
    hits = (gp["lang1"] == label_ids).astype(np.int64)
    calibration = []
    nb = len(edges) - 1
    docs_b = np.bincount(bucket, minlength=nb)[:nb]
    hits_b = np.bincount(bucket, weights=hits, minlength=nb)[:nb]
    rel_b = np.bincount(bucket, weights=gp["rel"].astype(np.int64),
                        minlength=nb)[:nb]
    for b in range(nb):
        if docs_b[b] == 0:
            continue
        calibration.append({
            "pct_lo": int(edges[b]), "pct_hi": int(edges[b + 1] - 1),
            "docs": int(docs_b[b]),
            "label_top1": float(hits_b[b] / docs_b[b]),
            "reliable_frac": float(rel_b[b] / docs_b[b]),
        })

    return {
        "corpus_docs": n,
        "languages": len(set(labels)),
        "quick": bool(quick),
        "engine": engine_kind,
        "agreement": {"top1": top1_agree, "top3": top3_agree,
                      "floor": AGREEMENT_FLOOR},
        "label_accuracy": {"top1": label_top1, "top3": label_top3},
        "per_script": per_script,
        "calibration": calibration,
        "hint_flip": hint_flip_demo(tables, reg),
    }


def check_floor(card: dict) -> None:
    """Raise when the published scorecard is below the agreement floor
    (the ci.sh accuracy smoke's gate)."""
    top1 = card["agreement"]["top1"]
    if top1 < AGREEMENT_FLOOR:
        raise AssertionError(
            f"device-vs-scalar top-1 agreement {top1:.4f} below the "
            f"{AGREEMENT_FLOOR} floor — engines diverged")


if __name__ == "__main__":
    print(json.dumps(run_eval(quick=True), indent=2))
