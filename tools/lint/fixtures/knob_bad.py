"""Fixture: knob-registry violations — direct env reads plus an
accessor naming an undeclared knob."""
import os
from os import environ


def f():
    a = os.environ.get("LDT_X")             # direct env access
    b = os.getenv("LDT_Y")                  # direct env access
    c = knobs.get_int("LDT_NOT_DECLARED")   # undeclared knob
    return a, b, c, environ
