"""Multi-host initialization for the data-parallel detector.

The reference scales horizontally with independent processes behind a load
balancer (SURVEY.md §2.7); the TPU-native equivalent keeps one logical
program and extends the same 1-D "batch" mesh axis across hosts:

  - within a host, the axis spans chips over ICI;
  - across hosts, the same axis spans processes over DCN.

Because documents are independent and the scoring program is
communication-free (parallel/mesh.py), the only cross-host traffic is
jax.distributed control-plane setup — no collectives ride DCN in steady
state. Each host packs and feeds its own batch slice (the service layer
runs per-host, like the reference's per-container servers); eval-harness
accuracy reductions are the one place XLA inserts psums, and those ride
ICI first by construction of the mesh axis order.

Typical multi-host launch (one process per host; TPU pod slices discover
topology from the runtime):

    from language_detector_tpu.parallel import distributed, mesh
    distributed.initialize()               # no-op on single process
    m = mesh.batch_mesh()                  # all global devices
    eng = NgramBatchEngine(mesh=m)
"""
from __future__ import annotations

from .. import knobs


def distributed_is_initialized() -> bool:
    """Version-compatible `jax.distributed.is_initialized()`.

    The public helper only exists in newer jax releases; older jaxlibs
    (including the pinned 0.4.x) expose just initialize/shutdown. Fall
    back to probing the private global distributed state, and treat a
    totally unprobeable build as "not initialized" — initialize() is
    documented as safe to call twice, and jax.distributed.initialize
    itself raises a clear error on a genuine double-init."""
    import jax

    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        try:
            return bool(probe())
        except Exception:  # noqa: BLE001 - fall through to the state probe
            pass
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:  # noqa: BLE001 - private layout changed
        return False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Initialize jax.distributed for multi-host execution.

    Arguments default from the standard environment variables
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, as
    set by TPU pod launchers) and fall back to jax's own TPU-metadata
    autodetection when none are present. Returns True when distributed
    mode was initialized, False for the single-process case (nothing to
    do). Safe to call twice (second call is a no-op)."""
    import jax

    coordinator_address = coordinator_address or \
        knobs.get_str("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = knobs.get_int("JAX_NUM_PROCESSES")
    if process_id is None:
        process_id = knobs.get_int("JAX_PROCESS_ID")

    if distributed_is_initialized():
        return True
    # Multi-host iff explicitly configured, or the TPU runtime lists more
    # than one worker. (Decided from env vars only — probing
    # jax.process_count() would initialize the XLA backend and break a
    # later initialize(); single-worker setups may still export
    # TPU_WORKER_HOSTNAMES=localhost.)
    workers = knobs.get_str("TPU_WORKER_HOSTNAMES") or ""
    if coordinator_address is None and num_processes is None and \
            "," not in workers:
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    # Some jaxlib builds (e.g. tunneled single-chip platforms) accept the
    # call but never form the cluster; fail loudly rather than silently
    # running 1/N of the workload as if it were the whole job.
    if num_processes is not None and jax.process_count() != num_processes:
        raise RuntimeError(
            f"jax.distributed did not form the requested cluster: "
            f"process_count()={jax.process_count()} != {num_processes}; "
            "this jaxlib build may not support multi-process execution")
    return True


def addressable_pool_devices() -> list:
    """Devices the fault-tolerant device pool (parallel/pool.py) may
    form dispatch lanes over on THIS process. Lanes launch and fetch
    independently per process — a lane spanning another host's chips
    could never be dispatched from here — so on a multi-host cluster
    the pool partitions the process's ADDRESSABLE devices, while the
    single-host case (and the CPU simulator) uses them all. Pass the
    result to mesh.batch_mesh(devices=...) before building the engine
    when running pooled lanes under jax.distributed."""
    import jax
    if distributed_is_initialized():
        return jax.local_devices()
    return jax.devices()


def local_batch_slice(global_batch: int) -> tuple[int, int]:
    """(start, size) of this process's document slice of a global batch:
    contiguous shares in process order, matching the contiguous shard
    layout the flat pack builds (native.pack_chunks_native). The last
    process takes the remainder when the batch does not divide evenly."""
    import jax
    n = jax.process_count()
    i = jax.process_index()
    per = global_batch // n
    start = i * per
    size = global_batch - start if i == n - 1 else per
    return start, size
