"""Fixture: suppression mechanics — a reasoned suppression silences,
a reasonless one stays inert AND flags itself."""
import jax
import numpy as np


def scorer(dt, wire):
    a = np.asarray(wire)  # ldt-lint: disable=trace-host-sync -- fixture: documented exception
    b = np.asarray(wire)  # ldt-lint: disable=trace-host-sync
    return a, b


score = jax.jit(scorer)
