"""Deterministic bounded model checking of the concurrency products
(docs/STATIC_ANALYSIS.md).

Where the FSM conformance pass proves each machine's code against its
declared table, this module proves properties of the machines
COMPOSED: a pure-Python BFS over event interleavings drives the real
classes (CircuitBreaker, BrownoutLadder, Lane, DevicePool,
AdmissionController, and the supervisor's ``_forward_stop`` latch)
under a fake clock, using the existing fault points (``lane_dispatch``)
to inject failures — no wall-clock reads, no randomness, no threads.

Each product replays event sequences from scratch against freshly
built systems and memoizes on an abstract state key, so exploration is
exhaustive over the abstraction and terminates when no new abstract
state is reachable. The proved invariants:

  breaker x ladder      (a) never serve while not-ready: an OPEN
                        breaker inside its cooldown never admits a
                        device call, and once the cooldown elapses it
                        always admits exactly the half-open probe;
                        a CLOSED breaker never carries >= `failures`
                        consecutive failures; the ladder level always
                        matches its EMA under the hysteresis bounds.
  pool-lane x brownout  (b) a fully evicted pool always recovers via
                        the probe trickle (cooldown -> wants_probe ->
                        _pick_lane admits a PROBING lane -> success
                        re-activates it); (d) no reachable state has
                        all lanes evicted AND admission shedding the
                        due probe — the probe vehicle is admitted
                        through a full-shed brownout.
  stop forwarding       (c) SIGTERM is forwarded to each worker
                        generation exactly once across the signal
                        handler, the spawn race, the wait loop, and a
                        racing swap drill's drain.

A failed invariant is a ``model-check-invariant`` violation carrying
the minimal event trace that reached the bad state. The state spaces
are small by construction (tens to a few hundred abstract states), so
the full run stays well inside the lint budget asserted by
``bench.py --smoke``.
"""
from __future__ import annotations

import sys
from pathlib import Path

from .base import Violation, repo_root

_REPO = repo_root()
if str(_REPO) not in sys.path:  # `python -m tools.lint` has it; direct
    sys.path.insert(0, str(_REPO))  # imports of this module may not


class FakeClock:
    """Injectable monotonic clock: time moves only via advance()."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _explore(build, events, key_fn, invariants, max_depth=24,
             max_states=5000):
    """Generic BFS over event interleavings.

    build() -> system tuple; events: name -> fn(*system) (applied in
    sorted-name order for determinism; a fn may return False to mark
    itself inapplicable in the current state, pruning that branch);
    key_fn(*system) -> hashable abstract state; invariants: name ->
    fn(*system) returning None (holds) or a failure string, run on a
    FRESH replay of each newly reached state (invariant probes may
    mutate the system).

    Returns (failures, n_states, exhausted): failures are
    (invariant_name, event_trace, detail) tuples; exhausted is False
    only if a safety cap stopped the walk early.
    """
    ordered = sorted(events)

    def replay(trace):
        sys_ = build()
        for name in trace:
            events[name](*sys_)
        return sys_

    def check(trace, failures):
        for inv in sorted(invariants):
            sys_ = replay(trace)
            detail = invariants[inv](*sys_)
            if detail:
                failures.append((inv, trace, detail))

    failures: list = []
    seen = {key_fn(*build())}
    check((), failures)
    frontier: list = [()]
    exhausted = True
    for _ in range(max_depth):
        if not frontier:
            break
        nxt: list = []
        for trace in frontier:
            for name in ordered:
                seq = trace + (name,)
                sys_ = build()
                applicable = True
                for ev in seq:
                    if events[ev](*sys_) is False:
                        applicable = False
                        break
                if not applicable:
                    continue
                key = key_fn(*sys_)
                if key in seen:
                    continue
                seen.add(key)
                check(seq, failures)
                nxt.append(seq)
                if len(seen) >= max_states:
                    return failures, len(seen), False
        frontier = nxt
    else:
        exhausted = not frontier
    return failures, len(seen), exhausted


# ---------------------------------------------------------------------
# product 1: circuit breaker x brownout ladder

_BL_FAILURES = 2
_BL_COOLDOWN = 5.0
_BL_STALL_MS = 2000.0
_BL_ENTER = (0.60, 0.80, 0.95)
_BL_EXIT = (0.45, 0.65, 0.80)


def _bl_build():
    from language_detector_tpu.service.admission import (
        BrownoutLadder, CircuitBreaker)
    clock = FakeClock()
    # stall_factor=0 pins the watchdog to stall_min_ms so the explored
    # space cannot depend on whatever the process-wide telemetry
    # registry happens to hold
    br = CircuitBreaker(failures=_BL_FAILURES,
                        cooldown_sec=_BL_COOLDOWN, stall_factor=0.0,
                        stall_min_ms=_BL_STALL_MS, clock=clock)
    ladder = BrownoutLadder(enter=_BL_ENTER, exit=_BL_EXIT, alpha=1.0)
    return clock, br, ladder


def _bl_allow(clock, br, ladder):
    br.allow_device()  # result intentionally dropped: the event is
    # the state mutation (OPEN -> HALF_OPEN past cooldown, probe
    # bookkeeping), not the verdict — verdicts are what invariants
    # assert on fresh replicas


_BL_EVENTS = {
    "fail": lambda c, b, l: b.record_failure(),
    "ok": lambda c, b, l: b.record_success(),
    "stall": lambda c, b, l: b.record_success(_BL_STALL_MS + 1.0),
    "allow": _bl_allow,
    "cool": lambda c, b, l: c.advance(_BL_COOLDOWN + 0.1),
    "load": lambda c, b, l: l.observe(1.2),
    "drain": lambda c, b, l: l.observe(0.0),
}


def _bl_key(clock, br, ladder):
    return (br._state, min(br._consec, _BL_FAILURES),
            clock() - br._opened_at >= _BL_COOLDOWN,
            None if br._probe_at is None
            else (clock() - br._probe_at) * 1e3 >= _BL_STALL_MS,
            ladder.level, round(ladder.ema, 6))


def _bl_inv_never_serve_open(clock, br, ladder):
    from language_detector_tpu.service.admission import BREAKER_OPEN
    if br._state != BREAKER_OPEN:
        return None
    in_cooldown = clock() - br._opened_at < _BL_COOLDOWN
    allowed = br.allow_device()
    if in_cooldown and allowed:
        return ("OPEN breaker inside its cooldown admitted a device "
                "call")
    if not in_cooldown and not allowed:
        return ("OPEN breaker past its cooldown refused the half-open "
                "probe — the device path can never recover")
    return None


def _bl_inv_closed_consec(clock, br, ladder):
    from language_detector_tpu.service.admission import BREAKER_CLOSED
    if br._state == BREAKER_CLOSED and br._consec >= _BL_FAILURES:
        return (f"CLOSED breaker holding {br._consec} consecutive "
                f"failures (trip threshold {_BL_FAILURES})")
    return None


def _bl_inv_ladder_consistent(clock, br, ladder):
    lvl, ema = ladder.snapshot()
    if not 0 <= lvl <= 3:
        return f"ladder level {lvl} outside [0, 3]"
    if lvl < 3 and ema >= _BL_ENTER[lvl]:
        return (f"ladder level {lvl} with ema {ema:.3f} >= enter "
                f"threshold {_BL_ENTER[lvl]} — failed to climb")
    if lvl > 0 and ema < _BL_EXIT[lvl - 1]:
        return (f"ladder level {lvl} with ema {ema:.3f} < exit "
                f"threshold {_BL_EXIT[lvl - 1]} — failed to descend")
    return None


# ---------------------------------------------------------------------
# product 2: pool lane health x brownout admission

_P2_COOLDOWN = 10.0


def _p2_build():
    import numpy as np

    from language_detector_tpu.parallel.pool import DevicePool, Lane
    from language_detector_tpu.service.admission import (
        AdmissionConfig, AdmissionController)

    clock = FakeClock()
    raw = np.zeros(1, dtype=np.int32)
    lanes = [Lane(0, None), Lane(1, None)]
    pool = DevicePool(lanes, hedge_factor=0.0, hedge_min_ms=0.0,
                      evict_failures=1,
                      probe_cooldown_sec=_P2_COOLDOWN,
                      max_redispatch=1, clock=clock)
    adm = AdmissionController(AdmissionConfig(
        max_inflight=8, brownout_alpha=1.0, brownout_enter=_BL_ENTER,
        brownout_exit=_BL_EXIT, breaker_failures=100))
    adm.attach_pool(lambda: pool)
    return clock, pool, adm, raw


def _p2_fail(clock, pool, adm, raw):
    """One dispatch with the real ``lane_dispatch`` fault armed: the
    picked lane records the failure (ACTIVE -> EVICTED at
    evict_failures=1; a PROBING lane re-evicts) and the launch
    surfaces PoolExhausted — the typed error, never a hang."""
    from language_detector_tpu import faults
    from language_detector_tpu.parallel.pool import PoolExhausted
    faults.configure("lane_dispatch:error")
    try:
        pool.launch(lambda lane: raw)
    except PoolExhausted:
        pass
    finally:
        faults.configure(None)


def _p2_ok(clock, pool, adm, raw):
    """One successful dispatch + fetch through the real pool paths
    (launch -> _fetch_on); a PROBING lane's success re-admits it."""
    pf = pool.launch(lambda lane: raw)
    pool._fetch_on(pf.lane, pf.raw)


def _p2_admit(clock, pool, adm, raw):
    """One front-door round trip: the ladder observes the occupancy
    (including pool capacity loss) on admit and on release."""
    a = adm.try_admit(["probe text"], priority=False)
    if not a.shed:
        adm.release(a)


_P2_EVENTS = {
    "fail": _p2_fail,
    "ok": _p2_ok,
    "admit": _p2_admit,
    "advance": lambda c, pool, adm, raw: c.advance(_P2_COOLDOWN + 0.1),
}


def _p2_key(clock, pool, adm, raw):
    lanes = tuple(
        (ln._state, min(ln._consecutive, 1),
         ln.probe_due(clock(), pool.probe_cooldown_sec))
        for ln in pool.lanes)
    return (lanes, pool._rr % len(pool.lanes), adm.ladder.level,
            round(adm.ladder.ema, 6))


def _all_evicted(pool):
    from language_detector_tpu.parallel.pool import LANE_EVICTED
    return all(ln.state() == LANE_EVICTED for ln in pool.lanes)


def _p2_inv_evicted_pool_recovers(clock, pool, adm, raw):
    """(b) from any all-evicted state: once a cooldown elapses the pool
    asks for a probe, the next dispatch runs as that probe, and its
    success restores serving capacity."""
    from language_detector_tpu.parallel.pool import LANE_PROBING
    if not _all_evicted(pool):
        return None
    clock.advance(_P2_COOLDOWN + 0.1)
    if not pool.wants_probe():
        return ("all lanes evicted and cooldown elapsed, but the pool "
                "does not want a probe — no recovery path")
    lane = pool._pick_lane()
    if lane.state() != LANE_PROBING:
        return ("all lanes evicted past cooldown, but _pick_lane did "
                "not admit a half-open probe")
    lane.record_success(1.0, clock())
    if pool.capacity()[0] < 1:
        return "a successful probe did not restore any capacity"
    return None


def _p2_inv_probe_admitted_through_shed(clock, pool, adm, raw):
    """(d) no reachable state may shed the due probe: with every lane
    evicted (capacity load 1.2 -> brownout level 3) and a probe due,
    try_admit must admit the request as the probe vehicle."""
    if not _all_evicted(pool):
        return None
    clock.advance(_P2_COOLDOWN + 0.1)
    if not pool.wants_probe():
        return ("all lanes evicted and cooldown elapsed, but the pool "
                "does not want a probe")
    a = adm.try_admit(["probe text"], priority=False)
    if a.shed:
        return (f"admission shed (status {a.status}, reason "
                f"{a.reason}) the due pool probe — a fully evicted "
                f"pool would stay down forever")
    if not a.probe:
        return "the due probe was admitted but not marked probe=True"
    adm.release(a)
    return None


# ---------------------------------------------------------------------
# product 3: stop forwarding (SIGTERM exactly once)

class _FakeChild:
    """Popen stand-in: counts SIGTERMs, stays alive until told."""

    def __init__(self):
        self.terms = 0
        self.alive = True

    def poll(self):
        return None if self.alive else 0

    def send_signal(self, signum=None):
        self.terms += 1


class _SupModel:
    """The supervisor's forwarding surface: the real _forward_stop
    latch driven from all the call sites main() has (signal handler,
    spawn race, wait loop, drill drain)."""

    def __init__(self):
        from language_detector_tpu.service.supervisor import \
            _forward_stop
        self._fwd = _forward_stop
        self.children: list = []
        self.child = None
        self.signaled = None
        self.stopping = False
        self.spawns = 0
        self.drills = 0
        self.sigterms = 0

    def spawn(self):
        # main() only respawns after the current generation exited.
        # Spawning with stopping already set models the race where the
        # signal lands between the loop top and Popen — the post-spawn
        # forwarding site must cover the fresh child.
        if self.child is not None and self.child.alive:
            return False
        if self.spawns >= 2:
            return False
        self.child = _FakeChild()
        self.children.append(self.child)
        self.spawns += 1
        if self.stopping:
            self.signaled = self._fwd(self.child, self.signaled)
        return True

    def sigterm(self):
        # repeat signals re-enter the handler; the latch (not the
        # model) must keep delivery exactly-once
        if self.sigterms >= 2:
            return False
        self.sigterms += 1
        self.stopping = True
        self.signaled = self._fwd(self.child, self.signaled)
        return True

    def tick(self):
        # one wait-loop iteration under stopping
        if not self.stopping or self.child is None:
            return False
        self.signaled = self._fwd(self.child, self.signaled)
        return True

    def exit(self):
        if self.child is None or not self.child.alive:
            return False
        self.child.alive = False
        return True

    def drill(self, racing_stop):
        # SIGHUP drill: only runs from the wait loop when not stopping
        # and the worker is healthy; with racing_stop a SIGTERM lands
        # mid-drill (handler forwards to the OLD child), then the
        # cutover drains old through the same latch
        if self.stopping or self.child is None \
                or not self.child.alive or self.drills >= 2:
            return False
        self.drills += 1
        old = self.child
        standby = _FakeChild()
        self.children.append(standby)
        if racing_stop:
            self.stopping = True
            self.signaled = self._fwd(self.child, self.signaled)
        self.signaled = self._fwd(old, self.signaled)  # drain
        old.alive = False
        self.child = standby
        return True


def _p3_build():
    return (_SupModel(),)


_P3_EVENTS = {
    "spawn": lambda m: m.spawn(),
    "sigterm": lambda m: m.sigterm(),
    "tick": lambda m: m.tick(),
    "exit": lambda m: m.exit(),
    "drill": lambda m: m.drill(racing_stop=False),
    "drill_racing_stop": lambda m: m.drill(racing_stop=True),
}


def _p3_key(m):
    return (m.stopping, m.spawns, m.drills, m.sigterms,
            None if m.child is None else m.children.index(m.child),
            None if m.signaled is None
            else m.children.index(m.signaled),
            tuple((c.alive, min(c.terms, 2)) for c in m.children))


def _p3_inv_at_most_once(m):
    for i, c in enumerate(m.children):
        if c.terms > 1:
            return (f"generation {i + 1} received {c.terms} SIGTERMs "
                    f"— forwarding is not exactly-once")
    return None


def _p3_inv_delivered(m):
    """Stopping with a live current generation: the next wait-loop
    iteration must leave it signaled exactly once (never zero — a
    stop that was swallowed would hang `docker stop`)."""
    if not (m.stopping and m.child is not None and m.child.alive):
        return None
    m.tick()
    if m.child.terms != 1:
        return (f"after a stop and one wait-loop tick the current "
                f"generation holds {m.child.terms} SIGTERMs "
                f"(want exactly 1)")
    return None


# ---------------------------------------------------------------------
# product 4: fleet control plane (members x crash circuit)

_P4_N = 2
_P4_LOOP_MAX = 2
_P4_COOLDOWN = 5.0


class _FleetModel:
    """The fleet supervisor's control surface: real FleetMember FSMs
    composed with the real FleetControl crash circuit, driven exactly
    the way fleet_main drives them (reap -> record_crash with the
    post-death accepting count; probe selection; respawn gating on the
    circuit). Process I/O (Popen, scrapes) is abstracted away — the
    policy composition is what the invariants are about."""

    def __init__(self):
        from language_detector_tpu.service.fleet import (
            FleetControl, FleetMember)
        self.clock = FakeClock()
        self.control = FleetControl(
            loop_max=_P4_LOOP_MAX, loop_window=60.0,
            cooldown_sec=_P4_COOLDOWN, scale_hold_sec=10.0,
            up_depth=64, down_depth=0)
        self.members = [FleetMember(slot) for slot in range(_P4_N)]
        self.probe_slot = None
        self.crashes = 0  # bounds the walk, like product 3's counters

    def _accepting(self):
        return sum(1 for m in self.members if m.accepting())

    def ready(self, i):
        """Member i's ready handshake lands (fleet_main._health_step).
        A probe member reaching READY closes the circuit."""
        from language_detector_tpu.service.fleet import FLEET_SPAWNING
        m = self.members[i]
        if m.state != FLEET_SPAWNING:
            return False
        m.mark_ready()
        self.control.bootstrapped = True
        if self.probe_slot == m.slot:
            self.probe_slot = None
            self.control.probe_ok()
        return True

    def degrade(self, i):
        from language_detector_tpu.service.fleet import FLEET_READY
        m = self.members[i]
        if m.state != FLEET_READY:
            return False
        m.mark_degraded()
        return True

    def crash(self, i):
        """Member i's process dies (fleet_main._reap crash branch):
        mark dead, then account the crash with the post-death
        accepting count — probe deaths re-open, others may trip."""
        from language_detector_tpu.service.fleet import FLEET_SPAWNING
        m = self.members[i]
        alive = m.accepting() or m.state == FLEET_SPAWNING
        if not alive or self.crashes >= 3:
            return False
        m.mark_dead()
        self.crashes += 1
        if self.probe_slot == m.slot:
            self.probe_slot = None
            self.control.probe_failed(self.clock())
        else:
            self.control.record_crash(self.clock(), self._accepting())
        return True

    def respawn(self, i):
        """fleet_main._spawn_step for one member: only while the
        circuit is closed (or the member is the admitted probe)."""
        from language_detector_tpu.service.fleet import (
            CIRCUIT_CLOSED, FLEET_DEAD)
        m = self.members[i]
        if m.state != FLEET_DEAD or m.parked:
            return False
        if self.control.circuit != CIRCUIT_CLOSED \
                and m.slot != self.probe_slot:
            return False
        m.mark_restarting()
        m.mark_spawning()
        return True

    def cool(self):
        if self.clock() - self.control.opened_at > 100.0:
            return False  # idempotent past the window: prune
        self.clock.advance(_P4_COOLDOWN + 0.1)
        return True

    def probe(self):
        """fleet_main._probe_step: cooldown elapsed -> one half-open
        probe; capacity that survived closes the circuit outright."""
        from language_detector_tpu.service.fleet import FLEET_DEAD
        if not self.control.probe_due(self.clock()):
            return False
        self.control.begin_probe()
        if self._accepting() > 0:
            self.control.probe_ok()
            return True
        cand = next((m for m in self.members
                     if m.state == FLEET_DEAD and not m.parked), None)
        if cand is None:
            self.control.probe_failed(self.clock())
            return True
        self.probe_slot = cand.slot
        return True


def _p4_build():
    return (_FleetModel(),)


_P4_EVENTS = {
    "ready_0": lambda f: f.ready(0),
    "ready_1": lambda f: f.ready(1),
    "degrade_0": lambda f: f.degrade(0),
    "degrade_1": lambda f: f.degrade(1),
    "crash_0": lambda f: f.crash(0),
    "crash_1": lambda f: f.crash(1),
    "respawn_0": lambda f: f.respawn(0),
    "respawn_1": lambda f: f.respawn(1),
    "cool": lambda f: f.cool(),
    "probe": lambda f: f.probe(),
}


def _p4_key(f):
    return (tuple(m.state for m in f.members),
            f.control.circuit,
            min(len(f.control.crash_times), _P4_LOOP_MAX),
            f.control.probe_due(f.clock()),
            f.control.bootstrapped,
            f.probe_slot,
            f.crashes)


def _p4_inv_min_one_accepting(f):
    """The headline fleet invariant: while the fleet is nominally up
    (bootstrapped, circuit closed — i.e. NOT in declared-outage
    posture) at least one member is accepting. Equivalently: losing
    the last accepting member always trips the circuit, so a silent
    zero-capacity fleet is unreachable."""
    from language_detector_tpu.service.fleet import CIRCUIT_CLOSED
    if not f.control.bootstrapped:
        return None
    if f.control.circuit != CIRCUIT_CLOSED:
        return None
    if f._accepting() == 0:
        return ("fleet nominally up (bootstrapped, circuit closed) "
                "with zero accepting members")
    return None


def _p4_inv_open_recovers(f):
    """An open circuit always has a recovery path: once the cooldown
    elapses, the probe step either closes it (capacity survived) or
    admits exactly one probe member to respawn."""
    from language_detector_tpu.service.fleet import (
        CIRCUIT_CLOSED, CIRCUIT_OPEN, CIRCUIT_PROBE, FLEET_SPAWNING)
    if f.control.circuit != CIRCUIT_OPEN:
        return None
    f.clock.advance(_P4_COOLDOWN + 0.1)
    if not f.control.probe_due(f.clock()):
        return ("open fleet circuit past its cooldown does not admit "
                "a probe — restarts are parked forever")
    f.probe()
    if f.control.circuit == CIRCUIT_CLOSED:
        return None
    if f.control.circuit != CIRCUIT_PROBE or f.probe_slot is None:
        return ("probe step on a due circuit neither closed it nor "
                "selected a probe member")
    if not f.respawn(f.probe_slot):
        return "the selected probe member was refused its respawn"
    if f.members[f.probe_slot].state != FLEET_SPAWNING:
        return "the probe member did not enter SPAWNING"
    return None


def _p4_inv_closed_window(f):
    """A closed circuit never silently holds a full crash window —
    mirror of the breaker's closed-consec bound."""
    from language_detector_tpu.service.fleet import CIRCUIT_CLOSED
    n = len([t for t in f.control.crash_times
             if f.clock() - t <= f.control.loop_window])
    if f.control.circuit == CIRCUIT_CLOSED and n >= _P4_LOOP_MAX:
        return (f"closed fleet circuit holding {n} crashes inside the "
                f"window (trip threshold {_P4_LOOP_MAX})")
    return None


# ---------------------------------------------------------------------
# product 5: shm ring slot lifecycle x client-crash x worker-crash x
# generation bump (service/shmring.py)
#
# Drives the REAL RingSlot FSM mirrors through the abstract twin of the
# worker's sweep (lease, reclaim, fence) and the client's write/commit/
# consume, with at most one client crash, one worker crash, and one
# fleet-roll generation bump. The headline obligation from the lease
# protocol: every interleaving leaves every slot reclaimable — a
# bounded recovery procedure (restart the worker if dead, let leases
# and sweeps run, let a live client consume) always reaches
# every-slot-FREE, with fenced frames passing through an explicit
# error frame (DONE+failed), never a silent hang.

_R5_TIMEOUT = 2.0


class _RingModel:
    def __init__(self):
        from language_detector_tpu.service.shmring import RingSlot
        self.clock = FakeClock()
        self.slots = [RingSlot(0), RingSlot(1)]
        self.slot_gen = [0, 0]    # generation stamped on the frame
        self.lease_ts = [0.0, 0.0]
        self.failed = [False, False]  # DONE carries an error frame
        self.gen = 1              # worker's current ring generation
        self.client_alive = True
        self.worker_alive = True
        self.ccrashes = 0
        self.wcrashes = 0
        self.bumps = 0

    def _fresh(self, i):
        return self.clock() - self.lease_ts[i] <= _R5_TIMEOUT

    # -- client side --------------------------------------------------

    def write(self, i):
        from language_detector_tpu.service.shmring import SLOT_FREE
        s = self.slots[i]
        if not self.client_alive or s.state != SLOT_FREE:
            return False
        s.mark_writing()
        self.slot_gen[i] = self.gen   # client stamps what it observed
        self.lease_ts[i] = self.clock()
        self.failed[i] = False
        return True

    def commit(self, i):
        from language_detector_tpu.service.shmring import SLOT_WRITING
        s = self.slots[i]
        if not self.client_alive or s.state != SLOT_WRITING:
            return False
        s.mark_ready()
        return True

    def consume(self, i):
        from language_detector_tpu.service.shmring import SLOT_DONE
        s = self.slots[i]
        if not self.client_alive or s.state != SLOT_DONE:
            return False
        s.mark_free()
        self.failed[i] = False
        self.slot_gen[i] = 0
        return True

    # -- worker side --------------------------------------------------

    def lease(self, i):
        from language_detector_tpu.service.shmring import SLOT_READY
        s = self.slots[i]
        if not self.worker_alive or s.state != SLOT_READY \
                or self.slot_gen[i] != self.gen:
            return False
        s.mark_leased()
        self.lease_ts[i] = self.clock()
        return True

    def done(self, i):
        from language_detector_tpu.service.shmring import SLOT_LEASED
        s = self.slots[i]
        if not self.worker_alive or s.state != SLOT_LEASED \
                or self.slot_gen[i] != self.gen:
            return False
        s.mark_done()
        self.failed[i] = False
        return True

    def sweep(self):
        """One reclaim/fence pass of ShmRingServer._sweep_ring (no
        clock advance — `expire` models the lease horizon passing)."""
        from language_detector_tpu.service.shmring import (
            SLOT_DONE, SLOT_LEASED, SLOT_READY, SLOT_WRITING)
        if not self.worker_alive:
            return False
        changed = False
        for i, s in enumerate(self.slots):
            if s.state == SLOT_WRITING and \
                    (not self.client_alive or not self._fresh(i)):
                s.mark_free()
                changed = True
            elif s.state in (SLOT_READY, SLOT_LEASED) and \
                    self.slot_gen[i] != self.gen:
                s.mark_failed()        # explicit error frame
                self.failed[i] = True
                changed = True
            elif s.state == SLOT_DONE and not self.client_alive \
                    and not self._fresh(i):
                s.mark_free()
                self.failed[i] = False
                changed = True
        return changed

    def expire(self):
        """The lease horizon passes (idempotent: prune when nothing is
        fresh so the clock stays bounded in the abstraction)."""
        if not any(self._fresh(i) for i in range(len(self.slots))):
            return False
        self.clock.advance(_R5_TIMEOUT + 0.1)
        return True

    # -- crashes & generations ---------------------------------------

    def client_crash(self):
        if not self.client_alive or self.ccrashes >= 1:
            return False
        self.client_alive = False
        self.ccrashes += 1
        return True

    def worker_crash(self):
        if not self.worker_alive or self.wcrashes >= 1:
            return False
        self.worker_alive = False
        self.wcrashes += 1
        return True

    def worker_restart(self):
        """Re-attach after a crash: the generation bump IS the fence."""
        if self.worker_alive:
            return False
        self.worker_alive = True
        self.gen += 1
        return True

    def gen_bump(self):
        """Fleet roll: a live re-attach (new member process adopts the
        member's ring directory) bumps the generation once."""
        if not self.worker_alive or self.bumps >= 1:
            return False
        self.gen += 1
        self.bumps += 1
        return True


def _r5_build():
    return (_RingModel(),)


_R5_EVENTS = {
    "write_0": lambda r: r.write(0),
    "write_1": lambda r: r.write(1),
    "commit_0": lambda r: r.commit(0),
    "commit_1": lambda r: r.commit(1),
    "lease_0": lambda r: r.lease(0),
    "lease_1": lambda r: r.lease(1),
    "done_0": lambda r: r.done(0),
    "done_1": lambda r: r.done(1),
    "consume_0": lambda r: r.consume(0),
    "consume_1": lambda r: r.consume(1),
    "sweep": lambda r: r.sweep(),
    "expire": lambda r: r.expire(),
    "client_crash": lambda r: r.client_crash(),
    "worker_crash": lambda r: r.worker_crash(),
    "worker_restart": lambda r: r.worker_restart(),
    "gen_bump": lambda r: r.gen_bump(),
}


def _r5_key(r):
    return (tuple(s.state for s in r.slots),
            tuple(g == r.gen for g in r.slot_gen),
            tuple(r.failed),
            tuple(r._fresh(i) for i in range(len(r.slots))),
            r.client_alive, r.worker_alive,
            r.ccrashes, r.wcrashes, r.bumps)


def _r5_recover(r):
    """The bounded recovery procedure every reachable state must admit:
    restart the worker if it crashed, then let the protocol run (lease
    horizon passes, sweeps reclaim/fence, the worker serves what it
    legally can, a live client consumes)."""
    if not r.worker_alive:
        r.worker_restart()
    for _ in range(4):
        r.expire()
        r.sweep()
        for i in range(len(r.slots)):
            r.lease(i)
            r.done(i)
            if r.client_alive:
                r.consume(i)


def _r5_inv_recovers(r):
    from language_detector_tpu.service.shmring import SLOT_FREE
    _r5_recover(r)
    bad = [i for i, s in enumerate(r.slots) if s.state != SLOT_FREE]
    if bad:
        return (f"slots {bad} not reclaimed to FREE after recovery "
                f"(states {[r.slots[i].state for i in bad]}, "
                f"client_alive={r.client_alive})")
    return None


def _r5_inv_no_premature_reclaim(r):
    """A live client's fresh WRITING slot survives a sweep: reclaim
    only fires on a dead writer or an expired lease."""
    from language_detector_tpu.service.shmring import SLOT_WRITING
    if not r.worker_alive:
        return None
    fresh_writing = [i for i, s in enumerate(r.slots)
                     if s.state == SLOT_WRITING and r.client_alive
                     and r._fresh(i)]
    r.sweep()
    for i in fresh_writing:
        if r.slots[i].state != SLOT_WRITING:
            return (f"sweep reclaimed slot {i} although its writer is "
                    f"alive and its lease is fresh")
    return None


def _r5_inv_fenced_fail_explicitly(r):
    """A committed or leased frame stamped by a previous generation
    always answers an explicit error frame (DONE+failed) on the next
    sweep — the client polls it out; it never silently vanishes or
    dangles LEASED forever."""
    from language_detector_tpu.service.shmring import (
        SLOT_DONE, SLOT_LEASED, SLOT_READY)
    if not r.worker_alive:
        r.worker_restart()
    stale = [i for i, s in enumerate(r.slots)
             if s.state in (SLOT_READY, SLOT_LEASED)
             and r.slot_gen[i] != r.gen]
    r.sweep()
    for i in stale:
        if r.slots[i].state != SLOT_DONE or not r.failed[i]:
            return (f"fenced frame in slot {i} did not fail back as an "
                    f"explicit error frame (state "
                    f"{r.slots[i].state}, failed={r.failed[i]})")
    return None


# ---------------------------------------------------------------------
# product 6: integrity scrub x pool lane quarantine/heal
#
# The real IntegrityMonitor drives the real Lane/DevicePool edges;
# only the digest closures are fake (a mutable corrupt flag per lane
# stands in for the device fold). `host_bad` models the heal source
# itself failing verification — re-uploaded tables still hash wrong —
# which is the only way a lane can stay CORRUPT past a scrub.

_P6_COOLDOWN = 10.0
_P6_GOOD = ("good",)
_P6_BAD = ("bad",)


def _p6_build():
    import numpy as np

    from language_detector_tpu.integrity import IntegrityMonitor
    from language_detector_tpu.parallel.pool import DevicePool, Lane

    clock = FakeClock()
    raw = np.zeros(1, dtype=np.int32)
    lanes = [Lane(0, None), Lane(1, None)]
    pool = DevicePool(lanes, hedge_factor=0.0, hedge_min_ms=0.0,
                      evict_failures=1,
                      probe_cooldown_sec=_P6_COOLDOWN,
                      max_redispatch=1, clock=clock)
    st = {"corrupt": [False, False], "host_bad": False, "raw": raw}

    def digest_fn(lane):
        return _P6_BAD if st["corrupt"][lane.idx] else _P6_GOOD

    def reupload_fn(lane):
        if not st["host_bad"]:
            st["corrupt"][lane.idx] = False
        return _P6_GOOD

    mon = IntegrityMonitor(lanes, {0: _P6_GOOD, 1: _P6_GOOD},
                           digest_fn, reupload_fn,
                           interval_sec=1.0, clock=clock)
    return clock, pool, mon, st


def _p6_corrupt(i):
    def ev(clock, pool, mon, st):
        if st["corrupt"][i]:
            return False        # already corrupt: prune the branch
        st["corrupt"][i] = True
    return ev


def _p6_scrub(clock, pool, mon, st):
    """One full scrub pass over both lanes: mismatch -> detect
    (ACTIVE -> CORRUPT) -> heal attempt (re-upload; CORRUPT ->
    EVICTED with the probe due, unless the host source is bad)."""
    mon.scrub_pass()


def _p6_ok(clock, pool, mon, st):
    """One successful dispatch + fetch; a PROBING lane's success
    re-admits it. An all-corrupt pool refuses typed instead."""
    from language_detector_tpu.parallel.pool import PoolExhausted
    try:
        pf = pool.launch(lambda lane: st["raw"])
    except PoolExhausted:
        return
    pool._fetch_on(pf.lane, pf.raw)


_P6_EVENTS = {
    "corrupt0": _p6_corrupt(0),
    "corrupt1": _p6_corrupt(1),
    "host_bad": lambda c, p, m, st: (
        False if st["host_bad"] else st.__setitem__("host_bad", True)),
    "host_ok": lambda c, p, m, st: (
        False if not st["host_bad"]
        else st.__setitem__("host_bad", False)),
    "scrub": _p6_scrub,
    "ok": _p6_ok,
    "advance": lambda c, p, m, st: c.advance(_P6_COOLDOWN + 0.1),
}


def _p6_key(clock, pool, mon, st):
    lanes = tuple(
        (ln._state, min(ln._consecutive, 1),
         ln.probe_due(clock(), pool.probe_cooldown_sec))
        for ln in pool.lanes)
    return (lanes, pool._rr % len(pool.lanes),
            tuple(st["corrupt"]), st["host_bad"])


def _p6_inv_never_serve_corrupt(clock, pool, mon, st):
    """THE integrity property: no reachable state lets the pool draft
    a CORRUPT lane — and when every lane is quarantined, launch
    refuses with the typed PoolExhausted, never a silent wrong-answer
    dispatch."""
    from language_detector_tpu.parallel.pool import (LANE_CORRUPT,
                                                     PoolExhausted)
    states = [ln.state() for ln in pool.lanes]
    if LANE_CORRUPT not in states:
        return None
    if all(s == LANE_CORRUPT for s in states):
        try:
            pool.launch(lambda lane: st["raw"])
        except PoolExhausted:
            return None
        return ("every lane quarantined CORRUPT but launch still "
                "dispatched instead of raising PoolExhausted")
    for _ in range(4 * len(pool.lanes)):
        pf = pool.launch(lambda lane: st["raw"])
        if pf.lane.state() == LANE_CORRUPT:
            return (f"pool drafted quarantined lane {pf.lane.idx} "
                    f"(state CORRUPT) for a dispatch")
        pool._fetch_on(pf.lane, pf.raw)
    return None


def _p6_inv_corrupt_converges_active(clock, pool, mon, st):
    """From any state with a quarantined lane: once the heal source is
    good again, one scrub re-uploads + hands the lane back to the
    half-open flow with its probe due, and served batches complete the
    probes back to ACTIVE — full capacity restored."""
    from language_detector_tpu.parallel.pool import (LANE_ACTIVE,
                                                     LANE_CORRUPT)
    if not any(ln.state() == LANE_CORRUPT for ln in pool.lanes):
        return None
    st["host_bad"] = False
    mon.scrub_pass()
    for ln in pool.lanes:
        if ln.state() == LANE_CORRUPT:
            return (f"lane {ln.idx} still CORRUPT after a scrub with "
                    f"a healthy heal source — heal never retried")
    for _ in range(4 * len(pool.lanes)):
        if all(ln.state() == LANE_ACTIVE for ln in pool.lanes):
            break
        pf = pool.launch(lambda lane: st["raw"])
        pool._fetch_on(pf.lane, pf.raw)
    for ln in pool.lanes:
        if ln.state() != LANE_ACTIVE:
            return (f"healed lane {ln.idx} did not re-admit to ACTIVE "
                    f"through served probe batches (state "
                    f"{ln.state()})")
    if pool.capacity()[0] < len(pool.lanes):
        return "heal converged but capacity was not fully restored"
    return None


# ---------------------------------------------------------------------
# product 7: runtime config plane (configplane.py) — canary push x
# SLO burn x probation x member crash
#
# Drives the REAL ConfigPlane (real knobs.apply_overrides validation,
# injectable clock + burn source) as the canary of a two-member fleet,
# composed with an abstract coordinator (fleet._fleet_config_push's
# canary-then-fan-out protocol) and an abstract follower member. The
# obligations from the ISSUE: a burn >= 1.0 during probation always
# rolls the batch back to the pre-push overrides; the follower never
# holds a generation the coordinator has not committed (the >= N-1
# hold); and from ANY reachable state — including a canary SIGKILL
# mid-probation — the bounded heal procedure reconverges every member
# onto the committed generation, so a stable split brain is
# unreachable.

_CFG_PROBATION = 5.0
_CFG_VALUES = ({"LDT_MAX_INFLIGHT": "64"}, {"LDT_MAX_INFLIGHT": "96"})


class _CfgModel:
    """Coordinator + real-ConfigPlane canary + abstract follower.

    The canary's knob overrides are the real process-global ones
    (knobs._OVERRIDES) — build() resets them, so every replay is
    deterministic; run_product/check clear them afterwards."""

    def __init__(self, no_rollback: bool = False):
        import logging

        from language_detector_tpu import knobs
        from language_detector_tpu.configplane import ConfigPlane

        # thousands of replayed rollbacks would each warn otherwise
        logging.getLogger(
            "language_detector_tpu.configplane").setLevel(logging.ERROR)
        knobs.clear_overrides()
        self.knobs = knobs
        self.clock = FakeClock()
        self.burn = 0.0
        if no_rollback:
            # the doctored apply path: probation ignores the burn
            # signal and commits on time alone — the
            # cfg-bad-config-rolls-back invariant must catch it
            class _NoRollbackPlane(ConfigPlane):
                def _rollback_locked(self, reason):
                    self._commit_locked()
            self._plane_cls = _NoRollbackPlane
        else:
            self._plane_cls = ConfigPlane
        self.canary = self._plane_cls(
            clock=self.clock, burn_source=lambda: self.burn)
        # coordinator (fleet supervisor) state
        self.pending_gen = None       # push in flight, not yet decided
        self.pending_values: dict = {}
        self.pre_push: dict = {}      # overrides before the apply
        self.fleet_gen = 0            # last coordinator-committed
        self.fleet_values: dict = {}
        # abstract follower member (its own process in reality)
        self.follower_gen = 0
        self.follower_values: dict = {}
        self.pushes = 0
        self.canary_crashes = 0
        self.follower_crashes = 0

    # -- coordinator --------------------------------------------------

    def push(self):
        """Coordinator stages the next batch on the canary with a
        probation window (fleet._fleet_config_push step 1)."""
        if self.pending_gen is not None or self.pushes >= 2:
            return False
        values = _CFG_VALUES[self.pushes]
        self.pushes += 1
        self.pre_push = self.knobs.current()["overrides"]
        gen = self.fleet_gen + 1
        snap = self.canary.push(values, probation_sec=_CFG_PROBATION,
                                generation=gen)
        if "error" in snap:
            return True  # refused: coordinator reports and gives up
        self.pending_gen = gen
        self.pending_values = dict(values)
        return True

    def poll(self):
        """Coordinator observes the canary's GET /configz outcome
        (step 2): commit-and-record, or abort on rollback."""
        if self.pending_gen is None:
            return False
        from language_detector_tpu.configplane import (
            CONFIG_COMMITTED, CONFIG_ROLLED_BACK)
        if self.canary.state == CONFIG_COMMITTED \
                and self.canary.generation == self.pending_gen:
            self.fleet_gen = self.pending_gen
            self.fleet_values = dict(self.pending_values)
            self.pending_gen = None
            return True
        if self.canary.state == CONFIG_ROLLED_BACK \
                and self.canary.staged_generation == self.pending_gen:
            self.pending_gen = None
            return True
        return False

    def push_timeout(self):
        """Coordinator's poll deadline fires: the canary crashed
        mid-probation and its replacement knows nothing of the staged
        generation — the push is abandoned uncommitted."""
        from language_detector_tpu.configplane import CONFIG_IDLE
        if self.pending_gen is None \
                or self.canary.state != CONFIG_IDLE:
            return False
        self.pending_gen = None
        return True

    def fanout(self):
        """Step 3 / the heal pass: push the COMMITTED batch (and only
        that) onto a drifted follower with no probation."""
        if self.fleet_gen <= 0 \
                or self.follower_gen == self.fleet_gen:
            return False
        self.follower_gen = self.fleet_gen
        self.follower_values = dict(self.fleet_values)
        return True

    def heal_canary(self):
        """The supervisor's _config_heal aimed at a respawned canary:
        re-push the committed batch with generation stamp, probation
        0."""
        if self.fleet_gen <= 0 \
                or self.canary.generation == self.fleet_gen \
                or self.pending_gen is not None:
            return False
        snap = self.canary.push(self.fleet_values, probation_sec=0,
                                generation=self.fleet_gen)
        return "error" not in snap or True

    # -- canary-side dynamics -----------------------------------------

    def burn_high(self):
        if self.burn >= 1.0:
            return False
        self.burn = 2.0
        return True

    def burn_ok(self):
        if self.burn < 1.0:
            return False
        self.burn = 0.0
        return True

    def elapse(self):
        from language_detector_tpu.configplane import CONFIG_PROBATION
        if self.canary.state != CONFIG_PROBATION \
                or self.clock() >= self.canary.probation_deadline:
            return False
        self.clock.advance(_CFG_PROBATION + 0.1)
        return True

    def tick(self):
        from language_detector_tpu.configplane import CONFIG_PROBATION
        if self.canary.state != CONFIG_PROBATION:
            return False
        self.canary.tick()
        return True

    def canary_crash(self):
        """SIGKILL mid-anything: the replacement process has a fresh
        plane and NO overrides (they lived in the dead process)."""
        if self.canary_crashes >= 1:
            return False
        self.canary_crashes += 1
        self.knobs.clear_overrides()
        self.canary = self._plane_cls(
            clock=self.clock, burn_source=lambda: self.burn)
        return True

    def follower_crash(self):
        if self.follower_crashes >= 1 or self.follower_gen == 0:
            return False
        self.follower_crashes += 1
        self.follower_gen = 0
        self.follower_values = {}
        return True


def _cfg_build():
    return (_CfgModel(),)


def doctored_config_build():
    """Negative-test build: the no-rollback apply path. Exploring the
    same events must now produce a minimal counterexample trace for
    cfg-bad-config-rolls-back."""
    return (_CfgModel(no_rollback=True),)


_CFG_EVENTS = {
    "push": lambda m: m.push(),
    "poll": lambda m: m.poll(),
    "push_timeout": lambda m: m.push_timeout(),
    "fanout": lambda m: m.fanout(),
    "heal_canary": lambda m: m.heal_canary(),
    "burn_high": lambda m: m.burn_high(),
    "burn_ok": lambda m: m.burn_ok(),
    "elapse": lambda m: m.elapse(),
    "tick": lambda m: m.tick(),
    "canary_crash": lambda m: m.canary_crash(),
    "follower_crash": lambda m: m.follower_crash(),
}


def _cfg_key(m):
    from language_detector_tpu.configplane import CONFIG_PROBATION
    deadline_passed = (m.canary.state == CONFIG_PROBATION
                       and m.clock() >= m.canary.probation_deadline)
    return (m.canary.state, m.canary.generation,
            m.canary.staged_generation,
            tuple(sorted(m.knobs.current()["overrides"].items())),
            m.burn >= 1.0, deadline_passed,
            m.pending_gen, m.fleet_gen,
            tuple(sorted(m.fleet_values.items())),
            m.follower_gen,
            tuple(sorted(m.follower_values.items())),
            m.pushes, m.canary_crashes, m.follower_crashes)


def _cfg_inv_bad_config_rolls_back(m):
    """THE rollback property: a probation observing burn >= 1.0 always
    rolls back, restoring the exact pre-push override map — the bad
    batch never commits."""
    from language_detector_tpu.configplane import (
        CONFIG_PROBATION, CONFIG_ROLLED_BACK)
    if m.canary.state != CONFIG_PROBATION or m.burn < 1.0:
        return None
    m.canary.tick()
    if m.canary.state != CONFIG_ROLLED_BACK:
        return ("probation ticked with fast burn >= 1.0 but the plane "
                "did not roll back (state "
                f"{m.canary.state})")
    if m.knobs.current()["overrides"] != m.pre_push:
        return ("rollback did not restore the pre-push overrides: "
                f"{m.knobs.current()['overrides']} != {m.pre_push}")
    return None


def _cfg_inv_follower_holds_old(m):
    """The >= N-1 hold: while a push is in flight (staged on the
    canary, not yet coordinator-committed) the follower still serves
    the OLD generation — it never sees an uncommitted batch."""
    if m.pending_gen is None:
        return None
    if m.follower_gen >= m.pending_gen:
        return (f"follower holds generation {m.follower_gen} while "
                f"generation {m.pending_gen} is still on canary "
                f"probation — the fleet lost its N-1 hold")
    return None


def _cfg_inv_no_stable_split_brain(m):
    """From ANY reachable state — canary SIGKILLed mid-probation
    included — the coordinator's resolve + heal procedure reconverges
    every member onto the committed generation and values. A crashed
    member can delay convergence, never prevent it."""
    from language_detector_tpu.configplane import CONFIG_PROBATION
    for _ in range(3):
        if m.pending_gen is None:
            break
        if m.canary.state == CONFIG_PROBATION:
            m.burn = 0.0
            m.elapse()
            m.tick()
        if not m.poll():
            m.push_timeout()
    if m.pending_gen is not None:
        return ("the coordinator could not resolve an in-flight push "
                "(neither commit, rollback, nor timeout applied)")
    m.fanout()
    m.heal_canary()
    if m.follower_gen != m.fleet_gen \
            or m.follower_values != m.fleet_values:
        return (f"follower stuck on generation {m.follower_gen} "
                f"(fleet committed {m.fleet_gen}) after the heal pass")
    if m.fleet_gen > 0:
        if m.canary.generation != m.fleet_gen:
            return (f"canary stuck on generation "
                    f"{m.canary.generation} (fleet committed "
                    f"{m.fleet_gen}) after the heal pass")
        if m.knobs.current()["overrides"] != m.fleet_values:
            return ("canary's live overrides diverge from the "
                    "committed batch after the heal pass: "
                    f"{m.knobs.current()['overrides']} != "
                    f"{m.fleet_values}")
    return None


# ---------------------------------------------------------------------
# analyzer entry point

PRODUCTS = (
    ("breaker-x-ladder", "language_detector_tpu/service/admission.py",
     _bl_build, _BL_EVENTS, _bl_key, {
         "never-serve-while-open": _bl_inv_never_serve_open,
         "closed-consec-bound": _bl_inv_closed_consec,
         "ladder-consistent": _bl_inv_ladder_consistent,
     }),
    ("pool-x-brownout", "language_detector_tpu/parallel/pool.py",
     _p2_build, _P2_EVENTS, _p2_key, {
         "evicted-pool-recovers": _p2_inv_evicted_pool_recovers,
         "probe-admitted-through-shed":
             _p2_inv_probe_admitted_through_shed,
     }),
    ("stop-forwarding", "language_detector_tpu/service/supervisor.py",
     _p3_build, _P3_EVENTS, _p3_key, {
         "sigterm-at-most-once": _p3_inv_at_most_once,
         "sigterm-delivered": _p3_inv_delivered,
     }),
    ("fleet-control", "language_detector_tpu/service/fleet.py",
     _p4_build, _P4_EVENTS, _p4_key, {
         "fleet-min-one-accepting": _p4_inv_min_one_accepting,
         "fleet-open-circuit-recovers": _p4_inv_open_recovers,
         "fleet-closed-window-bound": _p4_inv_closed_window,
     }),
    ("ring-reclaim", "language_detector_tpu/service/shmring.py",
     _r5_build, _R5_EVENTS, _r5_key, {
         "ring-every-slot-recovers": _r5_inv_recovers,
         "ring-no-premature-reclaim": _r5_inv_no_premature_reclaim,
         "ring-fenced-fail-explicitly": _r5_inv_fenced_fail_explicitly,
     }),
    ("scrub-heal", "language_detector_tpu/integrity.py",
     _p6_build, _P6_EVENTS, _p6_key, {
         "never-serve-while-corrupt": _p6_inv_never_serve_corrupt,
         "corrupt-converges-active": _p6_inv_corrupt_converges_active,
     }),
    ("config-apply", "language_detector_tpu/configplane.py",
     _cfg_build, _CFG_EVENTS, _cfg_key, {
         "cfg-bad-config-rolls-back": _cfg_inv_bad_config_rolls_back,
         "cfg-follower-holds-old": _cfg_inv_follower_holds_old,
         "cfg-no-stable-split-brain": _cfg_inv_no_stable_split_brain,
     }),
)


def run_product(name, max_depth=24, max_states=5000, build=None):
    """Explore one named product; returns (failures, n_states,
    exhausted). Test hook — check() wraps this for the CLI. `build`
    substitutes a doctored system factory (the negative tests prove
    the invariants actually bite)."""
    from language_detector_tpu import knobs
    try:
        for pname, _path, bld, events, key_fn, invs in PRODUCTS:
            if pname == name:
                return _explore(build or bld, events, key_fn, invs,
                                max_depth=max_depth,
                                max_states=max_states)
        raise KeyError(name)
    finally:
        # the config-apply product drives the real runtime-override
        # map; never leak its final replay state to the caller
        knobs.clear_overrides()


def check(root=None, files=None, products=PRODUCTS):
    """Run every product's exploration. `files` (repo-relative paths)
    restricts to products whose subject module is listed. Violations
    carry the minimal event trace that reached the failing state."""
    from language_detector_tpu import faults
    root = Path(root) if root else _REPO
    if files is not None:
        keep = {str(f) for f in files}
        products = [p for p in products if p[1] in keep]
    violations: list = []
    prev = faults.ACTIVE
    try:
        faults.configure(None)
        for name, path, build, events, key_fn, invs in products:
            failures, n_states, exhausted = _explore(
                build, events, key_fn, invs)
            from language_detector_tpu import knobs
            knobs.clear_overrides()
            if not exhausted:
                violations.append(Violation(
                    "model-check-invariant", path, 1,
                    f"[{name}] exploration hit a safety cap after "
                    f"{n_states} abstract states without closing — "
                    f"shrink the event alphabet or raise the cap"))
            for inv, trace, detail in failures:
                violations.append(Violation(
                    "model-check-invariant", path, 1,
                    f"[{name}] invariant {inv} violated after "
                    f"events {' -> '.join(trace) or '(initial)'}: "
                    f"{detail}"))
    finally:
        faults.ACTIVE = prev
    return violations, 0
