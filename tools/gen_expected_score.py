#!/usr/bin/env python3
"""Regenerate the expected-score table from a labeled corpus.

The TPU rebuild of the reference's cld2_do_score tool
(cld2_do_score.cc:34-270): detect every labeled line, and for lines
whose top-1 language matches the label, accumulate raw score and bytes
per (language, script4); each table cell is then
round(total_score * 1024 / total_bytes) — the kAvgDeltaOctaScore
"expected score per KB" that drives ReliabilityExpected
(cldutil.cc:587-605).

Input: a TSV of "code<TAB>text" lines (the eval harness format). The
label's script4 comes from the document's dominant RTypeMany span (the
reference's corpus labels carried explicit ll-Ssss scripts; TSV labels
are bare codes).

Output: an npz holding `expected_score_override` [614, 4] int16 plus a
coverage report. NOT applied to the live tables by default — a round-3
experiment showed a synthetic-corpus regeneration REGRESSING accuracy
(-42%), because expected scores trained on unrepresentative text
mis-calibrate ReliabilityExpected. Apply deliberately by copying the
array into quad_tables.npz (tools/train_quad_tables.py does this when
retraining) and re-packing the mmap artifact.

Usage:
  python3 tools/gen_expected_score.py --corpus file.tsv --out exp.npz
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))
sys.path.insert(0, str(REPO / "tools"))

from eval_corpus import iter_pairs  # noqa: E402  (tools/ sibling)


def _doc_script4(text: str, tables, reg) -> int:
    """script4 of the document's dominant RTypeMany span (Latn=0,
    Cyrl=1, Arab=2, other=3 — ops/score.py _lscript4)."""
    from language_detector_tpu.preprocess.segment import segment_text
    best = (0, 0)  # (bytes, script)
    for span in segment_text(text, tables):
        if reg.rtype(span.ulscript) >= 2 and span.text_bytes > best[0]:
            best = (span.text_bytes, span.ulscript)
    s = best[1]
    return 0 if s == 1 else 1 if s == 3 else 2 if s == 6 else 3


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", default=None,
                    help="TSV code<TAB>text (default: golden suite)")
    ap.add_argument("--out", default="expected_score.npz")
    ap.add_argument("--limit", type=int, default=None)
    args = ap.parse_args()

    from language_detector_tpu.registry import registry
    from language_detector_tpu.tables import load_tables
    tables = load_tables()

    try:
        from language_detector_tpu.models.ngram import NgramBatchEngine
        eng = NgramBatchEngine(tables, registry)
        detect = eng.detect_many
    except (ImportError, RuntimeError):
        from language_detector_tpu.engine_scalar import detect_scalar
        detect = lambda ts: [detect_scalar(t, tables, registry)  # noqa: E731
                             for t in ts]

    n_lang = registry.num_languages
    score = np.zeros((n_lang, 4), np.float64)
    byts = np.zeros((n_lang, 4), np.float64)
    n_match = n_lines = 0
    code_to_lang = registry.code_to_lang

    def flush(block):
        nonlocal n_match, n_lines
        results = detect([t for _, t in block])
        n_lines += len(block)
        for (label, text), r in zip(block, results):
            lang = code_to_lang.get(label)
            if lang is None or r.language3[0] != lang:
                continue  # only label-agreeing lines (cld2_do_score)
            s4 = _doc_script4(text, tables, registry)
            # the reference's exact accumulation (cld2_do_score.cc:255):
            # normalized_score3[0] (score per 1024 bytes) x text_bytes /
            # 1024 — including its approximation on multilingual lines,
            # where the score is normalized by per-language bytes but
            # weighted here by whole-document bytes
            score[lang, s4] += r.normalized_score3[0] * r.text_bytes \
                / 1024.0
            byts[lang, s4] += r.text_bytes
            n_match += 1

    # stream in blocks: multi-GB corpora never materialize
    block: list = []
    for pair in iter_pairs(args.corpus, args.limit):
        block.append(pair)
        if len(block) >= 65536:
            flush(block)
            block = []
    if block:
        flush(block)

    table = np.round(score * 1024.0 / np.maximum(byts, 1.0)) \
        .astype(np.int16)
    covered = int((table > 0).sum())
    cur = tables.avg_delta_octa_score.astype(np.int32)
    both = (table > 0) & (cur[:n_lang] > 0)
    drift = (np.abs(table[both] - cur[:n_lang][both]).mean()
             if both.any() else 0.0)
    np.savez_compressed(args.out, expected_score_override=table)
    print(f"{n_lines} lines, {n_match} label-agreeing; "
          f"{covered} (lang, script4) cells covered; "
          f"mean |delta| vs current table on shared cells: {drift:.1f}")
    print(f"wrote {args.out} (apply deliberately — see module docstring)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
