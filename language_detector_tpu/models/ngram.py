"""Batched n-gram detection engine: the TPU hot path.

Pipeline per batch (the TPU redesign of DetectLanguageSummaryV2,
compact_lang_det_impl.cc:1707-2106):

  host   pack_resolve    texts -> resolved hit wire (C++: segmentation,
                         hashing, table probes, repeat cache, chunking)
  device score_resolved  langprob decode + chunk totes + top-2 + reliability
  host   _doc_epilogue   DocTote replay + close pairs + unreliable removal +
                         summary language (O(1) per doc, scalar-exact)

Documents the packer flags (squeeze triggers, slot overflow) and documents
failing the recursion gate (impl.cc:1978-1991) fall back to the scalar
engine, which performs the reference's re-score recursion. Everything else
is batched: the result agrees with `detect_scalar` on every document
(tests/test_batch_agreement.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..engine_scalar import (FLAG_BEST_EFFORT, FLAG_FINISH, FLAG_REPEATS,
                             FLAG_SHORT, FLAG_SQUEEZE, FLAG_TOP40,
                             FLAG_USE_WORDS,
                             GOOD_LANG1_PERCENT, GOOD_LANG1AND2_PERCENT,
                             SHORT_TEXT_THRESH, DocTote, ScalarResult,
                             calc_summary_lang, detect_scalar,
                             extract_lang_etc, refine_close_pairs,
                             remove_unreliable)
from ..ops.device_tables import DeviceTables
from ..ops.score import score_resolved, unpack_resolved_out
from ..registry import Registry, registry as default_registry
from ..tables import ScoringTables, load_tables

# Flags the device path supports. FINISH/BEST_EFFORT alter only the
# epilogue gate; SQUEEZE/REPEATS run natively in the packer (squeeze_span /
# cheap_rep_words_inplace); TOP40/SHORT/USE_WORDS are vestigial in this
# CLD2 version (set by the recursion, read nowhere). Anything else
# (score-as-quads) routes the batch to the scalar engine.
_DEVICE_OK_FLAGS = (FLAG_FINISH | FLAG_BEST_EFFORT | FLAG_SQUEEZE |
                    FLAG_REPEATS | FLAG_TOP40 | FLAG_SHORT |
                    FLAG_USE_WORDS)

def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power-of-two >= n within [lo, hi] (shape bucketing: a small
    set of compiled programs covers every batch)."""
    b = lo
    while b < n and b < hi:
        b <<= 1
    return b


def to_wire(rb, max_slots: int, max_chunks: int, n_shards: int = 1) -> dict:
    """ResolvedBatch -> flat ragged device wire (see score_resolved_impl):
    3-4 bytes per RESOLVED hit (u16 cat_ind2 index + doc-local chunk id,
    u8 when the chunk budget fits, u16 for long single-script documents)
    + 5 bytes per chunk + 8 per doc. Misses, offsets, and fingerprints
    never cross the host->device link — the native packer already probed
    the tables, ran the quad repeat cache, assigned chunks, and rotated
    the distinct-boost lists (packer.cc ldt_pack_resolve).

    n_shards: leading shard axis size for shard_map data parallelism; docs
    split into contiguous equal groups, each flattened separately with
    shard-local doc_start offsets (parallel/mesh.py shards every leaf on
    axis 0)."""
    B, Lfull = rb.idx.shape
    assert B % n_shards == 0, (B, n_shards)
    assert max_chunks <= 0xFFFF, "chunk ids must fit the u16 wire lane"
    used_slots = max(int(rb.n_slots.max(initial=1)), 1)
    used_chunks = max(int(rb.n_chunks.max(initial=1)), 1)
    L = _bucket(used_slots, 64, max_slots)
    C = _bucket(used_chunks, 8, max_chunks)

    D = n_shards
    Bd = B // D
    n_slots = rb.n_slots.astype(np.int32)
    per_shard_total = n_slots.reshape(D, Bd).sum(axis=1)
    # 32K-slot granularity: resolved slots are ~36/doc, so power-of-two
    # bucketing would ship up to 2x padding over the slow host->device
    # link; 32K steps cap waste at ~96KB while keeping the compiled
    # program set small
    N = max(4096, -int(per_shard_total.max()) // 32768 * -32768)

    from .. import native
    wire = native.flatten_resolved_native(rb, D, N)
    if C <= 256:
        # common case: chunk ids fit u8 — halve that wire lane (the u16
        # lane exists for long single-script documents, C up to 2048)
        wire["chk"] = wire["chk"].astype(np.uint8)
    wire["cmeta"] = np.ascontiguousarray(rb.cmeta[:, :C])
    wire["cscript"] = np.ascontiguousarray(rb.cscript[:, :C])
    wire["l_iota"] = np.zeros(L, np.uint8)
    return wire


class NgramBatchEngine:
    """Batched detector over a table artifact.

    Batches are padded to power-of-two document counts so jit compiles a
    small, reusable set of programs (static [B, L] shapes).
    """

    def __init__(self, tables: ScoringTables | None = None,
                 reg: Registry | None = None, flags: int = 0,
                 max_slots: int = 2048, max_chunks: int = 64,
                 mesh=None):
        """mesh: optional jax.sharding.Mesh with a "batch" axis; when given,
        batches shard over it data-parallel (parallel/mesh.py) and the
        batch size rounds up to a multiple of the mesh size."""
        self.tables = tables or load_tables()
        self.reg = reg or default_registry
        self.flags = flags
        self.max_slots = max_slots
        self.max_chunks = max_chunks
        self.dt = DeviceTables.from_host(self.tables, self.reg)
        self.mesh = mesh
        if mesh is not None:
            from ..parallel.mesh import BATCH_AXIS, sharded_score_fn
            self._score_fn = sharded_score_fn(mesh)
            # wire shards over the batch axis only; any extra mesh axes
            # (e.g. a vestigial "model" axis) replicate
            self._mesh_size = mesh.shape[BATCH_AXIS]
        else:
            self._score_fn = score_resolved
            self._mesh_size = 1
        from .. import native
        if not native.available():
            raise RuntimeError(
                "batched engine requires the native packer "
                "(language_detector_tpu/native/build.sh); "
                "use detect_scalar without it")
        # engine-owned buffer pool: rotation is safe because only this
        # engine's pipeline (<= 4 in-flight batches) uses it
        self._buf_pool = native.BufferPool()
        import functools
        self._pack = functools.partial(native.pack_resolve_native,
                                       pool=self._buf_pool)
        # Running totals for observability (service /metrics): batches
        # scored, packer-fallback docs, and docs that failed the
        # good-answer gate into the scalar recursion
        self.stats = {"batches": 0, "fallback_docs": 0,
                      "scalar_recursion_docs": 0}
        import threading
        self._stats_lock = threading.Lock()

    # -- device dispatch ----------------------------------------------------

    def score_packed(self, rb) -> np.ndarray:
        """Run the jitted device program over a ResolvedBatch; returns the
        [B, C, 5] stacked chunk-summary array on host."""
        p = to_wire(rb, self.max_slots, self.max_chunks,
                    n_shards=self._mesh_size)
        out = np.asarray(self._score_fn(self.dt, p))
        return unpack_resolved_out(out, p["cmeta"])

    # -- public API ---------------------------------------------------------

    def detect_batch(self, texts: list[str]) -> list[ScalarResult]:
        if not texts:
            return []
        if self.flags & ~_DEVICE_OK_FLAGS:
            return [detect_scalar(t, self.tables, self.reg, self.flags)
                    for t in texts]
        packed, fut = self._dispatch(texts)
        return self._finish(texts, packed, fut)

    # documents longer than this route to a wide-slot engine (few, large
    # batches) so they stay on the device instead of overflowing the
    # standard slot budget into the scalar fallback
    LONG_DOC_BYTES = 1536
    _LONG_SLOTS = 32768
    _LONG_CHUNKS = 2048
    # mid-length docs (to ~8KB) bucket to modest L/C: decent batches are
    # safe; past that the [B, C, L] one-hot chunk matrix at the wide
    # buckets (C=2048, L=32768) costs B * 128MB bf16, so batches shrink
    _HUGE_DOC_BYTES = 8192
    _LONG_BATCH = 64
    _HUGE_BATCH = 16

    def detect_many(self, texts: list[str],
                    batch_size: int = 16384) -> list[ScalarResult]:
        """Multi-batch detection with host/device pipelining: the main
        thread packs + dispatches batch N+1 while pool workers force
        batch N's device execution and run its epilogue (both the C++
        pack and epilogue release the GIL). Long documents split off to
        a wide-slot sibling engine in small batches. Sustained-throughput
        entry point for the service layer and bench."""
        if self.flags & ~_DEVICE_OK_FLAGS or not texts:
            return self.detect_batch(texts)
        long_idx = [i for i, t in enumerate(texts)
                    if len(t) > self.LONG_DOC_BYTES // 4 and
                    len(t.encode("utf-8", "surrogatepass")) >
                    self.LONG_DOC_BYTES]
        if not long_idx:
            return self._detect_many_uniform(texts, batch_size)
        long_set = set(long_idx)
        short = [t for i, t in enumerate(texts) if i not in long_set]
        results: list = [None] * len(texts)
        short_res = self._detect_many_uniform(short, batch_size) if short \
            else []
        longs = [texts[i] for i in long_idx]
        eng = self._long_engine()
        mid = [t for t in longs
               if len(t.encode("utf-8", "surrogatepass")) <=
               self._HUGE_DOC_BYTES]
        huge = [t for t in longs
                if len(t.encode("utf-8", "surrogatepass")) >
                self._HUGE_DOC_BYTES]
        rs = eng._detect_many_uniform(mid, self._LONG_BATCH) + \
            eng._detect_many_uniform(huge, self._HUGE_BATCH)
        mid_it = iter(rs[:len(mid)])
        huge_it = iter(rs[len(mid):])
        for j, i in enumerate(long_idx):
            t = texts[i]
            if len(t.encode("utf-8", "surrogatepass")) <= \
                    self._HUGE_DOC_BYTES:
                results[i] = next(mid_it)
            else:
                results[i] = next(huge_it)
        si = 0
        for i in range(len(texts)):
            if i not in long_set:
                results[i] = short_res[si]
                si += 1
        return results

    def _detect_many_uniform(self, texts: list[str],
                             batch_size: int) -> list[ScalarResult]:
        if not texts:
            return []
        from concurrent.futures import ThreadPoolExecutor
        results: list[ScalarResult] = []
        pending: list = []
        # two workers: batch N's device fetch + epilogue overlap batch
        # N+1's C++ packing on the main thread (both release the GIL)
        with ThreadPoolExecutor(2) as pool:
            for i in range(0, len(texts), batch_size):
                chunk = texts[i:i + batch_size]
                packed, fut = self._dispatch(chunk)
                pending.append(pool.submit(self._finish, chunk, packed,
                                           fut))
                while len(pending) > 2:
                    results.extend(pending.pop(0).result())
            for f in pending:
                results.extend(f.result())
        return results

    def _long_engine(self) -> "NgramBatchEngine":
        if getattr(self, "_long_eng", None) is None:
            self._long_eng = NgramBatchEngine(
                self.tables, self.reg, self.flags,
                max_slots=self._LONG_SLOTS, max_chunks=self._LONG_CHUNKS,
                mesh=self.mesh)
            # surface the sibling's counters through this engine's stats
            self._long_eng.stats = self.stats
            self._long_eng._stats_lock = self._stats_lock
        return self._long_eng

    def _dispatch(self, texts: list[str]):
        """Pack + launch the device program asynchronously; returns
        (packed, (cmeta, device future))."""
        bsz = _next_pow2(len(texts))
        bsz += -bsz % self._mesh_size  # divisible over the mesh axis
        padded = list(texts) + [""] * (bsz - len(texts))
        packed = self._pack(padded, self.tables, self.reg,
                            max_slots=self.max_slots,
                            max_chunks=self.max_chunks, flags=self.flags)
        p = to_wire(packed, self.max_slots, self.max_chunks,
                    n_shards=self._mesh_size)
        return packed, (p["cmeta"], self._score_fn(self.dt, p))

    def _finish(self, texts: list[str], packed,
                fut) -> list[ScalarResult]:
        """Fetch the device result ((cmeta, device array)) and run the
        document epilogue. Runs on detect_many's worker pool, so stats
        updates take the lock."""
        cmeta, dev = fut
        out = unpack_resolved_out(np.asarray(dev), cmeta)
        with self._stats_lock:
            self.stats["batches"] += 1
            self.stats["fallback_docs"] += int(packed.fallback.sum())
        from .. import native
        if native.available():
            return self._epilogue_native(texts, packed, out)
        results = []
        for b, text in enumerate(texts):
            if packed.fallback[b]:
                results.append(detect_scalar(text, self.tables, self.reg,
                                             self.flags))
                continue
            r = self._doc_epilogue(packed, out, b)
            if r is None:  # failed the good-answer gate: scalar recursion
                with self._stats_lock:
                    self.stats["scalar_recursion_docs"] += 1
                r = detect_scalar(text, self.tables, self.reg, self.flags)
            results.append(r)
        return results

    def _epilogue_native(self, texts: list[str], packed,
                         out: np.ndarray) -> list[ScalarResult]:
        """Batched C++ epilogue (native/epilogue.cc). Docs that fail the
        good-answer gate re-score as a BATCH with the recursion flags
        (TOP40|REPEATS|FINISH, plus SQUEEZE for docs whose first pass
        squeezed) -- the reference's recursive DetectLanguageSummaryV2
        call (impl.cc:2061-2105) run on the device instead of per-doc in
        the scalar engine. Packer-fallback docs stay scalar."""
        from .. import native
        ep = native.epilogue_batch_native(
            out, packed.direct_adds, packed.text_bytes, packed.fallback,
            self.flags, self.reg)
        results: list = [None] * len(texts)
        retry = {False: [], True: []}  # squeezed? -> [(index, text)]
        for b, text in enumerate(texts):
            row = ep[b]
            if row[12]:  # need_scalar: fallback or gate failure
                if packed.fallback[b]:
                    results[b] = detect_scalar(text, self.tables, self.reg,
                                               self.flags)
                else:
                    retry[bool(packed.squeezed[b])].append((b, text))
                continue
            results[b] = ScalarResult(
                summary_lang=int(row[0]),
                language3=[int(row[1]), int(row[2]), int(row[3])],
                percent3=[int(row[4]), int(row[5]), int(row[6])],
                normalized_score3=[float(row[7]), float(row[8]),
                                   float(row[9])],
                text_bytes=int(row[10]),
                is_reliable=bool(row[11]))
        n_retry = len(retry[False]) + len(retry[True])
        if n_retry:
            with self._stats_lock:
                self.stats["scalar_recursion_docs"] += n_retry
            extra = FLAG_TOP40 | FLAG_REPEATS | FLAG_FINISH
            for squeezed, group in retry.items():
                if not group:
                    continue
                flags = self.flags | extra | \
                    (FLAG_SQUEEZE if squeezed else 0)
                rs = self._score_with_flags([t for _, t in group], flags)
                for (b, _), r in zip(group, rs):
                    results[b] = r
        return results

    def _score_with_flags(self, texts: list[str],
                          flags: int) -> list[ScalarResult]:
        """One device pass with explicit flags (the gate-failure retry;
        FINISH forces the gate so no further recursion happens). Docs the
        packer cannot place fall back to the scalar engine with the
        engine's own flags, exactly like a first-pass fallback.

        Packs WITHOUT the engine buffer pool: retries run on detect_many's
        worker threads while the pipeline holds up to RING same-shape
        batches alive, so a pooled retry pack could recycle a still
        in-flight batch's buffers mid-transfer."""
        from .. import native
        bsz = _next_pow2(len(texts))
        bsz += -bsz % self._mesh_size
        padded = list(texts) + [""] * (bsz - len(texts))
        packed = native.pack_resolve_native(
            padded, self.tables, self.reg, max_slots=self.max_slots,
            max_chunks=self.max_chunks, flags=flags, pool=None)
        out = self.score_packed(packed)
        ep = native.epilogue_batch_native(
            out, packed.direct_adds, packed.text_bytes, packed.fallback,
            flags, self.reg)
        results = []
        for b, text in enumerate(texts):
            row = ep[b]
            if packed.fallback[b] or row[12]:
                results.append(detect_scalar(text, self.tables, self.reg,
                                             self.flags))
                continue
            results.append(ScalarResult(
                summary_lang=int(row[0]),
                language3=[int(row[1]), int(row[2]), int(row[3])],
                percent3=[int(row[4]), int(row[5]), int(row[6])],
                normalized_score3=[float(row[7]), float(row[8]),
                                   float(row[9])],
                text_bytes=int(row[10]),
                is_reliable=bool(row[11])))
        return results

    # -- exact host epilogue ------------------------------------------------

    def _doc_epilogue(self, packed, out: np.ndarray,
                      b: int) -> ScalarResult | None:
        """DocTote replay in chunk-id (= span) order, then the document
        post-processing pipeline, byte-identical to detect_scalar
        (impl.cc:1956-2106). Returns None when the good-answer gate fails
        and the reference would recurse."""
        doc_tote = DocTote()
        direct = {int(cid): (int(lang), int(nb))
                  for cid, lang, nb in packed.direct_adds[b] if cid >= 0}
        rows = out[b]  # [C, 5] lang1, bytes, score1, rel, real
        for c in range(rows.shape[0]):
            if c in direct:
                lang, nb = direct[c]
                doc_tote.add(lang, nb, nb, 100)
            elif rows[c, 4]:
                doc_tote.add(int(rows[c, 0]), int(rows[c, 1]),
                             int(rows[c, 2]), int(rows[c, 3]))
        total_text_bytes = int(packed.text_bytes[b])
        flags = self.flags

        refine_close_pairs(self.reg, doc_tote)
        doc_tote.sort()
        lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
            doc_tote, total_text_bytes)

        good = (flags & FLAG_FINISH) or total <= SHORT_TEXT_THRESH or \
            (is_reliable and percent3[0] >= GOOD_LANG1_PERCENT) or \
            (is_reliable and
             percent3[0] + percent3[1] >= GOOD_LANG1AND2_PERCENT)
        if not good:
            return None

        if not (flags & FLAG_BEST_EFFORT):
            remove_unreliable(self.reg, doc_tote)
        doc_tote.sort()
        lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
            doc_tote, total_text_bytes)
        summary, reliable = calc_summary_lang(self.reg, lang3, percent3,
                                              total, is_reliable, flags)
        return ScalarResult(summary_lang=summary, language3=lang3,
                            percent3=percent3, normalized_score3=ns3,
                            text_bytes=total, is_reliable=reliable)
