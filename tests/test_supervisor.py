"""service/supervisor.py: restart-on-recycle loop, exit-code
propagation, and PID-1 signal forwarding, exercised against the
scriptable tests/fake_worker.py child over real subprocesses."""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from language_detector_tpu.service.recycle import RECYCLE_EXIT_CODE

REPO = Path(__file__).resolve().parent.parent
SUPERVISOR = [sys.executable, "-m",
              "language_detector_tpu.service.supervisor",
              "tests.fake_worker"]


def _run(env_extra: dict, timeout: float = 30):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(SUPERVISOR, cwd=REPO, env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


def test_child_exit_code_propagates():
    r = _run({"FAKE_WORKER_EXIT": "5"})
    assert r.returncode == 5
    assert "propagating" in r.stdout


def test_clean_exit_propagates_zero():
    r = _run({"FAKE_WORKER_EXIT": "0"})
    assert r.returncode == 0
    assert "generation 1" in r.stdout
    assert "generation 2" not in r.stdout


def test_recycle_restarts_then_propagates(tmp_path):
    marker = tmp_path / "recycled.marker"
    r = _run({"FAKE_WORKER_RECYCLE": str(marker)})
    # generation 1 exits RECYCLE_EXIT_CODE -> supervisor restarts;
    # generation 2 sees the marker and exits 0, which propagates
    assert r.returncode == 0
    assert marker.exists()
    assert "generation 1" in r.stdout and "generation 2" in r.stdout
    assert "worker recycled" in r.stdout
    assert str(RECYCLE_EXIT_CODE) not in str(r.returncode)


@pytest.mark.parametrize("signum", [signal.SIGTERM, signal.SIGINT])
def test_signal_forwarded_to_child(tmp_path, signum):
    sigfile = tmp_path / "sig.txt"
    env = dict(os.environ)
    env["FAKE_WORKER_SIGFILE"] = str(sigfile)
    proc = subprocess.Popen(SUPERVISOR, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        ready = sigfile.with_suffix(".txt.ready")
        deadline = time.time() + 20
        while not ready.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert ready.exists(), "worker never became ready"
        proc.send_signal(signum)
        rc = proc.wait(timeout=20)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # the worker received the forwarded signal, wrote it down, and
    # exited 0 — which the supervisor propagates without restarting
    assert sigfile.read_text() == str(int(signum))
    assert rc == 0
