"""Fixture: every trace-safety rule family tripped at least once."""
import jax
import numpy as np


def scorer(dt, wire):
    n = wire.sum()
    if n:                       # trace-python-branch
        pass
    x = float(n)                # trace-host-sync (host cast)
    y = n.item()                # trace-host-sync (.item)
    z = np.asarray(wire)        # trace-host-sync (np materialize)
    return x, y, z


score = jax.jit(scorer)


def launch(dt, texts):
    wire = [np.zeros(4)]
    return score(dt, wire)      # jit-shape-source (ad-hoc wire)
