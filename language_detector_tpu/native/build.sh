#!/bin/bash
# Build the native packer shared library.
#
# Owns the flag set and the ISA sidecar for EVERY build of this library:
# tools/profile_pack.py reuses it with LDT_SRC/LDT_EXTRA_FLAGS for the
# instrumented twin, so production and profile binaries can never drift
# to different compile flags.
#
#   $1               output .so name (default libldtpack.so)
#   LDT_SRC          packer source (default packer.cc)
#   LDT_EXTRA_FLAGS  extra compile flags (e.g. -DLDT_PROF)
set -e
cd "$(dirname "$0")"

# ISA sidecar writer. LOUD on failure: a silently missing sidecar used
# to force a rebuild every process; the loader now treats missing as
# "unknown, load anyway" (read-only installs), but an unwritable build
# dir is still worth a warning — this build just wrote a .so there.
write_sidecar() {
    if ! { uname -m; grep -m1 '^flags' /proc/cpuinfo 2>/dev/null | md5sum; } \
            > "$1"; then
        echo "WARNING: could not write ISA sidecar $1;" \
             "the loader will treat $2 as unknown-ISA and load it" \
             "anyway (SIGILL risk if this tree moves to different" \
             "hardware)" >&2
    fi
}

if [ "${1:-}" = "--glue-only" ]; then
    # rebuild ONLY the marshalling helper: never rewrite libldtpack.so
    # in place — it may be dlopen'd by the calling process already.
    # LDT_PYINC: the CALLING interpreter's header dir (native/__init__
    # passes it) — PATH python3 may be a different CPython, and glue
    # compiled against the wrong headers would mis-marshal silently.
    PYINC="${LDT_PYINC:-$(python3 -c 'import sysconfig; print(sysconfig.get_paths()["include"])' \
            2>/dev/null || true)}"
    if [ -n "$PYINC" ] && [ -f "$PYINC/Python.h" ]; then
        gcc -O2 -shared -fPIC -I"$PYINC" -o libldtglue.so pyglue.c
        write_sidecar libldtglue.so.host libldtglue.so
        echo "built $(pwd)/libldtglue.so"
    fi
    exit 0
fi
OUT="${1:-libldtpack.so}"
# -march=native: the library is always built on the host that runs it
# (build-on-demand via native/__init__.py; the wheel ships sources).
# The .host sidecar records the build host's ISA so the loader rebuilds
# instead of SIGILL-ing when a copied working tree lands on a host with
# a different instruction set (native/__init__.py _host_isa()).
g++ -O3 -march=native -funroll-loops ${LDT_EXTRA_FLAGS:-} \
    -shared -fPIC -std=c++17 \
    -o "$OUT" "${LDT_SRC:-packer.cc}" epilogue.cc -lpthread
write_sidecar "$OUT.host" "$OUT"
echo "built $(pwd)/$OUT"
# Optional GIL-held marshalling helper (ctypes.PyDLL; symbols resolve
# from the running interpreter, no libpython link). Best effort: hosts
# without CPython headers keep the pure-Python marshalling path.
PYINC="$(python3 -c 'import sysconfig; print(sysconfig.get_paths()["include"])' \
        2>/dev/null || true)"
if [ -n "$PYINC" ] && [ -f "$PYINC/Python.h" ]; then
    if gcc -O2 -shared -fPIC -I"$PYINC" -o libldtglue.so pyglue.c; then
        write_sidecar libldtglue.so.host libldtglue.so
        echo "built $(pwd)/libldtglue.so"
    else
        echo "WARNING: glue build failed; keeping the pure-Python" \
             "marshalling path" >&2
    fi
fi
