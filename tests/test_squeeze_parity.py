"""Anti-spam squeeze/repeat-stripping parity with the oracle
(CheapSqueeze / CheapRepWords / trigger recursion,
compact_lang_det_impl.cc:541-971, :1852-1918, :2061-2105)."""
import random

import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.registry import registry

from conftest import oracle_detect


def _cases():
    rng = random.Random(5)
    vocab = ["maison", "jardin", "fleuve", "montagne", "rivière", "forêt",
             "soleil", "lune"]
    ru = ["москва", "жизнь", "человек", "город", "страна", "время",
          "работа", "слово", "день", "рука"]
    return {
        "repeat300": "le monde est grand et la vie est belle " * 300,
        "vocab4000": " ".join(rng.choice(vocab) for _ in range(4000)),
        "ru1500": " ".join(random.Random(2).choice(ru) for _ in range(1500)),
        "spaces": ("a  b  c  d  e  f  " * 400),
        "ja_repeat": "国民の大多数が内閣を支持した。" * 500,
    }


@pytest.mark.parametrize("name", sorted(_cases()))
def test_squeeze_parity(oracle, base_tables, name):
    text = _cases()[name]
    code, _, top3, reliable, tb = oracle_detect(oracle, text.encode())
    r = detect_scalar(text, base_tables)
    mine = (registry.code(r.summary_lang), r.text_bytes,
            [(registry.code(l), p) for l, p in zip(r.language3, r.percent3)],
            r.is_reliable)
    assert mine == (code, tb, [(c, p) for c, p, _ in top3], reliable)
