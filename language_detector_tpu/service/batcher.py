"""Request batcher: many concurrent HTTP requests -> few large device
batches, with an optional bounded LRU result cache.

The reference calls the detector once per item inside the handler loop
(handlers.go:133-186, one cgo call each); the TPU redesign accumulates
items from all in-flight requests and dispatches them as one batch
(SURVEY.md §3.1), trading a small queueing delay for device efficiency.
A collector thread drains the queue, flushing when `max_batch` items are
pending or `max_delay_ms` has passed since the oldest undispatched item
arrived; flushes run on a small worker pool so batch N+1 accumulates and
dispatches while batch N is still in flight on the device — without
this, every flush pays the backend's full ~95ms dispatch latency
serially and HTTP throughput collapses to flush_size/latency.

The result cache (off by default, `cache_bytes` > 0 enables) keys on
(hints_key, normalized text) — the service normalizes via strip_extras
BEFORE submit, so equal keys imply byte-identical detector input.
Entries from requests with different hint configurations can never
serve each other: the hints_key is part of the key, full stop. At
millions-of-users scale the traffic is dominated by repeated hot
documents (retweets, boilerplate, spam campaigns), so a small cache
absorbs a large fraction of the stream before it ever reaches the
engine; the hit rate exports as a /metrics gauge.
"""
from __future__ import annotations

import inspect
import queue
import threading
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor

from .. import faults, knobs, telemetry
from ..locks import make_lock
from . import sharedcache
from .admission import (DeadlineExceeded, FairScheduler,
                        note_deadline_expired)

# concurrent flushes: >= 3 reaches the TPU tunnel's dispatch-overlap
# ceiling (models/ngram.py's scheduler pool uses the same depth)
_FLUSH_WORKERS = 3


def flush_workers() -> int:
    """Flush-worker count for both batchers: the fixed overlap depth,
    widened when the device pool runs more lanes — N lanes can carry N
    concurrent flushes (plus one accumulating), and a narrower worker
    pool would idle healthy lanes exactly when a sick lane is being
    covered for — and when the dispatch pipeline runs deeper than the
    default (LDT_PIPELINE_DEPTH batches in flight plus one packing
    need as many flush slots to stay full)."""
    return max(_FLUSH_WORKERS,
               (knobs.get_int("LDT_POOL_LANES") or 0) + 1,
               (knobs.get_int("LDT_PIPELINE_DEPTH") or 0) + 1)

_MISS = object()  # cache sentinel: any real result (even None) differs


def _accepts_trace(fn) -> bool:
    """Does this detect callable take a trace= keyword? (Both batchers
    pass the flush trace through when it does.)"""
    try:
        return "trace" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def _value_nbytes(v) -> int:
    """Charged size of a cached result: exact for the code-string
    production path, a flat estimate for result objects."""
    if isinstance(v, (str, bytes)):
        return len(v)
    return 64


class ResultCache:
    """Bounded LRU over detection results, keyed (hints_key, text).

    Byte accounting charges each entry its text bytes + result bytes +
    a fixed per-entry structure overhead, and eviction keeps the total
    at or under max_bytes — the bound is a real memory ceiling, not an
    entry count. Thread-safe: flush workers probe and fill
    concurrently."""

    ENTRY_OVERHEAD = 96  # dict slot + key tuple + bookkeeping, amortized

    def __init__(self, max_bytes: int, shared=None):
        self.max_bytes = max_bytes
        self._d: OrderedDict = OrderedDict()  # key -> (value, nbytes)
        self._lock = make_lock("batcher.result_cache")
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        # single-flight pending map: a key some in-flight flush is
        # already computing. A second flush carrying the same doc waits
        # on the Event instead of dispatching the duplicate (claim /
        # resolve below)
        self._pending: dict = {}
        # fleet-shared L2 (service/sharedcache.py): probed on an L1
        # miss, written through on fill. None unless
        # LDT_RESULT_CACHE_SHM_MB is set; `shared` overrides for tests
        self._shared = shared if shared is not None \
            else sharedcache.shared_tier()
        # artifact epoch: results are only valid against the tables
        # that produced them, so every key is namespaced by the serving
        # artifact's generation and a swap flushes the lot (set_epoch
        # in service/swap.py) — a hit can never be a stale answer from
        # the pre-swap model
        self._epoch = None

    def set_epoch(self, epoch) -> None:
        """Namespace the cache to a new artifact generation, dropping
        every entry produced under the old one. Called by swap_artifact
        after the rebind commits; idempotent for a repeated epoch."""
        with self._lock:
            if epoch == self._epoch:
                return
            self._epoch = epoch
            self._d.clear()
            self.bytes = 0
            # wake every single-flight waiter: the answer its owner is
            # computing belongs to the old artifact — waiters re-probe,
            # miss, and dispatch against the new tables themselves
            pending = list(self._pending.values())
            self._pending.clear()
        for ev in pending:
            ev.set()
        if self._shared is not None:
            self._shared.set_epoch(epoch)

    def get(self, key):
        """Returns the cached value or the module's _MISS sentinel."""
        ekey = (self._epoch,) + key
        with self._lock:
            ent = self._d.get(ekey)
            if ent is not None:
                self._d.move_to_end(ekey)
                self.hits += 1
                return ent[0]
        # L1 miss: probe the fleet-shared tier (outside the L1 lock —
        # the mmap protocol is lock-free) and promote a hit so the hot
        # doc answers from the dict next time
        if self._shared is not None:
            v = self._shared.get(key)
            if v is not None:
                self._put_local(ekey, v, key[-1])
                with self._lock:
                    self.hits += 1
                return v
        with self._lock:
            self.misses += 1
        return _MISS

    def claim(self, key):
        """Single-flight a freshly probed _MISS: returns None when the
        caller becomes the key's owner (it MUST resolve() after its
        dispatch fills — or fails to fill — the cache), else the
        threading.Event the owning flush will set. Waiters re-probe
        get() after the wait and dispatch themselves on a still-miss
        (owner failed, epoch rolled, or the wait timed out)."""
        ekey = (self._epoch,) + key
        with self._lock:
            ev = self._pending.get(ekey)
            if ev is not None:
                return ev
            self._pending[ekey] = threading.Event()
            return None

    def resolve(self, key) -> None:
        """Owner's release of a claimed key, success or failure: wakes
        every flush waiting on it. Idempotent (an epoch roll may have
        already swept the claim)."""
        ekey = (self._epoch,) + key
        with self._lock:
            ev = self._pending.pop(ekey, None)
        if ev is not None:
            ev.set()

    def _put_local(self, ekey, value, text: str):
        nbytes = (len(text.encode("utf-8", "surrogatepass")) +
                  _value_nbytes(value) + self.ENTRY_OVERHEAD)
        if nbytes > self.max_bytes:
            return  # a single oversized doc must not wipe the cache
        with self._lock:
            if ekey in self._d:
                return
            self._d[ekey] = (value, nbytes)
            self.bytes += nbytes
            while self.bytes > self.max_bytes and self._d:
                _, (_, nb) = self._d.popitem(last=False)
                self.bytes -= nb

    def put(self, key, value, text: str):
        self._put_local((self._epoch,) + key, value, text)
        # write-through: only the code-string production values travel
        # to the shared tier (its slots pack utf-8 fragments; richer
        # result objects stay per-worker)
        if self._shared is not None and isinstance(value, str):
            self._shared.put(key, value)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            out = {"hits": self.hits, "misses": self.misses,
                   "bytes": self.bytes, "entries": len(self._d),
                   "pending": len(self._pending),
                   "hit_rate": self.hits / total if total else 0.0}
        if self._shared is not None:
            out["shared"] = self._shared.stats()
        return out


class Batcher:
    """Deadline/size-batched dispatcher over a detection engine."""

    def __init__(self, detect_fn, max_batch: int = 16384,
                 max_delay_ms: float = 5.0, cache_bytes: int = 0):
        self._detect = detect_fn          # list[str] -> list[results]
        # engine-backed detect fns accept trace= and record their
        # scheduler spans into the flush trace; plain list->list
        # callables (tests, bench harnesses) are served as-is
        self._detect_takes_trace = _accepts_trace(detect_fn)
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self._cache = ResultCache(cache_bytes) if cache_bytes > 0 \
            else None
        self._q: queue.Queue = queue.Queue()
        # deficit-weighted fair queueing at dequeue (LDT_TENANT_WEIGHTS;
        # None = strict FIFO). Owned by the collector thread alone.
        self._sched = FairScheduler.from_env()
        self._stop = threading.Event()
        nw = flush_workers()
        self._pool = ThreadPoolExecutor(nw,
                                        thread_name_prefix="ldt-flush")
        # bound in-flight flushes so a backed-up device cannot pile
        # unbounded batches in memory
        self._slots = threading.Semaphore(nw + 1)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ldt-batcher")
        self._thread.start()

    def submit(self, texts: list, hints_key=None, trace=None) -> Future:
        """Queue one request's texts; resolves to their results (in
        order) once a batch containing them completes. hints_key: any
        hashable token identifying the request's hint configuration —
        cached results are only ever shared within one hints_key.
        trace: optional telemetry.Trace; the flush serving this request
        grafts its stage spans (dedup/pack/dispatch/...) into it before
        resolving the future.

        Callers: the sync front's detect closure and the UDS lane
        (wire.handle_frame) — every ingest path funnels through here,
        so the Future only gets armed once the request is certain to
        enter the queue (a fault-seam raise or post-close fail-fast
        never allocates one just to abandon it)."""
        if self._stop.is_set():
            # post-close submits fail fast instead of sitting in a
            # queue nobody drains until the caller's 60s result timeout
            closed: Future = Future()
            closed.set_exception(RuntimeError("batcher closed"))
            return closed
        if faults.ACTIVE is not None:
            # an injected queue_put error raises out of submit: the
            # handler answers it like any enqueue failure, and no
            # future enters the queue half-armed
            faults.hit("queue_put")
        fut: Future = Future()
        self._q.put((texts, hints_key, trace, fut))
        return fut

    def cache_stats(self) -> dict | None:
        """Live hit/miss/byte counters, or None when the cache is
        disabled (the /metrics exporter reads this)."""
        return self._cache.stats() if self._cache else None

    def close(self):
        """Bounded shutdown: a wedged flush (device hang) must not pin
        close() forever — the collector re-checks _stop while waiting
        for a flush slot, the join is time-limited, and the pool
        shutdown cancels queued (not yet running) flushes rather than
        waiting behind them."""
        self._stop.set()
        self._q.put(None)  # wake the collector
        self._thread.join(timeout=5)
        self._pool.shutdown(wait=False, cancel_futures=True)
        # fail whatever is still sitting in the queue: with the
        # collector gone nothing will ever drain it, and a submit()
        # caller blocked on its future would hang to its full timeout
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._fail([item], RuntimeError("batcher closed"))
        # the WFQ stash is collector-owned; with the collector joined
        # (or abandoned after its timeout) nothing else drains it
        if self._sched is not None:
            stranded = self._sched.drain_all()
            if stranded:
                self._fail(stranded, RuntimeError("batcher closed"))

    # -- collector -----------------------------------------------------------

    def _run(self):
        while not self._stop.is_set():
            sched = self._sched
            if sched is not None and sched.backlog:
                # stashed backlog exists: don't block on an empty
                # queue, just sweep in whatever already arrived
                try:
                    item = self._q.get(timeout=self.max_delay)
                except queue.Empty:
                    item = None
            else:
                item = self._q.get()
            if item is None and (sched is None or not sched.backlog):
                continue
            pending = [item] if item is not None else []
            n = len(item[0]) if item is not None else 0
            # accumulate until deadline or size cap
            import time
            deadline = time.monotonic() + self.max_delay
            while n < self.max_batch and item is not None:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                pending.append(nxt)
                n += len(nxt[0])
            if sched is not None:
                # fair queueing at dequeue: stash the sweep, pop the
                # next batch in deficit-round-robin order; whatever a
                # saturating tenant over-queued waits in its lane
                for it in pending:
                    sched.push(it)
                pending = sched.pop_batch(self.max_batch)
                if not pending:
                    continue
            if faults.ACTIVE is not None:
                # a dequeue fault fails THIS batch's waiters (typed
                # error, not a hang) and the collector moves on — the
                # collector thread itself must survive any chaos profile
                try:
                    faults.hit("queue_get")
                except faults.FaultInjected as e:
                    self._fail(pending, e)
                    continue
            # wait for a flush slot, re-checking _stop so a wedged
            # device (every slot held by a stuck flush) cannot pin the
            # collector — and with it close()'s join — forever. A
            # HEALTHY close still serves the batch in hand: the grace
            # window comfortably covers normal ~95ms flushes and stays
            # inside close()'s 5s join budget.
            import time as _time
            grace_until = None
            while not self._slots.acquire(timeout=0.5):
                if self._stop.is_set():
                    now = _time.monotonic()
                    if grace_until is None:
                        grace_until = now + 3.0
                    elif now >= grace_until:
                        self._fail(pending,
                                   RuntimeError(
                                       "batcher closed while waiting "
                                       "for a flush slot"))
                        return
            try:
                f = self._pool.submit(self._flush, pending)
                # a close() that cancels queued flushes must fail their
                # waiters, not leave them to their submit timeouts
                f.add_done_callback(
                    lambda ftr, p=pending: self._fail(
                        p, RuntimeError("batcher closed"))
                    if ftr.cancelled() else None)
            except RuntimeError as e:  # pool shut down first
                self._slots.release()
                self._fail(pending, e)
                return

    @staticmethod
    def _fail(pending: list, err: Exception):
        # done() (not just cancelled()) so _fail is idempotent: the
        # flush catch-all and the cancellation done-callback can both
        # sweep a batch whose futures already resolved
        for *_, fut in pending:
            if not fut.done():
                fut.set_exception(err)

    def _run_detect(self, texts: list, ftrace):
        if self._detect_takes_trace:
            return self._detect(texts, trace=ftrace)
        return self._detect(texts)

    @staticmethod
    def _graft(tr, ftrace):
        """Adopt the flush's stage spans as children of the request's
        (still-open) detect span, just before its future resolves."""
        if tr is not None and ftrace is not None:
            tr.graft(ftrace, depth=1)

    @staticmethod
    def _drop_expired(pending: list) -> list:
        """Dequeue-time deadline check: a request whose X-LDT-Deadline
        budget passed while it queued fails with DeadlineExceeded (the
        front answers 504) instead of burning flush capacity on an
        answer nobody is waiting for. Returns the still-live items.
        Items are (..., trace, fut) — shared with AioBatcher, whose
        3-tuples have the same tail."""
        live: list = []
        expired = 0
        for item in pending:
            tr = item[-2]
            dl = getattr(tr, "deadline", None) if tr is not None \
                else None
            if dl is not None and dl.expired():
                expired += 1
                fut = item[-1]
                if not fut.done():
                    fut.set_exception(DeadlineExceeded(
                        "deadline expired before dispatch"))
            else:
                live.append(item)
        if expired:
            note_deadline_expired(expired)
        return live

    def _flush(self, pending: list):
        try:
            pending = self._drop_expired(pending)
            if not pending:
                return
            # one flush-scoped trace shared by every traced request in
            # the batch: the engine records dedup/pack/dispatch spans
            # into it, and each request adopts a copy at resolve time
            ftrace = telemetry.Trace() \
                if any(tr is not None for _, _, tr, _ in pending) \
                else None
            if ftrace is not None:
                ftrace.adopt_constraints(tr for _, _, tr, _ in pending)
            if self._cache is None:
                texts = [t for ts, _, _, _ in pending for t in ts]
                try:
                    results = self._run_detect(texts, ftrace)
                except Exception as e:  # noqa: BLE001 - fail every waiter
                    self._fail(pending, e)
                    return
                i = 0
                for ts, _, tr, fut in pending:
                    if not fut.cancelled():
                        self._graft(tr, ftrace)
                        fut.set_result(results[i:i + len(ts)])
                    i += len(ts)
                return
            # cached flush: probe per item, detect only the misses, fill
            # the cache, then assemble each request's results in order.
            # Misses another in-flight flush already owns (single-flight
            # claim) are not re-dispatched: this flush parks them and
            # adopts the owner's fill after its own detect returns.
            plans: list = []       # one value list per request
            miss_texts: list = []
            miss_refs: list = []   # (plan, slot, key, text) — ours
            waits: list = []       # (plan, slot, key, text, event)
            owned: list = []       # keys we must resolve() no matter what
            for ts, hk, _, _ in pending:
                plan = []
                for t in ts:
                    key = (hk, t)
                    v = self._cache.get(key)
                    plan.append(v)
                    if v is _MISS:
                        ev = self._cache.claim(key)
                        if ev is None:
                            owned.append(key)
                            miss_refs.append(
                                (plan, len(plan) - 1, key, t))
                            miss_texts.append(t)
                        else:
                            waits.append(
                                (plan, len(plan) - 1, key, t, ev))
                plans.append(plan)
            try:
                miss_results = self._run_detect(miss_texts, ftrace) \
                    if miss_texts else []
            except Exception as e:  # noqa: BLE001 - fail every waiter
                for key in owned:
                    self._cache.resolve(key)  # wake waiters to retry
                self._fail(pending, e)
                return
            for (plan, slot, key, t), v in zip(miss_refs, miss_results):
                plan[slot] = v
                self._cache.put(key, v, t)
            for key in owned:
                self._cache.resolve(key)
            if waits:
                # our own claims are resolved above, so a same-flush
                # duplicate's event is already set — only genuinely
                # cross-flush waits block here, for as long as the
                # owning flush's device dispatch can take
                import time as _t
                leftover = []   # (plan, slot, key, text)
                deadline = _t.monotonic() + 30.0
                for plan, slot, key, t, ev in waits:
                    ev.wait(timeout=max(0.0,
                                        deadline - _t.monotonic()))
                    v = self._cache.get(key)
                    if v is _MISS:
                        leftover.append((plan, slot, key, t))
                    else:
                        plan[slot] = v
                if leftover:
                    # the owner failed, timed out, or an epoch roll
                    # swept its claim: score the stragglers ourselves
                    # (no re-claim — a second wait could livelock)
                    try:
                        vals = self._run_detect(
                            [t for _, _, _, t in leftover], ftrace)
                    except Exception as e:  # noqa: BLE001
                        self._fail(pending, e)
                        return
                    for (plan, slot, key, t), v in zip(leftover, vals):
                        plan[slot] = v
                        self._cache.put(key, v, t)
            for (ts, _, tr, fut), plan in zip(pending, plans):
                if not fut.cancelled():
                    self._graft(tr, ftrace)
                    fut.set_result(plan)
        except Exception as e:  # noqa: BLE001 - never orphan a waiter
            # anything unexpected (graft, cache fill, a half-resolved
            # batch) fails the REMAINING futures instead of leaving
            # them to their submit timeouts; _fail skips resolved ones
            self._fail(pending, e)
        finally:
            self._slots.release()
