"""Request batcher: many concurrent HTTP requests -> few large device
batches.

The reference calls the detector once per item inside the handler loop
(handlers.go:133-186, one cgo call each); the TPU redesign accumulates
items from all in-flight requests and dispatches them as one batch
(SURVEY.md §3.1), trading a small queueing delay for device efficiency.
A collector thread drains the queue, flushing when `max_batch` items are
pending or `max_delay_ms` has passed since the oldest undispatched item
arrived; flushes run on a small worker pool so batch N+1 accumulates and
dispatches while batch N is still in flight on the device — without
this, every flush pays the backend's full ~95ms dispatch latency
serially and HTTP throughput collapses to flush_size/latency.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import Future, ThreadPoolExecutor

# concurrent flushes: >= 3 reaches the TPU tunnel's dispatch-overlap
# ceiling (models/ngram.py _pipelined uses the same depth)
_FLUSH_WORKERS = 3


class Batcher:
    """Deadline/size-batched dispatcher over a detection engine."""

    def __init__(self, detect_fn, max_batch: int = 16384,
                 max_delay_ms: float = 5.0):
        self._detect = detect_fn          # list[str] -> list[results]
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(_FLUSH_WORKERS,
                                        thread_name_prefix="ldt-flush")
        # bound in-flight flushes so a backed-up device cannot pile
        # unbounded batches in memory
        self._slots = threading.Semaphore(_FLUSH_WORKERS + 1)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ldt-batcher")
        self._thread.start()

    def submit(self, texts: list) -> Future:
        """Queue one request's texts; resolves to their results (in
        order) once a batch containing them completes."""
        fut: Future = Future()
        self._q.put((texts, fut))
        return fut

    def close(self):
        """Bounded shutdown: a wedged flush (device hang) must not pin
        close() forever — the collector re-checks _stop while waiting
        for a flush slot, the join is time-limited, and the pool
        shutdown cancels queued (not yet running) flushes rather than
        waiting behind them."""
        self._stop.set()
        self._q.put(None)  # wake the collector
        self._thread.join(timeout=5)
        self._pool.shutdown(wait=False, cancel_futures=True)

    # -- collector -----------------------------------------------------------

    def _run(self):
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                continue
            pending = [item]
            n = len(item[0])
            # accumulate until deadline or size cap
            import time
            deadline = time.monotonic() + self.max_delay
            while n < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                pending.append(nxt)
                n += len(nxt[0])
            # wait for a flush slot, re-checking _stop so a wedged
            # device (every slot held by a stuck flush) cannot pin the
            # collector — and with it close()'s join — forever. A
            # HEALTHY close still serves the batch in hand: the grace
            # window comfortably covers normal ~95ms flushes and stays
            # inside close()'s 5s join budget.
            import time as _time
            grace_until = None
            while not self._slots.acquire(timeout=0.5):
                if self._stop.is_set():
                    now = _time.monotonic()
                    if grace_until is None:
                        grace_until = now + 3.0
                    elif now >= grace_until:
                        self._fail(pending,
                                   RuntimeError(
                                       "batcher closed while waiting "
                                       "for a flush slot"))
                        return
            try:
                f = self._pool.submit(self._flush, pending)
                # a close() that cancels queued flushes must fail their
                # waiters, not leave them to their submit timeouts
                f.add_done_callback(
                    lambda ftr, p=pending: self._fail(
                        p, RuntimeError("batcher closed"))
                    if ftr.cancelled() else None)
            except RuntimeError as e:  # pool shut down first
                self._slots.release()
                self._fail(pending, e)
                return

    @staticmethod
    def _fail(pending: list, err: Exception):
        for _, fut in pending:
            if not fut.cancelled():
                fut.set_exception(err)

    def _flush(self, pending: list):
        try:
            texts = [t for ts, _ in pending for t in ts]
            try:
                results = self._detect(texts)
            except Exception as e:  # noqa: BLE001 - fail every waiter
                for _, fut in pending:
                    if not fut.cancelled():
                        fut.set_exception(e)
                return
            i = 0
            for ts, fut in pending:
                if not fut.cancelled():
                    fut.set_result(results[i:i + len(ts)])
                i += len(ts)
        finally:
            self._slots.release()
