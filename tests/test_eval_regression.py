"""Golden-suite accuracy gate: the table-change regression tripwire.

Policy (README "Expected-score policy"): the shipped scoring tables —
quadgram weights, kAvgDeltaOctaScore expected scores, everything in
data/ — must keep golden-suite accuracy at its established level. Any
"improvement" applied to the tables (a gen_expected_score.py override,
a quad retrain, an artifact re-pack) that silently mis-calibrates
scoring fails HERE instead of shipping: a round-3 expected-score
regeneration from synthetic text regressed accuracy by 42% and was only
caught by hand.

The gate runs the scalar engine (compile-free, deterministic,
oracle-parity-pinned; the batched engines agree with it exactly per the
agreement suites, so one engine's accuracy is every engine's).
"""
from __future__ import annotations

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent))
from golden_data import golden_pairs  # noqa: E402

from language_detector_tpu.engine_scalar import detect_scalar  # noqa: E402
from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import load_tables  # noqa: E402

# Established level: 306/402 (76.1%) since round 3 (docs/eval_goldens_*).
# The floor leaves ~2% slack for genuinely neutral table rebuilds; a
# mis-calibration like the round-3 incident lands ~40 points below it.
ACCURACY_FLOOR = 0.74
ALIASES = {("hmn", "blu")}


def test_golden_accuracy_floor():
    pairs = golden_pairs()
    if not pairs:
        pytest.skip("reference snapshot unavailable")
    tables = load_tables()
    correct = 0
    for _, want, raw in pairs:
        text = raw.decode("utf-8", errors="replace")
        got = registry.code(
            detect_scalar(text, tables, registry).summary_lang)
        if got == want or (got, want) in ALIASES:
            correct += 1
    acc = correct / len(pairs)
    assert acc >= ACCURACY_FLOOR, (
        f"golden accuracy {acc:.1%} ({correct}/{len(pairs)}) fell below "
        f"the {ACCURACY_FLOOR:.0%} gate — a table change (expected-score "
        "override? quad retrain? artifact re-pack?) regressed scoring; "
        "see README 'Expected-score policy'")
