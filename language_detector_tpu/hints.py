"""Detection hints: content-language / TLD / encoding / language /
HTML lang= tags -> per-script chunk boosts and close-set whacks.

Rebuild of the reference hints engine (compact_lang_det_hint_code.cc:
941-1651 and ApplyHints, compact_lang_det_impl.cc:1587-1684). The three
hand-curated lookup tables (long lang-tags, short lang codes, TLDs) are
data extracted into the table artifact; this module implements the
merge/trim prior algebra, the HTML lang-attribute scanner, and the
conversion into the boost/whack lists that chunk scoring applies
(ScoreBoosts, scoreonescriptspan.cc:125-152).

Prior packing (OneCLDLangPrior, compact_lang_det_hint_code.h:30-44):
language id in the low 10 bits, signed weight above (lang + (w << 10)).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .registry import Registry, UNKNOWN_LANGUAGE
from .tables import ScoringTables

MAX_PRIORS = 14                 # kMaxOneCLDLangPrior
PRIOR_ENCODING_WEIGHT = 4       # kCLDPriorEncodingWeight
PRIOR_LANGUAGE_WEIGHT = 8       # kCLDPriorLanguageWeight
MAX_LANG_TAG_SCAN_BYTES = 8 << 10   # FLAGS_cld_max_lang_tag_scan_kb

# kLgProbV2Tbl backmap (cldutil_shared.h:311-314; MakeLangProb cldutil.cc:610)
_BACKMAP = [0, 0, 1, 3, 6, 10, 15, 21, 28, 36, 45, 55, 66]


@dataclasses.dataclass
class CLDHints:
    """compact_lang_det.h:134-139."""
    content_language_hint: str | None = None   # HTTP Content-Language
    tld_hint: str | None = None                # hostname last element
    encoding_hint: str | int | None = None     # legacy encoding name/id
    language_hint: int = UNKNOWN_LANGUAGE


def prior_lang(olp: int) -> int:
    return olp & 0x3FF


def prior_weight(olp: int) -> int:
    return olp >> 10  # arithmetic: weights may be negative


def _merge_max(olp: int, priors: list):
    """MergeCLDLangPriorsMax (hint_code.cc:941-956)."""
    if olp == 0:
        return
    lang = prior_lang(olp)
    for i, p in enumerate(priors):
        if prior_lang(p) == lang:
            w = max(prior_weight(p), prior_weight(olp))
            priors[i] = lang + (w << 10)
            return
    if len(priors) < MAX_PRIORS:
        priors.append(olp)


def _merge_boost(olp: int, priors: list):
    """MergeCLDLangPriorsBoost (hint_code.cc:958-973): +2 if present."""
    if olp == 0:
        return
    lang = prior_lang(olp)
    for i, p in enumerate(priors):
        if prior_lang(p) == lang:
            priors[i] = lang + ((prior_weight(p) + 2) << 10)
            return
    if len(priors) < MAX_PRIORS:
        priors.append(olp)


def _trim(priors: list, max_entries: int = 4):
    """TrimCLDLangPriors (hint_code.cc:975-996): stable sort by
    descending |weight|, keep the top max_entries."""
    if len(priors) <= max_entries:
        return priors
    priors.sort(key=lambda p: -abs(prior_weight(p)))
    del priors[max_entries:]
    return priors


class _HintTables:
    """Binary-searchable views of the artifact's hint tables."""

    def __init__(self, t: ScoringTables):
        z = t
        self.lt1 = {str(k): (int(a), int(b)) for k, a, b in
                    zip(z.langtag1_keys, z.langtag1_prior1,
                        z.langtag1_prior2)}
        self.lt2 = {str(k): (int(a), int(b)) for k, a, b in
                    zip(z.langtag2_keys, z.langtag2_prior1,
                        z.langtag2_prior2)}
        self.tld = {str(k): (int(a), int(b)) for k, a, b in
                    zip(z.tld_hint_keys, z.tld_hint_prior1,
                        z.tld_hint_prior2)}
        self.encoding_id = {str(n): i for i, n in
                            enumerate(z.encoding_names)}


_tables_cache: tuple = ()


def _hint_tables(t: ScoringTables) -> _HintTables:
    global _tables_cache
    if _tables_cache and _tables_cache[0] is t:
        return _tables_cache[1]
    h = _HintTables(t)
    _tables_cache = (t, h)
    return h


# ---------------------------------------------------------------------------
# Lang-tag list normalization + the SetCLD* family
# ---------------------------------------------------------------------------

def copy_one_quoted_string(s: str) -> str:
    """Normalize a language attribute value into a comma-separated list
    (CopyOneQuotedString's 3-state scanner, hint_code.cc:1100-1207):
    letters lowercase, underscore -> minus, tab/space/comma separate,
    any other character poisons the rest of the token (a comma is
    emitted at the start of skipping), consecutive commas collapse.
    Returns '' or a list ending in a comma."""
    out = []
    state = 1   # 0 = after letter, 1 = after comma, 2 = skipping
    for c in s:
        o = ord(c)
        if o < 256 and (0x41 <= o <= 0x5A or 0x61 <= o <= 0x7A):
            cls = "letter"
        elif c in "-_":
            cls = "minus"
        elif c in " \t,":
            cls = "comma"
        else:
            cls = "bad"
        if state == 0:
            if cls == "letter" or cls == "minus":
                out.append("-" if cls == "minus" else c.lower())
            elif cls == "comma":
                out.append(",")
                state = 1
            else:
                out.append(",")
                state = 2
        elif state == 1:
            if cls == "letter":
                out.append(c.lower())
                state = 0
            elif cls == "comma":
                pass
            else:
                state = 2
        else:  # skipping
            if cls == "comma":
                state = 1
    if state == 0:
        out.append(",")
    return "".join(out)


def set_lang_tags_hint(langtags: str, priors: list, t: ScoringTables):
    """SetCLDLangTagsHint (hint_code.cc:1394-1435): comma-separated
    normalized tags through lookup table 1 (long tags), falling back to
    table 2 with the code truncated at the first hyphen."""
    if not langtags:
        return
    if langtags.count(",") > 4:
        return
    ht = _hint_tables(t)
    for tag in langtags.split(","):
        if not tag or len(tag) > 16:
            continue
        entry = ht.lt1.get(tag)
        if entry is None:
            short = tag.split("-", 1)[0]
            if len(short) <= 3:
                entry = ht.lt2.get(short)
        if entry is not None:
            _merge_max(entry[0], priors)
            _merge_max(entry[1], priors)


def set_content_lang_hint(contentlang: str, priors: list,
                          t: ScoringTables):
    """SetCLDContentLangHint (hint_code.cc:1439-1443)."""
    set_lang_tags_hint(copy_one_quoted_string(contentlang), priors, t)


def set_tld_hint(tld: str, priors: list, t: ScoringTables):
    """SetCLDTLDHint (hint_code.cc:1446-1464)."""
    if not tld or len(tld) > 3:
        return
    entry = _hint_tables(t).tld.get(tld.lower())
    if entry is not None:
        _merge_boost(entry[0], priors)
        _merge_boost(entry[1], priors)


# SetCLDEncodingHint (hint_code.cc:1466-1501): encoding families -> lang
_ENCODING_LANG = {}
for _names, _code in [
        (("CHINESE_GB", "GBK", "GB18030", "ISO_2022_CN", "HZ_GB_2312"),
         "zh"),
        (("CHINESE_BIG5", "CHINESE_BIG5_CP950", "BIG5_HKSCS"), "zh-Hant"),
        (("JAPANESE_EUC_JP", "JAPANESE_SHIFT_JIS", "JAPANESE_CP932",
          "JAPANESE_JIS"), "ja"),
        (("KOREAN_EUC_KR", "ISO_2022_KR"), "ko")]:
    for _n in _names:
        _ENCODING_LANG[_n] = _code


def set_encoding_hint(enc: str | int, priors: list, t: ScoringTables,
                      reg: Registry):
    ht = _hint_tables(t)
    if isinstance(enc, int):
        names = list(ht.encoding_id)
        name = names[enc] if 0 <= enc < len(names) else None
    else:
        name = enc
    code = _ENCODING_LANG.get(name or "")
    if code is None:
        return
    lang = reg.code_to_lang.get(code)
    if lang is not None:
        _merge_boost(lang + (PRIOR_ENCODING_WEIGHT << 10), priors)


def set_language_hint(lang: int, priors: list):
    """SetCLDLanguageHint (hint_code.cc:1503-1508)."""
    if lang != UNKNOWN_LANGUAGE:
        _merge_boost(lang + (PRIOR_LANGUAGE_WEIGHT << 10), priors)


# ---------------------------------------------------------------------------
# HTML lang= attribute scanner (GetLangTagsFromHtml, hint_code.cc:1557-1645)
# ---------------------------------------------------------------------------

def _find_after(body: str, pos: int, max_pos: int, s: str) -> bool:
    i = pos
    while i < max_pos - len(s) and body[i] in " \"'":
        i += 1
    return body[i:i + len(s)].lower() == s


def _find_before(body: str, min_pos: int, pos: int, s: str) -> bool:
    i = pos
    while i > min_pos + len(s) and body[i - 1] == " ":
        i -= 1
    i -= len(s)
    if i < min_pos:
        return False
    return body[i:i + len(s)].lower() == s


def _find_equal_sign(body: str, pos: int, max_pos: int) -> int:
    i = pos
    while i < max_pos:
        c = body[i]
        if c == "=":
            return i
        if c in "\"'":
            q = c
            j = i + 1
            while j < max_pos:
                if body[j] == q:
                    break
                if body[j] == "\\":
                    j += 1
                j += 1
            i = j
        i += 1
    return -1


def _copy_quoted_string(body: str, pos: int, max_pos: int) -> str:
    i = pos
    while i < max_pos and body[i] == " ":
        i += 1
    if i >= max_pos or body[i] not in "\"'":
        return ""
    start = i + 1
    j = start
    while j < max_pos and body[j] not in "\"'><=&":
        j += 1
    if j >= max_pos:
        return ""
    return copy_one_quoted_string(body[start:j])


def get_lang_tags_from_html(body: str,
                            max_scan: int = MAX_LANG_TAG_SCAN_BYTES) -> str:
    """Scan the first max_scan BYTES for lang= / xml:lang= /
    <meta http-equiv=content-language content=...> attributes
    (the reference budget is bytes, not characters)."""
    if len(body) > max_scan:  # chars >= bytes, so only then can it exceed
        # surrogatepass: lone surrogates must not crash the scanner
        # (the ignore-decode then drops any split/invalid tail bytes)
        body = body.encode("utf-8", "surrogatepass")[:max_scan] \
            .decode("utf-8", "ignore")
    n = len(body)
    out = ""
    k = 0
    while k < n:
        start = body.find("<", k)
        if start < 0 or start >= n:
            break
        # FindTagEnd: stop at > (tag), or back off at < or &
        end = -1
        for i in range(start + 1, n):
            c = body[i]
            if c == ">":
                end = i
                break
            if c in "<&":
                end = i - 1
                break
        if end < 0:
            break
        if any(_find_after(body, start + 1, end, s) for s in
               ("!--", "font ", "script ", "link ", "img ", "a ")):
            k = end + 1
            continue
        in_meta = _find_after(body, start + 1, end, "meta ")
        content_is_lang = False
        kk = start + 1
        while True:
            eq = _find_equal_sign(body, kk, end)
            if eq < 0:
                break
            if in_meta:
                if _find_before(body, kk, eq, " http-equiv") and \
                        _find_after(body, eq + 1, end, "content-language "):
                    content_is_lang = True
                elif _find_before(body, kk, eq, " name") and \
                        (_find_after(body, eq + 1, end, "dc.language ") or
                         _find_after(body, eq + 1, end, "language ")):
                    content_is_lang = True
            if (content_is_lang and
                    _find_before(body, kk, eq, " content")) or \
                    _find_before(body, kk, eq, " lang") or \
                    _find_before(body, kk, eq, ":lang"):
                temp = _copy_quoted_string(body, eq + 1, end)
                if temp and temp not in out:
                    out += temp
            kk = eq + 1
        k = end + 1
    return out[:-1] if len(out) > 1 else out


# ---------------------------------------------------------------------------
# ApplyHints -> per-script boost/whack lists
# ---------------------------------------------------------------------------

class _Rotating4(list):
    """4-slot rotating langprob buffer (LangBoosts,
    scoreonescriptspan.h:70-89): past 4 entries, the oldest is
    overwritten, not the newest dropped."""

    def __init__(self):
        super().__init__()
        self._n = 0

    def add(self, lp: int):
        if len(self) < 4:
            self.append(lp)
        else:
            self[self._n] = lp
        self._n = (self._n + 1) & 3


@dataclasses.dataclass
class HintBoosts:
    """Per-script-side langprob lists for chunk scoring (ScoringContext
    langprior_boost/langprior_whack, scoreonescriptspan.h)."""
    boost_latn: _Rotating4
    boost_othr: _Rotating4
    whack_latn: _Rotating4
    whack_othr: _Rotating4

    def empty(self) -> bool:
        return not (self.boost_latn or self.boost_othr or
                    self.whack_latn or self.whack_othr)


def make_langprob(reg: Registry, lang: int, qprob: int) -> int:
    """MakeLangProb (cldutil.cc:610-614)."""
    pslang = reg.per_script_number(1, lang)
    return (pslang << 8) | _BACKMAP[max(1, min(qprob, 12))]


def prior_vector(hb: "HintBoosts | None",
                 tables: ScoringTables) -> np.ndarray | None:
    """One document's HintBoosts -> dense per-side prior vector
    [2, 256] u8 for the device reduction (LDT_HINTS=1), or None when
    the document carries no boosts.

    Each boost langprob decodes exactly as the chunk tote would decode
    a hint slot (plane 0 only — make_langprob fills one plane): pslang
    from bits 8-15, qprob from lg_prob plane 0 of the row in bits 0-7.
    The vector is the per-chunk score the reduction adds to every
    POSITIVE post-whack tote entry before the top-2 select
    (ops/score.py _chunk_out_word prior term); zero entries stay zero,
    so a prior can never promote a language with no chunk evidence."""
    if hb is None or hb.empty():
        return None
    lg3 = np.asarray(tables.lg_prob[:, 5:8], dtype=np.uint8)
    pv = np.zeros((2, 256), np.int32)
    any_set = False
    for side, boosts in ((0, hb.boost_latn), (1, hb.boost_othr)):
        for lp in list(boosts):
            if lp <= 0:
                continue
            ps = (lp >> 8) & 0xFF
            if ps == 0:
                continue
            row = min(lp & 0xFF, lg3.shape[0] - 1)
            pv[side, ps] += int(lg3[row, 0])
            any_set = True
    if not any_set:
        return None
    return np.minimum(pv, 255).astype(np.uint8)


def _is_latn_lang(reg: Registry, lang: int) -> bool:
    return int(reg.plang_to_lang_latn[reg.per_script_number(1, lang)]) \
        == lang


def _is_othr_lang(reg: Registry, lang: int) -> bool:
    return int(reg.plang_to_lang_othr[reg.per_script_number(1, lang)]) \
        == lang


def apply_hints(text: str, is_plain_text: bool, hints: CLDHints | None,
                tables: ScoringTables, reg: Registry) -> HintBoosts:
    """ApplyHints (compact_lang_det_impl.cc:1587-1684)."""
    priors: list = []
    if not is_plain_text:
        set_lang_tags_hint(get_lang_tags_from_html(text), priors, tables)
    if hints is not None:
        if hints.content_language_hint:
            set_content_lang_hint(hints.content_language_hint, priors,
                                  tables)
        if hints.tld_hint:
            set_tld_hint(hints.tld_hint, priors, tables)
        if hints.encoding_hint is not None:
            set_encoding_hint(hints.encoding_hint, priors, tables, reg)
        if hints.language_hint != UNKNOWN_LANGUAGE:
            set_language_hint(hints.language_hint, priors)
    _trim(priors, 4)

    hb = HintBoosts(_Rotating4(), _Rotating4(), _Rotating4(), _Rotating4())
    for p in priors:
        lang = prior_lang(p)
        qprob = prior_weight(p)
        if qprob > 0:
            lp = make_langprob(reg, lang, qprob)
            if _is_latn_lang(reg, lang):
                hb.boost_latn.add(lp)
            if _is_othr_lang(reg, lang):
                hb.boost_othr.add(lp)

    # Whacks: when exactly one member of a close set is hinted, suppress
    # the others (zh/zh-Hant form an honorary close pair here)
    zh = reg.code_to_lang.get("zh")
    zht = reg.code_to_lang.get("zh-Hant")
    close_count: dict = {}
    zh_count = 0
    for p in priors:
        lang = prior_lang(p)
        cs = reg.close_set(lang)
        close_count[cs] = close_count.get(cs, 0) + 1
        if lang in (zh, zht):
            zh_count += 1

    def add_whack(whacker: int, whackee: int):
        # AddOneWhack (impl.cc:1541-1561): the whacker must share the
        # script side — hr-Latn must not whack sr-Cyrl, only sr-Latn
        lp = make_langprob(reg, whackee, 1)
        if _is_latn_lang(reg, whacker) and _is_latn_lang(reg, whackee):
            hb.whack_latn.add(lp)
        if _is_othr_lang(reg, whacker) and _is_othr_lang(reg, whackee):
            hb.whack_othr.add(lp)

    for p in priors:
        lang = prior_lang(p)
        if prior_weight(p) <= 0:
            continue
        if lang == zh and zh_count == 1:
            add_whack(lang, zht)
            continue
        if lang == zht and zh_count == 1:
            add_whack(lang, zh)
            continue
        cs = reg.close_set(lang)
        if cs > 0 and close_count.get(cs) == 1:
            for lang2 in range(reg.num_languages):
                if lang2 != lang and reg.close_set(lang2) == cs:
                    add_whack(lang, lang2)
    return hb
