"""Clean twin of jit_ring_bad.py: the staging-ring reuse pattern —
wire reads only AFTER the donating call's result future resolves
(np.asarray / block_until_ready), which is when every host byte has
been copied into device buffers and the ring slot is reusable."""
import jax
import numpy as np


def score_impl(dt, wire):
    return wire * dt


score_donated = jax.jit(score_impl, donate_argnums=(1,))


def fetch_then_reuse(dt, wire, ring):
    fut = score_donated(dt, wire)
    rows = np.asarray(fut)  # resolution settles the dispatch
    meta = wire.sum()  # legal: ring-slot reuse after resolution
    ring.release(wire)
    return rows, meta


def fetch_and_read_one_statement(dt, wire, unpack):
    # the engine's fetch shape: resolve and read in one statement
    fut = score_donated(dt, wire)
    return unpack(np.asarray(fut), wire)


def block_until_ready_form(dt, wire):
    fut = score_donated(dt, wire)
    fut.block_until_ready()
    return wire.sum()
