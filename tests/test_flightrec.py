"""Crash-safe flight recorder (language_detector_tpu/flightrec.py):
ring write/read roundtrip, wraparound accounting, torn-slot rejection,
postmortem harvest, and the declared-event contract."""
from __future__ import annotations

import json
import struct

import pytest

from language_detector_tpu import flightrec
from language_detector_tpu.flightrec import (EVENTS, FILE_HDR,
                                             SLOT_HDR, FlightRecorder)


@pytest.fixture
def ring(tmp_path):
    rec = FlightRecorder(str(tmp_path / "flightrec-1.ring"), slots=8,
                         slot_bytes=256)
    yield rec
    rec.close()


def test_roundtrip_and_order(ring):
    for i in range(5):
        assert ring.emit("request_start", {"request_id": f"r{i}",
                                           "lane": "tcp"})
    info = flightrec.read_ring(ring.path)
    assert info["pid"] > 0
    assert info["events_total"] == 5
    assert [e["seq"] for e in info["events"]] == [1, 2, 3, 4, 5]
    assert [e["request_id"] for e in info["events"]] == \
        [f"r{i}" for i in range(5)]
    assert all(e["ev"] == "request_start" for e in info["events"])
    assert all(e["ts"] > 0 for e in info["events"])


def test_wraparound_keeps_newest_and_total(ring):
    for i in range(20):  # 8 slots: only the last 8 survive
        ring.emit("request_end", {"status": 200, "n": i})
    info = flightrec.read_ring(ring.path)
    assert info["events_total"] == 20
    assert len(info["events"]) == 8
    assert [e["n"] for e in info["events"]] == list(range(12, 20))


def test_oversize_payload_dropped_not_torn(ring):
    assert not ring.emit("slow_trace", {"blob": "x" * 4096})
    assert ring.emit("slow_trace", {"total_ms": 1.5})
    st = ring.stats()
    assert st["dropped"] == 1
    assert st["events_total"] == 1
    assert len(flightrec.read_ring(ring.path)["events"]) == 1


def test_torn_slot_rejected_by_reader(ring):
    """A committed seq word over a half-written payload (the one
    record in flight at SIGKILL) must be skipped, not fatal."""
    ring.emit("request_start", {"request_id": "ok"})
    # forge slot 1: commit word present, payload garbage
    off = FILE_HDR.size + 1 * ring.slot_bytes
    ring.mm[off:off + SLOT_HDR.size] = SLOT_HDR.pack(2, 40, 123.0)
    ring.mm[off + SLOT_HDR.size:off + SLOT_HDR.size + 40] = b"\xff" * 40
    info = flightrec.read_ring(ring.path)
    assert [e["request_id"] for e in info["events"]] == ["ok"]
    # a rejected record contributes nothing, not a crash
    assert info["events_total"] == 1


def test_reader_rejects_foreign_files(tmp_path):
    bad = tmp_path / "flightrec-9.ring"
    bad.write_bytes(b"NOPE" + b"\x00" * 64)
    with pytest.raises(ValueError):
        flightrec.read_ring(str(bad))
    bad.write_bytes(b"\x00" * 4)
    with pytest.raises(ValueError):
        flightrec.read_ring(str(bad))


def test_harvest_postmortem_inflight_ids(ring):
    ring.emit("request_start", {"request_id": "done", "lane": "tcp"})
    ring.emit("request_start", {"request_id": "stuck", "lane": "uds"})
    ring.emit("request_end", {"request_id": "done", "status": 200})
    pm = flightrec.harvest_postmortem(ring.path, reason="crash", rc=-9)
    assert pm["reason"] == "crash"
    assert pm["rc"] == -9
    assert pm["clean_exit"] is False
    assert pm["inflight_request_ids"] == ["stuck"]
    assert pm["events_total"] == 3
    assert pm["tail"][-1]["ev"] == "request_end"


def test_harvest_sees_clean_exit(ring):
    ring.emit("proc_start", {"role": "test"})
    ring.emit("proc_exit", {"role": "test"})
    pm = flightrec.harvest_postmortem(ring.path)
    assert pm["clean_exit"] is True
    assert pm["inflight_request_ids"] == []


def test_request_events_tagged_with_pid(ring):
    ring.emit("request_start", {"request_id": "ab12"})
    ring.emit("breaker_state", {"state": "open"})  # no request id
    evs = flightrec.request_events(ring.path)
    assert [e["request_id"] for e in evs] == ["ab12"]
    assert evs[0]["pid"] == flightrec.read_ring(ring.path)["pid"]
    # unreadable path -> [] (merge is best-effort)
    assert flightrec.request_events(ring.path + ".missing") == []


def test_emit_event_requires_declaration(monkeypatch):
    monkeypatch.setattr(flightrec, "RECORDER", None)
    with pytest.raises(KeyError):
        flightrec.emit_event("totally_rogue_event", x=1)
    # disabled recorder: declared events are an all-but-free no-op
    assert flightrec.emit_event("request_start", request_id="x") \
        is False


def test_events_registry_shape():
    assert len(EVENTS) >= 13
    for name, (category, doc) in EVENTS.items():
        assert name.replace("_", "").isalnum() and name.islower()
        assert category and doc


def test_init_from_env_and_module_emit(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setattr(flightrec, "RECORDER", None)
    rec = flightrec.init_from_env(role="test-front")
    try:
        assert rec is not None
        assert flightrec.emit_event("request_start",
                                    request_id="deadbeef", lane="tcp",
                                    none_dropped=None)
        info = flightrec.read_ring(rec.path)
        assert info["events"][0]["ev"] == "proc_start"
        assert info["events"][0]["role"] == "test-front"
        assert "none_dropped" not in info["events"][1]
        assert flightrec.stats()["events_total"] == 2
        # idempotent: a second init returns the same recorder
        assert flightrec.init_from_env() is rec
    finally:
        rec.close()
        monkeypatch.setattr(flightrec, "RECORDER", None)


def test_publish_order_commit_word_last(ring):
    """The wire contract the crash-safety argument rests on: zeroing
    just the 4-byte commit word makes the record invisible even though
    its payload bytes are intact."""
    ring.emit("fault_fired", {"point": "accept"})
    off = FILE_HDR.size
    ring.mm[off:off + 4] = struct.pack("<I", 0)
    assert flightrec.read_ring(ring.path)["events"] == []
    payload = bytes(ring.mm[off + SLOT_HDR.size:
                            off + SLOT_HDR.size + 64])
    assert json.loads(payload[:payload.index(b"}") + 1])["ev"] \
        == "fault_fired"


class _Journal:
    """mm wrapper recording every (offset, bytes) store while applying
    it — lets a test replay crash prefixes of a real emit()."""

    def __init__(self, mm):
        self.mm = mm
        self.stores: list = []

    def __setitem__(self, idx, val):
        self.mm[idx] = val
        self.stores.append((idx.start, bytes(val)))

    def __getitem__(self, idx):
        return self.mm[idx]


def test_wrap_invalidates_commit_word_before_rewrite(ring, tmp_path):
    """Regression: emit() on a wrapped slot must zero the previous
    lap's commit word BEFORE storing the new tail/payload. The old
    code's first store was the header tail, so a crash between the
    payload and the final commit left the OLD seq word presiding over
    NEW payload bytes — a torn record read_ring accepted."""
    for i in range(8):
        ring.emit("request_end", {"status": 200, "n": i})
    base = bytes(ring.mm[:])
    j = _Journal(ring.mm)
    ring.mm = j
    try:
        ring.emit("request_end", {"status": 200, "n": 8})
    finally:
        ring.mm = j.mm
    off = FILE_HDR.size            # seq 9 wraps onto slot 0
    # store order is the contract: invalidate first, commit last
    assert j.stores[0] == (off, b"\0\0\0\0")
    last_off, last_data = j.stores[-1]
    assert (last_off, len(last_data)) == (off, 4)
    assert struct.unpack("<I", last_data)[0] == 9
    # crash-replay every store prefix: the reader returns only whole
    # committed records, never an old-seq/new-payload hybrid
    allowed = {(i + 1, i) for i in range(9)}
    probe = tmp_path / "crash.ring"
    for k in range(len(j.stores) + 1):
        state = bytearray(base)
        for soff, data in j.stores[:k]:
            state[soff:soff + len(data)] = data
        probe.write_bytes(state)
        events = flightrec.read_ring(str(probe))["events"]
        seen = {(e["seq"], e["n"]) for e in events}
        assert seen <= allowed, f"torn record after {k} stores: {seen}"
        if k >= 1:                 # once invalidated, slot 0's old
            assert all(s != 1 for s, _ in seen)   # record never
                                                  # resurfaces torn
    assert (9, 8) in seen                     # full replay publishes
