"""Language / script registry.

Wraps the registry arrays from the table artifact: the 614-entry language
enum (names, ISO-639 codes, per-script 8-bit packing, close sets, closest
statistical alternates) and the 102-entry unicode-letter-script enum
(recognition type, default language). Mirrors the data contracts of the
reference's generated_language.cc / generated_ulscript.cc / lang_script.cc.
"""
from __future__ import annotations

import dataclasses
from functools import cached_property
from pathlib import Path

import numpy as np

# Well-known language ids (generated_language.h:31-647)
ENGLISH = 0
TG_UNKNOWN_LANGUAGE = 25  # "Ignore" bucket
UNKNOWN_LANGUAGE = 26

# Recognition types per script (generated_ulscript.h:26)
RTYPE_NONE = 0
RTYPE_ONE = 1
RTYPE_MANY = 2
RTYPE_CJK = 3

# Scripts (generated_ulscript.h:30-135)
ULSCRIPT_COMMON = 0
ULSCRIPT_LATIN = 1
ULSCRIPT_HANI = 24

_DATA = Path(__file__).parent / "data" / "cld2_tables.npz"


@dataclasses.dataclass
class Registry:
    """Immutable registry of languages and scripts."""

    lang_name: np.ndarray        # [614] str
    lang_code: np.ndarray        # [614] str ISO-639-1/2/3 (+ -Latn variants)
    lang_cname: np.ndarray       # [614] str C enum identifiers
    lang_scripts: np.ndarray     # [614, 4] int32 ULScript ids (0=none)
    lang_to_plang: np.ndarray    # [512] uint8 per-script language number
    plang_to_lang_latn: np.ndarray   # [256] uint16
    plang_to_lang_othr: np.ndarray   # [256] uint16
    plang_close_set_latn: np.ndarray  # [256] uint8 close-set id (0=none)
    plang_close_set_othr: np.ndarray  # [256] uint8
    closest_alt_lang: np.ndarray  # [166] int32 closest statistical alternate
    ulscript_name: np.ndarray    # [102] str
    ulscript_code: np.ndarray    # [102] str 4-letter codes
    ulscript_rtype: np.ndarray   # [102] int32 RTYPE_*
    ulscript_default_lang: np.ndarray  # [102] int32 Language

    @classmethod
    def load(cls, path: Path = _DATA) -> "Registry":
        z = np.load(path, allow_pickle=False)
        return cls(**{f.name: z[f.name] for f in dataclasses.fields(cls)})

    @property
    def num_languages(self) -> int:
        return len(self.lang_name)

    @property
    def num_scripts(self) -> int:
        return len(self.ulscript_name)

    @cached_property
    def code_to_lang(self) -> dict:
        return {str(c): i for i, c in enumerate(self.lang_code)}

    def code(self, lang: int) -> str:
        """ISO code for a language id (reference LanguageCode, lang_script.h)."""
        return str(self.lang_code[lang])

    def name(self, lang: int) -> str:
        return str(self.lang_name[lang])

    def default_language(self, ulscript: int) -> int:
        """Most common language for a script (lang_script.cc:314)."""
        return int(self.ulscript_default_lang[ulscript])

    def rtype(self, ulscript: int) -> int:
        return int(self.ulscript_rtype[ulscript])

    def per_script_number(self, ulscript: int, lang: int) -> int:
        """Pack a full language into its per-script 8-bit number
        (PerScriptNumber, lang_script.cc:320-326)."""
        if ulscript < 0 or ulscript >= self.num_scripts:
            return 0
        if int(self.ulscript_rtype[ulscript]) == 0:  # RTypeNone
            return 1
        if lang < len(self.lang_to_plang):
            return int(self.lang_to_plang[lang])
        return 0

    def from_per_script_number(self, ulscript: int, pslang: int) -> int:
        """Inverse of per_script_number, script-sensitive
        (FromPerScriptNumber, lang_script.cc:328-341)."""
        if ulscript < 0 or ulscript >= self.num_scripts:
            return UNKNOWN_LANGUAGE
        if int(self.ulscript_rtype[ulscript]) in (0, 1):  # RTypeNone/One
            return int(self.ulscript_default_lang[ulscript])
        if ulscript == ULSCRIPT_LATIN:
            return int(self.plang_to_lang_latn[pslang])
        return int(self.plang_to_lang_othr[pslang])

    @cached_property
    def _close_sets(self) -> dict:
        """Statistically-close language sets (LanguageCloseSet,
        lang_script.cc:261-303): winner-take-all groups."""
        groups = [("id", "ms"), ("bo", "dz"), ("cs", "sk"), ("zu", "xh"),
                  ("bs", "hr", "sr", "sr-ME"), ("hi", "mr", "bh", "ne"),
                  ("no", "nn", "da"), ("gl", "es", "pt"), ("rw", "rn")]
        out = {}
        for gid, codes in enumerate(groups, start=1):
            for c in codes:
                if c in self.code_to_lang:
                    out[self.code_to_lang[c]] = gid
        return out

    def close_set(self, lang: int) -> int:
        """Close-set id (id/ms, bs/hr/sr, cs/sk, no/nn/da...; 0 = none)."""
        return self._close_sets.get(lang, 0)

    def closest_alt(self, lang: int) -> int:
        """Closest statistical alternate for merging unreliable languages
        (compact_lang_det_impl.cc:259-427); UNKNOWN if none/too far."""
        if lang < len(self.closest_alt_lang):
            return int(self.closest_alt_lang[lang])
        return UNKNOWN_LANGUAGE


registry = Registry.load()
