"""Knob-registry analyzer: knobs.py is the only legal env-config read.

Four checks over language_detector_tpu/:

  knob-direct-env   any os.environ / os.getenv / os.environb touch (or
                    `from os import environ/getenv`) outside knobs.py.
                    Before the registry existed the package had ~19
                    direct reads across 7 files, each with its own
                    unset/mistype semantics; the registry is only a
                    single source of truth while new reads are banned
  knob-undeclared   a knobs.get_*/raw/is_set/value call naming a knob
                    that language_detector_tpu/knobs.py does not
                    declare (would raise KeyError at runtime — caught
                    at lint time instead)
  knob-mutable-cached
                    an accessor read of a knob declared mutable=True
                    that executes at module import time (module top
                    level, a class body, or a function default) — the
                    value freezes into a module/class attribute before
                    any POST /configz override can land, silently
                    detaching that code from the runtime config plane.
                    Mutable knobs must be read at use time (or behind
                    an overrides_version() staleness check)
  knob-docs-drift   the generated table in docs/OBSERVABILITY.md
                    (between the ldt-knob-table markers) no longer
                    matches knobs.doc_table(); regenerate with
                    `python -m tools.lint --write-knob-docs`
"""
from __future__ import annotations

import ast
import importlib.util
import sys
from pathlib import Path

from .base import (Violation, apply_suppressions, first_str_arg,
                   iter_package_files, load_source, repo_root)

KNOBS_REL = "language_detector_tpu/knobs.py"
DOCS_REL = "docs/OBSERVABILITY.md"
MARK_BEGIN = "<!-- ldt-knob-table:begin -->"
MARK_END = "<!-- ldt-knob-table:end -->"

ACCESSORS = frozenset({"raw", "is_set", "value", "get_int", "get_float",
                       "get_str", "get_bool", "get_levels"})


def declared_knobs(root: Path) -> set:
    """Knob names declared in knobs.py, by AST (no import needed)."""
    return {name for name, _mutable in _declarations(root)}


def mutable_knob_names(root: Path) -> set:
    """Knob names declared with mutable=True — the runtime-config
    subset the knob-mutable-cached rule polices."""
    return {name for name, mutable in _declarations(root) if mutable}


def _declarations(root: Path) -> list:
    sf = load_source(root / KNOBS_REL, root)
    out: list = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("Knob", "_k"):
            name = first_str_arg(node)
            if not name:
                continue
            mutable = any(
                kw.arg == "mutable" and
                isinstance(kw.value, ast.Constant) and
                kw.value.value is True
                for kw in node.keywords)
            out.append((name, mutable))
    return out


def _import_time_calls(tree) -> set:
    """ids of Call nodes that execute while the module is being
    imported: module top level, class bodies, decorator lists, and
    function default-argument expressions. Function *bodies* run at
    call time and are excluded (nested defs included — their defaults
    evaluate when the enclosing body runs, not at import)."""
    calls: set = set()

    def walk(node, at_import):
        if isinstance(node, ast.Call) and at_import:
            calls.add(id(node))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                walk(dec, at_import)
            for default in (list(node.args.defaults)
                            + [d for d in node.args.kw_defaults
                               if d is not None]):
                walk(default, at_import)
            for stmt in node.body:
                walk(stmt, False)
            return
        if isinstance(node, ast.Lambda):
            for default in (list(node.args.defaults)
                            + [d for d in node.args.kw_defaults
                               if d is not None]):
                walk(default, at_import)
            walk(node.body, False)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, at_import)

    walk(tree, True)
    return calls


def load_knobs_module(root: Path):
    """Import knobs.py standalone (it only touches the stdlib), so the
    doc-table check never drags the full package import in."""
    spec = importlib.util.spec_from_file_location(
        "_ldt_lint_knobs", root / KNOBS_REL)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves the module through sys.modules while
    # processing the Knob class; register before exec
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return mod


def generated_table(root: Path) -> str:
    return load_knobs_module(root).doc_table()


def _check_file(sf, declared: set, mutable: set, out: list):
    import_calls = _import_time_calls(sf.tree)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            bad = [a.name for a in node.names
                   if a.name in ("environ", "environb", "getenv")]
            if bad:
                out.append(Violation(
                    "knob-direct-env", sf.rel, node.lineno,
                    f"import of os.{'/'.join(bad)}: env configuration "
                    f"must go through language_detector_tpu.knobs"))
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "os" and \
                node.attr in ("environ", "environb", "getenv"):
            out.append(Violation(
                "knob-direct-env", sf.rel, node.lineno,
                f"direct os.{node.attr} access: env configuration "
                f"must go through language_detector_tpu.knobs"))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "knobs" and \
                node.func.attr in ACCESSORS:
            name = first_str_arg(node)
            if name is not None and name not in declared:
                out.append(Violation(
                    "knob-undeclared", sf.rel, node.lineno,
                    f"knob {name!r} is not declared in "
                    f"language_detector_tpu/knobs.py"))
            elif name in mutable and id(node) in import_calls:
                out.append(Violation(
                    "knob-mutable-cached", sf.rel, node.lineno,
                    f"import-time read of mutable knob {name!r}: the "
                    f"cached value can never see a POST /configz "
                    f"override; read it at use time"))


def _check_docs(root: Path, out: list):
    docs = root / DOCS_REL
    if not docs.exists():
        out.append(Violation("knob-docs-drift", DOCS_REL, 1,
                             "docs/OBSERVABILITY.md is missing"))
        return
    text = docs.read_text()
    if MARK_BEGIN not in text or MARK_END not in text:
        out.append(Violation(
            "knob-docs-drift", DOCS_REL, 1,
            f"knob-table markers ({MARK_BEGIN} / {MARK_END}) are "
            f"missing; the env-knob table must be generated, not "
            f"hand-maintained"))
        return
    current = text.split(MARK_BEGIN, 1)[1].split(MARK_END, 1)[0].strip()
    want = generated_table(root).strip()
    if current != want:
        line = text[:text.index(MARK_BEGIN)].count("\n") + 1
        out.append(Violation(
            "knob-docs-drift", DOCS_REL, line,
            "env-knob table is stale; run "
            "`python -m tools.lint --write-knob-docs`"))


def write_knob_docs(root: Path | None = None) -> bool:
    """Regenerate the docs table in place. Returns True when the file
    changed."""
    root = root or repo_root()
    docs = root / DOCS_REL
    text = docs.read_text()
    head, _, rest = text.partition(MARK_BEGIN)
    _, _, tail = rest.partition(MARK_END)
    new = (head + MARK_BEGIN + "\n" + generated_table(root).strip()
           + "\n" + MARK_END + tail)
    if new != text:
        docs.write_text(new)
        return True
    return False


def check(root: Path | None = None, files=None, check_docs=True):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    declared = declared_knobs(root)
    mutable = mutable_knob_names(root)
    violations: list = []
    n_suppressed = 0
    paths = list(iter_package_files(root)) if files is None else \
        [root / f if not Path(f).is_absolute() else Path(f)
         for f in files]
    for path in paths:
        sf = load_source(path, root)
        if sf.rel == KNOBS_REL:
            continue
        file_violations: list = []
        _check_file(sf, declared, mutable, file_violations)
        kept, ns = apply_suppressions(sf, file_violations)
        violations.extend(kept)
        n_suppressed += ns
    if check_docs and files is None:
        _check_docs(root, violations)
    return violations, n_suppressed
