"""Per-range ResultChunkVector from the batched (device) path.

The reference exposes per-byte-range languages on its main Ext entry
points (compact_lang_det.h:147-154, :380) by post-processing scored
chunks on the host (SummaryBufferToVector scoreonescriptspan.cc:389-509,
SharpenBoundaries :780-845, FinishResultVector impl.cc:1688-1704). The
batched engine does the same: the packer's want_ranges sidecars carry
per-slot span/original offsets and per-chunk original byte ranges, the
device's full-output word adds lang2/rd/rs per chunk, and this module
replays the EXACT scalar-path algorithms — boundary sharpening over the
resolved hit lanes, then the shared merge_mapped_records — so the
batched vector agrees with the scalar engine (itself oracle-pinned,
tests/test_result_vector.py) document for document.

Sharpening runs only on the vector path, exactly like the reference,
and shifts chunk byte counts BEFORE the document epilogue consumes them
— build_doc_records therefore also edits the epilogue rows in place.
"""
from __future__ import annotations

import numpy as np

from .engine_scalar import (UNKNOWN_LANGUAGE, _better_boundary,
                            _same_close_set, merge_mapped_records)
from .registry import Registry
from .tables import ScoringTables


def _sharpen_round(reg: Registry, lg: np.ndarray, ulscript: int,
                   offs: list, origs: list, lps: list,
                   chunk_starts: list, langs: list,
                   starts_out: list, deltas_out: list) -> None:
    """SharpenBoundaries (scoreonescriptspan.cc:780-845) over one hit
    round's filtered linear lanes; identical control flow to the scalar
    engine's _sharpen_boundaries. starts_out[i] (original-byte chunk
    starts) and deltas_out[i] (span-coord byte shifts) update in place;
    chunk_starts updates so later boundaries see earlier moves."""
    n = len(langs)
    if n < 2:
        return
    lps = np.asarray(lps)
    prior_linear = chunk_starts[0]
    prior_lang = langs[0]
    for i in range(1, n):
        this_lang = langs[i]
        if this_lang == prior_lang:
            prior_linear = chunk_starts[i]
            continue
        this_linear = chunk_starts[i]
        next_linear = chunk_starts[i + 1]
        if _same_close_set(reg, prior_lang, this_lang):
            prior_linear = this_linear
            prior_lang = this_lang
            continue
        pslang0 = reg.per_script_number(ulscript, prior_lang)
        pslang1 = reg.per_script_number(ulscript, this_lang)
        better = _better_boundary(lps, lg, pslang0, pslang1,
                                  prior_linear, this_linear, next_linear)
        old_offset = offs[this_linear]
        new_offset = offs[better]
        chunk_starts[i] = better
        starts_out[i] = origs[better]
        deltas_out[i] -= new_offset - old_offset
        deltas_out[i - 1] += new_offset - old_offset
        prior_linear = better
        prior_lang = this_lang


def build_doc_records(b: int, cb, rows: np.ndarray, rows2: np.ndarray,
                     cstart_flat: np.ndarray, cat_ind2: np.ndarray,
                     tables: ScoringTables, reg: Registry):
    """One packed document -> mapped chunk records for
    merge_mapped_records, or None when the doc's offsets cannot map back
    (squeeze/repeat rewrites — the caller resolves such docs via the
    scalar engine). Also applies the sharpened byte shifts to rows[:, 1]
    (the epilogue's chunk byte weights), mirroring the scalar vector
    path where sharpening precedes the DocTote adds."""
    if cb.fallback[b] or cb.squeezed[b]:
        return None
    r = cb.ranges
    g0 = int(cb.doc_chunk_start[b])
    nc = int(cb.n_chunks[b])
    idx = cb.wire["idx"].reshape(-1)
    cnsl = cb.wire["cnsl"].reshape(-1)
    cscript = cb.wire["cscript"].reshape(-1)
    soff = r["soff"].reshape(-1)
    sorig = r["sorig"].reshape(-1)
    clo = r["clo"].reshape(-1)
    chi = r["chi"].reshape(-1)
    crid = r["crid"].reshape(-1)
    cdir = r["cdir"].reshape(-1)
    lg = tables.lg_prob

    # direct-add chunk -> language (doc-local chunk position, packer
    # dadds rows [chunk_pos, lang, bytes], -1-terminated)
    dir_lang = {}
    for pos, lang, _ in cb.direct_adds[b]:
        if pos < 0:
            break
        dir_lang[int(pos)] = int(lang)

    chunks = range(g0, g0 + nc)
    if any(clo[c] < 0 for c in chunks):
        return None  # unmappable range (rewritten span)

    # per-chunk working state
    langs1 = [int(rows[c, 0]) for c in chunks]
    starts = [int(clo[c]) for c in chunks]
    deltas = [0] * nc

    # sharpen per hit round (consecutive same-rid non-direct chunks)
    i = 0
    while i < nc:
        if cdir[g0 + i]:
            i += 1
            continue
        j = i
        while j < nc and not cdir[g0 + j] and \
                crid[g0 + j] == crid[g0 + i]:
            j += 1
        if j - i >= 2:
            offs: list = []
            origs: list = []
            lps: list = []
            chunk_starts: list = []
            for k in range(i, j):
                c = g0 + k
                chunk_starts.append(len(offs))
                s0 = int(cstart_flat[c])
                for s in range(s0, s0 + int(cnsl[c])):
                    if soff[s] < 0:
                        continue  # boost/hint slot: not a linear hit
                    offs.append(int(soff[s]))
                    origs.append(int(sorig[s]))
                    lps.append(int(cat_ind2[int(idx[s])]))
            chunk_starts.append(len(offs))
            sub_starts = starts[i:j]
            sub_deltas = deltas[i:j]
            _sharpen_round(reg, lg, int(cscript[g0 + i]), offs, origs,
                           lps, chunk_starts, langs1[i:j], sub_starts,
                           sub_deltas)
            starts[i:j] = sub_starts
            deltas[i:j] = sub_deltas
        i = j

    # apply byte shifts to the epilogue rows (vector-path DocTote
    # weights use the SHARPENED chunk bytes, impl.cc:1099-1111)
    for k in range(nc):
        if deltas[k]:
            rows[g0 + k, 1] += deltas[k]

    # records in scalar round-id order: hit rounds and JustOneItem spans
    # consume ids from one sequence (scalar ctx.round_id)
    recs: list = []
    rid_seq = -1
    prev_crid = None
    for k in range(nc):
        c = g0 + k
        if cdir[c]:
            rid_seq += 1
            prev_crid = None
            recs.append((rid_seq, int(clo[c]), int(chi[c]),
                         dir_lang.get(k, UNKNOWN_LANGUAGE),
                         UNKNOWN_LANGUAGE, 100, 100, True))
            continue
        if prev_crid is None or crid[c] != prev_crid:
            rid_seq += 1
            prev_crid = crid[c]
        recs.append((rid_seq, starts[k], int(chi[c]), langs1[k],
                     int(rows2[c, 0]), int(rows2[c, 1]),
                     int(rows2[c, 2]), False))
    return recs


def chunks_for_doc(text: str, records: list, reg: Registry):
    """Mapped records -> ResultChunk vector over the original bytes."""
    raw = text.encode("utf-8", "surrogatepass")
    return merge_mapped_records(raw, records, reg)


# -- long-doc chunk merge (the engine's longdoc lane) ------------------------


def merge_longdoc_chunks(rows: np.ndarray, cb, groups: list,
                         keep_spans: bool = False):
    """Per-chunk score rows of span-aligned sub-documents -> one virtual
    document per group, ready for the flat epilogue.

    `rows` is the fetched [G, 5] chunk-summary array for a ChunkBatch
    whose B docs are sub-documents (preprocess/pack.py split_longdoc);
    `groups` lists (first_subdoc, n_subdocs) per original document, in
    order, covering all B sub-docs. Returns (merged_rows, merged_cb):
    merged_cb is a ChunkBatch-shaped view whose doc b replays exactly
    the chunk sequence the unsplit document would have produced —
    sub-doc row slices concatenate in source order, direct-add chunk
    ids shift by the chunks of prior sub-docs (they are doc-local in
    the wire, epilogue.cc ldt_epilogue_flat), text bytes sum, and
    fallback/squeeze on ANY sub-doc marks the whole document (those
    resolve via the scalar engine, same as an unsplit fallback). The
    DocTote is purely additive over chunks, so epilogue(merged) ==
    epilogue(unsplit) whenever the split was span-exact.

    keep_spans=True returns (merged_rows, merged_cb, span_rows):
    span_rows[j] lists one (row_start, n_chunks, text_bytes) record per
    sub-document of group j, with row_start indexing into merged_rows —
    the per-sub-doc verdict rows the merge used to discard (the
    LDT_SPANS surface replays the epilogue over each slice for per-span
    verdicts; tests/test_longdoc_span_merge.py pins that the retained
    slices sum exactly to the merged totals)."""
    from .native import ChunkBatch
    rows = np.asarray(rows)
    n_out = len(groups)
    total_chunks = int(cb.n_chunks.sum())
    merged_rows = np.zeros((max(total_chunks, 1), rows.shape[1]),
                           np.int32)
    # widest merged direct-add row set decides the output Dcap
    dcap = 1
    for s, n in groups:
        valid = int((cb.direct_adds[s:s + n, :, 0] >= 0).sum())
        dcap = max(dcap, valid)
    doc_chunk_start = np.zeros(n_out, np.int64)
    direct_adds = np.full((n_out, dcap, 3), -1, np.int32)
    text_bytes = np.zeros(n_out, np.int32)
    fallback = np.zeros(n_out, bool)
    squeezed = np.zeros(n_out, bool)
    n_slots = np.zeros(n_out, np.int32)
    n_chunks = np.zeros(n_out, np.int32)

    span_rows: list = [[] for _ in range(n_out)] if keep_spans else []
    pos = 0  # write cursor in merged_rows
    for j, (s, n) in enumerate(groups):
        doc_chunk_start[j] = pos
        chunk_off = 0  # doc-local chunk ids of later sub-docs shift up
        nd = 0
        for i in range(s, s + n):
            nc = int(cb.n_chunks[i])
            g0 = int(cb.doc_chunk_start[i])
            merged_rows[pos:pos + nc] = rows[g0:g0 + nc]
            if keep_spans:
                span_rows[j].append((pos, nc, int(cb.text_bytes[i])))
            for pos_d in range(cb.direct_adds.shape[1]):
                c, lang, nbytes = cb.direct_adds[i, pos_d]
                if c < 0:
                    break
                direct_adds[j, nd] = (int(c) + chunk_off, lang, nbytes)
                nd += 1
            pos += nc
            chunk_off += nc
            text_bytes[j] += int(cb.text_bytes[i])
            fallback[j] |= bool(cb.fallback[i])
            squeezed[j] |= bool(cb.squeezed[i])
            n_slots[j] += int(cb.n_slots[i])
        n_chunks[j] = chunk_off
    merged = ChunkBatch(wire={}, doc_chunk_start=doc_chunk_start,
                        direct_adds=direct_adds, text_bytes=text_bytes,
                        fallback=fallback, squeezed=squeezed,
                        n_slots=n_slots, n_chunks=n_chunks,
                        n_docs=n_out)
    if keep_spans:
        return merged_rows, merged, span_rows
    return merged_rows, merged
