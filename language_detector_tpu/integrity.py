"""Data-plane integrity: scrubbing, canaries, quarantine, auto-heal.

The serving stack survives crashed workers and evicted lanes, but a
bit-flip in the mmap'd artifact, the device-resident tables, or a
frame payload silently yields WRONG languages at full throughput with
no signal. This module makes corruption detected, attributed, and
healed:

  artifact digests   model.ldta carries a per-blob crc32 footer
                     (artifact.py) verified at every load and re-checked
                     before a swap cutover (service/swap.py refuses a
                     corrupt standby).
  device scrubbing   between flushes, on an LDT_SCRUB_INTERVAL_SEC
                     cadence, each pool lane's table planes fold to a
                     digest ON DEVICE (ops/kernels.table_digest — the
                     same reduce machinery as the fused tote) and
                     compare against the fingerprint recorded at upload
                     (ops/device_tables.fingerprint).
  golden canaries    each scrub also scores a pinned canary pack whose
                     expected codes are baked into the artifact at pack
                     time (tools/artifact_tool.py, the g/ arrays) —
                     catching compute faults a table digest can't see.
  quarantine + heal  a mismatch marks the lane CORRUPT
                     (parallel/pool.py): never drafted, excluded from
                     capacity. Heal re-uploads fresh tables from the
                     host mmap, verifies the new fingerprint, and
                     re-admits the lane through the half-open PROBING
                     flow — one healthy served batch completes it.

Every detection/heal counts into the ldt_integrity_* series and emits
a flight-recorder event; the "scrub-heal" model-check product
(tools/lint/model_check.py) proves no interleaving serves from a
CORRUPT lane and every corrupt lane converges back to ACTIVE.
"""
from __future__ import annotations

import time

import numpy as np

from . import faults, flightrec, knobs, telemetry
from .locks import make_lock

# Pinned golden-query canary pack: 8 short, unambiguous, multi-script
# docs. Baked into the artifact with their expected codes at pack time
# (tools/artifact_tool.py); the pack must stay deterministic on the
# device path (no packer fallback, no gate retry).
CANARY_DOCS = (
    "This is a simple English sentence about the weather today, "
    "which should be perfectly easy to detect.",
    "Ceci est une phrase française tout à fait ordinaire qui parle "
    "de la pluie et du beau temps.",
    "Dies ist ein ganz gewöhnlicher deutscher Satz über das Wetter "
    "und die Jahreszeiten.",
    "Esta es una frase española muy normal que habla del tiempo y "
    "de las estaciones del año.",
    "Это совершенно обычное русское предложение о погоде и "
    "временах года.",
    "これは天気と季節についてのごく普通の日本語の文章です。"
    "言語検出は簡単なはずです。",
    "هذه جملة عربية عادية تماما تتحدث عن الطقس والفصول "
    "في السنة.",
    "Αυτή είναι μια συνηθισμένη ελληνική πρόταση για τον καιρό "
    "και τις εποχές του χρόνου.",
)


def corrupt_tables(dt, seed: int):
    """Chaos helper: one seeded bit-flip in one plane of a DeviceTables
    (plane chosen by the seed, flip by faults.corrupt_buffer), arrays
    re-uploaded — models HBM corruption for the table_upload fault
    seam and the scrub chaos smoke."""
    import jax
    import jax.numpy as jnp
    leaves, treedef = jax.tree_util.tree_flatten(dt)
    i = seed % len(leaves)
    bad = faults.corrupt_buffer(np.asarray(leaves[i]), seed)
    leaves[i] = jnp.asarray(bad)
    return jax.tree_util.tree_unflatten(treedef, leaves)


class IntegrityMonitor:
    """Per-lane scrub/canary scheduler with quarantine + auto-heal.

    Decoupled from the engine through four closures so the bounded
    model checker can drive the REAL detect/heal edges against fake
    digests (tools/lint/model_check.py "scrub-heal"):

      digest_fn(lane)    -> current per-plane digest tuple of the
                            lane's device tables (on-device fold)
      reupload_fn(lane)  -> fresh tables uploaded to the lane; returns
                            the new expected fingerprint
      canary_fn(lane)    -> True when the lane's canary pack scored
                            its expected codes (None = canary off)
      expected[lane.idx] -> the fingerprint recorded at upload

    maybe_scrub() is the engine hook (models/ngram._epilogue): a
    monotonic-clock cadence gate, one scrub in flight at most, never
    raises — a scrub error counts result="error" and the flush that
    triggered it proceeds untouched."""

    def __init__(self, lanes, expected: dict, digest_fn, reupload_fn,
                 canary_fn=None, interval_sec: float = 0.0,
                 clock=None) -> None:
        self.lanes = lanes
        self.expected = expected      # lane idx -> fingerprint tuple
        self.digest_fn = digest_fn
        self.reupload_fn = reupload_fn
        self.canary_fn = canary_fn
        self.interval_sec = interval_sec
        self._clock = clock or time.monotonic
        self._lock = make_lock("integrity.scrub")
        self._last_scrub = self._clock()
        self.stats = {"scrubs": 0, "detected": 0, "healed": 0,
                      "last_scrub_ms": 0.0}

    # -- detection / heal edges (the model-checked state machine) -----

    def detect(self, lane, kind: str) -> bool:
        """Quarantine a lane the scrub or canary caught: ACTIVE ->
        CORRUPT. Returns False when the lane was already out of
        rotation (no double-count)."""
        if not lane.mark_corrupt(self._clock()):
            return False
        self.stats["detected"] += 1
        telemetry.REGISTRY.counter_inc("ldt_integrity_detected_total",
                                       kind=kind, lane=lane.name)
        flightrec.emit_event("integrity_detected", lane=lane.name,
                             kind=kind)
        flightrec.emit_event("pool_lane_state", lane=lane.name,
                             state="corrupt")
        return True

    def heal(self, lane) -> bool:
        """Re-upload fresh tables from the host copy, verify the new
        fingerprint, and hand the lane back to the pool's half-open
        flow (CORRUPT -> EVICTED with the probe immediately due; the
        next rotation admits it PROBING and one healthy served batch
        re-activates it). Returns False when the fresh upload itself
        fails verification (the lane stays quarantined; the next scrub
        retries)."""
        fp = self.reupload_fn(lane)
        self.expected[lane.idx] = fp
        if tuple(self.digest_fn(lane)) != tuple(fp):
            return False
        if not lane.mark_healed(self._clock()):
            return False
        self.stats["healed"] += 1
        telemetry.REGISTRY.counter_inc("ldt_integrity_healed_total",
                                       lane=lane.name)
        flightrec.emit_event("integrity_healed", lane=lane.name)
        return True

    # -- the scrub pass ----------------------------------------------

    def scrub_lane(self, lane) -> str:
        """One lane's scrub: digest compare, then canary. Returns the
        result label ("ok" | "mismatch" | "error")."""
        if faults.ACTIVE is not None:
            # chaos seam: a `corrupt` rule on table_upload bit-flips
            # one plane of THIS lane's device tables before the scan —
            # exactly what the scan must then catch
            seed = faults.corruption("table_upload")
            if seed is not None and lane.dt is not None:
                lane.dt = corrupt_tables(lane.dt, seed)
        if tuple(self.digest_fn(lane)) != \
                tuple(self.expected.get(lane.idx, ())):
            # detect() is a no-op for a lane already quarantined, but
            # heal() always retries: a lane whose earlier heal failed
            # (host artifact itself bad) must not be stranded CORRUPT
            self.detect(lane, "scrub")
            self.heal(lane)
            return "mismatch"
        if self.canary_fn is not None and not self.canary_fn(lane):
            self.detect(lane, "canary")
            self.heal(lane)
            return "mismatch"
        return "ok"

    def scrub_pass(self) -> None:
        """Scrub every lane once. Per-lane errors are contained: a
        lane whose digest launch itself dies counts result="error" and
        the pass moves on — the scrub must never take the flush path
        down with it."""
        t0 = self._clock()
        for lane in self.lanes:
            try:
                result = self.scrub_lane(lane)
            except Exception:  # noqa: BLE001 - scrub must not kill the flush
                result = "error"
            telemetry.REGISTRY.counter_inc("ldt_integrity_scrub_total",
                                           lane=lane.name,
                                           result=result)
        self.stats["scrubs"] += 1
        self.stats["last_scrub_ms"] = (self._clock() - t0) * 1e3

    def maybe_scrub(self) -> bool:
        """Engine hook: run a scrub pass when the cadence is due.
        Non-blocking — concurrent flushes skip instead of queueing
        behind an in-flight scrub."""
        if self.interval_sec <= 0:
            return False
        now = self._clock()
        if now - self._last_scrub < self.interval_sec:
            return False
        if not self._lock.acquire(blocking=False):
            return False
        try:
            if self._clock() - self._last_scrub < self.interval_sec:
                return False
            self.scrub_pass()
            self._last_scrub = self._clock()
            return True
        finally:
            self._lock.release()


def build_from_env(engine) -> IntegrityMonitor | None:
    """The engine's integrity monitor, or None when scrubbing is off
    (LDT_SCRUB_INTERVAL_SEC unset/0 — the epilogue hook is a single
    attribute test) or the engine has no device pool (no per-lane
    tables to scrub; artifact digests still verify at load)."""
    interval = knobs.get_float("LDT_SCRUB_INTERVAL_SEC") or 0.0
    if interval <= 0 or engine.pool is None:
        return None
    from .ops import kernels
    from .ops.score import unpack_chunks_out
    from .ops.device_tables import DeviceTables, fingerprint

    def digest_fn(lane):
        dt = lane.dt if lane.dt is not None else engine.dt
        return tuple(int(x)
                     for x in np.asarray(kernels.table_digest(dt)))

    def reupload_fn(lane):
        lane.dt = DeviceTables.from_host(engine.tables, engine.reg)
        return fingerprint(lane.dt)

    n_canary = knobs.get_int("LDT_CANARY_DOCS")
    n_canary = 8 if n_canary is None else n_canary
    # statistical canary gate: the first 8 docs are the pinned core
    # set (exact-match, any miss fails — they are chosen to be
    # unambiguous, so a single flip means real corruption); docs past
    # 8 draw deterministically from the evalsuite corpus and pass on
    # an agreement fraction >= LDT_CANARY_FLOOR, so a large canary set
    # scales confidence without turning one borderline eval doc into
    # a permanent false alarm
    n_core = min(max(0, n_canary), len(CANARY_DOCS))
    docs = list(CANARY_DOCS[:n_core])
    if n_canary > len(CANARY_DOCS):
        from .evalsuite import corpus_pairs
        extra = [t for _, t in corpus_pairs()]
        docs += extra[:n_canary - len(CANARY_DOCS)]
    floor = knobs.get_float("LDT_CANARY_FLOOR")
    floor = 0.95 if floor is None else floor
    canary_fn = None
    if docs:
        from . import native
        # expected codes: baked into the artifact at pack time (the
        # g/ canary arrays, tables.load_mmap) when present; else
        # pinned at first use from the engine's own trusted-at-init
        # tables via the scalar oracle
        state = {"expect": None}

        def expected_codes():
            if state["expect"] is None:
                baked_docs = getattr(engine.tables, "canary_docs",
                                     None)
                baked = getattr(engine.tables, "canary_codes", None)
                if baked is not None and baked_docs is not None \
                        and list(baked_docs) == docs:
                    state["expect"] = list(baked)
                else:
                    from .engine_scalar import detect_scalar
                    state["expect"] = [
                        engine.reg.code(detect_scalar(
                            t, engine.tables, engine.reg,
                            engine.flags).summary_lang)
                        for t in docs]
            return state["expect"]

        def canary_fn(lane):
            cb = native.pack_chunks_native(
                docs, engine.tables, engine.reg, flags=engine.flags,
                l_doc=engine.max_slots, c_doc=engine.max_chunks)
            fut = engine._launch_raw(cb, lane="canary",
                                     score_fn=lane.score_fn,
                                     dt=lane.dt)
            rows = unpack_chunks_out(np.asarray(fut),
                                     cb.wire["cmeta"])
            ep = native.epilogue_flat_native(rows, cb, engine.flags,
                                             engine.reg)
            got = [engine.reg.code(int(ep[b][0]))
                   for b in range(len(docs))]
            want = expected_codes()
            if got[:n_core] != want[:n_core]:
                return False
            ext_got, ext_want = got[n_core:], want[n_core:]
            if not ext_got:
                return True
            agree = sum(g == w for g, w in zip(ext_got, ext_want)) \
                / len(ext_got)
            return agree >= floor

    expected = {ln.idx: fingerprint(ln.dt)
                for ln in engine.pool.lanes if ln.dt is not None}
    return IntegrityMonitor(
        [ln for ln in engine.pool.lanes if ln.dt is not None],
        expected, digest_fn, reupload_fn, canary_fn=canary_fn,
        interval_sec=interval)


def bench_scrub_overhead(engine) -> dict | None:
    """Measure one full scrub+canary cycle on the engine's monitor
    (bench.py --smoke gate): the cycle cost amortized over the scrub
    interval must stay under 1% of serving capacity."""
    mon = getattr(engine, "integrity", None)
    if mon is None:
        return None
    mon.scrub_pass()   # warm: jit the digest fold + canary ladder
    t0 = time.monotonic()
    mon.scrub_pass()
    cycle_ms = (time.monotonic() - t0) * 1e3
    interval_ms = max(mon.interval_sec, 1e-9) * 1e3
    return {"scrub_cycle_ms": round(cycle_ms, 3),
            "interval_ms": interval_ms,
            "overhead_frac": cycle_ms / (cycle_ms + interval_ms)}
