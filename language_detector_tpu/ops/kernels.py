"""Fused scoring kernels: the chunk grid in one pass, no per-ngram HBM.

Round 14 (ROADMAP item 2). ops/score.py lowers the scorer through
generic XLA ops — three one-hot reduce passes over [G, K, 256] int32
with every intermediate eligible for an HBM round trip on a real
backend. This module provides the fused alternatives behind one knob:

  LDT_KERNEL=pallas   the Pallas kernel: langprob gather + 3-way qprob
                      decode + chunk tote + whack mask + group-in-use
                      top-2 + reliability as ONE tiled program over
                      chunk rows (grid over G; the K slot axis and the
                      256-language tote live in VMEM/registers). TPU
                      only — a non-TPU backend has no Mosaic lowering,
                      so the request degrades to the fused XLA program
                      below (interpret mode is available for parity
                      tests via LDT_KERNEL_INTERPRET).
  LDT_KERNEL=fused    the kernel's pure-XLA fallback: the same fused
                      math as a single vectorized reduction over the
                      combined [G, 3K] plane with quantized operands
                      (u8 compares, i16 accumulation, padded tables
                      from ops/device_tables.py) — byte-identical to
                      the reference program, ~1.5-2x faster on CPU.
  LDT_KERNEL=xla      the reference XLA program (ops/score.py),
                      unchanged — the conservative escape hatch.
  LDT_KERNEL=lax      a jax.lax.scan reference path: one slot column
                      per step, nothing wider than [G, 256] live.
                      Debugging/parity oracle, not a serving mode.
  LDT_KERNEL=auto     pallas on TPU, fused elsewhere (the default).

Every mode is bit-identical to ops/score.py and to the scalar engine
(tests/test_kernel_parity.py fuzzes adversarial grids; the
batch-agreement suite pins end-to-end equality). Exactness of the
quantized accumulators is an invariant, not luck: chunk totes are
bounded by K(256) x 3 planes x qprob_max, and DeviceTables.from_host
rejects tables whose qprob_max would let an int16 tote overflow
(_validate_qprobs).
"""
from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp

from .. import knobs
from .device_tables import DeviceTables, digest_arrays
from .score import (HINT_BASE, _chunk_out_word, _decode3, _lscript4,
                    _reliability_delta, _reliability_expected,
                    score_chunks, score_chunks_donated,
                    score_chunks_full)

_log = logging.getLogger(__name__)

try:  # gate, don't require: CPU wheels without Pallas still serve
    from jax.experimental import pallas as pl
    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001 - any import failure means "no pallas"
    pl = None
    _HAVE_PALLAS = False

# Pallas tile: chunk rows per program instance. 8 sublanes x 128 lanes
# is the f32/i32 min tile; the kernel's widest live value is the
# [TILE_G, 3K, 256] one-hot select (8 x 768 x 256 i16 = 3MB at K=256)
# plus the [TILE_G, 256] tote — comfortably inside a 16MB VMEM budget
# (docs/PERF.md round 14 carries the full math).
TILE_G = 8


def _gather_wire(dt: DeviceTables, p: dict):
    """Shared wire prologue: the idx -> langprob gather and chunk-meta
    decode, line-for-line the same math as score_chunks_impl
    (ops/score.py) so every kernel mode scores the identical [G, K]
    langprob grid. Returns (lp, cbytes, grams, side, real, script,
    wmask-or-None, prior-or-None); lp is zero outside each chunk's
    slot count."""
    idxf = p["idx"].reshape(-1)
    N = idxf.shape[0]
    cnsl2 = p["cnsl"].astype(jnp.int32)            # [D, Gs]
    cstart = (jnp.cumsum(cnsl2, axis=-1) - cnsl2).reshape(-1)
    cnsl = cnsl2.reshape(-1)
    cmeta = p["cmeta"].reshape(-1).astype(jnp.uint32)
    K = p["k_iota"].shape[0]

    ki = jnp.arange(K, dtype=jnp.int32)
    valid = ki[None, :] < cnsl[:, None]
    gidx = jnp.clip(cstart[:, None] + ki[None, :], 0, N - 1)
    raw = idxf[gidx].astype(jnp.int32)
    hint_lp = p["hint_lp"]
    H = hint_lp.shape[0]
    lp_tbl = dt.cat_ind2[jnp.clip(raw, 0, dt.cat_ind2.shape[0] - 1)]
    lp_hint = hint_lp[jnp.clip(raw - HINT_BASE, 0, H - 1)]
    lp = jnp.where(valid,
                   jnp.where(raw >= HINT_BASE, lp_hint, lp_tbl), 0)

    cbytes = (cmeta & jnp.uint32(0xFFFF)).astype(jnp.int32)
    grams = ((cmeta >> 16) & jnp.uint32(0xFFF)).astype(jnp.int32)
    side = ((cmeta >> 28) & jnp.uint32(1)).astype(jnp.int32)
    real = ((cmeta >> 29) & jnp.uint32(1)).astype(jnp.int32)
    script = p["cscript"].reshape(-1).astype(jnp.int32)

    if p["cwhack"].shape[-1] == 1:
        wmask = None  # hint-free batch: the whack gather drops out
    else:
        cwhack = p["cwhack"].reshape(-1).astype(jnp.int32)
        wmask = p["whack_tbl"][jnp.clip(cwhack, 0,
                                        p["whack_tbl"].shape[0] - 1),
                               side]
    if "cprior" in p:  # ldt-lint: disable=trace-python-branch -- dict-key membership on the wire dict is a trace-time structural test (like the cwhack shape test above), not a traced value
        # LDT_HINTS=1 per-doc prior planes (see score_chunks_impl)
        cprior = p["cprior"].reshape(-1).astype(jnp.int32)
        prior = p["prior_tbl"][
            jnp.clip(cprior, 0, p["prior_tbl"].shape[0] - 1),
            side].astype(jnp.int32)
    else:
        prior = None
    return lp, cbytes, grams, side, real, script, wmask, prior


# ---------------------------------------------------------------------------
# Fused XLA path: the Pallas kernel's portable fallback.
#
# One reduction instead of three: the 3 pslang planes concatenate into
# a single [G, 3K] plane, the one-hot compare runs on u8 (pslangs and
# the lane iota both fit a byte), the select/accumulate runs on int16
# (totes bounded < 2^15, enforced at table load), and the qprob decode
# gathers from the 128-lane-padded lg_prob3_pad — no clip, rows >= 240
# replicate the clamp row so out-of-range decodes match XLA's clamped
# gather bit-for-bit.
# ---------------------------------------------------------------------------


def score_chunks_fused_impl(dt: DeviceTables, p: dict,
                            full_out: bool = False):
    lp, cbytes, grams, side, real, script, wmask, prior = \
        _gather_wire(dt, p)
    G = lp.shape[0]
    K = lp.shape[1]

    lpu = lp.astype(jnp.uint32)
    ps = jnp.stack([(lpu >> 8) & 0xFF, (lpu >> 16) & 0xFF,
                    (lpu >> 24) & 0xFF], axis=-1).astype(jnp.uint8)
    row = (lpu & 0xFF).astype(jnp.int32)
    q = dt.lg_prob3_pad[row]                       # [G, K, 3] u8
    contrib = jnp.where(ps > 0, q, 0)              # u8: qprob or nothing

    psf = ps.reshape(G, 3 * K)
    contribf = contrib.reshape(G, 3 * K).astype(jnp.int16)
    iota256 = jnp.arange(256, dtype=jnp.uint8)
    sel = jnp.where(psf[..., None] == iota256, contribf[..., None],
                    jnp.int16(0))
    scores = jnp.sum(sel, axis=1, dtype=jnp.int16).astype(jnp.int32)

    if wmask is None:
        whacked = scores
    else:
        whacked = jnp.where(wmask > 0, 0, scores)
    return _chunk_out_word(dt, whacked, cbytes, grams, side, real,
                           script, group_scores=scores,
                           full_out=full_out, prior=prior)


score_chunks_fused = jax.jit(score_chunks_fused_impl)
score_chunks_fused_full = jax.jit(
    lambda dt, p: score_chunks_fused_impl(dt, p, full_out=True))
# donated variant: same wire-donation contract as score_chunks_donated
# (ops/score.py) — host numpy inputs copy synchronously, the staging
# ring reuses its arrays once the launch returns
score_chunks_fused_donated = jax.jit(score_chunks_fused_impl,
                                     donate_argnums=(1,))


# ---------------------------------------------------------------------------
# lax reference path: one slot column per scan step. Nothing wider
# than [G, 256] is ever live, which makes it the memory-floor oracle
# the parity fuzz compares the wide paths against.
# ---------------------------------------------------------------------------


def score_chunks_lax_impl(dt: DeviceTables, p: dict,
                          full_out: bool = False):
    lp, cbytes, grams, side, real, script, wmask, prior = \
        _gather_wire(dt, p)
    G = lp.shape[0]
    iota256 = jnp.arange(256, dtype=jnp.int32)

    def _tote_column(scores, lp_col):
        ps, row = _decode3(lp_col)                 # [G, 3]
        q = dt.lg_prob3[row].astype(jnp.int32)
        for j in range(3):
            contrib = jnp.where(ps[:, j] > 0, q[:, j], 0)
            scores = scores + jnp.where(ps[:, j, None] == iota256,
                                        contrib[:, None], 0)
        return scores, None

    scores, _ = jax.lax.scan(_tote_column,
                             jnp.zeros((G, 256), jnp.int32), lp.T)
    if wmask is None:
        whacked = scores
    else:
        whacked = jnp.where(wmask > 0, 0, scores)
    return _chunk_out_word(dt, whacked, cbytes, grams, side, real,
                           script, group_scores=scores,
                           full_out=full_out, prior=prior)


score_chunks_lax = jax.jit(score_chunks_lax_impl)
score_chunks_lax_full = jax.jit(
    lambda dt, p: score_chunks_lax_impl(dt, p, full_out=True))
score_chunks_lax_donated = jax.jit(score_chunks_lax_impl,
                                   donate_argnums=(1,))


# ---------------------------------------------------------------------------
# Pallas kernel: decode + tote + whack + top-2 + reliability fused in
# one tiled program. The grid runs over chunk-row tiles of TILE_G; each
# program instance holds its [TILE_G, K] langprob block, the small
# quantized tables, and the [TILE_G, 256] tote entirely in VMEM, and
# writes both packed output words — no intermediate tensor ever reaches
# HBM. The idx -> langprob gather stays in XLA (two gathers over the
# few-MB cat_ind2; a table that size is HBM-resident either way), so
# the kernel's inputs are dense blocks with trivial index maps.
# ---------------------------------------------------------------------------


def _fused_tote_kernel(lp_ref, meta_ref, script_ref, wmask_ref,
                       prior_ref, lg3_ref, exp_ref, p2l_ref, close_ref,
                       out_ref):
    """One [TILE_G, K] tile: tote + whack + prior + top-2 +
    reliability."""
    lp = lp_ref[...].astype(jnp.uint32)            # [TG, K]
    tg = lp.shape[0]
    ps = jnp.stack([(lp >> 8) & 0xFF, (lp >> 16) & 0xFF,
                    (lp >> 24) & 0xFF], axis=-1).astype(jnp.uint8)
    row = (lp & 0xFF).astype(jnp.int32)
    q = jnp.take(lg3_ref[...], row.reshape(-1), axis=0) \
        .reshape(ps.shape)                         # [TG, K, 3] u8
    contrib = jnp.where(ps > 0, q, 0)

    psf = ps.reshape(tg, -1)
    contribf = contrib.reshape(tg, -1).astype(jnp.int16)
    iota256 = jax.lax.broadcasted_iota(jnp.uint8, (1, 1, 256), 2)
    sel = jnp.where(psf[..., None] == iota256, contribf[..., None],
                    jnp.int16(0))
    group_scores = jnp.sum(sel, axis=1,
                           dtype=jnp.int16).astype(jnp.int32)
    wmask = wmask_ref[...]
    scores = jnp.where(wmask > 0, 0, group_scores)
    # hint prior (LDT_HINTS=1): all-zero plane when hints are off, so
    # the add is the identity — matches the gated term bit-for-bit
    prior = prior_ref[...].astype(jnp.int32)
    scores = jnp.where(scores > 0, scores + prior, scores)

    # group-in-use top-2 (tote.cc semantics; see _chunk_out_word)
    groups = jnp.any((group_scores > 0).reshape(tg, 64, 4), axis=-1)
    slot_in_use = jnp.repeat(groups, 4, axis=-1)
    iota_i = jax.lax.broadcasted_iota(jnp.int32, (tg, 256), 1)
    sortkey = jnp.where(slot_in_use, scores * 256 + (255 - iota_i), -1)
    k1 = jnp.argmax(sortkey, axis=-1)
    top1 = jnp.take_along_axis(sortkey, k1[:, None], axis=-1)[:, 0]
    sortkey2 = jnp.where(iota_i == k1[:, None], -1, sortkey)
    k2 = jnp.argmax(sortkey2, axis=-1)
    top2 = jnp.take_along_axis(sortkey2, k2[:, None], axis=-1)[:, 0]
    s1 = jnp.where(top1 >= 0, top1 >> 8, 0)
    s2 = jnp.where(top2 >= 0, top2 >> 8, 0)
    k1 = jnp.where(top1 >= 0, k1, 0)
    k2 = jnp.where(top2 >= 0, k2, 0)

    meta = meta_ref[...]                           # [TG, 4] i32
    cbytes, grams = meta[:, 0], meta[:, 1]
    side, real = meta[:, 2], meta[:, 3]
    script = script_ref[...][:, 0]

    p2l = p2l_ref[...]
    lang1 = p2l[side, k1]
    lang2 = p2l[side, k2]
    actual_kb = jnp.where(cbytes > 0,
                          (s1 << 10) // jnp.maximum(cbytes, 1), 0)
    expected_kb = exp_ref[...][lang1, _lscript4(script)]
    rd = _reliability_delta(s1, s2, grams)
    close = close_ref[...][:, 0]
    same_set = (close[lang1] != 0) & (close[lang1] == close[lang2])
    rd = jnp.where(same_set, 100, rd)
    rs = _reliability_expected(actual_kb, expected_kb)
    crel = jnp.minimum(rd, rs)

    word1 = (lang1.astype(jnp.uint32) |
             (jnp.clip(s1, 0, 0x3FFF).astype(jnp.uint32) << 10) |
             (jnp.clip(crel, 0, 127).astype(jnp.uint32) << 24) |
             (real.astype(jnp.uint32) << 31))
    word2 = (lang2.astype(jnp.uint32) |
             (jnp.clip(rd, 0, 127).astype(jnp.uint32) << 10) |
             (jnp.clip(rs, 0, 127).astype(jnp.uint32) << 17))
    out_ref[...] = jnp.stack([word1, word2], axis=-1)


def _pallas_score_impl(dt: DeviceTables, p: dict, interpret: bool,
                       full_out: bool = False):
    """XLA prologue (gather) + the fused Pallas grid + output slice."""
    lp, cbytes, grams, side, real, script, wmask, prior = \
        _gather_wire(dt, p)
    G = lp.shape[0]
    K = lp.shape[1]
    if wmask is None:
        # the kernel body is branch-free: an all-zero mask whacks
        # nothing, matching the dropped gather exactly
        wmask = jnp.zeros((G, 256), jnp.uint8)
    if prior is None:
        # same trick for the hint-prior plane: zero add = identity
        prior = jnp.zeros((G, 256), jnp.int32)
    meta = jnp.stack([cbytes, grams, side, real], axis=-1)  # [G, 4]
    gp = max(TILE_G, -(-G // TILE_G) * TILE_G)
    pad = gp - G
    lp = jnp.pad(lp, ((0, pad), (0, 0)))
    meta = jnp.pad(meta, ((0, pad), (0, 0)))
    script2 = jnp.pad(script[:, None], ((0, pad), (0, 0)))
    wmask = jnp.pad(wmask, ((0, pad), (0, 0)))
    prior = jnp.pad(prior, ((0, pad), (0, 0)))

    n_exp = dt.expected_score_pad.shape[0]
    out = pl.pallas_call(
        _fused_tote_kernel,
        grid=(gp // TILE_G,),
        in_specs=[
            pl.BlockSpec((TILE_G, K), lambda i: (i, 0)),
            pl.BlockSpec((TILE_G, 4), lambda i: (i, 0)),
            pl.BlockSpec((TILE_G, 1), lambda i: (i, 0)),
            pl.BlockSpec((TILE_G, 256), lambda i: (i, 0)),
            pl.BlockSpec((TILE_G, 256), lambda i: (i, 0)),
            pl.BlockSpec((256, 3), lambda i: (0, 0)),
            pl.BlockSpec((n_exp, 4), lambda i: (0, 0)),
            pl.BlockSpec((2, 256), lambda i: (0, 0)),
            pl.BlockSpec((n_exp, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_G, 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((gp, 2), jnp.uint32),
        interpret=interpret,
    )(lp, meta, script2, wmask, prior, dt.lg_prob3_pad,
      dt.expected_score_pad, dt.plang_to_lang,
      dt.close_set_pad[:, None])
    word = out[:G]
    if not full_out:
        return word[:, 0]
    return word


_pallas_fns_cache: dict = {}


def _pallas_score_fns(interpret: bool):
    """(score, donated, full) jits for one interpret setting; cached so
    repeated engine constructions reuse the XLA jit cache."""
    if interpret not in _pallas_fns_cache:
        def score_impl(dt, p):
            return _pallas_score_impl(dt, p, interpret)

        def score_full_impl(dt, p):
            return _pallas_score_impl(dt, p, interpret, full_out=True)

        _pallas_fns_cache[interpret] = (
            jax.jit(score_impl),
            jax.jit(score_impl, donate_argnums=(1,)),
            jax.jit(score_full_impl),
        )
    return _pallas_fns_cache[interpret]


# ---------------------------------------------------------------------------
# Integrity scrub fold
# ---------------------------------------------------------------------------


def _fold(a: jnp.ndarray) -> jnp.ndarray:
    """Device twin of device_tables.fold_host: one table plane ->
    scalar u32 digest via a position-weighted wrap-sum. Pure XLA (a
    gather-free reduction runs on every backend the scorer does — the
    same reduce machinery the fused tote uses), and bit-identical to
    the numpy fold by construction: both normalize to u32 words and
    wrap mod 2^32."""
    v = a
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.uint8)
    if v.dtype.itemsize == 1:
        w = v.astype(jnp.uint32)
    elif v.dtype.itemsize == 2:
        w = jax.lax.bitcast_convert_type(v, jnp.uint16).astype(
            jnp.uint32)
    else:
        w = jax.lax.bitcast_convert_type(v, jnp.uint32)
    w = w.reshape(-1)
    weights = (jnp.arange(w.size, dtype=jnp.uint32) % 65521) + 1
    return jnp.sum(w * weights, dtype=jnp.uint32)


def table_digest_impl(dt: DeviceTables) -> jnp.ndarray:
    """All dt planes folded on-device -> [n_planes] u32, index-aligned
    with device_tables.fingerprint(). The scrub pass compares this
    against the lane's recorded upload fingerprint."""
    return jnp.stack([_fold(a) for a in digest_arrays(dt)])  # ldt-lint: disable=trace-python-branch -- digest_arrays is a static tuple of planes, not a traced value; the loop unrolls at trace time


table_digest = jax.jit(table_digest_impl)


# ---------------------------------------------------------------------------
# Selection
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelSelection:
    """Resolved scoring-kernel choice: the three jitted entry points the
    engine wires through _launch, plus what was asked for and why the
    resolution differs (surfaced in /debug/vars under pipeline)."""
    mode: str          # resolved: pallas | pallas-interpret | fused |
    #                    xla | lax
    requested: str     # the LDT_KERNEL value (or "auto")
    reason: str        # selection / fallback explanation
    score: object      # jit(dt, wire) -> [G] u32
    donated: object    # same, wire donated (pipeline depth > 1)
    full: object       # jit(dt, wire) -> [G, 2] u32


_MODE_FNS = {
    "xla": (score_chunks, score_chunks_donated, score_chunks_full),
    "fused": (score_chunks_fused, score_chunks_fused_donated,
              score_chunks_fused_full),
    "lax": (score_chunks_lax, score_chunks_lax_donated,
            score_chunks_lax_full),
}

_KNOWN = ("auto", "pallas", "fused", "xla", "lax")


def select_kernel(backend: str | None = None) -> KernelSelection:
    """Resolve LDT_KERNEL against the live backend. Never raises: an
    unknown value logs loudly and behaves like auto (the knob contract),
    and a pallas request off-TPU degrades to the fused XLA program with
    the reason recorded rather than failing the engine."""
    requested = (knobs.get_str("LDT_KERNEL") or "auto").lower()
    if requested not in _KNOWN:
        _log.warning("LDT_KERNEL=%r is not one of %s; using auto",
                     requested, "|".join(_KNOWN))
        requested = "auto"
    if backend is None:
        backend = jax.default_backend()

    if requested in ("auto", "pallas"):
        if backend == "tpu" and _HAVE_PALLAS:
            score, donated, full = _pallas_score_fns(False)
            return KernelSelection(
                "pallas", requested, f"{backend} backend: fused Pallas "
                "kernel (Mosaic)", score, donated, full)
        if requested == "pallas" and _HAVE_PALLAS and \
                knobs.get_bool("LDT_KERNEL_INTERPRET"):
            score, donated, full = _pallas_score_fns(True)
            return KernelSelection(
                "pallas-interpret", requested,
                f"{backend} backend + LDT_KERNEL_INTERPRET: Pallas "
                "kernel body under the interpreter (parity/debug "
                "only)", score, donated, full)
        why = ("no Pallas in this jax install"
               if not _HAVE_PALLAS else
               f"{backend} backend has no Mosaic lowering")
        score, donated, full = _MODE_FNS["fused"]
        return KernelSelection(
            "fused", requested,
            f"{why}; quantized fused XLA fallback", score, donated,
            full)

    score, donated, full = _MODE_FNS[requested]
    return KernelSelection(requested, requested,
                           f"explicit LDT_KERNEL={requested}",
                           score, donated, full)


def mesh_selection(base: KernelSelection) -> KernelSelection:
    """The sharded engine keeps its shard_map program for the main
    scorer (LDT_KERNEL governs the single-lane paths: the result-vector
    full-output dispatch still follows the knob)."""
    return dataclasses.replace(
        base, mode="xla",
        reason="mesh engine: shard_map program scores the main lane "
               f"(single-lane paths keep {base.mode})")
