"""Artifact verifier (tools/artifact_tool.py): the npz-artifact
counterpart of the reference's cld2_dynamic_data_tool --verify round-trip
(cld2_dynamic_data_tool.cc:51+, header contract cld2_dynamic_data.h:23-110).
"""
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import artifact_tool  # noqa: E402


def test_shipped_artifacts_verify():
    assert artifact_tool.cmd_verify() == 0


def test_structure_checks_catch_corruption(tmp_path, monkeypatch):
    src = artifact_tool.DATA / "quad_tables.npz"
    z = dict(np.load(src, allow_pickle=False))
    # out-of-range indirect subscript in a filled slot
    bad = dict(z)
    buckets = z["quadgram_buckets"].copy()
    filled = np.argwhere(buckets != 0)
    r, c = filled[0]
    keymask = int(z["quadgram_meta"][2])
    buckets[r, c] = (buckets[r, c] & np.uint32(keymask)) | np.uint32(
        len(z["quadgram_ind"]) + 5)
    bad["quadgram_buckets"] = buckets
    p = tmp_path / "quad_tables.npz"
    np.savez(p, **bad)
    errors = artifact_tool.check_structure(p)
    assert any("indirect" in e for e in errors), errors

    # non-power-of-two bucket count
    bad2 = dict(z)
    meta = z["quadgram_meta"].copy()
    meta[1] = int(meta[1]) - 1
    bad2["quadgram_meta"] = meta
    p2 = tmp_path / "quad2" ; p2.mkdir()
    f2 = p2 / "quad_tables.npz"
    np.savez(f2, **bad2)
    errors = artifact_tool.check_structure(f2)
    assert any("power of two" in e or "!= bucket rows" in e
               for e in errors), errors


def test_manifest_detects_drift(tmp_path, monkeypatch):
    import json
    manifest = json.loads((artifact_tool.DATA / "MANIFEST.json").read_text())
    name = "quad_tables.npz"
    key = next(iter(manifest[name]["arrays"]))
    manifest[name]["arrays"][key]["sha256"] = "0" * 64
    mpath = tmp_path / "MANIFEST.json"
    mpath.write_text(json.dumps(manifest))
    monkeypatch.setattr(artifact_tool, "MANIFEST", mpath)
    assert artifact_tool.cmd_verify() == 1
