"""Native (C++) packer == Python packer, array for array.

The Python packer (preprocess/pack.py) is the behavioral spec — itself
validated against the scalar engine and oracle. The native packer
(native/packer.cc) must reproduce every output array exactly on goldens,
random composites, CJK, and edge inputs.
"""
import dataclasses
import random
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent))
from golden_data import golden_pairs  # noqa: E402


@pytest.fixture(scope="session")
def native_mod():
    from language_detector_tpu import native
    if not native.available():
        pytest.skip("native packer unavailable (no compiler)")
    return native


@pytest.fixture(scope="session")
def tables_reg():
    from language_detector_tpu.registry import registry
    from language_detector_tpu.tables import load_tables
    return load_tables(), registry


def _assert_packed_equal(texts, tables, reg, native_mod, **kw):
    from language_detector_tpu.preprocess.pack import pack_batch
    a = pack_batch(texts, tables, reg, **kw)
    b = native_mod.pack_batch_native(texts, tables, reg, **kw)
    for f in dataclasses.fields(a):
        if f.name == "n_docs":
            continue
        va, vb = getattr(a, f.name), getattr(b, f.name)
        assert np.array_equal(va, vb), \
            f"{f.name} differs at {np.argwhere(np.asarray(va) != vb)[:5]}"


def _golden_texts():
    pairs = golden_pairs()
    if not pairs:
        pytest.skip("reference snapshot unavailable")
    return [t.decode("utf-8", errors="replace") for _, _, t in pairs]


def test_goldens(tables_reg, native_mod):
    _assert_packed_equal(_golden_texts(), *tables_reg, native_mod)


def test_random_composites(tables_reg, native_mod):
    texts = _golden_texts()
    rng = random.Random(99)
    docs = []
    for _ in range(64):
        parts = []
        for _ in range(rng.randint(1, 5)):
            t = texts[rng.randrange(len(texts))]
            lo = rng.randrange(max(1, len(t) - 300))
            parts.append(t[lo:lo + rng.randint(10, 300)])
        docs.append(" ".join(parts))
    _assert_packed_equal(docs, *tables_reg, native_mod)


def test_edge_inputs(tables_reg, native_mod):
    docs = ["", " ", "a", "\n", "🎉🎊 fiesta", "123 !!!",
            "x" * 5000, ("word " * 2000).strip(),
            "Ğİıquick brown fox ÄÖÜ ß straße",
            "日本語とEnglishの混在テキスト mixed script",
            "а б в г д е ж з и к л м н о п",
            "́̂ combining-first", "ab" * 30000]
    _assert_packed_equal(docs, *tables_reg, native_mod)


def test_flags_finish(tables_reg, native_mod):
    docs = [("spam ham " * 600).strip(), "normal short text here"]
    _assert_packed_equal(docs, *tables_reg, native_mod, flags=1)
    _assert_packed_equal(docs, *tables_reg, native_mod, flags=0)


def test_small_capacities(tables_reg, native_mod):
    """Overflow -> fallback decisions must match at tight capacities."""
    texts = _golden_texts()[:48]
    _assert_packed_equal(texts, *tables_reg, native_mod,
                         max_slots=128, max_chunks=8, max_direct=1)
