"""Worker supervisor: restart the serving process on planned recycles.

The reference ships its restart story as a container policy
(/root/reference/Dockerfile); this is the same story for bare-metal and
for the repo's own Dockerfile CMD: run the HTTP front as a child, and
while it exits with RECYCLE_EXIT_CODE (a planned self-recycle — see
service/recycle.py), start a fresh one. Any other exit propagates, so
crashes still surface to the outer restart policy / operator.

Run: python -m language_detector_tpu.service.supervisor [module]
     (module defaults to language_detector_tpu.service.aioserver, the
      single-core production front; pass .service.server for the
      threaded one)
"""
from __future__ import annotations

import json
import signal
import subprocess
import sys
import time

from .recycle import RECYCLE_EXIT_CODE


def main() -> int:
    module = sys.argv[1] if len(sys.argv) > 1 else \
        "language_detector_tpu.service.aioserver"
    generation = 0
    child: subprocess.Popen | None = None
    stopping = False

    # PID-1 duty (the Dockerfile CMD): forward SIGTERM/SIGINT to the
    # worker so `docker stop` gives it a graceful shutdown instead of
    # the namespace teardown SIGKILLing it mid-request; then stop
    # restarting and exit with the worker's code.
    def _forward(signum, frame):
        nonlocal stopping
        stopping = True
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)

    while True:
        generation += 1
        print(json.dumps({"msg": f"supervisor: starting {module} "
                                 f"(generation {generation})"}),
              flush=True)
        t0 = time.time()
        child = subprocess.Popen([sys.executable, "-m", module])
        if stopping:  # signal raced the spawn: stop the new worker too
            child.send_signal(signal.SIGTERM)
        while True:
            try:
                rc = child.wait()
                break
            except KeyboardInterrupt:  # Ctrl+C raced the handler
                continue
        if stopping or rc != RECYCLE_EXIT_CODE:
            print(json.dumps({"msg": f"supervisor: worker exited rc={rc} "
                                     f"after {time.time() - t0:.1f}s — "
                                     "propagating"}), flush=True)
            return rc
        print(json.dumps({"msg": "supervisor: worker recycled after "
                                 f"{time.time() - t0:.1f}s"}), flush=True)
        if stopping:  # SIGTERM landed in the reap/restart gap
            return rc


if __name__ == "__main__":
    sys.exit(main())
