"""Hints engine parity vs the oracle's ExtDetectLanguageSummary.

Covers the four CLDHints channels (content-language, TLD, encoding,
explicit language) and HTML lang= attribute scanning, on texts where the
hint matters (close pairs, short ambiguous snippets) and where it must
not override clear evidence (compact_lang_det_hint_code.cc:1394-1508,
ApplyHints impl.cc:1587-1684).
"""
import ctypes

import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.hints import (CLDHints, apply_hints,
                                         get_lang_tags_from_html)
from language_detector_tpu.registry import UNKNOWN_LANGUAGE, registry
from language_detector_tpu.tables import load_tables


def oracle_detect_hints(lib, text: bytes, flags: int = 0,
                        is_plain_text: bool = True,
                        content_language: bytes = b"", tld: bytes = b"",
                        encoding: int = 75,  # UNKNOWN_ENCODING
                        language: int = UNKNOWN_LANGUAGE):
    lib.o_detect_hints.restype = ctypes.c_int
    l3 = (ctypes.c_int * 3)()
    p3 = (ctypes.c_int * 3)()
    s3 = (ctypes.c_double * 3)()
    tb = ctypes.c_int()
    rel = ctypes.c_int()
    lang = lib.o_detect_hints(text, len(text), 1 if is_plain_text else 0,
                              flags, content_language, tld, encoding,
                              language, l3, p3, s3, ctypes.byref(tb),
                              ctypes.byref(rel))
    return (lang, [int(l3[i]) for i in range(3)],
            [int(p3[i]) for i in range(3)], bool(rel.value), tb.value)


TEXT_ID_MS = "ini rumah besar kami yang baru dan sangat cantik sekali"
TEXT_HR = "ovo je velika kuća i lijepo je vrijeme danas u gradu"
TEXT_EN = ("this is a simple english sentence with common words that "
           "should be detected without any trouble at all")

CASES = [
    # (text, plain, kwargs)
    (TEXT_ID_MS, True, dict(tld=b"my")),
    (TEXT_ID_MS, True, dict(tld=b"id")),
    (TEXT_ID_MS, True, dict(content_language=b"ms")),
    (TEXT_ID_MS, True, dict(language=registry.code_to_lang["ms"])),
    (TEXT_HR, True, dict(content_language=b"sr")),
    (TEXT_HR, True, dict(tld=b"rs")),
    (TEXT_EN, True, dict(tld=b"fr")),    # clear evidence beats weak hint
    (TEXT_EN, True, dict(content_language=b"fr")),
    ("short text", True, dict(content_language=b"de")),
    ("short text", True, dict(language=registry.code_to_lang["nl"])),
    ('<html lang="sr"><p>' + TEXT_HR + "</p></html>", False, dict()),
    # hr (Latin-only) must not whack Serbian in the Cyrillic list
    # (AddOneWhack script condition, impl.cc:1541-1561)
    ("Београд је главни град Србије и највећи град у земљи данас", True,
     dict(content_language=b"hr")),
    # >4 whacks per script exercise the rotating overwrite
    (TEXT_HR, True, dict(content_language=b"sr,no")),
    ('<meta http-equiv="content-language" content="ms"><p>' +
     TEXT_ID_MS + "</p>", False, dict()),
]


@pytest.mark.parametrize("case", CASES, ids=range(len(CASES)))
def test_hinted_detection_parity(oracle, base_tables, case):
    text, plain, kw = case
    want = oracle_detect_hints(oracle, text.encode(), is_plain_text=plain,
                               content_language=kw.get("content_language",
                                                       b""),
                               tld=kw.get("tld", b""),
                               language=kw.get("language",
                                               UNKNOWN_LANGUAGE))
    hints = CLDHints(
        content_language_hint=kw.get("content_language", b"").decode()
        or None,
        tld_hint=kw.get("tld", b"").decode() or None,
        language_hint=kw.get("language", UNKNOWN_LANGUAGE))
    r = detect_scalar(text, base_tables, registry, 0,
                      is_plain_text=plain, hints=hints)
    assert r.summary_lang == want[0], (registry.code(r.summary_lang),
                                       registry.code(want[0]))
    assert r.language3 == want[1]
    assert r.percent3 == want[2]
    assert r.is_reliable == want[3]


def test_encoding_hint_parity(oracle, base_tables):
    """Encoding-family hints (SetCLDEncodingHint)."""
    tables = load_tables()
    names = [str(n) for n in tables.encoding_names]
    for enc_name, text in [("CHINESE_GB", "短文"), ("JAPANESE_EUC_JP", "短文"),
                           ("KOREAN_EUC_KR", "短文")]:
        enc = names.index(enc_name)
        want = oracle_detect_hints(oracle, text.encode(), encoding=enc)
        r = detect_scalar(text, base_tables, registry, 0,
                          hints=CLDHints(encoding_hint=enc_name))
        assert r.summary_lang == want[0], (enc_name,
                                           registry.code(r.summary_lang),
                                           registry.code(want[0]))


def test_lang_tag_scanner():
    """GetLangTagsFromHtml normalization behaviors."""
    assert get_lang_tags_from_html('<html lang="fr">') == "fr"
    assert get_lang_tags_from_html("<html lang='pt-BR'>") == "pt-br"
    assert get_lang_tags_from_html('<div xml:lang="DE_de">x</div>') \
        == "de-de"
    # unquoted attribute values match (the reference's FindAfter needs a
    # trailing space, which a closing quote prevents — quoted values are
    # faithfully NOT matched, quirk of hint_code.cc:1328-1352)
    assert get_lang_tags_from_html(
        '<meta http-equiv=content-language content="es, en" x=y>') \
        == "es,en"
    assert get_lang_tags_from_html(
        '<meta http-equiv="content-language" content="es, en">') == ""
    # skipped elements contribute nothing
    assert get_lang_tags_from_html('<a lang="it" href=x>') == ""
    assert get_lang_tags_from_html('<script lang="js">') == ""
    # duplicates collapse
    assert get_lang_tags_from_html(
        '<p lang="fr"></p><p lang="fr"></p>') == "fr"


def test_apply_hints_whacks():
    """A single hinted close-set member whacks its rivals."""
    tables = load_tables()
    hb = apply_hints("", True,
                     CLDHints(language_hint=registry.code_to_lang["id"]),
                     tables, registry)
    assert hb.boost_latn  # INDONESIAN boost
    assert hb.whack_latn  # MALAY suppressed
    # tld=id carries a paired negative MALAY prior, so both close-set
    # members are present and no whack fires (ApplyHints counts priors
    # regardless of weight sign, impl.cc:1660-1666)
    hb2 = apply_hints("", True, CLDHints(tld_hint="id"), tables, registry)
    assert hb2.boost_latn and not hb2.whack_latn
    hb3 = apply_hints("", True,
                      CLDHints(content_language_hint="id,ms"), tables,
                      registry)
    assert not hb3.whack_latn  # both of the set hinted: no whack
