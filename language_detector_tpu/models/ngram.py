"""Batched n-gram detection engine: the TPU hot path.

Pipeline per batch (the TPU redesign of DetectLanguageSummaryV2,
compact_lang_det_impl.cc:1707-2106):

  host   pack_batch      texts -> fixed-shape candidate tensors
  device score_batch     probes + totes + chunk summaries, one jitted program
  host   _doc_epilogue   DocTote replay + close pairs + unreliable removal +
                         summary language (O(1) per doc, scalar-exact)

Documents the packer flags (squeeze triggers, slot overflow) and documents
failing the recursion gate (impl.cc:1978-1991) fall back to the scalar
engine, which performs the reference's re-score recursion. Everything else
is batched: the result agrees with `detect_scalar` on every document
(tests/test_batch_agreement.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..engine_scalar import (FLAG_BEST_EFFORT, FLAG_FINISH,
                             GOOD_LANG1_PERCENT, GOOD_LANG1AND2_PERCENT,
                             SHORT_TEXT_THRESH, DocTote, ScalarResult,
                             calc_summary_lang, detect_scalar,
                             extract_lang_etc, refine_close_pairs,
                             remove_unreliable)
from ..ops.device_tables import DeviceTables
from ..ops.score import score_batch
from ..preprocess.pack import PackedBatch, pack_batch
from ..registry import Registry, registry as default_registry
from ..tables import ScoringTables, load_tables

# Per-slot / per-chunk arrays shipped to the device
_DEVICE_FIELDS = ("kind", "offset", "sub", "key", "fp", "direct",
                  "chunk_base", "span_start", "span_end_off", "side", "cjk",
                  "chunk_script", "chunk_side")

# Flags the device path supports. FLAG_FINISH and FLAG_BEST_EFFORT only
# alter the host epilogue / packer gate; every other flag changes span
# preprocessing or scoring dispatch (squeeze, repeat-strip, score-as-quads)
# and routes the whole batch to the scalar engine.
_DEVICE_OK_FLAGS = FLAG_FINISH | FLAG_BEST_EFFORT


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class NgramBatchEngine:
    """Batched detector over a table artifact.

    Batches are padded to power-of-two document counts so jit compiles a
    small, reusable set of programs (static [B, L] shapes).
    """

    def __init__(self, tables: ScoringTables | None = None,
                 reg: Registry | None = None, flags: int = 0,
                 max_slots: int = 2048, max_chunks: int = 64,
                 mesh=None):
        """mesh: optional jax.sharding.Mesh with a "batch" axis; when given,
        batches shard over it data-parallel (parallel/mesh.py) and the
        batch size rounds up to a multiple of the mesh size."""
        self.tables = tables or load_tables()
        self.reg = reg or default_registry
        self.flags = flags
        self.max_slots = max_slots
        self.max_chunks = max_chunks
        self.dt = DeviceTables.from_host(self.tables, self.reg)
        self.mesh = mesh
        if mesh is not None:
            from ..parallel.mesh import sharded_score_fn
            self._score_fn = sharded_score_fn(mesh)
            self._mesh_size = mesh.devices.size
        else:
            self._score_fn = score_batch
            self._mesh_size = 1

    # -- device dispatch ----------------------------------------------------

    def score_packed(self, packed: PackedBatch) -> dict:
        """Run the jitted device program over a packed batch; returns host
        numpy chunk-summary arrays."""
        p = {k: jnp.asarray(getattr(packed, k)) for k in _DEVICE_FIELDS}
        out = self._score_fn(self.dt, p)
        return {k: np.asarray(v) for k, v in out.items()}

    # -- public API ---------------------------------------------------------

    def detect_batch(self, texts: list[str]) -> list[ScalarResult]:
        if not texts:
            return []
        if self.flags & ~_DEVICE_OK_FLAGS:
            return [detect_scalar(t, self.tables, self.reg, self.flags)
                    for t in texts]
        bsz = _next_pow2(len(texts))
        bsz += -bsz % self._mesh_size  # divisible over the mesh axis
        padded = list(texts) + [""] * (bsz - len(texts))
        packed = pack_batch(padded, self.tables, self.reg,
                            max_slots=self.max_slots,
                            max_chunks=self.max_chunks, flags=self.flags)
        out = self.score_packed(packed)
        results = []
        for b, text in enumerate(texts):
            if packed.fallback[b]:
                results.append(detect_scalar(text, self.tables, self.reg,
                                             self.flags))
                continue
            r = self._doc_epilogue(packed, out, b)
            if r is None:  # failed the good-answer gate: scalar recursion
                r = detect_scalar(text, self.tables, self.reg, self.flags)
            results.append(r)
        return results

    # -- exact host epilogue ------------------------------------------------

    def _doc_epilogue(self, packed: PackedBatch, out: dict,
                      b: int) -> ScalarResult | None:
        """DocTote replay in chunk-id (= span) order, then the document
        post-processing pipeline, byte-identical to detect_scalar
        (impl.cc:1956-2106). Returns None when the good-answer gate fails
        and the reference would recurse."""
        doc_tote = DocTote()
        direct = {int(cid): (int(lang), int(nb))
                  for cid, lang, nb in packed.direct_adds[b] if cid >= 0}
        real = out["chunk_real"][b]
        lang1 = out["chunk_lang1"][b]
        cbytes = out["chunk_bytes"][b]
        score1 = out["chunk_score1"][b]
        crel = out["chunk_rel"][b]
        for c in range(len(real)):
            if c in direct:
                lang, nb = direct[c]
                doc_tote.add(lang, nb, nb, 100)
            elif real[c]:
                doc_tote.add(int(lang1[c]), int(cbytes[c]), int(score1[c]),
                             int(crel[c]))
        total_text_bytes = int(packed.text_bytes[b])
        flags = self.flags

        refine_close_pairs(self.reg, doc_tote)
        doc_tote.sort()
        lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
            doc_tote, total_text_bytes)

        good = (flags & FLAG_FINISH) or total <= SHORT_TEXT_THRESH or \
            (is_reliable and percent3[0] >= GOOD_LANG1_PERCENT) or \
            (is_reliable and
             percent3[0] + percent3[1] >= GOOD_LANG1AND2_PERCENT)
        if not good:
            return None

        if not (flags & FLAG_BEST_EFFORT):
            remove_unreliable(self.reg, doc_tote)
        doc_tote.sort()
        lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
            doc_tote, total_text_bytes)
        summary, reliable = calc_summary_lang(self.reg, lang3, percent3,
                                              total, is_reliable, flags)
        return ScalarResult(summary_lang=summary, language3=lang3,
                            percent3=percent3, normalized_score3=ns3,
                            text_bytes=total, is_reliable=reliable)
