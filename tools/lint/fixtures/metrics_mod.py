"""Fixture: a stand-in telemetry module for the metric-registry
analyzer (passed via telemetry_rel)."""

METRICS: dict = {
    "ldt_fix_used_total": ("counter", "emitted and documented"),
    "ldt_fix_unused_total": ("counter", "declared, never emitted"),
    "ldt_fix_undoc_total": ("counter", "emitted, absent from docs"),
}
