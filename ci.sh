#!/bin/bash
# One-command CI: build natives -> verify artifacts -> tests -> entry
# checks -> bench smoke. Exits non-zero on the first failure.
set -euo pipefail
cd "$(dirname "$0")"

echo "== native build =="
language_detector_tpu/native/build.sh

if [ -d /root/reference/cld2 ] && [ ! -f tools/oracle/libcld2_oracle.so ]; then
    echo "== oracle build =="
    tools/oracle/build.sh
fi

echo "== artifact verify =="
python3 tools/artifact_tool.py --verify

echo "== tests =="
python3 -m pytest tests/ -q

echo "== graft entry =="
python3 __graft_entry__.py

echo "== bench smoke =="
python3 bench.py --smoke | tee /tmp/ldt_bench_smoke.out
# scheduler invariants on the smoke numbers: the mixed corpus must
# never hit the packer-fallback path, and the bucketed-scheduler
# counters (cache hit rate, per-tier dispatches, dedup) must report
python3 - <<'EOF'
import json
line = [ln for ln in open("/tmp/ldt_bench_smoke.out")
        if ln.startswith("{")][-1]
d = json.loads(line)["detail"]
assert d["mixed_fallback_docs"] == 0, \
    f"mixed_fallback_docs = {d['mixed_fallback_docs']} (want 0)"
assert d["cache_hit_rate"] is not None and d["cache_hit_rate"] > 0, \
    f"cache_hit_rate = {d['cache_hit_rate']} (want > 0)"
print("bucketed scheduler:",
      "cache_hit_rate", d["cache_hit_rate"],
      "| tier_dispatches", d["tier_dispatches"],
      "| dedup_docs", d["mixed_dedup_docs"],
      "| retry_lane_dispatches", d["mixed_retry_lane_dispatches"])
EOF

echo "CI OK"
