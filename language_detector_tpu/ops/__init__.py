from .device_tables import DeviceTables  # noqa: F401
from .score import score_resolved  # noqa: F401
