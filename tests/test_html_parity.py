"""HTML path parity vs the reference scanner (is_plain_text=false).

Span-level: our clean-then-segment pipeline (preprocess/html.py +
segment.py) must produce the same lowercased span text and scripts as the
reference's inline tag state machine + entity expansion
(getonescriptspan.cc:150-196, :393-480), on synthetic HTML and on the
reference's own docs/a_little_french_test_input.html.
"""
from pathlib import Path

import pytest

from language_detector_tpu.preprocess.segment import segment_text

from conftest import oracle_detect, oracle_spans

FRENCH_HTML = Path("/root/reference/cld2/docs/a_little_french_test_input.html")

HTML_TEXTS = [
    "<html><body><p>Hello world this is English</p></body></html>",
    "Plain start <b>bold words</b> and <i>italic ones</i> here",
    "caf&eacute; fran&ccedil;ais &agrave; l&#39;heure &#xE9;t&eacute;",
    "<!-- a comment with English words inside --> visible text only",
    "<script>var x = 'code noise';</script> real sentence here",
    "<script src=x>alert(1)</script> attributed script tag",
    "<style>body { color: red; }</style> styled text after",
    "a < b but also x > y inequalities",
    "<a href=\"http://x.example/path?q=1&lang=en\">le lien</a> suite du texte",
    "<div class='unterminated",
    "text with &amp; and &lt;tags&gt; escaped &unknownent; kept",
    "<p lang=\"fr\">Ceci est une phrase française assez longue.</p>",
    "R&D department results &NotAnEntity works",
    "&#120; &#x79; &#122; numeric entities",
    "<<double open then text",
    # script followed by a non-ASCII char: UTF-8 lead byte is PL class,
    # so this is an ordinary tag and the content stays visible
    "<script« attr>hidden words</script> le texte visible ici",
]


def _spans_mine(text: str):
    return [(sp.text, sp.ulscript)
            for sp in segment_text(text, is_plain_text=False)]


@pytest.mark.parametrize("text", HTML_TEXTS)
def test_html_span_parity(oracle, text):
    ref = oracle_spans(oracle, text.encode("utf-8"), is_plain_text=False)
    mine = _spans_mine(text)
    assert len(mine) == len(ref), (mine, ref)
    for (mt, ms), (rt, rs) in zip(mine, ref):
        assert ms == rs, (mt, rt, rs)
        assert mt == rt, (mt, rt)


def test_french_html_file_span_parity(oracle):
    if not FRENCH_HTML.exists():
        pytest.skip("reference snapshot unavailable")
    raw = FRENCH_HTML.read_bytes()
    text = raw.decode("utf-8", errors="replace")
    ref = oracle_spans(oracle, text.encode("utf-8"), is_plain_text=False)
    mine = _spans_mine(text)
    assert len(mine) == len(ref), (len(mine), len(ref))
    for (mt, ms), (rt, rs) in zip(mine, ref):
        assert ms == rs
        assert mt == rt


def test_french_html_detection_parity(oracle, base_tables):
    """Full-document HTML detection agrees with the oracle."""
    if not FRENCH_HTML.exists():
        pytest.skip("reference snapshot unavailable")
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.registry import registry
    text = FRENCH_HTML.read_bytes().decode("utf-8", errors="replace")
    code, _, top3, reliable, tb = oracle_detect(
        oracle, text.encode("utf-8"), is_plain_text=False)
    r = detect_scalar(text, base_tables, is_plain_text=False)
    assert registry.code(r.summary_lang) == code
    assert r.is_reliable == reliable
    assert r.text_bytes == tb
