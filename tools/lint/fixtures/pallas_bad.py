"""Fixture: Pallas entry-point violations — host syncs and a Python
branch inside a pallas_call kernel body, and a read of a buffer that
was aliased into the outputs via input_output_aliases (the Pallas
spelling of donation)."""
import numpy as np
from jax.experimental import pallas as pl


def _score_kernel(wire_ref, out_ref):
    v = wire_ref[...]
    if v.sum() > 0:             # trace-python-branch
        pass
    x = float(v[0, 0])          # trace-host-sync (host cast)
    host = np.asarray(v)        # trace-host-sync (np materialize)
    out_ref[...] = v + x + host.sum()


score_fused = pl.pallas_call(_score_kernel, out_shape=None,
                             input_output_aliases={0: 0})


def launch_then_touch(wire):
    out = score_fused(wire)     # wire's buffer aliased into `out`
    return out + wire           # jit-donated-read
