// Native host-side batch packer: UTF-8 texts -> fixed-shape candidate
// tensors for the TPU scorer.
//
// C++ twin of preprocess/{segment,grams,hashing,squeeze,pack}.py — the
// byte-level, inherently sequential front half of detection (reference:
// getonescriptspan.cc:799 scanner, cldutil_shared.cc:107-386 hashes,
// cldutil.cc:315-533 gram scans, compact_lang_det_impl.cc:541-971 squeeze
// predictor). The Python packer is the behavioral spec (itself
// oracle-parity-tested); tests/test_native_pack.py asserts array-for-array
// equality between the two.
//
// Build: native/build.sh  ->  libldtpack.so (loaded via ctypes).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---- candidate kinds (preprocess/pack.py) ----
enum Kind : int8_t {
  PAD = 0, SEED = 1, QUAD = 2, UNI = 3,
  DELTA_OCTA = 4, DISTINCT_OCTA = 5, BI_DELTA = 6, BI_DISTINCT = 7
};

constexpr int kMaxScoringHits = 1000;       // scoreonescriptspan.h:93
constexpr int kMaxSpanPutBytes = 40960 - 32;  // getonescriptspan.h:29-32
constexpr int kSoftSpanPutBytes = kMaxSpanPutBytes - 32;
constexpr int kTailPad = 32;
constexpr int kSqueezeTestThresh = 4096;    // kCheapSqueezeTestThresh
constexpr int kSqueezeTestLen = 256;
constexpr int kPredictionTableSize = 4096;
constexpr int kUlScriptInherited = 40;
constexpr int kUlScriptLatin = 1;

// ---- global tables (ldt_init; backing arrays owned by Python) ----
struct Ctx {
  const uint8_t* script_of_cp;   // [0x110000]
  const uint32_t* lower_map;     // [0x110000]
  const uint8_t* cjk_prop;       // [0x110000]
  const int32_t* rtype;          // [n_scripts]
  const int32_t* deflang;        // [n_scripts]
  const uint32_t* seed_lp;       // [n_scripts]
  int n_scripts;
  int distinctbi_empty;
};
Ctx g;

// ---- byte-class advance tables (cldutil_shared.h:462, cldutil.cc:49-99) --
struct AdvTables {
  int8_t but_space[256];   // 0 for <=0x20; 1/2/3/4 by UTF-8 lead
  int8_t one[256];
  int8_t space_vowel[256]; // 1 on space/ASCII-vowel/continuation/ctrl
  AdvTables() {
    for (int i = 0; i < 256; i++) {
      but_space[i] = i <= 0x20 ? 0 : i < 0xC0 ? 1 : i < 0xE0 ? 2
                     : i < 0xF0 ? 3 : 4;
      one[i] = i < 0xC0 ? 1 : i < 0xE0 ? 2 : i < 0xF0 ? 3 : 4;
      space_vowel[i] = (i <= 0x20) || (i >= 0x80 && i < 0xC0);
    }
    for (const char* v = "AEIOUaeiou"; *v; v++)
      space_vowel[(uint8_t)*v] = 1;
  }
};
const AdvTables adv;

inline uint32_t load32(const uint8_t* p) {
  uint32_t w;
  std::memcpy(&w, p, 4);  // little-endian hosts only (x86/arm64)
  return w;
}

constexpr uint32_t kPreSpace = 0x00004444;   // cldutil_shared.cc:41
constexpr uint32_t kPostSpace = 0x44440000;
const uint32_t kWordMask[4] = {0xFFFFFFFFu, 0x000000FFu, 0x0000FFFFu,
                               0x00FFFFFFu};

// QuadHashV2 (cldutil_shared.cc:196; preprocess/hashing.py quad_hash_v2)
uint32_t quad_hash(const uint8_t* buf, int64_t pos, int64_t len) {
  if (len == 0) return 0;
  uint32_t prepost = (buf[pos - 1] == 0x20 ? kPreSpace : 0) |
                     (buf[pos + len] == 0x20 ? kPostSpace : 0);
  uint32_t mask = kWordMask[len & 3];
  if (len <= 4) {
    uint32_t w0 = load32(buf + pos) & mask;
    w0 ^= w0 >> 3;
    return w0 ^ prepost;
  }
  uint32_t w0 = load32(buf + pos);
  w0 ^= w0 >> 3;
  if (len <= 8) {
    uint32_t w1 = load32(buf + pos + 4) & mask;
    w1 ^= w1 << 4;
    return (w0 ^ prepost) + w1;
  }
  uint32_t w1 = load32(buf + pos + 4);
  w1 ^= w1 << 4;
  uint32_t w2 = load32(buf + pos + 8) & mask;
  w2 ^= w2 << 2;
  return (w0 ^ prepost) + w1 + w2;
}

// OctaHash40 (cldutil_shared.cc:348; hashing.py octa_hash40)
const int kOctaShift[6] = {3, -4, -2, 8, 4, 6};

uint64_t octa_hash40(const uint8_t* buf, int64_t pos, int64_t len,
                     int64_t buflen) {
  if (len == 0) return 0;
  uint64_t prepost = (buf[pos - 1] == 0x20 ? kPreSpace : 0) |
                     (buf[pos + len] == 0x20 ? kPostSpace : 0);
  uint64_t mask = kWordMask[len & 3];
  int ngroups = (int)((len - 1) >> 2);
  if (ngroups > 5) ngroups = 5;
  uint64_t word0 = 0, csum = 0;
  for (int gidx = 0; gidx <= ngroups; gidx++) {
    int64_t gpos = pos + 4 * gidx;
    if (gpos > buflen - 4) gpos = buflen - 4;  // clip like the Python spec
    uint64_t w = load32(buf + gpos);
    if (gidx == ngroups) w &= mask;
    csum += w;
    int s = kOctaShift[gidx];
    uint64_t mixed = s > 0 ? (w ^ (w >> s)) : (w ^ (w << -s));
    word0 += mixed;
  }
  csum += csum >> 17;
  csum += csum >> 9;
  csum = (csum & 0xFF) << 32;
  return (word0 ^ prepost) + csum;
}

// BiHashV2 (cldutil_shared.cc:107; hashing.py bi_hash_v2)
uint32_t bi_hash(const uint8_t* buf, int64_t pos, int64_t len) {
  if (len == 0) return 0;
  uint32_t mask = kWordMask[len & 3];
  if (len <= 4) {
    uint32_t w0 = load32(buf + pos) & mask;
    w0 ^= w0 >> 3;
    return w0;
  }
  uint32_t w0 = load32(buf + pos);
  w0 ^= w0 >> 3;
  uint32_t w1 = load32(buf + pos + 4) & mask;
  w1 ^= w1 << 18;
  return w0 + w1;
}

// PairHash (cldutil_shared.cc:384)
inline uint64_t pair_hash(uint64_t a, uint64_t b) {
  return ((a >> 13) | (a << 51)) + b;
}

// ---- squeeze trigger (compact_lang_det_impl.cc:541-605, :952-971) ----
int count_spaces4(const uint8_t* buf, int len) {
  int n = len & ~3, c = 0;
  for (int i = 0; i < n; i++) c += buf[i] == 0x20;
  return c;
}

bool cheap_squeeze_trigger(const uint8_t* buf, int src_len) {
  const int testsize = kSqueezeTestLen;
  if (src_len < testsize) return false;
  if (count_spaces4(buf, testsize) >= testsize * 25 / 100) return true;
  // CountPredictedBytes with a fresh 12-bit-hash table
  std::vector<int64_t> tbl(kPredictionTableSize, 0);
  int predicted = 0, h = 0, i = 0;
  while (i < testsize) {
    uint8_t c0 = buf[i];
    int64_t c;
    int incr;
    if (c0 < 0xC0) { c = c0; incr = 1; }
    else if ((c0 & 0xE0) == 0xC0) { c = (c0 << 8) | buf[i + 1]; incr = 2; }
    else if ((c0 & 0xF0) == 0xE0) {
      c = ((int64_t)c0 << 16) | (buf[i + 1] << 8) | buf[i + 2]; incr = 3;
    } else {
      c = ((int64_t)c0 << 24) | ((int64_t)buf[i + 1] << 16) |
          (buf[i + 2] << 8) | buf[i + 3];
      incr = 4;
    }
    i += incr;
    if (tbl[h] == c) predicted += incr;
    tbl[h] = c;
    h = ((h << 4) ^ (int)c) & 0xFFF;
  }
  return predicted >= testsize * 67 / 100;
}

// ---- segmentation (preprocess/segment.py segment_text) ----
struct Span {
  std::vector<uint8_t> buf;      // ' ' + lowered letters + "   \0" + pad
  std::vector<uint32_t> cps;     // decoded buf codepoints + trailing space
  int text_bytes;
  int ulscript;
};

inline int u8len_of(uint32_t cp) {
  return cp < 0x80 ? 1 : cp < 0x800 ? 2 : cp < 0x10000 ? 3 : 4;
}

inline void u8encode(uint32_t cp, std::vector<uint8_t>* out) {
  if (cp < 0x80) out->push_back((uint8_t)cp);
  else if (cp < 0x800) {
    out->push_back(0xC0 | (cp >> 6));
    out->push_back(0x80 | (cp & 0x3F));
  } else if (cp < 0x10000) {
    out->push_back(0xE0 | (cp >> 12));
    out->push_back(0x80 | ((cp >> 6) & 0x3F));
    out->push_back(0x80 | (cp & 0x3F));
  } else {
    out->push_back(0xF0 | (cp >> 18));
    out->push_back(0x80 | ((cp >> 12) & 0x3F));
    out->push_back(0x80 | ((cp >> 6) & 0x3F));
    out->push_back(0x80 | (cp & 0x3F));
  }
}

// Decode valid UTF-8 (input comes from a Python str).
void u8decode(const uint8_t* s, int len, std::vector<uint32_t>* out) {
  int i = 0;
  while (i < len) {
    uint8_t c = s[i];
    if (c < 0x80) { out->push_back(c); i += 1; }
    else if (c < 0xE0) {
      out->push_back(((c & 0x1F) << 6) | (s[i + 1] & 0x3F));
      i += 2;
    } else if (c < 0xF0) {
      out->push_back(((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6) |
                     (s[i + 2] & 0x3F));
      i += 3;
    } else {
      out->push_back(((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12) |
                     ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F));
      i += 4;
    }
  }
}

void build_span(const std::vector<uint32_t>& cur, int ulscript,
                std::vector<Span>* out) {
  Span sp;
  sp.ulscript = ulscript;
  sp.cps.reserve(cur.size() + 2);
  sp.cps.push_back(0x20);
  for (uint32_t cp : cur) sp.cps.push_back(cp);
  sp.buf.reserve(cur.size() * 2 + kTailPad + 4);
  for (uint32_t cp : sp.cps) u8encode(cp, &sp.buf);
  sp.text_bytes = (int)sp.buf.size();
  sp.buf.push_back(0x20); sp.buf.push_back(0x20); sp.buf.push_back(0x20);
  sp.buf.resize(sp.text_bytes + kTailPad, 0);
  sp.cps.push_back(0x20);
  out->push_back(std::move(sp));
}

void segment_text(const uint8_t* text, int text_len,
                  std::vector<Span>* spans) {
  std::vector<uint32_t> cps;
  cps.reserve(text_len);
  u8decode(text, text_len, &cps);
  const int n = (int)cps.size();
  if (n == 0) return;

  std::vector<uint8_t> script(n);
  std::vector<uint32_t> lower(n);
  std::vector<int8_t> u8l(n);
  std::vector<int64_t> byte_before(n + 1);
  int64_t acc = 0;
  for (int i = 0; i < n; i++) {
    uint32_t cp = cps[i] > 0x10FFFF ? 0x10FFFF : cps[i];
    script[i] = g.script_of_cp[cp];
    lower[i] = g.lower_map[cp];
    u8l[i] = (int8_t)u8len_of(cp);
    byte_before[i] = acc;
    acc += u8l[i];
  }
  byte_before[n] = acc;
  const int64_t total_bytes = acc;

  int i = 0;
  while (i < n) {
    int64_t remaining = total_bytes - byte_before[i];
    int soft_limit = kSoftSpanPutBytes;
    if (remaining >= kMaxSpanPutBytes && remaining < 2 * kMaxSpanPutBytes)
      soft_limit = (int)(remaining / 2);
    while (i < n && script[i] == 0) i++;
    if (i >= n) break;
    const int spanscript = script[i];
    std::vector<uint32_t> cur;
    int put = 1;

    while (i < n) {
      // letter run
      while (i < n) {
        int sc = script[i];
        if (sc == 0) break;
        if (sc != spanscript && sc != kUlScriptInherited) {
          // one embedded foreign letter allowed when the next char is
          // Common or back in-script (getonescriptspan.cc:900-930)
          int sc2 = i + 1 < n ? script[i + 1] : 0;
          if (sc2 != 0 && sc2 != spanscript) break;
        }
        cur.push_back(lower[i]);
        put += u8l[i];
        i++;
        if (put >= kMaxSpanPutBytes) break;
      }
      // non-letter run -> single space
      cur.push_back(0x20);
      put += 1;
      while (i < n && script[i] == 0) i++;
      if (i >= n) break;
      if (script[i] != spanscript && script[i] != kUlScriptInherited) break;
      if (put >= soft_limit) break;
    }
    if (cur.size() > 1) build_span(cur, spanscript, spans);
  }
}

// ---- per-span candidate records (preprocess/pack.py) ----
struct Rec {
  int32_t offset;
  int8_t kind;
  int8_t prio;     // merge priority at equal offsets
  uint8_t fp_hi;   // octa hash bits 32-39
  int8_t pad_;
  uint32_t fp;     // fingerprint low 32 / seed langprob / uni class
};

inline int8_t prio_of(int8_t kind) {
  switch (kind) {
    case SEED: return -1;
    case DELTA_OCTA: case BI_DELTA: return 0;
    case DISTINCT_OCTA: case BI_DISTINCT: return 1;
    default: return 2;  // QUAD, UNI
  }
}

// Quad + word candidates in linear merge order; false => scalar fallback
bool pack_quad_span(const Span& sp, std::vector<Rec>* recs) {
  const uint8_t* b = sp.buf.data();
  const int64_t buflen = (int64_t)sp.buf.size();
  const int limit = sp.text_bytes;

  // quad positions (grams.py quad_positions: 2-char steps, word-end jump,
  // space/vowel skip; cldutil.cc:338-395)
  std::vector<int32_t> qpos, qlen;
  {
    int64_t src = 1;
    if (b[src] == 0x20) src++;
    while (src < limit) {
      int64_t e = src;
      e += adv.but_space[b[e]];
      e += adv.but_space[b[e]];
      int64_t mid = e;
      e += adv.but_space[b[e]];
      e += adv.but_space[b[e]];
      qpos.push_back((int32_t)src);
      qlen.push_back((int32_t)(e - src));
      src = b[e] == 0x20 ? e : mid;
      if (src < limit) src += adv.space_vowel[b[src]];
      else src = limit;
    }
  }
  if ((int)qpos.size() > kMaxScoringHits) return false;  // multi-round span

  // word records with hash-only repeat filter + pairs (cldutil.cc:459-502)
  {
    int64_t src = 1;
    if (b[src] == 0x20) src++;
    uint64_t cache[2] = {0, 0};
    int nxt = 0;
    int n_delta = 0, n_distinct = 0;
    int64_t srclimit = limit + 1;
    int charcount = 0;
    int64_t prior_word_start = src, word_start = src, word_end = word_start;
    while (src < srclimit) {
      if (b[src] == 0x20) {
        if (word_end > word_start) {
          int64_t wlen = word_end - word_start;
          uint64_t fpw = octa_hash40(b, word_start, wlen, buflen);
          if (fpw != cache[0] && fpw != cache[1]) {
            cache[nxt] = fpw;
            nxt = 1 - nxt;
            uint64_t prior = cache[nxt];
            if (prior != 0 && prior != fpw) {
              uint64_t pfp = pair_hash(prior, fpw);
              recs->push_back({(int32_t)prior_word_start, DISTINCT_OCTA, 0,
                               (uint8_t)(pfp >> 32), 0, (uint32_t)pfp});
              n_distinct++;
            }
            recs->push_back({(int32_t)word_start, DISTINCT_OCTA, 0,
                             (uint8_t)(fpw >> 32), 0, (uint32_t)fpw});
            recs->push_back({(int32_t)word_start, DELTA_OCTA, 0,
                             (uint8_t)(fpw >> 32), 0, (uint32_t)fpw});
            n_delta++;
            n_distinct++;
            if (n_delta >= kMaxScoringHits ||
                n_distinct >= kMaxScoringHits - 1)
              break;
          }
        }
        charcount = 0;
        prior_word_start = word_start;
        word_start = src + 1;
        word_end = word_start;
      } else {
        charcount++;
      }
      src += adv.one[b[src]];
      if (charcount <= 8) word_end = src;
    }
  }

  for (size_t i = 0; i < qpos.size(); i++) {
    uint32_t fp = quad_hash(b, qpos[i], qlen[i]);
    recs->push_back({qpos[i], QUAD, 0, 0, 0, fp});
  }
  return true;
}

bool pack_cjk_span(const Span& sp, std::vector<Rec>* recs) {
  const int n = (int)sp.cps.size();
  std::vector<int64_t> starts(n), ends(n);
  int64_t acc = 0;
  for (int i = 0; i < n; i++) {
    starts[i] = acc;
    acc += u8len_of(sp.cps[i]);
    ends[i] = acc;
  }
  int n_uni = 0;
  for (int i = 0; i < n; i++) {
    uint32_t cp = sp.cps[i] > 0x10FFFF ? 0x10FFFF : sp.cps[i];
    uint8_t prop = g.cjk_prop[cp];
    if (prop > 0 && starts[i] >= 1 && starts[i] < sp.text_bytes) n_uni++;
  }
  if (n_uni > kMaxScoringHits) return false;  // multi-round span
  for (int i = 0; i < n; i++) {
    uint32_t cp = sp.cps[i] > 0x10FFFF ? 0x10FFFF : sp.cps[i];
    uint8_t prop = g.cjk_prop[cp];
    if (prop > 0 && starts[i] >= 1 && starts[i] < sp.text_bytes)
      recs->push_back({(int32_t)ends[i], UNI, 0, 0, 0, prop});
  }
  for (int i = 0; i + 1 < n; i++) {
    int64_t len2 = ends[i + 1] - starts[i];
    if (len2 >= 6 && starts[i] >= 1 && starts[i] < sp.text_bytes) {
      uint32_t fp = bi_hash(sp.buf.data(), starts[i], len2);
      recs->push_back({(int32_t)starts[i], BI_DELTA, 0, 0, 0, fp});
      if (!g.distinctbi_empty)
        recs->push_back({(int32_t)starts[i], BI_DISTINCT, 0, 0, 0, fp});
    }
  }
  return true;
}

// ---- per-document packing (pack.py pack_batch body) ----
struct Out {
  int8_t* kind; int32_t* offset; uint32_t* fp; uint8_t* fp_hi;
  int32_t* chunk_base; int32_t* span_start;
  int32_t* span_end_off; int8_t* side; int8_t* cjk; int16_t* script;
  int16_t* chunk_script; int8_t* chunk_cjk; int8_t* chunk_side;
  int32_t* chunk_span_end;
  int32_t* direct_adds; int32_t* text_bytes; uint8_t* fallback;
  int32_t* n_slots; int32_t* n_chunks;
  int L, C, D, flags;
};

void pack_one_doc(const uint8_t* text, int text_len, int b, const Out& o) {
  std::vector<Span> spans;
  segment_text(text, text_len, &spans);

  const int L = o.L, C = o.C;
  int8_t* kind = o.kind + (int64_t)b * L;
  int32_t* offset = o.offset + (int64_t)b * L;
  uint32_t* fp = o.fp + (int64_t)b * L;
  uint8_t* fp_hi = o.fp_hi + (int64_t)b * L;
  int32_t* chunk_base_a = o.chunk_base + (int64_t)b * L;
  int32_t* span_start_a = o.span_start + (int64_t)b * L;
  int32_t* span_end_a = o.span_end_off + (int64_t)b * L;
  int8_t* side_a = o.side + (int64_t)b * L;
  int8_t* cjk_a = o.cjk + (int64_t)b * L;
  int16_t* script_a = o.script + (int64_t)b * L;
  int16_t* cscript = o.chunk_script + (int64_t)b * C;
  int8_t* ccjk = o.chunk_cjk + (int64_t)b * C;
  int8_t* cside = o.chunk_side + (int64_t)b * C;
  int32_t* cspanend = o.chunk_span_end + (int64_t)b * C;
  int32_t* dadds = o.direct_adds + (int64_t)b * o.D * 3;

  int slot = 0, chunk_base = 0, n_direct = 0;
  int64_t total = 0;
  bool ok = true;
  std::vector<Rec> recs;
  for (const Span& sp : spans) {
    total += sp.text_bytes;
    int rt = sp.ulscript < g.n_scripts ? g.rtype[sp.ulscript] : 0;
    if (!(o.flags & 1) && sp.text_bytes > (kSqueezeTestThresh >> 1) &&
        cheap_squeeze_trigger(sp.buf.data(), sp.text_bytes)) {
      ok = false;  // squeeze-trigger doc -> scalar path (FLAG_FINISH skips)
      break;
    }
    if (rt == 0 || rt == 1) {  // RTypeNone/One: direct doc-tote add
      if (n_direct >= o.D || chunk_base >= C) { ok = false; break; }
      dadds[n_direct * 3 + 0] = chunk_base;
      dadds[n_direct * 3 + 1] = g.deflang[sp.ulscript];
      dadds[n_direct * 3 + 2] = sp.text_bytes;
      n_direct++;
      chunk_base++;
      continue;
    }
    if (sp.text_bytes <= 1) continue;
    const bool cjk = rt == 3;
    recs.clear();
    bool fits = cjk ? pack_cjk_span(sp, &recs) : pack_quad_span(sp, &recs);
    if (!fits) { ok = false; break; }
    recs.push_back({1, SEED, 0, 0, 0,
                    sp.ulscript < g.n_scripts ? g.seed_lp[sp.ulscript] : 0});
    for (size_t i = 0; i < recs.size(); i++)
      recs[i].prio = prio_of(recs[i].kind);
    std::stable_sort(recs.begin(), recs.end(),
                     [](const Rec& a, const Rec& c) {
                       if (a.offset != c.offset) return a.offset < c.offset;
                       return a.prio < c.prio;
                     });
    int n_base_max = 0;
    for (const Rec& r : recs)
      n_base_max += (r.kind == SEED || r.kind == QUAD || r.kind == UNI);
    int chunksize = cjk ? 50 : 20;
    int span_chunks = 1 + (n_base_max + chunksize - 1) / chunksize;
    if (span_chunks < 1) span_chunks = 1;
    if (slot + (int)recs.size() > L || chunk_base + span_chunks > C) {
      ok = false;
      break;
    }
    int8_t side = sp.ulscript == kUlScriptLatin ? 0 : 1;
    int start = slot;
    for (const Rec& r : recs) {
      kind[slot] = r.kind;
      offset[slot] = r.offset;
      fp[slot] = r.fp;
      fp_hi[slot] = r.fp_hi;
      chunk_base_a[slot] = chunk_base;
      span_start_a[slot] = start;
      span_end_a[slot] = sp.text_bytes;
      side_a[slot] = side;
      cjk_a[slot] = cjk;
      script_a[slot] = (int16_t)sp.ulscript;
      slot++;
    }
    for (int c = chunk_base; c < chunk_base + span_chunks; c++) {
      cscript[c] = (int16_t)sp.ulscript;
      ccjk[c] = cjk;
      cside[c] = side;
      cspanend[c] = sp.text_bytes;
    }
    chunk_base += span_chunks;
  }
  o.text_bytes[b] = (int32_t)total;
  o.fallback[b] = !ok;
  o.n_slots[b] = slot;
  o.n_chunks[b] = chunk_base;
}

}  // namespace

extern "C" {

void ldt_init(const uint8_t* script_of_cp, const uint32_t* lower_map,
              const uint8_t* cjk_prop, const int32_t* rtype,
              const int32_t* deflang, const uint32_t* seed_lp,
              int32_t n_scripts, int32_t distinctbi_empty) {
  g = Ctx{script_of_cp, lower_map, cjk_prop, rtype, deflang, seed_lp,
          n_scripts, distinctbi_empty};
}

// texts: concatenated UTF-8 docs; bounds[i]..bounds[i+1] delimit doc i.
void ldt_pack_batch(const uint8_t* texts, const int64_t* bounds,
                    int32_t n_docs, int32_t L, int32_t C, int32_t D,
                    int32_t flags, int32_t n_threads,
                    int8_t* kind, int32_t* offset, uint32_t* fp,
                    uint8_t* fp_hi,
                    int32_t* chunk_base, int32_t* span_start,
                    int32_t* span_end_off, int8_t* side, int8_t* cjk,
                    int16_t* script, int16_t* chunk_script,
                    int8_t* chunk_cjk, int8_t* chunk_side,
                    int32_t* chunk_span_end,
                    int32_t* direct_adds, int32_t* text_bytes,
                    uint8_t* fallback, int32_t* n_slots,
                    int32_t* n_chunks) {
  Out o{kind, offset, fp, fp_hi, chunk_base, span_start,
        span_end_off, side, cjk, script, chunk_script, chunk_cjk,
        chunk_side, chunk_span_end, direct_adds, text_bytes, fallback,
        n_slots, n_chunks, L, C, D, flags};
  auto work = [&](int lo, int hi) {
    for (int b = lo; b < hi; b++)
      pack_one_doc(texts + bounds[b], (int)(bounds[b + 1] - bounds[b]), b,
                   o);
  };
  if (n_threads <= 1 || n_docs < 2 * n_threads) {
    work(0, n_docs);
    return;
  }
  std::vector<std::thread> ts;
  int per = (n_docs + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    int lo = t * per, hi = std::min(n_docs, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
