#!/usr/bin/env python3
"""Per-stage device timing for the batched scorer.

Runs the bench corpus through score_batch with staged early returns
(ops/score.py score_batch_staged): stage N compiles only the program prefix
up to that stage, so t(N) - t(N-1) attributes device time to stage N.
Results feed docs/PERF.md.
"""
from __future__ import annotations

import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

STAGES = {
    1: "dense reconstruct + table probes (gathers)",
    2: "+ langprob resolution (gathers)",
    3: "+ quad repeat filter / boost rotation (lax.scan)",
    4: "+ chunk assignment (cumsums + masked reduce)",
    5: "+ chunk totes (one-hot matmul)",
    6: "+ distinct boosts (elementwise)",
    0: "full program (double argmax + summaries)",
}


def main(batch_size: int = 4096, iters: int = 5):
    import jax
    from bench import make_corpus
    from language_detector_tpu.models.ngram import NgramBatchEngine, to_wire
    from language_detector_tpu.ops.score import score_batch_staged

    eng = NgramBatchEngine()
    docs = make_corpus(batch_size)
    packed = eng._pack(docs, eng.tables, eng.reg,
                       max_slots=eng.max_slots, max_chunks=eng.max_chunks,
                       flags=eng.flags)
    p = to_wire(packed, eng.max_slots, eng.max_chunks)
    B = p["doc_start"].shape[0]
    L = p["l_iota"].shape[0]
    C = p["chunks"].shape[1]
    print(f"wire shapes: B={B} L={L} C={C} N={p['w0'].shape[1]} "
          f"({sum(a.nbytes for a in p.values())/1e6:.2f} MB)", flush=True)

    # Device-resident inputs: time compute, not host->device transfer.
    # NOTE (axon backend): block_until_ready returns at dispatch, not at
    # completion — only a host fetch (np.asarray) forces execution, so all
    # timings below time through a fetch of the stage's tiny checksum.
    import numpy as np
    pd = {k: jax.device_put(v) for k, v in p.items()}
    jax.block_until_ready(list(pd.values()))

    t_transfer = time.time()
    for _ in range(iters):
        d = {k: jax.device_put(v) for k, v in p.items()}
        np.asarray(jnp_sum_probe(d))
    t_transfer = (time.time() - t_transfer) / iters
    print(f"host->device transfer (forced): {t_transfer*1e3:8.1f} ms",
          flush=True)

    prev = 0.0
    for stage in (1, 2, 3, 4, 5, 6, 0):
        np.asarray(score_batch_staged(eng.dt, pd, stage=stage))  # compile
        t0 = time.time()
        for _ in range(iters):
            np.asarray(score_batch_staged(eng.dt, pd, stage=stage))
        dt = (time.time() - t0) / iters
        print(f"stage {stage or 7}: {dt*1e3:8.1f} ms  "
              f"(+{(dt-prev)*1e3:7.1f} ms)  {STAGES[stage]}", flush=True)
        prev = dt


import jax.numpy as _jnp  # noqa: E402


def jnp_sum_probe(d):
    """Tiny reduction over every wire array: fetching it forces the
    transfers to complete without paying a large readback."""
    import jax
    return _probe_jit(d)


@__import__("functools").partial(__import__("jax").jit)
def _probe_jit(d):
    return sum(_jnp.sum(v.astype(_jnp.int32)) for v in d.values())


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
