"""HTML stripping for non-plain-text input.

The reference interleaves tag skipping and entity expansion with its
script scanner (GetOneScriptSpan's kTagParseTbl_0 state machine,
getonescriptspan.cc:150-196, and ReadEntity/EntityToBuffer :393-480). The
TPU-first design separates concerns: one host pre-pass turns HTML into
the equivalent clean text (tags become a single non-letter, entities
become their decoded characters), and the unchanged plain-text
segmentation/packing pipeline runs on the result. An offset map from
cleaned characters back to original character positions supports
per-range results.

Tag grammar reproduced from the reference state machine:
  - '<' opens a tag; it ends at '>'; quoted attribute values ("..."
    '...') may contain '>' / '<'
  - another '<' inside an unquoted tag body aborts: the original '<' is
    treated as a plain character (kTagParseTbl_0 state 3/9 column '<')
  - '<!--' comments run to '-->'
  - <script> and <style> swallow their content through the matching
    close tag
  - an unterminated construct swallows the rest of the input

Entity grammar (ReadEntity, getonescriptspan.cc:393-449): numeric
entities (&#123; &#x1F;) end at the first non-digit; named entities end
at the first non-alphanumeric; values >= 256 must be ';'-terminated
(the IE6 '&lang=' URL compatibility rule); a ';' terminator is consumed.
Values are clamped like FixUnicodeValue (surrogates/overflow -> U+FFFD,
C0/C1 controls preserved, fixunicodevalue.cc:22-54).
"""
from __future__ import annotations

import numpy as np

from ..tables import ScoringTables, load_tables

_WS = " \t\r\n"


def _fix_unicode_value(cp: int) -> int:
    """FixUnicodeValue (fixunicodevalue.cc:22-54)."""
    if 0 <= cp < 0xD800:
        return cp
    if 0xE000 <= cp <= 0x10FFFF:
        return cp
    return 0xFFFD


class _Entities:
    def __init__(self, tables: ScoringTables):
        self.map = {str(n): int(v) for n, v in
                    zip(tables.entity_names, tables.entity_values)}


_entities_cache: tuple = ()


def _entity_map(tables: ScoringTables) -> dict:
    global _entities_cache
    if _entities_cache and _entities_cache[0] is tables:
        return _entities_cache[1]
    m = _Entities(tables).map
    _entities_cache = (tables, m)
    return m


def _read_entity(text: str, i: int, entities: dict) -> tuple:
    """(codepoint | None, chars_consumed) for the '&' at text[i]."""
    n = len(text)
    j = i + 1
    if j >= n:
        return None, 1
    if text[j] == "#":
        if j + 2 >= n:
            return None, 1
        if text[j + 1] in "xX":
            k = j + 2
            start = k
            val = 0
            while k < n and text[k] in "0123456789abcdefABCDEF":
                val = min(val * 16 + int(text[k], 16), 0x110000)
                k += 1
            if k == start:
                return None, 1
        else:
            k = j + 1
            start = k
            val = 0
            while k < n and text[k].isdigit():
                val = min(val * 10 + int(text[k]), 0x110000)
                k += 1
            if k == start:
                return None, 1
        end = k
    else:
        k = j
        while k < n and text[k].isalnum() and ord(text[k]) < 128:
            k += 1
        name = text[j:k]
        if name not in entities:
            return None, 1
        val = entities[name]
        # IE6 rule: high-value entities require the ';' terminator
        if val >= 256 and not (k < n and text[k] == ";"):
            return None, 1
        end = k
    if end < n and text[end] == ";":
        end += 1
    return _fix_unicode_value(val), end - i


def _nl_or_gt_class(c: str) -> bool:
    """True for '>' and the CR/NL byte classes of kCharToSub
    (getonescriptspan.cc:81-103): ASCII whitespace/digits/punctuation
    other than the special tag chars. Non-ASCII characters present their
    UTF-8 LEAD byte (0xC2..) to the reference state machine, which is PL
    class — ordinary-tag routing."""
    if c == ">" or c in "\r\n":
        return True
    o = ord(c)
    if o >= 0x80:
        return False
    return not c.isalpha() and c not in "!\"&'-/<>"


def _skip_element_content(lower: str, i: int, elem: str) -> int:
    """Consume from '<elem' through the matching '</elem...>' (or to end
    of input), mirroring kTagParseTbl states 19-27/32-39 (CR/NL may
    separate '</' from the element name)."""
    n = len(lower)
    k = i + 1 + len(elem)
    close = "</"
    while k < n:
        idx = lower.find(close, k)
        if idx < 0:
            return n - i
        j = idx + 2
        while j < n and lower[j] in "\r\n":
            j += 1
        if lower.startswith(elem, j):
            end = lower.find(">", j + len(elem))
            return (n - i) if end < 0 else (end + 1 - i)
        k = idx + 2
    return n - i


def _skip_tag(text: str, lower: str, i: int) -> int:
    """Characters consumed from the '<' at text[i] (1 = treat '<' as a
    plain character)."""
    n = len(text)
    # comment?
    if text.startswith("<!--", i):
        end = text.find("-->", i + 4)
        return (n - i) if end < 0 else (end + 3 - i)
    # <script> / <style> swallow their content when the element name is
    # followed by '>', CR/NL, or any NL-class byte (whitespace, digit,
    # most punctuation — kCharToSub, getonescriptspan.cc:81-103; state
    # 18/31 routes those to the content states, so attributed
    # <script src=...> swallows too); a letter or one of !"&'-/< routes
    # to the ordinary-tag states instead (e.g. <scripts>)
    for elem in ("script", "style"):
        nxt = i + 1 + len(elem)
        if lower.startswith(elem, i + 1) and nxt < n and \
                _nl_or_gt_class(text[nxt]):
            return _skip_element_content(lower, i, elem)
    # ordinary tag: find '>' honoring quoted attribute values; a bare '<'
    # inside aborts (state 3/9 column '<'); a newline inside a quote
    # drops quote handling for the rest of the tag (state 10/11 -> 12)
    j = i + 1
    quote = None
    no_more_quotes = False
    while j < n:
        c = text[j]
        if quote is not None:
            if c == quote:
                quote = None
            elif c in "\r\n":
                quote = None
                no_more_quotes = True
        elif c == ">":
            break
        elif not no_more_quotes and c in "\"'":
            quote = c
        elif c == "<":
            return 1  # kTagParseTbl state 3/9 column '<': not a tag
        j += 1
    if j >= n:
        return n - i  # unterminated tag swallows the rest
    return j + 1 - i


def clean_html(text: str, tables: ScoringTables | None = None) -> tuple:
    """HTML -> (clean text, offsets): tags collapse to one space, entities
    decode in place. offsets[k] = original character index that produced
    clean[k] (space separators map to the position they replaced)."""
    tables = tables or load_tables()
    entities = _entity_map(tables)
    out: list = []
    offs: list = []
    i = 0
    n = len(text)
    lower = text.lower()
    while i < n:
        c = text[i]
        if c == "<":
            took = _skip_tag(text, lower, i)
            if took == 1:
                out.append("<")
                offs.append(i)
                i += 1
            else:
                out.append(" ")
                offs.append(i)
                i += took
        elif c == "&":
            cp, took = _read_entity(text, i, entities)
            if cp is not None and cp > 0:
                out.append(chr(cp))
                offs.append(i)
            # invalid entity: the '&' is consumed and dropped entirely,
            # so adjacent letters join ("R&D" -> "RD"; EntityToBuffer
            # getonescriptspan.cc:471-479 take=1, put=0)
            i += took
        else:
            out.append(c)
            offs.append(i)
            i += 1
    return "".join(out), np.array(offs, dtype=np.int32)
