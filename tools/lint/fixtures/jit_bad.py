"""Seeded jit-contract violations (tests/test_lint.py)."""
import jax


def accum_impl(acc, x):
    return acc + x


step = jax.jit(accum_impl, donate_argnums=(0,))


def run_donated(acc, xs):
    out = step(acc, xs)
    return out + acc  # jit-donated-read: acc's buffer was donated


def make_entry(tables):
    scale = 1.0
    for t in tables:
        scale = scale * t  # reassigned under a loop: per-call-varying

    def entry(x):
        return x * scale  # jit-recompile-capture

    return jax.jit(entry)
