#!/usr/bin/env python3
"""Per-language precision/recall/F evaluation harness.

The TPU rebuild of the reference's offline evaluator (scoreutf8text.cc:547,
whose published outputs are cld2/docs/evaluate_cld2_large_20140122.txt
etc.): detect every labeled document, tally per-language
correct/wrong-got/wrong-missed counts, and print per-language
precision/recall/F plus the _Totals_Known aggregate row and the top
confusions per language.

Input: a TSV of "code<TAB>text" lines (--corpus, streamed — corpora of
millions of lines never fully materialize), or the reference golden suite
by default (tests/golden_data.py). Detection runs the batched engine's
codes-only path in 16K-doc blocks when an accelerator is available, else
the scalar engine; --mesh N shards blocks data-parallel over an N-device
mesh (BASELINE configs #4-#5 are corpus streams over v5e meshes).

Usage:
  python3 tools/eval_corpus.py [--corpus file.tsv] [--out docs/eval.txt]
                               [--mesh N] [--limit N]
"""
from __future__ import annotations

import argparse
import collections
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

from language_detector_tpu import enable_jit_cache  # noqa: E402

enable_jit_cache()

from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import ScoringTables  # noqa: E402

# label aliases: the golden labels use a few codes our newer registry
# renames (tests/test_golden_parity.py applies the same equivalence)
ALIASES = {("hmn", "blu"): True}


def iter_pairs(path: str | None, limit: int | None = None):
    """Stream (label, text) pairs; TSV files are read line-by-line so a
    multi-GB corpus never materializes."""
    n = 0
    if path:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                if "\t" not in line:
                    continue
                code, text = line.rstrip("\n").split("\t", 1)
                yield code.strip(), text
                n += 1
                if limit and n >= limit:
                    return
        return
    from golden_data import golden_pairs
    for _, lang, raw in golden_pairs():
        yield lang, raw.decode("utf-8", errors="replace")
        n += 1
        if limit and n >= limit:
            return


def make_detector(tables, mesh_size: int | None = None):
    """codes-detector over 16K-doc blocks: batched engine (codes-only
    fast path, optionally mesh-sharded) or the scalar engine."""
    if mesh_size:
        # an explicit mesh request must not silently degrade: a
        # too-small device count or missing accelerator raises here
        # instead of publishing scalar numbers as "mesh" results
        from language_detector_tpu.models.ngram import NgramBatchEngine
        from language_detector_tpu.parallel.mesh import batch_mesh
        eng = NgramBatchEngine(tables, registry,
                               mesh=batch_mesh(mesh_size))
        return lambda texts: eng.detect_codes(texts, batch_size=16384)
    try:
        from language_detector_tpu.models.ngram import NgramBatchEngine
        eng = NgramBatchEngine(tables, registry)
        return lambda texts: eng.detect_codes(texts, batch_size=16384)
    except (ImportError, RuntimeError):
        from language_detector_tpu.engine_scalar import detect_scalar
        return lambda texts: [
            registry.code(detect_scalar(t, tables, registry).summary_lang)
            for t in texts]


BLOCK = 65536  # docs per streamed detection block


def evaluate(pair_iter, tables, mesh_size: int | None = None,
             warm: bool = False) -> str:
    """warm=True primes the detector's compiled programs on the first
    block before timing (small suites like the 402-doc goldens would
    otherwise publish a compile-dominated rate — the round-4 table's
    "92 docs/sec" header was exactly that artifact; streamed corpora
    amortize compiles naturally and don't need it)."""
    detect = make_detector(tables, mesh_size)
    per_lang = collections.defaultdict(lambda: dict(correct=0, got=0,
                                                    actual=0))
    confusion = collections.defaultdict(collections.Counter)
    n_docs = 0
    took = 0.0
    block: list = []

    def flush():
        nonlocal n_docs, took
        if not block:
            return
        t0 = time.time()
        got = detect([t for _, t in block])
        took += time.time() - t0
        n_docs += len(block)
        for (want, _), g in zip(block, got):
            hit = g == want or (g, want) in ALIASES
            per_lang[want]["actual"] += 1
            per_lang[g]["got"] += 1
            if hit:
                per_lang[want]["correct"] += 1
            else:
                confusion[want][g] += 1
        block.clear()

    for pair in pair_iter:
        block.append(pair)
        if len(block) >= BLOCK:
            if warm:
                detect([t for _, t in block])  # compile pass, untimed
                warm = False
            flush()
    if warm and block:
        detect([t for _, t in block])  # compile pass, untimed
    flush()

    lines = []
    lines.append(f"Evaluation over {n_docs} labeled documents "
                 f"({len(per_lang)} languages), "
                 f"{n_docs/max(took,1e-9):.0f} docs/sec")
    lines.append("")
    lines.append(f"{'Language':12s} {'Precision':>9s} {'Recall':>8s} "
                 f"{'F':>7s} {'N':>6s}  Top confusions")
    tot_c = tot_g = tot_a = 0
    for code in sorted(per_lang):
        d = per_lang[code]
        if d["actual"] == 0:
            continue  # only appears as a wrong guess
        prec = d["correct"] / d["got"] if d["got"] else 0.0
        rec = d["correct"] / d["actual"]
        f = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        conf = " ".join(f"{g}={n}" for g, n in
                        confusion[code].most_common(5))
        lines.append(f"{code:12s} {prec*100:8.2f}% {rec*100:7.2f}% "
                     f"{f:7.4f} {d['actual']:6d}  {conf}")
        tot_c += d["correct"]
        tot_g += d["got"]
        tot_a += d["actual"]
    prec = tot_c / tot_g if tot_g else 0.0
    rec = tot_c / tot_a if tot_a else 0.0
    f = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    lines.append("")
    lines.append(f"{'_Totals_Known':12s} {prec*100:8.2f}% {rec*100:7.2f}% "
                 f"{f:7.4f} {tot_a:6d}")
    return "\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", default=None,
                    help="TSV code<TAB>text (default: golden suite)")
    ap.add_argument("--quad-tables", default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--mesh", type=int, default=None,
                    help="shard blocks over an N-device mesh")
    ap.add_argument("--limit", type=int, default=None,
                    help="stop after N corpus lines")
    ap.add_argument("--warm", action="store_true",
                    help="prime compiled programs before timing "
                         "(small suites)")
    args = ap.parse_args()

    tables = ScoringTables.load(quad_path=args.quad_tables)
    pairs = iter_pairs(args.corpus, args.limit)
    report = evaluate(pairs, tables, args.mesh, warm=args.warm)
    print(report)
    if args.out:
        Path(args.out).write_text(report)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
