"""Long documents on the device path: multi-round hitbuffer fills.

Spans with more than 1000 base hits score in rounds (the reference's
hitbuffer refill loop, scoreonescriptspan.cc:1249-1274); the native packer
mirrors it (packer.cc scan_quad_round/scan_cjk_round). On the chunk-major
flat wire a long document simply contributes more chunk rows to the same
grid as everything else — no routing, no fallback, no special engine.
"""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
from golden_data import golden_pairs  # noqa: E402

from language_detector_tpu.engine_scalar import detect_scalar  # noqa: E402
from language_detector_tpu.models.ngram import NgramBatchEngine  # noqa: E402

PAIRS = golden_pairs()
pytestmark = pytest.mark.skipif(not PAIRS,
                                reason="reference snapshot unavailable")


def _texts():
    return [raw.decode("utf-8", errors="replace") for _, _, raw in PAIRS]


def _long_docs():
    texts = _texts()
    # distinct-paragraph concatenations (varied text, so the squeeze
    # predictor does not trigger), 5-35KB
    return [" ".join(texts[(k + i * 7) % len(texts)] for i in range(n))
            for k, n in ((3, 12), (17, 25), (41, 40), (89, 60), (11, 100))]


def test_multi_round_spans_stay_on_device():
    eng = NgramBatchEngine(max_slots=16384, max_chunks=256)
    docs = _long_docs()
    rs = eng.detect_batch(docs)
    assert eng.stats["fallback_docs"] == 0, \
        "long documents must score on the device path"
    for d, r in zip(docs, rs):
        s = detect_scalar(d, eng.tables, eng.reg)
        assert (r.summary_lang, r.language3, r.percent3) == \
            (s.summary_lang, s.language3, s.percent3), d[:60]


def test_detect_many_routes_long_docs():
    texts = _texts()
    docs = [texts[i % len(texts)][:200] for i in range(120)]
    for pos, d in zip((7, 40, 77), _long_docs()):
        docs.insert(pos, d)
    eng = NgramBatchEngine()
    rs = eng.detect_many(docs, batch_size=64)
    assert eng.stats["fallback_docs"] == 0
    for d, r in zip(docs, rs):
        s = detect_scalar(d, eng.tables, eng.reg)
        assert (r.summary_lang, r.percent3) == \
            (s.summary_lang, s.percent3), d[:60]


def test_dispatch_volume_cap():
    """Batches slice by content volume, not just document count: a pile
    of large documents must split into several dispatches (device memory
    is linear in chunk rows), and results stay scalar-exact across the
    slice boundaries."""
    texts = _texts()
    big = " ".join(texts[:40])
    docs = [big] * 8 + [texts[0][:200]]
    eng = NgramBatchEngine()
    eng.DISPATCH_CHAR_BUDGET = 3 * len(big)  # force multiple slices
    slices = list(eng._slices(docs, batch_size=1024))
    assert len(slices) >= 3
    assert sum(len(s) for s in slices) == len(docs)
    rs = eng.detect_batch(docs)
    want = detect_scalar(big, eng.tables, eng.reg)
    for r in rs[:8]:
        assert (r.summary_lang, r.percent3) == \
            (want.summary_lang, want.percent3)


def test_single_script_60kb_on_device():
    """A long single-SCRIPT document (one span chain, hundreds of chunk
    rows) stays on the device and in the SAME batch as short docs."""
    from language_detector_tpu import native
    texts = _texts()
    latin = [t for t in texts if max(t.encode("utf-8", "replace")) < 0xD0
             or all(ord(c) < 0x500 for c in t)]
    doc = " ".join((latin or texts) * 3)[:60000]
    eng = NgramBatchEngine()
    cb = native.pack_chunks_native([doc, texts[0][:200]], eng.tables,
                                   eng.reg)
    assert int(cb.n_chunks.max()) > 256, \
        "document must produce hundreds of chunk rows to pin this case"
    assert not cb.fallback.any()
    rs = eng.detect_batch([doc, texts[0][:200]])
    assert eng.stats["fallback_docs"] == 0
    s = detect_scalar(doc, eng.tables, eng.reg)
    assert (rs[0].summary_lang, rs[0].language3, rs[0].percent3) == \
        (s.summary_lang, s.language3, s.percent3)
