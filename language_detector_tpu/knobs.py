"""Central registry of environment knobs — the only legal way to read
env configuration inside the package.

Every knob the serving stack honors is declared here once, with its
name, type, default, and documentation. Call sites read through the
typed accessors (get_int / get_float / get_str / get_bool /
get_levels); `tools/lint`'s knob-registry analyzer bans direct
`os.environ` / `os.getenv` reads anywhere else in
`language_detector_tpu/`, and the docs table in docs/OBSERVABILITY.md
is generated from this registry (`python -m tools.lint
--write-knob-docs`), so code, checks, and docs cannot drift.

Semantics (shared by every knob, formerly re-implemented per file):

  - unset or blank -> the declared default (None for off-by-default
    bounds);
  - a mistyped value logs a loud warning and falls back to the default
    instead of silently disabling the guard the operator thinks is
    active (the rule service/recycle.py established);
  - `bound=True` knobs treat non-positive values as "feature off"
    (returns None), matching the admission/recycle bound contract.

Values are read from the environment at every call (no import-time
caching) so tests that monkeypatch a knob and re-init a component see
the change.

Knobs declared `mutable=True` form the runtime config plane's settable
surface: POST /configz stages a validated override batch
(apply_overrides) that the accessors consult before the environment,
and `current()` returns a versioned snapshot of the whole mutable
surface. Mutable knobs must therefore be read at use time — the lint
analyzer flags any import-time-cached read of one.
"""
from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

_log = logging.getLogger(__name__)

_FALSE_WORDS = frozenset(("", "0", "false", "no", "off"))


@dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    ktype: str              # "int" | "float" | "str" | "bool" | "levels"
    default: object         # typed default (None = off / not set)
    doc: str
    bound: bool = field(default=False)   # <= 0 means "off" -> None
    external: bool = field(default=False)  # contract var owned by the
    # platform (JAX/TPU launchers); declared for the docs table and the
    # lint registry, defaults are never exported back
    mutable: bool = field(default=False)  # runtime-settable through the
    # config plane (POST /configz -> apply_overrides); mutable knobs
    # must be read at use time, never cached at import (lint-enforced)
    mrange: tuple | None = field(default=None)  # (lo, hi) inclusive
    # bounds a runtime override must satisfy; also the autotuner's
    # declared search interval for this knob


def _k(name: str, ktype: str, default: object, doc: str,
       bound: bool = False, external: bool = False,
       mutable: bool = False, mrange: tuple | None = None) -> Knob:
    return Knob(name, ktype, default, doc, bound, external,
                mutable, mrange)


_DECLARATIONS: tuple[Knob, ...] = (
    # -- telemetry (telemetry.py) -------------------------------------
    _k("LDT_SLOW_TRACE_MS", "float", 0.0,
       "Slow-request sampler threshold in ms; requests over it record "
       "their full span tree into the /debug/slow ring. 0/unset = off."),
    _k("LDT_SLOW_TRACE_RING", "int", 64,
       "Capacity of the slow-trace ring (newest traces win)."),
    # -- result cache (service/batcher.py wiring) ---------------------
    _k("LDT_RESULT_CACHE_MB", "float", 0.0,
       "Batcher result-cache budget in MB; 0/unset disables the cache."),
    # -- worker self-recycle (service/recycle.py) ---------------------
    _k("LDT_MAX_DISPATCHES", "int", None,
       "Recycle the worker after this many device dispatches "
       "(tunneled-backend RSS leak mitigation, docs/PERF.md).",
       bound=True),
    _k("LDT_MAX_RSS_MB", "float", None,
       "Recycle the worker when process RSS exceeds this many MB.",
       bound=True),
    _k("LDT_RECYCLE_CHECK_SEC", "float", 5.0,
       "Recycle-watcher poll period in seconds (floor 0.05)."),
    _k("LDT_RECYCLE_DRAIN_SEC", "float", 5.0,
       "Bounded window for in-flight handlers to finish their response "
       "during a planned recycle before their sockets are aborted."),
    # -- admission control (service/admission.py) ---------------------
    _k("LDT_MAX_QUEUE_DOCS", "int", None,
       "Admission bound: max documents admitted and not yet completed; "
       "past it requests shed with 429.", bound=True,
       mutable=True, mrange=(1, 1_000_000)),
    _k("LDT_MAX_QUEUE_BYTES", "int", None,
       "Admission bound: max byte-weighted cost (4 bytes per estimated "
       "packer slot) held at once.", bound=True,
       mutable=True, mrange=(1, 1 << 31)),
    _k("LDT_MAX_INFLIGHT", "int", None,
       "Admission bound: max HTTP requests in flight.", bound=True,
       mutable=True, mrange=(1, 65536)),
    _k("LDT_DEFAULT_DEADLINE_MS", "float", None,
       "Default request deadline when X-LDT-Deadline-Ms is absent; "
       "expired work is dropped at dequeue (504).", bound=True,
       mutable=True, mrange=(1.0, 600_000.0)),
    _k("LDT_BROWNOUT_ALPHA", "float", 0.3,
       "EWMA smoothing factor for the brownout ladder's load signal.",
       mutable=True, mrange=(0.01, 1.0)),
    _k("LDT_BROWNOUT_ENTER", "levels", (0.60, 0.80, 0.95),
       "Comma-separated occupancy thresholds to ENTER brownout levels "
       "1..3."),
    _k("LDT_BROWNOUT_EXIT", "levels", (0.45, 0.65, 0.80),
       "Comma-separated occupancy thresholds to EXIT brownout levels "
       "1..3 (must sit below the enter thresholds: hysteresis)."),
    _k("LDT_BROWNOUT_P95_MS", "float", None,
       "Optional latency target: flush p95 over this feeds the "
       "brownout load signal.", bound=True),
    _k("LDT_BREAKER_FAILURES", "int", 5,
       "Consecutive device-flush failures that trip the circuit "
       "breaker open."),
    _k("LDT_BREAKER_COOLDOWN_SEC", "float", 10.0,
       "Seconds an open breaker waits before admitting a half-open "
       "probe."),
    _k("LDT_BREAKER_STALL_FACTOR", "float", 10.0,
       "A flush slower than factor x compile-aware expected p95 counts "
       "as a breaker failure (stall watchdog)."),
    _k("LDT_BREAKER_STALL_MIN_MS", "float", 2000.0,
       "Floor of the stall watchdog threshold in ms."),
    # -- fault injection & flush timeout (faults.py, both fronts) -----
    _k("LDT_FAULTS", "str", None,
       "Fault-injection spec, comma-separated "
       "`point:action[:p=][:seed=][:once][:after=]` rules "
       "(docs/ROBUSTNESS.md). Unset = injection disabled (seams cost "
       "one attribute check)."),
    _k("LDT_FLUSH_TIMEOUT_SEC", "float", 60.0,
       "How long a handler waits on its flush future before answering "
       "504 (both fronts; formerly a hardcoded 60 s)."),
    # -- supervisor crash policy (service/supervisor.py) --------------
    _k("LDT_RESTART_ON_CRASH", "bool", False,
       "Supervisor restarts a crashed worker (any exit other than 0 / "
       "recycle) with exponential backoff instead of propagating the "
       "first crash."),
    _k("LDT_CRASH_BACKOFF_BASE_SEC", "float", 0.5,
       "First crash-restart backoff; doubles per consecutive crash "
       "(x0.5-1.5 jitter)."),
    _k("LDT_CRASH_BACKOFF_MAX_SEC", "float", 30.0,
       "Ceiling of the crash-restart backoff."),
    _k("LDT_CRASH_LOOP_WINDOW_SEC", "float", 60.0,
       "Crash-loop window: this many seconds of crash history count "
       "toward the loop detector."),
    _k("LDT_CRASH_LOOP_MAX", "int", 5,
       "Crashes inside the window that declare a crash loop: the "
       "supervisor stops restarting and propagates the exit code."),
    _k("LDT_WORKER_GENERATION", "int", 0,
       "Set BY the supervisor on each spawned worker (1, 2, ...); "
       "exported as the ldt_worker_generation gauge. 0 = running "
       "unsupervised."),
    # -- fleet supervisor (service/fleet.py) --------------------------
    _k("LDT_FLEET_WORKERS", "int", 0,
       "Worker count for the fleet supervisor: N members share the "
       "listen port via SO_REUSEPORT, each with its own generation, "
       "ready handshake, and crash policy. 0/unset = classic "
       "single-worker supervisor."),
    _k("LDT_FLEET_MIN", "int", None,
       "Autoscale floor (defaults to LDT_FLEET_WORKERS): scale-down "
       "never drains below this many members.", bound=True),
    _k("LDT_FLEET_MAX", "int", None,
       "Autoscale ceiling (defaults to LDT_FLEET_WORKERS; equal "
       "min/max disables autoscaling).", bound=True),
    _k("LDT_FLEET_HEALTH_SEC", "float", 1.0,
       "Per-member health-scrape period: the fleet GETs each member's "
       "/debug/vars for readiness, queue depth, and brownout level."),
    _k("LDT_FLEET_DEGRADED_FAILS", "int", 3,
       "Consecutive failed health scrapes that mark a member DEGRADED "
       "(at 3x this the member is killed and respawned)."),
    _k("LDT_FLEET_SCALE_UP_DEPTH", "int", 64,
       "Sustained per-member admission queue depth (or brownout level "
       ">= 2) that scales the fleet up one member.",
       mutable=True, mrange=(1, 100_000)),
    _k("LDT_FLEET_SCALE_DOWN_DEPTH", "int", 0,
       "Queue depth at or below which (with no brownout) the fleet "
       "scales down one member via a zero-drop drain.",
       mutable=True, mrange=(0, 100_000)),
    _k("LDT_FLEET_SCALE_HOLD_SEC", "float", 10.0,
       "Hysteresis hold: the overload/idle condition must persist this "
       "long before one scale step fires (and the timer re-arms).",
       mutable=True, mrange=(0.1, 3600.0)),
    _k("LDT_FLEET_CIRCUIT_COOLDOWN_SEC", "float", 5.0,
       "Open fleet-circuit cooldown before one half-open probe member "
       "is spawned; its readiness closes the circuit."),
    _k("LDT_FLEET_STATUS_PORT", "int", 0,
       "Fleet control-plane HTTP port (127.0.0.1): GET /fleetz (JSON "
       "member table) and /metrics (the fleet series; see docs/OBSERVABILITY.md). 0 = off."),
    _k("LDT_FLEET_SLOT", "int", None,
       "Set BY the fleet supervisor on each member (0, 1, ...): its "
       "stable slot index, independent of generation numbers.",
       bound=True),
    # -- artifact & hot swap (supervisor + service/swap.py) -----------
    _k("LDT_ARTIFACT_PATH", "str", None,
       "Path to the .ldta scoring artifact to serve. Unset -> the "
       "packaged data/model.ldta. The supervisor rewrites it on each "
       "standby spawn during a swap drill."),
    _k("LDT_ARTIFACT_POINTER", "str", None,
       "Path to a one-line text file naming the current artifact. The "
       "supervisor re-reads it on every SIGHUP swap drill, so an "
       "operator retargets a deployment by rewriting the pointer and "
       "signaling."),
    _k("LDT_SWAP_TIMEOUT_SEC", "float", 30.0,
       "How long the supervisor holds a standby worker waiting for its "
       "ready file before aborting the swap and keeping the old "
       "generation serving."),
    _k("LDT_READY_FILE", "str", None,
       "Set BY the supervisor on a standby worker: the front writes "
       "this file (JSON: generation/pid/ports/warmup_ms) once /readyz "
       "is true, signaling the supervisor to cut traffic over."),
    _k("LDT_SWAPPED", "bool", False,
       "Set BY the supervisor on a standby worker spawned for a swap "
       "drill; the front counts ldt_swap_total{result=ok} once it "
       "becomes ready, so the drill is visible on the new generation's "
       "/metrics."),
    _k("LDT_REUSEPORT", "bool", False,
       "Bind both fronts' listeners with SO_REUSEPORT so an old and a "
       "standby generation can overlap on the same port during a "
       "blue/green swap. Required (on the supervisor env) for "
       "zero-downtime SIGHUP drills on a fixed port."),
    # -- wire fast path & unix-socket lane (service/wire.py) ----------
    _k("LDT_UNIX_SOCKET", "str", None,
       "Filesystem path for the unix-domain-socket ingest lane on "
       "both fronts (length-prefixed frames, wire.py contract). "
       "Co-located callers skip HTTP parsing entirely; responses are "
       "byte-identical to the TCP front. Unset: no UDS listener."),
    _k("LDT_WIRE_FASTPATH", "bool", True,
       "Use the zero-copy request scanner for the strict common "
       "request shape (wire.fast_parse_texts); any deviation falls "
       "back to json.loads either way. Set 0 to force the json.loads "
       "path (parity debugging)."),
    _k("LDT_FRAME_READ_TIMEOUT_SEC", "float", 5.0,
       "Slow-loris guard for the UDS frame lane on both fronts: once "
       "the first byte of a frame arrives, the rest of the header and "
       "body must land within this budget or the connection is "
       "answered with a 408 error frame and closed (idle keep-alive "
       "between frames stays unbounded). 0 = off.", bound=True),
    # -- shared-memory ring ingest lane (service/shmring.py) ----------
    _k("LDT_SHM_DIR", "str", None,
       "Directory of mmap'd shared-memory ring files: the worker "
       "scans it and serves frames written by co-located RingClient "
       "processes in place (zero-copy twin of the UDS lane; see "
       "docs/ROBUSTNESS.md for the lease/fencing protocol). Under the "
       "fleet supervisor each member gets its own m<slot>/ subdir. "
       "Unset: no shm lane."),
    _k("LDT_SHM_SLOTS", "int", 8,
       "Slots per ring a RingClient creates (max 63): the client's "
       "max in-flight frames on the shm lane."),
    _k("LDT_SHM_SLOT_BYTES", "int", 65536,
       "Payload capacity per ring slot in bytes (rounded up to the "
       "mmap allocation granularity so each slot maps page-aligned); "
       "bounds both request and response frame size on the shm lane."),
    _k("LDT_SHM_LEASE_TIMEOUT_SEC", "float", 2.0,
       "Crash-reclaim horizon for ring slots: a WRITING slot whose "
       "client died (or stalled) longer than this is reclaimed to "
       "FREE, and a DONE slot with a dead client is reclaimed after "
       "the same grace."),
    _k("LDT_SHM_SCAN_INTERVAL_MS", "float", 1.0,
       "Idle sleep of the shm scan thread between sweeps when no "
       "frame was handled; the worst-case added latency for a frame "
       "landing in an idle ring."),
    # -- startup warmup & compile cache (server.py, models/ngram.py) --
    _k("LDT_WARMUP", "bool", False,
       "Pre-compile the bucket ladder's jitted shapes at startup and "
       "gate /readyz on that warmup finishing; the duration lands in "
       "the ldt_warmup_ms gauge."),
    _k("LDT_COMPILE_CACHE_DIR", "str", None,
       "Directory for JAX's persistent compilation cache "
       "(jax_compilation_cache_dir), set at engine init so restarted "
       "or standby worker generations start warm. Created (with a "
       "structured log) if missing."),
    # -- AOT executable bundles (aot.py, models/ngram.py) -------------
    _k("LDT_AOT_DIR", "str", None,
       "Directory of AOT-exported bucket-ladder executables (aot.py): "
       "engine init and warmup try to deserialize each ladder tier's "
       "compiled scorer from here before compiling, and write back "
       "entries they had to compile. The supervisor/fleet default it "
       "to a shared per-supervisor dir so spawned and standby "
       "generations boot hot. Created (with a structured log) if "
       "missing. Unset under no supervisor = AOT off."),
    _k("LDT_AOT_REQUIRE", "bool", False,
       "Strict AOT mode: a missing, stale, or corrupt bundle entry "
       "raises AotError out of the dispatch instead of falling back "
       "to a fresh compile (deploy guard: a fleet that must boot hot "
       "fails loud when it cannot)."),
    _k("LDT_RESULT_CACHE_SHM_MB", "float", 0.0,
       "Budget in MB for the shm-backed fleet-shared result-cache "
       "tier (service/sharedcache.py): a fixed-slot open-addressed "
       "mmap table under LDT_SHM_DIR (or /dev/shm) that every "
       "SO_REUSEPORT fleet member reads and writes, so duplicate "
       "docs hit across workers. 0/unset disables the shared tier "
       "(the per-worker LRU is unaffected). The tier rides the "
       "per-worker ResultCache, so LDT_RESULT_CACHE_MB must also be "
       "> 0 for it to see any traffic."),
    _k("LDT_SHARED_CACHE_FILE", "str", None,
       "Explicit path of the fleet-shared result-cache mmap file. "
       "Unset = <LDT_SHM_DIR or /dev/shm>/ldt-shared-cache.bin; the "
       "fleet pins a per-fleet path here because its members get "
       "per-slot LDT_SHM_DIR values and must still share ONE table."),
    # -- device-pool scheduler (parallel/pool.py) ---------------------
    _k("LDT_POOL_LANES", "int", None,
       "Dispatch-lane count for the fault-tolerant device pool. On a "
       "mesh the devices partition into this many sub-meshes (one lane "
       "each); on CPU the lanes share the single scorer (simulated "
       "lanes for chaos tests). Unset/0 = no pool: dispatch takes "
       "exactly the direct single-lane path.", bound=True),
    _k("LDT_POOL_HEDGE_FACTOR", "float", 4.0,
       "Straggler hedge threshold: a fetch slower than factor x the "
       "lane's observed p95 latency re-dispatches the batch on another "
       "healthy lane (first result wins). 0 disables hedging."),
    _k("LDT_POOL_HEDGE_MIN_MS", "float", 500.0,
       "Floor of the hedge threshold in ms, so cold lanes with "
       "microsecond p95s don't hedge every warm launch."),
    _k("LDT_POOL_EVICT_FAILURES", "int", 3,
       "Consecutive fetch/dispatch failures that evict a lane from "
       "rotation (per-lane circuit breaker)."),
    _k("LDT_POOL_PROBE_COOLDOWN_SEC", "float", 5.0,
       "Seconds an evicted lane waits before it may carry a half-open "
       "probe batch; a successful probe re-admits the lane."),
    _k("LDT_POOL_MAX_REDISPATCH", "int", 8,
       "Failover budget per batch: how many lane attempts (initial + "
       "re-dispatches) before the error surfaces to the batch's "
       "futures."),
    # -- data-plane integrity (integrity.py) --------------------------
    _k("LDT_SCRUB_INTERVAL_SEC", "float", 0.0,
       "On-device table-scrub cadence for pooled engines: between "
       "flushes, each pool lane's device table planes fold to a "
       "digest on device and compare against the fingerprint recorded "
       "at upload; a mismatch (or a canary deviation) quarantines the "
       "lane CORRUPT, re-uploads fresh tables from the host mmap, and "
       "re-admits it through the half-open probe flow. 0 (default) "
       "disables scrubbing entirely — the epilogue hook is a single "
       "attribute test."),
    _k("LDT_CANARY_DOCS", "int", 8,
       "Golden-query canary pack size per scrub pass (first N of the "
       "pinned 8-doc pack, expected codes baked into model.ldta at "
       "pack time): each lane scores the pack and any code deviation "
       "quarantines the lane — catching compute faults a table digest "
       "can't see. 0 disables the canary (digest scrub still runs). "
       "Values past the pinned pack extend it with deterministic draws "
       "from the bundled eval corpus (evalsuite.py) and the gate "
       "becomes the LDT_CANARY_FLOOR agreement floor instead of "
       "exact-8 equality."),
    _k("LDT_CANARY_FLOOR", "float", 0.95,
       "Agreement floor for the statistical canary gate: when "
       "LDT_CANARY_DOCS extends past the pinned 8-doc pack, a scrub "
       "pass quarantines the lane when the fraction of canary docs "
       "matching their expected codes drops below this (the pinned "
       "core 8 still require exact equality — any deviation there is "
       "a quarantine regardless of the floor)."),
    _k("LDT_WIRE_CRC", "bool", False,
       "End-to-end frame payload CRC32 on the wire lanes: UDS v2 "
       "frames carry a CRC ext-flag + trailer word and shm slots "
       "carry a CRC header word; the server verifies before parsing "
       "and refuses a mismatched frame with a typed 400 instead of "
       "scoring flipped bytes (ldt_integrity_crc_total). Both sides "
       "of the shm lane must agree on this knob."),
    # -- scoring kernel (ops/kernels.py) ------------------------------
    _k("LDT_KERNEL", "str", "auto",
       "Scoring-kernel selection for the engine's device program: "
       "'pallas' (fused Pallas kernel — decode + tote + whack + top-2 "
       "+ reliability in one tiled program; TPU only, degrades to the "
       "fused XLA program elsewhere), 'fused' (the kernel's pure-XLA "
       "fallback: single vectorized reduction with quantized u8/i16 "
       "operands), 'xla' (the reference XLA program, ops/score.py), "
       "'lax' (jax.lax.scan reference path — debugging/parity oracle, "
       "not a serving mode), 'auto' (default: pallas on TPU, fused "
       "elsewhere). Every mode is bit-identical "
       "(tests/test_kernel_parity.py); the resolved mode and fallback "
       "reason surface in /debug/vars under pipeline."),
    _k("LDT_KERNEL_INTERPRET", "bool", False,
       "With LDT_KERNEL=pallas on a non-TPU backend, run the Pallas "
       "kernel body under the Pallas interpreter instead of degrading "
       "to the fused XLA program. Orders of magnitude slower than any "
       "compiled mode — parity tests and kernel debugging only."),
    # -- dispatch pipeline & long-doc lane (models/ngram.py) ----------
    _k("LDT_PIPELINE_DEPTH", "int", 2,
       "Dispatch-pipeline depth: max scheduler jobs in flight on the "
       "device at once while later batches pack on the host. 1 = "
       "strictly serial pack->score->epilogue (byte-identical "
       "reference path); 2 (default) keeps one batch scoring while the "
       "next packs, with one extra overlapped retry-lane launch."),
    _k("LDT_LONGDOC_CHUNK_SLOTS", "int", 1024,
       "Long-document lane sub-pack size: split documents are cut at "
       "script-span boundaries into sub-packs of about this many "
       "slots, scored as ordinary bucket-ladder work, and merged back "
       "into one summary. 0 disables the lane entirely (oversized "
       "docs ride the widest tier unsplit)."),
    _k("LDT_LONGDOC_SPLIT_SLOTS", "int", 4096,
       "Long-document lane engage threshold: only documents whose "
       "estimated packer slot demand exceeds this enter the span-split "
       "lane (clamped up to LDT_LONGDOC_CHUNK_SLOTS). Splitting costs "
       "a host span scan and a chunk merge, and a doc that fails the "
       "reliability gate re-scores whole regardless, so the lane takes "
       "only the fat tail where bucket-shape inflation actually "
       "bites."),
    # -- accuracy plane (evalsuite.py, models/ngram.py, both fronts) --
    _k("LDT_SPANS", "bool", False,
       "Per-span language output: detector results carry a spans list "
       "[(byte_offset, byte_len, code, pct, reliable)] tiling the "
       "document (script-span-aligned, engine detect_spans), the HTTP "
       "front adds a per-item \"spans\" JSON field, and UDS v2 frames "
       "honor the FRAME_SPANS ext flag. Off (default): responses and "
       "every device program are byte-identical to the pre-span "
       "stack."),
    _k("LDT_HINTS", "bool", False,
       "Hint priors in the device reduction: hinted batches carry "
       "per-doc dense prior vectors (hints.prior_vector — the boost "
       "algebra's qprob deltas) that the scorer adds to languages a "
       "chunk already observed, post-whack and before the top-2 "
       "select, in every kernel mode. Bit-exact to the scalar-oracle "
       "extension (tests/test_hints_parity.py); off (default) the "
       "wire carries no prior keys and hint-off results stay "
       "byte-identical."),
    # -- per-tenant isolation (service/admission.py) ------------------
    _k("LDT_TENANT_QUOTA_DOCS", "int", None,
       "Per-tenant cap on queued documents (X-LDT-Tenant header; "
       "absent header = tenant \"default\"); over it the tenant sheds "
       "429 tenant_docs while other tenants keep admitting.",
       bound=True, mutable=True, mrange=(1, 1_000_000)),
    _k("LDT_TENANT_QUOTA_BYTES", "int", None,
       "Per-tenant cap on queued byte-weighted cost (same accounting "
       "as LDT_MAX_QUEUE_BYTES); over it the tenant sheds 429 "
       "tenant_bytes.", bound=True, mutable=True, mrange=(1, 1 << 31)),
    _k("LDT_TENANT_WEIGHTS", "str", None,
       "Deficit-weighted fair queueing weights as "
       "\"tenantA=4,tenantB=1\" (unlisted tenants weigh 1). Setting it "
       "turns on DRR dequeue in both fronts' batchers; unset keeps "
       "strict FIFO."),
    _k("LDT_WFQ_QUANTUM_BYTES", "int", 65536,
       "DRR quantum: bytes of queued cost a weight-1 tenant may "
       "dequeue per scheduler round."),
    # -- flight recorder & device profiling (flightrec.py) ------------
    _k("LDT_FLIGHTREC_DIR", "str", None,
       "Directory for the crash-safe flight recorder: each process "
       "writes flightrec-<pid>.ring there (mmap'd bounded event ring, "
       "readable after SIGKILL; see docs/OBSERVABILITY.md). The fleet "
       "supervisor harvests a dead member's ring into a postmortem on "
       "/fleetz. Unset: recorder off, every emit is one None check."),
    _k("LDT_FLIGHTREC_SLOTS", "int", 256,
       "Event slots per flight-recorder ring (newest events win; the "
       "total committed count survives eviction)."),
    _k("LDT_FLIGHTREC_SLOT_BYTES", "int", 512,
       "Bytes per flight-recorder slot including the 16-byte header; "
       "an event whose JSON payload exceeds the slot is dropped and "
       "counted in ldt_flightrec_dropped_total."),
    _k("LDT_PROFILE_DIR", "str", None,
       "Output directory for on-demand device-profiler captures "
       "(POST /profilez or SIGUSR2 arms jax.profiler for a bounded "
       "window). Unset: /profilez answers 503 profiling_disabled."),
    _k("LDT_PROFILE_WINDOW_SEC", "float", 5.0,
       "Capture window for an on-demand profile: the trace stops "
       "itself this many seconds after it was armed.", bound=True),
    # -- traffic capture & SLO engine (capture.py, slo.py) ------------
    _k("LDT_CAPTURE_DIR", "str", None,
       "Directory for the traffic-capture plane: each front writes "
       "one fixed-width anonymized record per completed request into "
       "capture-<pid>.ring (mmap'd, commit-word-published, readable "
       "after SIGKILL), sealing full rings into segment-*.cap files. "
       "The fleet gives each member its own m<slot>/ subdirectory. "
       "bench.py --replay re-drives a capture; see "
       "docs/OBSERVABILITY.md. Unset: capture off, zero-cost."),
    _k("LDT_CAPTURE_SAMPLE", "float", 1.0,
       "Fraction of completed requests recorded by the capture plane "
       "(probabilistic per-request sampling; 1.0 keeps everything, "
       "0.01 keeps ~1%)."),
    _k("LDT_CAPTURE_RING_RECORDS", "int", 4096,
       "Records per capture ring before it is sealed into an "
       "immutable segment file and restarted."),
    _k("LDT_CAPTURE_MAX_SEGMENTS", "int", 64,
       "Sealed capture segments kept per writer; the oldest are "
       "unlinked first, bounding on-disk capture size."),
    _k("LDT_SLO", "str", None,
       "SLO spec armed at front startup, e.g. "
       "'p99_ms=50,err_pct=0.5,window_sec=300': latency-percentile "
       "target, error-budget percentage, and fast-window seconds "
       "(the slow window is 12x). Drives per-tenant + fleet SLIs, "
       "multi-window burn rates, /sloz, and slo_breach / "
       "slo_recovered flight-recorder events. Unset: SLO engine off."),
    _k("LDT_SLO_MIN_EVENTS", "int", 4,
       "Minimum fast-window events before a burn-rate breach may "
       "fire; suppresses alerts on near-idle traffic."),
    # -- runtime config plane (configplane.py) ------------------------
    _k("LDT_CONFIG_PROBATION_SEC", "float", 10.0,
       "Default probation window for a POST /configz apply: the new "
       "config serves under SLO watch this long; a fast-window burn "
       "rate >= 1.0 inside the window auto-rolls the apply back "
       "(configplane.py). A per-request probation_sec overrides it; "
       "0 commits immediately (the fleet's fan-out of an already-"
       "proven config)."),
    # -- debug / CI ---------------------------------------------------
    _k("LDT_LOCK_DEBUG", "bool", False,
       "Build order-checking debug locks (language_detector_tpu/locks)"
       ": records lock acquisition order and raises on inversion or "
       "self-deadlock. CI runs the whole test suite with it on."),
    # -- service ports (reference contract, main.go:91-116) -----------
    _k("LISTEN_PORT", "int", 3000,
       "HTTP service port for both fronts."),
    _k("PROMETHEUS_PORT", "int", 30000,
       "Metrics/debug HTTP port for both fronts."),
    # -- multi-host launch contract (parallel/distributed.py) ---------
    _k("JAX_COORDINATOR_ADDRESS", "str", None,
       "jax.distributed coordinator address, as set by TPU pod "
       "launchers.", external=True),
    _k("JAX_NUM_PROCESSES", "int", None,
       "Total process count for jax.distributed.", external=True),
    _k("JAX_PROCESS_ID", "int", None,
       "This process's index for jax.distributed.", external=True),
    _k("TPU_WORKER_HOSTNAMES", "str", "",
       "TPU runtime worker list; more than one entry implies a "
       "multi-host slice.", external=True),
)

KNOBS: dict[str, Knob] = {k.name: k for k in _DECLARATIONS}

# Runtime overrides for MUTABLE knobs, applied by the config plane
# (configplane.apply -> apply_overrides). Stored as raw env-style
# strings so every override rides the exact same parse / bound
# semantics as an environment value. _VERSION bumps on every change so
# components that cache derived state (AdmissionController) can detect
# staleness with one int compare per call.
_OVERRIDES: dict[str, str] = {}
_VERSION: int = 0


def raw(name: str) -> str | None:
    """The registry's single environment touch: the raw string value of
    a DECLARED knob, or None when unset. Reading an undeclared name is
    a programming error (declare it above). Mutable knobs consult the
    runtime override map first, so an applied /configz change is live
    for every accessor without re-exec."""
    knob = KNOBS.get(name)
    if knob is None:
        raise KeyError(f"undeclared env knob {name!r}; declare it in "
                       "language_detector_tpu/knobs.py")
    if knob.mutable and name in _OVERRIDES:
        return _OVERRIDES[name]
    return os.environ.get(name)


def is_set(name: str) -> bool:
    """True when the knob has a non-blank value in the environment."""
    v = raw(name)
    return v is not None and v != ""


def _parse_scalar(knob: Knob, v: str) -> object:
    if knob.ktype == "int":
        # accept "8e3"-style floats the way the old per-file parsers
        # accepted them for byte/MB counts
        return int(v) if v.lstrip("+-").isdigit() else int(float(v))
    if knob.ktype == "float":
        return float(v)
    return v


def value(name: str) -> object:
    """Typed value of a declared knob, applying the shared default /
    mistype / bound semantics. Prefer the typed get_* accessors at call
    sites."""
    knob = KNOBS[name]
    v = raw(name)
    if knob.ktype == "bool":
        if v is None:
            return bool(knob.default)
        return v.strip().lower() not in _FALSE_WORDS
    if v in (None, ""):
        return knob.default
    if knob.ktype == "levels":
        try:
            parts = tuple(float(x) for x in v.split(","))
        except ValueError:
            parts = ()
        if len(parts) != len(knob.default):  # type: ignore[arg-type]
            _log.warning(
                "%s=%r must be %d comma-separated numbers — using %r",
                name, v, len(knob.default),  # type: ignore[arg-type]
                knob.default)
            return knob.default
        return parts
    if knob.ktype == "str":
        return v
    try:
        n = _parse_scalar(knob, v)
    except ValueError:
        _log.warning("%s=%r is not a valid %s — using default %r",
                     name, v, knob.ktype, knob.default)
        return knob.default
    if knob.bound and n <= 0:  # type: ignore[operator]
        return None  # non-positive bound = feature off
    return n


def get_int(name: str) -> int | None:
    knob = KNOBS[name]
    assert knob.ktype == "int", f"{name} is {knob.ktype}, not int"
    v = value(name)
    return None if v is None else int(v)  # type: ignore[call-overload]


def get_float(name: str) -> float | None:
    knob = KNOBS[name]
    assert knob.ktype == "float", f"{name} is {knob.ktype}, not float"
    v = value(name)
    return None if v is None else float(v)  # type: ignore[arg-type]


def get_str(name: str) -> str | None:
    knob = KNOBS[name]
    assert knob.ktype == "str", f"{name} is {knob.ktype}, not str"
    v = value(name)
    return None if v is None else str(v)


def get_bool(name: str) -> bool:
    knob = KNOBS[name]
    assert knob.ktype == "bool", f"{name} is {knob.ktype}, not bool"
    return bool(value(name))


def get_levels(name: str) -> tuple[float, ...]:
    knob = KNOBS[name]
    assert knob.ktype == "levels", f"{name} is {knob.ktype}, not levels"
    v = value(name)
    assert isinstance(v, tuple)
    return v


def mutable_knobs() -> tuple[Knob, ...]:
    """Every knob declared runtime-settable, in declaration order —
    the config plane's settable surface and the autotuner's search
    space."""
    return tuple(k for k in _DECLARATIONS if k.mutable)


def overrides_version() -> int:
    """Monotonic version of the runtime-override state; bumps on every
    apply_overrides / clear_overrides so callers can cache derived
    config behind one int compare."""
    return _VERSION


def current() -> dict:
    """Versioned snapshot of the mutable-knob surface: the effective
    (env + overrides, fully parsed) value of every mutable knob, the
    raw override map, and the override version. Components that must
    see /configz changes read through this (or the typed accessors,
    which consult the same override map) — never an import-time
    cache."""
    return {
        "version": _VERSION,
        "values": {k.name: value(k.name) for k in mutable_knobs()},
        "overrides": dict(_OVERRIDES),
    }


def _validate_override(name: str, rawv: str) -> str | None:
    """Error string when `rawv` is not a legal runtime value for the
    mutable knob `name`, else None. Validation is the same parse the
    environment gets, plus the declared mrange — an apply must refuse
    loudly where an env mistype merely warns-and-defaults."""
    knob = KNOBS.get(name)
    if knob is None:
        return f"undeclared knob {name!r}"
    if not knob.mutable:
        return f"{name} is not mutable"
    if knob.ktype not in ("int", "float"):
        return f"{name}: mutable {knob.ktype} knobs are unsupported"
    try:
        n = _parse_scalar(knob, rawv)
    except ValueError:
        return f"{name}={rawv!r} is not a valid {knob.ktype}"
    if knob.bound and n <= 0:  # type: ignore[operator]
        return None  # non-positive bound = "feature off": always legal
    if knob.mrange is not None:
        lo, hi = knob.mrange
        if not (lo <= n <= hi):  # type: ignore[operator]
            return (f"{name}={rawv} outside declared range "
                    f"[{lo}, {hi}]")
    return None


def apply_overrides(updates: dict) -> dict:
    """Validate and apply a runtime override batch atomically: every
    entry must pass the type/bound/mrange contract or the whole batch
    is refused with ValueError (no partial applies). A None value
    removes that knob's override (reverts to the environment). Returns
    the post-apply current() snapshot."""
    global _VERSION
    staged: dict[str, str | None] = {}
    errors: list[str] = []
    for name, v in updates.items():
        if v is None:
            knob = KNOBS.get(name)
            if knob is None or not knob.mutable:
                errors.append(f"{name} is not a mutable knob")
            else:
                staged[name] = None
            continue
        rawv = str(v)
        err = _validate_override(name, rawv)
        if err is not None:
            errors.append(err)
        else:
            staged[name] = rawv
    if errors:
        raise ValueError("; ".join(errors))
    for name, rawv in staged.items():
        if rawv is None:
            _OVERRIDES.pop(name, None)
        else:
            _OVERRIDES[name] = rawv
    _VERSION += 1
    return current()


def clear_overrides() -> None:
    """Drop every runtime override (rollback to pure-environment
    config). Bumps the version so cached derived state refreshes."""
    global _VERSION
    _OVERRIDES.clear()
    _VERSION += 1


def doc_table() -> str:
    """Markdown table of every declared knob, written into
    docs/OBSERVABILITY.md between the ldt-knob-table markers by
    `python -m tools.lint --write-knob-docs` and drift-checked by the
    knob-registry analyzer."""
    rows = ["| Knob | Type | Default | Mutable | Meaning |",
            "| --- | --- | --- | --- | --- |"]
    for knob in _DECLARATIONS:
        if knob.default is None:
            dflt = "off" if knob.bound else "unset"
        elif knob.ktype == "levels":
            dflt = ",".join(f"{x:g}" for x in knob.default)  # type: ignore[attr-defined]
        elif knob.default == "":
            dflt = "(empty)"
        else:
            dflt = f"{knob.default}"
        if knob.mutable and knob.mrange is not None:
            lo, hi = knob.mrange
            mut = f"yes [{lo:g}, {hi:g}]"
        elif knob.mutable:
            mut = "yes"
        else:
            mut = ""
        doc = knob.doc
        if knob.external:
            doc += " (platform contract variable)"
        rows.append(f"| `{knob.name}` | {knob.ktype} | {dflt} | {mut} "
                    f"| {doc} |")
    return "\n".join(rows)
