"""Fleet-shared result cache tests (round 16, service/sharedcache.py).

The shared tier's whole contract is "can lose entries, can never serve
a wrong or stale one" — so besides the happy path this file drives the
chaos cases: a writer killed mid-slot (odd seqlock word), a torn/
corrupt payload, an artifact-epoch roll mid-traffic, and displacement
eviction adopting dead slots. Plus the per-worker ResultCache
integration: L2 write-through/promote and the single-flight claim/
resolve protocol that collapses duplicate dispatches.
"""
import os
import struct
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from language_detector_tpu.service import sharedcache as sc
from language_detector_tpu.service.batcher import _MISS, ResultCache

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def cache_path(tmp_path):
    return str(tmp_path / "shared.bin")


def _cache(path, mb=1.0):
    return sc.SharedResultCache(path, int(mb * 1024 * 1024))


def _slot_off(cache, key, probe=0):
    kh = sc._key_hash(key)
    base = int.from_bytes(kh[:8], "little") % cache.slot_count
    return cache._off((base + probe) % cache.slot_count)


# -- basic protocol ----------------------------------------------------------


def test_put_get_roundtrip(cache_path):
    c = _cache(cache_path)
    c.set_epoch("digest-1")
    key = (None, "bonjour tout le monde")
    assert c.get(key) is None
    c.put(key, "fr")
    assert c.get(key) == "fr"
    st = c.stats()
    assert st["hits"] == 1 and st["misses"] == 1
    c.close()


def test_two_attached_views_share_entries(cache_path):
    a, b = _cache(cache_path), _cache(cache_path)
    a.set_epoch("d1")
    b.set_epoch("d1")
    a.put((None, "hola"), "es")
    assert b.get((None, "hola")) == "es"
    # geometry comes from the header, not the attacher's knob
    c = _cache(cache_path, mb=4.0)
    assert c.slot_count == a.slot_count
    for x in (a, b, c):
        x.close()


def test_incompatible_layout_refused(cache_path):
    c = _cache(cache_path)
    c.close()
    with open(cache_path, "r+b") as f:
        f.write(sc._HEADER.pack(sc.MAGIC, sc.VERSION + 9,
                                c.slot_count, sc.SLOT_BYTES))
    with pytest.raises(RuntimeError, match="incompatible layout"):
        _cache(cache_path)


def test_oversized_value_never_published(cache_path):
    c = _cache(cache_path)
    c.set_epoch("d1")
    c.put((None, "big"), "x" * (sc.PAYLOAD_CAP + 1))
    assert c.get((None, "big")) is None
    c.close()


# -- cross-process -----------------------------------------------------------


def _child(path, body):
    code = ("import sys, os, struct\n"
            "from language_detector_tpu.service import sharedcache as sc\n"
            f"c = sc.SharedResultCache(sys.argv[1], 1 << 20)\n"
            f"c.set_epoch('E1')\n" + body)
    return subprocess.run([sys.executable, "-c", code, path], cwd=REPO,
                          env=dict(os.environ, JAX_PLATFORMS="cpu"),
                          capture_output=True, text=True, timeout=120)


def test_cross_process_hits(cache_path):
    r = _child(cache_path,
               "for i in range(20):\n"
               "    c.put((None, f'doc-{i}'), 'fr')\n")
    assert r.returncode == 0, r.stderr
    c = _cache(cache_path)
    c.set_epoch("E1")
    assert all(c.get((None, f"doc-{i}")) == "fr" for i in range(20))
    c.close()


def test_writer_killed_mid_slot_never_serves_and_stays_live(cache_path):
    # the child claims a slot (seq -> odd) and dies there: exactly what
    # a SIGKILL between the claim and the publish leaves behind
    r = _child(cache_path,
               "key = (None, 'victim-doc')\n"
               "kh = sc._key_hash(key)\n"
               "base = int.from_bytes(kh[:8], 'little') % c.slot_count\n"
               "off = c._off(base)\n"
               "struct.pack_into('<I', c._mm, off, c._seq(off) + 1)\n"
               "os._exit(9)\n")
    assert r.returncode == 9
    c = _cache(cache_path)
    c.set_epoch("E1")
    key = (None, "victim-doc")
    off = _slot_off(c, key)
    assert c._seq(off) & 1, "child should have left an odd seq behind"
    # the dead slot reads as a miss, never garbage
    assert c.get(key) is None
    # ...and the table stays writable: put() probes past the dead slot
    c.put(key, "de")
    assert c.get(key) == "de"
    c.close()


# -- chaos: torn entries, dead-slot adoption, eviction -----------------------


def test_torn_payload_refused_by_crc(cache_path):
    c = _cache(cache_path)
    c.set_epoch("d1")
    key = (None, "torn-doc")
    c.put(key, "ru")
    # find the published slot and flip one payload byte under it
    for i in range(sc.PROBE_WINDOW):
        off = _slot_off(c, key, probe=i)
        _, _, _, skey, vlen, _ = sc._SLOT_HDR.unpack_from(c._mm, off)
        if skey == sc._key_hash(key) and vlen:
            p = off + sc.SLOT_HDR_BYTES
            c._mm[p] ^= 0x40
            break
    else:
        pytest.fail("published slot not found in the probe window")
    assert c.get(key) is None  # CRC refuses; a miss, not a wrong answer
    c.close()


def test_displacement_adopts_dead_slots(cache_path):
    c = _cache(cache_path)
    c.set_epoch("d1")
    key = (None, "heal-me")
    # leave every slot in the key's probe window with a dead writer
    for i in range(sc.PROBE_WINDOW):
        off = _slot_off(c, key, probe=i)
        s = c._seq(off)
        if not s & 1:
            struct.pack_into("<I", c._mm, off, s + 1)
    assert c.get(key) is None
    # the displacement path adopts the odd seq as its claim: the slot
    # heals on this overwrite instead of leaking forever
    c.put(key, "ja")
    assert c.get(key) == "ja"
    victim = _slot_off(c, key, probe=sc._key_hash(key)[8]
                       % sc.PROBE_WINDOW)
    assert not c._seq(victim) & 1
    c.close()


def test_eviction_on_full_window(tmp_path):
    # tiny table (minimum geometry = one probe window) so distinct keys
    # must displace each other
    c = sc.SharedResultCache(str(tmp_path / "tiny.bin"), 0)
    assert c.slot_count == sc.PROBE_WINDOW
    c.set_epoch("d1")
    for i in range(4 * sc.PROBE_WINDOW):
        c.put((None, f"k-{i}"), "en")
    assert c.stats()["evictions"] > 0
    # displaced or not, reads stay coherent: every hit is a real value
    alive = sum(1 for i in range(4 * sc.PROBE_WINDOW)
                if c.get((None, f"k-{i}")) == "en")
    assert 0 < alive <= sc.PROBE_WINDOW
    c.close()


# -- epoch discipline --------------------------------------------------------


def test_epoch_roll_flushes_and_refuses_stale(cache_path):
    a, b = _cache(cache_path), _cache(cache_path)
    a.set_epoch("digest-old")
    b.set_epoch("digest-old")
    for i in range(10):
        a.put((None, f"doc-{i}"), "fr")
    assert b.get((None, "doc-0")) == "fr"
    # one member swaps to a new artifact: its reads refuse instantly
    # and the sweep frees the old generation's slots
    b.set_epoch("digest-new")
    assert b.get((None, "doc-0")) is None
    assert b.stats()["epoch_flushes"] >= 10
    # the not-yet-swapped member now misses too (entries are gone) but
    # never sees a value from the wrong generation
    assert a.get((None, "doc-0")) is None
    # re-rolling to the same epoch is a no-op
    before = b.stats()["epoch_flushes"]
    b.set_epoch("digest-new")
    assert b.stats()["epoch_flushes"] == before
    for x in (a, b):
        x.close()


def test_put_under_new_epoch_reclaims_stale_slots(cache_path):
    c = _cache(cache_path)
    c.set_epoch("e1")
    key = (None, "reused")
    c.put(key, "fr")
    c2 = _cache(cache_path)  # fresh view still on the default epoch
    c2.set_epoch("e2")
    c2.put(key, "de")
    assert c2.get(key) == "de"
    assert c.get(key) is None  # e1 view refuses the e2 entry
    for x in (c, c2):
        x.close()


# -- ResultCache integration: L2 + single-flight -----------------------------


def test_result_cache_writes_through_and_promotes(cache_path):
    shared = _cache(cache_path)
    a = ResultCache(1 << 20, shared=shared)
    b = ResultCache(1 << 20, shared=shared)
    a.set_epoch("d1")
    b.set_epoch("d1")
    key = (None, "hola amigos")
    a.put(key, "es", key[-1])
    # b's L1 is empty: the hit comes from the shared tier and promotes
    assert b.get(key) == "es"
    assert b.stats()["hits"] == 1
    assert b.get(key) == "es"  # second read answers from L1
    assert shared.stats()["hits"] == 1  # the shm tier was probed once
    shared.close()


def test_result_cache_rich_values_stay_private(cache_path):
    shared = _cache(cache_path)
    a = ResultCache(1 << 20, shared=shared)
    b = ResultCache(1 << 20, shared=shared)
    a.set_epoch("d1")
    b.set_epoch("d1")
    key = (None, "rich result")
    a.put(key, {"lang": "en", "scores": [1, 2, 3]}, key[-1])
    assert a.get(key) == {"lang": "en", "scores": [1, 2, 3]}
    # only code-string production values travel through the shm slots
    assert b.get(key) is _MISS
    shared.close()


def test_result_cache_epoch_forwarded_to_shared(cache_path):
    shared = _cache(cache_path)
    a = ResultCache(1 << 20, shared=shared)
    a.set_epoch("d1")
    a.put((None, "x"), "en", "x")
    a.set_epoch("d2")
    assert a.get((None, "x")) is _MISS
    assert shared.stats()["epoch_flushes"] >= 1
    shared.close()


def test_single_flight_claim_resolve():
    cache = ResultCache(1 << 20)
    key = (None, "dup doc")
    assert cache.claim(key) is None  # first claimer owns the key
    ev = cache.claim(key)
    assert isinstance(ev, threading.Event) and not ev.is_set()
    cache.resolve(key)
    assert ev.is_set()
    # resolved: the key is claimable again
    assert cache.claim(key) is None
    cache.resolve(key)


def test_single_flight_epoch_roll_wakes_waiters():
    cache = ResultCache(1 << 20)
    key = (None, "swapped away")
    assert cache.claim(key) is None
    ev = cache.claim(key)
    cache.set_epoch("new-digest")
    assert ev.is_set()  # waiters re-probe and dispatch themselves
    # the old owner's late resolve is a harmless no-op
    cache.resolve(key)
    assert cache.claim(key) is None


def test_single_flight_collapses_concurrent_fills():
    import time
    cache = ResultCache(1 << 20)
    key = (None, "hot doc")
    assert cache.claim(key) is None  # main thread is the slow owner
    results = []

    def waiter():
        v = cache.get(key)
        if v is _MISS:
            ev = cache.claim(key)
            assert ev is not None  # the owner still holds the key
            assert ev.wait(5.0)
            v = cache.get(key)
        results.append(v)

    threads = [threading.Thread(target=waiter) for _ in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # let the stampede park on the event
    cache.put(key, "en", key[-1])
    cache.resolve(key)
    for t in threads:
        t.join()
    assert results == ["en"] * 8
