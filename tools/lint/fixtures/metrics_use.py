"""Fixture: metric emission sites for the metric-registry analyzer."""


def emit(metrics):
    metrics.counter_inc("ldt_fix_used_total", 1)
    metrics.counter_inc("ldt_fix_undoc_total", 1)
    metrics.counter_inc("ldt_fix_rogue_total", 1)  # never declared
