"""Disk-full (ENOSPC) hardening for the write-to-disk planes: the
capture ring seal, the flight-recorder ring create, and AOT bundle
export. Contract: the plane disables itself (sticky), emits a
structured log and an ldt_*_disabled_total{reason="enospc"} counter,
and the service keeps serving.
"""
from __future__ import annotations

import errno

import pytest

from language_detector_tpu import aot, capture, flightrec, telemetry

ENOSPC = OSError(errno.ENOSPC, "No space left on device")


@pytest.fixture(autouse=True)
def _clean_planes():
    capture.reset_for_tests()
    saved = flightrec.RECORDER
    flightrec.RECORDER = None
    yield
    capture.reset_for_tests()
    if flightrec.RECORDER is not None:
        flightrec.RECORDER.close()
    flightrec.RECORDER = saved


def _fill_ring(w):
    rec = (0, 0, 0, 1, 0.0, 1.0, 0.1, 0.2, 0.3, 200, 8, 0, 0, 0)
    for _ in range(w.ring_records + 1):  # +1 forces the seal
        w.append(rec)
    return rec


# -- capture ring seal --------------------------------------------------------


def test_capture_seal_enospc_flags_writer(tmp_path, monkeypatch):
    w = capture.CaptureWriter(str(tmp_path), ring_records=16)
    monkeypatch.setattr(capture.os, "replace",
                        lambda *a: (_ for _ in ()).throw(ENOSPC))
    _fill_ring(w)
    assert w.disabled_reason == "enospc"
    w.close()


def test_capture_seal_other_oserror_keeps_plane(tmp_path, monkeypatch):
    w = capture.CaptureWriter(str(tmp_path), ring_records=16)
    monkeypatch.setattr(
        capture.os, "replace",
        lambda *a: (_ for _ in ()).throw(OSError(errno.EACCES, "no")))
    _fill_ring(w)
    # transient failure: segment dropped, plane stays armed
    assert w.disabled_reason is None
    w.close()


def test_capture_observe_retires_flagged_writer(tmp_path, monkeypatch):
    """The sticky disable: observe() unbinds the module writer, counts
    the disable once, and later observes are one-attribute-check
    no-ops — serving continues."""
    monkeypatch.setenv("LDT_CAPTURE_DIR", str(tmp_path))
    w = capture.init_from_env()
    assert w is not None
    before = telemetry.REGISTRY.counter_value(
        "ldt_capture_disabled_total", reason="enospc")
    monkeypatch.setattr(capture.os, "replace",
                        lambda *a: (_ for _ in ()).throw(ENOSPC))

    class _Trace:
        t0 = 0.0
        tenant = "t"
        deadline = None

        def span_ms(self, _name):
            return 0.0

    tr = _Trace()
    for _ in range(w.ring_records + 2):
        capture.observe(tr, {"status": 200, "docs": 1}, 1.0)
    assert capture.WRITER is None
    after = telemetry.REGISTRY.counter_value(
        "ldt_capture_disabled_total", reason="enospc")
    assert after == before + 1
    capture.observe(tr, {"status": 200, "docs": 1}, 1.0)  # no-op, no raise
    assert telemetry.REGISTRY.counter_value(
        "ldt_capture_disabled_total", reason="enospc") == after


def test_capture_init_enospc_counts(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_CAPTURE_DIR", str(tmp_path / "sub"))
    before = telemetry.REGISTRY.counter_value(
        "ldt_capture_disabled_total", reason="enospc")
    monkeypatch.setattr(
        capture, "CaptureWriter",
        lambda *a, **k: (_ for _ in ()).throw(ENOSPC))
    assert capture.init_from_env() is None
    assert telemetry.REGISTRY.counter_value(
        "ldt_capture_disabled_total", reason="enospc") == before + 1


# -- flight recorder ----------------------------------------------------------


def test_flightrec_init_enospc_counts(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_FLIGHTREC_DIR", str(tmp_path))
    before = telemetry.REGISTRY.counter_value(
        "ldt_flightrec_disabled_total", reason="enospc")
    monkeypatch.setattr(
        flightrec, "FlightRecorder",
        lambda *a, **k: (_ for _ in ()).throw(ENOSPC))
    assert flightrec.init_from_env(role="test") is None
    assert flightrec.RECORDER is None
    assert telemetry.REGISTRY.counter_value(
        "ldt_flightrec_disabled_total", reason="enospc") == before + 1
    # the event path stays a safe no-op
    assert flightrec.emit_event("proc_start", role="test",
                                generation=0) is False


def test_flightrec_init_other_oserror_reason(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_FLIGHTREC_DIR", str(tmp_path))
    before = telemetry.REGISTRY.counter_value(
        "ldt_flightrec_disabled_total", reason="oserror")
    monkeypatch.setattr(
        flightrec, "FlightRecorder",
        lambda *a, **k: (_ for _ in ()).throw(
            OSError(errno.EACCES, "no")))
    assert flightrec.init_from_env(role="test") is None
    assert telemetry.REGISTRY.counter_value(
        "ldt_flightrec_disabled_total", reason="oserror") == before + 1


# -- aot export ---------------------------------------------------------------


def _store(tmp_path):
    return aot.AotStore(str(tmp_path), digest="d" * 16,
                        backend="cpu", kernel_mode="vector",
                        require=False)


class _RaisingJit:
    """Stand-in jit_fn whose lowering fails the way a full disk fails
    an export (the compile-cache write is the first thing to touch the
    filesystem on this path)."""

    def __init__(self, exc):
        self.exc = exc

    def lower(self, *a, **k):
        raise self.exc


_WIRE = {"x": aot._SpecView((4,), "float32")}


def test_aot_export_enospc_sticky_disable(tmp_path):
    store = _store(tmp_path)
    before = telemetry.REGISTRY.counter_value(
        "ldt_aot_disabled_total", reason="enospc")
    assert store.offer(_WIRE, jit_fn=_RaisingJit(ENOSPC),
                       dt=None) is False
    assert store.export_disabled is True
    assert telemetry.REGISTRY.counter_value(
        "ldt_aot_disabled_total", reason="enospc") == before + 1
    assert store.stats()["export_disabled"] is True
    # sticky: the next offer is refused before any compile work
    assert store.offer(_WIRE, jit_fn=_RaisingJit(ENOSPC),
                       dt=None) is False
    assert telemetry.REGISTRY.counter_value(
        "ldt_aot_disabled_total", reason="enospc") == before + 1


def test_aot_export_other_failure_not_sticky(tmp_path):
    store = _store(tmp_path)
    assert store.offer(_WIRE, jit_fn=_RaisingJit(RuntimeError("boom")),
                       dt=None) is False
    assert store.export_disabled is False


def test_aot_build_from_env_enospc_counts(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_AOT_DIR", str(tmp_path / "missing"))
    before = telemetry.REGISTRY.counter_value(
        "ldt_aot_disabled_total", reason="enospc")
    monkeypatch.setattr(aot.os, "makedirs",
                        lambda *a, **k: (_ for _ in ()).throw(ENOSPC))
    assert aot.build_from_env("vector", dt=None) is None
    assert telemetry.REGISTRY.counter_value(
        "ldt_aot_disabled_total", reason="enospc") == before + 1
