from . import distributed
from .mesh import batch_mesh, sharded_score_fn

__all__ = ["batch_mesh", "sharded_score_fn", "distributed"]
