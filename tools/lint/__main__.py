"""CLI: python -m tools.lint [--rule r1,r2] [--changed]
[--knob-table] [--write-knob-docs] [--layout-table]
[--write-layout-docs]

Default run executes all thirteen analyzers over the live tree and
exits non-zero on any violation — ci.sh runs exactly this before the
test suite. ``--changed`` is the editor-loop mode: analyzers scope to
the files git reports as modified (unstaged + staged + untracked), and
the run silently widens back to a full sweep whenever a registry or
analyzer file itself changed — an edited transition table must re-judge
every conforming file, not just the table.
"""
from __future__ import annotations

import argparse
import subprocess
import sys

from . import event_registry, faults_registry, fsm_registry, \
    future_resolution, jit_contract, knob_registry, layout_registry, \
    lock_discipline, metric_registry, model_check, publish_order, \
    torn_write, trace_safety
from .base import RULE_IDS, repo_root

# analyzer -> the rule ids it can emit (every analyzer can additionally
# emit lint-suppression-missing-reason for its scanned files)
ANALYZERS = (
    ("trace-safety", trace_safety.check,
     {"trace-host-sync", "trace-python-branch", "jit-shape-source"}),
    ("jit-contract", jit_contract.check,
     {"jit-donated-read", "jit-recompile-capture"}),
    ("lock-discipline", lock_discipline.check, {"lock-discipline"}),
    ("knob-registry", knob_registry.check,
     {"knob-direct-env", "knob-undeclared", "knob-mutable-cached",
      "knob-docs-drift"}),
    ("metric-registry", metric_registry.check,
     {"metric-undeclared", "metric-undocumented", "metric-unused"}),
    ("event-registry", event_registry.check,
     {"event-undeclared", "event-undocumented", "event-unused"}),
    ("fault-registry", faults_registry.check,
     {"fault-undeclared", "fault-undocumented", "fault-unused"}),
    ("fsm-conformance", fsm_registry.check,
     {"fsm-undeclared-transition", "fsm-dead-transition"}),
    ("model-check", model_check.check, {"model-check-invariant"}),
    ("future-resolution", future_resolution.check,
     {"future-unresolved", "future-consumer-guard"}),
    ("layout-registry", layout_registry.check,
     {"layout-undeclared", "layout-drift",
      "layout-reader-writer-mismatch"}),
    ("publish-order", publish_order.check, {"publish-order"}),
    ("torn-write", torn_write.check, {"torn-write-invariant"}),
)

# analyzers whose scan set is a fixed file list: in --changed mode they
# run over (changed ∩ scan set) and are skipped when that is empty
_SCOPED = {
    "trace-safety": lambda: set(trace_safety.SCAN_FILES),
    "jit-contract": lambda: set(trace_safety.SCAN_FILES),
    "future-resolution": lambda: set(future_resolution.SCAN_FILES),
    "fsm-conformance": lambda: {m.file for m in fsm_registry.MACHINES},
    "model-check": lambda: {p[1] for p in model_check.PRODUCTS},
    "layout-registry": lambda: set(layout_registry.SCAN_FILES),
    "publish-order": lambda: set(layout_registry.SCAN_FILES),
    "torn-write": lambda: {p[1] for p in torn_write.TORN_PRODUCTS},
}

# any change here invalidates incremental scoping wholesale: the
# analyzers themselves, the registries they read, and the doc tables
# the drift rules compare against
_FULL_RUN_TRIGGERS = (
    "tools/lint/",
    "language_detector_tpu/knobs.py",
    "language_detector_tpu/faults.py",
    "language_detector_tpu/telemetry.py",
    "language_detector_tpu/flightrec.py",
    "language_detector_tpu/locks.py",
    "docs/OBSERVABILITY.md",
    "docs/STATIC_ANALYSIS.md",
)


def _git_changed_files(root) -> set | None:
    """Repo-relative paths git sees as touched (unstaged + staged +
    untracked). None when git itself fails (not a work tree)."""
    out: set = set()
    cmds = (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    )
    for cmd in cmds:
        try:
            r = subprocess.run(cmd, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        out.update(ln.strip() for ln in r.stdout.splitlines()
                   if ln.strip())
    return out


def run(rules=None, root=None, changed=None) -> int:
    """changed: None for a full run, else the set of repo-relative
    changed paths to scope scoped analyzers to."""
    root = root or repo_root()
    want = None
    if rules:
        want = {r.strip() for r in rules.split(",") if r.strip()}
        unknown = want - RULE_IDS - {a for a, _, _ in ANALYZERS}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}; "
                  f"known: {', '.join(sorted(RULE_IDS))}",
                  file=sys.stderr)
            return 2
    pkg_changed = changed is not None and any(
        p.startswith("language_detector_tpu/") for p in changed)
    violations: list = []
    n_suppressed = 0
    for name, fn, emits in ANALYZERS:
        if want is not None and not (want & emits) and name not in want:
            continue
        if changed is not None:
            if name in _SCOPED:
                scope = sorted(_SCOPED[name]() & changed)
                if not scope:
                    continue
                v, ns = fn(root=root, files=scope)
            elif pkg_changed:
                # cross-file drift analyzers are only sound whole-tree
                v, ns = fn(root=root)
            else:
                continue
        else:
            v, ns = fn(root=root)
        if want is not None and name not in want:
            v = [x for x in v if x.rule in want
                 or x.rule == "lint-suppression-missing-reason"]
        violations.extend(v)
        n_suppressed += ns
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    for v in violations:
        print(v)
    if violations:
        by_rule: dict = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        summary = ", ".join(f"{r}: {n}"
                            for r, n in sorted(by_rule.items()))
        print(f"\nldt-lint: {len(violations)} violation(s) "
              f"({summary}); {n_suppressed} suppressed",
              file=sys.stderr)
        return 1
    scope_note = "" if changed is None \
        else f", scoped to {len(changed)} changed file(s)"
    print(f"ldt-lint: clean ({n_suppressed} suppressed{scope_note})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="AST-based static analysis for this repo "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--rule", default=None,
                    help="comma-separated rule ids or analyzer names "
                         "to run (default: everything)")
    ap.add_argument("--changed", action="store_true",
                    help="scope analyzers to git-changed files; falls "
                         "back to a full run when a registry/analyzer "
                         "file changed (CI always runs full)")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the generated env-knob markdown table "
                         "and exit")
    ap.add_argument("--write-knob-docs", action="store_true",
                    help="regenerate the knob table in "
                         "docs/OBSERVABILITY.md and exit")
    ap.add_argument("--layout-table", action="store_true",
                    help="print the generated binary-layout markdown "
                         "table and exit")
    ap.add_argument("--write-layout-docs", action="store_true",
                    help="regenerate the binary-layout table in "
                         "docs/OBSERVABILITY.md and exit")
    args = ap.parse_args(argv)
    root = repo_root()
    if args.knob_table:
        print(knob_registry.generated_table(root))
        return 0
    if args.write_knob_docs:
        changed = knob_registry.write_knob_docs(root)
        print("docs/OBSERVABILITY.md "
              + ("updated" if changed else "already current"))
        return 0
    if args.layout_table:
        print(layout_registry.generated_table())
        return 0
    if args.write_layout_docs:
        changed = layout_registry.write_layout_docs(root)
        print("docs/OBSERVABILITY.md "
              + ("updated" if changed else "already current"))
        return 0
    changed = None
    if args.changed:
        changed = _git_changed_files(root)
        if changed is None:
            print("ldt-lint: --changed: git unavailable, running full",
                  file=sys.stderr)
        elif any(p.startswith(t) for p in changed
                 for t in _FULL_RUN_TRIGGERS):
            print("ldt-lint: --changed: registry/analyzer files "
                  "changed, running full", file=sys.stderr)
            changed = None
    return run(rules=args.rule, root=root, changed=changed)


if __name__ == "__main__":
    sys.exit(main())
