"""Good fixture for the publish-order analyzer: the two legal writer
shapes (invalidate -> tail -> payload -> commit, and the seqlock
odd -> fields -> even bracket) and readers that re-validate."""
import struct

HDR = struct.Struct("<IId")
SEQ = struct.Struct("<I")


def write_rec(mm, off, rec, payload):
    mm[off:off + 4] = b"\0\0\0\0"
    mm[off + 4:off + HDR.size] = rec[4:]
    mm[off + HDR.size:off + HDR.size + len(payload)] = payload
    mm[off:off + 4] = rec[:4]


def read_rec(mm, off):
    seq, length, _ts = HDR.unpack_from(mm, off)
    if seq == 0:
        return None
    return mm[off + HDR.size:off + HDR.size + length]


class SeqSlot:
    def put(self, mm, off, payload, s):
        SEQ.pack_into(mm, off, s + 1)
        HDR.pack_into(mm, off, s + 1, len(payload), 0.0)
        mm[off + HDR.size:off + HDR.size + len(payload)] = payload
        SEQ.pack_into(mm, off, s + 2)

    def _seq(self, mm, off):
        return SEQ.unpack_from(mm, off)[0]

    def get(self, mm, off):
        s1 = self._seq(mm, off)
        if s1 & 1:
            return None
        body = mm[off + HDR.size:off + HDR.size + 8]
        if self._seq(mm, off) != s1:
            return None
        return body
