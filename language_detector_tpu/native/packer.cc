// Native host-side batch packer: UTF-8 texts -> fixed-shape candidate
// tensors for the TPU scorer.
//
// C++ twin of preprocess/{segment,grams,hashing,squeeze,pack}.py — the
// byte-level, inherently sequential front half of detection (reference:
// getonescriptspan.cc:799 scanner, cldutil_shared.cc:107-386 hashes,
// cldutil.cc:315-533 gram scans, compact_lang_det_impl.cc:541-971 squeeze
// predictor). The Python packer is the behavioral spec (itself
// oracle-parity-tested); tests/test_native_pack.py asserts array-for-array
// equality between the two.
//
// Build: native/build.sh  ->  libldtpack.so (loaded via ctypes).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include "ldt_internal.h"

// Stage cycle counters, compiled in only for profiling builds.
// build.sh never defines LDT_PROF and no LDT_PROF_SCOPE marker exists
// in this file: tools/profile_pack.py generates packer_prof.cc with
// scopes inserted at the stage boundaries and builds the instrumented
// .so side by side. Slots: 0 segment, 1 quad scan, 2 word scan,
// 4 emission pass, 5 build_span, 7 whole pack_resolve_one_doc.
#ifdef LDT_PROF
#include <x86intrin.h>

#include <atomic>
// Plain u64 storage for the ctypes reader, updated with atomic RMWs:
// the flat pack runs docs on multiple worker threads, and non-atomic
// += would silently drop increments on multi-core hosts.
extern "C" uint64_t ldt_prof_cycles[8];
uint64_t ldt_prof_cycles[8] = {};
namespace {
struct ProfScope {
  int i;
  uint64_t t0;
  explicit ProfScope(int i) : i(i), t0(__rdtsc()) {}
  ~ProfScope() {
    reinterpret_cast<std::atomic<uint64_t>*>(&ldt_prof_cycles[i])
        ->fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
  }
};
}  // namespace
#define LDT_PROF_CAT2(a, b) a##b
#define LDT_PROF_CAT(a, b) LDT_PROF_CAT2(a, b)
#define LDT_PROF_SCOPE(i) ProfScope LDT_PROF_CAT(_prof_scope_, __LINE__)(i)
#else
#define LDT_PROF_SCOPE(i)
#endif

namespace {

// ---- candidate kinds (preprocess/pack.py) ----
enum Kind : int8_t {
  PAD = 0, SEED = 1, QUAD = 2, UNI = 3,
  DELTA_OCTA = 4, DISTINCT_OCTA = 5, BI_DELTA = 6, BI_DISTINCT = 7
};

constexpr int kMaxScoringHits = 1000;       // scoreonescriptspan.h:93
constexpr int kMaxSpanPutBytes = 40960 - 32;  // getonescriptspan.h:29-32
constexpr int kSoftSpanPutBytes = kMaxSpanPutBytes - 32;
constexpr int kTailPad = 32;
constexpr int kSqueezeTestThresh = 4096;    // kCheapSqueezeTestThresh
constexpr int kSqueezeTestLen = 256;
constexpr int kPredictionTableSize = 4096;
constexpr int kUlScriptInherited = 40;
constexpr int kUlScriptLatin = 1;

// ---- global tables (ldt_init; backing arrays owned by Python) ----
struct Ctx {
  const uint8_t* script_of_cp;   // [0x110000]
  const uint32_t* lower_map;     // [0x110000]
  const uint8_t* cjk_prop;       // [0x110000]
  const int32_t* rtype;          // [n_scripts]
  const int32_t* deflang;        // [n_scripts]
  const uint32_t* seed_lp;       // [n_scripts]
  int n_scripts;
  int distinctbi_empty;
};
Ctx g;

// ---- byte-class advance tables (cldutil_shared.h:462, cldutil.cc:49-99) --
struct AdvTables {
  int8_t but_space[256];   // 0 for <=0x20; 1/2/3/4 by UTF-8 lead
  int8_t one[256];
  int8_t space_vowel[256]; // 1 on space/ASCII-vowel/continuation/ctrl
  AdvTables() {
    for (int i = 0; i < 256; i++) {
      but_space[i] = i <= 0x20 ? 0 : i < 0xC0 ? 1 : i < 0xE0 ? 2
                     : i < 0xF0 ? 3 : 4;
      one[i] = i < 0xC0 ? 1 : i < 0xE0 ? 2 : i < 0xF0 ? 3 : 4;
      space_vowel[i] = (i <= 0x20) || (i >= 0x80 && i < 0xC0);
    }
    for (const char* v = "AEIOUaeiou"; *v; v++)
      space_vowel[(uint8_t)*v] = 1;
  }
};
const AdvTables adv;

inline uint32_t load32(const uint8_t* p) {
  uint32_t w;
  std::memcpy(&w, p, 4);  // little-endian hosts only (x86/arm64)
  return w;
}

constexpr uint32_t kPreSpace = 0x00004444;   // cldutil_shared.cc:41
constexpr uint32_t kPostSpace = 0x44440000;
const uint32_t kWordMask[4] = {0xFFFFFFFFu, 0x000000FFu, 0x0000FFFFu,
                               0x00FFFFFFu};

// QuadHashV2 (cldutil_shared.cc:196; preprocess/hashing.py quad_hash_v2)
uint32_t quad_hash(const uint8_t* buf, int64_t pos, int64_t len) {
  if (len == 0) return 0;
  uint32_t prepost = (buf[pos - 1] == 0x20 ? kPreSpace : 0) |
                     (buf[pos + len] == 0x20 ? kPostSpace : 0);
  uint32_t mask = kWordMask[len & 3];
  if (len <= 4) {
    uint32_t w0 = load32(buf + pos) & mask;
    w0 ^= w0 >> 3;
    return w0 ^ prepost;
  }
  uint32_t w0 = load32(buf + pos);
  w0 ^= w0 >> 3;
  if (len <= 8) {
    uint32_t w1 = load32(buf + pos + 4) & mask;
    w1 ^= w1 << 4;
    return (w0 ^ prepost) + w1;
  }
  uint32_t w1 = load32(buf + pos + 4);
  w1 ^= w1 << 4;
  uint32_t w2 = load32(buf + pos + 8) & mask;
  w2 ^= w2 << 2;
  return (w0 ^ prepost) + w1 + w2;
}

// OctaHash40 (cldutil_shared.cc:348; hashing.py octa_hash40)
const int kOctaShift[6] = {3, -4, -2, 8, 4, 6};

uint64_t octa_hash40(const uint8_t* buf, int64_t pos, int64_t len,
                     int64_t buflen) {
  if (len == 0) return 0;
  uint64_t prepost = (buf[pos - 1] == 0x20 ? kPreSpace : 0) |
                     (buf[pos + len] == 0x20 ? kPostSpace : 0);
  uint64_t mask = kWordMask[len & 3];
  int ngroups = (int)((len - 1) >> 2);
  if (ngroups > 5) ngroups = 5;
  uint64_t word0 = 0, csum = 0;
  for (int gidx = 0; gidx <= ngroups; gidx++) {
    int64_t gpos = pos + 4 * gidx;
    if (gpos > buflen - 4) gpos = buflen - 4;  // clip like the Python spec
    uint64_t w = load32(buf + gpos);
    if (gidx == ngroups) w &= mask;
    csum += w;
    int s = kOctaShift[gidx];
    uint64_t mixed = s > 0 ? (w ^ (w >> s)) : (w ^ (w << -s));
    word0 += mixed;
  }
  csum += csum >> 17;
  csum += csum >> 9;
  csum = (csum & 0xFF) << 32;
  return (word0 ^ prepost) + csum;
}

// BiHashV2 (cldutil_shared.cc:107; hashing.py bi_hash_v2)
uint32_t bi_hash(const uint8_t* buf, int64_t pos, int64_t len) {
  if (len == 0) return 0;
  uint32_t mask = kWordMask[len & 3];
  if (len <= 4) {
    uint32_t w0 = load32(buf + pos) & mask;
    w0 ^= w0 >> 3;
    return w0;
  }
  uint32_t w0 = load32(buf + pos);
  w0 ^= w0 >> 3;
  uint32_t w1 = load32(buf + pos + 4) & mask;
  w1 ^= w1 << 18;
  return w0 + w1;
}

// PairHash (cldutil_shared.cc:384)
inline uint64_t pair_hash(uint64_t a, uint64_t b) {
  return ((a >> 13) | (a << 51)) + b;
}

// ---- squeeze trigger (compact_lang_det_impl.cc:541-605, :952-971) ----
int count_spaces4(const uint8_t* buf, int len) {
  int n = len & ~3, c = 0;
  for (int i = 0; i < n; i++) c += buf[i] == 0x20;
  return c;
}

// CountPredictedBytes (compact_lang_det_impl.cc:541; squeeze.py): bytes
// whose UTF-8 char the rolling 12-bit-hash table predicted.
int count_predicted(const uint8_t* buf, int start, int len, int* hash,
                    int64_t* tbl) {
  int predicted = 0, h = *hash, i = start;
  const int limit = start + len;
  while (i < limit) {
    uint8_t c0 = buf[i];
    int64_t c;
    int incr;
    if (c0 < 0xC0) { c = c0; incr = 1; }
    else if ((c0 & 0xE0) == 0xC0) { c = (c0 << 8) | buf[i + 1]; incr = 2; }
    else if ((c0 & 0xF0) == 0xE0) {
      c = ((int64_t)c0 << 16) | (buf[i + 1] << 8) | buf[i + 2]; incr = 3;
    } else {
      c = ((int64_t)c0 << 24) | ((int64_t)buf[i + 1] << 16) |
          (buf[i + 2] << 8) | buf[i + 3];
      incr = 4;
    }
    i += incr;
    if (tbl[h] == c) predicted += incr;
    tbl[h] = c;
    h = ((h << 4) ^ (int)c) & 0xFFF;
  }
  *hash = h;
  return predicted;
}

bool cheap_squeeze_trigger(const uint8_t* buf, int src_len) {
  const int testsize = kSqueezeTestLen;
  if (src_len < testsize) return false;
  if (count_spaces4(buf, testsize) >= testsize * 25 / 100) return true;
  std::vector<int64_t> tbl(kPredictionTableSize, 0);
  int h = 0;
  return count_predicted(buf, 0, testsize, &h, tbl.data()) >=
         testsize * 67 / 100;
}

// BackscanToSpace / ForwardscanToSpace (compact_lang_det_impl.cc:491-521)
int backscan_to_space(const uint8_t* b, int dst) {
  int limit = dst < 32 ? dst : 32;
  for (int n = 0; n < limit; n++)
    if (b[dst - n - 1] == 0x20) return n;
  for (int n = 0; n < limit; n++)
    if ((b[dst - n] & 0xC0) != 0x80) return n;
  return 0;
}

int forwardscan_to_space(const uint8_t* b, int src, int limit) {
  if (limit > 32) limit = 32;
  for (int n = 0; n < limit; n++)
    if (b[src + n] == 0x20) return n + 1;
  for (int n = 0; n < limit; n++)
    if ((b[src + n] & 0xC0) != 0x80) return n;
  return 0;
}

// CheapSqueezeInplace (compact_lang_det_impl.cc:785-865; squeeze.py
// cheap_squeeze): drop space-heavy / well-predicted 48-byte chunks,
// compacting in place. b must extend >= 4 bytes past src_len; returns the
// new length.
int cheap_squeeze_inplace(uint8_t* b, int src_len) {
  const int chunksize = 48;
  const int space_thresh = chunksize * 25 / 100;
  const int predict_thresh = chunksize * 40 / 100;
  std::vector<int64_t> tbl(kPredictionTableSize, 0);
  int h = 0;
  bool skipping = false;
  int src = 0, dst = 0;
  while (src < src_len) {
    int len = src_len - src < chunksize ? src_len - src : chunksize;
    while ((b[src + len] & 0xC0) == 0x80) len++;  // UTF-8 boundary
    int space_n = count_spaces4(b + src, len);
    int predb_n = count_predicted(b, src, len, &h, tbl.data());
    if (space_n >= space_thresh || predb_n >= predict_thresh) {
      if (!skipping) {
        dst -= backscan_to_space(b, dst);
        if (dst == 0) {
          b[0] = 0x20;
          dst = 1;
        }
        skipping = true;
      }
    } else {
      int take_from = src, take_len = len;
      if (skipping) {
        int n = forwardscan_to_space(b, src, len);
        take_from += n;
        take_len -= n;
        skipping = false;
      }
      if (take_len > 0) {
        std::memmove(b + dst, b + take_from, take_len);
        dst += take_len;
      }
    }
    src += len;
  }
  return dst;
}


// ---- segmentation (preprocess/segment.py segment_text) ----
struct Span {
  std::vector<uint8_t> buf;      // ' ' + lowered letters + "   \0" + pad
  std::vector<uint32_t> cps;     // decoded buf codepoints + trailing space
  std::vector<int32_t> b2o;      // span byte -> ORIGINAL byte (result-
                                 // vector packs only; else empty; the
                                 // segment.py src_idx composed with the
                                 // char->byte cumsum)
  int text_bytes;
  int ulscript;
};

inline int u8len_of(uint32_t cp) {
  return cp < 0x80 ? 1 : cp < 0x800 ? 2 : cp < 0x10000 ? 3 : 4;
}

inline void u8encode(uint32_t cp, std::vector<uint8_t>* out) {
  if (cp < 0x80) out->push_back((uint8_t)cp);
  else if (cp < 0x800) {
    out->push_back(0xC0 | (cp >> 6));
    out->push_back(0x80 | (cp & 0x3F));
  } else if (cp < 0x10000) {
    out->push_back(0xE0 | (cp >> 12));
    out->push_back(0x80 | ((cp >> 6) & 0x3F));
    out->push_back(0x80 | (cp & 0x3F));
  } else {
    out->push_back(0xF0 | (cp >> 18));
    out->push_back(0x80 | ((cp >> 12) & 0x3F));
    out->push_back(0x80 | ((cp >> 6) & 0x3F));
    out->push_back(0x80 | (cp & 0x3F));
  }
}

// Decode valid UTF-8 (internal span buffers; truncated tails consume the
// lead byte alone rather than reading past `len`).
void u8decode(const uint8_t* s, int len, std::vector<uint32_t>* out) {
  int i = 0;
  while (i < len) {
    uint8_t c = s[i];
    if (c >= 0x80 && i + (c < 0xF0 ? (c < 0xE0 ? 2 : 3) : 4) > len) {
      out->push_back(c);
      i += 1;
    }
    else if (c < 0x80) { out->push_back(c); i += 1; }
    else if (c < 0xE0) {
      out->push_back(((c & 0x1F) << 6) | (s[i + 1] & 0x3F));
      i += 2;
    } else if (c < 0xF0) {
      out->push_back(((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6) |
                     (s[i + 2] & 0x3F));
      i += 3;
    } else {
      out->push_back(((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12) |
                     ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F));
      i += 4;
    }
  }
}

void build_span(const std::vector<uint32_t>& cur, int ulscript,
                Span* sp, const std::vector<int32_t>* src = nullptr) {
  sp->ulscript = ulscript;
  const size_t n = cur.size();
  sp->cps.resize(n + 2);
  uint32_t* cps = sp->cps.data();
  cps[0] = 0x20;
  if (n) std::memcpy(cps + 1, cur.data(), n * sizeof(uint32_t));
  cps[n + 1] = 0x20;
  size_t nb = 1;  // leading space
  for (size_t i = 0; i < n; i++) nb += u8len_of(cur[i]);
  sp->text_bytes = (int)nb;
  // sized writes through a raw pointer: the per-byte push_back capacity
  // checks were ~14% of single-core pack time
  sp->buf.resize(nb + kTailPad);
  uint8_t* p = sp->buf.data();
  *p++ = 0x20;
  for (size_t i = 0; i < n; i++) {
    uint32_t cp = cur[i];
    if (cp < 0x80) {
      *p++ = (uint8_t)cp;
    } else if (cp < 0x800) {
      *p++ = (uint8_t)(0xC0 | (cp >> 6));
      *p++ = (uint8_t)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      *p++ = (uint8_t)(0xE0 | (cp >> 12));
      *p++ = (uint8_t)(0x80 | ((cp >> 6) & 0x3F));
      *p++ = (uint8_t)(0x80 | (cp & 0x3F));
    } else {
      *p++ = (uint8_t)(0xF0 | (cp >> 18));
      *p++ = (uint8_t)(0x80 | ((cp >> 12) & 0x3F));
      *p++ = (uint8_t)(0x80 | ((cp >> 6) & 0x3F));
      *p++ = (uint8_t)(0x80 | (cp & 0x3F));
    }
  }
  p[0] = p[1] = p[2] = 0x20;
  std::memset(p + 3, 0, kTailPad - 3);
  // span byte -> original byte (segment.py _build_span src_idx: each
  // cp's source repeated over its encoded length, leading space
  // inheriting the first letter's source, one trailing duplicate)
  sp->b2o.clear();
  if (src != nullptr) {
    sp->b2o.reserve(nb + 1);
    int32_t lead = n ? (*src)[0] : 0;
    sp->b2o.push_back(lead);  // leading space (1 byte)
    for (size_t i = 0; i < n; i++) {
      int l = u8len_of(cur[i]);
      for (int k = 0; k < l; k++) sp->b2o.push_back((*src)[i]);
    }
    sp->b2o.push_back(sp->b2o.back());
  }
}

// Reusable per-thread segmentation scratch: all vectors keep their
// capacity across documents, making steady-state packing allocation-free
// (the malloc + first-touch cost was ~25% of single-thread pack time).
struct SegScratch {
  std::vector<uint32_t> lower, cur;
  std::vector<int32_t> cur_src;  // orig byte per cur entry (ranges mode)
  std::vector<uint8_t> script;
  std::vector<int8_t> u8l;
  std::vector<int64_t> byte_before;
  std::vector<Span> spans;  // pool; only [0, n_spans) are live
  int n_spans = 0;

  Span* alloc_span() {
    if (n_spans == (int)spans.size()) spans.emplace_back();
    return &spans[n_spans++];
  }

  // Bound long-lived retention: one pathological multi-MB document must
  // not pin worst-case capacity on a persistent thread forever.
  void maybe_shrink() {
    if (lower.capacity() > (1 << 20) || spans.size() > 512)
      *this = SegScratch();
  }
};

// CheapRepWordsInplace (compact_lang_det_impl.cc:610-692; squeeze.py
// cheap_rep_words): drop words with more than half their bytes predicted.
// hash/tbl persist across the spans of one document.
int cheap_rep_words_inplace(uint8_t* b, int src_len, int* hash,
                            int64_t* tbl) {
  int h = *hash;
  int dst = 0, word_dst = 0, good_predict = 0, word_len = 0, src = 0;
  while (src < src_len) {
    uint8_t c0 = b[src];
    b[dst++] = c0;
    if (c0 == 0x20) {
      if (good_predict * 2 > word_len) dst = word_dst;
      word_dst = dst;
      good_predict = 0;
      word_len = 0;
    }
    int64_t c;
    int incr;
    if (c0 < 0xC0) { c = c0; incr = 1; }
    else if ((c0 & 0xE0) == 0xC0) {
      b[dst++] = b[src + 1];
      c = (c0 << 8) | b[src + 1];
      incr = 2;
    } else if ((c0 & 0xF0) == 0xE0) {
      b[dst++] = b[src + 1];
      b[dst++] = b[src + 2];
      c = ((int64_t)c0 << 16) | (b[src + 1] << 8) | b[src + 2];
      incr = 3;
    } else {
      b[dst++] = b[src + 1];
      b[dst++] = b[src + 2];
      b[dst++] = b[src + 3];
      c = ((int64_t)c0 << 24) | ((int64_t)b[src + 1] << 16) |
          (b[src + 2] << 8) | b[src + 3];
      incr = 4;
    }
    src += incr;
    word_len += incr;
    if (tbl[h] == c) good_predict += incr;
    tbl[h] = c;
    h = ((h << 4) ^ (int)c) & 0xFFF;
  }
  *hash = h;
  return dst;
}

// Rebuild a span around rewritten (shorter) text
void respan(Span* sp, int n) {
  sp->b2o.clear();  // offsets no longer map to the original input
  sp->text_bytes = n;
  sp->buf.resize(n + kTailPad);
  sp->buf[n] = sp->buf[n + 1] = sp->buf[n + 2] = 0x20;
  std::memset(sp->buf.data() + n + 3, 0, kTailPad - 3);
  sp->cps.clear();
  u8decode(sp->buf.data(), n, &sp->cps);
  sp->cps.push_back(0x20);
}

// Rebuild a span around its squeezed text (engine_scalar _respan)
void squeeze_span(Span* sp) {
  respan(sp, cheap_squeeze_inplace(sp->buf.data(), sp->text_bytes));
}

void segment_text(const uint8_t* text, int text_len, SegScratch* ss,
                  bool collect_src = false) {
  ss->n_spans = 0;
  if (text_len == 0) return;
  // Single fused pass: decode + script/lower classification + byte
  // accounting (the decode increment IS the codepoint's u8 length for
  // the valid UTF-8 a Python str encodes to, so no second u8len pass)
  std::vector<uint8_t>& script = ss->script;
  std::vector<uint32_t>& lower = ss->lower;
  std::vector<int8_t>& u8l = ss->u8l;
  std::vector<int64_t>& byte_before = ss->byte_before;
  script.resize(text_len);
  lower.resize(text_len);
  u8l.resize(text_len);
  byte_before.resize(text_len + 1);
  int n = 0;
  {
    int i = 0;
    while (i < text_len) {
      uint8_t c = text[i];
      uint32_t cp;
      int incr;
      if (c < 0x80) {
        // ASCII run fast path: one-byte classification straight from
        // the low end of the global tables, no decode branches (most
        // service traffic is Latin; this loop was the largest single
        // pack cost after the scanners)
        do {
          script[n] = g.script_of_cp[c];
          lower[n] = g.lower_map[c];
          u8l[n] = 1;
          byte_before[n] = i;
          n++;
          i++;
          if (i >= text_len) break;
          c = text[i];
        } while (c < 0x80);
        continue;
      }
      if (i + (c < 0xF0 ? (c < 0xE0 ? 2 : 3) : 4) > text_len) {
        // truncated multibyte tail OR stray continuation byte at the end
        // (reachable via the C ABI, which takes arbitrary bytes):
        // consume one byte instead of reading past the buffer
        cp = c;
        incr = 1;
      } else if (c < 0xE0) {
        cp = ((c & 0x1F) << 6) | (text[i + 1] & 0x3F);
        incr = 2;
      } else if (c < 0xF0) {
        cp = ((c & 0x0F) << 12) | ((text[i + 1] & 0x3F) << 6) |
             (text[i + 2] & 0x3F);
        incr = 3;
      } else {
        cp = ((c & 0x07) << 18) | ((text[i + 1] & 0x3F) << 12) |
             ((text[i + 2] & 0x3F) << 6) | (text[i + 3] & 0x3F);
        incr = 4;
      }
      uint32_t cpc = cp > 0x10FFFF ? 0x10FFFF : cp;
      script[n] = g.script_of_cp[cpc];
      lower[n] = g.lower_map[cpc];
      u8l[n] = (int8_t)incr;
      byte_before[n] = i;
      n++;
      i += incr;
    }
    byte_before[n] = i;
  }
  if (n == 0) return;
  const int64_t total_bytes = byte_before[n];

  int i = 0;
  while (i < n) {
    int64_t remaining = total_bytes - byte_before[i];
    int soft_limit = kSoftSpanPutBytes;
    if (remaining >= kMaxSpanPutBytes && remaining < 2 * kMaxSpanPutBytes)
      soft_limit = (int)(remaining / 2);
    while (i < n && script[i] == 0) i++;
    if (i >= n) break;
    const int spanscript = script[i];
    std::vector<uint32_t>& cur = ss->cur;
    std::vector<int32_t>& cur_src = ss->cur_src;
    cur.clear();
    cur_src.clear();
    int put = 1;

    while (i < n) {
      // letter run
      while (i < n) {
        int sc = script[i];
        if (sc == 0) break;
        if (sc != spanscript && sc != kUlScriptInherited) {
          // one embedded foreign letter allowed when the next char is
          // Common or back in-script (getonescriptspan.cc:900-930)
          int sc2 = i + 1 < n ? script[i + 1] : 0;
          if (sc2 != 0 && sc2 != spanscript) break;
        }
        cur.push_back(lower[i]);
        if (collect_src) cur_src.push_back((int32_t)byte_before[i]);
        put += u8l[i];
        i++;
        if (put >= kMaxSpanPutBytes) break;
      }
      // non-letter run -> single space
      cur.push_back(0x20);
      if (collect_src)
        cur_src.push_back((int32_t)byte_before[i < n ? i : n - 1]);
      put += 1;
      while (i < n && script[i] == 0) i++;
      if (i >= n) break;
      if (script[i] != spanscript && script[i] != kUlScriptInherited) break;
      if (put >= soft_limit) break;
    }
    if (cur.size() > 1)
      build_span(cur, spanscript, ss->alloc_span(),
                 collect_src ? &cur_src : nullptr);
  }
}

// ---- per-span candidate records (preprocess/pack.py) ----
struct Rec {
  int32_t offset;
  int8_t kind;
  int8_t prio;     // merge priority at equal offsets
  uint8_t fp_hi;   // octa hash bits 32-39
  int8_t pad_;
  uint32_t fp;     // fingerprint low 32 / seed langprob / uni class
};

inline int8_t prio_of(int8_t kind) {
  switch (kind) {
    case SEED: return -1;
    case DELTA_OCTA: case BI_DELTA: return 0;
    case DISTINCT_OCTA: case BI_DISTINCT: return 1;
    default: return 2;  // QUAD, UNI
  }
}

// Quad + word candidates in linear merge order; false => scalar fallback
bool pack_quad_span(const Span& sp, std::vector<Rec>* recs) {
  const uint8_t* b = sp.buf.data();
  const int64_t buflen = (int64_t)sp.buf.size();
  const int limit = sp.text_bytes;

  // quad positions (grams.py quad_positions: 2-char steps, word-end jump,
  // space/vowel skip; cldutil.cc:338-395)
  std::vector<int32_t> qpos, qlen;
  {
    int64_t src = 1;
    if (b[src] == 0x20) src++;
    while (src < limit) {
      int64_t e = src;
      e += adv.but_space[b[e]];
      e += adv.but_space[b[e]];
      int64_t mid = e;
      e += adv.but_space[b[e]];
      e += adv.but_space[b[e]];
      qpos.push_back((int32_t)src);
      qlen.push_back((int32_t)(e - src));
      src = b[e] == 0x20 ? e : mid;
      if (src < limit) src += adv.space_vowel[b[src]];
      else src = limit;
    }
  }
  if ((int)qpos.size() > kMaxScoringHits) return false;  // multi-round span

  // word records with hash-only repeat filter + pairs (cldutil.cc:459-502)
  {
    int64_t src = 1;
    if (b[src] == 0x20) src++;
    uint64_t cache[2] = {0, 0};
    int nxt = 0;
    int n_delta = 0, n_distinct = 0;
    int64_t srclimit = limit + 1;
    int charcount = 0;
    int64_t prior_word_start = src, word_start = src, word_end = word_start;
    while (src < srclimit) {
      if (b[src] == 0x20) {
        if (word_end > word_start) {
          int64_t wlen = word_end - word_start;
          uint64_t fpw = octa_hash40(b, word_start, wlen, buflen);
          if (fpw != cache[0] && fpw != cache[1]) {
            cache[nxt] = fpw;
            nxt = 1 - nxt;
            uint64_t prior = cache[nxt];
            if (prior != 0 && prior != fpw) {
              uint64_t pfp = pair_hash(prior, fpw);
              recs->push_back({(int32_t)prior_word_start, DISTINCT_OCTA, 0,
                               (uint8_t)(pfp >> 32), 0, (uint32_t)pfp});
              n_distinct++;
            }
            recs->push_back({(int32_t)word_start, DISTINCT_OCTA, 0,
                             (uint8_t)(fpw >> 32), 0, (uint32_t)fpw});
            recs->push_back({(int32_t)word_start, DELTA_OCTA, 0,
                             (uint8_t)(fpw >> 32), 0, (uint32_t)fpw});
            n_delta++;
            n_distinct++;
            if (n_delta >= kMaxScoringHits ||
                n_distinct >= kMaxScoringHits - 1)
              break;
          }
        }
        charcount = 0;
        prior_word_start = word_start;
        word_start = src + 1;
        word_end = word_start;
      } else {
        charcount++;
      }
      src += adv.one[b[src]];
      if (charcount <= 8) word_end = src;
    }
  }

  for (size_t i = 0; i < qpos.size(); i++) {
    uint32_t fp = quad_hash(b, qpos[i], qlen[i]);
    recs->push_back({qpos[i], QUAD, 0, 0, 0, fp});
  }
  return true;
}

bool pack_cjk_span(const Span& sp, std::vector<Rec>* recs) {
  const int n = (int)sp.cps.size();
  std::vector<int64_t> starts(n), ends(n);
  int64_t acc = 0;
  for (int i = 0; i < n; i++) {
    starts[i] = acc;
    acc += u8len_of(sp.cps[i]);
    ends[i] = acc;
  }
  int n_uni = 0;
  for (int i = 0; i < n; i++) {
    uint32_t cp = sp.cps[i] > 0x10FFFF ? 0x10FFFF : sp.cps[i];
    uint8_t prop = g.cjk_prop[cp];
    if (prop > 0 && starts[i] >= 1 && starts[i] < sp.text_bytes) n_uni++;
  }
  if (n_uni > kMaxScoringHits) return false;  // multi-round span
  for (int i = 0; i < n; i++) {
    uint32_t cp = sp.cps[i] > 0x10FFFF ? 0x10FFFF : sp.cps[i];
    uint8_t prop = g.cjk_prop[cp];
    if (prop > 0 && starts[i] >= 1 && starts[i] < sp.text_bytes)
      recs->push_back({(int32_t)ends[i], UNI, 0, 0, 0, prop});
  }
  for (int i = 0; i + 1 < n; i++) {
    int64_t len2 = ends[i + 1] - starts[i];
    if (len2 >= 6 && starts[i] >= 1 && starts[i] < sp.text_bytes) {
      uint32_t fp = bi_hash(sp.buf.data(), starts[i], len2);
      recs->push_back({(int32_t)starts[i], BI_DELTA, 0, 0, 0, fp});
      if (!g.distinctbi_empty)
        recs->push_back({(int32_t)starts[i], BI_DISTINCT, 0, 0, 0, fp});
    }
  }
  return true;
}

// ---- per-document packing (pack.py pack_batch body) ----
struct Out {
  int8_t* kind; int32_t* offset; uint32_t* fp; uint8_t* fp_hi;
  int32_t* chunk_base; int32_t* span_start;
  int32_t* span_end_off; int8_t* side; int8_t* cjk; int16_t* script;
  int16_t* chunk_script; int8_t* chunk_cjk; int8_t* chunk_side;
  int32_t* chunk_span_end;
  int32_t* direct_adds; int32_t* text_bytes; uint8_t* fallback;
  int32_t* n_slots; int32_t* n_chunks;
  int L, C, D, flags;
};

void pack_one_doc(const uint8_t* text, int text_len, int b, const Out& o) {
  static thread_local SegScratch seg;
  seg.maybe_shrink();
  segment_text(text, text_len, &seg);

  const int L = o.L, C = o.C;
  int8_t* kind = o.kind + (int64_t)b * L;
  int32_t* offset = o.offset + (int64_t)b * L;
  uint32_t* fp = o.fp + (int64_t)b * L;
  uint8_t* fp_hi = o.fp_hi + (int64_t)b * L;
  int32_t* chunk_base_a = o.chunk_base + (int64_t)b * L;
  int32_t* span_start_a = o.span_start + (int64_t)b * L;
  int32_t* span_end_a = o.span_end_off + (int64_t)b * L;
  int8_t* side_a = o.side + (int64_t)b * L;
  int8_t* cjk_a = o.cjk + (int64_t)b * L;
  int16_t* script_a = o.script + (int64_t)b * L;
  int16_t* cscript = o.chunk_script + (int64_t)b * C;
  int8_t* ccjk = o.chunk_cjk + (int64_t)b * C;
  int8_t* cside = o.chunk_side + (int64_t)b * C;
  int32_t* cspanend = o.chunk_span_end + (int64_t)b * C;
  int32_t* dadds = o.direct_adds + (int64_t)b * o.D * 3;

  int slot = 0, chunk_base = 0, n_direct = 0;
  int64_t total = 0;
  bool ok = true;
  static thread_local std::vector<Rec> recs;
  for (int _si = 0; _si < seg.n_spans; _si++) {
    const Span& sp = seg.spans[_si];
    total += sp.text_bytes;
    int rt = sp.ulscript < g.n_scripts ? g.rtype[sp.ulscript] : 0;
    if (!(o.flags & 1) && sp.text_bytes > (kSqueezeTestThresh >> 1) &&
        cheap_squeeze_trigger(sp.buf.data(), sp.text_bytes)) {
      ok = false;  // squeeze-trigger doc -> scalar path (FLAG_FINISH skips)
      break;
    }
    if (rt == 0 || rt == 1) {  // RTypeNone/One: direct doc-tote add
      if (n_direct >= o.D || chunk_base >= C) { ok = false; break; }
      dadds[n_direct * 3 + 0] = chunk_base;
      dadds[n_direct * 3 + 1] = g.deflang[sp.ulscript];
      dadds[n_direct * 3 + 2] = sp.text_bytes;
      n_direct++;
      chunk_base++;
      continue;
    }
    if (sp.text_bytes <= 1) continue;
    const bool cjk = rt == 3;
    recs.clear();
    bool fits = cjk ? pack_cjk_span(sp, &recs) : pack_quad_span(sp, &recs);
    if (!fits) { ok = false; break; }
    recs.push_back({1, SEED, 0, 0, 0,
                    sp.ulscript < g.n_scripts ? g.seed_lp[sp.ulscript] : 0});
    for (size_t i = 0; i < recs.size(); i++)
      recs[i].prio = prio_of(recs[i].kind);
    std::stable_sort(recs.begin(), recs.end(),
                     [](const Rec& a, const Rec& c) {
                       if (a.offset != c.offset) return a.offset < c.offset;
                       return a.prio < c.prio;
                     });
    int n_base_max = 0;
    for (const Rec& r : recs)
      n_base_max += (r.kind == SEED || r.kind == QUAD || r.kind == UNI);
    int chunksize = cjk ? 50 : 20;
    int span_chunks = 1 + (n_base_max + chunksize - 1) / chunksize;
    if (span_chunks < 1) span_chunks = 1;
    if (slot + (int)recs.size() > L || chunk_base + span_chunks > C) {
      ok = false;
      break;
    }
    int8_t side = sp.ulscript == kUlScriptLatin ? 0 : 1;
    int start = slot;
    for (const Rec& r : recs) {
      kind[slot] = r.kind;
      offset[slot] = r.offset;
      fp[slot] = r.fp;
      fp_hi[slot] = r.fp_hi;
      chunk_base_a[slot] = chunk_base;
      span_start_a[slot] = start;
      span_end_a[slot] = sp.text_bytes;
      side_a[slot] = side;
      cjk_a[slot] = cjk;
      script_a[slot] = (int16_t)sp.ulscript;
      slot++;
    }
    for (int c = chunk_base; c < chunk_base + span_chunks; c++) {
      cscript[c] = (int16_t)sp.ulscript;
      ccjk[c] = cjk;
      cside[c] = side;
      cspanend[c] = sp.text_bytes;
    }
    chunk_base += span_chunks;
  }
  o.text_bytes[b] = (int32_t)total;
  o.fallback[b] = !ok;
  o.n_slots[b] = slot;
  o.n_chunks[b] = chunk_base;
}

// ---- host-side table resolution (the device program's stages 1-4,
// ops/score.py) ------------------------------------------------------------
//
// The scoring tables are a few MB and host-cache-resident, so the 4-way
// associative probes (QuadHashV3Lookup4 / OctaHashV3Lookup4,
// cldutil_shared.h:403-454), the quad repeat cache (cldutil.cc:334-367),
// chunk assignment (ChunkAll, scoreonescriptspan.cc:978-1031), and the
// rotating distinct-boost lists (AddDistinctBoost2, :112-121) all run here
// during packing. The wire then carries only RESOLVED hits: a u16 index
// into the device's concatenated indirect array + a u8 doc-local chunk id
// (3 bytes/slot vs 8, and misses never cross the host->device link).

struct ResTables {
  const uint32_t* cat_buckets;  // [rows][4] all tables' buckets
  const uint32_t* cat_ind;      // concatenated indirect arrays
  int64_t n_ind;
  // per-kind geometry (DeviceTables.kind_tbl)
  int64_t bucket_off[8];
  uint32_t size[8], keymask[8];
  int32_t ind_off[8], size_one[8];
  uint8_t probes[8];
  // dual quadgram table
  int64_t q2_bucket_off;
  uint32_t q2_size, q2_keymask;
  int32_t q2_ind_off, q2_size_one;
  int q2_enabled;
  int32_t seed_ind_base;  // cat_ind2 index of script 0's seed langprob
};
ResTables rt;
bool rt_ready = false;

inline uint32_t probe4(const uint32_t* row, uint32_t key, uint32_t keymask) {
  for (int s = 0; s < 4; s++)
    if (((row[s] ^ key) & keymask) == 0) return row[s];
  return 0;
}

// Resolve one candidate exactly as the device program did (ops/score.py
// stages 2-3): word A at the indirect address, word B only for QUAD/UNI
// double entries. A zero word A makes the whole candidate inactive for
// every kind except UNI (whose word B scores independently). Returns
// (a_nonzero, b_nonzero, ia); emitted indices are ia / ia + 1.
struct Resolved { bool a, b; int32_t ia; };

inline Resolved resolve_rec(const Rec& r) {
  int kind = r.kind;
  if (kind == UNI) {
    // direct double entry (cjkcompat: size_one == 0)
    int32_t ia = rt.ind_off[UNI] + 2 * (int32_t)r.fp - rt.size_one[UNI];
    return {rt.cat_ind[ia] != 0, rt.cat_ind[ia + 1] != 0, ia};
  }
  uint32_t fp = r.fp, size = rt.size[kind], keymask = rt.keymask[kind];
  uint32_t sub, key;
  if (kind == DELTA_OCTA || kind == DISTINCT_OCTA) {
    uint32_t hi = r.fp_hi;
    sub = (fp + ((fp >> 12) | (hi << 20))) & (size - 1);
    key = ((fp >> 4) | (hi << 28)) & keymask;
  } else {
    sub = (fp + (fp >> 12)) & (size - 1);
    key = fp & keymask;
  }
  uint32_t kv = probe4(rt.cat_buckets + 4 * (rt.bucket_off[kind] + sub),
                       key, keymask);
  int32_t io = rt.ind_off[kind], so = rt.size_one[kind];
  if (kv == 0 && kind == QUAD && rt.q2_enabled) {
    uint32_t sub2 = (fp + (fp >> 12)) & (rt.q2_size - 1);
    kv = probe4(rt.cat_buckets + 4 * (rt.q2_bucket_off + sub2),
                fp & rt.q2_keymask, rt.q2_keymask);
    io = rt.q2_ind_off;
    so = rt.q2_size_one;
    keymask = rt.q2_keymask;
  }
  if (kv == 0) return {false, false, 0};
  int32_t ind_raw = (int32_t)(kv & ~keymask);
  if (ind_raw < so) {
    int32_t ia = io + ind_raw;
    return {rt.cat_ind[ia] != 0, false, ia};
  }
  int32_t ia = io + 2 * ind_raw - so;
  // word B scores only for QUAD/UNI doubles (device lp_b gating)
  bool b = kind == QUAD && rt.cat_ind[ia + 1] != 0;
  return {rt.cat_ind[ia] != 0, b, ia};
}

// ---- per-round scanners (hitbuffer fills of <= 1000 base hits,
// scoreonescriptspan.cc:1163-1277; Python spec preprocess/grams.py) ------

// One quadgram round from `start`: pushes RESOLVED quad hits (Rec.pad_=1,
// fp=indirect address, fp_hi=word-B flag) and returns next_offset (the
// next candidate position when the fill hits kMaxScoringHits, else the
// scan end). Repeat cache is round-local (GetQuadHits, cldutil.cc:334).
// *n_quota / *n_emit accumulate resolved hits and emitted slots (a + b).
//
// Two-phase per 512-quad block: phase A is pure byte work (positions +
// hashes) and issues a software prefetch for each hash's probe row;
// phase B runs the repeat cache + 4-way probes over lines that are
// already inbound. The probes' random access into the multi-MB bucket
// array was the single largest pack cost (~200 cycles/miss).
int64_t scan_quad_round(const Span& sp, int64_t start,
                        std::vector<Rec>* recs, int* n_quota,
                        int* n_emit) {
  const uint8_t* b = sp.buf.data();
  const int limit = sp.text_bytes;
  int64_t src = start;
  if (b[src] == 0x20) src++;
  uint32_t cache[2] = {0, 0};
  int nxt = 0, hits = 0, emitted = 0;
  static thread_local std::vector<int32_t> qpos, qnext;
  static thread_local std::vector<uint32_t> qfp;
  constexpr int kBlock = 512;  // prefetched lines stay L1/L2-resident
  const uint32_t qmask = rt.size[QUAD] - 1;
  const uint32_t* qbase = rt.cat_buckets + 4 * rt.bucket_off[QUAD];
  while (src < limit) {
    qpos.clear();
    qfp.clear();
    qnext.clear();
    while (src < limit && (int)qpos.size() < kBlock) {
      int64_t e = src;
      e += adv.but_space[b[e]];
      e += adv.but_space[b[e]];
      int64_t mid = e;
      e += adv.but_space[b[e]];
      e += adv.but_space[b[e]];
      uint32_t fp = quad_hash(b, src, e - src);
      qpos.push_back((int32_t)src);
      qfp.push_back(fp);
      __builtin_prefetch(qbase + 4 * ((fp + (fp >> 12)) & qmask));
      src = b[e] == 0x20 ? e : mid;
      if (src < limit) src += adv.space_vowel[b[src]];
      else src = limit;
      qnext.push_back((int32_t)src);
    }
    const size_t nq = qpos.size();
    for (size_t i = 0; i < nq; i++) {
      uint32_t fp = qfp[i];
      if (fp != cache[0] && fp != cache[1]) {
        Rec raw{qpos[i], QUAD, 0, 0, 0, fp};
        Resolved rs = resolve_rec(raw);
        if (rs.a) {
          cache[nxt] = fp;
          nxt = 1 - nxt;
          recs->push_back({qpos[i], QUAD, 0, (uint8_t)(rs.b ? 1 : 0), 1,
                           (uint32_t)rs.ia});
          emitted += 1 + (rs.b ? 1 : 0);
          if (++hits >= kMaxScoringHits) {
            *n_quota += hits;
            *n_emit += emitted;
            return qnext[i];
          }
        }
      }
    }
  }
  *n_quota += hits;
  *n_emit += emitted;
  return src;
}

// Word (octa) hits over [start, end): RESOLVED delta + distinct + pair
// records (Rec.pad_=1), caches and HIT caps round-local (GetOctaHits,
// cldutil.cc:416-533; Python spec grams.py get_octa_hits). *n_emit
// accumulates pushed records (1 emitted slot each).
//
// Two-phase per 512-word block like scan_quad_round: the repeat cache
// here advances independently of table resolution, so phase A applies
// it while hashing + prefetching the three probe rows each word needs
// (pair / delta / distinct), and phase B probes warm lines. DELTA is
// pushed before DISTINCT at each offset: emission order IS the final
// merge order (offset, then kind priority) — there is no sort.
void scan_word_range(const Span& sp, int64_t start, int64_t end,
                     std::vector<Rec>* recs, int* n_emit) {
  const uint8_t* b = sp.buf.data();
  const int64_t buflen = (int64_t)sp.buf.size();
  int64_t src = start;
  if (b[src] == 0x20) src++;
  uint64_t cache[2] = {0, 0};
  int nxt = 0;
  int n_delta = 0, n_distinct = 0;
  int64_t srclimit = end + 1;  // include trailing space off the end
  int charcount = 0;
  int64_t prior_word_start = src, word_start = src, word_end = word_start;
  struct WordEnt {
    int32_t prior_start, start;
    uint64_t fpw, pfp;  // pfp == 0: no pair record
  };
  static thread_local std::vector<WordEnt> ents;
  constexpr int kBlock = 512;
  const uint32_t dmask = rt.size[DELTA_OCTA] - 1;
  const uint32_t xmask = rt.size[DISTINCT_OCTA] - 1;
  const uint32_t* dbase = rt.cat_buckets + 4 * rt.bucket_off[DELTA_OCTA];
  const uint32_t* xbase =
      rt.cat_buckets + 4 * rt.bucket_off[DISTINCT_OCTA];
  auto octa_sub = [](uint64_t fp64, uint32_t mask) {
    uint32_t lo = (uint32_t)fp64, hi = (uint32_t)(fp64 >> 32) & 0xFF;
    return (lo + ((lo >> 12) | (hi << 20))) & mask;
  };
  bool capped = false;
  while (src < srclimit && !capped) {
    ents.clear();
    while (src < srclimit && (int)ents.size() < kBlock) {
      if (b[src] == 0x20) {
        if (word_end > word_start) {
          uint64_t fpw = octa_hash40(b, word_start, word_end - word_start,
                                     buflen);
          if (fpw != cache[0] && fpw != cache[1]) {
            cache[nxt] = fpw;
            nxt = 1 - nxt;
            uint64_t prior = cache[nxt];
            uint64_t pfp =
                prior != 0 && prior != fpw ? pair_hash(prior, fpw) : 0;
            if (pfp) __builtin_prefetch(xbase + 4 * octa_sub(pfp, xmask));
            __builtin_prefetch(dbase + 4 * octa_sub(fpw, dmask));
            __builtin_prefetch(xbase + 4 * octa_sub(fpw, xmask));
            ents.push_back({(int32_t)prior_word_start, (int32_t)word_start,
                            fpw, pfp});
          }
        }
        charcount = 0;
        prior_word_start = word_start;
        word_start = src + 1;
        word_end = word_start;
      } else {
        charcount++;
      }
      src += adv.one[b[src]];
      if (charcount <= 8) word_end = src;
    }
    for (const WordEnt& w : ents) {
      if (w.pfp) {
        Rec raw{w.prior_start, DISTINCT_OCTA, 0, (uint8_t)(w.pfp >> 32),
                0, (uint32_t)w.pfp};
        Resolved rs = resolve_rec(raw);
        if (rs.a) {
          recs->push_back({w.prior_start, DISTINCT_OCTA, 0, 0, 1,
                           (uint32_t)rs.ia});
          n_distinct++;
          (*n_emit)++;
        }
      }
      Rec rawd{w.start, DELTA_OCTA, 0, (uint8_t)(w.fpw >> 32), 0,
               (uint32_t)w.fpw};
      Resolved rd = resolve_rec(rawd);
      if (rd.a) {
        recs->push_back({w.start, DELTA_OCTA, 0, 0, 1, (uint32_t)rd.ia});
        n_delta++;
        (*n_emit)++;
      }
      Rec rawx{w.start, DISTINCT_OCTA, 0, (uint8_t)(w.fpw >> 32), 0,
               (uint32_t)w.fpw};
      Resolved rx = resolve_rec(rawx);
      if (rx.a) {
        recs->push_back({w.start, DISTINCT_OCTA, 0, 0, 1,
                         (uint32_t)rx.ia});
        n_distinct++;
        (*n_emit)++;
      }
      if (n_delta >= kMaxScoringHits || n_distinct >= kMaxScoringHits - 1) {
        capped = true;
        break;
      }
    }
  }
}

// Per-span CJK codepoint geometry, computed once and reused across
// rounds (with a resume index so multi-round spans stay O(n) total).
struct CjkGeom {
  std::vector<int64_t> starts, ends;
  int resume = 0;  // first codepoint index not yet consumed by a round

  void init(const Span& sp) {
    const int n = (int)sp.cps.size();
    starts.resize(n);
    ends.resize(n);
    int64_t acc = 0;
    for (int i = 0; i < n; i++) {
      starts[i] = acc;
      acc += u8len_of(sp.cps[i]);
      ends[i] = acc;
    }
    resume = 0;
  }
};

// One CJK round from `start`: unigram candidates (cap 1000 ->
// next_offset just past the capping char, cldutil.cc:233) into *recs,
// bigram candidates over the round range into *birecs (kept separate so
// the caller's offset merge can order them without sorting). Unigrams
// are pushed RESOLVED (fp=indirect address; fp_hi bit1=word A valid,
// bit0=word B valid — a B-only unigram still consumes an entry rank);
// *n_quota / *n_emit accumulate resolved hits and emitted slots.
int64_t scan_cjk_round(const Span& sp, int64_t start, CjkGeom* gm,
                       std::vector<Rec>* recs, std::vector<Rec>* birecs,
                       int* n_quota, int* n_emit) {
  const int n = (int)sp.cps.size();
  const std::vector<int64_t>& starts = gm->starts;
  const std::vector<int64_t>& ends = gm->ends;
  int64_t next_offset = sp.text_bytes;
  int hits = 0;
  int round_first = gm->resume;
  for (int i = round_first; i < n; i++) {
    uint32_t cp = sp.cps[i] > 0x10FFFF ? 0x10FFFF : sp.cps[i];
    uint8_t prop = g.cjk_prop[cp];
    if (prop > 0 && starts[i] >= start && starts[i] < sp.text_bytes) {
      Resolved rs = resolve_rec({(int32_t)ends[i], UNI, 0, 0, 0, prop});
      if (rs.a || rs.b) {
        recs->push_back({(int32_t)ends[i], UNI, 0,
                         (uint8_t)((rs.a ? 2 : 0) | (rs.b ? 1 : 0)), 1,
                         (uint32_t)rs.ia});
        if (rs.a) {
          (*n_quota)++;
          *n_emit += 1 + (rs.b ? 1 : 0);
        }
      }
      if (++hits >= kMaxScoringHits) {
        next_offset = ends[i];
        gm->resume = i + 1;
        break;
      }
    }
  }
  if (hits < kMaxScoringHits) gm->resume = n;
  int nd = 0, nx = 0;
  for (int i = round_first; i + 1 < n; i++) {
    int64_t len2 = ends[i + 1] - starts[i];
    if (starts[i] >= next_offset) break;
    if (len2 >= 6 && starts[i] >= start) {
      uint32_t fp = bi_hash(sp.buf.data(), starts[i], len2);
      if (nd < kMaxScoringHits) {
        Resolved rs = resolve_rec(
            {(int32_t)starts[i], BI_DELTA, 0, 0, 0, fp});
        if (rs.a) {
          birecs->push_back({(int32_t)starts[i], BI_DELTA, 0, 0, 1,
                             (uint32_t)rs.ia});
          nd++;
          (*n_emit)++;
        }
      }
      if (!g.distinctbi_empty && nx < kMaxScoringHits - 1) {
        Resolved rs = resolve_rec(
            {(int32_t)starts[i], BI_DISTINCT, 0, 0, 0, fp});
        if (rs.a) {
          birecs->push_back({(int32_t)starts[i], BI_DISTINCT, 0, 0, 1,
                             (uint32_t)rs.ia});
          nx++;
          (*n_emit)++;
        }
      }
    }
  }
  return next_offset;
}

// Closed-form ChunkAll boundary rule (ops/score.py _chunk_of_rank;
// scoreonescriptspan.cc:994-1003)
inline int chunk_of_rank(int r, int n_quota, int c) {
  int k_full = n_quota < 2 * c ? 0 : (n_quota - 2 * c) / c + 1;
  int tail = n_quota - k_full * c;
  if (r < k_full * c) return r / c;
  int tr = r - k_full * c;
  bool tail_single = tail < c + (c >> 1);
  int half = (tail + 1) >> 1;
  return k_full + (tail_single ? 0 : (tr >= half ? 1 : 0));
}

// Hint-boost slots (hints engine priors riding the wire): idx values at
// or above kHintBase address the per-batch hint_lp window instead of the
// scoring tables (cat_ind2 ends ~38.8K; seeds sit just above it).
constexpr int kHintBase = 40960;

// Resolved-wire per-doc output views
struct ROut {
  uint16_t* idx;      // [B, L] cat_ind2 indices
  uint16_t* chk;      // [B, L] doc-local chunk ids
  uint32_t* cmeta;    // [B, C] cbytes(16) | grams(12) | side<<28 | real<<29
  uint8_t* cscript;   // [B, C]
  int32_t* direct_adds;
  int32_t* text_bytes;
  uint8_t* fallback;
  uint8_t* squeezed;  // [B] doc took the squeeze re-scan
  int32_t* n_slots;
  int32_t* n_chunks;
  int L, C, D, flags;
  // per-doc hint boosts: window indices into the batch hint_lp table,
  // [2 sides][4 slots], -1 = empty; nullptr = no hints (the common case)
  const int32_t* hint_boost = nullptr;
  // result-vector sidecars (all null unless the caller asked for chunk
  // ranges; never read by the device — they feed the host-side
  // ResultChunkVector builder):
  int32_t* slot_soff = nullptr;  // [L] span-coord offset per slot
                                 //     (-1: boost/hint slot, no offset)
  int32_t* slot_orig = nullptr;  // [L] original-byte offset (-1 boosts)
  int32_t* c_orig_lo = nullptr;  // [C] chunk range in original bytes
  int32_t* c_orig_hi = nullptr;  // [C]
  int32_t* c_rid = nullptr;      // [C] hit round id (-1 direct-add)
  uint8_t* c_isdir = nullptr;    // [C] direct-add (JustOneItem) chunk
};

void pack_resolve_one_doc(const uint8_t* text, int text_len, int b,
                          const ROut& o) {
  // NOTE: worker threads are spawned per batch, so thread_local scratch
  // amortizes over one batch's ~n_docs/n_threads documents (hundreds at
  // service batch sizes), and persists fully on the single-threaded
  // calling-thread path.
  static thread_local SegScratch seg;
  seg.maybe_shrink();
  segment_text(text, text_len, &seg, o.slot_soff != nullptr);

  const int L = o.L, C = o.C;
  uint16_t* idx = o.idx + (int64_t)b * L;
  uint16_t* chk = o.chk + (int64_t)b * L;
  uint32_t* cmeta = o.cmeta + (int64_t)b * C;
  uint8_t* cscript = o.cscript + (int64_t)b * C;
  int32_t* dadds = o.direct_adds + (int64_t)b * o.D * 3;

  // per-chunk accumulators (sized to the per-doc chunk budget; resize
  // to an already-seen size is O(1), and entries zero lazily at
  // allocation — see zero_chunks)
  static thread_local std::vector<int32_t> c_grams, c_lo, c_span_end;
  static thread_local std::vector<int32_t> c_span;  // i32: tier-2 round
                                                    // counts pass 32767
  static thread_local std::vector<int8_t> c_side, c_real, c_dir;
  static thread_local std::vector<int32_t> c_spanix;
  c_grams.resize(C); c_lo.resize(C); c_span_end.resize(C);
  c_span.resize(C); c_side.resize(C); c_real.resize(C);
  const bool want_ranges = o.slot_soff != nullptr;
  if (want_ranges) { c_dir.resize(C); c_spanix.resize(C); }
  int32_t boosts[2][4];
  int bptr[2];
  int slot, chunk_base, n_direct, round_no, open_chunk;
  int64_t total;
  bool ok;
  // scanner outputs, each offset-ordered by construction: brecs = base
  // kinds (QUAD / CJK UNI), wrecs = word kinds (OCTA deltas/distincts/
  // pairs, CJK BI) which outrank base kinds at equal offsets
  static thread_local std::vector<Rec> brecs, wrecs;
  // Repetitive documents restart the whole doc with span squeezing, like
  // the reference's recursive kCLDFlagSqueeze call (impl.cc:1867-1901) —
  // previously such docs fell back to the (much slower) scalar engine.
  // FLAG_SQUEEZE (2) forces it batch-wide; FLAG_REPEATS (4) strips
  // well-predicted words (the gate-failure recursion pass).
  bool squeeze = (o.flags & 2) != 0;
  static thread_local std::vector<int64_t> rep_tbl;
  int rep_hash;

  // Chunk accumulators zero lazily at allocation (zero_chunks below):
  // upfront O(C) init would dominate packing when the per-doc budget is
  // generous (the flat path's C_doc is 16K+ while real docs use ~4).
  auto zero_chunks = [&](int lo, int hi) {
    for (int c = lo; c < hi; c++) {
      c_grams[c] = 0;
      c_lo[c] = 1 << 30; c_span_end[c] = 0;
      c_side[c] = 0; c_real[c] = 0; c_span[c] = -1;
      if (want_ranges) { c_dir[c] = 0; c_spanix[c] = 0; }
    }
  };

restart:
  rep_hash = 0;
  if (o.flags & 4) rep_tbl.assign(kPredictionTableSize, 0);
  // per-doc rotating distinct-boost lists (idx into cat_ind; 0 = empty)
  std::memset(boosts, 0, sizeof(boosts));
  bptr[0] = bptr[1] = 0;
  // round_no uniquely ids each (span, hitbuffer-round): chunk byte
  // ranges chain only within one round (scalar _score_round's end_off)
  slot = 0; chunk_base = 0; n_direct = 0; round_no = 0;
  open_chunk = -1;  // chunk awaiting its boost flush
  total = 0;
  ok = true;

  // emit the pending chunk's boost adds (list state at its last slot):
  // hint priors first, then the rotating distinct boosts (ScoreBoosts
  // order, scoreonescriptspan.cc:125-152 — tote adds commute, whacks
  // apply as a separate device mask)
  auto flush_boosts = [&](int c) {
    if (c < 0 || !c_real[c]) return;
    int side = c_side[c];
    if (o.hint_boost != nullptr) {
      for (int s = 0; s < 4; s++) {
        int w = o.hint_boost[side * 4 + s];
        if (w >= 0 && slot < L) {
          idx[slot] = (uint16_t)(kHintBase + w);
          chk[slot] = (uint16_t)c;
          if (want_ranges) {
            o.slot_soff[slot] = -1;
            o.slot_orig[slot] = -1;
          }
          slot++;
        }
      }
    }
    for (int s = 0; s < 4; s++) {
      if (boosts[side][s] && slot < L) {
        idx[slot] = (uint16_t)boosts[side][s];
        chk[slot] = (uint16_t)c;
        if (want_ranges) {
          o.slot_soff[slot] = -1;
          o.slot_orig[slot] = -1;
        }
        slot++;
      }
    }
  };

  for (int _si = 0; _si < seg.n_spans; _si++) {
    Span& sp = seg.spans[_si];
    if (squeeze) {
      // Remove repetitive or mostly-space chunks (impl.cc:1852-1864)
      squeeze_span(&sp);
    } else if (!(o.flags & 1) &&
               sp.text_bytes > (kSqueezeTestThresh >> 1) &&
               cheap_squeeze_trigger(sp.buf.data(), sp.text_bytes)) {
      // re-scan the whole document with squeezing on
      squeeze = true;
      segment_text(text, text_len, &seg, want_ranges);
      goto restart;
    }
    if (o.flags & 4) {
      // Remove repeated words (impl.cc:1905-1918)
      respan(&sp, cheap_rep_words_inplace(sp.buf.data(), sp.text_bytes,
                                          &rep_hash, rep_tbl.data()));
    }
    total += sp.text_bytes;
    int rtv = sp.ulscript < g.n_scripts ? g.rtype[sp.ulscript] : 0;
    if (rtv == 0 || rtv == 1) {  // RTypeNone/One: direct doc-tote add
      if (n_direct >= o.D || chunk_base >= C) { ok = false; break; }
      dadds[n_direct * 3 + 0] = chunk_base;
      dadds[n_direct * 3 + 1] = g.deflang[sp.ulscript];
      dadds[n_direct * 3 + 2] = sp.text_bytes;
      n_direct++;
      zero_chunks(chunk_base, chunk_base + 1);
      if (want_ranges) {
        // JustOneItem record range: [1, text_bytes) in span coords
        // (scoreonescriptspan.cc:513-548)
        c_dir[chunk_base] = 1;
        c_spanix[chunk_base] = _si;
        c_lo[chunk_base] = 1;
        c_span_end[chunk_base] = sp.text_bytes;
      }
      chunk_base++;
      continue;
    }
    if (sp.text_bytes <= 1) continue;
    const bool cjk = rtv == 3;
    const int chunksize = cjk ? 50 : 20;
    const int side = sp.ulscript == kUlScriptLatin ? 0 : 1;
    const uint32_t seed_lp =
        sp.ulscript < g.n_scripts ? g.seed_lp[sp.ulscript] : 0;

    // hitbuffer rounds of <= 1000 base hits, each with its own seed,
    // repeat caches, and chunk grid (score_span_hits / the reference's
    // fill loops, scoreonescriptspan.cc:1163-1277)
    static thread_local CjkGeom geom;
    if (cjk) geom.init(sp);
    int64_t lo_pos = 1;
    while (lo_pos < sp.text_bytes && ok) {
      brecs.clear();
      wrecs.clear();
      int quota = 0, emit = 0;
      int64_t round_end =
          cjk ? scan_cjk_round(sp, lo_pos, &geom, &brecs, &wrecs,
                               &quota, &emit)
              : scan_quad_round(sp, lo_pos, &brecs, &quota, &emit);
      if (!cjk) scan_word_range(sp, lo_pos, round_end, &wrecs, &emit);
      const bool seed_valid = seed_lp != 0;
      emit += seed_valid;

      // round chunk count from quota (chunk_boundaries grid)
      int round_chunks = quota <= 0 ? 1
          : chunk_of_rank(quota - 1, quota, chunksize) + 1;
      // budget: emitted hits + per-chunk boost flush (4 rotating + up
      // to 4 hint priors when the doc carries hints)
      int per_chunk = o.hint_boost != nullptr ? 8 : 4;
      if (slot + emit + per_chunk * round_chunks > L ||
          chunk_base + round_chunks > C) {
        ok = false;
        break;
      }
      zero_chunks(chunk_base, chunk_base + round_chunks);

      // ---- single merged emission pass: seed first, then the offset
      // merge of the two scanner lists (each offset-ordered by
      // construction; word kinds precede base kinds at equal offsets —
      // the canonical order the per-round stable_sort used to produce).
      // Chunk assignment with device-exact accounting (ops/score.py
      // stages 4-8): entry RANKS consume a+b for base kinds regardless
      // of word-A validity; scores, grams, lo_off, and chunk realness
      // require word A (slot_valid).
      int cum_entries = 0;  // consumed base entries, exclusive
      size_t mb = 0, mw = 0;
      bool on_seed = true;
      while (on_seed || mb < brecs.size() || mw < wrecs.size()) {
        int32_t r_offset, r_ia;
        int8_t r_a, r_b, r_kind;
        if (on_seed) {
          on_seed = false;
          r_offset = (int32_t)lo_pos;
          r_kind = SEED;
          r_a = seed_valid;
          r_b = 0;
          r_ia = rt.seed_ind_base + sp.ulscript;
        } else {
          bool take_w =
              mw < wrecs.size() &&
              (mb >= brecs.size() ||
               wrecs[mw].offset <= brecs[mb].offset);
          const Rec& r = take_w ? wrecs[mw++] : brecs[mb++];
          r_offset = r.offset;
          r_ia = (int32_t)r.fp;
          r_kind = r.kind;
          if (r.kind == UNI) {  // a/b validity in fp_hi (scan_cjk_round)
            r_a = (r.fp_hi >> 1) & 1;
            r_b = r.fp_hi & 1;
          } else {
            r_a = 1;
            r_b = r.kind == QUAD ? (int8_t)(r.fp_hi & 1) : 0;
          }
        }
        bool base_kind = r_kind == SEED || r_kind == QUAD ||
                         r_kind == UNI;
        int contrib = base_kind ? r_a + r_b : 0;
        if (!r_a) {
          cum_entries += contrib;  // UNI word-B rank quirk
          continue;
        }
        int r_excl = cum_entries;
        int rank = quota > 0 ? std::min(r_excl, quota - 1) : 0;
        int local = quota > 0 ? chunk_of_rank(rank, quota, chunksize) : 0;
        int c = chunk_base + local;
        if (c != open_chunk) {
          flush_boosts(open_chunk);
          open_chunk = c;
        }
        idx[slot] = (uint16_t)r_ia;
        chk[slot] = (uint16_t)c;
        if (want_ranges) {
          int32_t orig = -1;
          if (!sp.b2o.empty()) {
            size_t k = r_offset < 0 ? 0 : (size_t)r_offset;
            if (k >= sp.b2o.size()) k = sp.b2o.size() - 1;
            orig = sp.b2o[k];
          }
          o.slot_soff[slot] = r_offset;
          o.slot_orig[slot] = orig;
          if (r_b) {
            o.slot_soff[slot + 1] = r_offset;
            o.slot_orig[slot + 1] = orig;
          }
        }
        slot++;
        if (r_b) {
          idx[slot] = (uint16_t)(r_ia + 1);
          chk[slot] = (uint16_t)c;
          slot++;
        }
        cum_entries += contrib;
        if (base_kind) c_grams[c] += r_a + r_b;
        if (r_offset < c_lo[c]) c_lo[c] = r_offset;
        c_real[c] = 1;
        c_side[c] = (int8_t)side;
        c_span[c] = round_no;
        c_span_end[c] = (int32_t)round_end;
        cscript[c] = (uint8_t)sp.ulscript;
        if (want_ranges) c_spanix[c] = _si;
        // rotating distinct boost (device scan: update AFTER scoring the
        // slot, state read by the chunk containing the slot)
        if (r_kind == DISTINCT_OCTA || r_kind == BI_DISTINCT) {
          boosts[side][bptr[side]] = r_ia;
          bptr[side] = (bptr[side] + 1) & 3;
        }
      }
      // mark allocated-but-empty chunks of this round (runt grids)
      for (int c = chunk_base; c < chunk_base + round_chunks; c++) {
        if (c_span[c] < 0) {
          c_span[c] = round_no;
          c_span_end[c] = (int32_t)round_end;
          c_side[c] = (int8_t)side;
          cscript[c] = (uint8_t)sp.ulscript;
          if (want_ranges) c_spanix[c] = _si;
        }
      }
      chunk_base += round_chunks;
      round_no++;
      if (round_end <= lo_pos) break;  // no forward progress possible
      lo_pos = round_end;
    }
    if (!ok) break;  // fallback doc: skip remaining spans
  }
  flush_boosts(open_chunk);

  // ---- chunk byte ranges: hi = next real chunk's lo (same span) else
  // span_end (device stages 8) ----
  for (int c = 0; c < chunk_base && c < C; c++) {
    if (!c_real[c]) {
      cmeta[c] = 0;
      continue;
    }
    int hi = c_span_end[c];
    if (c + 1 < chunk_base && c_real[c + 1] && c_span[c + 1] == c_span[c])
      hi = c_lo[c + 1];
    int cbytes = hi > c_lo[c] ? hi - c_lo[c] : 0;
    if (cbytes > 0xFFFF) cbytes = 0xFFFF;
    int grams = c_grams[c] > 0xFFF ? 0xFFF : c_grams[c];
    cmeta[c] = (uint32_t)cbytes | ((uint32_t)grams << 16) |
               ((uint32_t)(c_side[c] & 1) << 28) | (1u << 29);
  }
  // result-vector sidecar: per-chunk ranges mapped to ORIGINAL bytes
  // (spans hold their byte->orig maps until the next segment_text)
  if (want_ranges && o.c_orig_lo != nullptr) {
    for (int c = 0; c < chunk_base && c < C; c++) {
      const Span& sps = seg.spans[c_spanix[c]];
      auto mp = [&](int off) -> int32_t {
        if (sps.b2o.empty()) return -1;  // squeezed/respun: unmappable
        size_t k = off < 0 ? 0 : (size_t)off;
        if (k >= sps.b2o.size()) k = sps.b2o.size() - 1;
        return sps.b2o[k];
      };
      int lo, hi;
      if (c_dir[c]) {
        lo = c_lo[c];
        hi = c_span_end[c];
      } else if (c_real[c]) {
        lo = c_lo[c];
        hi = c_span_end[c];
        if (c + 1 < chunk_base && c_real[c + 1] &&
            c_span[c + 1] == c_span[c])
          hi = c_lo[c + 1];
      } else {
        lo = hi = c_span_end[c];  // runt: zero-length at the round end
      }
      o.c_orig_lo[c] = mp(lo);
      o.c_orig_hi[c] = mp(hi);
      o.c_rid[c] = c_dir[c] ? -1 : c_span[c];
      o.c_isdir[c] = c_dir[c];
    }
  }

  // Tails are NOT cleared: every consumer respects the n_slots/n_chunks
  // bounds (the flat compaction copies exactly [0, n_chunks) rows).
  // direct_adds pads with -1 sentinels (the epilogue's stop condition).
  for (int d = n_direct; d < o.D; d++) dadds[d * 3 + 0] = -1;
  o.text_bytes[b] = (int32_t)total;
  o.fallback[b] = !ok;
  o.squeezed[b] = squeeze ? 1 : 0;
  o.n_slots[b] = slot;
  o.n_chunks[b] = chunk_base;
}

// ---- chunk-major ragged pack (the flat wire) ------------------------------
//
// The doc-major dense wire ([B, L] slots + [B, C] chunks) couples device
// program shape to the LONGEST document in a batch: one 60KB doc forces
// L=32768/C=2048 buckets whose [B, C, L] one-hot chunk matmul is quadratic
// in doc length, capping batches at 16 docs. Chunks, however, are
// independent once the packer assigns them (the reference's chunk totes
// are order-free sums, scoreonescriptspan.cc:978-1031; doc aggregation
// :305-315) and the packer emits slots with monotone chunk ids — so the
// flat wire drops the doc axis entirely: all docs' slots concatenate into
// one [N] lane, chunks become rows of a [G, K] grid (K = fattest chunk in
// the batch, <= kMaxChunkSlots), and a long document simply contributes
// more chunk rows. Device cost is linear in total text; batches freely
// mix 100-byte tweets with 100KB documents in ONE dispatch.
//
// Two-phase because the wire is sized by content (total slots/chunks and
// the K bucket are known only after packing): begin() packs every doc via
// pack_resolve_one_doc into thread-local dense scratch and compacts into
// per-thread growing buffers; the caller then sizes/allocates the wire
// and finish() lays it out shard-major and frees the state.

// A chunk holds <= ~20 quads / ~50 CJK unigrams (a+b pairs), trailing
// runt merges (x1.5), interleaved word hits, and a 4-slot boost flush;
// 255 covers every real text with margin (and lets per-chunk slot
// counts ride the wire as u8). Fatter chunks (adversarial
// constructions) route the doc to the scalar fallback.
constexpr int kMaxChunkSlots = 255;

struct FlatThreadBuf {
  std::vector<uint16_t> idx;     // resolved slots, concat over this
                                 // thread's docs
  std::vector<uint16_t> cnsl;    // per-chunk slot count
  std::vector<uint32_t> cmeta;   // per-chunk meta (ROut layout)
  std::vector<uint8_t> cscript;  // per-chunk ULScript
  // result-vector sidecars (filled only in want_ranges packs)
  std::vector<int32_t> soff, sorig;          // per slot
  std::vector<int32_t> clo, chi, crid;       // per chunk
  std::vector<uint8_t> cdir;                 // per chunk
};

struct FlatPackState {
  int B = 0;
  std::vector<FlatThreadBuf> bufs;
  std::vector<int32_t> doc_buf;        // thread-buffer index per doc
  std::vector<int64_t> doc_slot_off;   // doc's slot offset in its buffer
  std::vector<int64_t> doc_chunk_off;  // doc's chunk offset in its buffer
};

// ---- C ABI detection (wrapper.h:8 seam) -----------------------------------
//
// A cgo/Go host links this library and calls detect_language() /
// ldt_detect_batch_codes() with no Python in the loop: the chunk scorer
// below is a bit-exact C twin of the device program (ops/score.py
// score_chunks_impl — same integer decode/tote/top-2/reliability math),
// and the document epilogue is the existing ldt_epilogue_flat. Tables
// arrive via ldt_init_tables + ldt_init_detect (today driven by the
// Python runtime; the mmap artifact loader can drive them C-only).

struct DetectCtx {
  const uint8_t* lg_prob3 = nullptr;        // [256, 3] (padded from 240)
  const int32_t* plang_to_lang = nullptr;   // [2, 256]
  const int32_t* expected_score = nullptr;  // [n_lang, 4]
  const int32_t* close_set = nullptr;       // [n_lang]
  const int32_t* closest_alt = nullptr;     // [n_lang]
  const uint8_t* is_figs = nullptr;         // [n_lang]
  const char* codes = nullptr;              // [n_lang, code_stride]
  int32_t n_lang = 0;
  int32_t code_stride = 0;
  bool ready = false;
};
DetectCtx dctx;

inline int lscript4_of(int script) {
  return script == 1 ? 0 : script == 3 ? 1 : script == 6 ? 2 : 3;
}

// cldutil.cc:553-570 (ops/score.py _reliability_delta)
inline int c_rel_delta(int s1, int s2, int grams) {
  int maxp = grams < 8 ? 12 * grams : 100;
  int thresh = (grams * 5) >> 3;
  thresh = thresh < 3 ? 3 : thresh > 16 ? 16 : thresh;
  int delta = s1 - s2;
  if (delta >= thresh) return maxp;
  if (delta <= 0) return 0;
  int pct = (100 * delta) / thresh;
  return pct < maxp ? pct : maxp;
}

// cldutil.cc:587-605 (ops/score.py _reliability_expected; f32 math)
inline int c_rel_expected(int actual, int expected) {
  if (actual == 0) return expected == 0 ? 100 : 0;
  float hi = (float)(actual > expected ? actual : expected);
  float lo = (float)(actual < expected ? actual : expected);
  float ratio = hi / (lo > 1.0f ? lo : 1.0f);
  int pct = (int)(100.0f * (4.0f - ratio) / 2.5f);
  if (ratio <= 1.5f) pct = 100;
  else if (ratio > 4.0f) pct = 0;
  if (expected == 0) pct = 100;
  return pct;
}

// Score the chunk rows of one packed doc into [nc, 5] epilogue rows
// (lang1, cbytes, score1, rel, real) — the C twin of the device scorer.
void score_chunks_host(const uint16_t* idx, const uint16_t* chk, int ns,
                       int nc, const uint32_t* cmeta,
                       const uint8_t* cscript, int32_t* rows) {
  static thread_local std::vector<int32_t> scores;
  // one tier-2 adversarial doc would otherwise pin ~64MB per thread
  if (scores.capacity() > (size_t)(8 << 20))
    std::vector<int32_t>().swap(scores);
  scores.assign((size_t)nc * 256, 0);
  for (int i = 0; i < ns; i++) {
    uint32_t lp = rt.cat_ind[idx[i]];
    int row = lp & 0xFF;
    int c = chk[i];
    int32_t* sc = scores.data() + (size_t)c * 256;
    for (int j = 0; j < 3; j++) {
      int ps = (lp >> (8 * (j + 1))) & 0xFF;
      if (ps > 0) sc[ps] += dctx.lg_prob3[row * 3 + j];
    }
  }
  for (int c = 0; c < nc; c++) {
    const int32_t* sc = scores.data() + (size_t)c * 256;
    uint32_t cm = cmeta[c];
    int cbytes = cm & 0xFFFF;
    int grams = (cm >> 16) & 0xFFF;
    int side = (cm >> 28) & 1;
    int real = (cm >> 29) & 1;
    // group-in-use top-2 (tote.cc:30-100 semantics)
    int k1 = -1, k2 = -1;
    int64_t top1 = -1, top2 = -1;
    for (int gi = 0; gi < 64; gi++) {
      bool in_use = sc[gi * 4] > 0 || sc[gi * 4 + 1] > 0 ||
                    sc[gi * 4 + 2] > 0 || sc[gi * 4 + 3] > 0;
      if (!in_use) continue;
      for (int k = gi * 4; k < gi * 4 + 4; k++) {
        int64_t key = (int64_t)sc[k] * 256 + (255 - k);
        if (key > top1) {
          top2 = top1; k2 = k1;
          top1 = key; k1 = k;
        } else if (key > top2) {
          top2 = key; k2 = k;
        }
      }
    }
    int s1 = top1 >= 0 ? (int)(top1 >> 8) : 0;
    int s2 = top2 >= 0 ? (int)(top2 >> 8) : 0;
    if (k1 < 0) k1 = 0;
    if (k2 < 0) k2 = 0;
    int lang1 = dctx.plang_to_lang[side * 256 + k1];
    int lang2 = dctx.plang_to_lang[side * 256 + k2];
    int actual_kb = cbytes > 0 ? (s1 << 10) / cbytes : 0;
    int expected_kb =
        dctx.expected_score[lang1 * 4 + lscript4_of(cscript[c])];
    int rd = c_rel_delta(s1, s2, grams);
    int cs1 = dctx.close_set[lang1];
    if (cs1 != 0 && cs1 == dctx.close_set[lang2]) rd = 100;
    int rs = c_rel_expected(actual_kb, expected_kb);
    int rel = rd < rs ? rd : rs;
    // device wire clips (OUTW packing): keep bit-for-bit agreement
    if (s1 > 0x3FFF) s1 = 0x3FFF;
    if (rel < 0) rel = 0;
    if (rel > 127) rel = 127;
    rows[c * 5 + 0] = lang1;
    rows[c * 5 + 1] = cbytes;
    rows[c * 5 + 2] = s1;
    rows[c * 5 + 3] = rel;
    rows[c * 5 + 4] = real;
  }
}

constexpr int kCabiFlagFinish = 1;
constexpr int kCabiFlagSqueeze = 2;
constexpr int kCabiFlagRepeats = 4;
constexpr int kCabiFlagTop40 = 8;
constexpr int kCabiUnknown = 26;  // UNKNOWN_LANGUAGE

}  // namespace

extern "C" {

// Bumped on ANY change to the exported function signatures or wire
// layouts; the Python loader refuses (and rebuilds) on mismatch so a
// stale .so can never silently corrupt results across an ABI change.
int32_t ldt_abi_version() { return 10; }

// Phase 1: pack + compact. Per-doc outputs (direct_adds [B, D_cap, 3],
// text_bytes/fallback/squeezed/n_slots/n_chunks [B]) land in caller
// arrays; slots and chunk meta stay in C++-owned buffers until finish().
// Fallback docs report 0 slots/chunks (they resolve via the scalar
// engine, so nothing of theirs belongs on the wire). Returns an opaque
// handle; *max_chunk_nsl gets the fattest chunk's slot count (the
// caller's K bucket). L_doc/C_doc are per-doc scratch budgets —
// generosity costs thread-local scratch only, not wire.
int64_t ldt_pack_flat_begin(
    const uint8_t* texts, const int64_t* bounds, int32_t n_docs,
    int32_t L_doc, int32_t C_doc, int32_t D_cap, int32_t flags,
    int32_t n_threads, int32_t want_ranges,
    const int32_t* hint_boost,  // [B, 2, 4] hint-window indices, or null
    int32_t* direct_adds, int32_t* text_bytes, uint8_t* fallback,
    uint8_t* squeezed, int32_t* n_slots, int32_t* n_chunks,
    int32_t* max_chunk_nsl) {
  FlatPackState* st = new FlatPackState;
  st->B = n_docs;
  st->doc_buf.assign(n_docs, 0);
  st->doc_slot_off.assign(n_docs, 0);
  st->doc_chunk_off.assign(n_docs, 0);
  if (!rt_ready) {
    for (int b = 0; b < n_docs; b++) {
      fallback[b] = 1;
      squeezed[b] = 0;
      n_slots[b] = 0;
      n_chunks[b] = 0;
      text_bytes[b] = 0;
      for (int d = 0; d < D_cap; d++)
        direct_adds[((int64_t)b * D_cap + d) * 3] = -1;
    }
    st->bufs.resize(1);
    *max_chunk_nsl = 0;
    return (int64_t)(intptr_t)st;
  }
  int nt = n_threads;
  if (nt <= 1 || n_docs < 2 * nt) nt = 1;
  st->bufs.resize(nt);
  std::vector<int32_t> tmax(nt, 0);

  auto work = [&](int t, int lo, int hi) {
    FlatThreadBuf& tb = st->bufs[t];
    static thread_local std::vector<uint16_t> sidx, schk;
    static thread_local std::vector<uint32_t> scmeta;
    static thread_local std::vector<uint8_t> scscript;
    static thread_local std::vector<int32_t> counts;
    static thread_local std::vector<int32_t> ssoff, ssorig, sclo, schi,
        scrid;
    static thread_local std::vector<uint8_t> scdir;
    sidx.resize(L_doc);
    schk.resize(L_doc);
    scmeta.resize(C_doc);
    scscript.resize(C_doc);
    if (want_ranges) {
      ssoff.resize(L_doc);
      ssorig.resize(L_doc);
      sclo.resize(C_doc);
      schi.resize(C_doc);
      scrid.resize(C_doc);
      scdir.resize(C_doc);
    }
    for (int b = lo; b < hi; b++) {
      // per-doc views: scratch for slot/chunk lanes (b=0 addressing),
      // caller rows for everything per-doc
      ROut o{sidx.data(), schk.data(), scmeta.data(), scscript.data(),
             direct_adds + (int64_t)b * D_cap * 3, text_bytes + b,
             fallback + b, squeezed + b, n_slots + b, n_chunks + b,
             L_doc, C_doc, D_cap, flags,
             hint_boost ? hint_boost + (int64_t)b * 8 : nullptr};
      if (want_ranges) {
        o.slot_soff = ssoff.data();
        o.slot_orig = ssorig.data();
        o.c_orig_lo = sclo.data();
        o.c_orig_hi = schi.data();
        o.c_rid = scrid.data();
        o.c_isdir = scdir.data();
      }
      pack_resolve_one_doc(texts + bounds[b],
                           (int)(bounds[b + 1] - bounds[b]), 0, o);
      st->doc_buf[b] = t;
      st->doc_slot_off[b] = (int64_t)tb.idx.size();
      st->doc_chunk_off[b] = (int64_t)tb.cnsl.size();
      int ns = n_slots[b], nc = n_chunks[b];
      if (!fallback[b] && nc > 0) {
        counts.assign(nc, 0);
        for (int i = 0; i < ns; i++) counts[schk[i]]++;
        int mx = 0;
        for (int c = 0; c < nc; c++) mx = std::max(mx, counts[c]);
        if (mx > kMaxChunkSlots) fallback[b] = 1;  // adversarial chunk
        else {
          if (mx > tmax[t]) tmax[t] = mx;
          tb.idx.insert(tb.idx.end(), sidx.begin(), sidx.begin() + ns);
          for (int c = 0; c < nc; c++)
            tb.cnsl.push_back((uint16_t)counts[c]);
          tb.cmeta.insert(tb.cmeta.end(), scmeta.begin(),
                          scmeta.begin() + nc);
          tb.cscript.insert(tb.cscript.end(), scscript.begin(),
                            scscript.begin() + nc);
          if (want_ranges) {
            tb.soff.insert(tb.soff.end(), ssoff.begin(),
                           ssoff.begin() + ns);
            tb.sorig.insert(tb.sorig.end(), ssorig.begin(),
                            ssorig.begin() + ns);
            tb.clo.insert(tb.clo.end(), sclo.begin(), sclo.begin() + nc);
            tb.chi.insert(tb.chi.end(), schi.begin(), schi.begin() + nc);
            tb.crid.insert(tb.crid.end(), scrid.begin(),
                           scrid.begin() + nc);
            tb.cdir.insert(tb.cdir.end(), scdir.begin(),
                           scdir.begin() + nc);
          }
        }
      }
      if (fallback[b]) {
        n_slots[b] = 0;
        n_chunks[b] = 0;
      }
    }
  };
  if (nt == 1) {
    work(0, 0, n_docs);
  } else {
    std::vector<std::thread> ts;
    int per = (n_docs + nt - 1) / nt;
    for (int t = 0; t < nt; t++) {
      int lo = t * per, hi = std::min(n_docs, lo + per);
      if (lo >= hi) break;
      ts.emplace_back(work, t, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  int mx = 0;
  for (int t = 0; t < nt; t++) mx = std::max(mx, tmax[t]);
  *max_chunk_nsl = mx;
  return (int64_t)(intptr_t)st;
}

// Free a begin() handle without laying out the wire (error-path cleanup:
// the caller could not allocate the wire arrays, or was interrupted).
void ldt_pack_flat_free(int64_t handle) {
  delete (FlatPackState*)(intptr_t)handle;
}

// Scoring/epilogue tables for the C-only detection path. Pointers must
// outlive detection calls (the Python runtime pins them; a C host keeps
// the artifact mapped).
void ldt_init_detect(const uint8_t* lg_prob3, const int32_t* plang_to_lang,
                     const int32_t* expected_score,
                     const int32_t* close_set, const int32_t* closest_alt,
                     const uint8_t* is_figs, int32_t n_lang,
                     const char* codes, int32_t code_stride) {
  dctx = DetectCtx{lg_prob3, plang_to_lang, expected_score, close_set,
                   closest_alt, is_figs, codes, n_lang, code_stride, true};
}

// Scoring subset cap, mirroring the reference's 160KB-per-document
// subsetting (compact_lang_det_impl.h:159-161, impl.cc:192): detection
// quality saturates long before this, and the cap is what lets the
// budget ladder below GUARANTEE an answer for any input.
constexpr int32_t kCabiMaxScoreBytes = 160 << 10;

// One full C-side detection: pack -> score -> epilogue, plus the
// reference's gate-failure recursion (impl.cc:2061-2105) as a second
// pass with the recursion flags. Fills the 14-lane epilogue row
// (ldt_epilogue_flat contract). Documents that overflow the default
// per-doc budgets retry once with a large tier instead of giving up —
// the reference's wrapper never answers "un" for mere size
// (wrapper.cc:7-16): 512K slots / 64K chunks / 64K direct adds cover
// every real 160KB-capped document (~3 resolved hits per 6-byte word
// plus per-chunk boost flushes < 512K slots; a chunk or direct add
// needs a fresh hit round or a script flip).
static bool detect_one_row(const uint8_t* text, int32_t len,
                           int64_t* out) {
  if (!rt_ready || !dctx.ready) return false;
  if (len > kCabiMaxScoreBytes) len = kCabiMaxScoreBytes;
  static thread_local std::vector<uint16_t> sidx, schk;
  static thread_local std::vector<uint32_t> scmeta;
  static thread_local std::vector<uint8_t> scscript;
  static thread_local std::vector<int32_t> rows, dadds;
  struct Tier { int L, C, D; };
  // The chunk-id lane is u16 (ROut.chk), so no tier may budget more
  // than 1<<16 chunks; 64K chunks need >32K script alternations inside
  // the 160KB cap, so only adversarial constructions exceed tier 2 —
  // those return false here (the Python caller falls back to the
  // scalar engine; the raw C ABI answers "un").
  const Tier tiers[2] = {{1 << 17, 1 << 14, 64},
                         {1 << 19, 1 << 16, 1 << 16}};
  for (const Tier& bud : tiers) {
    sidx.resize(bud.L);
    schk.resize(bud.L);
    scmeta.resize(bud.C);
    scscript.resize(bud.C);
    dadds.resize((size_t)bud.D * 3);
    int32_t text_bytes = 0, n_slots = 0, n_chunks = 0;
    uint8_t fallback = 0, squeezed = 0;
    int flags = 0;
    for (int pass = 0; pass < 2; pass++) {
      ROut o{sidx.data(), schk.data(), scmeta.data(), scscript.data(),
             dadds.data(), &text_bytes, &fallback, &squeezed, &n_slots,
             &n_chunks, bud.L, bud.C, bud.D, flags};
      pack_resolve_one_doc(text, len, 0, o);
      if (fallback) break;  // budget overflow: try the large tier
      rows.assign((size_t)n_chunks * 5, 0);
      score_chunks_host(sidx.data(), schk.data(), n_slots, n_chunks,
                        scmeta.data(), scscript.data(), rows.data());
      int64_t dcs = 0;
      uint8_t skip = 0;
      ldt_epilogue_flat(rows.data(), &dcs, &n_chunks, dadds.data(),
                        &text_bytes, &skip, 1, bud.D, flags,
                        dctx.close_set, dctx.closest_alt, dctx.is_figs,
                        dctx.n_lang, out);
      if (!out[12]) return true;
      // good-answer gate failed: one recursion pass (FINISH forces it)
      flags = kCabiFlagTop40 | kCabiFlagRepeats | kCabiFlagFinish |
              (squeezed ? kCabiFlagSqueeze : 0);
    }
  }
  return false;  // adversarial: >64K chunks inside the 160KB cap
}

static int32_t detect_one_c(const uint8_t* text, int32_t len) {
  int64_t out[14];
  if (!detect_one_row(text, len, out)) return kCabiUnknown;
  return (int32_t)out[0];
}

// The reference seam (wrapper.h:8 / wrapper.cc:7-16): NUL-terminated
// UTF-8 in, static ISO-639 code string out, no allocation. The returned
// pointer is thread-local and valid until this thread's next call.
const char* detect_language(const char* src) {
  if (src == nullptr || !dctx.ready) return "un";
  int32_t lang = detect_one_c((const uint8_t*)src,
                              (int32_t)strlen(src));
  if (lang < 0 || lang >= dctx.n_lang) lang = kCabiUnknown;
  return dctx.codes + (size_t)lang * dctx.code_stride;
}

// Length-taking twin of detect_language (embedded NULs are legal in
// the length-delimited contract; the NUL-terminated seam cannot carry
// them). Same static-string return semantics.
const char* detect_language_n(const char* src, int32_t len) {
  if (src == nullptr || len < 0 || !dctx.ready) return "un";
  int32_t lang = detect_one_c((const uint8_t*)src, len);
  if (lang < 0 || lang >= dctx.n_lang) lang = kCabiUnknown;
  return dctx.codes + (size_t)lang * dctx.code_stride;
}

// Full 14-lane epilogue row for one document (the richer
// ExtDetectLanguageSummary surface, compact_lang_det.h:168-426, over
// the C pipeline): summary lang, top-3 languages / percents /
// normalized scores, text bytes, reliability. Returns 1 on success.
int32_t ldt_detect_one_full(const uint8_t* text, int32_t len,
                            int64_t* out14) {
  if (text == nullptr || out14 == nullptr || len < 0) return 0;
  if (!detect_one_row(text, len, out14)) {
    for (int i = 0; i < 14; i++) out14[i] = 0;
    out14[0] = kCabiUnknown;
    return 0;
  }
  return 1;
}

// Batched variant: concatenated UTF-8 docs + bounds, language ids out.
// Thread-parallel like the packer (each doc is independent).
void ldt_detect_batch_codes(const uint8_t* texts, const int64_t* bounds,
                            int32_t n_docs, int32_t n_threads,
                            int32_t* lang_out) {
  auto work = [&](int lo, int hi) {
    for (int b = lo; b < hi; b++)
      lang_out[b] = detect_one_c(texts + bounds[b],
                                 (int32_t)(bounds[b + 1] - bounds[b]));
  };
  if (n_threads <= 1 || n_docs < 2 * n_threads) {
    work(0, n_docs);
    return;
  }
  std::vector<std::thread> ts;
  int per = (n_docs + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    int lo = t * per, hi = std::min(n_docs, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// Phase 2: lay the packed content out shard-major and free the state.
// Shard d takes docs [d*B/D, (d+1)*B/D); within a shard, slots and
// chunks concatenate in doc order (chunk_start is shard-local so the
// device program is identical on every shard). doc_chunk_start[b] is
// the doc's first chunk row in the flattened [D*Gs] grid (the epilogue's
// map back from chunk rows to documents). Tails beyond each shard's
// content are zeroed: cnsl=0 rows are dead on device (masked) and in
// the epilogue (real bit 0).
void ldt_pack_flat_finish(
    int64_t handle, int32_t B, int32_t D, int32_t N, int32_t Gs,
    const int32_t* n_slots, const int32_t* n_chunks,
    const int32_t* doc_whack_row,  // [B] whack-table rows, or null
    uint16_t* idx_flat, uint8_t* cnsl_flat,
    uint32_t* cmeta_flat, uint8_t* cscript_flat, uint16_t* cwhack_flat,
    int64_t* doc_chunk_start,
    // result-vector sidecars, [D,N] / [D,Gs] like the wire lanes; all
    // null unless the pack ran with want_ranges (host-only — never
    // shipped to the device)
    int32_t* soff_flat, int32_t* sorig_flat, int32_t* clo_flat,
    int32_t* chi_flat, int32_t* crid_flat, uint8_t* cdir_flat) {
  // No chunk-start lane on the wire: slots concatenate in chunk order,
  // so the device derives starts as an exclusive cumsum of cnsl.
  // cwhack_flat may be null (hint-free batches carry a 1-wide dummy).
  FlatPackState* st = (FlatPackState*)(intptr_t)handle;
  int Bd = B / D;
  const bool ranges = soff_flat != nullptr;
  for (int d = 0; d < D; d++) {
    int64_t spos = 0, gpos = 0;
    for (int i = 0; i < Bd; i++) {
      int b = d * Bd + i;
      const FlatThreadBuf& tb = st->bufs[st->doc_buf[b]];
      int ns = n_slots[b], nc = n_chunks[b];
      std::memcpy(idx_flat + (int64_t)d * N + spos,
                  tb.idx.data() + st->doc_slot_off[b],
                  (size_t)ns * sizeof(uint16_t));
      if (ranges && !tb.soff.empty()) {
        std::memcpy(soff_flat + (int64_t)d * N + spos,
                    tb.soff.data() + st->doc_slot_off[b],
                    (size_t)ns * sizeof(int32_t));
        std::memcpy(sorig_flat + (int64_t)d * N + spos,
                    tb.sorig.data() + st->doc_slot_off[b],
                    (size_t)ns * sizeof(int32_t));
      }
      doc_chunk_start[b] = (int64_t)d * Gs + gpos;
      int64_t src = st->doc_chunk_off[b];
      int64_t dst = (int64_t)d * Gs + gpos;
      uint16_t wrow = doc_whack_row ? (uint16_t)doc_whack_row[b] : 0;
      for (int c = 0; c < nc; c++) {
        cnsl_flat[dst + c] = (uint8_t)tb.cnsl[src + c];
        cmeta_flat[dst + c] = tb.cmeta[src + c];
        cscript_flat[dst + c] = tb.cscript[src + c];
        if (cwhack_flat) cwhack_flat[dst + c] = wrow;
        if (ranges && !tb.clo.empty()) {
          clo_flat[dst + c] = tb.clo[src + c];
          chi_flat[dst + c] = tb.chi[src + c];
          crid_flat[dst + c] = tb.crid[src + c];
          cdir_flat[dst + c] = tb.cdir[src + c];
        }
      }
      spos += ns;
      gpos += nc;
    }
    for (int64_t g = gpos; g < Gs; g++) {
      int64_t dst = (int64_t)d * Gs + g;
      cnsl_flat[dst] = 0;
      cmeta_flat[dst] = 0;
      cscript_flat[dst] = 0;
      if (cwhack_flat) cwhack_flat[dst] = 0;
      if (ranges) {
        clo_flat[dst] = chi_flat[dst] = -1;
        crid_flat[dst] = -1;
        cdir_flat[dst] = 0;
      }
    }
  }
  delete st;
}

// Table geometry + data for host-side resolution. Pointers are owned by
// Python (DeviceTables host copies) and must outlive packing calls.
void ldt_init_tables(const uint32_t* cat_buckets, const uint32_t* cat_ind,
                     int64_t n_ind, const int64_t* bucket_off,
                     const uint32_t* size, const uint32_t* keymask,
                     const int32_t* ind_off, const int32_t* size_one,
                     const uint8_t* probes, int64_t q2_bucket_off,
                     uint32_t q2_size, uint32_t q2_keymask,
                     int32_t q2_ind_off, int32_t q2_size_one,
                     int32_t q2_enabled, int32_t seed_ind_base) {
  rt.cat_buckets = cat_buckets;
  rt.cat_ind = cat_ind;
  rt.n_ind = n_ind;
  for (int k = 0; k < 8; k++) {
    rt.bucket_off[k] = bucket_off[k];
    rt.size[k] = size[k];
    rt.keymask[k] = keymask[k];
    rt.ind_off[k] = ind_off[k];
    rt.size_one[k] = size_one[k];
    rt.probes[k] = probes[k];
  }
  rt.q2_bucket_off = q2_bucket_off;
  rt.q2_size = q2_size;
  rt.q2_keymask = q2_keymask;
  rt.q2_ind_off = q2_ind_off;
  rt.q2_size_one = q2_size_one;
  rt.q2_enabled = q2_enabled;
  rt.seed_ind_base = seed_ind_base;
  rt_ready = true;
}

void ldt_init(const uint8_t* script_of_cp, const uint32_t* lower_map,
              const uint8_t* cjk_prop, const int32_t* rtype,
              const int32_t* deflang, const uint32_t* seed_lp,
              int32_t n_scripts, int32_t distinctbi_empty) {
  g = Ctx{script_of_cp, lower_map, cjk_prop, rtype, deflang, seed_lp,
          n_scripts, distinctbi_empty};
}

// texts: concatenated UTF-8 docs; bounds[i]..bounds[i+1] delimit doc i.
void ldt_pack_batch(const uint8_t* texts, const int64_t* bounds,
                    int32_t n_docs, int32_t L, int32_t C, int32_t D,
                    int32_t flags, int32_t n_threads,
                    int8_t* kind, int32_t* offset, uint32_t* fp,
                    uint8_t* fp_hi,
                    int32_t* chunk_base, int32_t* span_start,
                    int32_t* span_end_off, int8_t* side, int8_t* cjk,
                    int16_t* script, int16_t* chunk_script,
                    int8_t* chunk_cjk, int8_t* chunk_side,
                    int32_t* chunk_span_end,
                    int32_t* direct_adds, int32_t* text_bytes,
                    uint8_t* fallback, int32_t* n_slots,
                    int32_t* n_chunks) {
  Out o{kind, offset, fp, fp_hi, chunk_base, span_start,
        span_end_off, side, cjk, script, chunk_script, chunk_cjk,
        chunk_side, chunk_span_end, direct_adds, text_bytes, fallback,
        n_slots, n_chunks, L, C, D, flags};
  auto work = [&](int lo, int hi) {
    for (int b = lo; b < hi; b++)
      pack_one_doc(texts + bounds[b], (int)(bounds[b + 1] - bounds[b]), b,
                   o);
  };
  if (n_threads <= 1 || n_docs < 2 * n_threads) {
    work(0, n_docs);
    return;
  }
  std::vector<std::thread> ts;
  int per = (n_docs + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    int lo = t * per, hi = std::min(n_docs, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
