#!/bin/bash
# Build the parity-oracle shared library (reference CLD2 with stubbed quad
# tables) for use by tests via ctypes. Output: tools/oracle/libcld2_oracle.so
set -euo pipefail
cd "$(dirname "$0")"

REF=/root/reference/cld2
CXXFLAGS="-O2 -w -fPIC -I$REF/internal -I$REF/public"

# Same library file list as the reference's compile_libs.sh full build, with
# debug_empty instead of debug and quad_stub.cc standing in for the two
# quadgram table files missing from the snapshot.
g++ $CXXFLAGS -shared \
  shim.cc quad_stub.cc \
  $REF/internal/cldutil.cc $REF/internal/cldutil_shared.cc \
  $REF/internal/compact_lang_det.cc \
  $REF/internal/compact_lang_det_hint_code.cc \
  $REF/internal/compact_lang_det_impl.cc \
  $REF/internal/debug_empty.cc \
  $REF/internal/fixunicodevalue.cc \
  $REF/internal/generated_entities.cc \
  $REF/internal/generated_language.cc \
  $REF/internal/generated_ulscript.cc \
  $REF/internal/getonescriptspan.cc \
  $REF/internal/lang_script.cc \
  $REF/internal/offsetmap.cc \
  $REF/internal/scoreonescriptspan.cc \
  $REF/internal/tote.cc \
  $REF/internal/utf8statetable.cc \
  $REF/internal/cld_generated_cjk_uni_prop_80.cc \
  $REF/internal/cld2_generated_cjk_compatible.cc \
  $REF/internal/cld_generated_cjk_delta_bi_32.cc \
  $REF/internal/generated_distinct_bi_0.cc \
  $REF/internal/cld2_generated_deltaocta0527.cc \
  $REF/internal/cld2_generated_distinctocta0527.cc \
  $REF/internal/cld_generated_score_quad_octa_1024_256.cc \
  -o libcld2_oracle.so

echo "built $(pwd)/libcld2_oracle.so"
