#!/usr/bin/env python3
"""HTTP-path throughput benchmark: docs/sec through POST / end-to-end.

Starts the real service in-process (device engine + batcher + the
reference's JSON contract, service/server.py), drives it with concurrent
keep-alive HTTP clients, and reports end-to-end docs/sec — the number the
reference actually shipped (its Go layer logged throughput per 1000
objects, main.go:209-218, but never published one). Results feed
docs/PERF.md.

Usage: bench_service.py [total_docs] [clients] [docs_per_request]
       bench_service.py --aio [total_docs] [clients] [docs_per_request]
Prints one JSON line. --aio benches the asyncio server (the single-core
production front) with a same-loop asyncio load generator; the default
benches the threaded server with threaded clients.
"""
from __future__ import annotations

import http.client
import json
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from language_detector_tpu import enable_jit_cache  # noqa: E402

enable_jit_cache()


def run(total_docs: int = 98304, clients: int = 8,
        docs_per_request: int = 512) -> dict:
    from bench import make_corpus
    from language_detector_tpu.service.server import (DetectorService,
                                                      make_server)

    svc = DetectorService(use_device=True, max_delay_ms=4.0)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    port = httpd.server_address[1]

    docs = make_corpus(total_docs)
    n_requests = total_docs // docs_per_request
    payloads = []
    for r in range(n_requests):
        chunk = docs[r * docs_per_request:(r + 1) * docs_per_request]
        payloads.append(json.dumps(
            {"request": [{"text": d} for d in chunk]}).encode())

    # warm-up: compile the device programs on a small request
    warm = json.dumps({"request": [{"text": d}
                                   for d in docs[:256]]}).encode()
    conn = http.client.HTTPConnection("127.0.0.1", port)
    conn.request("POST", "/", warm,
                 {"Content-Type": "application/json"})
    conn.getresponse().read()
    conn.close()

    results = {"docs": 0, "errors": 0}
    lock = threading.Lock()
    work = list(enumerate(payloads))
    widx = [0]

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", port)
        got, errs = 0, 0
        while True:
            with lock:
                if widx[0] >= len(work):
                    break
                _, payload = work[widx[0]]
                widx[0] += 1
            conn.request("POST", "/", payload,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status in (200, 203):
                # byte count instead of a JSON parse: the client runs on
                # the same single core as the server, so client-side
                # parsing steals serve-side throughput
                got += body.count(b'"iso6391code"')
            else:
                errs += 1
        conn.close()
        with lock:
            results["docs"] += got
            results["errors"] += errs

    threads = [threading.Thread(target=client) for _ in range(clients)]
    t0 = time.time()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    took = time.time() - t0

    httpd.shutdown()
    svc.batcher.close()
    docs_sec = results["docs"] / took
    return dict(
        metric="service_http_throughput",
        value=round(docs_sec, 1),
        unit="docs/sec",
        detail=dict(total_docs=results["docs"], errors=results["errors"],
                    clients=clients, docs_per_request=docs_per_request,
                    took_sec=round(took, 2)),
    )


def run_aio(total_docs: int = 98304, clients: int = 32,
            docs_per_request: int = 512) -> dict:
    """Bench the asyncio server: server + clients share one event loop
    (and the one CPU core), no thread thrash."""
    import asyncio

    from bench import make_corpus
    from language_detector_tpu.service.aioserver import serve
    from language_detector_tpu.service.server import DetectorService

    docs = make_corpus(total_docs)
    n_requests = total_docs // docs_per_request
    payloads = []
    for r in range(n_requests):
        chunk = docs[r * docs_per_request:(r + 1) * docs_per_request]
        body = json.dumps(
            {"request": [{"text": d} for d in chunk]}).encode()
        payloads.append(
            b"POST / HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % len(body) + body)

    async def client(port, work, results):
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", port, limit=1 << 22)
        sock = writer.get_extra_info("socket")
        import socket as _s
        sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        while work:
            payload = work.pop()
            writer.write(payload)
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            length = int(head.lower().split(b"content-length:")[1]
                         .split(b"\r\n")[0])
            body = await reader.readexactly(length)
            status = int(head.split(b" ")[1])
            if status in (200, 203):
                results["docs"] += body.count(b'"iso6391code"')
            else:
                results["errors"] += 1
        writer.close()

    async def main():
        svc = DetectorService(use_device=True, max_delay_ms=4.0,
                              start_batcher=False)
        ready = asyncio.get_running_loop().create_future()
        server_task = asyncio.create_task(
            serve(0, 0, svc=svc, ready=ready))
        port, _ = await ready

        async def one_pass():
            results = {"docs": 0, "errors": 0}
            work = list(payloads)
            t0 = time.time()
            await asyncio.gather(*[client(port, work, results)
                                   for _ in range(clients)])
            return results, time.time() - t0

        # Cold pass first (compiles + first-flush shapes land inside it;
        # reported as cold_docs_sec), then the warm timed pass. Sequential
        # small warm-ups are NOT enough: the full-size flush shapes only
        # appear under concurrent load, so a cold "warmed" window used to
        # pay them and read ~40% low.
        cold_results, cold_took = await one_pass()
        results, took = await one_pass()
        server_task.cancel()
        return results, took, cold_results, cold_took

    results, took, cold_results, cold_took = asyncio.run(main())
    docs_sec = results["docs"] / took
    return dict(
        metric="service_http_throughput_aio",
        value=round(docs_sec, 1),
        unit="docs/sec",
        detail=dict(total_docs=results["docs"], errors=results["errors"],
                    clients=clients, docs_per_request=docs_per_request,
                    took_sec=round(took, 2),
                    cold_docs_sec=round(
                        cold_results["docs"] / cold_took, 1),
                    cold_errors=cold_results["errors"]),
    )


if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "--aio":
        print(json.dumps(run_aio(*[int(a) for a in argv[1:]])))
    else:
        print(json.dumps(run(*[int(a) for a in argv])))
