"""Seeded synthetic load model for replay benchmarking and autotuning.

The PR 17 capture plane replays REAL traffic on its recorded schedule;
this module fabricates the traffic shapes an operator needs to probe
overload behaviour but rarely has a capture of: flash crowds (a step
x10 arrival-rate surge), diurnal ramps, burst/lull alternation, and a
tenant-skew shift where the zipf-hot tenant rotates mid-run. Every
scenario emits records in the merge_captures() dict shape, so
``bench.py --replay-synth <scenario>`` drives them through the
ordinary replay driver and the SLO autotuner (autotune.py) can search
knob settings against them.

Determinism contract (tests/test_loadgen.py):

  - generate(scenario, seed=s, ...) is a pure function of its
    arguments — the same call returns a byte-identical schedule
    (reproducible benchmarks, resumable autotune searches);
  - distinct seeds jitter WHICH arrivals land where and what each
    request carries, but conserve the rate envelope: the arrival
    count, total span, and per-interval arrival counts match across
    seeds, because arrivals are inverse-CDF stratified samples of the
    scenario's intensity profile (arrival i lands at
    t_i = L^-1((i + u_i) / n) with u_i the only seeded freedom), not
    free-running exponential draws.
"""
from __future__ import annotations

import math
import random

from . import capture as _capture

# intensity profiles are tabulated on this many grid points; the
# cumulative inverse is linear-interpolated between them
_GRID = 4096

# flash crowd: the step surge multiplies the baseline arrival rate by
# this factor between CROWD_START and CROWD_END (fractions of the span)
FLASH_FACTOR = 10.0
CROWD_START = 0.4
CROWD_END = 0.7


def _flash_crowd(x: float) -> float:
    return FLASH_FACTOR if CROWD_START <= x < CROWD_END else 1.0


def _diurnal(x: float) -> float:
    # one full day compressed into the span: smooth ramp up to a
    # midday peak and back down, never fully idle
    return 1.0 + 0.8 * math.sin(2.0 * math.pi * (x - 0.25))


def _burst_lull(x: float) -> float:
    # square wave, five cycles per span, mean 1.0: the shape that
    # defeats naive rate averaging
    return 1.6 if (x * 10.0) % 2.0 < 1.0 else 0.4


def _flat(x: float) -> float:
    return 1.0


# name -> (intensity fn over [0,1), tenant-shift phases, doc)
SCENARIOS = {
    "flash_crowd": (_flash_crowd, 1, "step x10 arrival-rate surge "
                                     "over the middle of the span"),
    "diurnal": (_diurnal, 1, "sinusoidal ramp to a midday peak"),
    "burst_lull": (_burst_lull, 1, "alternating x1.6 bursts and x0.4 "
                                   "lulls, mean-conserving"),
    "tenant_shift": (_flat, 3, "flat rate; the zipf-hot tenant "
                               "rotates at each third of the span"),
}


def scenario_names() -> tuple:
    return tuple(sorted(SCENARIOS))


def _cumulative(intensity) -> list:
    """Tabulated cumulative intensity L(x) on the unit span,
    normalized so L(1) == 1 — the inverse maps uniform stratified
    samples onto the scenario's arrival envelope."""
    acc = 0.0
    cum = [0.0]
    for i in range(_GRID):
        acc += max(intensity((i + 0.5) / _GRID), 0.0) / _GRID
        cum.append(acc)
    total = cum[-1] or 1.0
    return [c / total for c in cum]


def _inverse(cum: list, u: float) -> float:
    """L^-1(u) by bisection + linear interpolation on the table."""
    lo, hi = 0, len(cum) - 1
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if cum[mid] <= u:
            lo = mid
        else:
            hi = mid
    span = cum[hi] - cum[lo]
    frac = (u - cum[lo]) / span if span > 0 else 0.0
    return (lo + frac) / (len(cum) - 1)


def _zipf_cdf(tenants: int) -> list:
    weights = [1.0 / r for r in range(1, tenants + 1)]
    total = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    return cdf


def mean_intensity(scenario: str) -> float:
    """Span-mean of the scenario's intensity profile (flash_crowd
    > 1: the surge adds real load, it does not steal from the
    baseline)."""
    intensity, _phases, _doc = SCENARIOS[scenario]
    return sum(max(intensity((i + 0.5) / _GRID), 0.0)
               for i in range(_GRID)) / _GRID


def generate(scenario: str, n: int = 2000, tenants: int = 32,
             base_rps: float = 200.0, seed: int = 1234) -> list:
    """`n` capture-shaped records following `scenario`'s arrival
    envelope. `base_rps` is the BASELINE arrival rate (intensity 1.0);
    the span stretches so intensity-x regions really arrive at
    x * base_rps."""
    if scenario not in SCENARIOS:
        raise KeyError(f"unknown scenario {scenario!r} "
                       f"(have: {', '.join(scenario_names())})")
    intensity, phases, _doc = SCENARIOS[scenario]
    # string seeds hash via sha512 (deterministic across processes —
    # tuple seeds would go through the salted hash() and break the
    # byte-identical contract)
    rng = random.Random(f"{seed}:{scenario}:{n}:{tenants}")
    cum = _cumulative(intensity)
    # span such that the average arrival rate is mean(intensity) *
    # base_rps — i.e. intensity 1.0 regions run at exactly base_rps
    span_sec = n / (base_rps * mean_intensity(scenario))
    zipf = _zipf_cdf(tenants)
    out = []
    for i in range(n):
        # stratified inverse-CDF arrival: the seed only jitters WITHIN
        # stratum i, so every seed lands exactly one arrival per
        # stratum — rate conservation by construction
        u = (i + rng.random()) / n
        x = _inverse(cum, u)
        # tenant-shift scenarios re-rank the zipf order per phase:
        # the hot tenant is a different one in each third of the span
        phase = min(int(x * phases), phases - 1)
        uz = rng.random()
        rank = next(r for r, edge in enumerate(zipf) if uz <= edge)
        tenant = f"tenant-{(rank + phase * 7) % tenants:02d}"
        out.append({
            "arrival_ns": int(x * span_sec * 1e9),
            "tenant": tenant,
            "tenant_hash": _capture.tenant_hash(tenant),
            "docs": 1 + rng.randrange(8),
            "size_bucket": 8 + rng.randrange(4),
            "approx_bytes": 1 << (7 + rng.randrange(4)),
            "deadline_ms": 0.0,
            "priority": rng.random() < 0.10,
            "verdict": "ok",
        })
    return out


def interval_counts(records: list, buckets: int = 10) -> list:
    """Arrival count per equal time slice of the schedule's span —
    the rate envelope two seeds of the same scenario must share."""
    if not records:
        return [0] * buckets
    span = max(r["arrival_ns"] for r in records) + 1
    counts = [0] * buckets
    for r in records:
        counts[min(int(r["arrival_ns"] * buckets / span),
                   buckets - 1)] += 1
    return counts
