#!/bin/bash
# One-command CI: build natives -> verify artifacts -> tests -> entry
# checks -> bench smoke. Exits non-zero on the first failure.
set -euo pipefail
cd "$(dirname "$0")"

echo "== native build =="
language_detector_tpu/native/build.sh

if [ -d /root/reference/cld2 ] && [ ! -f tools/oracle/libcld2_oracle.so ]; then
    echo "== oracle build =="
    tools/oracle/build.sh
fi

echo "== artifact verify =="
python3 tools/artifact_tool.py --verify

echo "== static analysis =="
# AST lint (docs/STATIC_ANALYSIS.md): trace safety, jit contracts,
# lock discipline, knob/metric/fault registries, FSM conformance,
# bounded model checking, future resolution, and the binary-protocol
# plane (layout registry, publish-order, torn-write crash schedules).
# Non-zero on any violation. CI always runs the FULL suite;
# `python3 -m tools.lint --changed` is the git-diff-scoped variant
# for the local edit loop (it can skip analyzers, never weaken them —
# registry or tools/lint changes fall back to a full run).
python3 -m tools.lint

if python3 -c "import mypy" 2>/dev/null; then
    echo "== mypy =="
    python3 -m mypy --config-file mypy.ini
else
    echo "== mypy SKIPPED (mypy not installed in this image) =="
fi

echo "== tests =="
# the whole suite runs under the lock-order watchdog: any lock-order
# inversion or self-deadlock reachable by a test raises immediately
LDT_LOCK_DEBUG=1 python3 -m pytest tests/ -q

echo "== graft entry =="
python3 __graft_entry__.py

echo "== bench smoke =="
python3 bench.py --smoke | tee /tmp/ldt_bench_smoke.out
# scheduler invariants on the smoke numbers: the mixed corpus must
# never hit the packer-fallback path, and the bucketed-scheduler
# counters (cache hit rate, per-tier dispatches, dedup) must report
python3 - <<'EOF'
import json
line = [ln for ln in open("/tmp/ldt_bench_smoke.out")
        if ln.startswith("{")][-1]
d = json.loads(line)["detail"]
assert d["mixed_fallback_docs"] == 0, \
    f"mixed_fallback_docs = {d['mixed_fallback_docs']} (want 0)"
assert d["cache_hit_rate"] is not None and d["cache_hit_rate"] > 0, \
    f"cache_hit_rate = {d['cache_hit_rate']} (want > 0)"
# round-9 pipeline invariants: pack must actually overlap device
# scoring at the default depth (measured ~0.57 on this host; depth 1
# would pin it to 0.0), retried docs must re-enter at their own tier,
# and the long-doc lane must stay within noise of lane-off on the
# long-heavy mix (measured ~0.92x on the CPU host; 0.5 floors a real
# collapse, not shared-host jitter)
assert d["pack_overlap_ratio"] > 0.5, \
    f"pack_overlap_ratio = {d['pack_overlap_ratio']} (want > 0.5)"
assert d["mixed_retry_offtier_docs"] == 0, \
    f"mixed_retry_offtier_docs = {d['mixed_retry_offtier_docs']} (want 0)"
assert d["longheavy_lane_speedup"] > 0.5, \
    f"longheavy_lane_speedup = {d['longheavy_lane_speedup']} (want > 0.5)"
print("bucketed scheduler:",
      "cache_hit_rate", d["cache_hit_rate"],
      "| tier_dispatches", d["tier_dispatches"],
      "| dedup_docs", d["mixed_dedup_docs"],
      "| retry_lane_dispatches", d["mixed_retry_lane_dispatches"],
      "| lint_ms", d["lint_ms"])
print("pipeline:",
      "overlap_ratio", d["pack_overlap_ratio"],
      "| depth", d["pipeline_depth"],
      "| donation_hits", d["pipeline_donation_hits"],
      "| longheavy_lane_speedup", d["longheavy_lane_speedup"],
      "| longheavy_split_docs", d["longheavy_split_docs"])
# round-14 kernel selection: the smoke must say which scoring kernel
# the engine resolved to and why (the same fields /debug/vars exports
# under pipeline.kernel*) — a CPU host degrades pallas->fused with a
# stated reason rather than silently falling back
assert d["kernel"] in ("pallas", "pallas-interpret", "fused", "xla",
                       "lax"), d["kernel"]
assert d["kernel_reason"], "kernel fallback reason missing"
print("kernel:", d["kernel"], "|", d["kernel_reason"])
EOF

echo "== kernel smoke =="
# round-14 fused scoring kernel (docs/PERF.md): the parity subset must
# hold bit-identical words under LDT_KERNEL=xla and LDT_KERNEL=pallas
# (off-TPU the latter resolves to the fused XLA path — same program,
# stated fallback reason), and two engines built under those modes
# must answer byte-identically end-to-end
LDT_KERNEL=xla python3 -m pytest tests/test_kernel_parity.py -q \
    -k "empty_chunks or s1_clip_boundary or hint_window or each_script"
LDT_KERNEL=pallas python3 -m pytest tests/test_kernel_parity.py -q \
    -k "empty_chunks or s1_clip_boundary or hint_window or each_script"
python3 - <<'EOF'
import os

texts = [
    "hello world this is an english sentence about detection",
    "bonjour le monde ceci est une phrase en francais",
    "das ist ein deutscher satz uber die erkennung von sprachen",
    "", "a",
    "это русское предложение о языках и обнаружении",
    "これは日本語の文章ですよろしくお願いします",
] * 8


def answers(mode):
    os.environ["LDT_KERNEL"] = mode
    from language_detector_tpu.models.ngram import NgramBatchEngine
    eng = NgramBatchEngine()
    stats = eng.pipeline_stats()
    assert stats["kernel_requested"] == mode, stats
    assert stats["kernel_reason"], stats
    out = [(r.summary_lang, tuple(r.language3), tuple(r.percent3),
            tuple(r.normalized_score3), r.is_reliable)
           for r in eng.detect_batch(texts)]
    return out, stats


a, sa = answers("xla")
b, sb = answers("pallas")
assert a == b, "LDT_KERNEL=xla and =pallas engines disagree"
assert sa["kernel"] == "xla", sa
# CPU host: pallas degrades to the fused program with a stated reason
assert sb["kernel"] in ("pallas", "fused"), sb
os.environ.pop("LDT_KERNEL", None)
print("kernel smoke:", len(texts), "docs byte-identical across modes;",
      "xla ->", sa["kernel"], "| pallas ->", sb["kernel"],
      f"({sb['kernel_reason']})")
EOF

echo "== telemetry smoke =="
# drive one request through the sync front and scrape /metrics: the new
# per-stage + request histograms must be present with _count > 0, and
# /debug/vars must answer statusz JSON (docs/OBSERVABILITY.md)
python3 - <<'EOF'
import json
import threading
import urllib.request

from language_detector_tpu.service.server import make_server

httpd, metricsd, svc = make_server(0, 0)
threading.Thread(target=httpd.serve_forever, daemon=True).start()
threading.Thread(target=metricsd.serve_forever, daemon=True).start()
port = httpd.server_address[1]
mport = metricsd.server_address[1]

body = json.dumps({"request": [{"text": f"hello world number {i}"}
                               for i in range(100)]}).encode()
req = urllib.request.Request(
    f"http://127.0.0.1:{port}/", data=body,
    headers={"Content-Type": "application/json"})
resp = json.loads(urllib.request.urlopen(req, timeout=60).read())
assert len(resp["response"]) == 100, resp

metrics = urllib.request.urlopen(
    f"http://127.0.0.1:{mport}/", timeout=10).read().decode()


def series_value(name):
    for line in metrics.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"series {name} missing from /metrics")


assert series_value("ldt_request_latency_ms_count") > 0
assert series_value("ldt_stage_latency_ms_count") > 0
assert "# HELP ldt_request_latency_ms" in metrics
dv = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{mport}/debug/vars", timeout=10).read())
assert dv["requests"]["count"] > 0, dv
print("telemetry:",
      "request_count", dv["requests"]["count"],
      "| stages", sorted(dv["stage_latency_ms"]),
      "| xla_compiles", dv["xla_compiles"])
svc.batcher.close()
EOF

echo "== wire smoke =="
# the unix-socket lane must answer byte-identical payloads to the TCP
# front for the same batch, and survive a multi-connection burst under
# the lock-order watchdog; then the HTTP-front bench must hold the
# parse fast-path hit rate and the http-vs-engine throughput floor
LDT_LOCK_DEBUG=1 python3 - <<'EOF'
import http.client
import json
import os
import socket
import struct
import tempfile
import threading

from language_detector_tpu.service import wire
from language_detector_tpu.service.server import (DetectorService,
                                                  make_server)

svc = DetectorService(use_device=False, max_delay_ms=1.0)
httpd, metricsd, svc = make_server(0, 0, service=svc)
threading.Thread(target=httpd.serve_forever, daemon=True).start()
threading.Thread(target=metricsd.serve_forever, daemon=True).start()
port = httpd.server_address[1]
uds_path = os.path.join(tempfile.mkdtemp(prefix="ldt-ci-wire-"),
                        "ldt.sock")
uds = wire.UnixFrameServer(svc, uds_path)
uds.start()

body = json.dumps({"request": [{"text": f"the quick brown fox {i}"}
                               for i in range(256)]}).encode()


def tcp_post(payload):
    conn = http.client.HTTPConnection("127.0.0.1", port)
    conn.request("POST", "/", payload,
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    out = (r.status, r.read())
    conn.close()
    return out


def uds_post(sock, payload):
    sock.sendall(struct.pack("!I", len(payload)) + payload)
    hdr = b""
    while len(hdr) < 6:
        hdr += sock.recv(6 - len(hdr))
    length, status = struct.unpack("!IH", hdr)
    resp = b""
    while len(resp) < length:
        resp += sock.recv(length - len(resp))
    return status, resp


s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
s.connect(uds_path)
t = tcp_post(body)
u = uds_post(s, body)
assert t == u, ("UDS bytes differ from TCP", t[0], u[0])
s.close()

errs = []


def burst():
    try:
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.connect(uds_path)
        for _ in range(20):
            st, resp = uds_post(c, body)
            assert st in (200, 203), st
            assert resp == u[1], "burst payload drifted"
        c.close()
    except Exception as e:  # noqa: BLE001 - report via main thread
        errs.append(e)


threads = [threading.Thread(target=burst) for _ in range(8)]
for th in threads:
    th.start()
for th in threads:
    th.join(timeout=120)
assert not errs, errs
assert not any(th.is_alive() for th in threads), "uds burst hung"
uds.close(drain_sec=5.0)
assert not os.path.exists(uds_path), "socket file not unlinked"
httpd.shutdown()
metricsd.shutdown()
svc.batcher.close()
print("wire smoke: UDS == TCP bytes, 160 burst frames OK under the "
      "lock watchdog")
EOF

python3 tools/bench_service.py --aio 32768 16 2048 \
    | tee /tmp/ldt_http_smoke.out
python3 - <<'EOF'
import json

d = json.loads([ln for ln in open("/tmp/ldt_http_smoke.out")
                if ln.startswith('{"metric"')][-1])
det = d["detail"]
assert det["errors"] == 0 and det["uds_errors"] == 0, det
# the bench corpus is plain conforming JSON: nearly every request must
# take the zero-copy scanner, not the json.loads fallback
assert det["parse_fast_hit_rate"] > 0.9, det["parse_fast_hit_rate"]
eng = json.loads([ln for ln in open("/tmp/ldt_bench_smoke.out")
                  if ln.startswith("{")][-1])["value"]
ratio = d["value"] / eng
# measured ~1.05x on this host (the front adds <5% over the raw
# engine); 0.3 floor = the HTTP path still pushes at least a third of
# engine throughput even on a noisy shared runner
assert ratio >= 0.3, (f"http/engine ratio {ratio:.2f} < 0.3 "
                      f"(http {d['value']}, engine {eng})")
assert det["uds_docs_sec"] >= 0.3 * eng, \
    f"uds {det['uds_docs_sec']} < 0.3x engine {eng}"
print(f"http front: {d['value']} docs/s ({ratio:.2f}x engine), "
      f"uds {det['uds_docs_sec']} docs/s, "
      f"fast-path hit rate {det['parse_fast_hit_rate']}")
EOF

echo "== torn-write smoke =="
# a real crash, not just a model: SIGKILL a capture-ring writer and a
# shm-ring client mid-record under the lock watchdog, then prove the
# readers accept only whole committed records; finally re-prove the
# crash-schedule product and its broken-protocol detector
# (docs/STATIC_ANALYSIS.md, tools/lint/torn_write.py)
LDT_LOCK_DEBUG=1 python3 - <<'EOF'
import glob
import json
import os
import signal
import sys
import tempfile
import time

from language_detector_tpu import capture as cap
from language_detector_tpu.service import shmring as sm

# -- capture ring: writer SIGKILLed mid-append ------------------------
td = tempfile.mkdtemp(prefix="ldt-ci-torn-cap-")
pid = os.fork()
if pid == 0:
    try:
        w = cap.CaptureWriter(td, ring_records=64, sample=1.0,
                              max_segments=4)
        i = 0
        while True:
            w.append((i, i, 0, i % 64, 0.0, 1.0, 0.1, 0.2, 0.3,
                      200, 1, 0, 0, 0))
            i += 1
    finally:
        os._exit(1)
deadline = time.time() + 10.0
while time.time() < deadline and not cap.read_capture(td):
    time.sleep(0.01)
time.sleep(0.05)                      # let the writer get mid-record
os.kill(pid, signal.SIGKILL)
os.waitpid(pid, 0)
recs = cap.read_capture(td)
assert recs, "killed capture writer left no committed records"
for r in recs:
    # docs and arrival were written from the same counter: a torn
    # half-record accepted by the reader cannot keep them consistent
    assert r["docs"] == r["arrival_mono_ns"] % 64, r
    assert r["status"] == 200 and r["total_ms"] == 1.0, r
ring = glob.glob(os.path.join(td, "capture-*.ring"))[0]
data = open(ring, "rb").read()
committed = sum(
    1 for i in range(64)
    if cap.COMMIT.unpack_from(
        data, cap.FILE_HDR.size + i * cap.SLOT_BYTES)[0] == i + 1)
live = len(cap._read_file(ring))
assert live == committed, (live, committed)

# -- shm ring: client SIGKILLed mid-submit ----------------------------
td2 = tempfile.mkdtemp(prefix="ldt-ci-torn-shm-")
pid = os.fork()
if pid == 0:
    try:
        c = sm.RingClient(td2, slots=4, slot_bytes=4096)
        c.rf.set_generation(1, os.getpid())
        i = 0
        while True:
            body = json.dumps({"k": i, "pad": "x" * (i % 7)}).encode()
            s = c.submit(body)
            if s is not None and s > 0:   # play the worker: free the
                c.rf.write_slot(s, sm.SLOT_FREE, 0, 0, 0.0, 0, 0)
                c.slots[s] = sm.RingSlot(s)   # slot (slot 0 is left
                                              # READY for the parent)
            i += 1
    finally:
        os._exit(1)
deadline = time.time() + 10.0
ring2 = None
while time.time() < deadline and ring2 is None:
    found = glob.glob(os.path.join(td2, "*.ring"))
    ring2 = found[0] if found else None
    time.sleep(0.01)
assert ring2, "shm client never created its ring"
time.sleep(0.2)                       # let submits spin mid-store
os.kill(pid, signal.SIGKILL)
os.waitpid(pid, 0)
rf = sm.RingFile(ring2)
ready = 0
for i in range(rf.nslots):
    st, gen, wpid, ts, ln, status = rf.read_slot(i)
    assert st in (sm.SLOT_FREE, sm.SLOT_WRITING, sm.SLOT_READY,
                  sm.SLOT_LEASED, sm.SLOT_DONE), st
    if st == sm.SLOT_READY:
        # READY is the commit word: the payload under it must be the
        # whole frame the dead client stored, never a torn prefix
        doc = json.loads(rf.read_payload(i, ln))
        assert doc["k"] >= 0 and doc["pad"] == "x" * (doc["k"] % 7)
        ready += 1
assert ready >= 1, "slot 0 should have stayed READY"
rf.close()

# -- the exhaustive model over the same writers -----------------------
from tools.lint import torn_write

failures, n, exhausted = torn_write.run_product("torn-capture")
assert failures == [] and exhausted and n > 10, (failures, n)
bad, _n2, _e2 = torn_write.run_product(
    "torn-capture", writer=torn_write.doctored_capture_commit_first)
assert bad, "doctored commit-first writer must yield a counterexample"
print(f"torn-write smoke: capture reader kept {len(recs)} whole "
      f"records after SIGKILL, shm ring coherent ({ready} READY), "
      f"product exhausted {n} schedules, doctored writer caught")
EOF

echo "== overload smoke =="
# tiny admission limits + concurrent clients: some requests must shed
# with 429 + a sane Retry-After, nothing may hang, and once the burst
# drains a plain request is served again (docs/OBSERVABILITY.md)
python3 - <<'EOF'
import json
import threading
import urllib.error
import urllib.request

from language_detector_tpu.service.admission import (AdmissionConfig,
                                                     AdmissionController)
from language_detector_tpu.service.server import (DetectorService,
                                                  make_server)

# ladder thresholds parked far above reachable occupancy: this smoke
# pins the HARD-bound behavior (429), not brownout policy (503)
ctrl = AdmissionController(AdmissionConfig(
    max_queue_docs=8, max_inflight=2,
    brownout_enter=(90.0, 95.0, 99.0), brownout_exit=(80.0, 85.0, 90.0)))
svc = DetectorService(use_device=False, max_delay_ms=20.0,
                      admission=ctrl)
httpd, metricsd, svc = make_server(0, 0, service=svc)
threading.Thread(target=httpd.serve_forever, daemon=True).start()
threading.Thread(target=metricsd.serve_forever, daemon=True).start()
port = httpd.server_address[1]
mport = metricsd.server_address[1]

body = json.dumps({"request": [
    {"text": f"hello overload world number {i}"} for i in range(4)
]}).encode()
results = []
lock = threading.Lock()


def hammer():
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            status, retry_after = r.status, None
    except urllib.error.HTTPError as e:
        status, retry_after = e.code, e.headers.get("Retry-After")
        e.read()
    with lock:
        results.append((status, retry_after))


threads = [threading.Thread(target=hammer) for _ in range(16)]
for t in threads:
    t.start()
for t in threads:
    t.join(timeout=90)
assert not any(t.is_alive() for t in threads), "overload burst hung"
shed = [(s, ra) for s, ra in results if s == 429]
served = [s for s, _ in results if s in (200, 203)]
assert shed, f"no 429s under 16x burst vs 8-doc/2-inflight: {results}"
assert served, f"every request shed — bounds too tight: {results}"
assert all(ra is not None and int(ra) >= 1 for _, ra in shed), \
    f"shed responses missing a sane Retry-After: {shed}"

# recovery: the burst is over, a plain request is served again
req = urllib.request.Request(
    f"http://127.0.0.1:{port}/", data=body,
    headers={"Content-Type": "application/json"})
with urllib.request.urlopen(req, timeout=60) as r:
    assert r.status in (200, 203), r.status

metrics = urllib.request.urlopen(
    f"http://127.0.0.1:{mport}/", timeout=10).read().decode()
assert "ldt_shed_total" in metrics
assert "ldt_admission_queue_docs" in metrics
print("overload:", len(shed), "shed /", len(served), "served,",
      "retry_after", sorted({ra for _, ra in shed}))
svc.batcher.close()
EOF

echo "== chaos smoke =="
# a SUPERVISED asyncio front under the docs/ROBUSTNESS.md mixed chaos
# profile (flaky device fetches + one slow compile) with a dispatch
# bound that forces one mid-run recycle. The invariants: every request
# resolves (a 200 or a typed 500 — never a hang), the breaker trips
# and recovers through a half-open probe, generation 2 serves after
# the recycle, the fault counter exports, and SIGINT exits 0.
python3 - <<'EOF'
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

PORT, MPORT = 3177, 31771
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MPORT),
    "LDT_FAULTS":
        "device_flush:error:p=0.3:seed=7,compile:delay_ms=200:once",
    "LDT_BREAKER_FAILURES": "1",       # any injected fetch error trips
    "LDT_BREAKER_COOLDOWN_SEC": "0.3",
    # a low dispatch bound forces a mid-run recycle: the counter
    # climbs only on HEALTHY device flushes (faulted fetches and
    # breaker-open scalar stretches don't count), about 1 per 7
    # requests under this profile
    "LDT_MAX_DISPATCHES": "3",
    "LDT_RECYCLE_CHECK_SEC": "0.1",
    "LDT_RESTART_ON_CRASH": "1",
})
log = open("/tmp/ldt_chaos_smoke.log", "w")
# own session: on failure the cleanup kills the process GROUP, so a
# dead supervisor never orphans a worker still holding the port
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)

body = json.dumps({"request": [
    {"text": f"the quick brown fox jumps over the lazy dog {i}"}
    for i in range(80)  # > the 64-doc all-C shortcut: crosses the seams
]}).encode()


def post(timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{PORT}/", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code
    except Exception:
        return None  # connection-level (recycle window): retryable


def get_json(path, port=MPORT):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        b = e.read()
        return e.code, json.loads(b) if b else None
    except Exception:
        return None, None


def metrics_text():
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{MPORT}/metrics", timeout=10) as r:
            return r.read().decode()
    except Exception:
        return ""


try:
    deadline = time.time() + 180
    while get_json("/readyz")[0] != 200:
        assert time.time() < deadline, "worker never became ready"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)

    statuses = []
    breaker_seen = set()
    generations = set()
    for i in range(40):
        attempt_deadline = time.time() + 180
        status = post()
        while status is None:  # riding out the recycle: retry, bounded
            assert time.time() < attempt_deadline, \
                f"request {i} never resolved"
            time.sleep(0.3)
            status = post()
        assert status in (200, 500), f"request {i}: status {status}"
        statuses.append(status)
        _, dv = get_json("/debug/vars")
        if dv:
            breaker_seen.add(dv["admission"]["breaker"]["state_name"])
        for line in metrics_text().splitlines():
            if line.startswith("ldt_worker_generation "):
                generations.add(float(line.split()[-1]))

    assert statuses.count(200) > 0, f"nothing served: {statuses}"
    assert "open" in breaker_seen or "half_open" in breaker_seen, \
        f"breaker never tripped under the storm: {breaker_seen}"
    assert 2.0 in generations, \
        f"no post-recycle generation observed: {generations}"

    # recovery: faults stay armed (p=0.3), but probes are 70% likely —
    # drive traffic until the breaker closes and /readyz answers 200
    deadline = time.time() + 120
    while True:
        st, ready = get_json("/readyz")
        if st == 200 and ready["ok"]:
            break
        assert time.time() < deadline, f"never recovered: {ready}"
        post()
        time.sleep(0.1)

    mtext = metrics_text()
    assert 'ldt_fault_injected_total{point="device_flush"}' in mtext, \
        "fault counter missing from /metrics"

    sup.send_signal(signal.SIGINT)  # forwarded; aio front exits 0
    rc = sup.wait(timeout=60)
    assert rc == 0, f"supervisor exit {rc}"
finally:
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()

suplog = open("/tmp/ldt_chaos_smoke.log").read()
assert "worker recycled" in suplog, "no recycle in supervisor log"
served = sum(1 for s in statuses if s == 200)
print("chaos:", served, "served /", statuses.count(500),
      "typed 500s across", len(statuses), "requests,",
      "breaker states", sorted(breaker_seen - {None}),
      "| generations", sorted(g for g in generations if g))
EOF

echo "== pool chaos smoke =="
# the device-pool scheduler (parallel/pool.py) under lane chaos: a
# SUPERVISED asyncio front with two simulated lanes and lost-batch +
# stall injection armed on the lane seams. The invariants: every
# request is a 2xx (lost-batch failover absorbs every injected loss —
# no 5xx, no hang), at least one lane eviction exports, the evicted
# lane re-admits through a half-open probe (lanes_active recovers to
# 2 with the faults still armed), and SIGTERM exits 0.
python3 - <<'EOF'
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

PORT, MPORT = 3181, 31811
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MPORT),
    "LDT_POOL_LANES": "2",
    "LDT_POOL_EVICT_FAILURES": "1",    # any injected loss evicts
    "LDT_POOL_PROBE_COOLDOWN_SEC": "1",
    "LDT_FAULTS": "lane_lost:error:p=0.2:seed=5,"
                  "lane_stall:delay_ms=150:p=0.1:seed=6",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_pool_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)


def post(tag, timeout=120):
    # 80 DISTINCT docs per request: above the 64-doc all-C shortcut,
    # unique across the run so batch dedup can't collapse the dispatch
    body = json.dumps({"request": [
        {"text": f"the quick brown fox jumps over the lazy dog "
                 f"burst {tag} document {i}"} for i in range(80)
    ]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{PORT}/", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code
    except Exception:
        return None


def scrape():
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{MPORT}/metrics", timeout=10) as r:
            return r.read().decode()
    except Exception:
        return ""


def series_sum(text, prefix):
    total, seen = 0.0, False
    for line in text.splitlines():
        if line.startswith(prefix) and not line.startswith("#"):
            total += float(line.rsplit(" ", 1)[1])
            seen = True
    return total if seen else None


try:
    deadline = time.time() + 180
    while True:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{MPORT}/readyz", timeout=10) as r:
                if r.status == 200:
                    break
        except Exception:
            pass
        assert time.time() < deadline, "worker never became ready"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)

    statuses = []
    lock = threading.Lock()

    def burst(worker):
        for i in range(8):
            attempt_deadline = time.time() + 180
            s = post(f"w{worker}r{i}")
            while s is None:
                assert time.time() < attempt_deadline, "request hung"
                time.sleep(0.2)
                s = post(f"w{worker}r{i}retry")
            with lock:
                statuses.append(s)

    threads = [threading.Thread(target=burst, args=(w,))
               for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "burst hung"

    bad = [s for s in statuses if not 200 <= s < 300]
    assert not bad, \
        f"non-2xx under lane chaos (failover must absorb): {sorted(set(bad))}"

    mtext = scrape()
    evicted = series_sum(mtext, "ldt_pool_lane_evicted_total")
    lost = series_sum(
        mtext, 'ldt_fault_injected_total{point="lane_lost"}')
    failovers = series_sum(mtext, "ldt_pool_failover_total")
    assert lost and lost > 0, "lane_lost fault never fired"
    assert failovers and failovers > 0, "no lost-batch failovers counted"
    assert evicted and evicted > 0, \
        f"no lane eviction under p=0.2 loss with evict_failures=1"

    # recovery with the faults STILL ARMED: probes re-admit the evicted
    # lane on healthy completions — drive traffic until both lanes are
    # active again
    deadline = time.time() + 120
    i = 0
    while True:
        active = series_sum(scrape(), "ldt_pool_lanes_active")
        if active == 2.0:
            break
        assert time.time() < deadline, \
            f"evicted lane never re-admitted: lanes_active={active}"
        post(f"recover{i}")
        i += 1
        time.sleep(0.1)
    readmitted = series_sum(scrape(), "ldt_pool_lane_readmitted_total")

    sup.send_signal(signal.SIGTERM)
    rc = sup.wait(timeout=60)
    assert rc == 0, f"supervisor exit {rc}"
finally:
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()

print("pool chaos:", len(statuses), "requests all 2xx,",
      int(lost), "injected losses,", int(failovers), "failovers,",
      int(evicted), "evictions,", int(readmitted or 0),
      "re-admissions — lanes_active recovered to 2")
EOF

echo "== scrub chaos smoke =="
# data-plane integrity under table corruption (integrity.py): a
# two-lane engine with the on-device scrub cadence armed, one lane's
# device tables bit-flipped mid-burst through the table_upload corrupt
# seam. The invariants: the scrub detects the flip (digest mismatch ->
# CORRUPT), heals it (fresh upload -> PROBING), the lane re-admits
# through a served batch, ldt_integrity_detected_total and
# ldt_integrity_healed_total both advance, and post-heal answers are
# byte-identical to the pre-corruption baseline — zero wrong answers
# after heal.
JAX_PLATFORMS=cpu LDT_POOL_LANES=2 LDT_SCRUB_INTERVAL_SEC=0.01 \
LDT_CANARY_DOCS=8 LDT_LOCK_DEBUG=1 python3 - <<'EOF'
import time

from language_detector_tpu import faults, telemetry
from language_detector_tpu.models.ngram import NgramBatchEngine
from language_detector_tpu.parallel.pool import (LANE_ACTIVE,
                                                 LANE_STATE_NAMES)


def series(prefix):
    text = telemetry.render_exposition(telemetry.REGISTRY.families())
    total = 0.0
    for line in text.splitlines():
        if line.startswith(prefix) and not line.startswith("#"):
            total += float(line.rsplit(" ", 1)[1])
    return total


eng = NgramBatchEngine()
mon = eng.integrity
assert mon is not None, "integrity monitor did not build"
assert len(eng.pool.lanes) == 2, "expected two pool lanes"

docs = [f"the quick brown fox jumps over the lazy dog document {i}"
        for i in range(40)]
docs += [f"le gouvernement a annoncé de nouvelles mesures {i}"
         for i in range(20)]


def burst():
    return [eng.reg.code(r.summary_lang)
            for r in eng.detect_batch(docs)]


baseline = burst()
mon.scrub_pass()   # warm + prove a clean scrub passes canary
assert mon.stats["detected"] == 0, "clean tables flagged corrupt"

# one seeded bit-flip in one lane's device tables on the next scrub
faults.configure("table_upload:corrupt:seed=7:once")
try:
    time.sleep(0.02)           # scrub cadence due
    burst()                    # epilogue scrub fires mid-traffic
    deadline = time.time() + 60
    while mon.stats["healed"] < 1:
        assert time.time() < deadline, \
            f"corruption never detected+healed: {mon.stats}"
        time.sleep(0.02)
        burst()
finally:
    faults.configure(None)

detected = series("ldt_integrity_detected_total")
healed = series("ldt_integrity_healed_total")
assert detected >= 1, f"ldt_integrity_detected_total = {detected}"
assert healed >= 1, f"ldt_integrity_healed_total = {healed}"

# the healed lane re-admits through served batches (PROBING -> ACTIVE)
deadline = time.time() + 60
while not all(ln.state() == LANE_ACTIVE for ln in eng.pool.lanes):
    assert time.time() < deadline, "healed lane never re-admitted: " \
        + str([LANE_STATE_NAMES[ln.state()] for ln in eng.pool.lanes])
    burst()
    time.sleep(0.01)

after = burst()
assert after == baseline, \
    "post-heal answers diverge from the pre-corruption baseline"
print("scrub chaos:", int(series('ldt_integrity_scrub_total')),
      "scrubs,", int(detected), "detected,", int(healed),
      "healed — lanes active, post-heal answers match baseline")
EOF

echo "== swap-drill smoke =="
# blue/green hot swap under live traffic (docs/ROBUSTNESS.md): a
# SUPERVISED asyncio front with LDT_REUSEPORT + warmup-gated readiness,
# 8 concurrent clients bursting, SIGHUP mid-burst. The invariants:
# every response is a 2xx or a 429 (never a 5xx, never a hang — the
# standby holds until warmed, the old generation drains in-flight
# work), generation 2 takes over, the promoted standby counts its
# cutover in ldt_swap_total{result="ok"}, and SIGTERM exits 0. Runs
# under the lock-order watchdog like the rest of CI.
python3 - <<'EOF'
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

PORT, MPORT = 3179, 31791
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MPORT),
    "LDT_REUSEPORT": "1",       # generations overlap on the port
    "LDT_WARMUP": "1",          # standby pre-compiles before cutover
    "LDT_SWAP_TIMEOUT_SEC": "150",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_swap_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)

body = json.dumps({"request": [
    {"text": f"the quick brown fox jumps over the lazy dog {i}"}
    for i in range(12)
]}).encode()
stop = threading.Event()
statuses, conn_errors = [], []
lock = threading.Lock()


def client():
    while not stop.is_set():
        req = urllib.request.Request(
            f"http://127.0.0.1:{PORT}/", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
                status = r.status
        except urllib.error.HTTPError as e:
            e.read()
            status = e.code
        except Exception as e:
            # connection-level blips are retried (and counted) — only
            # HTTP statuses feed the zero-5xx invariant below
            with lock:
                conn_errors.append(repr(e))
            time.sleep(0.05)
            continue
        with lock:
            statuses.append(status)


def scrape():
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{MPORT}/metrics", timeout=10) as r:
            return r.read().decode()
    except Exception:
        return ""


def series(text, prefix):
    for line in text.splitlines():
        if line.startswith(prefix):
            return float(line.rsplit(" ", 1)[1])
    return None


try:
    deadline = time.time() + 180
    while True:  # warmup-gated readiness: generation 1 pre-compiles
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{MPORT}/readyz", timeout=10) as r:
                if r.status == 200:
                    break
        except Exception:
            pass
        assert time.time() < deadline, "worker never became ready"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)
    mtext = scrape()
    assert series(mtext, "ldt_warmup_ms ") > 0, "warmup gauge missing"

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.5)                      # burst established
    os.kill(sup.pid, signal.SIGHUP)      # hot swap, mid-burst

    deadline = time.time() + 170
    while True:  # one scrape must show the PROMOTED generation's view
        mtext = scrape()
        if (series(mtext, "ldt_worker_generation ") == 2.0
                and (series(mtext, 'ldt_swap_total{result="ok"}')
                     or 0) >= 1.0):
            break
        gen = series(mtext, "ldt_worker_generation ")
        ok = series(mtext, 'ldt_swap_total{result="ok"}')
        assert time.time() < deadline, \
            f"generation 2 never took over: gen={gen} swap_ok={ok}"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)
    time.sleep(0.5)                      # traffic rides the new gen
    stop.set()
    for t in threads:
        t.join(timeout=90)
    assert not any(t.is_alive() for t in threads), "client hung"

    bad = [s for s in statuses if not (200 <= s < 300 or s == 429)]
    assert not bad, f"non-2xx/non-429 during swap: {sorted(set(bad))}"
    assert statuses.count(200) > 0, "nothing served during the drill"

    sup.send_signal(signal.SIGTERM)      # forwarded; gen 2 drains, 0
    rc = sup.wait(timeout=60)
    assert rc == 0, f"supervisor exit {rc}"
finally:
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()

suplog = open("/tmp/ldt_swap_smoke.log").read()
assert "swap drill starting" in suplog, "no drill in supervisor log"
assert "swap cutover" in suplog, "no cutover in supervisor log"
assert "swap complete" in suplog, "swap never completed"
assert "swap-abort" not in suplog, "drill aborted:\n" + suplog
print("swap drill:", statuses.count(200), "served,",
      statuses.count(429), "shed,", len(conn_errors),
      "connection retries — generation 2 promoted, zero 5xx")
EOF

echo "== fleet chaos smoke =="
# fleet supervisor under fire (docs/ROBUSTNESS.md): a 3-worker front
# tier sharing the listen port, 64 concurrent clients bursting, then a
# SIGKILL of one READY member (pid taken from /fleetz) AND a SIGHUP
# rolling swap, both mid-burst. The invariants: every HTTP status is a
# 2xx, 429, or 503 (never any other 5xx, never a hang — surviving
# members keep the port answering while the dead slot respawns and the
# roll replaces generations one at a time), the fleet recovers to 3
# READY members with the circuit closed, and SIGINT drains every
# member and exits 0. Runs under the lock-order watchdog like the
# rest of CI.
python3 - <<'EOF'
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

PORT, MBASE, SPORT = 3183, 31830, 31839
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MBASE),
    "LDT_FLEET_WORKERS": "3",
    "LDT_FLEET_STATUS_PORT": str(SPORT),
    "LDT_CRASH_BACKOFF_BASE_SEC": "0.2",
    "LDT_CRASH_BACKOFF_MAX_SEC": "1.0",
    "LDT_SWAP_TIMEOUT_SEC": "150",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_fleet_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)

body = json.dumps({"request": [
    {"text": f"the quick brown fox jumps over the lazy dog {i}"}
    for i in range(4)
]}).encode()
stop = threading.Event()
statuses, conn_errors = [], []
threads = []
lock = threading.Lock()


def client():
    while not stop.is_set():
        req = urllib.request.Request(
            f"http://127.0.0.1:{PORT}/", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
                status = r.status
        except urllib.error.HTTPError as e:
            e.read()
            status = e.code
        except Exception as e:
            # connection-level blips (a SIGKILLed member's sockets die
            # with it) are retried and counted — only HTTP statuses
            # feed the status invariant below
            with lock:
                conn_errors.append(repr(e))
            time.sleep(0.05)
            continue
        with lock:
            statuses.append(status)


def fleetz():
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{SPORT}/fleetz", timeout=10) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def wait_fleet(pred, what, deadline_sec):
    deadline = time.time() + deadline_sec
    while True:
        snap = fleetz()
        if snap is not None and pred(snap):
            return snap
        assert time.time() < deadline, \
            f"fleet never reached: {what} — last: {snap}"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)


try:
    snap = wait_fleet(
        lambda s: s["ready"] == 3 and s["circuit"] == "closed",
        "3 READY members", 240)
    gen0 = max(m["generation"] for m in snap["members"])

    threads = [threading.Thread(target=client) for _ in range(64)]
    for t in threads:
        t.start()
    time.sleep(0.5)                      # burst established

    victim = next(m for m in snap["members"] if m["state"] == "ready")
    os.kill(victim["pid"], signal.SIGKILL)   # hard member loss

    # failover first: the dead slot respawns on a fresh generation
    # while the survivors keep the port answering
    snap = wait_fleet(
        lambda s: (s["ready"] == 3
                   and max(m["generation"] for m in s["members"])
                   > gen0),
        "3 READY post-SIGKILL", 240)
    gen1 = max(m["generation"] for m in snap["members"])

    os.kill(sup.pid, signal.SIGHUP)          # rolling swap, mid-burst

    # the roll replaces every member one standby at a time (never
    # below N-1 ready), still under the burst: all generations fresh,
    # 3 READY again, circuit closed
    wait_fleet(
        lambda s: (s["ready"] == 3 and s["circuit"] == "closed"
                   and min(m["generation"] for m in s["members"])
                   > gen1),
        "3 READY post-roll", 420)
    time.sleep(0.5)                      # traffic rides the new fleet
    stop.set()
    for t in threads:
        t.join(timeout=90)
    assert not any(t.is_alive() for t in threads), "client hung"

    bad = [s for s in statuses
           if not (200 <= s < 300 or s in (429, 503))]
    assert not bad, f"unexpected statuses mid-chaos: {sorted(set(bad))}"
    assert statuses.count(200) > 0, "nothing served during the chaos"

    sup.send_signal(signal.SIGINT)       # drain all members, exit 0
    rc = sup.wait(timeout=120)
    assert rc == 0, f"fleet exit {rc}"
finally:
    stop.set()                           # a failed assert must not
    for t in threads:                    # leave 64 clients spinning
        t.join(timeout=10)
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()

suplog = open("/tmp/ldt_fleet_smoke.log").read()
assert '"reason": "crash"' in suplog, "SIGKILL never seen as a crash"
assert "rolling swap complete" in suplog, "the roll never completed"
assert "swap-abort" not in suplog, "roll aborted:\n" + suplog
assert '"fleet-circuit-open"' not in suplog, \
    "one kill must not open the fleet circuit:\n" + suplog
print("fleet chaos:", statuses.count(200), "served,",
      statuses.count(429) + statuses.count(503), "shed,",
      len(conn_errors), "connection retries —",
      "member respawned + fleet rolled, 3 READY, clean exit")
EOF

echo "== postmortem chaos smoke =="
# the flight-recorder tentpole end-to-end (docs/OBSERVABILITY.md): a
# 2-member fleet with LDT_FLIGHTREC_DIR armed and clients bursting
# with X-LDT-Request-Id headers, then a SIGKILL of a READY member
# mid-burst. The invariants: /fleetz carries a postmortem for the dead
# pid — harvested from its crash-safe mmap ring, so nonzero recorder
# events and the request ids in flight at the kill survive the SIGKILL
# — and ONE correlation id sent over both members' UDS lanes merges
# into a single /tracez entry spanning two pids.
python3 - <<'EOF'
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

from language_detector_tpu.service import wire

PORT, MBASE, SPORT = 3187, 31870, 31879
FR_DIR = f"/tmp/ldt_fr_smoke_{os.getpid()}"
UDS = f"/tmp/ldt_fr_smoke_{os.getpid()}.sock"
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MBASE),
    "LDT_FLEET_WORKERS": "2",
    "LDT_FLEET_STATUS_PORT": str(SPORT),
    "LDT_FLIGHTREC_DIR": FR_DIR,
    "LDT_UNIX_SOCKET": UDS,
    "LDT_CRASH_BACKOFF_BASE_SEC": "0.2",
    "LDT_CRASH_BACKOFF_MAX_SEC": "1.0",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_postmortem_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)

body = json.dumps({"request": [
    {"text": f"the quick brown fox jumps over the lazy dog {i}"}
    for i in range(4)
]}).encode()
stop = threading.Event()
lock = threading.Lock()
served = [0]
rid_seq = [0]
threads = []


def client():
    while not stop.is_set():
        with lock:
            rid_seq[0] += 1
            rid = f"pm-{rid_seq[0]}"
        req = urllib.request.Request(
            f"http://127.0.0.1:{PORT}/", data=body,
            headers={"Content-Type": "application/json",
                     "X-LDT-Request-Id": rid})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                r.read()
                assert r.headers.get("X-LDT-Request-Id") == rid, \
                    "request id not echoed on the response"
                with lock:
                    served[0] += 1
        except Exception:
            time.sleep(0.05)    # kill blips retry; harvest is the test


def fleetz():
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{SPORT}/fleetz", timeout=10) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def wait_fleet(pred, what, deadline_sec):
    deadline = time.time() + deadline_sec
    while True:
        snap = fleetz()
        if snap is not None and pred(snap):
            return snap
        assert time.time() < deadline, \
            f"fleet never reached: {what} — last: {snap}"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)


def uds_request_id(path, rid):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(30)
    s.connect(path)
    try:
        s.sendall(wire.pack_frame(body, request_id=rid))
        status, echoed, _ = wire.recv_response_frame(s)
        assert echoed == rid, f"UDS echo {echoed!r} != {rid!r}"
        return status
    finally:
        s.close()


try:
    snap = wait_fleet(lambda s: s["ready"] == 2, "2 READY members", 240)

    threads = [threading.Thread(target=client) for _ in range(32)]
    for t in threads:
        t.start()
    deadline = time.time() + 60
    while served[0] < 20 and time.time() < deadline:
        time.sleep(0.1)                  # burst established end-to-end
    assert served[0] >= 20, "burst never served"

    victim = next(m for m in snap["members"] if m["state"] == "ready")
    os.kill(victim["pid"], signal.SIGKILL)   # mid-burst hard loss

    # the dead slot's ring is harvested into /fleetz postmortems while
    # the slot respawns
    snap = wait_fleet(
        lambda s: (s["ready"] == 2
                   and any(p.get("pid") == victim["pid"]
                           for p in s.get("postmortems", []))),
        "postmortem harvested + 2 READY", 240)
    pm = next(p for p in snap["postmortems"]
              if p["pid"] == victim["pid"])
    assert pm["reason"] in ("crash", "lost"), pm["reason"]
    assert pm["rc"] == -signal.SIGKILL, pm["rc"]
    assert pm["clean_exit"] is False
    assert pm["events_total"] > 0, "empty ring survived the SIGKILL?"
    assert pm["tail"], "no recorder tail in the postmortem"
    inflight = pm["inflight_request_ids"]
    assert inflight and all(r.startswith("pm-") for r in inflight), \
        f"in-flight ids not recovered from the dead member: {inflight}"
    stop.set()
    for t in threads:
        t.join(timeout=90)
    assert not any(t.is_alive() for t in threads), "client hung"

    # cross-process correlation: the SAME id over both members' UDS
    # lanes must merge into one /tracez entry spanning two pids
    rid = "cafe0001"
    for slot in (0, 1):
        assert uds_request_id(f"{UDS}.{slot}", rid) < 500
    with urllib.request.urlopen(
            f"http://127.0.0.1:{SPORT}/tracez", timeout=10) as r:
        tz = json.loads(r.read().decode())
    entry = next((e for e in tz["requests"]
                  if e["request_id"] == rid), None)
    assert entry is not None, \
        f"/tracez lost the correlation id: {tz['count']} entries"
    pids = {p for p in entry["processes"] if p.startswith("pid:")}
    assert len(pids) >= 2, \
        f"one id across two members merged to {sorted(pids)}"
    lanes = {e.get("lane") for e in entry["events"]
             if e["ev"] == "request_start"}
    assert "uds" in lanes, f"recorder lanes: {lanes}"

    sup.send_signal(signal.SIGINT)
    rc = sup.wait(timeout=120)
    assert rc == 0, f"fleet exit {rc}"
finally:
    stop.set()
    for t in threads:
        t.join(timeout=10)
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()
    shutil.rmtree(FR_DIR, ignore_errors=True)

suplog = open("/tmp/ldt_postmortem_smoke.log").read()
assert "postmortem harvested" in suplog, \
    "the fleet never logged the harvest:\n" + suplog[-2000:]
print("postmortem chaos:", served[0], "served with id echo —",
      "SIGKILL ring harvested (events:", pm["events_total"],
      "inflight:", len(pm["inflight_request_ids"]), ") and one id",
      "correlated across", len(pids), "pids via /tracez")
EOF

echo "== shm chaos smoke =="
# the shared-memory ring lane under fire (docs/ROBUSTNESS.md): a
# SUPERVISED asyncio front with LDT_SHM_DIR set, shm_lease errors
# (p=0.2) and the poison_doc fault armed, under the lock-order
# watchdog. The invariants: every frame answers despite the lease
# chaos (a failed lease retries next sweep — zero hangs, zero drops),
# a poison frame bisects down to exactly the planted docs (quarantine
# count == docs planted, the rest of the frame still answers), a
# client SIGKILLed mid-burst has its slots reclaimed and its ring
# unlinked (the lane returns to all-FREE), and SIGTERM exits 0.
python3 - <<'EOF'
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

from language_detector_tpu.service import shmring

PORT, MPORT = 3185, 31851
SHM_DIR = f"/tmp/ldt_shm_smoke_{os.getpid()}"
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MPORT),
    "LDT_SHM_DIR": SHM_DIR,
    "LDT_SHM_LEASE_TIMEOUT_SEC": "1.0",
    "LDT_FAULTS": "shm_lease:error:p=0.2:seed=3,poison_doc:error",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_shm_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)

# a second client process for the kill drill: fills ring slots as fast
# as they free up until it is SIGKILLed mid-burst
CHILD_SRC = """
import json, sys, time
from language_detector_tpu.service import shmring
cli = shmring.RingClient(sys.argv[1])
cli.wait_attached(120.0)
body = json.dumps({"request": [
    {"text": f"child burst doc {i}"} for i in range(4)]}).encode()
while True:
    if cli.submit(body) is None:
        time.sleep(0.001)
"""


def scrape(path="/metrics"):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{MPORT}{path}", timeout=10) as r:
            return r.read().decode()
    except Exception:
        return ""


def series_sum(text, prefix):
    total, seen = 0.0, False
    for line in text.splitlines():
        if line.startswith(prefix) and not line.startswith("#"):
            total += float(line.rsplit(" ", 1)[1])
            seen = True
    return total if seen else None


def shm_vars():
    try:
        return json.loads(scrape("/debug/vars")).get("shm")
    except Exception:
        return None


child = None
try:
    deadline = time.time() + 180
    while True:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{MPORT}/readyz", timeout=10) as r:
                if r.status == 200:
                    break
        except Exception:
            pass
        assert time.time() < deadline, "worker never became ready"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)

    cli = shmring.RingClient(SHM_DIR)
    cli.wait_attached(120.0)

    # -- burst under lease chaos: every frame answers, zero drops ----
    served = 0
    for r in range(48):
        body = json.dumps({"request": [
            {"text": f"the quick brown fox jumps over the lazy dog "
                     f"round {r} doc {i}"} for i in range(8)
        ]}).encode()
        status, resp = cli.request(body, timeout=120.0)
        assert 200 <= status < 300, f"frame {r} answered {status}"
        served += resp.count(b'"iso6391code"')
    assert served == 48 * 8, f"served {served}/384 docs under chaos"
    faults_fired = series_sum(
        scrape(), 'ldt_fault_injected_total{point="shm_lease"}')
    assert faults_fired and faults_fired > 0, \
        "shm_lease fault never fired — the burst proved nothing"

    # -- poison frame: bisection isolates exactly the planted docs ---
    poison_at = (3, 7, 11)
    docs = [{"text": f"the quick brown fox jumps poison round doc {i}"}
            for i in range(16)]
    for j, i in enumerate(poison_at):
        docs[i]["text"] = \
            f"poison {j} {shmring.POISON_MARKER} kills the batch"
    pbody = json.dumps({"request": docs}).encode()
    status, resp = cli.request(pbody, timeout=120.0)
    assert 200 <= status < 300, f"poison frame answered {status}"
    answers = json.loads(resp)["response"]
    # every doc answers (the seed model's codes are not asserted here —
    # tests/test_shmring.py pins exact poison/healthy isolation with a
    # deterministic detector; this smoke pins the quarantine counts)
    assert len(answers) == 16 and \
        all("iso6391code" in a for a in answers), \
        f"poison frame answered {len(answers)}/16 docs"
    quarantined = series_sum(scrape(), "ldt_quarantine_docs_total")
    assert quarantined == len(poison_at), \
        f"quarantined {quarantined} docs, planted {len(poison_at)}"
    # resubmission: known poison answers from quarantine, count stays
    status, _ = cli.request(pbody, timeout=120.0)
    assert 200 <= status < 300
    quarantined = series_sum(scrape(), "ldt_quarantine_docs_total")
    assert quarantined == len(poison_at), \
        f"resubmission re-quarantined: {quarantined}"

    # -- client SIGKILLed mid-burst: slots reclaimed, ring unlinked --
    child = subprocess.Popen(
        [sys.executable, "-c", CHILD_SRC, SHM_DIR], env=env)
    time.sleep(1.5)                      # burst established
    assert child.poll() is None, "child client died on its own"
    child.kill()
    child.wait(timeout=30)

    deadline = time.time() + 120
    while True:
        v = shm_vars()
        if (v and v["rings"] == 1
                and v["slots_free"] == v["slots_total"]):
            break                        # child ring gone, all FREE
        assert time.time() < deadline, \
            f"killed client never reclaimed: {v}"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)
    reclaimed = series_sum(scrape(), "ldt_shm_reclaimed_total")
    assert reclaimed and reclaimed > 0, "no slot reclaims counted"

    frames = series_sum(scrape(), "ldt_shm_frames_total")
    cli.close(unlink=True)
    sup.send_signal(signal.SIGTERM)
    rc = sup.wait(timeout=60)
    assert rc == 0, f"supervisor exit {rc}"
finally:
    if child is not None and child.poll() is None:
        child.kill()
        child.wait(timeout=10)
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()

print("shm chaos:", served, "docs served under lease faults",
      f"({int(faults_fired)} fired),", int(quarantined),
      "docs quarantined by bisection,", int(reclaimed),
      "slots reclaimed after the client kill,",
      int(frames or 0), "frames total — all-FREE, clean exit")
EOF

echo "== boot-hot smoke =="
# the boot-hot fleet (docs/PERF.md round 16): a supervised 2-member
# front tier with LDT_AOT_DIR + LDT_COMPILE_CACHE_DIR pointing at
# FRESH dirs, warmup gating /readyz. Generation 1 compiles for real
# and AOT-exports every ladder tier it touched; a SIGHUP roll then
# boots generation 2 against the bundle. The invariants: gen-2 members
# deserialize executables instead of compiling (ldt_aot_loads_total
# > 0, zero refusals) and warm up in < 0.5x their slot's gen-1 wall
# time; a duplicate-heavy sequential burst over fresh connections
# (SO_REUSEPORT hops members) lands cross-member hits in the
# shm-backed shared result-cache tier; SIGINT drains and exits 0.
# Runs under the lock-order watchdog like the rest of CI.
python3 - <<'EOF'
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

PORT, MBASE, SPORT = 3187, 31870, 31879
TMP = tempfile.mkdtemp(prefix="ldt_boothot_")
env = dict(os.environ)
env.pop("LDT_AOT_DIR", None)             # fresh dirs: gen-1 must pay
env.pop("LDT_COMPILE_CACHE_DIR", None)   # the real compiles
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MBASE),
    "LDT_FLEET_WORKERS": "2",
    "LDT_FLEET_STATUS_PORT": str(SPORT),
    "LDT_WARMUP": "1",
    "LDT_AOT_DIR": os.path.join(TMP, "aot"),
    "LDT_COMPILE_CACHE_DIR": os.path.join(TMP, "cc"),
    # the shm tier rides the per-worker ResultCache, so the private
    # L1 knob must be armed too (docs/OBSERVABILITY.md)
    "LDT_RESULT_CACHE_MB": "64",
    "LDT_RESULT_CACHE_SHM_MB": "8",
    "LDT_CRASH_BACKOFF_BASE_SEC": "0.2",
    "LDT_CRASH_BACKOFF_MAX_SEC": "1.0",
    "LDT_SWAP_TIMEOUT_SEC": "150",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_boothot_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)


def fleetz():
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{SPORT}/fleetz", timeout=10) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def wait_fleet(pred, what, deadline_sec):
    deadline = time.time() + deadline_sec
    while True:
        snap = fleetz()
        if snap is not None and pred(snap):
            return snap
        assert time.time() < deadline, \
            f"fleet never reached: {what} — last: {snap}"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.2)


def series(text, name):
    """Sum every sample of a metric family in a /metrics scrape
    (labelled or not); None when the family is absent."""
    total, seen = 0.0, False
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            total += float(line.rsplit(" ", 1)[1])
            seen = True
    return total if seen else None


def member_scrape(port, generation, deadline_sec=60):
    """Scrape a member's /metrics, retrying until the scrape comes
    from the expected worker generation (a roll hands the metrics
    port from the old process to its replacement)."""
    deadline = time.time() + deadline_sec
    last = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                last = r.read().decode()
            if series(last, "ldt_worker_generation") == generation:
                return last
        except Exception:
            pass
        time.sleep(0.2)
    raise AssertionError(
        f"member metrics on :{port} never showed generation "
        f"{generation} — last scrape: {(last or '')[:400]}")


def debug_vars(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/vars", timeout=10) as r:
        return json.loads(r.read().decode())


def shared_hits(snap):
    total = 0.0
    for m in snap["members"]:
        sc = debug_vars(m["metrics_port"]).get("shared_cache")
        assert sc, f"shared_cache block missing on slot {m['slot']}"
        total += sc["hits"]
    return total


try:
    snap = wait_fleet(
        lambda s: s["ready"] == 2 and s["circuit"] == "closed",
        "2 READY members", 300)
    gen1 = max(m["generation"] for m in snap["members"])

    # gen-1 baseline: real compiles, and the bundle got written
    warm1, exports1 = {}, 0.0
    for m in snap["members"]:
        text = member_scrape(m["metrics_port"], m["generation"])
        w = series(text, "ldt_warmup_ms")
        assert w and w > 0, f"slot {m['slot']} never warmed: {w}"
        warm1[m["slot"]] = w
        exports1 += series(text, "ldt_aot_exports_total") or 0.0
    assert exports1 > 0, "generation 1 exported nothing to the bundle"

    os.kill(sup.pid, signal.SIGHUP)          # roll onto the bundle

    snap = wait_fleet(
        lambda s: (s["ready"] == 2 and s["circuit"] == "closed"
                   and min(m["generation"] for m in s["members"])
                   > gen1),
        "2 READY post-roll", 420)

    # gen-2: executables deserialize instead of compiling
    for m in snap["members"]:
        text = member_scrape(m["metrics_port"], m["generation"])
        w2 = series(text, "ldt_warmup_ms")
        loads = series(text, "ldt_aot_loads_total") or 0.0
        refused = series(text, "ldt_aot_refused_total") or 0.0
        w1 = warm1[m["slot"]]
        assert loads > 0, f"slot {m['slot']} loaded no AOT executable"
        assert refused == 0, \
            f"slot {m['slot']} refused {refused} bundle entries"
        assert w2 and w2 < 0.5 * w1, \
            (f"slot {m['slot']} gen-2 warmup {w2:.0f}ms not < 0.5x "
             f"gen-1 {w1:.0f}ms")

    # duplicate-heavy burst: the SAME 8 docs, 16 sequential requests,
    # each on a fresh connection so SO_REUSEPORT hops members. A
    # member's own fills live in its private L1, so every shared-tier
    # hit below is cross-member by construction. (Sequential on
    # purpose: a concurrent burst would race both members through
    # their private miss paths in the same instant.)
    hits0 = shared_hits(snap)
    body = json.dumps({"request": [
        {"text": f"el veloz murcielago hindu comia feliz cardillo {i}"}
        for i in range(8)
    ]}).encode()
    for _ in range(16):
        req = urllib.request.Request(
            f"http://127.0.0.1:{PORT}/", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read().decode())
        assert len(out["response"]) == 8, out
    hits1 = shared_hits(snap)
    assert hits1 > hits0, \
        (f"no cross-member shared-cache hits during the burst "
         f"({hits0} -> {hits1})")

    sup.send_signal(signal.SIGINT)           # drain both members
    rc = sup.wait(timeout=120)
    assert rc == 0, f"fleet exit {rc}"
finally:
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()
    shutil.rmtree(TMP, ignore_errors=True)

suplog = open("/tmp/ldt_boothot_smoke.log").read()
assert "rolling swap complete" in suplog, "the roll never completed"
assert "swap-abort" not in suplog, "roll aborted:\n" + suplog

g1 = max(warm1.values())
print("boot-hot:", f"{exports1:.0f} executables exported by gen-1,",
      f"gen-1 warmup {g1:.0f}ms -> gen-2 loaded the bundle in",
      "< 0.5x per slot,", f"{hits1 - hits0:.0f} cross-member",
      "shared-cache hits on the duplicate burst, clean exit")
EOF

echo "== capture/replay + SLO smoke =="
# the traffic capture plane + per-tenant SLO engine (PR 17,
# docs/OBSERVABILITY.md): a 2-member fleet under the lock-order
# watchdog with LDT_CAPTURE_DIR, LDT_SLO (8 s fast window so the drill
# recovers on CI timescales, and a deliberately unmeetable 1 ms
# latency target so every drill request burns budget — the drill
# must be deterministic, not timing-dependent), and a tight
# per-tenant doc quota. The invariants: the burn-rate alert FIRES on
# /sloz under the burning drill and RECOVERS once the fast window
# ages out (slo_breach + slo_recovered land in the flight recorder);
# a throttled tenant's sheds show as per-tenant SLIs on /fleetz while
# the other tenant keeps serving; every completed request (sheds
# included) lands in the per-member capture rings; and `bench.py
# --replay --speedup 4` re-drives the merged capture against a fresh
# fleet with zero drops.
python3 - <<'EOF'
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

PORT, MBASE, SPORT = 3189, 31890, 31899
TMP = tempfile.mkdtemp(prefix="ldt_capslo_")
CAP = os.path.join(TMP, "capture")
FREC = os.path.join(TMP, "flightrec")
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MBASE),
    "LDT_FLEET_WORKERS": "2",
    "LDT_FLEET_STATUS_PORT": str(SPORT),
    "LDT_CAPTURE_DIR": CAP,
    "LDT_FLIGHTREC_DIR": FREC,
    # 1 ms target: every served request overshoots it, so the drill
    # burns budget deterministically — no fault timing to race
    "LDT_SLO": "p99_ms=1,err_pct=2,window_sec=8",
    "LDT_TENANT_QUOTA_DOCS": "8",
    "LDT_CRASH_BACKOFF_BASE_SEC": "0.2",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_capslo_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)


def get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def wait_for(pred, what, deadline_sec, url=f"http://127.0.0.1:{SPORT}"):
    deadline = time.time() + deadline_sec
    while True:
        doc = get(url + "/sloz") if "slo" in what else get(url + "/fleetz")
        if doc is not None and pred(doc):
            return doc
        assert time.time() < deadline, \
            f"never reached: {what} — last: {json.dumps(doc)[:4000]}"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.25)


def detect(tenant, docs=4, timeout=60):
    body = json.dumps({"request": [
        {"text": f"the quick brown fox jumps over the lazy dog {i}"}
        for i in range(docs)
    ]}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{PORT}/", data=body,
        headers={"Content-Type": "application/json",
                 "X-LDT-Tenant": tenant})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code


try:
    wait_for(lambda s: s["ready"] == 2, "2 READY members", 300)

    # -- burn-rate alert drill: every request misses the 1 ms target -
    for _ in range(12):          # fresh connections hop both members
        st = detect("base", docs=4)
        assert st == 200, f"drill request answered {st}"
    slo = wait_for(lambda s: s.get("alert") == "breach",
                   "slo alert breach", 60)
    assert slo["enabled"] and slo["spec"]["target_ms"] == 1.0, slo
    assert "base" in slo["tenants"], slo["tenants"].keys()

    # -- recovery: the 8 s fast window ages out, nothing else burns --
    wait_for(lambda s: s.get("alert") == "ok", "slo alert recovered",
             120)

    # -- throttled tenant: quota sheds show per-tenant, others serve -
    results = {"hot": [], "base": []}
    lock = threading.Lock()

    def burst(tenant, n):
        for _ in range(n):
            st = detect(tenant, docs=8, timeout=120)
            with lock:
                results[tenant].append(st)

    threads = [threading.Thread(target=burst, args=("hot", 4))
               for _ in range(12)]
    threads.append(threading.Thread(target=burst, args=("base", 12)))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(s == 200 for s in results["base"]), \
        f"throttle bled across tenants: {results['base']}"
    hot_shed = sum(1 for s in results["hot"] if s == 429)
    hot_ok = sum(1 for s in results["hot"] if s == 200)
    assert hot_shed > 0, f"quota never shed: {results['hot']}"
    assert hot_ok > 0, f"hot tenant fully starved: {results['hot']}"

    # per-tenant SLIs ride a rolling 8 s fast window, so this poll
    # runs right after the burst while its sheds are still in-window
    fz = wait_for(
        lambda s: (s.get("slo", {}).get("tenants", {})
                   .get("hot", {}).get("shed", 0)) >= 1
        and "base" in s.get("slo", {}).get("tenants", {}),
        "per-tenant SLIs on /fleetz", 30)
    t_hot = fz["slo"]["tenants"]["hot"]
    assert t_hot["count"] >= t_hot["shed"] > 0, t_hot

    sup.send_signal(signal.SIGINT)           # drain both members
    rc = sup.wait(timeout=120)
    assert rc == 0, f"fleet exit {rc}"
finally:
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()

# -- the capture holds every completed request, sheds included -------
sys.path.insert(0, os.getcwd())
from language_detector_tpu import capture, flightrec  # noqa: E402

member_dirs = sorted(glob.glob(os.path.join(CAP, "m*")))
assert len(member_dirs) == 2, f"per-member capture dirs: {member_dirs}"
records = capture.merge_captures(CAP)
total_reqs = 12 + 12 * 4 + 12
assert len(records) == total_reqs, \
    f"captured {len(records)} records, served {total_reqs} requests"
sheds = sum(1 for r in records if r["shed"])
assert sheds == hot_shed, f"capture sheds {sheds} != {hot_shed} (429s)"
tenants = {r["tenant"] for r in records}
assert len(tenants) == 2, f"tenants in capture: {tenants}"
arrivals = [r["arrival_ns"] for r in records]
assert arrivals == sorted(arrivals), "merge not arrival-ordered"

evs = []
for ring in glob.glob(os.path.join(FREC, "**", "flightrec-*.ring"),
                      recursive=True):
    evs += [e["ev"] for e in flightrec.read_ring(ring)["events"]]
assert "slo_breach" in evs, "no slo_breach event recorded"
assert "slo_recovered" in evs, "no slo_recovered event recorded"

# -- replay the capture at 4x against a fresh fleet: zero drops ------
renv = dict(os.environ)
for k in ("LDT_FAULTS", "LDT_SLO", "LDT_CAPTURE_DIR",
          "LDT_FLIGHTREC_DIR", "LDT_TENANT_QUOTA_DOCS"):
    renv.pop(k, None)
renv["LDT_LOCK_DEBUG"] = "1"
r = subprocess.run(
    [sys.executable, "bench.py", "--replay", CAP, "--speedup", "4"],
    env=renv, capture_output=True, text=True, timeout=600)
assert r.returncode == 0, \
    f"bench --replay failed:\n{r.stdout}\n{r.stderr}"
out = json.loads(open("BENCH_replay.json").read())
d = out["detail"]
assert d["requests"] == total_reqs, d["requests"]
assert d["completed"] == d["requests"], \
    f"replay completed {d['completed']}/{d['requests']}"
assert d["counts"]["drop"] == 0, f"replay drops: {d['counts']}"

shutil.rmtree(TMP, ignore_errors=True)
print("capture/replay + SLO:", f"{len(records)} records captured",
      f"({sheds} sheds) across 2 members,",
      "burn-rate alert fired under the burning drill and recovered,",
      f"replay at 4x re-drove {d['completed']} requests",
      f"with 0 drops (p95 skew {d['schedule']['p95_skew_ms']}ms)")
EOF

echo "== flash-crowd config-plane smoke =="
# the runtime config plane + synthetic load model (PR 20,
# docs/ROBUSTNESS.md): a 2-member fleet under the lock-order watchdog
# and a declared SLO rides out a seeded loadgen flash crowd; a
# doctored-bad fleet config push (1 ms default deadline: every request
# 504s, deterministically — no fault timing to race) burns the SLO
# fast window on the canary and AUTO-ROLLS-BACK within probation while
# the rest of the fleet never sees the bad generation; the SLO alert
# fires during the burn and recovers after; a good push then commits
# canary-then-fan-out and every member converges on the new
# generation; a second flash crowd serves clean under it. Zero worker
# deaths throughout, SIGTERM drains to exit 0, and the config_* event
# journal lands in the flight recorder.
python3 - <<'EOF'
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

PORT, MBASE, SPORT = 3193, 31930, 31939
TMP = tempfile.mkdtemp(prefix="ldt_cfg_")
FREC = os.path.join(TMP, "flightrec")
env = dict(os.environ)
env.update({
    "LISTEN_PORT": str(PORT), "PROMETHEUS_PORT": str(MBASE),
    "LDT_FLEET_WORKERS": "2",
    "LDT_FLEET_STATUS_PORT": str(SPORT),
    "LDT_FLIGHTREC_DIR": FREC,
    # ~1.6k requests emit start+end pairs; the default 256-slot ring
    # would wrap and evict the drill-phase slo_breach/config_* journal
    # this smoke asserts on
    "LDT_FLIGHTREC_SLOTS": "8192",
    # generous latency target: the flash crowd itself holds the SLO;
    # only the doctored deadline's 504s burn budget. The 2% error
    # budget makes the slow (96 s) window cross burn 1.0 on the first
    # few 504s — before rollback restores the canary — so the
    # multiwindow alert provably fires during the drill
    "LDT_SLO": "p99_ms=30000,err_pct=2,window_sec=8",
    # the crowd must stress the 2 members we assert on, not autoscale
    "LDT_FLEET_SCALE_UP_DEPTH": "100000",
    "LDT_CRASH_BACKOFF_BASE_SEC": "0.2",
    "LDT_LOCK_DEBUG": "1",
})
log = open("/tmp/ldt_cfg_smoke.log", "w")
sup = subprocess.Popen(
    [sys.executable, "-m", "language_detector_tpu.service.supervisor",
     "language_detector_tpu.service.aioserver"],
    env=env, stdout=log, stderr=subprocess.STDOUT,
    start_new_session=True)

sys.path.insert(0, os.getcwd())
import bench  # noqa: E402
from language_detector_tpu import flightrec, loadgen  # noqa: E402


def get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def wait_for(pred, what, deadline_sec, path="/fleetz"):
    deadline = time.time() + deadline_sec
    while True:
        doc = get(f"http://127.0.0.1:{SPORT}{path}")
        if doc is not None and pred(doc):
            return doc
        assert time.time() < deadline, \
            f"never reached: {what} — last: {json.dumps(doc)[:4000]}"
        assert sup.poll() is None, f"supervisor died rc={sup.poll()}"
        time.sleep(0.25)


def push_config(batch, probation_sec, timeout=90):
    body = json.dumps({"set": batch,
                       "probation_sec": probation_sec}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{SPORT}/configz", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


try:
    fz = wait_for(lambda s: s["ready"] == 2, "2 READY members", 300)
    pids0 = sorted(m["pid"] for m in fz["members"])

    # -- lap 1: seeded flash crowd under env defaults holds the SLO --
    crowd = loadgen.generate("flash_crowd", n=160, tenants=8,
                             base_rps=40, seed=7)
    r1 = bench.replay_records(crowd, PORT, speedup=1.0, clients=8)
    assert r1["counts"]["drop"] == 0, f"lap1 drops: {r1['counts']}"
    assert r1["counts"]["error"] == 0, f"lap1 errors: {r1['counts']}"

    # -- doctored-bad push: canary burns, rolls back, fleet is held --
    # the 1 ms deadline only bites under concurrency (queue wait must
    # exceed it), so the flash crowd keeps replaying while the push is
    # in flight: the canary's 504s burn its SLO fast window
    push_out = {}

    def bad_push():
        push_out["st"], push_out["doc"] = push_config(
            {"LDT_DEFAULT_DEADLINE_MS": "1"}, probation_sec=30,
            timeout=120)

    t = threading.Thread(target=bad_push)
    t.start()
    burned = 0
    while t.is_alive():
        # 32 concurrent clients: the doctored deadline fails a whole
        # swept batch at once, so several 504s land inside one
        # probation tick — enough to cross the slow window's burn
        # (firing the multiwindow alert), not just the fast one
        lap = bench.replay_records(crowd, PORT, speedup=2.0,
                                   clients=32)
        burned += lap["counts"]["error"]
    t.join()
    assert push_out["st"] == 409, push_out
    assert "rolled" in push_out["doc"]["error"], push_out
    assert push_out["doc"]["canary"]["state"] == "rolled_back", push_out
    assert burned > 0, "doctored deadline never bit (no 504s)"
    # the bad generation never reached the fleet-committed config
    fz = get(f"http://127.0.0.1:{SPORT}/fleetz")
    assert fz["config"]["generation"] == 0, fz["config"]
    assert fz["config"]["values"] == {}, fz["config"]

    # -- rollback restored the prior config: a clean lap serves ------
    r_back = bench.replay_records(crowd, PORT, speedup=1.0, clients=8)
    assert r_back["counts"]["error"] == 0, \
        f"canary still doctored after rollback: {r_back['counts']}"

    # -- the burn fired the alert; rollback lets it recover ----------
    wait_for(lambda s: s.get("alert") == "ok", "slo alert recovered",
             120, path="/sloz")

    # -- good push: canary probation, commit, fan-out, convergence ---
    st, doc = push_config({"LDT_MAX_QUEUE_DOCS": "4000"},
                          probation_sec=3)
    assert st == 200, (st, doc)
    gen = doc["generation"]
    assert doc["values"] == {"LDT_MAX_QUEUE_DOCS": "4000"}, doc
    wait_for(
        lambda s: s["config"]["generation"] == gen
        and all(m["config_generation"] == gen for m in s["members"]),
        "every member on the committed generation", 60)

    # -- lap 2: flash crowd again, on the committed config -----------
    r2 = bench.replay_records(crowd, PORT, speedup=1.0, clients=8)
    assert r2["counts"]["drop"] == 0, f"lap2 drops: {r2['counts']}"
    assert r2["counts"]["error"] == 0, f"lap2 errors: {r2['counts']}"
    slo = get(f"http://127.0.0.1:{SPORT}/sloz")
    assert slo.get("alert") == "ok", slo

    # -- zero worker deaths, clean SIGTERM drain ---------------------
    fz = get(f"http://127.0.0.1:{SPORT}/fleetz")
    assert sorted(m["pid"] for m in fz["members"]) == pids0, \
        f"a member was respawned: {fz['members']}"
    assert not fz.get("postmortems"), fz["postmortems"]
    sup.send_signal(signal.SIGTERM)
    rc = sup.wait(timeout=120)
    assert rc == 0, f"fleet exit {rc}"
finally:
    try:
        os.killpg(sup.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    sup.wait(timeout=30)
    log.close()

evs = []
for ring in glob.glob(os.path.join(FREC, "**", "flightrec-*.ring"),
                      recursive=True):
    evs += [e["ev"] for e in flightrec.read_ring(ring)["events"]]
for want in ("config_staged", "config_applied", "config_rolled_back",
             "config_committed", "slo_breach", "slo_recovered"):
    assert want in evs, f"no {want} event recorded"

shutil.rmtree(TMP, ignore_errors=True)
print("flash-crowd config plane:",
      f"{len(crowd)} crowd requests per lap with 0 drops,",
      f"doctored push rolled back on the canary ({burned} burned"
      " 504s, fleet held at gen 0),",
      f"good push committed at gen {gen} and converged,",
      "alert fired+recovered, 0 worker deaths, SIGTERM exit 0")
EOF

echo "== accuracy smoke =="
# the evalsuite scorecard (docs/ACCURACY.md): score the bundled corpus
# through the device engine, pin device-vs-scalar-oracle agreement at
# the evalsuite floor (check_floor exits non-zero below it), and pin
# the documented hint-flip demo. --quick strides the corpus 3x for CI
# cadence; the full run publishes the same schema. The ACC_r*.json the
# run publishes must also render through the postmortem CLI.
JAX_PLATFORMS=cpu python3 bench.py --eval --quick \
    | tee /tmp/ldt_acc_smoke.out
python3 - <<'EOF'
import json

card = json.loads([ln for ln in open("/tmp/ldt_acc_smoke.out")
                   if ln.startswith("{")][-1])
ag = card["agreement"]
assert ag["top1"] >= ag["floor"], \
    f"top-1 agreement {ag['top1']} under the {ag['floor']} floor"
assert ag["top3"] >= ag["floor"], \
    f"top-3 agreement {ag['top3']} under the {ag['floor']} floor"
assert card["hint_flip"]["flipped"], \
    f"the documented hint flip regressed: {card['hint_flip']}"
print("accuracy:", "top1", ag["top1"], "| top3", ag["top3"],
      "| label", card["label_accuracy"]["top1"],
      "| hint flip", card["hint_flip"]["before"], "->",
      card["hint_flip"]["after"])
EOF
JAX_PLATFORMS=cpu python3 -m language_detector_tpu.debug --eval \
    > /dev/null

echo "CI OK"
