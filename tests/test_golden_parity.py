"""Engine-vs-oracle parity and accuracy stats over the reference golden suite.

Parity must be exact (same tables, same algorithms). Accuracy against the
labeled languages is reported as an aggregate gate: with the snapshot's
octagram/CJK tables (quadgram tables absent upstream), a large fraction of
non-Latin golden paragraphs must still be correctly identified.
"""
import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.registry import registry

from conftest import oracle_detect
from golden_data import golden_pairs

PAIRS = golden_pairs()


@pytest.mark.skipif(not PAIRS, reason="reference snapshot unavailable")
def test_golden_full_parity(oracle, base_tables):
    mismatches = []
    for name, lang, raw in PAIRS:
        text = raw.decode("utf-8", errors="replace")
        code, lang_id, top3, reliable, tb = oracle_detect(oracle, raw)
        r = detect_scalar(text, base_tables)
        mine = (registry.code(r.summary_lang),
                [(registry.code(l), p) for l, p in
                 zip(r.language3, r.percent3)], r.is_reliable)
        ref = (code, [(c, p) for c, p, _ in top3], reliable)
        if mine != ref:
            mismatches.append((name, mine, ref))
    assert not mismatches, (len(mismatches), mismatches[:5])


@pytest.mark.skipif(not PAIRS, reason="reference snapshot unavailable")
def test_golden_accuracy_floor():
    """Accuracy gate on the production table set (trained quadgram tables).

    Context: the reference snapshot is missing its quadgram data files, so
    the compiled reference itself scores only 56/402 here; the trained
    tables (tools/train_quad_tables.py: octa-word + CLDR vocabulary,
    sweep-selected hyperparameters) recover detection to ~76.1%
    (docs/eval_goldens_r03.txt). The gate sits just under that. About 5%
    of the suite is unreachable from clean vocabulary (Zawgyi-encoded
    Burmese, the X_BORK_BORK_BORK joke languages, Arabic-script Tajik,
    languages with no vocabulary source); the rest of the gap to the
    >=99% north star needs running-text n-gram statistics that no corpus
    in this environment provides. Round-3 exploration (all flat or
    negative on this suite): quantizer base/slope/alpha/hi_cap sweeps,
    close-set quadgram pooling, training-mass priors, English stop-word
    and gettext-catalog sources, win-rate bias calibration, and
    expected-score regeneration from synthetic dev docs (-42%: synthetic
    scores mis-scale vs real text). Root cause of the residual errors:
    the delta-octa word source systematically lacks the base language's
    function/content words (e.g. the quad '_the' carries no English mass
    at all), which no reweighting can recover."""
    from language_detector_tpu.detector import LanguageDetector
    from language_detector_tpu.tables import ScoringTables
    det = LanguageDetector(tables=ScoringTables.load())
    hits = 0
    total = 0
    for name, lang, raw in PAIRS:
        # detect_bytes applies the interchange-validity gate, like the
        # reference harness (ExtDetectLanguageSummaryCheckUTF8,
        # cld2_unittest.cc:194)
        r = det.detect_bytes(raw)
        total += 1
        got = r.language
        if got == lang or (got, lang) == ("hmn", "blu"):  # same language
            hits += 1
    assert total > 100
    assert hits / total > 0.74, f"accuracy {hits}/{total}"
