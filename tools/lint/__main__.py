"""CLI: python -m tools.lint [--rule r1,r2] [--knob-table]
[--write-knob-docs]

Default run executes all five analyzers over the live tree and exits
non-zero on any violation — ci.sh runs exactly this before the test
suite.
"""
from __future__ import annotations

import argparse
import sys

from . import faults_registry, knob_registry, lock_discipline, \
    metric_registry, trace_safety
from .base import RULE_IDS, repo_root

# analyzer -> the rule ids it can emit (every analyzer can additionally
# emit lint-suppression-missing-reason for its scanned files)
ANALYZERS = (
    ("trace-safety", trace_safety.check,
     {"trace-host-sync", "trace-python-branch", "jit-shape-source"}),
    ("lock-discipline", lock_discipline.check, {"lock-discipline"}),
    ("knob-registry", knob_registry.check,
     {"knob-direct-env", "knob-undeclared", "knob-docs-drift"}),
    ("metric-registry", metric_registry.check,
     {"metric-undeclared", "metric-undocumented", "metric-unused"}),
    ("fault-registry", faults_registry.check,
     {"fault-undeclared", "fault-undocumented", "fault-unused"}),
)


def run(rules=None, root=None) -> int:
    root = root or repo_root()
    want = None
    if rules:
        want = {r.strip() for r in rules.split(",") if r.strip()}
        unknown = want - RULE_IDS - {a for a, _, _ in ANALYZERS}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}; "
                  f"known: {', '.join(sorted(RULE_IDS))}",
                  file=sys.stderr)
            return 2
    violations: list = []
    n_suppressed = 0
    for name, fn, emits in ANALYZERS:
        if want is not None and not (want & emits) and name not in want:
            continue
        v, ns = fn(root=root)
        if want is not None and name not in want:
            v = [x for x in v if x.rule in want
                 or x.rule == "lint-suppression-missing-reason"]
        violations.extend(v)
        n_suppressed += ns
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    for v in violations:
        print(v)
    if violations:
        by_rule: dict = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        summary = ", ".join(f"{r}: {n}"
                            for r, n in sorted(by_rule.items()))
        print(f"\nldt-lint: {len(violations)} violation(s) "
              f"({summary}); {n_suppressed} suppressed",
              file=sys.stderr)
        return 1
    print(f"ldt-lint: clean ({n_suppressed} suppressed)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="AST-based static analysis for this repo "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--rule", default=None,
                    help="comma-separated rule ids or analyzer names "
                         "to run (default: everything)")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the generated env-knob markdown table "
                         "and exit")
    ap.add_argument("--write-knob-docs", action="store_true",
                    help="regenerate the knob table in "
                         "docs/OBSERVABILITY.md and exit")
    args = ap.parse_args(argv)
    root = repo_root()
    if args.knob_table:
        print(knob_registry.generated_table(root))
        return 0
    if args.write_knob_docs:
        changed = knob_registry.write_knob_docs(root)
        print("docs/OBSERVABILITY.md "
              + ("updated" if changed else "already current"))
        return 0
    return run(rules=args.rule, root=root)


if __name__ == "__main__":
    sys.exit(main())
