"""Per-range result-chunk vector parity: scalar engine vs the oracle's
ExtDetectLanguageSummary(resultchunkvector) overload.

Covers SummaryBufferToVector (scoreonescriptspan.cc:389-509), ItemToVector
(:341-378), FinishResultVector (compact_lang_det_impl.cc:1688-1704), and the
offset-preserving Overwrite squeeze variants (impl.cc:696-940) that the
vector path switches to.

The oracle snapshot has no quadgram tables, so parity texts exercise the
CJK / script-only / octagram paths it can actually score.
"""
import ctypes

import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.registry import registry


def oracle_vector(lib, text: bytes, flags: int = 0,
                  is_plain_text: bool = True, cap: int = 128):
    offs = (ctypes.c_int * cap)()
    byts = (ctypes.c_int * cap)()
    langs = (ctypes.c_int * cap)()
    n = lib.o_detect_vector(text, len(text), 1 if is_plain_text else 0,
                            flags, offs, byts, langs, cap)
    return [(offs[i], byts[i], langs[i]) for i in range(n)]


TEXTS = [
    # single-script CJK / script-only
    (True, "国民の大多数が内閣を支持し、集団的自衛権の行使を認める判断を歓迎した。"),
    (True, "한국어는 한글을 사용하는 언어이며 대한민국의 공용어입니다."),
    (True, "ελληνικά γλώσσα είναι πολύ όμορφη και έχει μεγάλη ιστορία"),
    (True, "ภาษาไทยเป็นภาษาที่สวยงามและมีประวัติศาสตร์ยาวนาน"),
    # mixed scripts -> multiple ranges
    (True, "国民の大多数が内閣を支持し ελληνικά γλώσσα είναι πολύ όμορφη "
           "集団的自衛権の行使を認める判断を歓迎した。"),
    (True, "ภาษาไทยเป็นภาษา 中华人民共和国是世界上人口最多的国家 "
           "ქართული ენა ძალიან ლამაზია"),
    (True, "This is English text mixed with 日本語のテキストです。"
           "東京は日本の首都 and back to English words again."),
    (True, "Это русский текст и ภาษาไทยเป็นภาษาที่สวยงาม и ещё русский"),
    # degenerate
    (True, ""),
    (True, "   "),
    (True, "a"),
    (True, "12345 67890 !!! ???"),
    # HTML path (composed clean-text offset map)
    (False, "<html><body><p>国民の大多数が内閣を支持し</p>"
            "<p>ελληνικά γλώσσα είναι πολύ όμορφη</p></body></html>"),
    (False, "<div lang=ja>日本語のテキストです。東京は日本の首都</div>"
            " plain tail ภาษาไทยเป็นภาษา"),
    (True, "한국어는 한글을 &amp; 사용하는 언어이며"),
    # short letter run abutting an RTYPE_ONE span: JustOneItem records
    # must skip the word-boundary trim / relabeling (scoreonescriptspan.cc
    # :513-548 vs :419-505)
    (True, "ελληνικά γλώσσα αβγქართული ენა ძალიან ლამაზია და საინტერესო"),
    (True, "ελληνικά γλώσσα @ქართული ენა ძალიან ლამაზია და საინტერესო"),
    (True, "abcქართული ენა ძალიან ლამაზია და საინტერესო ისტორია აქვს"),
    # same-script language switches mid-chunk: SharpenBoundaries must move
    # the chunk boundary to the sharpest per-hit split
    # (scoreonescriptspan.cc:780-845)
    (True, "中华人民共和国是世界上人口最多的国家拥有悠久历史和丰富文化传统经济发展迅速科学技术不断进步"[:37]
           + "ひらがなのぶんしょうですきょうはとてもいいてんきですねさんぽにいきましょうたのしいです"),
    (True, "中华人民共和国是世界上人口最多的国家拥有悠久历史和丰富文化传统经济发展迅速科学技术不断进步"
           + "ひらがなのぶんしょうですきょうはとてもいいてんきですね"
           + "中华人民共和国是世界上人口最多的国家拥有悠久历史和丰富文化传统经济发展迅速科学技术不断进步"),
    (True, ("中华人民共和国是世界上人口最多的国家拥有悠久历史和丰富文化传统经济发展迅速科学技术不断进步"
            + "ひらがなのぶんしょうですきょうはとてもいいてんきですねさんぽにいきましょうたのしいです") * 2),
    # squeeze-trigger texts -> Overwrite variants must keep offsets exact
    (True, "国民の大多数が内閣を支持し、集団的自衛権の行使を認める判断を歓迎した。" * 20),
    (True, "ελληνικά γλώσσα είναι " * 50 + " ภาษาไทยเป็นภาษาที่สวยงาม " * 30),
    (False, "<p>" + "ελληνικά γλώσσα είναι " * 60 + "</p><p>"
            + "ภาษาไทยเป็นภาษาที่สวยงาม " * 40 + "</p>"),
    (True, "დიდი ისტორია " * 100),
    (True, "国民の大多数が " * 200 + "한국어는 한글을 " * 100),
]


@pytest.mark.parametrize("is_plain,text",
                         TEXTS, ids=[repr(t[:28]) for _, t in TEXTS])
def test_result_vector_parity(oracle, base_tables, is_plain, text):
    want = oracle_vector(oracle, text.encode("utf-8"),
                         is_plain_text=is_plain)
    r = detect_scalar(text, base_tables, is_plain_text=is_plain,
                      want_chunks=True)
    got = [(c.offset, c.bytes, c.lang1) for c in (r.chunks or [])]
    assert got == want, (text[:60],
                         [(o, b, registry.code(l)) for o, b, l in got],
                         [(o, b, registry.code(l)) for o, b, l in want])


def test_vector_covers_input(base_tables):
    """FinishResultVector contract: chunks tile [0, len) exactly."""
    text = "This is English text mixed with 日本語のテキストです。and back."
    r = detect_scalar(text, base_tables, want_chunks=True)
    raw = text.encode("utf-8")
    pos = 0
    for c in r.chunks:
        assert c.offset == pos
        assert c.bytes > 0
        pos += c.bytes
    assert pos == len(raw)


def test_detector_api_chunks(base_tables):
    from language_detector_tpu.detector import LanguageDetector
    det = LanguageDetector(tables=base_tables)
    r = det.detect("国民の大多数が内閣を支持し ελληνικά γλώσσα είναι",
                   return_chunks=True)
    assert r.chunks is not None and len(r.chunks) >= 2
    codes = [c[2] for c in r.chunks]
    assert "ja" in codes and "el" in codes
    # default path leaves chunks unset
    assert det.detect("hello world").chunks is None


def test_device_path_chunks_match_scalar(base_tables):
    """The batched engine's result-chunk vector (want_ranges sidecars +
    full-output device word + host sharpening/merge, result_vector.py)
    must agree with the scalar engine — which this file pins against the
    oracle — on EVERY document: the plain TEXTS corpus, a golden-suite
    sample, and squeeze/degenerate constructions (those resolve via the
    scalar engine inside the batched call, so equality is the contract
    either way). Summary fields must match too: sharpening shifts chunk
    byte weights before the epilogue, exactly like the scalar vector
    path."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from golden_data import golden_pairs
    from language_detector_tpu import native
    from language_detector_tpu.models.ngram import NgramBatchEngine
    if not native.available():
        pytest.skip("native library unavailable")
    texts = [t for is_plain, t in TEXTS if is_plain]
    texts += [raw.decode("utf-8", errors="replace")
              for _, _, raw in golden_pairs()][::8]
    texts += ["buy cheap now " * 400, "word " * 600]
    eng = NgramBatchEngine(tables=base_tables)
    got = eng.detect_batch(texts, return_chunks=True)
    for t, g in zip(texts, got):
        w = detect_scalar(t, base_tables, want_chunks=True)
        gch = [(c.offset, c.bytes, c.lang1) for c in (g.chunks or [])]
        wch = [(c.offset, c.bytes, c.lang1) for c in (w.chunks or [])]
        assert gch == wch, (t[:60], gch[:6], wch[:6])
        assert g.summary_lang == w.summary_lang, t[:60]
        assert list(g.percent3) == list(w.percent3), t[:60]


def test_device_path_chunks_fuzz(base_tables):
    """Randomized construction soup through the batched vector path:
    the same generator the batch-agreement fuzz uses, asserted
    chunk-vector- and summary-exact against the scalar engine (device
    sharpening, offset map-back, and the scalar fallback for
    squeeze/retry docs all get hit)."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from test_batch_agreement import _fuzz_docs
    from language_detector_tpu import native
    from language_detector_tpu.models.ngram import NgramBatchEngine
    if not native.available():
        pytest.skip("native library unavailable")
    docs = _fuzz_docs(48, seed=20260801)
    eng = NgramBatchEngine(tables=base_tables)
    got = eng.detect_batch(docs, return_chunks=True)
    for t, g in zip(docs, got):
        w = detect_scalar(t, base_tables, want_chunks=True)
        gch = [(c.offset, c.bytes, c.lang1) for c in (g.chunks or [])]
        wch = [(c.offset, c.bytes, c.lang1) for c in (w.chunks or [])]
        assert gch == wch, (t[:60], gch[:5], wch[:5])
        assert g.summary_lang == w.summary_lang, t[:60]
