"""Metric-registry analyzer: every ldt_* series declared, documented,
and emitted.

The declaration is telemetry.METRICS (name -> (type, help)); the docs
contract is docs/OBSERVABILITY.md. Usage is extracted from the first
string argument of the registry's emission/readback calls
(counter_inc, counter_value, histogram, histogram_peek,
percentile_across, metric_family — plus server.py's local one/fam
wrappers around metric_family). Native symbol names like
ldt_pack_flat_begin share the prefix but never appear as these calls'
first argument, so the extraction is context-limited by construction.

  metric-undeclared    emitted in code but missing from METRICS (no
                       HELP/TYPE at scrape time)
  metric-unused        declared in METRICS but never emitted (dead
                       series rot in dashboards)
  metric-undocumented  drift between METRICS and docs/OBSERVABILITY.md,
                       either direction
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .base import (Violation, apply_suppressions, first_str_arg,
                   iter_package_files, load_source, repo_root)

TELEMETRY_REL = "language_detector_tpu/telemetry.py"
DOCS_REL = "docs/OBSERVABILITY.md"

EMIT_CALLS = frozenset({"counter_inc", "counter_value", "histogram",
                        "histogram_peek", "percentile_across",
                        "metric_family", "one", "fam"})

# exposition-derived suffixes a doc may legally append to a series name
_SUFFIXES = ("_bucket", "_sum", "_count")

_DOC_TOKEN_RE = re.compile(r"\bldt_[a-z0-9_]+\b")


def declared_metrics(root: Path, telemetry_rel: str = TELEMETRY_REL):
    """{name: line} of METRICS keys, by AST."""
    sf = load_source(root / telemetry_rel, root)
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            is_metrics = any(isinstance(t, ast.Name)
                             and t.id == "METRICS"
                             for t in node.targets)
        elif isinstance(node, ast.AnnAssign):
            is_metrics = (isinstance(node.target, ast.Name)
                          and node.target.id == "METRICS")
        else:
            continue
        if is_metrics and isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


def used_metrics(sources):
    """{name: (rel, line)} of ldt_* series used as the first argument
    of an emission/readback call."""
    used: dict = {}
    for sf in sources:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func.attr \
                if isinstance(node.func, ast.Attribute) \
                else getattr(node.func, "id", None)
            if fname not in EMIT_CALLS:
                continue
            name = first_str_arg(node)
            if name and name.startswith("ldt_"):
                used.setdefault(name, (sf.rel, node.lineno))
    return used


def doc_metrics(root: Path, docs_rel: str = DOCS_REL) -> set:
    text = (root / docs_rel).read_text()
    return set(_DOC_TOKEN_RE.findall(text))


def _base_name(token: str, declared) -> str:
    """Collapse an exposition token (ldt_foo_ms_bucket) onto its
    declared family name, when one matches."""
    if token in declared:
        return token
    for suf in _SUFFIXES:
        if token.endswith(suf) and token[:-len(suf)] in declared:
            return token[:-len(suf)]
    return token


def check(root: Path | None = None, files=None,
          telemetry_rel: str = TELEMETRY_REL,
          docs_rel: str = DOCS_REL):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    declared = declared_metrics(root, telemetry_rel)
    paths = list(iter_package_files(root)) if files is None else \
        [root / f if not Path(f).is_absolute() else Path(f)
         for f in files]
    sources = [load_source(p, root) for p in paths]
    used = used_metrics(sources)
    in_docs = doc_metrics(root, docs_rel) \
        if (root / docs_rel).exists() else set()
    doc_bases = {_base_name(t, declared) for t in in_docs}

    per_file: dict = {sf.rel: [] for sf in sources}
    extra: list = []

    for name, (rel, line) in sorted(used.items()):
        if name not in declared:
            per_file.setdefault(rel, []).append(Violation(
                "metric-undeclared", rel, line,
                f"series {name} is emitted but not declared in "
                f"telemetry.METRICS (no HELP/TYPE at scrape time)"))
    for name, line in sorted(declared.items()):
        if name not in used:
            extra.append(Violation(
                "metric-unused", telemetry_rel, line,
                f"series {name} is declared in telemetry.METRICS but "
                f"never emitted"))
        if name not in doc_bases:
            extra.append(Violation(
                "metric-undocumented", telemetry_rel, line,
                f"series {name} is declared but not documented in "
                f"{docs_rel}"))
    for token in sorted(in_docs):
        if _base_name(token, declared) not in declared:
            extra.append(Violation(
                "metric-undocumented", docs_rel, 1,
                f"{docs_rel} mentions {token}, which is not declared "
                f"in telemetry.METRICS (stale docs)"))

    violations: list = []
    n_suppressed = 0
    for sf in sources:
        kept, ns = apply_suppressions(sf, per_file.get(sf.rel, []))
        violations.extend(kept)
        n_suppressed += ns
    violations.extend(extra)
    return violations, n_suppressed
