"""Long single-script documents: span-splitting parity with the reference
scanner (40KB buffer cap, near-end halving, getonescriptspan.cc:814-1000)."""
import random

import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.preprocess.segment import segment_text
from language_detector_tpu.registry import registry

from conftest import oracle_detect, oracle_spans


# Mixed-kanji alphabets exercise the >1000-hit hitbuffer rounds
_JA = "のがをにはで大内閣を支持し判断東京都内会議専門家参加世界経済議論政府政策発表国民生活影響"
_ZH = "的是在有人这中大为上个国我以要他时来用们生到作地于出就分对成会可主发年动"


@pytest.mark.parametrize("n_chars,alphabet", [
    (13849, "αβγδεζηθικλμνξοπρστυφχψω "),
    (27699, "αβγδεζηθικλμνξοπρστυφχψω "),
    (50000, "αβγδεζηθικλμνξοπρστυφχψω "),
    (60000, "abcdefghijklmnopqrstuvwxyz  "),
    (3500, _JA + _ZH),
    (20000, _JA + _ZH),
])
def test_long_span_parity(oracle, base_tables, n_chars, alphabet):
    rng = random.Random(3)
    text = "".join(rng.choice(alphabet) for _ in range(n_chars))
    ref = [(t, s) for t, s in oracle_spans(oracle, text.encode())]
    mine = segment_text(text)
    assert [(sp.text, sp.ulscript) for sp in mine] == ref

    code, _, top3, reliable, tb = oracle_detect(oracle, text.encode())
    r = detect_scalar(text, base_tables)
    assert registry.code(r.summary_lang) == code
    assert r.text_bytes == tb
    # Full top-3 including percents and normalized scores: catches chunk
    # boundary / reliability drift on multi-round spans.
    mine3 = [(registry.code(l), p, s) for l, p, s in
             zip(r.language3, r.percent3, r.normalized_score3)]
    assert mine3 == top3
    assert r.is_reliable == reliable
