"""Hit gathering: scan a script span for n-gram candidates and probe tables.

Re-implements the reference hot loops (cldutil.cc GetQuadHits:315,
GetOctaHits:416, GetUniHits:201, GetBiHits:248) in a host-friendly split:
positions are found with a small sequential scan (data-dependent strides),
then fingerprints and 4-way bucket probes run as vectorized numpy over all
candidates at once — the same shape the TPU path uses on device.

Hit records are (offset, indirect) pairs exactly as the reference's
ScoringHitBuffer holds them; `indirect` carries the 0x80000000 dual-table
flag for quadgram table-2 hits (cldutil.cc:360-373).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..tables import NgramTable, ScoringTables
from .hashing import (bi_hash_v2, octa_hash40, octa_subscript_key, pair_hash,
                      quad_hash_v2, quad_subscript_key)
from .segment import ScriptSpan, utf8_len_of_cps

DUAL_TABLE_FLAG = 0x80000000

# Hitbuffer capacity per scoring round (kMaxScoringHits,
# scoreonescriptspan.h:93): base hits fill in rounds of <=1000; delta and
# distinct hits are capped per round and excess is dropped.
MAX_SCORING_HITS = 1000

# Byte-class advance tables (cldutil_shared.h:462, cldutil.cc:49-99)
_ADV_BUT_SPACE = np.zeros(256, dtype=np.int64)   # 0 for <=0x20
_ADV_BUT_SPACE[0x21:0xC0] = 1
_ADV_BUT_SPACE[0xC0:0xE0] = 2
_ADV_BUT_SPACE[0xE0:0xF0] = 3
_ADV_BUT_SPACE[0xF0:0x100] = 4

_ADV_ONE = np.ones(256, dtype=np.int64)
_ADV_ONE[0xC0:0xE0] = 2
_ADV_ONE[0xE0:0xF0] = 3
_ADV_ONE[0xF0:0x100] = 4

_ADV_SPACE_VOWEL = np.zeros(256, dtype=np.int64)  # 1 on space/vowel/cont/ctrl
_ADV_SPACE_VOWEL[0x00:0x21] = 1
for _c in b"AEIOUaeiou":
    _ADV_SPACE_VOWEL[_c] = 1
_ADV_SPACE_VOWEL[0x80:0xC0] = 1


@dataclasses.dataclass
class HitList:
    offsets: np.ndarray    # int32 span-buffer offsets
    indirects: np.ndarray  # uint32 indirect subscripts (maybe dual-flagged)

    @staticmethod
    def empty() -> "HitList":
        return HitList(np.zeros(0, np.int32), np.zeros(0, np.uint32))


def lookup4(table: NgramTable, fps: np.ndarray, *, octa: bool) -> np.ndarray:
    """Vectorized 4-way associative probe (cldutil_shared.h:403-454).

    Returns the matching keyvalue word per fingerprint, or 0 on miss.
    """
    if len(fps) == 0:
        return np.zeros(0, dtype=np.uint32)
    if octa:
        sub, key = octa_subscript_key(fps, table.keymask, table.size)
    else:
        sub, key = quad_subscript_key(fps, table.keymask, table.size)
    rows = table.buckets[sub]                       # [n, 4]
    match = ((rows ^ key[:, None]) & np.uint32(table.keymask)) == 0
    hit = match.any(axis=1)
    slot = match.argmax(axis=1)
    kv = rows[np.arange(len(fps)), slot]
    return np.where(hit, kv, np.uint32(0))


def quad_positions(buf: np.ndarray, letter_offset: int,
                   letter_limit: int) -> tuple[np.ndarray, np.ndarray, int]:
    """Candidate quadgram (pos, len) pairs with the reference advance rule:
    jump to word end when the quad ends a word, else 2 chars, then skip one
    space/ASCII-vowel byte (cldutil.cc:338-395). Also returns the final scan
    position (the dummy-entry offset)."""
    adv_bs = _ADV_BUT_SPACE
    adv_sv = _ADV_SPACE_VOWEL
    b = buf.tolist()
    src = letter_offset
    if b[src] == 0x20:
        src += 1
    pos, lens = [], []
    while src < letter_limit:
        e = src
        e += adv_bs[b[e]]
        e += adv_bs[b[e]]
        mid = e
        e += adv_bs[b[e]]
        e += adv_bs[b[e]]
        pos.append(src)
        lens.append(e - src)
        src = e if b[e] == 0x20 else mid
        if src < letter_limit:
            src += adv_sv[b[src]]
        else:
            src = letter_limit
    return (np.array(pos, dtype=np.int64), np.array(lens, dtype=np.int64),
            src)


def get_quad_hits(span: ScriptSpan, tables: ScoringTables,
                  letter_offset: int = 1,
                  max_hits: int = MAX_SCORING_HITS) -> tuple[HitList, int]:
    """Quadgram hits with dual-table fallback and 2-entry repeat cache.

    Returns (hits, next_offset): scanning stops after max_hits recorded hits
    (hitbuffer fill, cldutil.cc:394), next_offset resumes the next round.
    """
    limit = span.text_bytes
    pos, lens, final_src = quad_positions(span.buf, letter_offset, limit)
    if len(pos) == 0:
        return HitList.empty(), final_src
    fps = quad_hash_v2(span.buf, pos, lens)
    kv1 = lookup4(tables.quadgram, fps, octa=False)
    use2 = not tables.quadgram2.empty and tables.quadgram2.size != 0
    kv2 = (lookup4(tables.quadgram2, fps, octa=False)
           if use2 else np.zeros_like(kv1))

    not_key1 = np.uint32(~np.uint32(tables.quadgram.keymask))
    not_key2 = np.uint32(~np.uint32(tables.quadgram2.keymask))
    offs, inds = [], []
    prior = [np.uint32(0), np.uint32(0)]
    nxt = 0
    next_offset = final_src
    for i in range(len(fps)):
        fp = fps[i]
        if fp == prior[0] or fp == prior[1]:
            continue  # repeat filter (cldutil.cc:352)
        if kv1[i] != 0:
            ind = np.uint32(kv1[i]) & not_key1
        elif kv2[i] != 0:
            ind = (np.uint32(kv2[i]) & not_key2) | np.uint32(DUAL_TABLE_FLAG)
        else:
            continue
        prior[nxt] = fp
        nxt ^= 1
        offs.append(pos[i])
        inds.append(ind)
        if len(offs) >= max_hits:
            # Buffer full: the round ends at the position the scan loop
            # would process next.
            next_offset = int(pos[i + 1]) if i + 1 < len(pos) else final_src
            break
    return (HitList(np.array(offs, dtype=np.int32),
                    np.array(inds, dtype=np.uint32)), next_offset)


def word_positions(buf: np.ndarray, letter_offset: int,
                   letter_limit: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(word_start, hashed_len, prior_word_start) per word; words are
    space-delimited and hashed truncated to 8 characters (cldutil.cc:443-517).
    """
    b = buf.tolist()
    src = letter_offset
    if b[src] == 0x20:
        src += 1
    starts, lens, priors = [], [], []
    srclimit = letter_limit + 1  # include trailing space off the end
    charcount = 0
    prior_word_start = src
    word_start = src
    word_end = word_start
    while src < srclimit:
        if b[src] == 0x20:
            if word_end > word_start:
                starts.append(word_start)
                lens.append(word_end - word_start)
                priors.append(prior_word_start)
            charcount = 0
            prior_word_start = word_start
            word_start = src + 1
            word_end = word_start
        else:
            charcount += 1
        src += _ADV_ONE[b[src]]
        if charcount <= 8:
            word_end = src
    return (np.array(starts, dtype=np.int64), np.array(lens, dtype=np.int64),
            np.array(priors, dtype=np.int64))


def get_octa_hits(span: ScriptSpan, tables: ScoringTables,
                  letter_offset: int = 1,
                  letter_limit: int | None = None) -> tuple[HitList, HitList]:
    """Word (delta-octa) and distinct-word/word-pair hits over
    [letter_offset, letter_limit).

    Returns (delta_hits, distinct_hits); distinct includes single words and
    consecutive-word pairs at the prior word's offset (cldutil.cc:470-502).
    """
    if letter_limit is None:
        letter_limit = span.text_bytes
    starts, lens, priors = word_positions(span.buf, letter_offset,
                                          letter_limit)
    if len(starts) == 0:
        return HitList.empty(), HitList.empty()
    fps = octa_hash40(span.buf, starts, lens)

    # Sequential repeat filter; cache updates even on table miss.
    keep = np.zeros(len(fps), dtype=bool)
    prior_hash = np.zeros(len(fps), dtype=np.uint64)  # other cache slot
    cache = [np.uint64(0), np.uint64(0)]
    nxt = 0
    for i in range(len(fps)):
        fp = fps[i]
        if fp == cache[0] or fp == cache[1]:
            continue
        keep[i] = True
        cache[nxt] = fp
        nxt = 1 - nxt
        prior_hash[i] = cache[nxt]

    k = np.flatnonzero(keep)
    kfps = fps[k]
    # (1) word pairs: rotate(prev,13)+cur, recorded at prior word start
    pair_ok = (prior_hash[k] != 0) & (prior_hash[k] != kfps)
    pfps = pair_hash(prior_hash[k], kfps)
    kv_pair = lookup4(tables.distinctocta, pfps, octa=True)
    kv_pair = np.where(pair_ok, kv_pair, np.uint32(0))
    # (2) distinct single words
    kv_dist = lookup4(tables.distinctocta, kfps, octa=True)
    # (3) delta words
    kv_delta = lookup4(tables.deltaocta, kfps, octa=True)

    not_key_d = np.uint32(~np.uint32(tables.deltaocta.keymask))
    not_key_x = np.uint32(~np.uint32(tables.distinctocta.keymask))
    d_off, d_ind, x_off, x_ind = [], [], [], []
    for j, i in enumerate(k):
        if kv_pair[j] != 0:
            x_off.append(priors[i])
            x_ind.append(np.uint32(kv_pair[j]) & not_key_x)
        if kv_dist[j] != 0:
            x_off.append(starts[i])
            x_ind.append(np.uint32(kv_dist[j]) & not_key_x)
        if kv_delta[j] != 0:
            d_off.append(starts[i])
            d_ind.append(np.uint32(kv_delta[j]) & not_key_d)
        # Per-round hitbuffer caps: excess words are dropped
        # (cldutil.cc:429-435, :520-521)
        if len(d_off) >= MAX_SCORING_HITS or \
                len(x_off) >= MAX_SCORING_HITS - 1:
            break
    return (HitList(np.array(d_off, np.int32), np.array(d_ind, np.uint32)),
            HitList(np.array(x_off, np.int32), np.array(x_ind, np.uint32)))


def _char_geometry(span: ScriptSpan):
    """(starts, ends) byte offsets per codepoint of the span buffer."""
    lens = utf8_len_of_cps(span.cps)
    ends = np.cumsum(lens)
    starts = ends - lens
    return starts.astype(np.int64), ends.astype(np.int64), lens


def get_uni_hits(span: ScriptSpan, tables: ScoringTables,
                 letter_offset: int = 1,
                 max_hits: int = MAX_SCORING_HITS) -> tuple[HitList, int]:
    """CJK unigram hits: per-character compat-class lookup (cldutil.cc:201).

    Offsets are recorded past the character (reference records src - text
    after advancing, cldutil.cc:222-230). Returns (hits, next_offset);
    scanning stops after max_hits recorded hits (hitbuffer fill)."""
    starts, ends, _ = _char_geometry(span)
    prop = tables.cjk_uni_prop[np.minimum(span.cps, 0x10FFFF)]
    sel = (prop > 0) & (starts >= letter_offset) & (starts < span.text_bytes)
    hit_ends = ends[sel]
    hit_props = prop[sel]
    if len(hit_ends) >= max_hits:
        # Round ends right after the max_hits-th hit's character (the
        # reference breaks even when it is the last hit, cldutil.cc:233).
        next_offset = int(hit_ends[max_hits - 1])
        hit_ends = hit_ends[:max_hits]
        hit_props = hit_props[:max_hits]
    else:
        next_offset = span.text_bytes
    return (HitList(hit_ends.astype(np.int32), hit_props.astype(np.uint32)),
            next_offset)


def get_bi_hits(span: ScriptSpan, tables: ScoringTables,
                letter_offset: int = 1,
                letter_limit: int | None = None) -> tuple[HitList, HitList]:
    """CJK bigram hits over [letter_offset, letter_limit): two >=3-byte
    chars hashed together (cldutil.cc:248)."""
    if letter_limit is None:
        letter_limit = span.text_bytes
    starts, ends, lens = _char_geometry(span)
    # bigram i = chars i, i+1; need len2 >= 6 bytes (two CJK chars)
    len2 = lens[:-1] + lens[1:]
    ok = ((len2 >= 6) & (starts[:-1] >= letter_offset) &
          (starts[:-1] < letter_limit))
    idx = np.flatnonzero(ok)
    if len(idx) == 0:
        return HitList.empty(), HitList.empty()
    fps = bi_hash_v2(span.buf, starts[idx], len2[idx])
    kv_delta = lookup4(tables.cjkdeltabi, fps, octa=False)
    kv_dist = lookup4(tables.distinctbi, fps, octa=False)
    nk_d = np.uint32(~np.uint32(tables.cjkdeltabi.keymask))
    nk_x = np.uint32(~np.uint32(tables.distinctbi.keymask))
    dsel = kv_delta != 0
    xsel = kv_dist != 0
    d_off = starts[idx][dsel].astype(np.int32)[:MAX_SCORING_HITS]
    d_ind = (kv_delta[dsel] & nk_d).astype(np.uint32)[:MAX_SCORING_HITS]
    x_off = starts[idx][xsel].astype(np.int32)[:MAX_SCORING_HITS - 1]
    x_ind = (kv_dist[xsel] & nk_x).astype(np.uint32)[:MAX_SCORING_HITS - 1]
    return HitList(d_off, d_ind), HitList(x_off, x_ind)
