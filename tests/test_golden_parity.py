"""Engine-vs-oracle parity and accuracy stats over the reference golden suite.

Parity must be exact (same tables, same algorithms). Accuracy against the
labeled languages is reported as an aggregate gate: with the snapshot's
octagram/CJK tables (quadgram tables absent upstream), a large fraction of
non-Latin golden paragraphs must still be correctly identified.
"""
import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.registry import registry

from conftest import oracle_detect
from golden_data import golden_pairs

PAIRS = golden_pairs()


@pytest.mark.skipif(not PAIRS, reason="reference snapshot unavailable")
def test_golden_full_parity(oracle):
    mismatches = []
    for name, lang, raw in PAIRS:
        text = raw.decode("utf-8", errors="replace")
        code, lang_id, top3, reliable, tb = oracle_detect(oracle, raw)
        r = detect_scalar(text)
        mine = (registry.code(r.summary_lang),
                [(registry.code(l), p) for l, p in
                 zip(r.language3, r.percent3)], r.is_reliable)
        ref = (code, [(c, p) for c, p, _ in top3], reliable)
        if mine != ref:
            mismatches.append((name, mine, ref))
    assert not mismatches, (len(mismatches), mismatches[:5])


@pytest.mark.skipif(not PAIRS, reason="reference snapshot unavailable")
def test_golden_accuracy_floor(oracle):
    """Sanity floor: the no-quad table set must still identify most
    CJK/script-only/distinct-word languages."""
    hits = 0
    total = 0
    for name, lang, raw in PAIRS:
        r = detect_scalar(raw.decode("utf-8", errors="replace"))
        total += 1
        if registry.code(r.summary_lang) == lang:
            hits += 1
    assert total > 100
    # With the snapshot's table set (quadgram tables absent upstream) the
    # compiled oracle itself scores 56/402; the floor tracks that. It rises
    # once trained quad tables land (train/quad_tables.py).
    assert hits / total > 0.12, f"accuracy {hits}/{total}"
