"""Seeded staging-ring donation violations (tests/test_lint.py):
reads of a donated wire between the launch and the result future's
resolution."""
import jax
import numpy as np


def score_impl(dt, wire):
    return wire * dt


score_donated = jax.jit(score_impl, donate_argnums=(1,))


def read_before_resolve(dt, wire):
    fut = score_donated(dt, wire)
    peek = wire.sum()  # jit-donated-read: fut not resolved yet
    rows = np.asarray(fut)
    return rows, peek


def never_resolved(dt, wire, other):
    fut = score_donated(dt, wire)
    fut = score_donated(dt, other)  # rebinds fut: first future lost
    np.asarray(fut)
    return wire  # jit-donated-read: first call's future never resolved
