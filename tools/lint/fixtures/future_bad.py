"""Seeded future-resolution violations (tests/test_lint.py)."""
from concurrent.futures import Future


def leak_on_branch(cond, q):
    fut = Future()
    if cond:
        q.put((1, fut))
    # cond False: normal exit with fut pending  -> future-unresolved


def leak_zero_iteration(items):
    fut = Future()
    for it in items:
        fut.set_result(it)
        break
    # empty items: falls through pending        -> future-unresolved


class Consumer:
    def _drain(self, q):
        pending = []
        while True:
            try:
                pending.append(q.get_nowait())
            except Exception:
                # swallows without failing the batch
                # -> future-consumer-guard
                return
