"""Data-parallel scaling over a jax device mesh.

The reference scales horizontally with one process per core behind a load
balancer (SURVEY §2.7: no distributed runtime of any kind); the TPU-native
equivalent is pure data parallelism: documents are embarrassingly parallel,
so the packed batch shards over a 1-D "batch" mesh axis via shard_map and
each device scores its slice with zero collectives. Tables (the model
weights, ~2MB) are replicated to every device.

Single-host meshes span ICI (v5e-8); multi-host deployments extend the same
axis over DCN via jax.distributed — the program is unchanged because no
cross-document communication exists. Collectives appear only in the eval
harness (accuracy reductions), where XLA inserts psums over the same axis.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.score import score_chunks_impl

# jax.shard_map graduated from jax.experimental in newer releases; the
# pinned 0.4.x only ships the experimental entry point
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

BATCH_AXIS = "batch"


def batch_mesh(n_devices: int | None = None,
               devices: list | None = None) -> Mesh:
    """1-D data-parallel mesh over the first n available devices."""
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(devs, (BATCH_AXIS,))


def sharded_score_chunks_fn(mesh: Mesh):
    """Jitted score_chunks with the CHUNK axis sharded over the mesh.

    The flat wire has no document axis; each shard row carries the slots
    and chunk rows of its contiguous document range (chunk starts derive
    per shard row as a cumsum of cnsl, so every shard's program is
    identical), keeping the body communication-free exactly like the
    doc-major scorer."""
    wire_specs = dict(idx=P(BATCH_AXIS),
                      cnsl=P(BATCH_AXIS), cmeta=P(BATCH_AXIS),
                      cscript=P(BATCH_AXIS), cwhack=P(BATCH_AXIS),
                      hint_lp=P(), whack_tbl=P(), k_iota=P())
    fn = _shard_map(score_chunks_impl, mesh=mesh,
                    in_specs=(P(), wire_specs),
                    out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for host->device transfer of packed batch arrays."""
    return NamedSharding(mesh, P(BATCH_AXIS))


def lane_meshes(mesh: Mesh, n_lanes: int) -> list:
    """Partition a batch mesh's devices into n_lanes equal contiguous
    sub-meshes — the device pool's lanes (parallel/pool.py). Each lane
    is an independent 1-D batch mesh over its share, so a lane failure
    never touches the others' programs and the pool is mesh-count
    agnostic: 8 devices serve 2 lanes of 4 or 4 lanes of 2 with the
    same scoring program per lane. Devices beyond an even split are
    dropped (a ragged lane would compile a second program set)."""
    devs = list(mesh.devices.flat)
    per = len(devs) // n_lanes
    if per < 1:
        raise ValueError(
            f"cannot split {len(devs)} devices into {n_lanes} lanes")
    return [Mesh(devs[i * per:(i + 1) * per], (BATCH_AXIS,))
            for i in range(n_lanes)]
