#!/usr/bin/env python3
"""End-to-round divergence soak: every engine path vs the scalar oracle.

Runs thousands of randomized fuzz documents (the construction soup from
tests/test_batch_agreement.py) through each production path and counts
exact-result mismatches against the scalar engine — the strongest
whole-system check the repo has, used as the round-end stability bake:

  plain    detect_batch, full ScalarResult tuple equality
  codes    multi-slice detect_codes (ragged slices force the deferred
           cross-slice gate-retry path)
  hints    TLD + content-language hints
  html     is_plain_text=False with rotating lang= attributes
  vectors  return_chunks: per-range vector AND summary equality
  c-abi    raw ctypes detect_language_n vs the device engine

Exits non-zero on any mismatch. Usage: python3 tools/soak.py [scale]
(scale multiplies the per-path document counts; default 1 ~ 4K docs,
a few minutes on the single-core host).
"""
from __future__ import annotations

import ctypes
import random
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

from language_detector_tpu import enable_jit_cache  # noqa: E402

enable_jit_cache()


def main(scale: int = 1) -> int:
    from test_batch_agreement import _fuzz_docs

    from language_detector_tpu import native
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.hints import CLDHints
    from language_detector_tpu.models.ngram import NgramBatchEngine
    from language_detector_tpu.registry import registry
    from language_detector_tpu.tables import load_tables

    eng = NgramBatchEngine()
    failures = 0

    def stuple(r):
        return (r.summary_lang, list(r.language3), list(r.percent3),
                r.text_bytes, r.is_reliable)

    def report(name, bad, n):
        nonlocal failures
        failures += bad
        print(f"{name:28s} {n - bad}/{n} exact", flush=True)

    n = 2048 * scale
    docs = _fuzz_docs(n, seed=99001)
    got = eng.detect_batch(docs)
    report("plain detect_batch", sum(
        1 for t, g in zip(docs, got)
        if stuple(g) != stuple(detect_scalar(t, eng.tables, eng.reg, 0))),
        n)

    codes = eng.detect_codes(docs, batch_size=257)
    report("codes multi-slice+retry", sum(
        1 for g, c in zip(got, codes)
        if eng.reg.code(g.summary_lang) != c), n)

    nh = 256 * scale
    hdocs = _fuzz_docs(nh, seed=99002)
    for hint in (CLDHints(tld_hint="fr"),
                 CLDHints(content_language_hint="de,en")):
        hgot = eng.detect_batch(hdocs, hints=hint)
        report(f"hints {hint.tld_hint or hint.content_language_hint}",
               sum(1 for t, g in zip(hdocs, hgot)
                   if stuple(g) != stuple(detect_scalar(
                       t, eng.tables, eng.reg, 0, hints=hint))), nh)

    rng = random.Random(99003)
    html_docs = [
        f"<html lang='{rng.choice(['fr', 'ja', '', 'de'])}'>"
        f"<p>{d[:400]}</p></html>"
        for d in _fuzz_docs(nh, seed=99004)]
    hg = eng.detect_batch(html_docs, is_plain_text=False)
    report("html", sum(
        1 for t, g in zip(html_docs, hg)
        if stuple(g) != stuple(detect_scalar(
            t, eng.tables, eng.reg, 0, is_plain_text=False))), nh)

    nv = 192 * scale
    vdocs = _fuzz_docs(nv, seed=99005)
    vg = eng.detect_batch(vdocs, return_chunks=True)
    vbad = 0
    for t, g in zip(vdocs, vg):
        w = detect_scalar(t, eng.tables, eng.reg, 0, want_chunks=True)
        gch = [(c.offset, c.bytes, c.lang1) for c in (g.chunks or [])]
        wch = [(c.offset, c.bytes, c.lang1) for c in (w.chunks or [])]
        if gch != wch or g.summary_lang != w.summary_lang:
            vbad += 1
    report("chunk vectors", vbad, nv)

    native.ensure_init(load_tables(), registry)
    lib = ctypes.CDLL(str(Path(native.__file__).parent /
                          "libldtpack.so"))
    lib.detect_language_n.restype = ctypes.c_char_p
    lib.detect_language_n.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    nc = 1024 * scale
    cdocs = _fuzz_docs(nc, seed=99010)
    cwant = eng.detect_codes(cdocs, batch_size=16384)
    cbad = 0
    for t, w in zip(cdocs, cwant):
        enc = t.encode("utf-8", "surrogatepass")
        if lib.detect_language_n(enc, len(enc)).decode() != w:
            cbad += 1
    report("raw C ABI", cbad, nc)

    print("SOAK", "CLEAN" if failures == 0 else f"FAILED ({failures})")
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main(*(int(a) for a in sys.argv[1:])))
