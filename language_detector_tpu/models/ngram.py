"""Batched n-gram detection engine: the TPU hot path.

Pipeline per batch (the TPU redesign of DetectLanguageSummaryV2,
compact_lang_det_impl.cc:1707-2106):

  host   pack_chunks     texts -> chunk-major flat wire (C++: segmentation,
                         hashing, table probes, repeat cache, chunk
                         assignment, boost rotation — packer.cc)
  device score_chunks    langprob decode + chunk totes + top-2 + reliability
                         over a [G, K] chunk grid with NO document axis
  host   epilogue_flat   DocTote replay + close pairs + unreliable removal +
                         summary language (C++: epilogue.cc, O(1) per doc)

The wire is chunk-major: every document's chunks are rows of one flat
grid, so a single dispatch freely mixes 100-byte tweets with 100KB
documents — device cost is linear in total text, never quadratic in
document length (the round-3 wide-slot engine's [B, C, L] cliff is gone,
and with it the size-class routing).

Documents the packer flags (per-doc budget overflow, adversarially fat
chunks) fall back to the scalar engine; documents failing the good-answer
gate (impl.cc:1978-1991) re-score as a batch with the recursion flags.
Everything agrees with `detect_scalar` on every document
(tests/test_batch_agreement.py).
"""
from __future__ import annotations

import contextlib
import time as _time

import numpy as np

from .. import faults, knobs, telemetry
from ..engine_scalar import (FLAG_BEST_EFFORT, FLAG_FINISH, FLAG_REPEATS,
                             FLAG_SQUEEZE, FLAG_TOP40,
                             ScalarResult, detect_scalar,
                             result_from_epilogue_row as _result_from_row)
from ..locks import make_lock
from ..ops import kernels
from ..ops.device_tables import DeviceTables
from ..ops.score import unpack_chunks_out
from ..registry import Registry, registry as default_registry
from ..tables import ScoringTables, load_tables

# Flags the device path supports. FINISH/BEST_EFFORT alter only the
# epilogue gate; SQUEEZE/REPEATS run natively in the packer (squeeze_span /
# cheap_rep_words_inplace); TOP40/SHORT/USE_WORDS are vestigial in this
# CLD2 version (set by the recursion, read nowhere). Anything else
# (score-as-quads) routes the batch to the scalar engine.
from ..engine_scalar import FLAG_SHORT, FLAG_USE_WORDS

_DEVICE_OK_FLAGS = (FLAG_FINISH | FLAG_BEST_EFFORT | FLAG_SQUEEZE |
                    FLAG_REPEATS | FLAG_TOP40 | FLAG_SHORT |
                    FLAG_USE_WORDS)


class NgramBatchEngine:
    """Batched detector over a table artifact.

    The compiled device program's shape depends only on content volume
    (slot/chunk/fattest-chunk buckets), never on batch size or document
    length — one small program set serves every traffic mix.
    """

    # process-global interpreter-tuning state for _gc_paused (shared
    # across engines: the knobs it guards are process-global too)
    _bulk_lock = make_lock("engine.bulk")
    _bulk_depth = 0
    _bulk_saved = (True, 0.005)
    # bulk calls completed since the last forced gc.collect(): under
    # sustained overlapping flushes the pause depth may never return to
    # 0, so cyclic garbage made by OTHER threads while the GC is paused
    # must be bounded by forcing a collection every N bulk exits
    _bulk_since_collect = 0
    GC_COLLECT_EVERY = 64

    def __init__(self, tables: ScoringTables | None = None,
                 reg: Registry | None = None, flags: int = 0,
                 max_slots: int = 1 << 17, max_chunks: int = 1 << 14,
                 mesh=None, longdoc_chunk_slots: int | None = None,
                 longdoc_split_slots: int | None = None):
        """max_slots / max_chunks: PER-DOCUMENT budgets (packer scratch);
        a document exceeding either falls back to the scalar engine. The
        defaults admit ~100KB documents. mesh: optional jax.sharding.Mesh
        with a "batch" axis; when given, the chunk grid shards over it
        data-parallel and batches pad to a multiple of the mesh size.
        longdoc_chunk_slots: long-doc lane sub-pack size target; None
        reads LDT_LONGDOC_CHUNK_SLOTS (bench passes 0 to build a
        no-split comparison engine). longdoc_split_slots: slot-demand
        threshold past which a doc enters the lane at all; None reads
        LDT_LONGDOC_SPLIT_SLOTS (tests pass the sub-pack size here to
        force splitting on mid-size docs)."""
        self.tables = tables or load_tables()
        self.reg = reg or default_registry
        self.flags = flags
        self.max_slots = max_slots
        self.max_chunks = max_chunks
        # persistent XLA compile cache: with LDT_COMPILE_CACHE_DIR set,
        # a fresh process (a recycled worker, the blue/green standby)
        # warms its bucket ladder from disk instead of recompiling —
        # the dominant cost of standby readiness. Best-effort: an old
        # jax without the option just compiles as before.
        cache_dir = knobs.get_str("LDT_COMPILE_CACHE_DIR")
        if cache_dir:
            # a nonexistent dir used to silently disable the cache (jax
            # skips unwritable cache dirs without a peep) — create it
            # and say so, a deploy that points at a fresh path gets a
            # working cache, not a cold fleet
            import json as _json
            import os as _os
            if not _os.path.isdir(cache_dir):
                try:
                    _os.makedirs(cache_dir, exist_ok=True)
                    print(_json.dumps(
                        {"msg": "compile cache dir created",
                         "dir": cache_dir}), flush=True)
                except OSError as e:
                    print(_json.dumps(
                        {"msg": "compile cache dir unusable — "
                                "persistent compile cache disabled",
                         "dir": cache_dir, "error": repr(e)}),
                        flush=True)
            try:
                import jax
                jax.config.update("jax_compilation_cache_dir",
                                  cache_dir)
                try:
                    # the default min-compile-time floor skips caching
                    # sub-second compiles — on the CPU simulator (and
                    # for the smaller bucket-ladder programs) that is
                    # ALL of them, which would leave a recycled worker
                    # cold despite the cache dir
                    jax.config.update(
                        "jax_persistent_cache_min_compile_time_secs", 0)
                except Exception:
                    pass
            except Exception:
                pass
        self.dt = DeviceTables.from_host(self.tables, self.reg)
        self.mesh = mesh
        # scoring-kernel selection (LDT_KERNEL, ops/kernels.py): the
        # fused Pallas kernel on TPU, its quantized fused XLA fallback
        # elsewhere, or the explicit reference programs — all
        # bit-identical, resolved once per engine and surfaced in
        # /debug/vars (pipeline.kernel / pipeline.kernel_reason)
        self._kernel = kernels.select_kernel()
        if mesh is not None:
            from ..parallel.mesh import BATCH_AXIS, sharded_score_chunks_fn
            self._score_fn = sharded_score_chunks_fn(mesh)
            self._kernel = kernels.mesh_selection(self._kernel)
            # wire shards over the batch axis only; any extra mesh axes
            # (e.g. a vestigial "model" axis) replicate
            self._mesh_size = mesh.shape[BATCH_AXIS]
        else:
            self._score_fn = self._kernel.score
            self._mesh_size = 1
        # fault-tolerant dispatch pool (parallel/pool.py): built only
        # when LDT_POOL_LANES is set; None = the direct single-lane
        # launch path, byte-identical to the pool-less engine
        from ..parallel import pool as pool_mod
        self.pool = pool_mod.build_from_env(self._score_fn, mesh)
        if self.pool is not None and mesh is not None:
            # lanes score over SUB-meshes: pad/pack to the lane size,
            # and point direct _score_fn users at lane 0's program
            self._score_fn = self.pool.lanes[0].score_fn
            self._mesh_size = self.pool.lane_mesh_size
        from .. import native
        if not native.available():
            raise RuntimeError(
                "batched engine requires the native packer "
                "(language_detector_tpu/native/build.sh); "
                "use detect_scalar without it")
        # Running totals for observability (service /metrics): batches
        # scored, packer-fallback docs, and docs that failed the
        # good-answer gate into the batched recursion
        self.stats = {"batches": 0, "fallback_docs": 0,
                      "scalar_recursion_docs": 0,
                      # DEVICE program launches (excludes the all-C tiny
                      # path) — what the recycle watcher meters, since
                      # the tunneled plugin's RSS leak is per dispatch
                      "device_dispatches": 0,
                      # bucketed-scheduler counters: dispatches per shape
                      # tier ("mixed" = small streams that skip the tier
                      # split), retry-lane dispatches (gate recursions
                      # overlapped with main lanes), and documents
                      # answered by batch-internal dedup
                      "tier_short_dispatches": 0,
                      "tier_mid_dispatches": 0,
                      "tier_long_dispatches": 0,
                      "tier_mixed_dispatches": 0,
                      "retry_lane_dispatches": 0,
                      "dedup_docs": 0,
                      # gate-failed docs resolved scalar because the
                      # flush was near its deadline or the brownout
                      # ladder disabled the retry lane (trace.no_retry)
                      "retry_skipped_docs": 0,
                      # long-doc lane: span-split documents, the
                      # sub-documents they became, and longdoc-lane
                      # dispatches (_count_tier reads the lane name)
                      "longdoc_split_docs": 0,
                      "longdoc_subdocs": 0,
                      "tier_longdoc_dispatches": 0,
                      # retried docs packed into a lane that does not
                      # match their own tier — the mixed-stream retry
                      # inflation the tier-keyed bins eliminate; bench
                      # asserts this stays 0
                      "retry_offtier_docs": 0,
                      # docs answered on the all-C tiny-batch path.
                      # Pre-seeded so the stats dict's key set is fixed
                      # at init: snapshot copies and key insertion must
                      # not race (stats_snapshot)
                      "c_path_docs": 0}
        self._stats_lock = make_lock("engine.stats")
        # -- dispatch pipeline (round 9) ------------------------------
        # depth = max scheduler jobs in flight on the device; 1 = the
        # strictly serial pack->score->epilogue reference path. The
        # in-flight bound the schedulers use is depth+1 (one batch may
        # finish fetching while depth batches queue behind it), 0
        # outstanding-while-packing at depth 1.
        self.pipeline_depth = max(1, knobs.get_int("LDT_PIPELINE_DEPTH")
                                  or 1)
        self.longdoc_chunk_slots = (
            knobs.get_int("LDT_LONGDOC_CHUNK_SLOTS") or 0
            if longdoc_chunk_slots is None else longdoc_chunk_slots)
        # engage threshold: splitting costs a Python span scan plus a
        # merge, and a gate-failed doc re-scores whole anyway, so the
        # lane takes only the fat tail where bucket inflation (and the
        # packer's per-span candidate ceiling) actually bites; docs
        # between the sub-pack size and this ride their tier unsplit
        self.longdoc_split_slots = max(
            self.longdoc_chunk_slots,
            knobs.get_int("LDT_LONGDOC_SPLIT_SLOTS") or 0
            if longdoc_split_slots is None else longdoc_split_slots)
        # LDT_HINTS=1: hinted batches additionally carry per-doc dense
        # prior vectors (hints.prior_vector) that the device reduction
        # adds to observed languages before the top-2 select. Off (the
        # default) no wire key exists and every traced program is
        # byte-identical to the pre-feature engine.
        self.hint_priors_enabled = knobs.get_bool("LDT_HINTS")
        # host staging ring for the wire's bucketed arrays: capacity
        # covers the in-flight bound plus the batch being packed
        self._staging = native.StagingRing(
            cap=self._inflight_bound() + 1)
        # donation composes with the plain single-lane scorer only (the
        # sharded/pooled programs keep their own jit); depth 1 keeps
        # the non-donating scorer so the serial path stays the exact
        # pre-pipeline program
        self._donate = (self.pipeline_depth > 1 and
                        self._score_fn is self._kernel.score)
        if self._donate:
            import warnings
            # CPU backends warn that buffer donation is unimplemented
            # and fall back to copying — expected on the simulator.
            # Matched by message, not module, so it covers every
            # donated kernel variant (xla/fused/lax/pallas fallback)
            warnings.filterwarnings(
                "ignore",
                message="Some donated buffers were not usable")
        # overlap accounting: pack wall time total/overlapped (a pack
        # counts as overlapped when any dispatch was in flight when it
        # started), donation hits, longdoc chunk count. Own lock — the
        # pack hot path must not contend with stats_snapshot readers.
        self._pipe = {"pack_ms_total": 0.0, "pack_ms_overlapped": 0.0,
                      "donation_hits": 0, "longdoc_chunks": 0}
        self._inflight = 0
        self._pipe_lock = make_lock("engine.pipe")
        # -- data-plane integrity (integrity.py) ----------------------
        # simulated (single-device) pool lanes each carry their own
        # table reference so the integrity monitor can quarantine and
        # re-upload one lane without touching the others; mesh lanes
        # keep dt=None (their sharded programs own table placement)
        if self.pool is not None and mesh is None:
            for ln in self.pool.lanes:
                ln.dt = self.dt
        # -- AOT executable bundle (aot.py, round 16) -----------------
        # lookup-first dispatch + compile write-back for the plain
        # single-device scorer (the sharded mesh programs keep their
        # own jit — their executables embed mesh topology and are not
        # portable across fleet shapes). Simulated pool lanes share the
        # scorer/table identity, so they ride the same bundle; a
        # quarantine-healed lane carries a fresh dt and the identity
        # guard in _launch_raw routes it back to the compile path.
        from .. import aot as aot_mod
        self._aot = aot_mod.build_from_env(self._kernel.mode, self.dt) \
            if mesh is None else None
        from .. import integrity as integrity_mod
        self.integrity = integrity_mod.build_from_env(self)

    def stats_snapshot(self) -> dict:
        """Copy of the running stats under the stats lock — the only
        safe way for another thread (the /metrics renderers) to read
        them; iterating the live dict races flush-worker updates."""
        with self._stats_lock:
            return dict(self.stats)

    def _inflight_bound(self) -> int:
        """Scheduler in-flight bound: how many dispatched jobs may be
        outstanding while the main thread packs the next one. Depth 1
        is strictly serial (0 outstanding — collect right after every
        submit); depth d >= 2 allows d+1 so one batch can drain while d
        queue behind it (the round-5 engine's hardcoded 3 == depth 2)."""
        d = self.pipeline_depth
        return 0 if d == 1 else d + 1

    def pipeline_stats(self) -> dict:
        """Dispatch-pipeline snapshot for /metrics and /debug/vars:
        overlap ratio (overlapped pack wall time / total pack wall
        time), configured depth, donation hits, staging-ring state, and
        longdoc chunk production."""
        with self._pipe_lock:
            p = dict(self._pipe)
            inflight = self._inflight
        ring = self._staging.stats()
        total = p["pack_ms_total"]
        return {
            "depth": self.pipeline_depth,
            "kernel": self._kernel.mode,
            "kernel_requested": self._kernel.requested,
            "kernel_reason": self._kernel.reason,
            "overlap_ratio":
                round(p["pack_ms_overlapped"] / total, 4) if total
                else 0.0,
            "pack_ms_total": round(total, 3),
            "pack_ms_overlapped": round(p["pack_ms_overlapped"], 3),
            "inflight": inflight,
            "donation_hits": p["donation_hits"],
            "longdoc_chunks": p["longdoc_chunks"],
            "staging_ring_occupancy": ring["occupancy"],
            "staging_ring_hits": ring["hits"],
            "staging_ring_misses": ring["misses"],
            "staging_ring_shapes": ring["shapes"],
        }

    # -- device dispatch ----------------------------------------------------

    def _launch_raw(self, cb, lane: str = "main", score_fn=None,
                    dt=None):
        """Launch a jitted scorer over a packed wire, metering compile
        events: the first execution of a new padded wire shape on a lane
        increments ldt_xla_compiles_total{lane=} and records the launch
        wall time (jit traces + compiles synchronously inside the
        dispatch call, so the elapsed time of a fresh-shape launch IS
        the compile cost; warm launches return in microseconds and are
        not timed at all — the hot path stays one set lookup).
        score_fn: the pool passes each lane's own program; the compile
        key carries its identity so per-lane first compiles meter as
        compiles instead of hiding behind another lane's warm mark.
        dt: the pool passes each lane's own device tables (integrity
        quarantine re-uploads per lane); None = the engine's shared
        upload — identical buffers, identical program."""
        if score_fn is None:
            score_fn = self._score_fn
        if dt is None:
            dt = self.dt
        # AOT bundle lookup (aot.py): only the canonical scorer over
        # the engine's own tables can match a serialized executable —
        # donated rewires, per-lane healed tables, and sharded programs
        # all fall through to the compile path below
        aot = self._aot if (score_fn is self._kernel.score and
                            dt is self.dt) else None
        if aot is not None:
            loaded = aot.lookup(cb.wire)
            if loaded is not None:
                # a deserialized executable is not a compile: skip the
                # first_seen meter and the donation rewire (the bundle
                # program was exported non-donating) and dispatch
                if faults.ACTIVE is not None:
                    faults.hit("scorer_launch")
                return loaded(dt, cb.wire)
        if self._donate and score_fn is self._kernel.score:
            # pipelined depth: donate the wire into the scorer so the
            # device reuses the transferred buffers (ops/kernels.py);
            # the host staging arrays are safe to reuse once the call
            # returns — jax copies numpy inputs synchronously
            score_fn = self._kernel.donated
            with self._pipe_lock:
                self._pipe["donation_hits"] += 1
            telemetry.REGISTRY.counter_inc(
                "ldt_pipeline_donation_hits_total")
        # fault seam BEFORE first_seen: an injected launch error must
        # not consume the first-shape marker and mislabel the real
        # retry's compile as warm
        if faults.ACTIVE is not None:
            faults.hit("scorer_launch")
        key = (self._mesh_size, id(score_fn),
               tuple(sorted((k, tuple(np.shape(v)))
                            for k, v in cb.wire.items())))
        if not telemetry.REGISTRY.compiles.first_seen(lane, key):
            fut = score_fn(dt, cb.wire)
            if aot is not None:
                # warm shape, no usable bundle entry (refused or never
                # written by this process's meter — another engine in
                # the process warmed the registry first): still write
                # back so a stale bundle self-heals. offer() memoizes
                # per shape, so steady state pays one set probe.
                aot.offer(cb.wire, self._kernel.score, dt)
            return fut
        if faults.ACTIVE is not None:
            faults.hit("compile")
        t0 = _time.monotonic()
        fut = score_fn(dt, cb.wire)
        telemetry.REGISTRY.counter_inc("ldt_xla_compiles_total",
                                       lane=lane)
        telemetry.REGISTRY.histogram("ldt_xla_compile_ms", lane=lane) \
            .observe((_time.monotonic() - t0) * 1e3)
        if aot is not None:
            # write-back: export the canonical (non-donated) scorer for
            # this tier shape so the next generation loads instead of
            # compiling; re-lowering here is served by the persistent
            # compile cache and happens once per shape per process
            aot.offer(cb.wire, self._kernel.score, dt)
        return fut

    def _launch(self, cb, lane: str = "main", trace=None):
        """Dispatch a packed wire: the direct jitted launch when the
        device pool is off (LDT_POOL_LANES unset — byte-identical to
        the pool-less engine), else a pool-supervised launch whose
        returned future carries straggler hedging and lost-batch
        failover (parallel/pool.py). Every fetch site already uses
        np.asarray(fut), which is exactly the pool future's supervised
        entry point."""
        with self._pipe_lock:
            self._inflight += 1
        try:
            if self.pool is None:
                return self._launch_raw(cb, lane)
            return self.pool.launch(
                lambda pl: self._launch_raw(cb, lane, pl.score_fn,
                                            pl.dt),
                trace=trace)
        except BaseException:
            # failed launch: the flush errors as a unit (the batcher
            # retries with a fresh pack), so retire the lease here
            with self._pipe_lock:
                self._inflight -= 1
            cb.release_staging()
            raise

    def _fetch_rows(self, cb, fut) -> np.ndarray:
        """Resolve a dispatch future and unpack it against the wire's
        chunk meta, then retire the dispatch: decrement the in-flight
        gauge (overlap accounting) and hand the wire's staging lease
        back to the ring. The release happens only AFTER
        unpack_chunks_out — it reads cb.wire["cmeta"] on the host, and
        a re-acquired lease zero-fills its arrays. On the pooled path
        the lease is released when the pool future SETTLES — a
        straggler hedge or failover may re-read the wire until its
        last launch attempt finishes (parallel/pool.py settled
        accounting); the direct path has no further reader."""
        try:
            out = np.asarray(fut)
            rows = unpack_chunks_out(out, cb.wire["cmeta"])
        except BaseException:
            # failed fetch: no retry reuses this pack (the pool only
            # surfaces errors after its failover budget), so the lease
            # must not leak
            with self._pipe_lock:
                self._inflight -= 1
            cb.release_staging()
            raise
        with self._pipe_lock:
            self._inflight -= 1
        if cb.staging is not None:
            settle = getattr(fut, "on_settled", None)
            if settle is not None:
                settle(cb.release_staging)
            else:
                cb.release_staging()
        return rows

    def score_chunk_batch(self, cb) -> np.ndarray:
        """Run the jitted device program over a ChunkBatch; returns the
        flat [G, 5] chunk-summary rows on host (test/debug seam)."""
        return self._fetch_rows(cb, self._launch(cb))

    # -- public API ---------------------------------------------------------

    # Per-dispatch content budget (chars; bytes <= 4x): device memory is
    # linear in total chunk rows (~1KB/row for the [G, 256] tote
    # accumulator plus decode intermediates), so slices bound TEXT VOLUME
    # as well as document count — a batch of 100KB documents splits into
    # several dispatches instead of one HBM-exhausting grid. 3M chars ~
    # 50-80K chunk rows ~ 50-100MB peak per dispatch; measured faster
    # than 6M on realistic mixes because a long-doc-heavy batch then
    # splits into 2+ slices whose packs, fetches, and gate-failure
    # retries overlap on the pipeline (+16% mixed, clean unchanged —
    # a clean 16K-doc service batch stays a single slice either way).
    DISPATCH_CHAR_BUDGET = 3 << 20

    # detect_codes batches at or under this size answer on the all-C
    # path instead of dispatching: 64 docs x ~1ms/doc stays under the
    # backend's fixed ~95ms dispatch latency
    TINY_BATCH_C_PATH = 64

    def detect_batch(self, texts: list[str], hints=None,
                     is_plain_text: bool = True,
                     return_chunks: bool = False) -> list:
        """ScalarResult-compatible results, one per text (EpilogueResult
        views for device-scored docs, real ScalarResults for scalar-path
        docs). hints: optional hints.CLDHints applied to every document
        of the call; is_plain_text=False strips HTML host-side and scans
        lang= tags into per-document hint priors — both stay on the
        device path. return_chunks fills each result's per-byte-range
        vector via the device path's want_ranges sidecars (exotic docs
        — squeeze, fallback, gate retry — resolve via the scalar
        engine, exactness over speed)."""
        if not texts:
            return []
        if self.flags & ~_DEVICE_OK_FLAGS:
            return [detect_scalar(t, self.tables, self.reg, self.flags,
                                  hints=hints,
                                  is_plain_text=is_plain_text,
                                  want_chunks=return_chunks)
                    for t in texts]
        if return_chunks:
            if hints is not None or not is_plain_text:
                # hinted/HTML chunk vectors keep the scalar engine (the
                # composed HTML offset maps live there)
                return [detect_scalar(t, self.tables, self.reg,
                                      self.flags, hints=hints,
                                      is_plain_text=is_plain_text,
                                      want_chunks=True)
                        for t in texts]
            return self._detect_with_chunks(texts)
        if hints is not None or not is_plain_text:
            return self._detect_hinted(texts, hints, is_plain_text)
        if sum(len(t) for t in texts) > self.DISPATCH_CHAR_BUDGET:
            return self.detect_many(texts, batch_size=len(texts))
        cb, fut = self._dispatch(texts)
        return self._finish(texts, cb, fut)

    def _detect_with_chunks(self, texts: list[str]) -> list:
        """Batched detection WITH per-range chunk vectors: want_ranges
        pack (per-slot/per-chunk offset sidecars) + the full-output
        device word (lang2/rd/rs), then the host replays the scalar
        vector path exactly — boundary sharpening over the resolved hit
        lanes (shifting the epilogue's chunk byte weights, like the
        reference's vector mode), the shared record merge, and scalar
        resolution for any doc whose offsets cannot map back (squeeze /
        fallback / gate retry). Low-volume API path: no pipelining."""
        from .. import native
        from ..ops.device_tables import host_tables
        from ..ops.score import unpack_chunks_out2
        from ..result_vector import build_doc_records, chunks_for_doc
        out: list = []
        for chunk in self._slices(texts, 16384):
            cb = native.pack_chunks_native(
                chunk, self.tables, self.reg, flags=self.flags,
                l_doc=self.max_slots, c_doc=self.max_chunks,
                want_ranges=True)
            full = np.asarray(self._kernel.full(self.dt, cb.wire))
            rows = unpack_chunks_out(full[..., 0], cb.wire["cmeta"])
            rows2 = unpack_chunks_out2(full[..., 1])
            cnsl2 = cb.wire["cnsl"].astype(np.int64)
            cstart_flat = (np.cumsum(cnsl2, axis=-1) - cnsl2).reshape(-1)
            cat_ind2 = host_tables(self.tables, self.reg).cat_ind2
            # records first: sharpening edits rows' byte weights, which
            # the epilogue must consume (vector-mode DocTote semantics)
            doc_recs = [build_doc_records(b, cb, rows, rows2,
                                          cstart_flat, cat_ind2,
                                          self.tables, self.reg)
                        for b in range(len(chunk))]
            ep = native.epilogue_flat_native(rows, cb, self.flags,
                                             self.reg)
            n_fb = n_retry = 0
            for b, text in enumerate(chunk):
                if doc_recs[b] is None or ep[b, 12]:
                    if cb.fallback[b]:
                        n_fb += 1
                    else:
                        n_retry += 1
                    out.append(detect_scalar(
                        text, self.tables, self.reg, self.flags,
                        want_chunks=True))
                    continue
                res = _result_from_row(ep[b])
                res.chunks = chunks_for_doc(text, doc_recs[b], self.reg)
                out.append(res)
            with self._stats_lock:
                self.stats["batches"] += 1
                self.stats["device_dispatches"] += 1
                self.stats["fallback_docs"] += n_fb
                self.stats["scalar_recursion_docs"] += n_retry
        return out

    def detect_spans(self, texts: list[str]) -> list:
        """Per-span language verdicts (the LDT_SPANS surface): each text
        splits on script-span boundaries exactly like the long-doc lane
        (preprocess/pack.py split_longdoc — the only exact split
        points), every sub-document scores as its own row range of one
        flat pack, the MERGED epilogue yields the whole-document
        summary (identical to the unsplit answer — the longdoc-lane
        invariant) and the UNMERGED per-sub-doc epilogue yields the
        span verdicts. Results are ScalarResult-compatible with .spans
        = [(byte_offset, byte_len, code, pct, reliable)] tiling the
        document's bytes (engine_scalar.span_coverage_records).
        Exception docs keep exactness over speed: a sub-doc the packer
        flagged or that failed the gate resolves its span via the
        scalar engine, and a merged-doc exception resolves the whole
        summary scalar — so the emitted records are bit-identical to
        detect_scalar_spans on every document. Low-volume API path
        (LDT_SPANS requests only): no pipelining, no retry lane."""
        from ..engine_scalar import SPAN_SPLIT_SLOTS, detect_scalar_spans
        if not texts:
            return []
        budget = self.longdoc_chunk_slots or SPAN_SPLIT_SLOTS
        if self.flags & ~_DEVICE_OK_FLAGS:
            return [detect_scalar_spans(t, self.tables, self.reg,
                                        self.flags, budget)
                    for t in texts]
        out: list = []
        for chunk in self._slices(texts, 16384):
            out.extend(self._detect_spans_slice(chunk, budget))
        return out

    def _detect_spans_slice(self, texts: list[str],
                            budget: int) -> list:
        from .. import native
        from ..engine_scalar import span_coverage_records, split_for_spans
        from ..result_vector import merge_longdoc_chunks
        subs_all: list = []
        groups: list = []
        bounds_all: list = []
        for t in texts:
            subs, bounds = split_for_spans(t, self.tables, budget)
            groups.append((len(subs_all), len(subs)))
            subs_all.extend(subs)
            bounds_all.append(bounds)
        cb = self._pack(subs_all)
        rows = self._fetch_rows(cb, self._launch(cb, "spans"))
        # per-sub-doc verdicts come from the UNMERGED epilogue (the rows
        # the merge used to discard — satellite of the span work), the
        # whole-doc summary from the merged one
        sub_ep = native.epilogue_flat_native(rows, cb, self.flags,
                                             self.reg)
        mrows, mcb, _ = merge_longdoc_chunks(rows, cb, groups,
                                             keep_spans=True)
        ep = native.epilogue_flat_native(mrows, mcb, self.flags,
                                         self.reg)
        results: list = []
        n_fb = 0
        for j, text in enumerate(texts):
            s, n = groups[j]
            verdicts = []
            for k in range(n):
                i = s + k
                row = sub_ep[i]
                if cb.fallback[i] or cb.squeezed[i] or row[12]:
                    r = detect_scalar(subs_all[i], self.tables,
                                      self.reg, self.flags)
                    verdicts.append((self.reg.code(r.summary_lang),
                                     int(r.percent3[0]),
                                     bool(r.is_reliable)))
                else:
                    verdicts.append((self.reg.code(int(row[0])),
                                     int(row[4]), bool(row[11])))
            if mcb.fallback[j] or mcb.squeezed[j] or ep[j, 12]:
                n_fb += 1
                res = detect_scalar(text, self.tables, self.reg,
                                    self.flags)
            else:
                res = _result_from_row(ep[j])
            res.spans = span_coverage_records(text, bounds_all[j],
                                              verdicts)
            results.append(res)
        with self._stats_lock:
            self.stats["batches"] += 1
            self.stats["device_dispatches"] += 1
            self.stats["fallback_docs"] += n_fb
        telemetry.REGISTRY.counter_inc("ldt_span_docs_total",
                                       len(texts))
        return results

    def _detect_hinted(self, texts: list[str], hints,
                       is_plain_text: bool) -> list:
        """Hinted / HTML detection on the device path: hint priors ride
        the wire as extra chunk slots (hint_lp window), whacks as
        per-chunk mask rows, and HTML cleans host-side before packing
        (the scalar engine does the same pre-pass, so segmentation sees
        identical bytes). Slices respect the same content-volume budget
        as the plain path. Gate-failing and fallback docs run the scalar
        engine with the ORIGINAL text + hints — exactness over speed on
        this low-volume path."""
        from .. import native
        from ..hints import apply_hints, prior_vector
        from ..preprocess.html import clean_html
        if is_plain_text:
            # without HTML there is no per-document hint input (lang=
            # scanning is the only one): one HintBoosts serves the batch
            shared_hb = apply_hints("", True, hints, self.tables,
                                    self.reg)
            hbs = [shared_hb] * len(texts)
            clean = texts
        else:
            hbs = [apply_hints(t, False, hints, self.tables, self.reg)
                   for t in texts]
            clean = [clean_html(t, self.tables)[0] for t in texts]
        # LDT_HINTS=1: densify each doc's boosts into the prior plane
        # the reduction adds pre-top-2; the plain-text batch shares one
        # plane (same HintBoosts), deduped to one table row by the pack
        prs = ([prior_vector(hb, self.tables) for hb in hbs]
               if self.hint_priors_enabled else None)

        # budget-sliced jobs carrying (clean slice, original slice, hint
        # slice); the shared pipeline overlaps pack/score across slices
        def jobs():
            pos = 0
            for chunk in self._slices(clean, 16384):
                n = len(chunk)
                yield (chunk, texts[pos:pos + n], hbs[pos:pos + n],
                       prs[pos:pos + n] if prs is not None else None)
                pos += n

        def pack(job):
            chunk, _, hb_slice, pr_slice = job
            return self._pack(chunk, hint_boosts=hb_slice,
                              hint_priors=pr_slice)

        def finish(job, cb, fut):
            # hinted twin of _epilogue/_finish: BOTH exception classes
            # (packer fallback, gate failure) resolve via the scalar
            # engine with the ORIGINAL text + hints — the batched retry
            # pass does not carry hint state
            _, orig, _, _ = job
            rows = self._fetch_rows(cb, fut)
            ep = native.epilogue_flat_native(rows, cb, self.flags,
                                             self.reg)
            out: list = []
            n_fb = n_retry = 0
            for b, text in enumerate(orig):
                if ep[b, 12]:
                    if cb.fallback[b]:
                        n_fb += 1
                    else:
                        n_retry += 1
                    out.append(detect_scalar(
                        text, self.tables, self.reg, self.flags,
                        hints=hints, is_plain_text=is_plain_text))
                else:
                    out.append(EpilogueResult(ep[b].tolist()))
            with self._stats_lock:
                self.stats["batches"] += 1
                self.stats["device_dispatches"] += 1
                self.stats["fallback_docs"] += n_fb
                self.stats["scalar_recursion_docs"] += n_retry
            return out

        results: list = []
        with self._gc_paused():
            for part in self._pipelined_jobs(jobs(), pack, finish):
                results.extend(part)
        return results

    @staticmethod
    @contextlib.contextmanager
    def _gc_paused():
        """Interpreter tuning for a bulk-detection call, always restored
        on exit. Two knobs:

        - pause the cyclic GC: each batch creates ~2 small acyclic
          objects per document (epilogue row list + result view), which
          trips several young-gen scans per batch — measured ~19ms of
          the single core per 16K docs, with zero cyclic garbage to
          find (refcounting frees them);
        - drop the GIL switch interval 5ms -> 1ms: the main thread
          re-acquires the GIL after every C++ pack while pool workers
          hold it for result building — at the default interval each
          handoff can stall the pack loop for most of 5ms (measured
          ~2-6% end-to-end on the single-core host).

        Used by the non-generator entry points only, so the try/finally
        always runs — never from inside a generator, whose finally
        could be stranded by an abandoned iterator. Both knobs are
        process-global, so a depth counter makes overlapping bulk
        calls from different threads safe: the first entry saves and
        sets, the last exit restores (naive save/restore would leave a
        stale value behind whichever call exits last).

        Cyclic garbage made by OTHER threads while the GC is paused is
        bounded two ways: a forced gc.collect() every GC_COLLECT_EVERY
        bulk exits (under sustained overlapping service flushes the
        pause depth may NEVER reach 0, so exit-only collection would be
        unbounded), and the normal re-enable when depth does return to
        0. The collect runs outside the lock — it can take tens of ms
        and must not stall other flushes' enter/exit."""
        import gc
        import sys
        cls = NgramBatchEngine
        with cls._bulk_lock:
            cls._bulk_depth += 1
            if cls._bulk_depth == 1:
                cls._bulk_saved = (gc.isenabled(),
                                   sys.getswitchinterval())
                if cls._bulk_saved[0]:
                    gc.disable()
                sys.setswitchinterval(0.001)
        try:
            yield
        finally:
            collect_now = False
            with cls._bulk_lock:
                cls._bulk_depth -= 1
                cls._bulk_since_collect += 1
                if cls._bulk_depth == 0:
                    was_enabled, prev_si = cls._bulk_saved
                    sys.setswitchinterval(prev_si)
                    if was_enabled:
                        gc.enable()
                if cls._bulk_since_collect >= cls.GC_COLLECT_EVERY:
                    cls._bulk_since_collect = 0
                    collect_now = True
            if collect_now:
                gc.collect()

    # Streams with more unique documents than this partition into
    # per-tier dispatch lanes (the preprocess.pack shape-tier ladder);
    # smaller streams keep one mixed lane — every dispatch pays the
    # backend's fixed ~95ms latency, so splitting a small flush three
    # ways buys nothing and costs two extra launches.
    TIER_MIN_DOCS = 1024

    # A tier lane below this many docs folds into the next wider lane
    # instead of paying its own dispatch (e.g. a mixed stream whose
    # "mid" tier holds 74 docs). Routing-only, like the ladder itself.
    TIER_COALESCE_MIN = 256

    # Retry lane: gate-failed docs accumulate across slices and dispatch
    # as soon as this many are pending, overlapping the recursion pass
    # with still-running main lanes instead of serializing one batched
    # pass at stream end. Smaller residues flush during the drain.
    RETRY_LANE_MIN = 64

    def detect_many(self, texts: list[str],
                    batch_size: int = 16384, trace=None) -> list:
        """Multi-batch detection through the shape-bucketed scheduler;
        returns ScalarResult-compatible rows (EpilogueResult views;
        scalar-path docs get real ScalarResults). Sustained-throughput
        entry point for the service layer and bench. trace: optional
        telemetry.Trace the scheduler records its stage spans into
        (dedup, tier planning, pack, dispatch, retry lane)."""
        if self.flags & ~_DEVICE_OK_FLAGS or not texts:
            return self.detect_batch(texts)
        with self._gc_paused():
            return self._detect_stream(texts, batch_size, self._finish,
                                       trace=trace)

    def _detect_stream(self, texts: list[str], batch_size: int,
                       finish_fn, patch_value=None, trace=None):
        """Shape-bucketed stream scheduler. Three moves on top of the
        round-5 pipeline:

        1. batch-internal DEDUP: each distinct text is scored once and
           its result fanned out to every duplicate position (hot
           documents — retweets, boilerplate, spam — are the dominant
           repeat pattern at service scale);
        2. TIER PARTITION: unique docs split by estimated slot demand
           into the pack ladder's short/mid/long lanes, so each lane's
           slices bucket-pad against peers instead of the global worst
           case (an all-one-tier stream degenerates to exactly the old
           single-lane behavior);
        3. pipelined RETRY LANE: gate-failed docs aggregate across
           slices and re-dispatch mid-stream on the same worker pool,
           overlapping the recursion with the next main batch instead
           of serializing one pass after the stream.

        finish_fn is _finish or _finish_codes (must accept deferred=);
        patch_value converts a retry/fallback ScalarResult into the
        stream's value type (identity for results, summary id for
        codes). Returns the complete per-doc value list in input
        order."""
        if patch_value is None:
            patch_value = lambda r: r  # noqa: E731
        # near-deadline flushes skip the pipelined retry lane: a gate
        # recursion is a second device round the budget cannot cover,
        # while the scalar resolution in _epilogue is immediate and
        # exact. 2x expected latency = this flush + a retry round.
        if trace is not None and not getattr(trace, "no_retry", False):
            dl = getattr(trace, "deadline", None)
            if dl is not None:
                from ..service.admission import expected_flush_ms
                if dl.remaining_ms() < 2.0 * expected_flush_ms():
                    trace.no_retry = True
        out: list = [None] * len(texts)
        # -- dedup: first occurrence scores, the rest copy ------------
        t_stage = _time.monotonic()
        first: dict = {}
        uniq_idx: list = []   # global index of each unique doc
        uniq_txt: list = []
        dups: list = []       # (duplicate global index, unique position)
        for i, t in enumerate(texts):
            p = first.get(t)
            if p is None:
                first[t] = len(uniq_txt)
                uniq_idx.append(i)
                uniq_txt.append(t)
            else:
                dups.append((i, p))
        if dups:
            with self._stats_lock:
                self.stats["dedup_docs"] += len(dups)
        t_stage = telemetry.observe_stage("dedup", t_stage, trace=trace)
        # -- long-doc lane: span-aligned splitting --------------------
        # Docs whose slot demand exceeds the top bucket split into
        # span-exact sub-packs (preprocess/pack.py split_longdoc) and
        # score as ordinary bucket-ladder work; the merge back into one
        # summary happens in the scheduler's longdoc worker. Only the
        # CHEAP pre-gate runs here (length bound + one vectorized
        # script scan): the Python span scan itself streams through
        # the scheduler's dispatch loop, overlapping the device rounds
        # of the main lanes instead of serializing ahead of them. A
        # candidate the scheduler then fails to split spills back into
        # an ordinary wide-lane job there.
        from ..preprocess.pack import (N_TIERS, TIER_NAMES,
                                       _TIER_BASE_SLOTS,
                                       _maybe_multi_span, tier_of_text)
        ld_cand: set = set()
        if self.longdoc_chunk_slots > 0:
            # length pre-gate: est_slot_demand is 8 + len//4, so docs
            # under the char threshold can never exceed the engage
            # threshold (longdoc_split_slots >= the sub-pack size)
            min_chars = (self.longdoc_split_slots
                         - _TIER_BASE_SLOTS) << 2
            for p, t in enumerate(uniq_txt):
                if len(t) > min_chars and \
                        _maybe_multi_span(t, self.tables):
                    ld_cand.add(p)
        ld_cands = [(uniq_idx[p], uniq_txt[p]) for p in sorted(ld_cand)]
        t_stage = telemetry.observe_stage("longdoc_split", t_stage,
                                          trace=trace)
        # -- tier partition + per-lane volume slicing -----------------
        positions = ([p for p in range(len(uniq_txt))
                      if p not in ld_cand]
                     if ld_cand else list(range(len(uniq_txt))))
        if len(positions) > self.TIER_MIN_DOCS:
            by_tier: list = [[] for _ in range(N_TIERS)]
            for p in positions:
                by_tier[tier_of_text(uniq_txt[p])].append(p)
            # coalesce undersized lanes upward into the next wider
            # budget (routing-only: a wider lane holds smaller docs
            # bit-exactly) — a near-empty lane is a full dispatch
            # latency spent on a handful of docs. The widest lane
            # never coalesces: isolating the fat tail from the main
            # lane is the point of the ladder.
            for k in range(N_TIERS - 1):
                if 0 < len(by_tier[k]) < self.TIER_COALESCE_MIN:
                    by_tier[k + 1] = sorted(by_tier[k] + by_tier[k + 1])
                    by_tier[k] = []
            lanes = [(TIER_NAMES[k], lane)
                     for k, lane in enumerate(by_tier) if lane]
        else:
            lanes = [("mixed", positions)] if positions else []
        jobs: list = []  # (tier name, global indices, texts)
        for name, lane in lanes:
            ltxt = [uniq_txt[p] for p in lane]
            for s, e in self._slice_bounds([len(t) for t in ltxt],
                                           batch_size):
                jobs.append((name,
                             [uniq_idx[lane[p]] for p in range(s, e)],
                             ltxt[s:e]))
        telemetry.observe_stage("tier_plan", t_stage, trace=trace)
        # -- dispatch -------------------------------------------------
        if len(jobs) == 1 and not ld_cands:
            # single-dispatch fast path (the service batcher's common
            # flush): no pool, local deferred retry as before
            name, idxs, txts = jobs[0]
            self._count_tier(name)
            t0 = _time.monotonic()
            cb = self._pack(txts)
            telemetry.observe_stage("pack", t0, trace=trace)
            d: list = []
            vals = finish_fn(txts, cb, self._launch(cb, name,
                                                    trace=trace),
                             deferred=d, trace=trace)
            for g, v in zip(idxs, vals):
                out[g] = v
            if d:
                t0 = _time.monotonic()
                for g, r in self._retry_deferred(
                        [(idxs[b], t, sq) for b, t, sq in d]).items():
                    out[g] = patch_value(r)
                telemetry.observe_stage("retry_lane", t0, trace=trace)
        elif jobs or ld_cands:
            self._run_scheduler(jobs, batch_size, finish_fn,
                                patch_value, out, trace=trace,
                                ld_cands=ld_cands)
        for i, p in dups:
            out[i] = out[uniq_idx[p]]
        return out

    def _run_scheduler(self, jobs, batch_size, finish_fn, patch_value,
                       out, trace=None, ld_cands=None):
        """Multi-lane pipeline with the overlapped retry lane. The main
        thread only packs (C++, GIL-released); pool workers launch the
        device program and run the epilogue. In-flight depth comes from
        LDT_PIPELINE_DEPTH via _inflight_bound (depth 1 collects every
        dispatch before the next pack — the strictly serial reference
        path; depth 2, the default, keeps the device busy across the
        next pack plus one overlapped retry launch). Main jobs drop
        their gate failures into (squeezed, tier)-keyed retry bins;
        whenever a bin reaches RETRY_LANE_MIN the bin re-packs AT ITS
        OWN TIER and dispatches as a retry job on the SAME pending
        queue, so recursion rounds overlap main-lane scoring without
        inflating every retried doc to the tail lane's bucket shape
        (retry_offtier_docs audits that invariant — it must stay 0).
        Retry jobs carry FINISH so they can never defer again — the
        drain loop terminates. Long-doc CANDIDATES (pre-gated in
        _detect_stream) stream through the dispatch loop AFTER the main
        jobs: each one's Python span scan (split_longdoc) runs on the
        main thread while earlier dispatches score on the device —
        pack is GIL-released C++ and the device wait parks in XLA, so
        the scan is host work the pipeline hides. Split docs group by
        char volume into longdoc jobs (score as ordinary bucket-ladder
        work, merge per-chunk rows back into one virtual document via
        result_vector.merge_longdoc_chunks); candidates that refuse to
        split spill into ordinary wide-lane jobs at the end."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor
        from .. import native
        from ..preprocess.pack import split_longdoc, tier_of_text
        from ..result_vector import merge_longdoc_chunks

        retry_lock = make_lock("engine.retry")
        # (squeezed, tier) -> [(gidx, text)]
        retry_bins: dict = {}

        def run_main(lane, idxs, txts, cb):
            fut = self._launch(cb, lane, trace=trace)
            d: list = []
            vals = finish_fn(txts, cb, fut, deferred=d, trace=trace)
            if d:
                with self._stats_lock:
                    self.stats["scalar_recursion_docs"] += len(d)
                with retry_lock:
                    for b, t, sq in d:
                        retry_bins.setdefault(
                            (sq, tier_of_text(t)), []).append((idxs[b], t))
            return ("main", idxs, vals)

        def run_longdoc(cb, groups, gidx, origs):
            """One long-doc job: sub-documents score as a normal pack,
            then merge back into per-document chunk sequences for the
            flat epilogue. Exactness: the split is span-aligned and
            verify-checked (split_longdoc), the DocTote is additive, so
            the merged epilogue equals the unsplit one. Fallback or
            squeeze on any sub-doc resolves the WHOLE doc via the
            scalar engine; gate failures re-enter the stream retry
            bins UNSPLIT at their own tier — the REPEATS squeeze in
            the recursion pass dedups words across the whole document,
            so a span-split retry would keep cross-span repeats the
            reference deletes; only the clean first pass is safe to
            split. run_retry resolves them exactly like any deferred
            doc (scalar if still failing)."""
            t0 = _time.monotonic()
            rows = self._fetch_rows(
                cb, self._launch(cb, "longdoc", trace=trace))
            with self._stats_lock:
                self.stats["device_dispatches"] += 1
            mrows, mcb = merge_longdoc_chunks(rows, cb, groups)
            nch = int(mcb.n_chunks.sum())
            with self._pipe_lock:
                self._pipe["longdoc_chunks"] += nch
            telemetry.REGISTRY.counter_inc(
                "ldt_pipeline_longdoc_chunks_total", nch)
            ep = native.epilogue_flat_native(mrows, mcb, self.flags,
                                             self.reg)
            no_retry = trace is not None and getattr(trace, "no_retry",
                                                     False)
            patches: dict = {}
            gate_fail: list = []
            n_fb = n_skip = 0
            for j, g in enumerate(gidx):
                if mcb.fallback[j] or mcb.squeezed[j]:
                    n_fb += 1
                    patches[g] = detect_scalar(origs[j], self.tables,
                                               self.reg, self.flags)
                elif ep[j, 12]:
                    if no_retry:
                        n_skip += 1
                        patches[g] = detect_scalar(
                            origs[j], self.tables, self.reg, self.flags)
                    else:
                        gate_fail.append(j)
                else:
                    patches[g] = _result_from_row(ep[j])
            if gate_fail:
                with retry_lock:
                    for j in gate_fail:
                        retry_bins.setdefault(
                            (False, tier_of_text(origs[j])),
                            []).append((gidx[j], origs[j]))
            with self._stats_lock:
                self.stats["fallback_docs"] += n_fb
                self.stats["retry_skipped_docs"] += n_skip
                self.stats["scalar_recursion_docs"] += len(gate_fail)
            telemetry.observe_stage("longdoc", t0, trace=trace)
            return ("retry", patches)

        def run_retry(idxs, txts, cb, flags):
            t0 = _time.monotonic()
            rows = self._fetch_rows(
                cb, self._launch(cb, "retry", trace=trace))
            with self._stats_lock:
                self.stats["device_dispatches"] += 1
                self.stats["retry_lane_dispatches"] += 1
            ep = native.epilogue_flat_native(rows, cb, flags, self.reg)
            patches: dict = {}
            for b, text in enumerate(txts):
                # FINISH pass: a doc the packer still can't place, or
                # that still fails the (now forced) gate, goes scalar —
                # identical to _score_with_flags resolution
                if cb.fallback[b] or ep[b, 12]:
                    patches[idxs[b]] = detect_scalar(
                        text, self.tables, self.reg, self.flags)
                else:
                    patches[idxs[b]] = _result_from_row(ep[b])
            telemetry.observe_stage("retry_lane", t0, trace=trace)
            return ("retry", patches)

        pending: deque = deque()

        def collect(res):
            if res[0] == "main":
                _, idxs, vals = res
                for g, v in zip(idxs, vals):
                    out[g] = v
            else:
                for g, r in res[1].items():
                    out[g] = patch_value(r)

        bound = self._inflight_bound()
        with ThreadPoolExecutor(max(1, bound)) as pool:

            def submit_retries(min_docs):
                grabbed = []
                with retry_lock:
                    for key, docs in retry_bins.items():
                        if docs and len(docs) >= max(min_docs, 1):
                            grabbed.append((key, docs))
                            retry_bins[key] = []
                for (sq, tier), group in grabbed:
                    flags = self._retry_flags(sq)
                    gidx = [g for g, _ in group]
                    gtxt = [t for _, t in group]
                    # tier-keyed bins repack each doc at its own bucket
                    # shape; any doc landing off-tier is a routing bug
                    off = sum(1 for t in gtxt if tier_of_text(t) != tier)
                    if off:
                        with self._stats_lock:
                            self.stats["retry_offtier_docs"] += off
                    for s, e in self._slice_bounds(
                            [len(t) for t in gtxt], batch_size):
                        t0 = _time.monotonic()
                        cb = self._pack(gtxt[s:e], flags=flags)
                        telemetry.observe_stage("pack", t0, trace=trace)
                        pending.append(pool.submit(
                            run_retry, gidx[s:e], gtxt[s:e], cb, flags))

            def keep_bound():
                while len(pending) > bound:
                    collect(pending.popleft().result())
                submit_retries(self.RETRY_LANE_MIN)

            # long-doc job accumulator: each doc's sub-packs stay
            # contiguous in one job — the merge needs the whole chunk
            # sequence in one fetch
            cur_txt: list = []
            cur_groups: list = []
            cur_gidx: list = []
            cur_orig: list = []
            cur_vol = 0

            def flush_ld():
                nonlocal cur_txt, cur_groups, cur_gidx, cur_orig, \
                    cur_vol
                if not cur_txt:
                    return
                t0 = _time.monotonic()
                self._count_tier("longdoc")
                cb = self._pack(cur_txt)
                telemetry.observe_stage("pack", t0, trace=trace)
                pending.append(pool.submit(run_longdoc, cb, cur_groups,
                                           cur_gidx, cur_orig))
                cur_txt, cur_groups, cur_gidx, cur_orig = \
                    [], [], [], []
                cur_vol = 0
                keep_bound()

            # main jobs first: their dispatches put work on the device
            # so the long-doc span scans below run under it
            for name, idxs, txts in jobs:
                t0 = _time.monotonic()
                self._count_tier(name)
                cb = self._pack(txts)
                telemetry.observe_stage("pack", t0, trace=trace)
                pending.append(pool.submit(run_main, name, idxs,
                                           txts, cb))
                keep_bound()
            # stream the long-doc candidates: split (main-thread
            # Python, overlapped with the in-flight device rounds),
            # group by char volume, dispatch as the budget fills
            spill_idx: list = []
            spill_txt: list = []
            for gidx_one, text in (ld_cands or []):
                t0 = _time.monotonic()
                subs = split_longdoc(text, self.tables,
                                     self.longdoc_chunk_slots)
                telemetry.observe_stage("longdoc_split", t0,
                                        trace=trace)
                if not subs:
                    # pre-gate optimism didn't pan out: ride the wide
                    # lane unsplit with the other spills
                    spill_idx.append(gidx_one)
                    spill_txt.append(text)
                    continue
                with self._stats_lock:
                    self.stats["longdoc_split_docs"] += 1
                    self.stats["longdoc_subdocs"] += len(subs)
                vol = sum(len(s) for s in subs)
                if cur_txt and cur_vol + vol > self.DISPATCH_CHAR_BUDGET:
                    flush_ld()
                cur_groups.append((len(cur_txt), len(subs)))
                cur_txt.extend(subs)
                cur_gidx.append(gidx_one)
                cur_orig.append(text)
                cur_vol += vol
            flush_ld()
            for s, e in self._slice_bounds(
                    [len(t) for t in spill_txt], batch_size):
                t0 = _time.monotonic()
                self._count_tier("long")
                cb = self._pack(spill_txt[s:e])
                telemetry.observe_stage("pack", t0, trace=trace)
                pending.append(pool.submit(run_main, "long",
                                           spill_idx[s:e],
                                           spill_txt[s:e], cb))
                keep_bound()
            # drain: once pending empties no worker is running, so the
            # bins are stable and min_docs=1 flushes the residue
            while pending or any(retry_bins.values()):
                if pending:
                    collect(pending.popleft().result())
                submit_retries(self.RETRY_LANE_MIN if pending else 1)

    def _count_tier(self, name: str) -> None:
        with self._stats_lock:
            self.stats[f"tier_{name}_dispatches"] += 1

    def _pipelined_jobs(self, jobs, pack, finish):
        """Shared pipeline core: the main thread ONLY packs (C++,
        GIL-released); each pool worker launches its slice's device
        program — paying the host->device wire transfer there, off the
        critical path — then forces execution and runs the epilogue.
        Yields finish(job, cb, fut) values in job order. The in-flight
        bound comes from LDT_PIPELINE_DEPTH via _inflight_bound: depth
        1 collects each dispatch before the next pack (strictly serial
        reference path), depth 2 — the default — bounds at 3, which
        keeps the device queue full across the ~95ms dispatch latency
        of this host's TPU tunnel (>= 3 concurrent fetches reach the
        backend's overlap ceiling; concurrent launches from worker
        threads are the service batcher's proven pattern). A single-job
        call (the service batcher's common flush) skips the pool
        entirely — its flushes already overlap on the batcher's worker
        pool, and per-call thread spawning is real cost on the
        single-core host."""
        jobs = iter(jobs)
        first = next(jobs, None)
        if first is None:
            return
        second = next(jobs, None)
        if second is None:
            cb = pack(first)
            yield finish(first, cb, self._launch(cb))
            return
        from concurrent.futures import ThreadPoolExecutor
        import itertools

        def launch_and_finish(job, cb):
            return finish(job, cb, self._launch(cb))

        bound = self._inflight_bound()
        pending: list = []
        with ThreadPoolExecutor(max(1, bound)) as pool:
            for job in itertools.chain([first, second], jobs):
                cb = pack(job)
                pending.append(pool.submit(launch_and_finish, job, cb))
                while len(pending) > bound:
                    yield pending.pop(0).result()
            for f in pending:
                yield f.result()

    def _slices(self, texts: list[str], batch_size: int):
        """Batch slicing by document count AND content volume,
        preserving order; every slice holds at least one document.
        The volume target is BALANCED: total content divided over the
        minimum number of budget-respecting slices, so a 4.3M-char
        stream cuts into two ~2.1M slices instead of 3M + 1.3M — equal
        slices overlap on the pipeline, a runt tail mostly waits
        (never exceeding DISPATCH_CHAR_BUDGET, the device memory
        bound)."""
        for s, e in self._slice_bounds([len(t) for t in texts],
                                       batch_size):
            yield texts[s:e]

    def _slice_bounds(self, lengths: list[int], batch_size: int):
        """_slices' core over lengths alone: yields (start, end) bounds
        so the bucketed scheduler can slice index lists without building
        intermediate text lists. Same balanced-volume contract."""
        total = sum(lengths)
        n_slices = max(-(-total // self.DISPATCH_CHAR_BUDGET), 1)
        target = max(-(-total // n_slices), 1)
        start = 0
        vol = 0
        for i, ln in enumerate(lengths):
            if i > start and (i - start >= batch_size or
                              vol + ln > target):
                yield start, i
                start, vol = i, 0
            vol += ln
        if start < len(lengths):
            yield start, len(lengths)

    def _pack(self, texts: list[str], flags: int | None = None,
              hint_boosts: list | None = None,
              hint_priors: list | None = None):
        """Pack only (no device launch): the pipeline core launches on
        its worker pool so slice N's host->device transfer never blocks
        slice N+1's pack on the single-core host. Wire arrays come from
        the staging ring (steady state allocates nothing), and the pack
        is timed for the overlap ratio: it counts as overlapped when a
        dispatch was in flight while it ran — the stall the pipeline
        exists to erase."""
        from .. import native
        fl = self.flags if flags is None else flags
        pad = -len(texts) % self._mesh_size
        padded = list(texts) + [""] * pad if pad else texts
        if pad and hint_boosts is not None:
            hint_boosts = list(hint_boosts) + [None] * pad
        if pad and hint_priors is not None:
            hint_priors = list(hint_priors) + [None] * pad
        t0 = _time.monotonic()
        with self._pipe_lock:
            overlapped = self._inflight > 0
        cb = native.pack_chunks_native(
            padded, self.tables, self.reg, flags=fl,
            n_shards=self._mesh_size, l_doc=self.max_slots,
            c_doc=self.max_chunks, hint_boosts=hint_boosts,
            hint_priors=hint_priors,
            staging=self._staging)
        ms = (_time.monotonic() - t0) * 1e3
        with self._pipe_lock:
            self._pipe["pack_ms_total"] += ms
            if overlapped or self._inflight > 0:
                self._pipe["pack_ms_overlapped"] += ms
        return cb

    def _dispatch(self, texts: list[str], flags: int | None = None,
                  hint_boosts: list | None = None):
        """Pack + launch the device program asynchronously; returns
        (ChunkBatch, device future). Single-shot path (detect_batch,
        the gate-failure retry); the multi-slice pipeline uses _pack."""
        cb = self._pack(texts, flags, hint_boosts)
        return cb, self._launch(cb)

    def _epilogue(self, texts: list[str], cb, fut, deferred=None,
                  trace=None):
        """Fetch the device result, run the C++ document epilogue, and
        resolve the exception docs: packer fallbacks go scalar; docs
        failing the good-answer gate re-score as a BATCH with the
        recursion flags (TOP40|REPEATS|FINISH, plus SQUEEZE for docs
        whose first pass squeezed) — the reference's recursive
        DetectLanguageSummaryV2 call (impl.cc:2061-2105) run on the
        device instead of per-doc in the scalar engine.

        deferred: when given (the multi-slice pipeline), gate-failed
        docs are appended as (local index, text, squeezed) instead of
        retried here — the caller retries ONCE for the whole stream, so
        a mixed corpus split into S slices pays 1-2 retry rounds
        instead of up to 2S serial device rounds. Returns (ep [B, 14],
        {doc index: ScalarResult} patches). Runs on detect_many's
        worker pool, so stats updates take the lock. The "dispatch"
        stage is the device WAIT — from fetch start to rows on host —
        which is where a dispatch's time shows up under the depth-3
        pipeline (the launch itself is asynchronous)."""
        from .. import native
        if faults.ACTIVE is not None:
            try:
                faults.hit("device_flush")
            except BaseException:
                # the flush dies before its fetch: retire the dispatch
                # so the in-flight gauge and the staging ring cannot
                # drift when the batcher's failure path re-dispatches
                with self._pipe_lock:
                    self._inflight -= 1
                cb.release_staging()
                raise
        t0 = _time.monotonic()
        rows = self._fetch_rows(cb, fut)
        t1 = _time.monotonic()
        B = len(texts)
        ep = native.epilogue_flat_native(rows, cb, self.flags, self.reg)
        t2 = _time.monotonic()
        # stats and trace spans record only AFTER every fallible step
        # (the device fetch and the native epilogue): when a pool
        # failover or the batcher's failure path retries this dispatch,
        # counters and spans must come out exactly once
        telemetry.observe_stage("dispatch", t0, t1, trace=trace)
        telemetry.observe_stage("epilogue", t1, t2, trace=trace)
        # device-time vs host-time split per flush: the profiler's
        # always-on shadow (POST /profilez arms the real one)
        telemetry.REGISTRY.histogram(
            "ldt_device_ms", phase="device").observe((t1 - t0) * 1000.0)
        telemetry.REGISTRY.histogram(
            "ldt_device_ms", phase="host").observe((t2 - t1) * 1000.0)
        with self._stats_lock:
            self.stats["batches"] += 1
            self.stats["device_dispatches"] += 1
            self.stats["fallback_docs"] += int(cb.fallback[:B].sum())
        if self.integrity is not None:
            # between-flush scrub cadence (integrity.py): cheap clock
            # check when not due; a due pass digests each lane's device
            # tables and heals any quarantined lane before the next
            # flush can land on it. Never raises.
            self.integrity.maybe_scrub()
        patches: dict[int, ScalarResult] = {}
        need = np.flatnonzero(ep[:B, 12])
        if not need.size:
            return ep, patches
        local_retry: list = []  # (index, text, squeezed)
        no_retry = trace is not None and getattr(trace, "no_retry",
                                                 False)
        n_skipped = 0
        for b in need:
            b = int(b)
            if cb.fallback[b]:
                patches[b] = detect_scalar(texts[b], self.tables,
                                           self.reg, self.flags)
            elif no_retry:
                # deadline/brownout: resolve the gate failure scalar
                # NOW instead of queueing another device round —
                # detect_scalar runs the full reference algorithm
                # (internal recursion included), so the answer is
                # identical to the batched retry's
                patches[b] = detect_scalar(texts[b], self.tables,
                                           self.reg, self.flags)
                n_skipped += 1
            elif deferred is not None:
                deferred.append((b, texts[b], bool(cb.squeezed[b])))
            else:
                local_retry.append((b, texts[b], bool(cb.squeezed[b])))
        if n_skipped:
            with self._stats_lock:
                self.stats["retry_skipped_docs"] += n_skipped
        patches.update(self._retry_deferred(local_retry))
        return ep, patches

    def _retry_flags(self, squeezed: bool) -> int:
        return (self.flags | FLAG_TOP40 | FLAG_REPEATS | FLAG_FINISH |
                (FLAG_SQUEEZE if squeezed else 0))

    def _retry_deferred(self, deferred: list) -> dict:
        """One batched recursion pass over every gate-failed doc of a
        multi-slice stream: {global index: ScalarResult}."""
        if not deferred:
            return {}
        with self._stats_lock:
            self.stats["scalar_recursion_docs"] += len(deferred)
        patches: dict = {}
        for squeezed in (False, True):
            group = [(g, t) for g, t, sq in deferred if sq == squeezed]
            if not group:
                continue
            rs = self._score_with_flags([t for _, t in group],
                                        self._retry_flags(squeezed))
            for (g, _), r in zip(group, rs):
                patches[g] = r
        return patches

    def _finish(self, texts: list[str], cb, fut,
                deferred=None, trace=None) -> list:
        ep, patches = self._epilogue(texts, cb, fut, deferred, trace)
        # lazy row views instead of eager dataclasses: constructing 16K
        # ScalarResults costs ~70ms on the single-core host while most
        # consumers read one or two fields; the view defers field
        # extraction to attribute access (ScalarResult-compatible)
        results = [EpilogueResult(r) for r in ep[:len(texts)].tolist()]
        for b, r in patches.items():
            results[b] = r
        return results

    def _finish_codes(self, texts: list[str], cb, fut,
                      deferred=None, trace=None) -> np.ndarray:
        """Summary-language ids only (no per-doc result objects)."""
        ep, patches = self._epilogue(texts, cb, fut, deferred, trace)
        out = ep[:len(texts), 0].astype(np.int32)
        for b, r in patches.items():
            out[b] = r.summary_lang
        return out

    def detect_codes(self, texts: list[str],
                     batch_size: int = 16384, trace=None) -> list[str]:
        """Summary ISO codes only — the reference's production semantic
        (wrapper.cc:7-16 discards everything but the code string), so
        the service (server.py) and eval harness consume this. Skips
        per-document result materialization entirely, which matters on a
        single-core host."""
        if self.flags & ~_DEVICE_OK_FLAGS or not texts:
            return [self.reg.code(r.summary_lang)
                    for r in self.detect_batch(texts)]
        # tiny batches (a low-traffic service flush) skip the device:
        # the all-C pipeline answers in ~1ms/doc while any dispatch
        # pays the backend's fixed ~95ms latency — and the C path is
        # agreement-pinned against the device path (test_c_abi)
        if len(texts) <= self.TINY_BATCH_C_PATH and self.flags == 0:
            from .. import native
            t0 = _time.monotonic()
            ids = native.detect_batch_codes_native(texts, self.tables,
                                                   self.reg)
            if ids is not None:
                telemetry.observe_stage("c_path", t0, trace=trace)
                # count the flush: the service Prometheus gauges read
                # eng.stats, and a low-traffic service whose every
                # flush is tiny must not render as idle
                with self._stats_lock:
                    self.stats["batches"] += 1
                    self.stats["c_path_docs"] += len(texts)
                return self.reg.lang_code[ids].tolist()
        with self._gc_paused():
            vals = self._detect_stream(
                texts, batch_size, self._finish_codes,
                patch_value=lambda r: int(r.summary_lang),
                trace=trace)
        ids = np.fromiter((int(v) for v in vals), np.int32,
                          count=len(vals))
        return self.reg.lang_code[ids].tolist()

    def _score_with_flags(self, texts: list[str],
                          flags: int) -> list[ScalarResult]:
        """Device passes with explicit flags (the gate-failure retry;
        FINISH forces the gate so no further recursion happens), sliced
        by the same content-volume budget as the main path — a deferred
        retry group can span the whole stream — and run through the
        shared pipeline core so multi-slice retries overlap instead of
        paying a serial device round each. Docs the packer cannot place
        fall back to the scalar engine with the engine's own flags,
        exactly like a first-pass fallback."""
        from .. import native

        def pack(chunk):
            return self._pack(chunk, flags=flags)

        def finish(chunk, cb, fut):
            with self._stats_lock:
                self.stats["device_dispatches"] += 1
            rows = self._fetch_rows(cb, fut)
            ep = native.epilogue_flat_native(rows, cb, flags, self.reg)
            out: list = []
            for b, text in enumerate(chunk):
                row = ep[b]
                if cb.fallback[b] or row[12]:
                    out.append(detect_scalar(text, self.tables,
                                             self.reg, self.flags))
                    continue
                out.append(_result_from_row(row))
            return out

        results: list = []
        for part in self._pipelined_jobs(self._slices(texts, 16384),
                                         pack, finish):
            results.extend(part)
        return results


class EpilogueResult:
    """Lazy ScalarResult-compatible view over one ldt_epilogue_flat row
    (a plain 14-int list). Field extraction happens on attribute access —
    building 16K eager dataclasses per batch costs ~70ms of single-core
    host time the common consumers (code-only service path, top-1 eval)
    never use."""
    __slots__ = ("_r", "spans")
    chunks = None  # ResultChunk vectors come from the scalar engine only

    def __init__(self, row: list):
        self._r = row
        # per-span verdicts [(byte_offset, byte_len, code, pct,
        # reliable)] — filled only by the LDT_SPANS surface
        # (detect_spans); None everywhere else
        self.spans = None

    @property
    def summary_lang(self) -> int:
        return self._r[0]

    @property
    def language3(self) -> list:
        return self._r[1:4]

    @property
    def percent3(self) -> list:
        return self._r[4:7]

    @property
    def normalized_score3(self) -> list:
        return [float(x) for x in self._r[7:10]]

    @property
    def text_bytes(self) -> int:
        return self._r[10]

    @property
    def is_reliable(self) -> bool:
        return self._r[11] != 0

    def __repr__(self):
        return (f"EpilogueResult(summary_lang={self.summary_lang}, "
                f"language3={self.language3}, percent3={self.percent3}, "
                f"is_reliable={self.is_reliable})")



