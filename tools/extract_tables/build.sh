#!/bin/bash
# Build and run the CLD2 table extractor against the read-only reference
# snapshot, producing raw blobs in tools/extract_tables/out/.
set -euo pipefail
cd "$(dirname "$0")"

REF=/root/reference/cld2
OUT=out
BUILD=build
mkdir -p "$OUT" "$BUILD"

CXXFLAGS="-O2 -w -I$REF/internal -I$REF/public"

g++ $CXXFLAGS -c extract_main.cc -o $BUILD/extract_main.o
g++ $CXXFLAGS -c prop_dump.cc -o $BUILD/prop_dump.o

# Reference translation units: generated DATA tables + the state-table
# interpreter needed to run the property DFAs at extraction time.
for src in \
  cld2_generated_deltaocta0527 \
  cld2_generated_distinctocta0527 \
  cld_generated_cjk_delta_bi_32 \
  generated_distinct_bi_0 \
  cld2_generated_cjk_compatible \
  cld_generated_cjk_uni_prop_80 \
  cld_generated_score_quad_octa_1024_256 \
  generated_language \
  generated_ulscript \
  utf8statetable \
  offsetmap \
  ; do
  g++ $CXXFLAGS -c "$REF/internal/$src.cc" -o "$BUILD/$src.o"
done

g++ $BUILD/*.o -o $BUILD/extract_cld2_tables
./$BUILD/extract_cld2_tables "$OUT"
