"""Tests for the bounded model checker (tools/lint/model_check.py).

Three layers: the shipped products must exhaust their abstract state
spaces with zero invariant failures (and do so deterministically — the
checker runs under a fake clock with no randomness); the generic
explorer must actually DETECT violations when handed a deliberately
broken system; and the lint-facing check() wrapper must leave the
process-wide fault configuration alone.
"""
from __future__ import annotations

from pathlib import Path

import pytest

from tools.lint import model_check
from tools.lint.model_check import FakeClock, _explore, run_product

REPO = Path(__file__).resolve().parent.parent

PRODUCT_NAMES = [p[0] for p in model_check.PRODUCTS]


# -- the shipped products hold ------------------------------------------------


@pytest.mark.parametrize("name", PRODUCT_NAMES)
def test_product_exhausts_with_no_failures(name):
    failures, n_states, exhausted = run_product(name)
    assert failures == [], failures
    assert exhausted, (f"{name}: exploration hit a safety cap after "
                       f"{n_states} states — raise the bound or "
                       f"shrink the abstraction")
    assert n_states > 1  # the walk actually went somewhere


@pytest.mark.parametrize("name", PRODUCT_NAMES)
def test_product_exploration_is_deterministic(name):
    a = run_product(name)
    b = run_product(name)
    assert a == b


def test_products_cover_all_four_invariants():
    """The ISSUE's four properties each map to a named invariant."""
    invs = {i for p in model_check.PRODUCTS for i in p[5]}
    assert "never-serve-while-open" in invs             # (a)
    assert "evicted-pool-recovers" in invs              # (b)
    assert "sigterm-at-most-once" in invs               # (c)
    assert "sigterm-delivered" in invs                  # (c)
    assert "probe-admitted-through-shed" in invs        # (d)


def test_config_doctored_no_rollback_produces_counterexample():
    """The config-plane harness detects a broken apply path, it does
    not just bless the working one: a plane whose probation ignores
    the burn signal must yield a minimal counterexample trace."""
    failures, _n, exhausted = run_product(
        "config-apply", build=model_check.doctored_config_build)
    assert exhausted
    assert failures, "doctored no-rollback plane survived exploration"
    by_inv = {}
    for inv, trace, detail in failures:
        by_inv.setdefault(inv, []).append(trace)
    assert "cfg-bad-config-rolls-back" in by_inv
    shortest = min(by_inv["cfg-bad-config-rolls-back"], key=len)
    assert len(shortest) <= 3  # minimal: burn spikes, bad batch lands
    assert "push" in shortest


def test_config_product_leaves_no_override_residue():
    """run_product drives the real runtime-override map; it must hand
    the process back with no overrides applied."""
    from language_detector_tpu import knobs

    run_product("config-apply")
    assert knobs.current()["overrides"] == {}


# -- the explorer detects broken systems --------------------------------------


class _BrokenLatch:
    """A stop-forwarding latch with the exactly-once guard removed:
    every forward call signals, so a repeated stop double-delivers."""

    def __init__(self):
        self.terms = 0
        self.stopping = False

    def stop(self):
        self.stopping = True
        self.terms += 1  # no latch: re-entry delivers again


def test_explorer_catches_double_delivery():
    failures, n_states, exhausted = _explore(
        build=lambda: (_BrokenLatch(),),
        events={"stop": lambda m: m.stop()},
        key_fn=lambda m: (m.stopping, min(m.terms, 3)),
        invariants={
            "at-most-once": lambda m:
                None if m.terms <= 1 else f"delivered {m.terms}x"},
        max_depth=4)
    assert exhausted
    assert failures, "broken latch escaped the invariant"
    inv, trace, detail = failures[0]
    assert inv == "at-most-once"
    assert trace == ("stop", "stop")  # minimal counterexample
    assert "2x" in detail


def test_explorer_event_returning_false_prunes():
    """An event that reports itself inapplicable must prune that
    branch, not record a new state."""

    def build():
        return ([0],)

    failures, n_states, exhausted = _explore(
        build=build,
        events={"bump": lambda s: (s.__setitem__(0, s[0] + 1)
                                   if s[0] < 2 else False)},
        key_fn=lambda s: s[0],
        invariants={"bounded": lambda s:
                    None if s[0] <= 2 else "escaped the guard"},
        max_depth=10)
    assert failures == []
    assert exhausted
    assert n_states == 3  # 0, 1, 2 — the guard stopped the walk


def test_fake_clock_is_the_only_time_source():
    clk = FakeClock()
    t0 = clk()
    clk.advance(5.0)
    assert clk() == t0 + 5.0
    # the module itself never reads wall clock or randomness
    src = (REPO / "tools/lint/model_check.py").read_text()
    for banned in ("time.monotonic()", "time.time()", "random."):
        assert banned not in src, banned


# -- lint wrapper -------------------------------------------------------------


def test_check_clean_and_restores_fault_config():
    from language_detector_tpu import faults

    faults.configure("queue_put:error:p=0.0")
    try:
        before = faults.ACTIVE
        violations, n_sup = model_check.check(root=REPO)
        assert violations == []
        assert n_sup == 0
        # the pool product configures lane faults internally; the
        # process-wide config must come back untouched
        assert faults.ACTIVE is before
    finally:
        faults.configure(None)


def test_check_files_filter_scopes_products():
    v, _ = model_check.check(
        root=REPO, files=["language_detector_tpu/parallel/pool.py"])
    assert v == []


# -- torn-write products (tools/lint/torn_write.py) ---------------------------

from tools.lint import torn_write  # noqa: E402

TORN_NAMES = [p[0] for p in torn_write.TORN_PRODUCTS]


@pytest.mark.parametrize("name", TORN_NAMES)
def test_torn_product_exhausts_with_no_failures(name):
    failures, n_schedules, exhausted = torn_write.run_product(name)
    assert failures == [], failures
    assert exhausted, (f"{name}: crash-schedule exploration hit the "
                       f"cap after {n_schedules} schedules")
    # the journal actually tore something: more schedules than stores
    assert n_schedules > 10


@pytest.mark.parametrize("name", TORN_NAMES)
def test_torn_product_is_deterministic(name):
    a = torn_write.run_product(name)
    b = torn_write.run_product(name)
    assert a == b


@pytest.mark.parametrize("name,doctored", [
    ("torn-flightrec", torn_write.doctored_flightrec_commit_first),
    ("torn-capture", torn_write.doctored_capture_commit_first),
])
def test_torn_doctored_writer_produces_counterexample(name, doctored):
    """The harness detects broken protocols, it does not just bless
    working ones: the classic commit-word-first memcpy must yield a
    minimal counterexample trace."""
    failures, _n, exhausted = torn_write.run_product(
        name, writer=doctored)
    assert exhausted
    assert failures, f"{name}: doctored writer survived every schedule"
    inv, trace, detail = failures[0]
    assert inv == "old-value-or-refusal"
    assert "store#" in trace        # the minimal crash-point schedule
    assert "torn" in trace or "->" in trace


def test_torn_check_clean_and_restores_fault_config():
    from language_detector_tpu import faults

    faults.configure("queue_put:error:p=0.0")
    try:
        before = faults.ACTIVE
        violations, n_sup = torn_write.check(root=REPO)
        assert violations == []
        assert n_sup == 0
        assert faults.ACTIVE is before
    finally:
        faults.configure(None)


def test_torn_check_files_filter_scopes_products():
    v, _ = torn_write.check(
        root=REPO, files=["language_detector_tpu/capture.py"])
    assert v == []
    # a non-subject file scopes to zero products, vacuously clean
    v, _ = torn_write.check(root=REPO, files=["README.md"])
    assert v == []
