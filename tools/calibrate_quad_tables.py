#!/usr/bin/env python3
"""Error-driven calibration of the trained quadgram tables.

Two passes the reference performs with big corpora, reproduced here with
synthetic per-language dev documents sampled from the training vocabulary
(octa-comment words + CLDR phrases, tools/train_quad_tables.py sources):

1. **Win-rate bias calibration.** Languages with too little/too much
   training mass systematically under/over-win against their neighbors
   (e.g. Scots beating English on shared function words). Iterate:
   train -> detect dev docs -> per-language win counts -> multiplicative
   bias update bias_l *= (truth_l / wins_l)^eta -> retrain. This is class-
   prior calibration; it uses no golden-suite data.

2. **Expected-score regeneration** (cld2_do_score.cc:34 equivalent).
   Mean score/KB per (language, script4) over correctly-detected dev
   docs populates kAvgDeltaOctaScore for the trained tables, giving
   ReliabilityExpected (cldutil.cc:587-605) real data instead of the
   "no data" zero that disables it.

Writes language_detector_tpu/data/quad_tables.npz (same artifact contract
as train_quad_tables.py) and prints golden-suite accuracy per iteration
for monitoring (selection uses only dev docs).
"""
from __future__ import annotations

import argparse
import collections
import dataclasses
import random
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))
sys.path.insert(0, str(REPO / "tests"))

from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import NgramTable, load_tables  # noqa: E402
import train_quad_tables as tq  # noqa: E402


def build_dev_docs(tables, reg, docs_per_lang: int = 12,
                   words_per_doc: int = 20, seed: int = 11):
    """[(lang, text)] synthetic dev documents sampled from the training
    vocabulary, weighted like the trainer weights words."""
    rng = random.Random(seed)
    vocab: dict = collections.defaultdict(list)   # lang -> [(word, wt)]
    for word, langs, sw in tq.collect_training_words(tables, reg):
        core = word.strip("_").replace("_", " ")
        if not core:
            continue
        for lang, q in langs:
            vocab[lang].append((core, sw * 3.0 ** (q / 2.0)))
    for phrase, langs, cls in tq.collect_cldr_phrases(tables, reg):
        if cls != "cldr":
            continue  # match the production training sources
        for lang, q in langs:
            vocab[lang].append((phrase, 3.0 ** (q / 2.0)))

    docs = []
    for lang, items in sorted(vocab.items()):
        if len(items) < 25:
            continue  # too little vocabulary to make meaningful docs
        words = [w for w, _ in items]
        weights = [wt for _, wt in items]
        for _ in range(docs_per_lang):
            toks = rng.choices(words, weights=weights, k=words_per_doc)
            docs.append((lang, " ".join(toks)))
    return docs


def make_tables(base_tables, out: dict):
    quad = NgramTable.from_npz(out, "quadgram")
    return dataclasses.replace(
        base_tables, quadgram=quad,
        avg_delta_octa_score=out["expected_score_override"])


def detect_all(prod, texts):
    """Detect a list of texts with the batched engine (TPU if present,
    else CPU jax, else scalar)."""
    try:
        from language_detector_tpu.models.ngram import NgramBatchEngine
        eng = NgramBatchEngine(prod, registry)
        return eng.detect_many(texts, batch_size=4096)
    except (ImportError, RuntimeError):
        from language_detector_tpu.engine_scalar import detect_scalar
        return [detect_scalar(t, prod, registry) for t in texts]


def golden_accuracy(prod) -> tuple:
    from golden_data import golden_pairs
    from language_detector_tpu.detector import LanguageDetector
    pairs = golden_pairs()
    if not pairs:
        return 0, 0
    det = LanguageDetector(tables=prod)
    hits = 0
    for name, lang, raw in pairs:
        # UTF-8 validity gate, like the reference harness (CheckUTF8)
        got = det.detect_bytes(raw).language
        if got == lang or (got, lang) == ("hmn", "blu"):
            hits += 1
    return hits, len(pairs)


def expected_scores_from_dev(prod, docs, results) -> np.ndarray:
    """Regenerate kAvgDeltaOctaScore from dev scoring (cld2_do_score.cc):
    mean normalized score (score<<10/bytes ~ score/KB) per (lang,
    script4) over correctly-detected docs; zero (= model off) elsewhere;
    reference values kept for the CJK uni/bi-scored languages."""
    sums = collections.defaultdict(float)
    counts = collections.Counter()
    for (lang, text), r in zip(docs, results):
        if r.summary_lang != lang or not r.normalized_score3[0]:
            continue
        # script4 of the doc's first letter script
        sc = 0
        for ch in text:
            sc = int(prod.script_of_cp[min(ord(ch), 0x10FFFF)])
            if sc:
                break
        ls4 = {1: 0, 3: 1, 6: 2}.get(sc, 3)
        sums[(lang, ls4)] += r.normalized_score3[0]
        counts[(lang, ls4)] += 1
    expected = np.zeros_like(prod.avg_delta_octa_score)
    for key, total in sums.items():
        if counts[key] >= 4:
            expected[key[0], key[1]] = int(total / counts[key])
    for code in ("ja", "ko", "zh", "zh-Hant"):
        lang = registry.code_to_lang[code]
        expected[lang] = load_tables().avg_delta_octa_score[lang]
    return expected


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--eta", type=float, default=0.5)
    ap.add_argument("--docs-per-lang", type=int, default=12)
    ap.add_argument("--train-args", default="{}",
                    help="JSON kwargs forwarded to train()")
    ap.add_argument("--out", default=str(
        REPO / "language_detector_tpu/data/quad_tables.npz"))
    ap.add_argument("--no-expected", action="store_true")
    args = ap.parse_args()
    import json
    train_kwargs = json.loads(args.train_args)

    base = load_tables()
    corpus = tq.collect_corpus(base, registry)
    print(f"corpus items: {len(corpus)}", flush=True)
    docs = build_dev_docs(base, registry, docs_per_lang=args.docs_per_lang)
    texts = [t for _, t in docs]
    truth = collections.Counter(lang for lang, _ in docs)
    print(f"dev docs: {len(docs)} across {len(truth)} languages",
          flush=True)

    bias: dict = {}
    best = None
    for it in range(max(args.iters, 1)):
        out = tq.train(base, registry, corpus, lang_bias=bias,
                       verbose=False, **train_kwargs)
        prod = make_tables(base, out)
        results = detect_all(prod, texts)
        wins = collections.Counter(r.summary_lang for r in results)
        dev_hits = sum(1 for (lang, _), r in zip(docs, results)
                       if r.summary_lang == lang)
        gh, gt = golden_accuracy(prod)
        print(f"iter {it}: dev {dev_hits}/{len(docs)} "
              f"({dev_hits/len(docs)*100:.1f}%), golden {gh}/{gt} "
              f"({gh/max(gt,1)*100:.1f}%)", flush=True)
        if best is None or dev_hits > best[0]:
            best = (dev_hits, dict(bias), out, docs, results)
        # multiplicative win-rate update on languages in the dev set
        for lang, t in truth.items():
            w = wins.get(lang, 0)
            upd = ((t / max(w, 0.5)) ** args.eta)
            bias[lang] = float(np.clip(bias.get(lang, 1.0) * upd, 0.25,
                                       4.0))

    dev_hits, bias, out, docs, results = best
    print(f"best dev: {dev_hits}/{len(docs)}; bias entries: "
          f"{sum(1 for v in bias.values() if abs(v-1) > 0.01)}")
    if not args.no_expected:
        prod = make_tables(base, out)
        results = detect_all(prod, texts)
        out["expected_score_override"] = expected_scores_from_dev(
            prod, docs, results)
        prod = make_tables(base, out)
        gh, gt = golden_accuracy(prod)
        print(f"with regenerated expected scores: golden {gh}/{gt} "
              f"({gh/max(gt,1)*100:.1f}%)")
    np.savez_compressed(args.out, **out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
