"""ldt-lint: AST-based static analysis for the repo's own hazard
classes (docs/STATIC_ANALYSIS.md).

Four analyzers, each guarding an invariant the test suite cannot cheaply
observe:

  trace_safety     host syncs / Python control flow on traced values
                   inside jit-reachable code, and jit call sites whose
                   wire shapes bypass the bucket ladder
  lock_discipline  declared lock-ownership map: owned attributes must be
                   touched under their lock (ownership.py)
  knob_registry    language_detector_tpu/knobs.py is the only legal
                   env-config read; docs table drift
  metric_registry  every ldt_* series declared once (telemetry.METRICS),
                   documented (docs/OBSERVABILITY.md), and emitted

Run: python -m tools.lint   (exits non-zero on any violation)
"""
