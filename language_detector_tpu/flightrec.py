"""Crash-safe flight recorder: a per-process mmap'd bounded ring of
typed structured events that stays readable after SIGKILL.

The slow-trace ring, breaker history, and admission counters all live
in process memory — a member that dies hard takes its last minutes of
history to the grave. This module writes the same story into a small
mmap'd file with the publish-order header discipline proven in
service/shmring.py: every record's payload and length land in the map
BEFORE the 4-byte commit word (the record's sequence number) is
stored, so a reader — the fleet supervisor harvesting a postmortem, or
/tracez merging recorder tails — never observes a torn-but-published
record. The one record in flight at the moment of death has a stale
commit word and a possibly half-written payload; the reader's JSON
parse rejects it (documented reader contract, not a checksum).

File layout (little-endian):

    FILE_HDR   magic "LDFR", version, slot_count, slot_bytes, pid,
               start_ts
    slot[i]    SLOT_HDR (commit seq u32, payload length u32, ts f64)
               + payload (compact JSON: {"ev": <name>, ...fields})

seq starts at 1 and increments per event; slot index = (seq-1) %
slot_count, so the ring holds the newest slot_count events and
`events_total` (the max committed seq) survives eviction.

Event types are DECLARED in the EVENTS registry below — same contract
as telemetry.METRICS / knobs / faults: an event emitted in code but
not declared, declared but never emitted, or missing from the event
table in docs/OBSERVABILITY.md fails `python -m tools.lint` (the
event-registry analyzer). Emitting an undeclared name raises KeyError
at the call site.

Enabled by LDT_FLIGHTREC_DIR (unset = every emit is one attribute
check, the faults.ACTIVE cost contract); the fleet supervisor points
each member at its own subdirectory and harvests
`flightrec-<pid>.ring` when the member dies.
"""
from __future__ import annotations

import json
import mmap
import os
import struct
import time
from pathlib import Path

from . import knobs
from .locks import make_lock

MAGIC = b"LDFR"
VERSION = 1

FILE_HDR = struct.Struct("<4sIIIId")   # magic, version, slots,
#                                        slot_bytes, pid, start_ts
SLOT_HDR = struct.Struct("<IId")       # commit seq, payload len, ts

# pinned on-disk geometry: a drive-by field edit must fail at import,
# not corrupt rings at runtime (tools/lint/layout_registry.py declares
# the same widths; layout-drift keeps module and registry in sync)
assert FILE_HDR.size == 28
assert SLOT_HDR.size == 16

# Declared event types: name -> (category, operator-facing doc). The
# event-registry analyzer (tools/lint/event_registry.py) keeps this
# dict, the emit_event call sites, and the event table in
# docs/OBSERVABILITY.md from drifting — both ways.
EVENTS: dict = {
    "proc_start": (
        "lifecycle",
        "Recorder armed: process pid, role, and generation."),
    "proc_exit": (
        "lifecycle",
        "Front shutting down cleanly (planned drain/recycle); absent "
        "from a postmortem tail = the process died hard."),
    "request_start": (
        "request",
        "A request entered a front or ingest lane, with its request "
        "id and lane."),
    "request_end": (
        "request",
        "telemetry.finish_request: status, total ms, request id — "
        "start ids without a matching end are the in-flight set a "
        "postmortem recovers."),
    "slow_trace": (
        "request",
        "A span tree was recorded into the slow ring (threshold or "
        "reason:error capture)."),
    "breaker_state": (
        "transition",
        "Device circuit breaker state change (service/admission.py)."),
    "brownout_level": (
        "transition",
        "Brownout ladder level change (service/admission.py)."),
    "pool_lane_state": (
        "transition",
        "Device-pool lane evicted from / re-admitted to rotation "
        "(parallel/pool.py)."),
    "fleet_member_state": (
        "transition",
        "Fleet member lifecycle edge seen by the control plane: "
        "spawned, ready, crashed (service/fleet.py)."),
    "shm_ring_state": (
        "transition",
        "Shm ingest lane edge: ring attached, ring unlinked, doc "
        "quarantined (service/shmring.py)."),
    "fault_fired": (
        "fault",
        "An injected fault actually fired at a seam "
        "(language_detector_tpu/faults.py)."),
    "integrity_detected": (
        "fault",
        "Data corruption detected: a lane's device-table digest or "
        "canary deviated, or a frame payload failed its CRC "
        "(integrity.py; kind + lane/request attribution)."),
    "integrity_healed": (
        "transition",
        "A quarantined CORRUPT lane healed: fresh tables re-uploaded "
        "and verified, lane re-admitted as a half-open probe "
        "(integrity.py)."),
    "slo_breach": (
        "transition",
        "An SLO error-budget burn-rate alert fired: both the fast and "
        "slow windows are burning budget faster than allowed "
        "(slo.py; scope fleet or tenant, burn rates attached)."),
    "slo_recovered": (
        "transition",
        "A firing SLO burn-rate alert cleared: the fast window's burn "
        "rate dropped back under 1.0 (slo.py)."),
    "config_staged": (
        "transition",
        "A runtime config batch passed registry validation and was "
        "staged for apply (configplane.py; generation + knob names "
        "attached)."),
    "config_applied": (
        "transition",
        "A staged config batch went live under SLO probation "
        "(configplane.py; the generation serves but is not yet "
        "committed)."),
    "config_committed": (
        "transition",
        "A config generation survived its probation window and "
        "committed (configplane.py)."),
    "config_rolled_back": (
        "transition",
        "A config generation was auto-rolled-back: the SLO fast-window "
        "burn rate crossed 1.0 during probation, or an operator "
        "reverted it — the prior overrides are restored "
        "(configplane.py)."),
    "postmortem": (
        "lifecycle",
        "A dead member's recorder was harvested into postmortem JSON "
        "(fleet/worker supervisor)."),
    "profile_capture": (
        "profiling",
        "On-demand device-profiler window armed or completed "
        "(POST /profilez, SIGUSR2)."),
}


class FlightRecorder:
    """One process's mmap'd event ring (single writer, any readers)."""

    def __init__(self, path: str, slots: int | None = None,
                 slot_bytes: int | None = None):
        if slots is None:
            slots = knobs.get_int("LDT_FLIGHTREC_SLOTS") or 256
        if slot_bytes is None:
            slot_bytes = knobs.get_int("LDT_FLIGHTREC_SLOT_BYTES") \
                or 512
        self.path = str(path)
        self.slots = max(int(slots), 8)
        self.slot_bytes = max(int(slot_bytes), SLOT_HDR.size + 64)
        self._seq = 0
        self._dropped = 0
        self._lock = make_lock("flightrec.ring")
        size = FILE_HDR.size + self.slots * self.slot_bytes
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR | os.O_TRUNC,
                     0o644)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.mm[:FILE_HDR.size] = FILE_HDR.pack(
            MAGIC, VERSION, self.slots, self.slot_bytes, os.getpid(),
            time.time())

    def emit(self, name: str, fields: dict) -> bool:
        """Write one event. Publish order: zero the slot's commit
        word (a wrapped slot holds the previous lap's committed
        record), then payload + header tail, then the 4-byte
        commit/seq word LAST — its store is the publication point, so
        a reader (even of a SIGKILLed writer's file) never sees a
        committed-but-torn record."""
        payload = json.dumps({"ev": name, **fields},
                             separators=(",", ":"),
                             default=str).encode("utf-8")
        cap = self.slot_bytes - SLOT_HDR.size
        if len(payload) > cap:
            with self._lock:
                self._dropped += 1
            from . import telemetry
            telemetry.REGISTRY.counter_inc(
                "ldt_flightrec_dropped_total")
            return False
        with self._lock:
            self._seq += 1
            seq = self._seq
            off = FILE_HDR.size + ((seq - 1) % self.slots) \
                * self.slot_bytes
            rec = SLOT_HDR.pack(seq & 0xFFFFFFFF, len(payload),
                                time.time())
            mm = self.mm
            # after the first lap this slot still holds a COMMITTED
            # record: zero its seq word before touching the tail or
            # payload, or a crash mid-rewrite leaves the OLD seq
            # presiding over NEW length/payload bytes — a torn record
            # a reader would accept
            mm[off:off + 4] = b"\0\0\0\0"
            mm[off + 4:off + SLOT_HDR.size] = rec[4:]
            mm[off + SLOT_HDR.size:off + SLOT_HDR.size + len(payload)] \
                = payload
            mm[off:off + 4] = rec[:4]
        return True

    def stats(self) -> dict:
        with self._lock:
            return {"path": self.path, "slots": self.slots,
                    "slot_bytes": self.slot_bytes,
                    "events_total": self._seq,
                    "dropped": self._dropped}

    def close(self) -> None:
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass


# Module-level recorder: None = disabled (the fast-path check). Armed
# by init_from_env() at front startup; rebound atomically, never
# mutated in place.
RECORDER: FlightRecorder | None = None


def ring_path(directory: str, pid: int | None = None) -> str:
    """Recorder file path for a pid inside a flightrec directory — the
    naming contract the fleet's postmortem harvest relies on."""
    return os.path.join(directory, f"flightrec-{pid or os.getpid()}"
                                   ".ring")


def init_from_env(role: str = "worker") -> FlightRecorder | None:
    """Arm the process recorder from LDT_FLIGHTREC_DIR (unset = stay
    disabled). Called by both fronts' startup and by the fleet
    supervisor itself; idempotent per process."""
    global RECORDER
    if RECORDER is not None:
        return RECORDER
    directory = knobs.get_str("LDT_FLIGHTREC_DIR")
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        rec = FlightRecorder(ring_path(directory))
    except OSError as e:
        # best-effort observability, never a startup fail — but a
        # counted, logged disable (a full disk silently eating the
        # postmortem recorder is how outages lose their evidence)
        import errno

        from . import telemetry
        reason = "enospc" if e.errno == errno.ENOSPC else "oserror"
        telemetry.REGISTRY.counter_inc("ldt_flightrec_disabled_total",
                                       reason=reason)
        print(json.dumps({"msg": "flightrec disabled",
                          "reason": reason, "dir": directory,
                          "detail": repr(e)}), flush=True)
        return None
    RECORDER = rec
    emit_event("proc_start", role=role,
               generation=knobs.get_int("LDT_WORKER_GENERATION") or 0)
    return rec


def emit_event(name: str, **fields) -> bool:
    """Record one typed event into the process recorder. No-op (one
    attribute check + dict membership) when the recorder is off; an
    undeclared event name is a programming error (KeyError), exactly
    like an undeclared knob or fault point."""
    if name not in EVENTS:
        raise KeyError(f"undeclared flight-recorder event {name!r}; "
                       "declare it in language_detector_tpu/"
                       "flightrec.py EVENTS")
    rec = RECORDER
    if rec is None:
        return False
    ok = rec.emit(name, {k: v for k, v in fields.items()
                         if v is not None})
    if ok:
        from . import telemetry
        telemetry.REGISTRY.counter_inc("ldt_flightrec_events_total")
    return ok


def stats() -> dict | None:
    rec = RECORDER
    return rec.stats() if rec is not None else None


# -- readers (harvest / /tracez merge) --------------------------------------


def read_ring(path: str) -> dict:
    """Parse a recorder file — live or left by a dead process — into
    {pid, start_ts, events_total, events: [...]}. Records whose commit
    word is set but whose payload fails to parse (the one write that
    can be in flight at SIGKILL) are skipped, not fatal."""
    data = Path(path).read_bytes()
    if len(data) < FILE_HDR.size:
        raise ValueError(f"{path}: truncated flight-recorder file")
    magic, version, slots, slot_bytes, pid, start_ts = \
        FILE_HDR.unpack_from(data, 0)
    if magic != MAGIC:
        raise ValueError(f"{path}: bad magic {magic!r}")
    if version != VERSION:
        raise ValueError(f"{path}: recorder version {version} "
                         f"(reader speaks {VERSION})")
    events: list = []
    top = 0
    for i in range(slots):
        off = FILE_HDR.size + i * slot_bytes
        if off + SLOT_HDR.size > len(data):
            break
        seq, length, ts = SLOT_HDR.unpack_from(data, off)
        if seq == 0 or length > slot_bytes - SLOT_HDR.size:
            continue
        raw = data[off + SLOT_HDR.size:off + SLOT_HDR.size + length]
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            continue  # torn in-flight write at death: reject, move on
        if not isinstance(doc, dict) or "ev" not in doc:
            continue
        doc["seq"] = seq
        doc["ts"] = ts
        events.append(doc)
        top = max(top, seq)
    events.sort(key=lambda e: e["seq"])
    return {"pid": pid, "start_ts": start_ts, "events_total": top,
            "events": events}


def harvest_postmortem(path: str, reason: str = "crash",
                       rc: int | None = None,
                       tail_events: int = 32) -> dict:
    """Read a dead process's recorder into postmortem JSON: event
    counts, the tail, and the request ids that were in flight (a
    request_start without a matching request_end) when it died."""
    info = read_ring(path)
    events = info["events"]
    started = [e.get("request_id") for e in events
               if e["ev"] == "request_start" and e.get("request_id")]
    ended = {e.get("request_id") for e in events
             if e["ev"] == "request_end"}
    inflight = sorted({r for r in started if r not in ended})
    return {
        "pid": info["pid"],
        "start_ts": info["start_ts"],
        "reason": reason,
        "rc": rc,
        "clean_exit": any(e["ev"] == "proc_exit" for e in events),
        "events_total": info["events_total"],
        "events_held": len(events),
        "inflight_request_ids": inflight,
        "tail": events[-tail_events:],
    }


def request_events(path: str) -> list:
    """Request-scoped recorder events (for the /tracez merge): every
    event carrying a request_id, in commit order, tagged with the
    writing process's pid so the merge can attribute them."""
    try:
        info = read_ring(path)
    except (OSError, ValueError):
        return []
    return [dict(e, pid=info["pid"])
            for e in info["events"] if e.get("request_id")]


def discard(path: str) -> None:
    """Remove a harvested (or stale) recorder file; missing is fine."""
    try:
        os.remove(path)
    except OSError:
        pass
