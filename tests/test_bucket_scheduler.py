"""Shape-bucketed scheduler + batcher result cache (round 6).

Self-contained (bench.py corpora, no golden data): exactness of every
new scheduler path against the scalar oracle — tier-boundary routing,
the pipelined retry lane, batch-internal dedup — plus the batcher LRU's
byte bound and hint isolation, and the new metrics series.

The engine constants TIER_MIN_DOCS / RETRY_LANE_MIN are class attrs
read through self, so tests shadow them per-instance to force the
multi-lane scheduler on small (fast) corpora; production thresholds
stay untouched.
"""
from __future__ import annotations

import random

import pytest

import bench
from language_detector_tpu.preprocess import pack


def _require_engine():
    from language_detector_tpu import native
    if not native.available():
        pytest.skip("native packer unavailable")
    from language_detector_tpu.models.ngram import NgramBatchEngine
    return NgramBatchEngine


@pytest.fixture(scope="module")
def engine():
    eng = _require_engine()()
    # force the bucketed machinery on test-sized corpora
    eng.TIER_MIN_DOCS = 8
    eng.RETRY_LANE_MIN = 2
    eng.TIER_COALESCE_MIN = 1
    return eng


def _stuple(r):
    return (r.summary_lang, list(r.language3), list(r.percent3),
            r.text_bytes, r.is_reliable)


def _scalar(eng, text):
    from language_detector_tpu.engine_scalar import detect_scalar
    return detect_scalar(text, eng.tables, eng.reg, 0)


# -- tier ladder (pure host logic) ------------------------------------------


def test_tier_ladder_boundaries():
    """tier_of_text flips exactly at tier_max_chars(k), and tiers are
    monotone in length."""
    assert pack.N_TIERS == len(pack.SLOT_TIER_BUDGETS) + 1
    for k in range(len(pack.SLOT_TIER_BUDGETS)):
        m = pack.tier_max_chars(k)
        assert pack.tier_of_text("x" * m) == k
        assert pack.tier_of_text("x" * (m + 1)) == k + 1
    assert pack.tier_of_text("") == 0
    last = 0
    for n in range(0, pack.tier_max_chars(len(
            pack.SLOT_TIER_BUDGETS) - 1) + 100, 97):
        t = pack.tier_of_text("y" * n)
        assert t >= last
        last = t


# -- scheduler exactness ----------------------------------------------------


def test_bucket_boundary_parity(engine):
    """Documents straddling every slot-budget tier boundary (length
    m-1, m, m+1 at each boundary) answer exactly the scalar engine
    through the tiered detect_many path — a doc landing one lane over
    must never change its result."""
    base = " ".join(bench._SEEDS) + " "
    boundary_docs = []
    for k in range(len(pack.SLOT_TIER_BUDGETS)):
        m = pack.tier_max_chars(k)
        src = base * (m // len(base) + 2)
        for delta in (-1, 0, 1):
            boundary_docs.append(src[:m + delta])
    # pad with short docs so multiple lanes exist and slices form
    docs = boundary_docs + bench.make_corpus(48)
    rng = random.Random(6)
    rng.shuffle(docs)
    got = engine.detect_many(docs, batch_size=16)
    for t, g in zip(docs, got):
        if t in boundary_docs:
            assert _stuple(g) == _stuple(_scalar(engine, t)), \
                f"boundary doc len={len(t)} diverged"
    # tier lanes actually ran: the boundary docs span short+mid+long
    st = engine.stats
    assert st["tier_short_dispatches"] > 0
    assert st["tier_mid_dispatches"] > 0
    assert st["tier_long_dispatches"] > 0


def test_undersized_lanes_coalesce_upward():
    """Lanes below TIER_COALESCE_MIN fold into the next wider budget
    rather than paying their own dispatch; results stay exact and only
    the widest (receiving) lane's counter moves."""
    eng = _require_engine()()
    eng.TIER_MIN_DOCS = 8  # tier, but leave TIER_COALESCE_MIN at 256
    docs = bench.make_corpus(20) + \
        [" ".join(bench.make_corpus(30))] * 2  # 2-doc long tail
    before = dict(eng.stats)
    got = eng.detect_many(docs, batch_size=4096)
    st = eng.stats
    assert st["tier_short_dispatches"] == before["tier_short_dispatches"]
    assert st["tier_mid_dispatches"] == before["tier_mid_dispatches"]
    assert st["tier_long_dispatches"] > before["tier_long_dispatches"]
    for t, g in zip(docs, got):
        assert _stuple(g) == _stuple(_scalar(eng, t))


def test_retry_lane_parity(engine):
    """Gate-failing docs (squeeze spam + degenerate tails of the mixed
    corpus) resolved through the pipelined retry lane stay exact vs the
    scalar engine, under a batch size small enough to force many
    overlapping slices."""
    docs = bench.make_mixed_corpus(300)
    before = engine.stats["retry_lane_dispatches"]
    got = engine.detect_many(docs, batch_size=32)
    assert engine.stats["retry_lane_dispatches"] > before, \
        "mixed corpus under tiny slices must exercise the retry lane"
    for t, g in zip(docs, got):
        assert _stuple(g) == _stuple(_scalar(engine, t)), repr(t[:60])


def test_dedup_parity_and_stats(engine):
    """Heavy duplication: every duplicate position gets a value equal
    to its representative's, results stay exact, and dedup_docs counts
    exactly the collapsed positions."""
    uniq = bench.make_corpus(40)
    rng = random.Random(11)
    docs = uniq + [uniq[rng.randrange(len(uniq))] for _ in range(120)]
    rng.shuffle(docs)
    before = engine.stats["dedup_docs"]
    got = engine.detect_many(docs, batch_size=16)
    assert engine.stats["dedup_docs"] - before == \
        len(docs) - len(set(docs))
    by_text: dict = {}
    for t, g in zip(docs, got):
        key = _stuple(g)
        assert by_text.setdefault(t, key) == key, \
            "same text answered differently within one stream"
    for t in set(docs):
        assert by_text[t] == _stuple(_scalar(engine, t))
    # codes path shares the scheduler (patch_value seam)
    codes = engine.detect_codes(docs, batch_size=16)
    for g, c in zip(got, codes):
        assert engine.reg.code(g.summary_lang) == c


def test_single_flush_fast_path(engine):
    """A batch that fits one dispatch (the service batcher's common
    flush) takes the no-pool path and stays exact, duplicates
    included."""
    docs = bench.make_corpus(24) + bench.make_corpus(24)
    got = engine.detect_many(docs, batch_size=4096)
    for t, g in zip(docs, got):
        assert _stuple(g) == _stuple(_scalar(engine, t))


# -- gc satellite -----------------------------------------------------------


def test_gc_paused_forces_periodic_collect(monkeypatch):
    """Sustained bulk calls force a gc.collect() at least every
    GC_COLLECT_EVERY exits, even though each call pauses the GC."""
    import gc
    NgramBatchEngine = _require_engine()
    calls = []
    real = gc.collect
    monkeypatch.setattr(gc, "collect", lambda *a: calls.append(1) or 0)
    monkeypatch.setattr(NgramBatchEngine, "GC_COLLECT_EVERY", 4)
    monkeypatch.setattr(NgramBatchEngine, "_bulk_since_collect", 0)
    try:
        for _ in range(9):
            with NgramBatchEngine._gc_paused():
                pass
    finally:
        monkeypatch.setattr(gc, "collect", real)
    assert len(calls) == 2
    assert gc.isenabled()


# -- batcher result cache ---------------------------------------------------


def _counting_detect():
    seen = []

    def detect(texts):
        seen.append(list(texts))
        return [f"r:{t}" for t in texts]
    detect.seen = seen
    return detect


def test_batcher_cache_hits_and_exactness():
    from language_detector_tpu.service.batcher import Batcher
    detect = _counting_detect()
    b = Batcher(detect, max_delay_ms=1.0, cache_bytes=1 << 20)
    try:
        texts = [f"doc number {i}" for i in range(20)]
        first = b.submit(texts).result(timeout=10)
        second = b.submit(texts).result(timeout=10)
        assert first == second == [f"r:{t}" for t in texts]
        # the second submission was served without re-detection
        assert sum(len(s) for s in detect.seen) == len(texts)
        cs = b.cache_stats()
        assert cs["hits"] == len(texts)
        assert cs["misses"] == len(texts)
        assert cs["hit_rate"] == pytest.approx(0.5)
    finally:
        b.close()


def test_batcher_cache_never_crosses_hints():
    """Identical text under different hints_key must re-detect — a
    cached result may only serve submissions with the same hint
    configuration."""
    from language_detector_tpu.service.batcher import Batcher
    detect = _counting_detect()
    b = Batcher(detect, max_delay_ms=1.0, cache_bytes=1 << 20)
    try:
        b.submit(["bonjour le monde"], hints_key=None).result(timeout=10)
        b.submit(["bonjour le monde"],
                 hints_key=("tld", "fr")).result(timeout=10)
        b.submit(["bonjour le monde"],
                 hints_key=("tld", "de")).result(timeout=10)
        assert sum(len(s) for s in detect.seen) == 3  # zero cross-hint hits
        # and the SAME hints_key does hit
        b.submit(["bonjour le monde"],
                 hints_key=("tld", "fr")).result(timeout=10)
        assert sum(len(s) for s in detect.seen) == 3
    finally:
        b.close()


def test_batcher_cache_respects_byte_bound():
    from language_detector_tpu.service.batcher import (Batcher,
                                                       ResultCache)
    detect = _counting_detect()
    bound = 4096
    b = Batcher(detect, max_delay_ms=1.0, cache_bytes=bound)
    try:
        for i in range(200):
            b.submit([f"filler document {i} " + "x" * 100]).result(
                timeout=10)
        cs = b.cache_stats()
        assert 0 < cs["bytes"] <= bound
        assert cs["entries"] < 200  # eviction happened
        # an evicted entry re-detects (LRU, oldest first)
        n_before = sum(len(s) for s in detect.seen)
        b.submit(["filler document 0 " + "x" * 100]).result(timeout=10)
        assert sum(len(s) for s in detect.seen) == n_before + 1
    finally:
        b.close()
    # oversized single entry is refused rather than wiping the cache
    c = ResultCache(64)
    c.put(("k", "y" * 1000), "v", "y" * 1000)
    assert c.bytes == 0


def test_aiobatcher_cache_hits_and_exactness():
    """The asyncio front's batching layer shares the ResultCache — a
    repeated flush must be served without re-detection there too (the
    sync Batcher's cache never sees aioserver traffic)."""
    import asyncio

    from language_detector_tpu.service.aioserver import AioBatcher
    detect = _counting_detect()

    async def run():
        b = AioBatcher(detect, max_delay_ms=1.0, cache_bytes=1 << 20)
        b.start()
        try:
            texts = [f"aio doc {i}" for i in range(12)]
            first = await b.submit(texts)
            second = await b.submit(texts)
            return first, second, b.cache_stats()
        finally:
            await b.close()

    first, second, cs = asyncio.run(run())
    assert first == second == [f"r:aio doc {i}" for i in range(12)]
    assert sum(len(s) for s in detect.seen) == 12
    assert cs["hits"] == 12


def test_batcher_without_cache_unchanged():
    from language_detector_tpu.service.batcher import Batcher
    detect = _counting_detect()
    b = Batcher(detect, max_delay_ms=1.0)
    try:
        assert b.cache_stats() is None
        out = b.submit(["a", "b"]).result(timeout=10)
        assert out == ["r:a", "r:b"]
    finally:
        b.close()


# -- metrics export ---------------------------------------------------------


def test_metrics_renders_scheduler_series():
    from language_detector_tpu.service.server import Metrics
    m = Metrics()
    m.engine_stats = lambda: {
        "batches": 3, "device_dispatches": 5, "fallback_docs": 0,
        "scalar_recursion_docs": 2, "tier_short_dispatches": 2,
        "tier_mid_dispatches": 1, "tier_long_dispatches": 1,
        "tier_mixed_dispatches": 1, "retry_lane_dispatches": 4,
        "dedup_docs": 7}
    m.cache_stats = lambda: {"hits": 30, "misses": 10, "bytes": 1234,
                             "entries": 10, "hit_rate": 0.75}
    text = m.render()
    assert 'ldt_tier_dispatches_total{tier="short"} 2' in text
    assert 'ldt_tier_dispatches_total{tier="long"} 1' in text
    assert "ldt_retry_lane_dispatches_total 4" in text
    assert "ldt_dedup_documents_total 7" in text
    assert "ldt_result_cache_hit_rate 0.75" in text
    assert "ldt_result_cache_hits_total 30" in text
    assert "ldt_result_cache_bytes 1234" in text


def test_format_engine_stats():
    from language_detector_tpu.debug import format_engine_stats
    out = format_engine_stats({"batches": 2, "dedup_docs": 5,
                               "tier_short_dispatches": 1})
    assert "batches" in out and "dedup_docs" in out
    assert format_engine_stats({}) == "(no engine stats)"
