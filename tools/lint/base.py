"""Shared lint infrastructure: violations, source loading, and the
suppression contract.

Suppression syntax (docs/STATIC_ANALYSIS.md):

    x = os.environ["HOME"]  # ldt-lint: disable=knob-direct-env -- why

The comment may ride the offending line or stand alone on the line
directly above it. The ` -- reason` is MANDATORY: a suppression without
a reason does not suppress anything and is itself reported
(lint-suppression-missing-reason, which cannot be suppressed) — the
reason is the review artifact, not the directive.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

# every rule id an analyzer can emit; `--rule` and disable= validate
# against this so a typo'd rule name fails loudly instead of silently
# matching nothing
RULE_IDS = frozenset({
    "trace-host-sync",
    "trace-python-branch",
    "jit-shape-source",
    "lock-discipline",
    "knob-direct-env",
    "knob-undeclared",
    "knob-mutable-cached",
    "knob-docs-drift",
    "metric-undeclared",
    "metric-undocumented",
    "metric-unused",
    "event-undeclared",
    "event-undocumented",
    "event-unused",
    "fault-undeclared",
    "fault-undocumented",
    "fault-unused",
    "fsm-undeclared-transition",
    "fsm-dead-transition",
    "model-check-invariant",
    "layout-undeclared",
    "layout-drift",
    "layout-reader-writer-mismatch",
    "publish-order",
    "torn-write-invariant",
    "future-unresolved",
    "future-consumer-guard",
    "jit-donated-read",
    "jit-recompile-capture",
    "lint-suppression-missing-reason",
})


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str   # repo-relative, forward slashes
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*ldt-lint:\s*disable=([A-Za-z0-9_,-]+)((?:\s*--\s*\S.*)?)\s*$")


@dataclasses.dataclass
class SourceFile:
    path: Path
    rel: str
    text: str
    tree: ast.Module
    # line -> set of rule ids suppressed on that line
    suppressed: dict
    # lines carrying a reason-less (inert) suppression comment
    missing_reason: list


def load_source(path: Path, root: Path | None = None) -> SourceFile:
    root = root or repo_root()
    text = path.read_text()
    tree = ast.parse(text, filename=str(path))
    suppressed: dict = {}
    missing_reason: list = []
    lines = text.splitlines()
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if not m.group(2).strip():
            missing_reason.append(i)
            continue  # inert: a suppression without a reason
        # a standalone comment line covers the next line; a trailing
        # comment covers its own line
        target = i + 1 if line.lstrip().startswith("#") else i
        suppressed.setdefault(target, set()).update(rules)
    try:
        rel = str(path.resolve().relative_to(root))
    except ValueError:
        rel = str(path)
    return SourceFile(path=path, rel=rel, text=text, tree=tree,
                      suppressed=suppressed,
                      missing_reason=missing_reason)


def apply_suppressions(sf: SourceFile, violations: list) -> tuple:
    """Filter a file's violations through its suppression comments.
    Returns (kept, n_suppressed); appends one unsuppressible violation
    per reason-less suppression comment."""
    kept: list = []
    n_suppressed = 0
    for v in violations:
        if v.rule in sf.suppressed.get(v.line, ()):
            n_suppressed += 1
        else:
            kept.append(v)
    for line in sf.missing_reason:
        kept.append(Violation(
            "lint-suppression-missing-reason", sf.rel, line,
            "suppression without a reason is inert; append "
            "' -- <why this is safe>'"))
    return kept, n_suppressed


def iter_package_files(root: Path):
    """Every .py of the shipped package, repo tools included —
    tools/lint/fixtures (deliberately-bad inputs) excluded."""
    pkg = root / "language_detector_tpu"
    yield from sorted(pkg.rglob("*.py"))


def first_str_arg(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def call_name(call: ast.Call) -> str | None:
    """Trailing identifier of the called object: f() -> 'f',
    a.b.f() -> 'f'."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None
