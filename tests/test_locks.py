"""Runtime lock-order watchdog (language_detector_tpu/locks.py).

The static half of the concurrency contract is tools/lint/ownership.py
(tested in test_lint.py); this file proves the runtime half: with
LDT_LOCK_DEBUG=1 every make_lock() is order-checked and raises on
inversion or self-deadlock, with it off make_lock() is a plain
threading.Lock.
"""
from __future__ import annotations

import threading

import pytest

from language_detector_tpu import locks
from language_detector_tpu.locks import (DebugLock, LockOrderInversion,
                                         _Watchdog, make_lock)


@pytest.fixture
def dog():
    return _Watchdog()


def _pair(dog, a="a", b="b"):
    return DebugLock(a, dog), DebugLock(b, dog)


def test_consistent_order_is_legal(dog):
    a, b = _pair(dog)
    for _ in range(3):
        with a:
            with b:
                pass
    assert dog.edges() == {"a": {"b"}}


def test_inversion_raises(dog):
    a, b = _pair(dog)
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderInversion, match="inversion"):
            a.acquire()


def test_transitive_inversion_raises(dog):
    # a->b and b->c recorded; c->a closes a cycle through b
    a, b = _pair(dog)
    c = DebugLock("c", dog)
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(LockOrderInversion):
            a.acquire()


def test_self_reacquire_raises(dog):
    a = DebugLock("a", dog)
    with a:
        with pytest.raises(LockOrderInversion, match="self-deadlock"):
            a.acquire()


def test_same_name_instances_not_ordered(dog):
    # two instances of one role (e.g. two Histograms) may nest — the
    # graph orders ROLES, not instances
    h1 = DebugLock("telemetry.histogram", dog)
    h2 = DebugLock("telemetry.histogram", dog)
    with h1:
        with h2:
            pass
    assert dog.edges() == {}


def test_release_out_of_order_tolerated(dog):
    a, b = _pair(dog)
    a.acquire()
    b.acquire()
    a.release()
    b.release()
    with a:
        with b:
            pass  # graph still consistent: no raise


def test_order_is_process_wide_across_threads(dog):
    a, b = _pair(dog)

    def establish():
        with a:
            with b:
                pass

    t = threading.Thread(target=establish)
    t.start()
    t.join()
    # this thread now violates the order the other thread recorded
    with b:
        with pytest.raises(LockOrderInversion):
            a.acquire()


def test_make_lock_honors_knob(monkeypatch):
    monkeypatch.delenv("LDT_LOCK_DEBUG", raising=False)
    assert not isinstance(make_lock("x"), DebugLock)
    monkeypatch.setenv("LDT_LOCK_DEBUG", "1")
    lk = make_lock("x")
    assert isinstance(lk, DebugLock)
    assert lk._dog is locks.WATCHDOG
