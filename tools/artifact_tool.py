#!/usr/bin/env python3
"""Table-artifact dump / verify tool.

The TPU framework's model weights live in npz artifacts
(language_detector_tpu/data/cld2_tables.npz + quad_tables.npz). This tool is
the counterpart of the reference's cld2_dynamic_data_tool --dump/--verify
(cld2_dynamic_data_tool.cc:51+, file contract cld2_dynamic_data.h:23-110):
it prints the artifact "header" (per-array shape/dtype/checksum), checks
structural invariants of every scoring table, and compares content hashes
against the checked-in manifest so silent drift/corruption is caught.

Usage:
  python3 tools/artifact_tool.py --dump
  python3 tools/artifact_tool.py --verify            # exit 1 on mismatch
  python3 tools/artifact_tool.py --write-manifest    # refresh MANIFEST.json
"""
from __future__ import annotations

import argparse
import hashlib
import json
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DATA = REPO / "language_detector_tpu" / "data"
MANIFEST = DATA / "MANIFEST.json"
ARTIFACTS = ("cld2_tables.npz", "quad_tables.npz")
FORMAT_VERSION = 1

# Ngram table prefixes per artifact (CLD2TableSummary equivalents,
# cld2tablesummary.h:37-49)
NGRAM_PREFIXES = {
    "cld2_tables.npz": ("deltaocta", "distinctocta", "cjkdeltabi",
                        "distinctbi", "cjkcompat"),
    "quad_tables.npz": ("quadgram", "quadgram2"),
}


def _sha(a: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()


def describe(path: Path) -> dict:
    z = np.load(path, allow_pickle=False)
    return {
        "format_version": FORMAT_VERSION,
        "arrays": {k: {"shape": list(z[k].shape), "dtype": str(z[k].dtype),
                       "sha256": _sha(z[k])}
                   for k in sorted(z.files)},
    }


def check_structure(path: Path) -> list[str]:
    """Structural invariants of the scoring tables (the bits the runtime
    assumes without checking on the hot path)."""
    errors: list[str] = []
    z = np.load(path, allow_pickle=False)

    def err(msg):
        errors.append(f"{path.name}: {msg}")

    for prefix in NGRAM_PREFIXES.get(path.name, ()):
        missing = [k for k in ("meta", "buckets", "ind")
                   if f"{prefix}_{k}" not in z.files]
        if missing:
            # the dual quad table (quadgram2, primary-bucket spill) is
            # optional: absent entirely is fine, partially present is not
            if prefix == "quadgram2" and len(missing) == 3:
                continue
            err(f"missing {', '.join(f'{prefix}_{k}' for k in missing)}")
            continue
        meta = z[f"{prefix}_meta"]
        buckets = z[f"{prefix}_buckets"]
        ind = z[f"{prefix}_ind"]
        size_one, size, keymask = int(meta[0]), int(meta[1]), int(meta[2])
        if buckets.dtype != np.uint32 or buckets.ndim != 2 \
                or buckets.shape[1] != 4:
            err(f"{prefix}_buckets must be [n,4] uint32, "
                f"got {buckets.shape} {buckets.dtype}")
            continue
        if size != buckets.shape[0]:
            err(f"{prefix} meta size {size} != bucket rows "
                f"{buckets.shape[0]}")
        if size & (size - 1):
            err(f"{prefix} bucket count {size} not a power of two")
        # 0xFFFFFFFF appears on the empty dummy table
        # (generated_distinct_bi_0.cc equivalent)
        if keymask not in (0xFFFFF000, 0xFFFF0000, 0xFFFFFF00, 0xFFFFFFFF):
            err(f"{prefix} unexpected keymask {keymask:#x}")
        if ind.dtype != np.uint32:
            err(f"{prefix}_ind must be uint32")
        # size_one == 0 is legal: every entry is then a two-word pair
        # (cjkcompat's direct-indexed layout)
        if not 0 <= size_one <= len(ind):
            err(f"{prefix} size_one {size_one} out of range "
                f"(indirect len {len(ind)})")
        # every non-empty slot's indirect subscript must be resolvable:
        # subscripts >= size_one consume TWO consecutive indirect words
        # (LinearizeAll convention, scoreonescriptspan.cc:936-964)
        subs = (buckets & ~np.uint32(keymask)).ravel()
        subs = subs[buckets.ravel() != 0]
        if len(subs):
            two = subs[subs >= size_one]
            if subs.max(initial=0) >= len(ind):
                err(f"{prefix} indirect subscript {int(subs.max())} >= "
                    f"indirect len {len(ind)}")
            elif len(two) and int(two.max()) + 1 >= len(ind):
                err(f"{prefix} two-word subscript {int(two.max())} "
                    f"overruns indirect array")

    if path.name == "cld2_tables.npz":
        for k, n in (("script_of_cp", 0x110000), ("cjk_uni_prop", 0x110000),
                     ("interchange_ok", 0x110000)):
            if k not in z.files:
                err(f"missing {k}")
            elif z[k].shape[0] != n:
                err(f"{k} must cover {n} codepoints, got {z[k].shape}")
        if "lg_prob_v2" in z.files and z["lg_prob_v2"].shape != (240, 8):
            err(f"lg_prob_v2 must be [240,8] (kLgProbV2Tbl), "
                f"got {z['lg_prob_v2'].shape}")
        if "avg_delta_octa_score" in z.files \
                and z["avg_delta_octa_score"].shape != (614, 4):
            err("avg_delta_octa_score must be [614,4] "
                "(kAvgDeltaOctaScore, 614 langs x 4 script4)")
    if path.name == "quad_tables.npz":
        if "expected_score_override" in z.files \
                and z["expected_score_override"].shape != (614, 4):
            err("expected_score_override must be [614,4]")
    return errors


def cmd_dump() -> int:
    for name in ARTIFACTS:
        path = DATA / name
        if not path.exists():
            print(f"{name}: MISSING")
            continue
        d = describe(path)
        print(f"{name} ({path.stat().st_size // 1024} KB, "
              f"format v{d['format_version']})")
        for k, info in d["arrays"].items():
            print(f"  {k:28} {str(info['shape']):>16} {info['dtype']:>8} "
                  f"{info['sha256'][:12]}")
    return 0


def cmd_verify() -> int:
    errors: list[str] = []
    manifest = json.loads(MANIFEST.read_text()) if MANIFEST.exists() else None
    if manifest is None:
        errors.append(f"manifest missing: {MANIFEST}")
    for name in ARTIFACTS:
        path = DATA / name
        if not path.exists():
            # quad_tables.npz is an optional trained add-on -- but once
            # the manifest records it, absence is drift, not an option
            if name == "quad_tables.npz" and not (manifest
                                                  and name in manifest):
                continue
            errors.append(f"{name}: artifact missing")
            continue
        errors.extend(check_structure(path))
        if manifest and name in manifest:
            want = manifest[name]["arrays"]
            got = describe(path)["arrays"]
            for k in want.keys() - got.keys():
                errors.append(f"{name}: array {k} missing")
            for k in got.keys() - want.keys():
                errors.append(f"{name}: unexpected array {k}")
            for k in want.keys() & got.keys():
                if want[k] != got[k]:
                    errors.append(
                        f"{name}: {k} drifted "
                        f"(manifest {want[k]['sha256'][:12]} != "
                        f"file {got[k]['sha256'][:12]})")
    # the mmap artifact is derived from the npz pair: stale contents
    # would silently serve old tables (load_tables prefers it)
    ldta = DATA / "model.ldta"
    if ldta.exists():
        from language_detector_tpu.artifact import load_artifact
        try:
            packed = load_artifact(ldta)
        except ValueError as e:
            packed = None
            errors.append(f"model.ldta: {e}")
        if packed is not None:
            expected_keys: set = set()
            for name, prefix, path in _npz_sources():
                if not path.exists():
                    continue
                z = np.load(path, allow_pickle=False)
                for k in z.files:
                    pk = prefix + k
                    expected_keys.add(pk)
                    if pk not in packed:
                        errors.append(f"model.ldta: {pk} missing "
                                      "(stale pack — rerun --pack)")
                    elif not np.array_equal(np.asarray(packed[pk]),
                                            z[k]):
                        errors.append(f"model.ldta: {pk} drifted from "
                                      f"{name} (rerun --pack)")
            # reverse direction: arrays the npz no longer carries (or a
            # deleted quad_tables.npz) must not survive in the pack
            for pk in sorted(set(packed) - expected_keys):
                if pk.startswith("g/"):
                    # golden-canary namespace: --pack derives these
                    # itself (no npz source), exempt from the check
                    continue
                errors.append(f"model.ldta: {pk} no longer in the npz "
                              "sources (stale pack — rerun --pack)")
    if errors:
        for e in errors:
            print(f"VERIFY FAIL: {e}")
        return 1
    print("artifact verify OK")
    return 0


def _npz_sources():
    """(name, prefix, path) for every npz source of the mmap artifact —
    the single enumeration --pack and --verify share."""
    for name, prefix in (("cld2_tables.npz", "c/"),
                         ("quad_tables.npz", "q/")):
        yield name, prefix, DATA / name


def _canary_arrays(npz: dict) -> dict:
    """Golden-query canary pack baked into the artifact (g/ namespace):
    the pinned integrity.CANARY_DOCS plus the codes the tables being
    packed ACTUALLY detect for them (scalar oracle — the device twin is
    bit-parity-pinned against it). integrity.py's per-lane canary check
    compares live device results against these at scrub time."""
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.integrity import CANARY_DOCS
    from language_detector_tpu.registry import registry as reg
    from language_detector_tpu.tables import ScoringTables

    tables = ScoringTables._build(npz["c/"], npz.get("q/"))
    codes = [reg.code(detect_scalar(d, tables, reg).summary_lang)
             for d in CANARY_DOCS]

    def concat(chunks):
        off = np.zeros(len(chunks) + 1, dtype=np.int64)
        off[1:] = np.cumsum([len(b) for b in chunks])
        return (np.frombuffer(b"".join(chunks), dtype=np.uint8), off)

    du8, doff = concat([d.encode("utf-8") for d in CANARY_DOCS])
    cu8, coff = concat([c.encode("ascii") for c in codes])
    return {"g/docs_u8": du8, "g/docs_off": doff,
            "g/codes_u8": cu8, "g/codes_off": coff}


def cmd_pack() -> int:
    """npz pair -> single-file mmap artifact (data/model.ldta) with an
    immediate round-trip verification: every array loaded back through
    the mmap path must be bit-identical to its npz source."""
    from language_detector_tpu.artifact import load_artifact, write_artifact

    arrays: dict = {}
    npz: dict = {}
    for name, prefix, path in _npz_sources():
        if not path.exists():
            if name == "quad_tables.npz":
                continue  # optional trained add-on
            print(f"PACK FAIL: {name} missing")
            return 1
        z = np.load(path, allow_pickle=False)
        npz[prefix] = {k: z[k] for k in z.files}
        for k in z.files:
            arrays[prefix + k] = z[k]
    arrays.update(_canary_arrays(npz))
    out = DATA / "model.ldta"
    write_artifact(arrays, out)
    back = load_artifact(out)
    bad = [k for k in arrays
           if not np.array_equal(np.asarray(back[k]), arrays[k])]
    missing = set(arrays) - set(back)
    if bad or missing:
        for k in bad:
            print(f"PACK FAIL: {k} round-trip mismatch")
        for k in missing:
            print(f"PACK FAIL: {k} missing after round trip")
        out.unlink(missing_ok=True)
        return 1
    print(f"wrote {out} ({out.stat().st_size // 1024} KB, "
          f"{len(arrays)} arrays, round-trip verified)")
    return 0


def cmd_write_manifest() -> int:
    manifest = {name: describe(DATA / name)
                for name in ARTIFACTS if (DATA / name).exists()}
    MANIFEST.write_text(json.dumps(manifest, indent=1, sort_keys=True)
                        + "\n")
    print(f"wrote {MANIFEST}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--dump", action="store_true")
    g.add_argument("--verify", action="store_true")
    g.add_argument("--pack", action="store_true",
                   help="npz pair -> data/model.ldta mmap artifact")
    g.add_argument("--write-manifest", action="store_true")
    args = ap.parse_args()
    if args.dump:
        return cmd_dump()
    if args.verify:
        return cmd_verify()
    if args.pack:
        return cmd_pack()
    return cmd_write_manifest()


if __name__ == "__main__":
    sys.exit(main())
