"""Good fixture for the layout-registry analyzer: one declared record,
width-asserted at import, with matched declared writer/reader, plus a
reasoned suppression for a scratch format."""
import struct

REC = struct.Struct("<IHH")
assert REC.size == 8

SCRATCH = struct.Struct("<B")  # ldt-lint: disable=layout-undeclared -- fixture: scratch format, never ships bytes


def write_rec(buf, a, b, c):
    REC.pack_into(buf, 0, a, b, c)


def read_rec(buf):
    return REC.unpack_from(buf, 0)
