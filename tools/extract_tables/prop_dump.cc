// Separate translation unit for the macro-heavy UTF-8 DFA data headers
// (utf8prop_lettermarkscriptnum.h and utf8repl_lettermarklower.h both
// re-#define S1_/T1_/etc., so they cannot share a TU).
//
// Exposes per-codepoint script-number and lowercase queries by running the
// reference's state-table interpreter (utf8statetable.cc, linked in) over
// single-character inputs. Extraction-time only; the runtime framework uses
// the resulting flat arrays.

#include <string.h>

#include "integral_types.h"
#include "utf8statetable.h"
#include "stringpiece.h"

#include "utf8prop_lettermarkscriptnum.h"

// The repl header's macros collide with the prop header's; isolate via a
// second nested include in a disjoint macro environment.
#undef S1_
#undef S2_
#undef S3_
#undef S21
#undef S31
#undef S32
#undef T1_
#undef T2_
#undef S11
#undef SL_

#include "utf8repl_lettermarklower.h"

static int EncodeUtf8(int cp, unsigned char* buf) {
  if (cp < 0x80) { buf[0] = cp; return 1; }
  if (cp < 0x800) {
    buf[0] = 0xC0 | (cp >> 6); buf[1] = 0x80 | (cp & 0x3F); return 2;
  }
  if (cp < 0x10000) {
    buf[0] = 0xE0 | (cp >> 12); buf[1] = 0x80 | ((cp >> 6) & 0x3F);
    buf[2] = 0x80 | (cp & 0x3F); return 3;
  }
  buf[0] = 0xF0 | (cp >> 18); buf[1] = 0x80 | ((cp >> 12) & 0x3F);
  buf[2] = 0x80 | ((cp >> 6) & 0x3F); buf[3] = 0x80 | (cp & 0x3F); return 4;
}

static int DecodeUtf8(const unsigned char* buf, int len) {
  if (len <= 0) return -1;
  unsigned char b0 = buf[0];
  if (b0 < 0x80) return b0;
  if (b0 < 0xE0) return ((b0 & 0x1F) << 6) | (buf[1] & 0x3F);
  if (b0 < 0xF0)
    return ((b0 & 0x0F) << 12) | ((buf[1] & 0x3F) << 6) | (buf[2] & 0x3F);
  return ((b0 & 0x07) << 18) | ((buf[1] & 0x3F) << 12) |
         ((buf[2] & 0x3F) << 6) | (buf[3] & 0x3F);
}

// ULScript number of a letter/mark codepoint, 0 otherwise.
int ScriptNumOfCodepoint(int cp) {
  unsigned char buf[8];
  int len = EncodeUtf8(cp, buf);
  const CLD2::uint8* src = buf;
  int srclen = len;
  return CLD2::UTF8GenericPropertyTwoByte(
      &CLD2::utf8prop_lettermarkscriptnum_obj, &src, &srclen);
}

// CLD2 lowercase of a codepoint (identity if unmapped). Returns the lowered
// codepoint, or -1 if the mapping is not 1 char -> 1 char.
int LowercaseCodepoint(int cp, unsigned char* out_utf8, int* out_len) {
  unsigned char inbuf[8];
  int inlen = EncodeUtf8(cp, inbuf);
  char outbuf[32];
  StringPiece istr(reinterpret_cast<const char*>(inbuf), inlen);
  StringPiece ostr(outbuf, sizeof(outbuf));
  int bytes_consumed = 0, bytes_filled = 0, chars_changed = 0;
  CLD2::UTF8GenericReplace(&CLD2::utf8repl_lettermarklower_obj, istr, ostr,
                           &bytes_consumed, &bytes_filled, &chars_changed);
  if (bytes_filled <= 0 || bytes_filled > 4) return -1;
  memcpy(out_utf8, outbuf, bytes_filled);
  *out_len = bytes_filled;
  return DecodeUtf8(reinterpret_cast<unsigned char*>(outbuf), bytes_filled);
}

// Third macro environment: the interchange-validity scanner table.
#undef X__
#undef RJ_
#undef S1_
#undef S2_
#undef S3_
#undef S21
#undef S31
#undef S32
#undef T1_
#undef T2_
#undef S11
#undef SP_
#undef D__
#undef RJA

#include "utf8acceptinterchange.h"

// 1 if the codepoint is interchange-valid per the reference scanner
// (utf8acceptinterchange.h; SpanInterchangeValid, compact_lang_det_impl.cc:74).
int InterchangeValidCodepoint(int cp) {
  unsigned char buf[8];
  int len = EncodeUtf8(cp, buf);
  StringPiece sp(reinterpret_cast<const char*>(buf), len);
  int consumed = 0;
  CLD2::UTF8GenericScan(&CLD2::utf8acceptinterchange_obj, sp, &consumed);
  return consumed == len;
}
