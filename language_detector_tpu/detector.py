"""Public detection API.

`LanguageDetector` wraps the engines: the scalar host engine (reference
semantics, used for validation and as fallback for rare recursion paths) and
the batched TPU engine (models/ngram.py) for throughput. Mirrors the service
surface of the reference wrapper (wrapper.cc:7-16 detect_language) and the
richer ExtDetectLanguageSummary (compact_lang_det.h:168-426).
"""
from __future__ import annotations

import dataclasses

from . import native
from .engine_scalar import (ScalarResult, detect_scalar,
                            result_from_epilogue_row)
from .registry import Registry, UNKNOWN_LANGUAGE, registry as default_registry
from .tables import ScoringTables, load_tables


@dataclasses.dataclass
class DetectionResult:
    """Top-3 detection result (compact_lang_det.h:147-165 contract)."""

    language: str             # ISO code of summary language ("un" if unknown)
    language_id: int
    is_reliable: bool
    top3: list                # [(code, percent, normalized_score)] * 3
    text_bytes: int
    # bytes of the longest interchange-valid UTF-8 prefix; set by the
    # CheckUTF8 entry points (compact_lang_det.h:168+ *CheckUTF8 contract)
    valid_prefix_bytes: int | None = None
    # per-range results: [(offset, bytes, iso_code)] covering the original
    # input when requested (ResultChunkVector, compact_lang_det.h:147-154)
    chunks: list | None = None
    # per-span verdicts: [(byte_offset, byte_len, iso_code, percent,
    # reliable)] tiling the document (LDT_SPANS surfaces; span contract
    # in docs/ACCURACY.md — engine_scalar.span_coverage_records)
    spans: list | None = None

    @classmethod
    def from_scalar(cls, r: ScalarResult, reg: Registry) -> "DetectionResult":
        return cls(
            language=reg.code(r.summary_lang),
            language_id=r.summary_lang,
            is_reliable=r.is_reliable,
            top3=[(reg.code(l), p, s) for l, p, s in
                  zip(r.language3, r.percent3, r.normalized_score3)],
            text_bytes=r.text_bytes,
            chunks=None if r.chunks is None else
            [(c.offset, c.bytes, reg.code(c.lang1)) for c in r.chunks],
            spans=getattr(r, "spans", None),
        )


class LanguageDetector:
    """Configurable detector over a table artifact."""

    def __init__(self, tables: ScoringTables | None = None,
                 reg: Registry | None = None, flags: int = 0):
        self.tables = tables or load_tables()
        self.registry = reg or default_registry
        self.flags = flags
        self._batch_engine = None  # lazily built batched JAX engine

    def detect(self, text: str, is_plain_text: bool = True,
               hints=None, return_chunks: bool = False) -> DetectionResult:
        """hints: optional hints.CLDHints (content-language / TLD /
        encoding / explicit language priors; ExtDetectLanguageSummary
        contract, compact_lang_det.h:168+). return_chunks additionally
        fills `.chunks` with per-byte-range languages over the original
        input (the ResultChunkVector overload, compact_lang_det.h:380).

        Plain, unhinted, chunk-less calls run the all-C single-document
        pipeline (native detect_one_row: pack -> C chunk scorer ->
        epilogue -> gate recursion, agreement-pinned against the device
        and scalar engines) — ~1000x the Python scalar engine. Exotic
        surfaces (hints, HTML, chunk vectors, non-default flags, docs
        past the C seam's 160KB reference subset, or no native library)
        keep the scalar engine."""
        if (is_plain_text and hints is None and not return_chunks
                and self.flags == 0):
            row = native.detect_one_native(text, self.tables,
                                           self.registry)
            if row is not None:
                return DetectionResult.from_scalar(
                    result_from_epilogue_row(row), self.registry)
        r = detect_scalar(text, self.tables, self.registry, self.flags,
                          is_plain_text=is_plain_text, hints=hints,
                          want_chunks=return_chunks)
        return DetectionResult.from_scalar(r, self.registry)

    def span_interchange_valid(self, data: bytes) -> int:
        """Length of the longest structurally-valid, interchange-valid
        UTF-8 prefix (SpanInterchangeValid, compact_lang_det_impl.cc:74-80
        over the utf8acceptinterchange scanner)."""
        import numpy as np
        try:
            text = data.decode("utf-8")
            struct_ok = len(data)
        except UnicodeDecodeError as e:
            struct_ok = e.start
            text = data[:e.start].decode("utf-8")
        if not text:
            return 0
        cps = np.frombuffer(text.encode("utf-32-le"), np.uint32)
        ok = self.tables.interchange_ok[cps] != 0
        if ok.all():
            return struct_ok
        bad = int(np.argmin(ok))
        return len(text[:bad].encode("utf-8"))

    def detect_bytes(self, data: bytes, is_plain_text: bool = True,
                     check_utf8: bool = True,
                     hints=None) -> DetectionResult:
        """Detect raw UTF-8 bytes. With check_utf8 (the reference's
        *CheckUTF8 entry points, compact_lang_det.cc:317), input that is
        not fully interchange-valid answers UNKNOWN with
        valid_prefix_bytes set instead of laundering bad bytes."""
        valid = self.span_interchange_valid(data)
        if check_utf8 and valid < len(data):
            return DetectionResult(
                language=self.registry.code(UNKNOWN_LANGUAGE),
                language_id=UNKNOWN_LANGUAGE, is_reliable=False,
                top3=[(self.registry.code(UNKNOWN_LANGUAGE), 0, 0.0)] * 3,
                text_bytes=0, valid_prefix_bytes=valid)
        r = self.detect(data.decode("utf-8", errors="replace"),
                        is_plain_text=is_plain_text, hints=hints)
        r.valid_prefix_bytes = valid
        return r

    def detect_batch(self, texts: list[str], hints=None,
                     is_plain_text: bool = True,
                     return_chunks: bool = False) -> list[DetectionResult]:
        """Batched detection (device engine when available). hints /
        is_plain_text ride the device path too: priors become wire-level
        chunk boosts, HTML cleans host-side before packing.
        return_chunks fills per-byte-range vectors from the batched
        path's offset sidecars (result_vector.py)."""
        eng = self._get_batch_engine()
        if eng is None:  # no usable accelerator backend: scalar per doc
            return [self.detect(t, hints=hints,
                                is_plain_text=is_plain_text,
                                return_chunks=return_chunks)
                    for t in texts]
        rs = eng.detect_batch(texts, hints=hints,
                              is_plain_text=is_plain_text,
                              return_chunks=return_chunks)
        return [DetectionResult.from_scalar(r, self.registry) for r in rs]

    def detect_spans(self, texts: list[str]) -> list[DetectionResult]:
        """Per-span detection: every result carries `.spans` records
        tiling the document bytes (byte_offset, byte_len, iso_code,
        percent, reliable) alongside the usual top-3 summary. The
        device lane (models/ngram.py detect_spans) and the scalar
        oracle (engine_scalar.detect_scalar_spans) are bit-identical
        (tests/test_spans.py); service fronts expose this behind
        LDT_SPANS=1."""
        from .engine_scalar import detect_scalar_spans
        eng = self._get_batch_engine()
        if eng is not None:
            rs = eng.detect_spans(texts)
        else:
            rs = [detect_scalar_spans(t, self.tables, self.registry,
                                      self.flags) for t in texts]
        return [DetectionResult.from_scalar(r, self.registry) for r in rs]

    def engine_stats(self) -> dict:
        """Snapshot of the batched engine's scheduler counters (batches,
        device dispatches, per-tier lanes, retry lane, dedup — see
        models/ngram.py NgramBatchEngine.stats). {} when the batched
        engine is unavailable or not yet built; never builds one."""
        eng = self._batch_engine or None
        if eng is None:
            return {}
        with eng._stats_lock:
            return dict(eng.stats)

    def _get_batch_engine(self):
        if self._batch_engine is None:
            try:
                from .models.ngram import NgramBatchEngine
                self._batch_engine = NgramBatchEngine(
                    self.tables, self.registry, self.flags)
            except (ImportError, RuntimeError) as e:
                # jax missing or accelerator backend failed to initialize;
                # anything else (bad tables, shape bugs) propagates loudly
                import warnings
                warnings.warn(f"batched engine unavailable ({e!r}); "
                              "falling back to scalar detection")
                self._batch_engine = False
        return self._batch_engine or None


def detect_language_version(tables: ScoringTables | None = None) -> str:
    """Version string "code_version - data_build_date"
    (DetectLanguageVersion, compact_lang_det_impl.cc:2112-2119). Empty
    when no quadgram tables are loaded, like the reference's dynamic mode
    before data load."""
    t = tables or load_tables()
    if t.quadgram.empty:
        return ""
    from . import __version__
    return f"V{__version__} - {t.quadgram.build_date}"


_default_detector: LanguageDetector | None = None


def _get_default() -> LanguageDetector:
    global _default_detector
    if _default_detector is None:
        _default_detector = LanguageDetector()
    return _default_detector


def detect(text: str, is_plain_text: bool = True, hints=None,
           return_chunks: bool = False) -> DetectionResult:
    return _get_default().detect(text, is_plain_text=is_plain_text,
                                 hints=hints, return_chunks=return_chunks)


def detect_batch(texts: list[str], hints=None, is_plain_text: bool = True,
                 return_chunks: bool = False) -> list[DetectionResult]:
    return _get_default().detect_batch(texts, hints=hints,
                                       is_plain_text=is_plain_text,
                                       return_chunks=return_chunks)
