"""Trace-safety analyzer: no host syncs or Python control flow on
traced values inside jit-reachable code, and no jit launches over
hand-built wire shapes.

Three rules over the device-path files (ops/, models/ngram.py,
preprocess/pack.py, parallel/mesh.py):

  trace-host-sync      .item()/.tolist(), float()/int()/bool() casts,
                       or np.asarray()/np.array() applied to a traced
                       value inside a function reachable from a
                       jax.jit/pjit entry — each is a silent device
                       sync that serializes the pipeline
  trace-python-branch  `if`/`while`/`for`/ternary driven by a traced
                       value's truthiness — a trace-time constant at
                       best, a ConcretizationTypeError at worst
  jit-shape-source     a call of a jitted scorer whose wire argument is
                       not `<chunkbatch>.wire` from the native packer
                       (the packer applies the bucket ladder; ad-hoc
                       wires churn the XLA jit cache, the round-3
                       regression class)

The taint model is deliberately shape-aware: `.shape`/`.dtype`/`.ndim`
reads, `is`/`is not` comparisons, and parameters with literal bool
defaults (static config flags like full_out) are trace-time constants
and legal to branch on — exactly the patterns ops/score.py relies on.
"""
from __future__ import annotations

import ast
from pathlib import Path

from .base import Violation, apply_suppressions, load_source, repo_root

SCAN_FILES = (
    "language_detector_tpu/ops/score.py",
    "language_detector_tpu/ops/kernels.py",
    "language_detector_tpu/ops/device_tables.py",
    "language_detector_tpu/models/ngram.py",
    "language_detector_tpu/preprocess/pack.py",
    "language_detector_tpu/parallel/mesh.py",
)

# attribute reads that are static at trace time (never tainted)
STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size"})
# builtins whose results are trace-time constants
UNTAINT_FUNCS = frozenset({"range", "len", "enumerate", "isinstance"})
HOST_SYNC_METHODS = frozenset({"item", "tolist"})
HOST_CASTS = frozenset({"float", "int", "bool"})
NP_SYNC_FUNCS = frozenset({"asarray", "array"})

# instance attributes holding jitted callables (models/ngram.py wires
# self._score_fn to score_chunks or the shard_map'd variant)
ATTR_JITTED = frozenset({"_score_fn"})
# parameter names that carry a jitted scorer into a launch helper
# (models/ngram._launch_raw receives the pool lane's program); any
# plain-name call of one of these is audited like a jitted call
PARAM_JITTED = frozenset({"score_fn"})
# calls that produce a bucket-padded ChunkBatch (native packer seam)
ALLOWED_PACKERS = frozenset({"pack_chunks_native", "_pack",
                             "_dispatch"})


class _TaintChecker:
    """One reachable function's body, forward taint propagation."""

    def __init__(self, fn: ast.FunctionDef, rel: str, out: list):
        self.rel = rel
        self.out = out
        self.tainted: set = set()
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = [None] * (len(pos) - len(args.defaults)) \
            + list(args.defaults)
        for a, d in zip(pos, defaults):
            if isinstance(d, ast.Constant) and isinstance(d.value, bool):
                continue  # literal bool default: static config flag
            self.tainted.add(a.arg)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if isinstance(d, ast.Constant) and isinstance(d.value, bool):
                continue
            self.tainted.add(a.arg)

    def _flag(self, rule: str, node, msg: str):
        self.out.append(Violation(rule, self.rel, node.lineno, msg))

    # -- expressions --------------------------------------------------------

    def expr(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            inner = self.expr(node.value)
            return False if node.attr in STATIC_ATTRS else inner
        if isinstance(node, ast.Subscript):
            return self.expr(node.value) | self.expr(node.slice)
        if isinstance(node, ast.Slice):
            return any(self.expr(x) for x in
                       (node.lower, node.upper, node.step))
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) | self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any([self.expr(v) for v in node.values])
        if isinstance(node, ast.Compare):
            taints = [self.expr(node.left)] + \
                [self.expr(c) for c in node.comparators]
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False  # identity tests are trace-static
            return any(taints)
        if isinstance(node, ast.IfExp):
            if self.expr(node.test):
                self._flag("trace-python-branch", node,
                           "conditional expression on a traced value; "
                           "use jnp.where")
            return self.expr(node.body) | self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self.expr(e) for e in node.elts])
        if isinstance(node, ast.Dict):
            return any([self.expr(k) | self.expr(v)
                        for k, v in zip(node.keys, node.values)])
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            t = False
            for gen in node.generators:
                if self.expr(gen.iter):
                    self._flag("trace-python-branch", node,
                               "Python iteration over a traced value")
                    t = True
            return t
        return False

    def _call(self, node: ast.Call) -> bool:
        arg_taints = [self.expr(a) for a in node.args] + \
            [self.expr(kw.value) for kw in node.keywords]
        any_tainted = any(arg_taints)
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in HOST_CASTS and any_tainted:
                self._flag("trace-host-sync", node,
                           f"{f.id}() on a traced value forces a "
                           f"device sync at trace time")
                return False
            if f.id in UNTAINT_FUNCS:
                return False
            return any_tainted
        if isinstance(f, ast.Attribute):
            recv_tainted = self.expr(f.value)
            if f.attr in HOST_SYNC_METHODS and recv_tainted:
                self._flag("trace-host-sync", node,
                           f".{f.attr}() on a traced value forces a "
                           f"device sync")
                return False
            if isinstance(f.value, ast.Name) and f.value.id == "np" \
                    and f.attr in NP_SYNC_FUNCS and any_tainted:
                self._flag("trace-host-sync", node,
                           f"np.{f.attr}() materializes a traced value "
                           f"on the host; use jnp")
                return False
            return any_tainted or recv_tainted
        return any_tainted

    # -- statements ---------------------------------------------------------

    def _bind(self, target, tainted: bool):
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            self.expr(target)

    def stmts(self, body):
        for s in body:
            self.stmt(s)

    def stmt(self, node):
        if isinstance(node, ast.Assign):
            t = self.expr(node.value)
            for tgt in node.targets:
                self._bind(tgt, t)
        elif isinstance(node, ast.AnnAssign):
            t = self.expr(node.value)
            self._bind(node.target, t)
        elif isinstance(node, ast.AugAssign):
            t = self.expr(node.value)
            if isinstance(node.target, ast.Name):
                if t:
                    self.tainted.add(node.target.id)
            else:
                self.expr(node.target)
        elif isinstance(node, ast.Expr):
            self.expr(node.value)
        elif isinstance(node, ast.Return):
            self.expr(node.value)
        elif isinstance(node, (ast.If, ast.While)):
            if self.expr(node.test):
                self._flag("trace-python-branch", node,
                           "Python branch on a traced value's "
                           "truthiness; use jnp.where or a shape test")
            # two passes: taint introduced late in a loop body must
            # propagate to its own top
            self.stmts(node.body)
            if isinstance(node, ast.While):
                self.stmts(node.body)
            self.stmts(node.orelse)
        elif isinstance(node, ast.For):
            if self.expr(node.iter):
                self._flag("trace-python-branch", node,
                           "Python iteration over a traced value")
            self._bind(node.target, False)
            self.stmts(node.body)
            self.stmts(node.body)
            self.stmts(node.orelse)
        elif isinstance(node, ast.With):
            for item in node.items:
                self.expr(item.context_expr)
            self.stmts(node.body)
        elif isinstance(node, (ast.Try,)):
            self.stmts(node.body)
            for h in node.handlers:
                self.stmts(h.body)
            self.stmts(node.orelse)
            self.stmts(node.finalbody)
        # nested defs/classes: out of scope for the traced entry


def _lambda_called_names(lam: ast.Lambda) -> set:
    names = set()
    for node in ast.walk(lam.body):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name):
            names.add(node.func.id)
    return names


def _collect_entries_and_jitted(sources) -> tuple:
    """(entry function names, jitted callable names).

    Entries are the functions jax traces: direct jit(f) arguments,
    functions called inside jit(lambda ...) bodies, the first
    argument of shard_map(f, ...) when the wrapped result is jitted,
    and the first argument of pl.pallas_call(kernel, ...) — Pallas
    kernel bodies trace under the same rules (a host sync inside one
    is a Mosaic lowering error on TPU, a silent serialization in
    interpret mode). Jitted names are module-level `X = jax.jit(...)`
    bindings — the callables whose call sites the shape-source rule
    audits."""
    entries: set = set()
    jitted: set = set()
    for sf in sources:
        # local name -> the Call node it was assigned from, per scope
        def scan(body, local_calls):
            for node in body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_calls[tgt.id] = node.value
                for child in ast.walk(node):
                    if not isinstance(child, ast.Call):
                        continue
                    fname = child.func.attr \
                        if isinstance(child.func, ast.Attribute) \
                        else getattr(child.func, "id", None)
                    if fname == "pallas_call" and child.args and \
                            isinstance(child.args[0], ast.Name):
                        entries.add(child.args[0].id)
                        continue
                    if fname not in ("jit", "pjit") or not child.args:
                        continue
                    arg = child.args[0]
                    if isinstance(arg, ast.Lambda):
                        entries.update(_lambda_called_names(arg))
                    elif isinstance(arg, ast.Name):
                        src = local_calls.get(arg.id)
                        sname = None
                        if src is not None:
                            sname = src.func.attr if isinstance(
                                src.func, ast.Attribute) \
                                else getattr(src.func, "id", None)
                        if sname in ("shard_map", "_shard_map") \
                                and src.args and \
                                isinstance(src.args[0], ast.Name):
                            entries.add(src.args[0].id)
                        else:
                            entries.add(arg.id)

        # module-level jitted bindings
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                fname = node.value.func.attr \
                    if isinstance(node.value.func, ast.Attribute) \
                    else getattr(node.value.func, "id", None)
                if fname in ("jit", "pjit"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jitted.add(tgt.id)
        # jit calls anywhere (module level and inside functions)
        scan(sf.tree.body, {})
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(node.body, {})
    return entries, jitted


def _check_shape_sources(sf, jitted: set, out: list):
    """Audit every call of a jitted callable: the wire argument must be
    `<name>.wire` where <name> is a ChunkBatch — a parameter of the
    enclosing function (callers own the packing) or a local assigned
    from the native packer."""

    def audit_scope(body, params: set):
        local_sources: dict = {}
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    cname = node.value.func.attr if isinstance(
                        node.value.func, ast.Attribute) \
                        else getattr(node.value.func, "id", None)
                    if cname in ALLOWED_PACKERS:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                local_sources[tgt.id] = cname
                            elif isinstance(tgt, (ast.Tuple, ast.List)):
                                for e in tgt.elts:
                                    if isinstance(e, ast.Name):
                                        local_sources[e.id] = cname
                if not isinstance(node, ast.Call):
                    continue
                fname = node.func.id \
                    if isinstance(node.func, ast.Name) else None
                fattr = node.func.attr \
                    if isinstance(node.func, ast.Attribute) else None
                if fname not in jitted and fname not in PARAM_JITTED \
                        and fattr not in ATTR_JITTED:
                    continue
                if not node.args:
                    continue
                wire = node.args[-1]
                ok = (isinstance(wire, ast.Attribute)
                      and wire.attr == "wire"
                      and isinstance(wire.value, ast.Name)
                      and (wire.value.id in params
                           or wire.value.id in local_sources))
                if not ok:
                    out.append(Violation(
                        "jit-shape-source", sf.rel, node.lineno,
                        "jitted scorer launched over a wire that is "
                        "not a native-packer ChunkBatch: shapes must "
                        "come from the bucket ladder "
                        "(native.pack_chunks_native)"))

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {a.arg for a in node.args.posonlyargs
                      + node.args.args + node.args.kwonlyargs}
            audit_scope(node.body, params)


def check(root: Path | None = None, files=None):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    rels = SCAN_FILES if files is None else files
    sources = [load_source(root / rel if not Path(rel).is_absolute()
                           else Path(rel), root)
               for rel in rels
               if (root / rel).exists() or Path(rel).is_absolute()]
    entries, jitted = _collect_entries_and_jitted(sources)

    # index of module-level functions across the scan set
    index: dict = {}
    for sf in sources:
        for node in sf.tree.body:
            if isinstance(node, ast.FunctionDef):
                index.setdefault(node.name, (sf, node))

    # reachability: BFS through plain-name calls
    reachable: list = []
    seen: set = set()
    frontier = [n for n in entries if n in index]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        sf, fn = index[name]
        reachable.append((sf, fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in index and node.func.id not in seen:
                frontier.append(node.func.id)

    per_file: dict = {id(sf): [] for sf in sources}
    for sf, fn in reachable:
        tc = _TaintChecker(fn, sf.rel, per_file[id(sf)])
        tc.stmts(fn.body)
    for sf in sources:
        _check_shape_sources(sf, jitted, per_file[id(sf)])

    violations: list = []
    n_suppressed = 0
    for sf in sources:
        kept, ns = apply_suppressions(sf, per_file[id(sf)])
        violations.extend(kept)
        n_suppressed += ns
    return violations, n_suppressed
