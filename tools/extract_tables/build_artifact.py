#!/usr/bin/env python3
"""Assemble the language_detector_tpu table artifact from extracted blobs.

Reads the raw .bin/.txt blobs produced by build.sh (extract_main.cc) plus the
closest-alt-language data table (parsed from the reference's source text,
compact_lang_det_impl.cc:259-427 — a data table of enum names), and writes a
single compressed .npz artifact that is the framework's model-weight file.

Run: python3 build_artifact.py [--out ../../language_detector_tpu/data/cld2_tables.npz]
"""
import argparse
import re
from pathlib import Path

import numpy as np

HERE = Path(__file__).resolve().parent
OUT_DIR = HERE / "out"
REF_IMPL = Path("/root/reference/cld2/internal/compact_lang_det_impl.cc")

DTYPES = {"uint8": np.uint8, "uint16": np.uint16, "uint32": np.uint32,
          "int16": np.int16, "int32": np.int32}


def load_blobs():
    arrays = {}
    strings = {}
    for line in (OUT_DIR / "manifest.txt").read_text().splitlines():
        name, dtype, n = line.split()
        if dtype == "str":
            txt = (OUT_DIR / f"{name}.txt").read_text()
            vals = txt.split("\n")
            if vals and vals[-1] == "":
                vals.pop()
            strings[name] = vals
        else:
            raw = (OUT_DIR / f"{name}.bin").read_bytes()
            arrays[name] = np.frombuffer(raw, dtype=DTYPES[dtype]).copy()
            assert arrays[name].size == int(n), name
    return arrays, strings


def parse_closest_alt(cnames):
    """Parse the kClosestAltLanguage data table out of the reference source."""
    src = REF_IMPL.read_text()
    m = re.search(r"kClosestAltLanguage\[\] = \{(.*?)\};", src, re.S)
    body = m.group(1)
    min_corr = int(re.search(r"kMinCorrPercent = (\d+)", src).group(1))
    unknown = cnames.index("UNKNOWN_LANGUAGE")  # 26
    cname_to_id = {c: i for i, c in enumerate(cnames)}
    ids = []
    # Entries look like: (28 >= kMinCorrPercent) ? SCOTS : UNKNOWN_LANGUAGE,
    for pct, alt in re.findall(
            r"\(\s*(\d+) >= kMinCorrPercent\) \? (\w+) : UNKNOWN_LANGUAGE",
            body):
        ids.append(cname_to_id.get(alt, unknown)
                   if int(pct) >= min_corr else unknown)
    return np.array(ids, dtype=np.int32)


def parse_entities():
    """Parse kNameToEntity (generated_entities.cc:26+) into parallel
    name/value arrays (sorted by name upstream, kept sorted here for the
    runtime's binary/dict lookup)."""
    src = (REF_IMPL.parent / "generated_entities.cc").read_text()
    body = re.search(r"kNameToEntity\[kNameToEntitySize\] = \{(.*?)\};",
                     src, re.S).group(1)
    pairs = re.findall(r'\{"([^"]+)",\s*(\d+)\}', body)
    names = np.array([n for n, _ in pairs])
    values = np.array([int(v) for _, v in pairs], dtype=np.int32)
    return names, values


def parse_hint_tables(cnames):
    """Parse the hand-curated hint data tables out of
    compact_lang_det_hint_code.cc: lang-tag lookup tables 1 (long tags,
    :102) and 2 (short codes, :348), the TLD table (:647), and the
    encoding enum names (public/encodings.h). Priors are packed
    OneCLDLangPrior values: language id | (weight << 10)."""
    src = (REF_IMPL.parent / "compact_lang_det_hint_code.cc").read_text()
    cname_to_id = {c: i for i, c in enumerate(cnames)}

    def parse_prior(expr):
        expr = expr.strip()
        if expr == "0":
            return 0
        m = re.match(r"(\w+)\s*([+-])\s*W(\d+)$", expr)
        assert m, expr
        w = int(m.group(3)) * (1 if m.group(2) == "+" else -1)
        return cname_to_id[m.group(1)] + (w << 10)  # weight may be negative

    def table_body(name):
        body = re.search(name + r"\[\w+\] = \{(.*?)\n\};", src,
                         re.S).group(1)
        # strip line comments (incl. commented-out entries)
        return re.sub(r"//[^\n]*", "", body)

    out = {}
    for key, name, has_code in [
            ("langtag1", "kCLDLangTagsHintTable1", True),
            ("langtag2", "kCLDLangTagsHintTable2", True),
            ("tld_hint", "kCLDTLDHintTable", False)]:
        body = table_body(name)
        if has_code:
            rows = re.findall(
                r'\{"([^"]+)",\s*"[^"]*",\s*([^,}]+?)\s*'
                r'(?:,\s*([^,}]+?)\s*)?\}', body)
        else:
            rows = re.findall(
                r'\{"([^"]+)",\s*([^,}]+?)\s*(?:,\s*([^,}]+?)\s*)?\}', body)
        keys = np.array([r[0] for r in rows])
        p1 = np.array([parse_prior(r[1]) for r in rows], dtype=np.int32)
        p2 = np.array([parse_prior(r[2] or "0") for r in rows],
                      dtype=np.int32)
        out[f"{key}_keys"] = keys
        out[f"{key}_prior1"] = p1
        out[f"{key}_prior2"] = p2

    enc_src = (REF_IMPL.parent.parent / "public/encodings.h").read_text()
    body = re.search(r"enum Encoding \{(.*?)\};", enc_src, re.S).group(1)
    body = re.sub(r"//[^\n]*", "", body)
    names = []
    for m in re.finditer(r"(\w+)\s*=\s*(\d+)", body):
        assert int(m.group(2)) == len(names), (m.group(1), len(names))
        names.append(m.group(1))
    out["encoding_names"] = np.array(names)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(HERE.parent.parent /
                    "language_detector_tpu/data/cld2_tables.npz"))
    args = ap.parse_args()

    arrays, strings = load_blobs()
    out = {}

    for t in ["deltaocta", "distinctocta", "cjkdeltabi", "distinctbi",
              "cjkcompat"]:
        meta = arrays[f"{t}_meta"]
        size = int(meta[1])
        out[f"{t}_buckets"] = arrays[f"{t}_buckets"].reshape(size, 4)
        out[f"{t}_ind"] = arrays[f"{t}_ind"]
        out[f"{t}_meta"] = meta
        out[f"{t}_langscripts"] = np.array(strings[f"{t}_langscripts"][0])

    out["avg_delta_octa_score"] = arrays["avg_delta_octa_score"].reshape(614, 4)
    out["lg_prob_v2"] = arrays["lg_prob_v2_tbl"].reshape(240, 8)
    out["lang_scripts"] = arrays["lang_scripts"].reshape(614, 4)
    for k in ["lang_to_plang", "plang_to_lang_latn", "plang_to_lang_othr",
              "plang_close_set_latn", "plang_close_set_othr",
              "ulscript_rtype", "ulscript_default_lang",
              "cjk_uni_prop", "script_of_cp"]:
        out[k] = arrays[k]
    out["lower_pairs"] = arrays["lower_pairs"].reshape(-1, 2)

    for k in ["lang_name", "lang_code", "lang_cname", "ulscript_name",
              "ulscript_code"]:
        out[k] = np.array(strings[k])

    out["closest_alt_lang"] = parse_closest_alt(strings["lang_cname"])
    out["interchange_ok"] = arrays["interchange_ok"]

    # HTML entity table (kNameToEntity, generated_entities.cc — generated
    # DATA like the scoring tables; parsed from source text)
    names, values = parse_entities()
    out["entity_names"] = names
    out["entity_values"] = values
    out.update(parse_hint_tables(strings["lang_cname"]))

    out_path = Path(args.out)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    np.savez_compressed(out_path, **out)
    print(f"wrote {out_path} ({out_path.stat().st_size/1e6:.2f} MB, "
          f"{len(out)} arrays)")


if __name__ == "__main__":
    main()
