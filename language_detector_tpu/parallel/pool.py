"""Fault-tolerant device-pool scheduler: health-tracked dispatch lanes.

ROADMAP item 1's prerequisite for real multi-chip serving: a pool of N
dispatch lanes (one per mesh sub-group, or N simulated lanes sharing
the single CPU scorer) that sits between the batchers' flush workers
and the engine's jitted-scorer launches and makes the dispatch seam
fault-tolerant end to end:

  health tracking   each lane keeps an EWMA of fetch latency, a bounded
                    sample ring for on-demand p95, a consecutive-failure
                    count, and a last-completion timestamp
  lane breaker      LDT_POOL_EVICT_FAILURES consecutive failures evict
                    the lane from rotation; after
                    LDT_POOL_PROBE_COOLDOWN_SEC it re-enters half-open
                    (PROBING) and carries exactly one probe batch — a
                    healthy probe re-admits it, a failed one re-evicts
  straggler hedge   a fetch exceeding max(LDT_POOL_HEDGE_MIN_MS,
                    LDT_POOL_HEDGE_FACTOR x lane p95) re-dispatches the
                    batch on another healthy lane; the first result
                    wins, the loser is cancelled and counted
                    (ldt_pool_hedges_total{result=won|lost}), and the
                    caller sees exactly one resolution
  lost-batch path   a device/runtime error at dispatch or fetch fails
                    the batch over to the next lane in rotation
                    (ldt_pool_failover_total), bounded by
                    LDT_POOL_MAX_REDISPATCH attempts and the trace's
                    no_retry/deadline contract, before any error
                    surfaces to the batch's futures

The pool is OFF unless LDT_POOL_LANES is set: build_from_env returns
None and models/ngram.py's `_launch` takes exactly the direct path —
byte-identical single-lane behavior. When on, `_launch` returns a
_PoolFuture whose `__array__` performs the supervised fetch, so every
existing `np.asarray(fut)` fetch site (epilogue, retry lane, hinted
detect) rides the recovery machinery without changing shape.

jax is imported lazily (build_from_env, mesh lanes only): the module
itself is importable anywhere in the service layer without touching
the device runtime.
"""
from __future__ import annotations

import time
from concurrent.futures import (FIRST_COMPLETED, ThreadPoolExecutor,
                                wait)

import numpy as np

from .. import faults, flightrec, knobs, telemetry
from ..locks import make_lock

# lane states: ACTIVE lanes are in rotation; EVICTED lanes sit out
# until their probe cooldown elapses; PROBING lanes carry exactly one
# half-open probe batch whose outcome decides re-admission. CORRUPT is
# the integrity quarantine (language_detector_tpu/integrity.py): a
# scrub-digest or canary mismatch parks the lane until fresh tables
# re-upload, then it re-enters through the PROBING flow — a CORRUPT
# lane is NEVER drafted, even when every other lane is out (wrong
# answers are worse than a typed refusal)
LANE_ACTIVE = 0
LANE_EVICTED = 1
LANE_PROBING = 2
LANE_CORRUPT = 3
LANE_STATE_NAMES = ("active", "evicted", "probing", "corrupt")

# minimum completed fetches before a lane's p95 is trusted enough to
# hedge against (a cold lane's first samples are compile-dominated)
HEDGE_MIN_SAMPLES = 5

# bounded latency sample ring per lane (p95 on demand over the ring)
LANE_SAMPLE_RING = 64


class PoolExhausted(RuntimeError):
    """Every failover attempt for a batch failed (or the trace's
    no_retry/deadline contract forbade another attempt). Carries the
    last lane error as __cause__; batch futures resolve with this —
    a typed error, never a hang."""


class Lane:
    """One dispatch lane: a jitted scorer bound to a device sub-group
    (or the shared CPU scorer) plus its health state. Mutable health
    fields are owned by self._lock; the pool never holds two lane
    locks at once."""

    def __init__(self, idx: int, score_fn, mesh=None) -> None:
        self.idx = idx
        self.name = f"lane{idx}"
        self.score_fn = score_fn
        self.mesh = mesh
        # per-lane device tables (models/ngram.py assigns after upload;
        # None = the lane scores with the engine's shared dt). The
        # integrity monitor swaps this on heal re-upload.
        self.dt = None
        self._lock = make_lock("pool.lane")
        self._state = LANE_ACTIVE
        self._ewma_ms = 0.0
        self._samples: list = []   # bounded ring of fetch latencies (ms)
        self._sample_pos = 0
        self._consecutive = 0
        self._dispatches = 0
        self._failures = 0
        self._inflight = 0         # launched, fetch not yet finished
        self._last_completion = 0.0
        self._evicted_at = 0.0

    def begin_dispatch(self) -> None:
        """A program launched on this lane: count it in flight until
        its fetch finishes (success OR failure). The gauge is what the
        donation path audits — while any lane shows in-flight work for
        a batch, a hedge or failover relaunch may still re-read that
        batch's host wire arrays, so its staging lease must not be
        back in the ring yet (_PoolFuture.on_settled orders that)."""
        with self._lock:
            self._inflight += 1

    def end_dispatch(self) -> None:
        with self._lock:
            self._inflight -= 1

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def state(self) -> int:
        with self._lock:
            return self._state

    def record_success(self, elapsed_ms: float, now: float) -> bool:
        """Fold one completed fetch into the health state. Returns True
        when this success re-admitted a probing lane to rotation."""
        with self._lock:
            self._dispatches += 1
            self._consecutive = 0
            self._last_completion = now
            self._ewma_ms = elapsed_ms if self._ewma_ms == 0.0 \
                else 0.8 * self._ewma_ms + 0.2 * elapsed_ms
            if len(self._samples) < LANE_SAMPLE_RING:
                self._samples.append(elapsed_ms)
            else:
                self._samples[self._sample_pos] = elapsed_ms
                self._sample_pos = (self._sample_pos + 1) \
                    % LANE_SAMPLE_RING
            readmitted = self._state == LANE_PROBING
            if readmitted:
                self._state = LANE_ACTIVE
            return readmitted

    def record_failure(self, now: float, evict_after: int) -> bool:
        """Fold one failed dispatch/fetch in. Returns True when this
        failure newly evicted the lane (a failed PROBE re-evicts but
        does not re-count as an eviction)."""
        with self._lock:
            self._failures += 1
            self._consecutive += 1
            if self._state == LANE_PROBING:
                self._state = LANE_EVICTED
                self._evicted_at = now
                return False
            if self._state == LANE_ACTIVE \
                    and self._consecutive >= max(evict_after, 1):
                self._state = LANE_EVICTED
                self._evicted_at = now
                return True
            return False

    def probe_due(self, now: float, cooldown_sec: float) -> bool:
        """Non-mutating peek: True when this lane is EVICTED with its
        cooldown elapsed, i.e. the next dispatch through _pick_lane
        would admit it as a half-open probe."""
        with self._lock:
            return self._state == LANE_EVICTED and \
                now - self._evicted_at >= cooldown_sec

    def try_begin_probe(self, now: float, cooldown_sec: float) -> bool:
        """EVICTED -> PROBING when the cooldown elapsed; the caller owns
        the single admitted probe batch."""
        with self._lock:
            if self._state != LANE_EVICTED:
                return False
            if now - self._evicted_at < cooldown_sec:
                return False
            self._state = LANE_PROBING
            return True

    def mark_corrupt(self, now: float) -> bool:
        """ACTIVE -> CORRUPT: the integrity monitor detected a table
        digest or canary mismatch on this lane. Returns False when the
        lane is already out of rotation (evicted/probing lanes heal
        through their own flow; a double detection is a no-op)."""
        with self._lock:
            if self._state != LANE_ACTIVE:
                return False
            self._state = LANE_CORRUPT
            self._evicted_at = now
            return True

    def mark_healed(self, now: float) -> bool:
        """CORRUPT -> EVICTED with the probe cooldown already elapsed,
        after fresh tables re-uploaded and their fingerprint verified.
        The lane re-enters rotation through the ordinary half-open
        flow (_pick_lane's try_begin_probe admits it on the next
        rotation pass — PROBING stays owned by exactly one dispatch),
        so re-admission still requires one healthy served batch."""
        with self._lock:
            if self._state != LANE_CORRUPT:
                return False
            self._state = LANE_EVICTED
            # fresh, verified tables: no reason to serve a cooldown —
            # the next dispatch rotation admits the probe immediately
            self._evicted_at = float("-inf")
            return True

    def p95_ms(self) -> float | None:
        """On-demand p95 over the sample ring; None below the hedge
        sample floor."""
        with self._lock:
            n = len(self._samples)
            if n < HEDGE_MIN_SAMPLES:
                return None
            s = sorted(self._samples)
            return s[min(int(n * 0.95), n - 1)]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "lane": self.name,
                "state": LANE_STATE_NAMES[self._state],
                "ewma_ms": round(self._ewma_ms, 3),
                "dispatches": self._dispatches,
                "failures": self._failures,
                "consecutive_failures": self._consecutive,
                "inflight": self._inflight,
                "last_completion": self._last_completion,
            }


class _PoolFuture:
    """Handle for a pool-supervised dispatch. `__array__` runs the
    supervised fetch (hedge + failover), so every np.asarray(fut) site
    in the engine resolves through the pool; the result is memoized so
    a double fetch can never re-dispatch (never double-resolved).

    Settled accounting: the future SETTLES when the supervised fetch
    has returned or raised — at that point every launch_fn invocation
    this batch will ever make (initial dispatch, hedge, failover
    relaunches) has already returned, because they all run
    synchronously inside the supervised fetch. launch_fn is the only
    consumer of the batch's host wire arrays (JAX copies them into
    device buffers during the call), so on_settled is exactly the
    point where a donated staging lease may re-enter the ring. A
    hedge-loser fetch can still be draining on its executor thread
    after settlement — it only reads the lane's DEVICE result buffer,
    never the host wire, and its value is discarded. `attempts` is the
    number of lane attempts the supervised fetch spent (1 = no
    failover)."""

    __slots__ = ("_pool", "lane", "raw", "launch_fn", "trace",
                 "_result", "_lock", "_settled", "_callbacks",
                 "attempts")

    def __init__(self, pool: "DevicePool", lane: Lane, raw,
                 launch_fn, trace) -> None:
        self._pool = pool
        self.lane = lane
        self.raw = raw
        self.launch_fn = launch_fn
        self.trace = trace
        self._result = None
        self._lock = make_lock("pool.future")
        self._settled = False
        self._callbacks: list = []
        self.attempts = 0

    def on_settled(self, callback) -> None:
        """Run callback once the future settles (immediately when it
        already has). The engine releases donated staging leases here;
        callbacks must be idempotent and must not block."""
        with self._lock:
            if not self._settled:
                self._callbacks.append(callback)
                return
        callback()

    def _settle(self) -> None:
        with self._lock:
            if self._settled:
                return
            self._settled = True
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb()

    def __array__(self, dtype=None) -> np.ndarray:
        if self._result is None:
            try:
                self._result = self._pool._fetch(self)
            finally:
                self._settle()
        out = self._result
        return out if dtype is None else np.asarray(out, dtype=dtype)


class DevicePool:
    """N health-tracked dispatch lanes with rotation, eviction,
    half-open probing, straggler hedging, and lost-batch failover.

    Thread-safety: the pool lock owns rotation state (the round-robin
    cursor); each Lane owns its own health under its lane lock. Fetches
    block on a private executor (sized to keep every lane's fetch plus
    a hedge in flight) so a stalled device never wedges the caller past
    the hedge threshold."""

    def __init__(self, lanes: list, lane_mesh_size: int = 1,
                 hedge_factor: float | None = None,
                 hedge_min_ms: float | None = None,
                 evict_failures: int | None = None,
                 probe_cooldown_sec: float | None = None,
                 max_redispatch: int | None = None,
                 clock=None) -> None:
        if not lanes:
            raise ValueError("DevicePool needs at least one lane")
        self.lanes = lanes
        self.lane_mesh_size = lane_mesh_size
        self.hedge_factor = knobs.get_float("LDT_POOL_HEDGE_FACTOR") \
            if hedge_factor is None else hedge_factor
        self.hedge_min_ms = knobs.get_float("LDT_POOL_HEDGE_MIN_MS") \
            if hedge_min_ms is None else hedge_min_ms
        self.evict_failures = knobs.get_int("LDT_POOL_EVICT_FAILURES") \
            if evict_failures is None else evict_failures
        self.probe_cooldown_sec = \
            knobs.get_float("LDT_POOL_PROBE_COOLDOWN_SEC") \
            if probe_cooldown_sec is None else probe_cooldown_sec
        self.max_redispatch = knobs.get_int("LDT_POOL_MAX_REDISPATCH") \
            if max_redispatch is None else max_redispatch
        self._now = clock or time.monotonic
        self._lock = make_lock("pool.rotation")
        self._rr = 0
        self._exec = ThreadPoolExecutor(
            max(2 * len(lanes) + 2, 4),
            thread_name_prefix="ldt-pool")

    def close(self) -> None:
        self._exec.shutdown(wait=False)

    # -- lane selection -----------------------------------------------------

    def _pick_lane(self, exclude: Lane | None = None) -> Lane:
        """Next lane in rotation: ACTIVE lanes round-robin; an EVICTED
        lane whose cooldown elapsed is admitted as a half-open probe.
        When every lane is out of rotation the least-recently-evicted
        lane is drafted anyway — work must go SOMEWHERE, and a fully
        evicted pool behaves like the breaker-open path (errors surface
        typed, the ladder sheds load upstream). The one exception is
        CORRUPT: a quarantined lane would serve WRONG answers, not slow
        ones, so the draft skips it and an all-corrupt pool raises
        typed instead (the scrub pass heals synchronously, so that
        state lasts one scrub interval at most)."""
        now = self._now()
        with self._lock:
            n = len(self.lanes)
            for _ in range(n):
                lane = self.lanes[self._rr % n]
                self._rr += 1
                if lane is exclude and n > 1:
                    continue
                if lane.state() == LANE_ACTIVE:
                    return lane
                if lane.try_begin_probe(now, self.probe_cooldown_sec):
                    return lane
            for skip_exclude in (True, False):
                for _ in range(n):
                    lane = self.lanes[self._rr % n]
                    self._rr += 1
                    if skip_exclude and lane is exclude and n > 1:
                        continue
                    if lane.state() != LANE_CORRUPT:
                        return lane
            raise PoolExhausted(
                "every pool lane is quarantined CORRUPT; refusing to "
                "serve from corrupt tables")

    def _lane_failed(self, lane: Lane) -> None:
        if lane.record_failure(self._now(), self.evict_failures):
            telemetry.REGISTRY.counter_inc(
                "ldt_pool_lane_evicted_total", lane=lane.name)
            flightrec.emit_event("pool_lane_state", lane=lane.name,
                                 state="evicted")

    # -- dispatch -----------------------------------------------------------

    def launch(self, launch_fn, trace=None) -> _PoolFuture:
        """Dispatch a batch on the pool: launch_fn(lane) must start the
        device program on that lane and return its raw future. A launch
        error (device lost, OOM at dispatch) counts against the lane
        and fails over to the next in rotation. Returns a _PoolFuture;
        the fetch side (np.asarray) carries hedging and lost-batch
        recovery."""
        last_err: Exception | None = None
        lane: Lane | None = None
        for _ in range(max(self.max_redispatch, 1)):
            lane = self._pick_lane(exclude=lane)
            try:
                raw = self._launch_on(lane, launch_fn)
            except Exception as e:  # noqa: BLE001 - any launch error fails over
                self._lane_failed(lane)
                last_err = e
                continue
            return _PoolFuture(self, lane, raw, launch_fn, trace)
        raise PoolExhausted(
            f"no lane accepted the dispatch after "
            f"{max(self.max_redispatch, 1)} attempts") from last_err

    def _launch_on(self, lane: Lane, launch_fn):
        if faults.ACTIVE is not None:
            faults.hit("lane_dispatch")
        raw = launch_fn(lane)
        # only a launch that RETURNED is in flight; a raising launch_fn
        # never occupied the lane
        lane.begin_dispatch()
        return raw

    # -- fetch: hedge + failover --------------------------------------------

    def _fetch_on(self, lane: Lane, raw) -> np.ndarray:
        """Blocking fetch of one raw future on one lane (executor
        thread). Success and latency fold into the lane's health; a
        probing lane's success re-admits it."""
        try:
            if faults.ACTIVE is not None:
                faults.hit("lane_stall")
                faults.hit("lane_lost")
            t0 = self._now()
            out = np.asarray(raw)
            if lane.record_success((self._now() - t0) * 1e3,
                                   self._now()):
                telemetry.REGISTRY.counter_inc(
                    "ldt_pool_lane_readmitted_total", lane=lane.name)
                flightrec.emit_event("pool_lane_state", lane=lane.name,
                                     state="readmitted")
            return out
        finally:
            # success OR failure retires the dispatch: the lane's
            # in-flight gauge must drain so redispatch of a donated
            # batch never double-counts the lost lane
            lane.end_dispatch()

    def _hedge_threshold_sec(self, lane: Lane, trace) -> float | None:
        """Seconds to wait before hedging this lane's fetch, or None
        when hedging is off (factor 0, no_retry flush, single lane, or
        the lane lacks a trusted p95)."""
        if not self.hedge_factor or self.hedge_factor <= 0:
            return None
        if len(self.lanes) < 2:
            return None
        if trace is not None and getattr(trace, "no_retry", False):
            return None
        p95 = lane.p95_ms()
        if p95 is None:
            return None
        return max(self.hedge_min_ms, self.hedge_factor * p95) / 1e3

    def _may_failover(self, trace) -> bool:
        """The existing no_retry/deadline contract: a near-deadline or
        brownout flush must not queue another device round — its error
        surfaces immediately and the epilogue resolves scalar."""
        if trace is None:
            return True
        if getattr(trace, "no_retry", False):
            return False
        dl = getattr(trace, "deadline", None)
        if dl is not None and dl.expired():
            return False
        return True

    def _await_result(self, lane, raw, pf) -> np.ndarray:
        """One supervised wait on one lane's fetch, hedging onto a
        second lane past the straggler threshold. Exactly one result
        is returned; the losing future is cancelled and counted, and a
        loser that still completes only updates lane health."""
        fut = self._exec.submit(self._fetch_on, lane, raw)
        thresh = self._hedge_threshold_sec(lane, pf.trace)
        if thresh is None:
            return fut.result()
        done, _ = wait([fut], timeout=thresh)
        if fut in done:
            return fut.result()
        hlane = self._pick_lane(exclude=lane)
        if hlane is lane or hlane.state() != LANE_ACTIVE:
            return fut.result()
        try:
            hraw = self._launch_on(hlane, pf.launch_fn)
        except Exception:  # noqa: BLE001 - hedge launch failure falls back
            self._lane_failed(hlane)
            return fut.result()
        hfut = self._exec.submit(self._fetch_on, hlane, hraw)
        done, _ = wait([fut, hfut], return_when=FIRST_COMPLETED)
        winner = fut if fut in done else hfut
        loser = hfut if winner is fut else fut
        # prefer a finished SUCCESS over a finished failure: when the
        # straggler finally errored while the hedge succeeded (or the
        # reverse), the caller gets the good result and the failure
        # only feeds lane health
        if winner.exception() is not None and loser.done() \
                and loser.exception() is None:
            winner, loser = loser, winner
        loser.cancel()
        if loser.cancelled():
            # the loser's _fetch_on never ran, so retire its dispatch
            # here — the in-flight gauge must not leak on a cancel
            (hlane if loser is hfut else lane).end_dispatch()
        if loser.done() and not loser.cancelled() \
                and loser.exception() is not None:
            self._lane_failed(hlane if loser is hfut else lane)
        telemetry.REGISTRY.counter_inc(
            "ldt_pool_hedges_total",
            result="won" if winner is hfut else "lost")
        return winner.result()

    def _fetch(self, pf) -> np.ndarray:
        """Supervised fetch for a _PoolFuture: hedge stragglers, catch
        lane errors, and fail the batch over to surviving lanes until
        the redispatch budget or the no_retry/deadline contract stops
        it. Every error path raises (typed) — futures upstream always
        resolve."""
        lane, raw = pf.lane, pf.raw
        budget = max(self.max_redispatch, 1)
        attempts = 0
        last_err: Exception | None = None
        while True:
            attempts += 1
            pf.attempts = attempts
            try:
                return self._await_result(lane, raw, pf)
            except Exception as e:  # noqa: BLE001 - any fetch error is a lost batch
                self._lane_failed(lane)
                last_err = e
                if not self._may_failover(pf.trace):
                    raise
            # lost batch: re-dispatch on the next lane in rotation
            # (failed relaunches spend the same attempt budget)
            relaunched = False
            while attempts < budget:
                telemetry.REGISTRY.counter_inc("ldt_pool_failover_total")
                lane = self._pick_lane(exclude=lane)
                try:
                    raw = self._launch_on(lane, pf.launch_fn)
                # ldt-lint: disable=future-consumer-guard -- handler re-enters the relaunch loop; every _fetch exit raises typed
                except Exception as e:  # noqa: BLE001 - relaunch error, next lane
                    self._lane_failed(lane)
                    last_err = e
                    attempts += 1
                    pf.attempts = attempts
                    continue
                relaunched = True
                break
            if not relaunched:
                break
        raise PoolExhausted(
            f"batch lost after {attempts} lane attempts "
            f"(budget {budget})") from last_err

    # -- capacity & stats ---------------------------------------------------

    def capacity(self) -> tuple[int, int]:
        """(lanes in rotation, lanes total); PROBING counts as in
        rotation — it is carrying work. EVICTED and CORRUPT lanes are
        out (a quarantined lane sheds load upstream exactly like an
        evicted one)."""
        active = sum(1 for ln in self.lanes
                     if ln.state() not in (LANE_EVICTED, LANE_CORRUPT))
        return active, len(self.lanes)

    def capacity_load(self) -> float:
        """Pool-capacity loss as an occupancy-scale load signal for the
        brownout ladder (service/admission.py): 0.0 fully healthy, 0.6
        at half the lanes evicted (ladder level 1), 1.2 fully evicted
        (level 3 — shed, like a breaker-open worker)."""
        active, total = self.capacity()
        if total == 0:
            return 0.0
        return 1.2 * (total - active) / total

    def wants_probe(self) -> bool:
        """True when some evicted lane's cooldown has elapsed and no
        probe is already in flight. Half-open probes are traffic-driven
        (_pick_lane only re-admits on a dispatch), so upstream load
        shedding must let ONE request through a full-shed brownout as
        the probe vehicle — shedding everything would turn a fully
        evicted pool into a self-sustaining outage (the ladder sheds
        because the pool is down, and the pool stays down because
        everything sheds)."""
        now = self._now()
        due = False
        for lane in self.lanes:
            state = lane.state()
            if state == LANE_PROBING:
                return False
            if state == LANE_EVICTED and \
                    lane.probe_due(now, self.probe_cooldown_sec):
                due = True
        return due

    def stats(self) -> dict:
        active, total = self.capacity()
        return {
            "lanes_total": total,
            "lanes_active": active,
            "lane_mesh_size": self.lane_mesh_size,
            "lanes": [ln.snapshot() for ln in self.lanes],
        }


def build_from_env(default_score_fn, mesh=None) -> "DevicePool | None":
    """Build the pool the LDT_POOL_* knobs describe, or None when
    LDT_POOL_LANES is unset/0 (pool off: the engine dispatches exactly
    as before). With a mesh, devices partition into one sub-mesh per
    lane (parallel/mesh.lane_meshes) and each lane gets its own
    shard_map'd scorer; without one, N simulated lanes share
    default_score_fn — same scheduler, same chaos seams, single
    device."""
    n = knobs.get_int("LDT_POOL_LANES")
    if not n:
        return None
    if mesh is not None:
        from .mesh import lane_meshes, sharded_score_chunks_fn
        meshes = lane_meshes(mesh, n)
        lanes = [Lane(i, sharded_score_chunks_fn(m), mesh=m)
                 for i, m in enumerate(meshes)]
        lane_mesh_size = len(list(meshes[0].devices.flat))
    else:
        lanes = [Lane(i, default_score_fn) for i in range(n)]
        lane_mesh_size = 1
    return DevicePool(lanes, lane_mesh_size=lane_mesh_size)
