#!/usr/bin/env python3
"""Stage-level cycle profile of the native packer's hot path.

Generates native/packer_prof.cc from packer.cc by inserting
LDT_PROF_SCOPE markers at the stage boundaries (the scaffolding —
counters + ProfScope — is compiled into packer.cc only under
-DLDT_PROF), builds a side-by-side instrumented .so, and runs the
bench corpus through it, printing per-stage cycle minima over N runs
(minimum-of-runs is the least host-interfered measurement on this
shared single-core machine; see docs/PERF.md).

Usage: python tools/profile_pack.py [batch_size] [runs]
"""
from __future__ import annotations

import ctypes
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
NATIVE = REPO / "language_detector_tpu" / "native"

# (anchor line, scope slot) — anchors are the exact signatures in
# packer.cc; a failed anchor is a hard error so the profile can never
# silently measure the wrong stage
SCOPES = [
    ("void segment_text(const uint8_t* text, int text_len, "
     "SegScratch* ss,\n                  bool collect_src = false) {\n",
     0),
    ("int64_t scan_quad_round(const Span& sp, int64_t start,\n"
     "                        std::vector<Rec>* recs, int* n_quota,\n"
     "                        int* n_emit) {\n", 1),
    ("void scan_word_range(const Span& sp, int64_t start, int64_t end,\n"
     "                     std::vector<Rec>* recs, int* n_emit) {\n", 2),
    ("      int cum_entries = 0;  // consumed base entries, exclusive", 4),
    ("void build_span(const std::vector<uint32_t>& cur, int ulscript,\n"
     "                Span* sp, const std::vector<int32_t>* src = "
     "nullptr) {\n", 5),
    ("void pack_resolve_one_doc(const uint8_t* text, int text_len, "
     "int b,\n                          const ROut& o) {\n", 7),
]
NAMES = ["segment", "quad_scan", "word_scan", "-", "emit",
         "build_span", "-", "total_doc"]


def build_instrumented() -> Path:
    src = (NATIVE / "packer.cc").read_text()
    for anchor, slot in SCOPES:
        if anchor not in src:
            sys.exit(f"profile anchor not found in packer.cc: "
                     f"{anchor.splitlines()[0]!r}")
        ins = f"  LDT_PROF_SCOPE({slot});\n"
        if not anchor.endswith("\n"):  # mid-line anchor: break the line
            ins = "\n    " + ins
        src = src.replace(anchor, anchor + ins, 1)
    prof_cc = NATIVE / "packer_prof.cc"
    prof_cc.write_text(src)
    so = NATIVE / "libldtpack_prof.so"
    # build.sh owns the flag set and the ISA sidecar — the instrumented
    # twin differs from production ONLY by -DLDT_PROF and the source file
    import os
    env = dict(os.environ, LDT_SRC=prof_cc.name,
               LDT_EXTRA_FLAGS="-DLDT_PROF")
    subprocess.run(["bash", str(NATIVE / "build.sh"), so.name],
                   check=True, env=env)
    return so


def main(batch_size: int = 16384, runs: int = 8):
    so = build_instrumented()
    from language_detector_tpu import native
    native._SO = so  # load the instrumented twin instead of the real lib
    from bench import make_corpus
    from language_detector_tpu.registry import registry as reg
    from language_detector_tpu.tables import load_tables
    tables = load_tables()
    docs = make_corpus(batch_size)
    native.pack_chunks_native(docs, tables, reg, flags=0)  # warm + init
    lib = native._load()
    prof = (ctypes.c_uint64 * 8).in_dll(lib, "ldt_prof_cycles")
    best = [float("inf")] * 8
    best_wall = float("inf")
    for _ in range(runs):
        for i in range(8):
            prof[i] = 0
        t0 = time.time()
        native.pack_chunks_native(docs, tables, reg, flags=0)
        best_wall = min(best_wall, time.time() - t0)
        for i in range(8):
            best[i] = min(best[i], prof[i])
    print(f"pack wall (best of {runs}): {best_wall * 1e3:.1f} ms "
          f"/ {batch_size} docs")
    for name, v in zip(NAMES, best):
        if name != "-":
            print(f"{name:12s} {v / 1e6:8.1f} Mcycles")


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
