"""C++ epilogue (native/epilogue.cc) vs the Python document epilogue.

The native path must agree with models/ngram.py _doc_epilogue (itself
pinned to the scalar engine by test_batch_agreement) on every document:
real texts through the full pipeline, plus randomized chunk summaries that
exercise DocTote eviction, close-pair merges, unreliable removal, and the
summary-language edge cases far beyond what natural text reaches.
"""
import numpy as np
import pytest

from language_detector_tpu import native
from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.models.ngram import NgramBatchEngine
from language_detector_tpu.registry import registry
from language_detector_tpu.tables import ScoringTables

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

TEXTS = [
    "The quick brown fox jumps over the lazy dog near the river bank",
    "Le gouvernement a annoncé de nouvelles mesures pour aider les familles",
    "Der Hund läuft schnell durch den großen Wald und findet einen Knochen",
    "こんにちは世界。今日はとても良い天気ですね。散歩に行きましょう。",
    "Привет мир, это предложение написано на русском языке для теста",
    "मैं आज बाजार गया और कुछ फल खरीदे क्योंकि वे ताजा थे",
    "Short",
    "",
    "Mixed language text avec du français and English zusammen gemischt",
    "ไปโรงเรียนทุกวันเพื่อเรียนหนังสือและพบเพื่อน",
]


@pytest.fixture(scope="module")
def eng():
    return NgramBatchEngine(ScoringTables.load(), registry)


def _python_results(eng, texts, packed, out):
    results = []
    for b, text in enumerate(texts):
        if packed.fallback[b]:
            results.append(detect_scalar(text, eng.tables, eng.reg,
                                         eng.flags))
            continue
        r = eng._doc_epilogue(packed, out, b)
        if r is None:
            r = detect_scalar(text, eng.tables, eng.reg, eng.flags)
        results.append(r)
    return results


def test_native_epilogue_real_texts(eng):
    texts = TEXTS * 3
    packed = eng._pack(texts, eng.tables, eng.reg,
                       max_slots=eng.max_slots, max_chunks=eng.max_chunks,
                       flags=eng.flags)
    out = eng.score_packed(packed)
    want = _python_results(eng, texts, packed, out)
    got = eng._epilogue_native(texts, packed, out)
    assert [dataclass_tuple(r) for r in got] == \
        [dataclass_tuple(r) for r in want]


def dataclass_tuple(r):
    return (r.summary_lang, r.language3, r.percent3, r.normalized_score3,
            r.text_bytes, r.is_reliable)


def test_native_epilogue_randomized(eng):
    """Synthetic chunk summaries: random languages/bytes/scores/reliability
    hammer the DocTote eviction + merge paths."""
    rng = np.random.default_rng(7)
    B, C, D = 256, 8, 4
    langs = rng.integers(0, 200, (B, C)).astype(np.int32)
    nbytes = rng.integers(0, 2000, (B, C)).astype(np.int32)
    scores = rng.integers(0, 4000, (B, C)).astype(np.int32)
    rel = rng.integers(0, 101, (B, C)).astype(np.int32)
    real = (rng.random((B, C)) < 0.8).astype(np.int32)
    rows = np.stack([langs, nbytes, scores, rel, real], axis=-1)
    direct = np.full((B, D, 3), -1, np.int32)
    # a third of docs get one direct add on a random chunk id
    for b in range(0, B, 3):
        direct[b, 0] = (int(rng.integers(0, C)),
                        int(rng.integers(0, 200)),
                        int(rng.integers(1, 500)))
    text_bytes = rng.integers(0, 20000, B).astype(np.int32)
    skip = np.zeros(B, bool)

    ep = native.epilogue_batch_native(rows, direct, text_bytes, skip,
                                      0, registry)

    from language_detector_tpu.engine_scalar import (
        FLAG_FINISH, GOOD_LANG1_PERCENT, GOOD_LANG1AND2_PERCENT,
        SHORT_TEXT_THRESH, DocTote, calc_summary_lang, extract_lang_etc,
        refine_close_pairs, remove_unreliable)
    for b in range(B):
        doc = DocTote()
        dmap = {int(c): (int(l), int(n)) for c, l, n in direct[b] if c >= 0}
        for c in range(C):
            if c in dmap:
                lang, nb = dmap[c]
                doc.add(lang, nb, nb, 100)
            elif rows[b, c, 4]:
                doc.add(int(rows[b, c, 0]), int(rows[b, c, 1]),
                        int(rows[b, c, 2]), int(rows[b, c, 3]))
        refine_close_pairs(registry, doc)
        doc.sort()
        lang3, percent3, rel3, ns3, total, is_rel = extract_lang_etc(
            doc, int(text_bytes[b]))
        good = total <= SHORT_TEXT_THRESH or \
            (is_rel and percent3[0] >= GOOD_LANG1_PERCENT) or \
            (is_rel and percent3[0] + percent3[1] >= GOOD_LANG1AND2_PERCENT)
        if not good:
            assert ep[b, 12] == 1, b
            continue
        assert ep[b, 12] == 0, b
        remove_unreliable(registry, doc)
        doc.sort()
        lang3, percent3, rel3, ns3, total, is_rel = extract_lang_etc(
            doc, int(text_bytes[b]))
        summary, reliable = calc_summary_lang(registry, lang3, percent3,
                                              total, is_rel, 0)
        assert ep[b, 0] == summary, b
        assert list(ep[b, 1:4]) == lang3, b
        assert list(ep[b, 4:7]) == percent3, b
        assert [float(x) for x in ep[b, 7:10]] == ns3, b
        assert ep[b, 10] == total, b
        assert bool(ep[b, 11]) == (is_rel and reliable), b
