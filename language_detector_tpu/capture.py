"""Traffic capture plane: crash-safe, anonymized, fixed-width request
records for replay and knob tuning.

The flight recorder (flightrec.py) records EVENTS; nothing records the
WORKLOAD — request shapes, arrival times, tenants, deadlines — which is
exactly what tuning the fleet's ~60 knobs (ROADMAP item 5) needs. When
LDT_CAPTURE_DIR is set, every completed request appends one fixed-width
binary record to an mmap'd capture ring using flightrec.py's
publish-order commit-word discipline: the record body lands in the map
BEFORE the 4-byte commit word is stored, so a reader — including one
harvesting the file of a SIGKILLed process — never observes a
torn-but-published record.

Record layout (little-endian, RECORD below; the struct sizes are
pinned by tests/test_capture.py so the format cannot drift silently):

    arrival_mono_ns  u64  monotonic arrival (trace.t0); the file
                          header's wall/mono anchor pair converts it
                          to comparable wall time across processes
    tenant_hash      u64  blake2b-8 of the tenant id — anonymized:
                          raw tenant strings never touch disk
    cache_bits       u64  per-doc cache-hit bitmap (first 64 docs)
                          when the front reports it; 0 otherwise
    docs             u32  documents in the request
    deadline_ms      f32  declared deadline budget (0 = none)
    total_ms         f32  end-to-end latency
    parse_ms         f32  } per-stage breakdown summed from the
    detect_ms        f32  } request's existing Trace spans
    encode_ms        f32  }
    status           u16  final HTTP status
    size_bucket      u8   log2 bucket of the request body bytes
    lane             u8   0=tcp 1=uds 2=shm
    verdict          u8   0=ok 1=shed 2=error 3=timeout 4=invalid
    flags            u8   bit0 priority, bit1 shed

Rotation is size-bounded: the active ring holds
LDT_CAPTURE_RING_RECORDS records; when it fills, the committed records
are sealed into an immutable segment file via tmp+rename (the aot.py
publication idiom — a crashed writer leaves only a torn tmp file no
reader ever opens) and the ring restarts. At most
LDT_CAPTURE_MAX_SEGMENTS sealed segments are kept per writer (oldest
unlinked first). LDT_CAPTURE_SAMPLE keeps a probabilistic fraction of
requests; the RNG is injectable/seedable so sampling is deterministic
under test.

Readers: read_capture(dir) parses one directory's sealed segments and
live/abandoned rings; merge_captures(dir) walks a directory tree (the
fleet gives each member m<slot>/ its own subdir, same pattern as
flightrec) and merges every record by wall-clock arrival time — the
input `bench.py --replay` re-drives against a live fleet.
"""
from __future__ import annotations

import errno
import glob
import hashlib
import json
import mmap
import os
import random
import struct
import time

from . import knobs
from .locks import make_lock


def _log(msg: str, **fields) -> None:
    print(json.dumps({"msg": msg, **fields}), flush=True)

RING_MAGIC = b"LDCR"
SEG_MAGIC = b"LDCS"
VERSION = 1

# ring/segment file header: magic, version, slots (record capacity;
# for segments: committed record count), record size, pid,
# wall anchor (epoch seconds), monotonic anchor (ns) — the anchor pair
# converts per-record monotonic arrivals to comparable wall time
FILE_HDR = struct.Struct("<4sIIIIdQ")
COMMIT = struct.Struct("<I")         # per-slot commit word (index + 1)
RECORD = struct.Struct("<QQQIfffffHBBBB")
SLOT_BYTES = COMMIT.size + RECORD.size

# pinned on-disk geometry: a drive-by field edit must fail at import,
# not corrupt capture rings or strand sealed segments
# (tools/lint/layout_registry.py declares the same widths)
assert FILE_HDR.size == 36
assert COMMIT.size == 4
assert RECORD.size == 54

LANES = {"tcp": 0, "uds": 1, "shm": 2}
LANE_NAMES = {v: k for k, v in LANES.items()}
# both HTTP fronts are the tcp lane; wire.handle_frame tags uds/shm
_FRONT_LANE = {"sync": 0, "aio": 0, "tcp": 0, "uds": 1, "shm": 2}

VERDICTS = {"ok": 0, "shed": 1, "error": 2, "timeout": 3, "invalid": 4}
VERDICT_NAMES = {v: k for k, v in VERDICTS.items()}

FLAG_PRIORITY = 0x01
FLAG_SHED = 0x02


def tenant_hash(tenant: str | None) -> int:
    """Stable anonymized tenant identity: 8-byte blake2b of the raw id.
    Raw tenant strings never reach the capture file; replay re-drives
    distinct tenants as t<hash hex>."""
    raw = (tenant or "default").encode("utf-8", "replace")
    return int.from_bytes(
        hashlib.blake2b(raw, digest_size=8).digest(), "little")


def size_bucket(nbytes: int) -> int:
    """Log2 byte-size bucket (0 for empty); anonymization by design —
    the capture stores shape, never content."""
    return max(int(nbytes).bit_length(), 0) if nbytes > 0 else 0


def _verdict(status, meta: dict) -> int:
    if meta.get("shed"):
        return VERDICTS["shed"]
    if isinstance(status, int) and status >= 500:
        return VERDICTS["timeout"] if meta.get("timeout") \
            else VERDICTS["error"]
    if isinstance(status, int) and status >= 400:
        return VERDICTS["invalid"]
    return VERDICTS["ok"]


def record_from(trace, meta: dict | None, total_ms: float) -> tuple:
    """One request -> the RECORD field tuple, built entirely from the
    Trace and the completion meta both fronts already assemble."""
    meta = meta or {}
    status = meta.get("status")
    deadline = getattr(trace, "deadline", None)
    deadline_ms = 0.0
    if deadline is not None:
        deadline_ms = float(getattr(deadline, "budget_ms", 0.0) or 0.0)
    flags = 0
    if meta.get("priority"):
        flags |= FLAG_PRIORITY
    if meta.get("shed"):
        flags |= FLAG_SHED
    return (
        int(trace.t0 * 1e9) & 0xFFFFFFFFFFFFFFFF,
        tenant_hash(getattr(trace, "tenant", None)),
        int(meta.get("cache_bits", 0)) & 0xFFFFFFFFFFFFFFFF,
        int(meta.get("docs", 0)) & 0xFFFFFFFF,
        deadline_ms,
        float(total_ms),
        float(trace.span_ms("parse")),
        float(trace.span_ms("detect")),
        float(trace.span_ms("encode")),
        int(status) & 0xFFFF if isinstance(status, int) else 0,
        min(size_bucket(int(meta.get("bytes", 0) or 0)), 255),
        _FRONT_LANE.get(meta.get("front"), 0),
        _verdict(status, meta),
        flags,
    )


class CaptureWriter:
    """One process's capture ring + sealed segments (single writer)."""

    def __init__(self, directory: str, ring_records: int | None = None,
                 sample: float | None = None,
                 max_segments: int | None = None,
                 seed: int | None = None):
        if ring_records is None:
            ring_records = knobs.get_int("LDT_CAPTURE_RING_RECORDS") \
                or 4096
        if sample is None:
            sample = knobs.get_float("LDT_CAPTURE_SAMPLE")
            sample = 1.0 if sample is None else sample
        if max_segments is None:
            max_segments = knobs.get_int("LDT_CAPTURE_MAX_SEGMENTS") \
                or 64
        self.dir = str(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.ring_records = max(int(ring_records), 16)
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.max_segments = max(int(max_segments), 1)
        self._rng = random.Random(seed)
        self._lock = make_lock("capture.ring")
        # set by _seal_locked on a disk-full seal; observe() reads it
        # outside the ring lock and retires the plane for good
        self.disabled_reason: str | None = None
        self._seq = 0            # committed records in the active ring
        self._segments = 0       # segments sealed over the lifetime
        self._records_total = 0
        self._sampled_out = 0
        self.path = os.path.join(self.dir,
                                 f"capture-{os.getpid()}.ring")
        self._wall_anchor = time.time()
        self._mono_anchor = time.monotonic_ns()
        size = FILE_HDR.size + self.ring_records * SLOT_BYTES
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR | os.O_TRUNC,
                     0o644)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.mm[:FILE_HDR.size] = FILE_HDR.pack(
            RING_MAGIC, VERSION, self.ring_records, RECORD.size,
            os.getpid(), self._wall_anchor, self._mono_anchor)

    # -- hot path -----------------------------------------------------------

    def append(self, rec: tuple) -> bool:
        """Record one request. Publish order: record body first, the
        commit word (slot index + 1) LAST — its store is the
        publication point (flightrec.emit discipline). Returns False
        when sampled out."""
        if self.sample < 1.0 and self._rng.random() >= self.sample:
            with self._lock:
                self._sampled_out += 1
            return False
        payload = RECORD.pack(*rec)
        with self._lock:
            if self._seq >= self.ring_records:
                self._seal_locked()
            i = self._seq
            off = FILE_HDR.size + i * SLOT_BYTES
            mm = self.mm
            mm[off + COMMIT.size:off + SLOT_BYTES] = payload
            mm[off:off + COMMIT.size] = COMMIT.pack(i + 1)
            self._seq = i + 1
            self._records_total += 1
        return True

    # -- rotation -----------------------------------------------------------

    def _seal_locked(self) -> None:
        """Seal the full ring into an immutable segment file (tmp +
        rename, aot.py publication idiom) and restart the ring. Prunes
        this writer's oldest segments past max_segments."""
        n = self._seq
        body = self.mm[FILE_HDR.size:FILE_HDR.size + n * SLOT_BYTES]
        records = bytearray()
        for i in range(n):
            off = i * SLOT_BYTES
            (commit,) = COMMIT.unpack_from(body, off)
            if commit != i + 1:
                continue  # torn slot: sealed segments hold only
                # committed records
            records += body[off + COMMIT.size:off + SLOT_BYTES]
        count = len(records) // RECORD.size
        self._segments += 1
        seg = os.path.join(
            self.dir,
            f"segment-{os.getpid()}-{self._segments:06d}.cap")
        tmp = f"{seg}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(FILE_HDR.pack(SEG_MAGIC, VERSION, count,
                                      RECORD.size, os.getpid(),
                                      self._wall_anchor,
                                      self._mono_anchor))
                f.write(bytes(records))
            os.replace(tmp, seg)
        except OSError as e:
            # a full disk is terminal for the plane, not the service:
            # flag it here (observe() retires the writer outside this
            # lock) instead of burning a failed seal every ring fill
            if e.errno == errno.ENOSPC:
                self.disabled_reason = "enospc"
            try:
                os.remove(tmp)
            except OSError:
                pass
        # restart the ring: zero every commit word so stale records
        # from the sealed generation can never be re-read
        self.mm[FILE_HDR.size:] = b"\0" * (len(self.mm) - FILE_HDR.size)
        self._seq = 0
        self._prune_locked()

    def _prune_locked(self) -> None:
        mine = sorted(glob.glob(os.path.join(
            self.dir, f"segment-{os.getpid()}-*.cap")))
        for path in mine[:max(len(mine) - self.max_segments, 0)]:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- views --------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"dir": self.dir,
                    "records_total": self._records_total,
                    "sampled_out": self._sampled_out,
                    "segments_sealed": self._segments,
                    "ring_records": self.ring_records,
                    "ring_occupancy": self._seq,
                    "sample": self.sample}

    def close(self) -> None:
        try:
            self.mm.flush()
            self.mm.close()
        except (BufferError, ValueError, OSError):
            pass


# Module-level writer: None = disabled (the fast-path check). Armed by
# init_from_env() at front startup; rebound atomically.
WRITER: CaptureWriter | None = None


def init_from_env() -> CaptureWriter | None:
    """Arm the process capture writer from LDT_CAPTURE_DIR (unset =
    stay disabled). Idempotent; best-effort — capture must never fail
    a front's startup."""
    global WRITER
    if WRITER is not None:
        return WRITER
    directory = knobs.get_str("LDT_CAPTURE_DIR")
    if not directory:
        return None
    try:
        WRITER = CaptureWriter(directory)
    except OSError as e:
        _disable("enospc" if e.errno == errno.ENOSPC else "oserror",
                 directory, repr(e))
        return None
    return WRITER


def _disable(reason: str, directory: str, detail: str) -> None:
    """Retire the capture plane: structured log + counted disable. The
    service keeps serving — capture is observability, never load-
    bearing."""
    from . import telemetry
    telemetry.REGISTRY.counter_inc("ldt_capture_disabled_total",
                                   reason=reason)
    _log("capture disabled", reason=reason, dir=directory,
         detail=detail)


def observe(trace, meta: dict | None, total_ms: float) -> None:
    """finish_request's capture hook: one record per completed
    request. No-op (one attribute check) when capture is off. Counter
    increments happen HERE, outside the ring lock — the telemetry
    registry lock must never nest inside capture.ring."""
    global WRITER
    w = WRITER
    if w is None:
        return
    if w.disabled_reason:
        # a seal hit disk-full: unbind the writer so the fast path
        # returns to one attribute check, and keep serving
        WRITER = None
        _disable(w.disabled_reason, w.dir, "seal failed")
        w.close()
        return
    segments_before = w._segments
    kept = w.append(record_from(trace, meta, total_ms))
    from . import telemetry
    if kept:
        telemetry.REGISTRY.counter_inc("ldt_capture_records_total")
    else:
        telemetry.REGISTRY.counter_inc("ldt_capture_sampled_out_total")
    if w._segments > segments_before:
        telemetry.REGISTRY.counter_inc("ldt_capture_segments_total")


def stats() -> dict | None:
    w = WRITER
    return w.stats() if w is not None else None


def reset_for_tests() -> None:
    global WRITER
    if WRITER is not None:
        WRITER.close()
    WRITER = None


# -- readers ----------------------------------------------------------------


def _decode(raw: bytes, off: int, wall_anchor: float,
            mono_anchor: int) -> dict:
    (arr_ns, thash, cache_bits, docs, deadline_ms, total_ms, parse_ms,
     detect_ms, encode_ms, status, sbucket, lane, verdict,
     flags) = RECORD.unpack_from(raw, off)
    return {
        "arrival_ns": int(wall_anchor * 1e9) + (arr_ns - mono_anchor),
        "arrival_mono_ns": arr_ns,
        "tenant": f"t{thash:016x}",
        "tenant_hash": thash,
        "cache_bits": cache_bits,
        "docs": docs,
        "deadline_ms": round(deadline_ms, 3),
        "total_ms": round(total_ms, 3),
        "parse_ms": round(parse_ms, 3),
        "detect_ms": round(detect_ms, 3),
        "encode_ms": round(encode_ms, 3),
        "status": status,
        "size_bucket": sbucket,
        "approx_bytes": (1 << max(sbucket - 1, 0)) if sbucket else 0,
        "lane": LANE_NAMES.get(lane, "tcp"),
        "verdict": VERDICT_NAMES.get(verdict, "ok"),
        "priority": bool(flags & FLAG_PRIORITY),
        "shed": bool(flags & FLAG_SHED),
    }


def _read_file(path: str) -> list:
    """Parse one ring or segment file into record dicts. A slot whose
    commit word is unset or wrong (the one write in flight at SIGKILL)
    is skipped, not fatal — the documented reader contract."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < FILE_HDR.size:
        raise ValueError(f"{path}: truncated capture file")
    magic, version, slots, rec_size, _pid, wall_anchor, mono_anchor = \
        FILE_HDR.unpack_from(data, 0)
    if magic not in (RING_MAGIC, SEG_MAGIC):
        raise ValueError(f"{path}: bad magic {magic!r}")
    if version != VERSION:
        raise ValueError(f"{path}: capture version {version} "
                         f"(reader speaks {VERSION})")
    if rec_size != RECORD.size:
        raise ValueError(f"{path}: record size {rec_size} "
                         f"(reader speaks {RECORD.size})")
    out: list = []
    if magic == SEG_MAGIC:
        for i in range(slots):
            off = FILE_HDR.size + i * RECORD.size
            if off + RECORD.size > len(data):
                break
            out.append(_decode(data, off, wall_anchor, mono_anchor))
        return out
    for i in range(slots):
        off = FILE_HDR.size + i * SLOT_BYTES
        if off + SLOT_BYTES > len(data):
            break
        (commit,) = COMMIT.unpack_from(data, off)
        if commit != i + 1:
            continue  # uncommitted / torn slot
        out.append(_decode(data, off + COMMIT.size, wall_anchor,
                           mono_anchor))
    return out


def read_capture(directory: str) -> list:
    """Every record in one capture directory (sealed segments + live or
    abandoned rings), sorted by wall-clock arrival. Unreadable files
    are skipped — a reader must survive whatever a crash left."""
    records: list = []
    for pattern in ("segment-*.cap", "capture-*.ring"):
        for path in sorted(glob.glob(os.path.join(directory, pattern))):
            try:
                records.extend(_read_file(path))
            except (OSError, ValueError):
                continue
    records.sort(key=lambda r: r["arrival_ns"])
    return records


def merge_captures(directory: str) -> list:
    """Records from a capture directory TREE — the fleet writes each
    member's capture under m<slot>/ — merged by wall-clock arrival
    time (the anchor pair in every file header makes per-process
    monotonic arrivals comparable). This is the replay input."""
    records: list = []
    seen: set = set()
    for pattern in ("**/segment-*.cap", "**/capture-*.ring"):
        for path in sorted(glob.glob(os.path.join(directory, pattern),
                                     recursive=True)):
            real = os.path.realpath(path)
            if real in seen:
                continue
            seen.add(real)
            try:
                records.extend(_read_file(path))
            except (OSError, ValueError):
                continue
    records.sort(key=lambda r: r["arrival_ns"])
    return records


def summarize(directory: str) -> dict:
    """Capture-dir summary for `debug.py --capture-summary`: file and
    record counts, the time span, and top tenants/lanes/statuses."""
    seg_files = glob.glob(os.path.join(directory, "**/segment-*.cap"),
                          recursive=True)
    ring_files = glob.glob(os.path.join(directory, "**/capture-*.ring"),
                           recursive=True)
    records = merge_captures(directory)
    tenants: dict = {}
    lanes: dict = {}
    statuses: dict = {}
    sheds = 0
    for r in records:
        tenants[r["tenant"]] = tenants.get(r["tenant"], 0) + 1
        lanes[r["lane"]] = lanes.get(r["lane"], 0) + 1
        statuses[r["status"]] = statuses.get(r["status"], 0) + 1
        if r["shed"]:
            sheds += 1
    span_sec = 0.0
    if len(records) >= 2:
        span_sec = (records[-1]["arrival_ns"]
                    - records[0]["arrival_ns"]) / 1e9
    top = sorted(tenants.items(), key=lambda kv: -kv[1])[:10]
    return {"dir": directory,
            "segments": len(seg_files),
            "rings": len(ring_files),
            "records": len(records),
            "span_sec": round(span_sec, 3),
            "sheds": sheds,
            "tenants": len(tenants),
            "top_tenants": [{"tenant": t, "records": n}
                            for t, n in top],
            "lanes": lanes,
            "statuses": {str(k): v for k, v in sorted(statuses.items())}}
