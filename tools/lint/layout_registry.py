"""Layout-registry analyzer: every on-wire/on-disk record, declared once.

PRs 10-17 grew five hand-rolled binary protocols (UDS frames, shm ring
slots, flightrec/capture rings, seqlock shared-cache slots) plus the
AOT bundle and the artifact footer. Each format lives as a bare
``struct.Struct`` in its module, and the byte-compat / crash-safety
claims in docs rest on nothing but convention. LAYOUTS below is the
single source of truth: name, declaring module, struct format, pinned
byte width, field names, magic/version, the commit/seq word (if any),
and the declared writer/reader functions. Three rules keep the code
and the registry from drifting — both ways:

  layout-undeclared   a struct.Struct / struct.pack* / struct.unpack*
                      call site in a protocol file whose format string
                      is not a declared layout (new records must be
                      registered before they ship bytes)
  layout-drift        the declared Struct no longer matches the
                      registry format, the format no longer calcsizes
                      to the pinned v1/v2 byte width, the module's
                      import-time width assert is missing or wrong, or
                      the generated layout table in
                      docs/OBSERVABILITY.md is stale
                      (``--write-layout-docs`` regenerates it)
  layout-reader-writer-mismatch
                      a declared writer/reader no longer packs/unpacks
                      its layout, or a function packs/unpacks a layout
                      without being declared — a reader whose format
                      disagrees with its paired writer shows up here
                      or as layout-undeclared before it ships

The commit-word fields (``commit``/``seqlock``/``pub_writers``/
``guard_readers``) additionally drive tools/lint/publish_order.py and
the torn-write model-check products (tools/lint/torn_write.py).
"""
from __future__ import annotations

import ast
import dataclasses
import struct
from pathlib import Path

from .base import (Violation, apply_suppressions, load_source,
                   repo_root)

DOCS_REL = "docs/OBSERVABILITY.md"
MARK_BEGIN = "<!-- ldt-layout-table:begin -->"
MARK_END = "<!-- ldt-layout-table:end -->"

_PACK_METHODS = frozenset({"pack", "pack_into"})
_UNPACK_METHODS = frozenset({"unpack", "unpack_from", "iter_unpack"})


@dataclasses.dataclass(frozen=True)
class Layout:
    """One binary record format. ``writers``/``readers`` entries are
    ``"<repo-relative file>::<qualname>"`` where qualname collapses to
    the topmost enclosing def (``Class.method``, ``function``, or
    ``<module>``)."""
    name: str
    file: str            # declaring module (repo-relative)
    var: str | None      # module-level Struct name; None = inline fmt
    fmt: str             # struct format; "{}" marks a dynamic count
    size: int | None     # pinned byte width (None only when dynamic)
    fields: tuple
    doc: str
    magic: str = ""
    version: str = ""
    commit: str = ""     # commit/seq/state field name ("" = none)
    seqlock: bool = False
    # how the commit word is stored: a 4-byte slice at the record base
    # (mm[base:base+4] = ...) and/or a dedicated Struct packed at base
    commit_slice: bool = False
    commit_struct: str = ""
    crc_span: str = ""
    writers: tuple = ()
    readers: tuple = ()
    # publish-order analyzer inputs (commit layouts only): the writer
    # functions whose store order is proven, the reader functions that
    # must re-validate the commit word, and helper callables whose
    # return value IS the commit word (e.g. sharedcache._seq)
    pub_writers: tuple = ()
    guard_readers: tuple = ()
    read_helpers: tuple = ()


_W = "language_detector_tpu/service/wire.py"
_AIO = "language_detector_tpu/service/aioserver.py"
_S = "language_detector_tpu/service/shmring.py"
_H = "language_detector_tpu/service/sharedcache.py"
_F = "language_detector_tpu/flightrec.py"
_C = "language_detector_tpu/capture.py"
_A = "language_detector_tpu/aot.py"
_R = "language_detector_tpu/artifact.py"

# the protocol files this analyzer scans (aioserver reaches wire's
# frame structs by attribute, so it is part of the conformance plane)
SCAN_FILES = (_W, _AIO, _S, _H, _F, _C, _A, _R)

# module-name -> declaring file, for cross-module uses like
# ``wire.FRAME_HEADER.unpack`` in aioserver.py
MODULE_FILES = {"wire": _W}

LAYOUTS: tuple = (
    # -- UDS frame lane (service/wire.py; network byte order) --------
    Layout(
        "uds-frame-len", _W, "FRAME_HEADER", "!I", 4, ("length",),
        "v1 request frame length prefix; v2 sets FRAME_V2_FLAG in the "
        "same word and appends the ext header",
        version="v1/v2",
        writers=(f"{_W}::pack_frame",),
        readers=(f"{_W}::UnixFrameServer._serve_conn",
                 f"{_AIO}::AioService.handle_uds")),
    Layout(
        "uds-resp-header", _W, "FRAME_RESP_HEADER", "!IH", 6,
        ("length", "status"),
        "response frame header: body length (v2 sets FRAME_V2_FLAG) "
        "and HTTP-equivalent status",
        version="v1/v2",
        writers=(f"{_W}::send_frame",
                 f"{_AIO}::AioService.handle_uds"),
        readers=(f"{_W}::recv_response_frame",)),
    Layout(
        "uds-ext-header", _W, "FRAME_EXT_HEADER", "!BHI", 7,
        ("flags", "tenant_len", "deadline_ms"),
        "v2 extension header: flag bits (priority/reqid/crc/spans), "
        "tenant byte length, deadline budget ms",
        version="v2",
        writers=(f"{_W}::pack_frame",),
        readers=(f"{_W}::UnixFrameServer._serve_conn",
                 f"{_AIO}::AioService.handle_uds")),
    Layout(
        "uds-crc-word", _W, "FRAME_CRC_WORD", "!I", 4, ("crc32",),
        "optional v2 body CRC (FRAME_CRC flag, LDT_WIRE_CRC)",
        version="v2", crc_span="frame body",
        writers=(f"{_W}::pack_frame",),
        readers=(f"{_W}::UnixFrameServer._serve_conn",)),
    # -- shm ingest ring (service/shmring.py) ------------------------
    Layout(
        "shm-ring-header", _S, "RING_HDR", "<IIIIII Q", 32,
        ("magic", "version", "generation", "slots", "client_pid",
         "worker_pid", "slot_bytes"),
        "ring file header; generation fences stale attachments",
        magic="0x5253444C", version="1",
        writers=(f"{_S}::RingFile.__init__",
                 f"{_S}::RingFile.set_generation"),
        readers=(f"{_S}::RingFile.__init__",
                 f"{_S}::RingFile.generation",
                 f"{_S}::RingFile.client_pid",
                 f"{_S}::RingFile.worker_pid",
                 f"{_S}::RingFile.set_generation")),
    Layout(
        "shm-slot-header", _S, "SLOT_HDR", "<IIII d II", 32,
        ("state", "generation", "owner_pid", "request_id", "ts",
         "length", "status"),
        "per-slot header; the state word is the publication point "
        "(tail stored first, state word last)",
        commit="state", commit_slice=True,
        writers=(f"{_S}::RingFile.write_slot",),
        readers=(f"{_S}::RingFile.read_slot",
                 f"{_S}::RingFile.slot_request_id"),
        pub_writers=(f"{_S}::RingFile.write_slot",),
        guard_readers=(f"{_S}::RingClient._refresh",
                       f"{_S}::ShmRingServer._sweep_ring"),
        read_helpers=("read_slot",)),
    Layout(
        "shm-slot-crc-word", _S, None, "<I", 4, ("crc32",),
        "optional per-slot payload CRC right after the slot header "
        "(LDT_WIRE_CRC)",
        crc_span="slot payload",
        writers=(f"{_S}::RingFile.write_crc",),
        readers=(f"{_S}::RingFile.read_crc",)),
    # -- seqlock shared result cache (service/sharedcache.py) --------
    Layout(
        "sharedcache-file-header", _H, "_HEADER", "<8sIII", 20,
        ("magic", "version", "slot_count", "slot_bytes"),
        "cache file header, written once under flock at creation",
        magic='b"LDTSHC1\\n"', version="1",
        writers=(f"{_H}::SharedResultCache._attach",),
        readers=(f"{_H}::SharedResultCache._attach",)),
    Layout(
        "sharedcache-slot-header", _H, "_SLOT_HDR", "<IIQ16sII", 40,
        ("seq", "crc", "epoch", "key", "vlen", "pad"),
        "seqlock slot header: odd seq claims, even seq publishes; "
        "readers re-check seq + epoch + CRC before trusting payload",
        commit="seq", seqlock=True, commit_struct="_U32",
        crc_span="epoch+key+vlen+payload",
        writers=(f"{_H}::SharedResultCache.put",
                 f"{_H}::SharedResultCache.set_epoch"),
        readers=(f"{_H}::SharedResultCache.get",
                 f"{_H}::SharedResultCache.put",
                 f"{_H}::SharedResultCache.set_epoch"),
        pub_writers=(f"{_H}::SharedResultCache.put",
                     f"{_H}::SharedResultCache.set_epoch"),
        guard_readers=(f"{_H}::SharedResultCache.get",
                       f"{_H}::SharedResultCache.put",
                       f"{_H}::SharedResultCache.set_epoch"),
        read_helpers=("_seq",)),
    Layout(
        "sharedcache-seq-word", _H, "_U32", "<I", 4, ("seq",),
        "bare seq-word view of the slot header, for the claim/publish "
        "stores and the reader's revalidation reads",
        writers=(f"{_H}::SharedResultCache.put",
                 f"{_H}::SharedResultCache.set_epoch"),
        readers=(f"{_H}::SharedResultCache._seq",)),
    Layout(
        "sharedcache-crc-span", _H, None, "<Q16sI", 28,
        ("epoch", "key", "vlen"),
        "CRC input material (never lands on disk as-is): the crc field "
        "covers epoch+key+vlen prefix plus the payload bytes",
        crc_span="epoch+key+vlen+payload",
        writers=(f"{_H}::SharedResultCache._crc",),
        readers=()),
    # -- flight recorder ring (flightrec.py) -------------------------
    Layout(
        "flightrec-file-header", _F, "FILE_HDR", "<4sIIIId", 28,
        ("magic", "version", "slots", "slot_bytes", "pid", "start_ts"),
        "recorder file header, written once at ring creation",
        magic='b"LDFR"', version="1",
        writers=(f"{_F}::FlightRecorder.__init__",),
        readers=(f"{_F}::read_ring",)),
    Layout(
        "flightrec-slot-header", _F, "SLOT_HDR", "<IId", 16,
        ("seq", "length", "ts"),
        "per-event slot header; the seq word is the publication point "
        "and is zeroed before a wrapped slot is rewritten",
        commit="seq", commit_slice=True,
        writers=(f"{_F}::FlightRecorder.emit",),
        readers=(f"{_F}::read_ring",),
        pub_writers=(f"{_F}::FlightRecorder.emit",),
        guard_readers=(f"{_F}::read_ring",)),
    # -- traffic capture ring (capture.py) ---------------------------
    Layout(
        "capture-file-header", _C, "FILE_HDR", "<4sIIIIdQ", 36,
        ("magic", "version", "slots", "record_size", "pid",
         "wall_anchor", "mono_anchor_ns"),
        "ring/segment file header; for sealed segments the slots "
        "field is the committed record count",
        magic='b"LDCR" / b"LDCS"', version="1",
        writers=(f"{_C}::CaptureWriter.__init__",
                 f"{_C}::CaptureWriter._seal_locked"),
        readers=(f"{_C}::_read_file",)),
    Layout(
        "capture-commit-word", _C, "COMMIT", "<I", 4, ("commit",),
        "per-slot commit word (slot index + 1), stored after the "
        "record payload",
        commit="commit", commit_slice=True,
        writers=(f"{_C}::CaptureWriter.append",),
        readers=(f"{_C}::CaptureWriter._seal_locked",
                 f"{_C}::_read_file"),
        pub_writers=(f"{_C}::CaptureWriter.append",),
        guard_readers=(f"{_C}::CaptureWriter._seal_locked",
                       f"{_C}::_read_file")),
    Layout(
        "capture-record", _C, "RECORD", "<QQQIfffffHBBBB", 54,
        ("arrival_mono_ns", "tenant_hash", "cache_bits", "docs",
         "deadline_ms", "total_ms", "parse_ms", "detect_ms",
         "encode_ms", "status", "size_bucket", "lane", "verdict",
         "flags"),
        "one anonymized request shape (docs/OBSERVABILITY.md)",
        writers=(f"{_C}::CaptureWriter.append",),
        readers=(f"{_C}::_decode",)),
    # -- AOT executable bundle (aot.py) ------------------------------
    Layout(
        "aot-section-len", _A, "_LEN", "<Q", 8, ("length",),
        "length prefix for each bundle section (meta/HLO/executable)",
        magic='b"LDTAOT1\\n"', version="1",
        writers=(f"{_A}::_pack_entry",),
        readers=(f"{_A}::_unpack_entry",)),
    Layout(
        "aot-entry-crc", _A, "_CRC", "<I", 4, ("crc32",),
        "entry trailer CRC over every section after the magic",
        crc_span="all sections after magic",
        writers=(f"{_A}::_pack_entry",),
        readers=(f"{_A}::_unpack_entry",)),
    # -- packed model artifact (artifact.py) -------------------------
    Layout(
        "artifact-header", _R, "_HDR", "<IIII QQ", 32,
        ("magic", "version", "n_arrays", "flags", "header_bytes",
         "total_bytes"),
        "artifact file header; total_bytes pins the exact file size",
        magic="0x4154444C", version="1",
        writers=(f"{_R}::write_artifact",),
        readers=(f"{_R}::load_artifact", f"{_R}::artifact_digest")),
    Layout(
        "artifact-descriptor", _R, "_DESC", "<48s8sI 4Q QQ", 108,
        ("name", "dtype", "ndim", "shape0", "shape1", "shape2",
         "shape3", "offset", "nbytes"),
        "per-array descriptor (name, dtype, shape, data extent)",
        writers=(f"{_R}::write_artifact",),
        readers=(f"{_R}::load_artifact",)),
    Layout(
        "artifact-footer", _R, "_FOOT", "<II", 8,
        ("magic", "n_digests"),
        "digest footer marker before the per-array CRC words",
        magic="0x4454444C",
        writers=(f"{_R}::write_artifact",),
        readers=(f"{_R}::load_artifact",)),
    Layout(
        "artifact-crc-words", _R, None, "<{}I", None, ("crc32[n]",),
        "per-array CRC32 words after the footer (FLAG_DIGESTS)",
        crc_span="per-array payload",
        writers=(f"{_R}::write_artifact",),
        readers=(f"{_R}::load_artifact",)),
)


def registry_sizes(rel: str, layouts=LAYOUTS) -> dict:
    """var -> pinned width for one module's static layouts — protocol
    modules assert against this at import time (via a literal the
    analyzer cross-checks, so the module never imports tools.lint)."""
    return {lay.var: lay.size for lay in layouts
            if lay.file == rel and lay.var and lay.size is not None}


def _fmt_key(fmt: str) -> str:
    """Normalize a format for matching: spaces are struct no-ops, and
    dynamic repeat counts collapse to the {} skeleton."""
    return fmt.replace(" ", "")


def _joined_skeleton(node: ast.JoinedStr) -> str | None:
    """f"<{n}I" -> "<{}I"; None when any literal part is non-str."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            if not isinstance(v.value, str):
                return None
            parts.append(v.value)
        elif isinstance(v, ast.FormattedValue):
            parts.append("{}")
    return "".join(parts)


def _fmt_of(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return _joined_skeleton(node)
    return None


@dataclasses.dataclass
class _Use:
    layout: "Layout | None"
    kind: str        # "pack" | "unpack"
    qual: str
    line: int


class _FileScan(ast.NodeVisitor):
    """One protocol file's declarations, struct call sites, and
    import-time width asserts, with topmost-def qualnames."""

    def __init__(self, sf, layouts):
        self.sf = sf
        self.layouts = layouts
        self.by_var = {lay.var: lay for lay in layouts
                       if lay.file == sf.rel and lay.var}
        self.by_fmt = {_fmt_key(lay.fmt): lay for lay in layouts
                       if lay.file == sf.rel and lay.var is None}
        self.decls: dict = {}     # var -> (fmt, line)
        self.asserts: dict = {}   # var -> (value, line)
        self.uses: list = []      # resolved _Use entries
        self.out: list = []       # violations
        self.fn_lines: dict = {}  # qualname -> def line
        self._stack: list = []    # enclosing (kind, name)

    # -- scope tracking ----------------------------------------------
    def _qual(self) -> str:
        names = [n for k, n in self._stack if k == "f"][:1]
        cls = [n for k, n in self._stack if k == "c"][:1]
        if not names:
            return "<module>"
        return ".".join(cls + names)

    def visit_ClassDef(self, node):
        self._stack.append(("c", node.name))
        self.generic_visit(node)
        self._stack.pop()

    def _visit_def(self, node):
        self._stack.append(("f", node.name))
        self.fn_lines.setdefault(self._qual(), node.lineno)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    # -- declarations and asserts ------------------------------------
    def visit_Assign(self, node):
        call = node.value
        if not self._stack and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(call, ast.Call) \
                and isinstance(call.func, ast.Attribute) \
                and call.func.attr == "Struct" \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "struct":
            fmt = _fmt_of(call.args[0]) if call.args else None
            if fmt is not None:
                self.decls[node.targets[0].id] = (fmt, node.lineno)
        self.generic_visit(node)

    def visit_Assert(self, node):
        t = node.test
        if not self._stack and isinstance(t, ast.Compare) \
                and len(t.ops) == 1 and isinstance(t.ops[0], ast.Eq) \
                and isinstance(t.left, ast.Attribute) \
                and t.left.attr == "size" \
                and isinstance(t.left.value, ast.Name) \
                and isinstance(t.comparators[0], ast.Constant) \
                and isinstance(t.comparators[0].value, int):
            self.asserts[t.left.value.id] = \
                (t.comparators[0].value, node.lineno)
        self.generic_visit(node)

    # -- call sites --------------------------------------------------
    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "Struct" and isinstance(f.value, ast.Name) \
                    and f.value.id == "struct" and self._stack:
                # module-level Struct assigns are handled in
                # visit_Assign; any other Struct() is an ad-hoc format
                self.out.append(Violation(
                    "layout-undeclared", self.sf.rel, node.lineno,
                    "ad-hoc struct.Struct: binary formats must be a "
                    "module-level Struct declared in "
                    "tools/lint/layout_registry.py"))
            elif f.attr in _PACK_METHODS or f.attr in _UNPACK_METHODS \
                    or f.attr == "calcsize":
                self._classify(node, f)
        self.generic_visit(node)

    def _classify(self, node, f):
        kind = "pack" if f.attr in _PACK_METHODS else "unpack"
        base = f.value
        if isinstance(base, ast.Name) and base.id == "struct":
            # bare struct.pack_into("fmt", ...) etc: inline format
            fmt = _fmt_of(node.args[0]) if node.args else None
            if fmt is None:
                self.out.append(Violation(
                    "layout-undeclared", self.sf.rel, node.lineno,
                    f"struct.{f.attr} with a non-literal format: "
                    f"formats must be registry-declared literals"))
                return
            lay = self.by_fmt.get(_fmt_key(fmt))
            if lay is None:
                self.out.append(Violation(
                    "layout-undeclared", self.sf.rel, node.lineno,
                    f"struct format {fmt!r} is not a declared layout "
                    f"of {self.sf.rel} "
                    f"(tools/lint/layout_registry.py)"))
                return
            if f.attr != "calcsize":
                self.uses.append(
                    _Use(lay, kind, self._qual(), node.lineno))
            return
        var = mod = None
        if isinstance(base, ast.Name):
            var = base.id
        elif isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name):
            var, mod = base.attr, base.value.id
        if var is None or not var[:1].isupper() and var[:1] != "_":
            return  # method named pack/unpack on a non-struct object
        if mod is not None:
            src = MODULE_FILES.get(mod)
            if src is None:
                return  # not a protocol module attribute
            lay = next((x for x in self.layouts
                        if x.file == src and x.var == var), None)
        else:
            lay = self.by_var.get(var)
            if lay is None and var not in self.decls:
                return  # local name, not a module-level Struct
        if lay is None:
            self.out.append(Violation(
                "layout-undeclared", self.sf.rel, node.lineno,
                f"{var}.{f.attr}: {var} is not a declared layout "
                f"(tools/lint/layout_registry.py)"))
            return
        if f.attr != "calcsize":
            self.uses.append(_Use(lay, kind, self._qual(), node.lineno))


def _check_file(sf, layouts, out: list, uses: dict, fn_lines: dict):
    scan = _FileScan(sf, layouts)
    scan.visit(sf.tree)
    out.extend(scan.out)
    fn_lines[sf.rel] = scan.fn_lines
    for u in scan.uses:
        uses.setdefault((u.layout.name, u.kind), {}).setdefault(
            f"{sf.rel}::{u.qual}", u.line)
    # declaration drift: the module Struct vs the registry, both ways
    mine = [lay for lay in layouts if lay.file == sf.rel and lay.var]
    for lay in mine:
        decl = scan.decls.get(lay.var)
        if decl is None:
            out.append(Violation(
                "layout-drift", sf.rel, 1,
                f"layout {lay.name!r}: module-level Struct "
                f"{lay.var} is declared in the registry but missing "
                f"from the module"))
            continue
        fmt, line = decl
        if _fmt_key(fmt) != _fmt_key(lay.fmt):
            out.append(Violation(
                "layout-drift", sf.rel, line,
                f"layout {lay.name!r}: module format {fmt!r} != "
                f"registry format {lay.fmt!r} — update "
                f"tools/lint/layout_registry.py (and bump the layout "
                f"version) or revert the field edit"))
        elif lay.size is not None \
                and struct.calcsize(fmt) != lay.size:
            out.append(Violation(
                "layout-drift", sf.rel, line,
                f"layout {lay.name!r}: format {fmt!r} is "
                f"{struct.calcsize(fmt)} bytes but the registry pins "
                f"{lay.size} — byte compatibility is versioned, not "
                f"incidental"))
        if lay.size is not None:
            a = scan.asserts.get(lay.var)
            if a is None:
                out.append(Violation(
                    "layout-drift", sf.rel, line,
                    f"layout {lay.name!r}: missing import-time width "
                    f"assert — add `assert {lay.var}.size == "
                    f"{lay.size}` so a drive-by field edit fails at "
                    f"import, not by corrupting rings"))
            elif a[0] != lay.size:
                out.append(Violation(
                    "layout-drift", sf.rel, a[1],
                    f"layout {lay.name!r}: import-time assert pins "
                    f"{a[0]} bytes but the registry declares "
                    f"{lay.size}"))
    # module-level Structs the registry does not know about
    for var, (fmt, line) in scan.decls.items():
        if not any(lay.var == var for lay in mine):
            out.append(Violation(
                "layout-undeclared", sf.rel, line,
                f"module-level Struct {var} ({fmt!r}) is not declared "
                f"in tools/lint/layout_registry.py"))


def _check_conformance(layouts, scope: set, out: list, uses: dict,
                       fn_lines: dict):
    """Both-ways writer/reader conformance over the scanned scope."""
    for lay in layouts:
        for kind, declared in (("pack", lay.writers),
                               ("unpack", lay.readers)):
            seen = uses.get((lay.name, kind), {})
            word = "writer" if kind == "pack" else "reader"
            verb = "packs" if kind == "pack" else "unpacks"
            for entry in declared:
                rel, _, qual = entry.partition("::")
                if rel not in scope:
                    continue
                if entry in seen:
                    continue
                line = fn_lines.get(rel, {}).get(qual, 1)
                out.append(Violation(
                    "layout-reader-writer-mismatch", rel, line,
                    f"declared {word} {qual} no longer {verb} layout "
                    f"{lay.name!r} — update the registry or restore "
                    f"the call"))
            for entry, line in sorted(seen.items()):
                if entry in declared:
                    continue
                rel, _, qual = entry.partition("::")
                out.append(Violation(
                    "layout-reader-writer-mismatch", rel, line,
                    f"{qual} {verb} layout {lay.name!r} but is not a "
                    f"declared {word} — declare it in "
                    f"tools/lint/layout_registry.py so the "
                    f"publish-order/torn-write contracts cover it"))


# -- generated docs table --------------------------------------------


def generated_table(root: Path | None = None, layouts=LAYOUTS) -> str:
    rows = ["| layout | module | format | bytes | magic | ver | "
            "commit word | CRC span |",
            "|---|---|---|---|---|---|---|---|"]
    for lay in sorted(layouts, key=lambda x: (x.file, x.name)):
        size = str(lay.size) if lay.size is not None else "dyn"
        commit = lay.commit or "—"
        if lay.seqlock:
            commit += " (seqlock)"
        rows.append(
            f"| `{lay.name}` | `{lay.file.rsplit('/', 1)[-1]}` "
            f"| `{lay.fmt}` | {size} | {lay.magic or '—'} "
            f"| {lay.version or '—'} | {commit} "
            f"| {lay.crc_span or '—'} |")
    return "\n".join(rows)


def _check_docs(root: Path, out: list):
    docs = root / DOCS_REL
    if not docs.exists():
        out.append(Violation("layout-drift", DOCS_REL, 1,
                             "docs/OBSERVABILITY.md is missing"))
        return
    text = docs.read_text()
    if MARK_BEGIN not in text or MARK_END not in text:
        out.append(Violation(
            "layout-drift", DOCS_REL, 1,
            f"layout-table markers ({MARK_BEGIN} / {MARK_END}) are "
            f"missing; the binary-layout table must be generated, "
            f"not hand-maintained"))
        return
    current = text.split(MARK_BEGIN, 1)[1].split(MARK_END, 1)[0].strip()
    if current != generated_table(root).strip():
        line = text[:text.index(MARK_BEGIN)].count("\n") + 1
        out.append(Violation(
            "layout-drift", DOCS_REL, line,
            "binary-layout table is stale; run "
            "`python -m tools.lint --write-layout-docs`"))


def write_layout_docs(root: Path | None = None) -> bool:
    """Regenerate the docs table in place. Returns True when the file
    changed."""
    root = root or repo_root()
    docs = root / DOCS_REL
    text = docs.read_text()
    head, _, rest = text.partition(MARK_BEGIN)
    _, _, tail = rest.partition(MARK_END)
    new = (head + MARK_BEGIN + "\n" + generated_table(root).strip()
           + "\n" + MARK_END + tail)
    if new != text:
        docs.write_text(new)
        return True
    return False


def check(root: Path | None = None, files=None, check_docs=True,
          layouts=LAYOUTS):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    rels = list(SCAN_FILES) if files is None else list(files)
    violations: list = []
    n_suppressed = 0
    uses: dict = {}
    fn_lines: dict = {}
    scope: set = set()
    for rel in rels:
        path = root / rel
        if not path.exists():
            continue
        sf = load_source(path, root)
        scope.add(sf.rel)
        file_violations: list = []
        _check_file(sf, layouts, file_violations, uses, fn_lines)
        kept, ns = apply_suppressions(sf, file_violations)
        violations.extend(kept)
        n_suppressed += ns
    _check_conformance(layouts, scope, violations, uses, fn_lines)
    if check_docs and files is None:
        _check_docs(root, violations)
    return violations, n_suppressed
