#!/bin/bash
# One-command CI: build natives -> verify artifacts -> tests -> entry
# checks -> bench smoke. Exits non-zero on the first failure.
set -euo pipefail
cd "$(dirname "$0")"

echo "== native build =="
language_detector_tpu/native/build.sh

if [ -d /root/reference/cld2 ] && [ ! -f tools/oracle/libcld2_oracle.so ]; then
    echo "== oracle build =="
    tools/oracle/build.sh
fi

echo "== artifact verify =="
python3 tools/artifact_tool.py --verify

echo "== tests =="
python3 -m pytest tests/ -q

echo "== graft entry =="
python3 __graft_entry__.py

echo "== bench smoke =="
python3 bench.py --smoke

echo "CI OK"
