"""Request batcher: many concurrent HTTP requests -> few large device
batches.

The reference calls the detector once per item inside the handler loop
(handlers.go:133-186, one cgo call each); the TPU redesign accumulates
items from all in-flight requests and dispatches them as one batch
(SURVEY.md §3.1), trading a small queueing delay for device efficiency.
A collector thread drains the queue, flushing when `max_batch` items are
pending or `max_delay_ms` has passed since the oldest undispatched item
arrived; flushes run on a small worker pool so batch N+1 accumulates and
dispatches while batch N is still in flight on the device — without
this, every flush pays the backend's full ~95ms dispatch latency
serially and HTTP throughput collapses to flush_size/latency.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import Future, ThreadPoolExecutor

# concurrent flushes: >= 3 reaches the TPU tunnel's dispatch-overlap
# ceiling (models/ngram.py _pipelined uses the same depth)
_FLUSH_WORKERS = 3


class Batcher:
    """Deadline/size-batched dispatcher over a detection engine."""

    def __init__(self, detect_fn, max_batch: int = 16384,
                 max_delay_ms: float = 5.0):
        self._detect = detect_fn          # list[str] -> list[results]
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(_FLUSH_WORKERS,
                                        thread_name_prefix="ldt-flush")
        # bound in-flight flushes so a backed-up device cannot pile
        # unbounded batches in memory
        self._slots = threading.Semaphore(_FLUSH_WORKERS + 1)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ldt-batcher")
        self._thread.start()

    def submit(self, texts: list) -> Future:
        """Queue one request's texts; resolves to their results (in
        order) once a batch containing them completes."""
        fut: Future = Future()
        self._q.put((texts, fut))
        return fut

    def close(self):
        self._stop.set()
        self._q.put(None)  # wake the collector
        self._thread.join(timeout=5)
        self._pool.shutdown(wait=True)

    # -- collector -----------------------------------------------------------

    def _run(self):
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                continue
            pending = [item]
            n = len(item[0])
            # accumulate until deadline or size cap
            import time
            deadline = time.monotonic() + self.max_delay
            while n < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                pending.append(nxt)
                n += len(nxt[0])
            # block for a flush slot, then submit. close() keeps the
            # pool alive until in-flight flushes finish (shutdown
            # wait=True after joining this thread), so a batch in hand
            # at shutdown still gets served; only a pool that is truly
            # gone fails the waiters instead of killing the collector.
            self._slots.acquire()
            try:
                self._pool.submit(self._flush, pending)
            except RuntimeError as e:  # pool shut down first
                self._slots.release()
                self._fail(pending, e)
                return

    @staticmethod
    def _fail(pending: list, err: Exception):
        for _, fut in pending:
            if not fut.cancelled():
                fut.set_exception(err)

    def _flush(self, pending: list):
        try:
            texts = [t for ts, _ in pending for t in ts]
            try:
                results = self._detect(texts)
            except Exception as e:  # noqa: BLE001 - fail every waiter
                for _, fut in pending:
                    if not fut.cancelled():
                        fut.set_exception(e)
                return
            i = 0
            for ts, fut in pending:
                if not fut.cancelled():
                    fut.set_result(results[i:i + len(ts)])
                i += len(ts)
        finally:
            self._slots.release()
