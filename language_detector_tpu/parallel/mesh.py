"""Data-parallel scaling over a jax device mesh.

The reference scales horizontally with one process per core behind a load
balancer (SURVEY §2.7: no distributed runtime of any kind); the TPU-native
equivalent is pure data parallelism: documents are embarrassingly parallel,
so the packed batch shards over a 1-D "batch" mesh axis via shard_map and
each device scores its slice with zero collectives. Tables (the model
weights, ~2MB) are replicated to every device.

Single-host meshes span ICI (v5e-8); multi-host deployments extend the same
axis over DCN via jax.distributed — the program is unchanged because no
cross-document communication exists. Collectives appear only in the eval
harness (accuracy reductions), where XLA inserts psums over the same axis.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.score import score_resolved_impl

BATCH_AXIS = "batch"


def batch_mesh(n_devices: int | None = None,
               devices: list | None = None) -> Mesh:
    """1-D data-parallel mesh over the first n available devices."""
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(devs, (BATCH_AXIS,))


def sharded_score_fn(mesh: Mesh):
    """Jitted score_resolved with the document axis sharded over the mesh.

    Tables replicate (in_specs P()); every wire leaf shards on its leading
    axis (to_wire builds the flat slot arrays with one shard row per
    device and shard-local doc_start offsets) except the L-carrier dummy,
    which replicates. The body is communication-free: all reductions are
    document-local."""
    wire_specs = dict(idx=P(BATCH_AXIS), chk=P(BATCH_AXIS),
                      doc_start=P(BATCH_AXIS), n_slots=P(BATCH_AXIS),
                      cmeta=P(BATCH_AXIS), cscript=P(BATCH_AXIS),
                      l_iota=P())
    fn = jax.shard_map(score_resolved_impl, mesh=mesh,
                       in_specs=(P(), wire_specs),
                       out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for host->device transfer of packed batch arrays."""
    return NamedSharding(mesh, P(BATCH_AXIS))
