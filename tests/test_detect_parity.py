"""Full-document detection parity: scalar engine vs the compiled oracle.

Both run the same table artifact (no quadgram tables in the snapshot), so
summary language, top-3, percents, and reliability must agree exactly.
"""
import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.registry import registry

from conftest import oracle_detect

TEXTS = [
    # CJK (unigram/bigram path is fully populated in the artifact)
    "国民の大多数が内閣を支持し、集団的自衛権の行使を認める判断を歓迎した。",
    "中华人民共和国是世界上人口最多的国家，拥有悠久的历史和丰富的文化。",
    "한국어는 한글을 사용하는 언어이며 대한민국의 공용어입니다. 한국어 텍스트와",
    "日本語のテキストです。東京は日本の首都であり、世界最大の都市圏です。",
    # Script-only (RTypeOne) languages
    "ελληνικά γλώσσα είναι πολύ όμορφη και έχει μεγάλη ιστορία",
    "ภาษาไทยเป็นภาษาที่สวยงามและมีประวัติศาสตร์ยาวนาน",
    "தமிழ் மொழி மிகவும் அழகான மொழி ஆகும்",
    "ქართული ენა ძალიან ლამაზია და აქვს დიდი ისტორია",
    # Latin/Cyrillic word-scored languages (octagram tables only)
    "This is a simple English sentence about the weather and the news.",
    "le monde est grand et la vie est belle pour tous les hommes",
    "das ist ein schöner Tag und die Sonne scheint hell über der Stadt",
    "Это советы помогут вам избежать проблем при покупке квартиры",
    "confiserie et de la chocolaterie des digues du fleuve",
    # Mixed scripts
    "国民の大多数が Some English mixed in. ещё немного по-русски тут",
    "हिन्दी भाषा में यह वाक्य लिखा गया है और यह सुंदर है",
    # Degenerate
    "12345 67890 !!! ???",
    "a",
    "",
    "   ",
]


@pytest.mark.parametrize("text", TEXTS)
def test_detect_parity(oracle, base_tables, text):
    code, lang_id, top3, reliable, tb = oracle_detect(oracle,
                                                      text.encode("utf-8"))
    r = detect_scalar(text, base_tables)
    mine_code = registry.code(r.summary_lang)
    mine_top3 = [(registry.code(l), p) for l, p in
                 zip(r.language3, r.percent3)]
    assert mine_code == code, (text, mine_code, code, top3, mine_top3)
    assert mine_top3 == [(c, p) for c, p, _ in top3], (text, mine_top3, top3)
    assert r.is_reliable == reliable, (text, r.is_reliable, reliable)
    assert r.text_bytes == tb, (text, r.text_bytes, tb)


def test_public_detect_fast_path_matches_scalar(base_tables):
    """The public detect() routes plain unhinted calls through the all-C
    pipeline (native detect_one_row); its full DetectionResult — summary,
    top-3, percents, normalized scores, reliability, text_bytes — must
    match the scalar engine document for document. Includes the
    squeeze / repeat / gate-retry constructions and a tier-2 budget doc."""
    from language_detector_tpu import native
    from language_detector_tpu.detector import (DetectionResult,
                                                LanguageDetector)
    if not native.available():
        pytest.skip("native library unavailable")
    det = LanguageDetector(tables=base_tables)
    texts = TEXTS + [
        "buy cheap now " * 400,
        "word " * 600,
        ("καλημέρα κόσμε 世界 " * 200).strip(),   # tier-2 budget ladder
        "🎉🎊", "\x00abc", "한국어 텍스트 \ud800 lone surrogate",
    ]
    for t in texts:
        got = det.detect(t)
        want = DetectionResult.from_scalar(
            detect_scalar(t, base_tables, registry, 0), registry)
        assert got == want, t[:50]
