"""SLO engine (round 17): spec parsing, rolling-window SLI math
checked against a scalar oracle, fake-clock window aging, the
multi-window burn-rate alert state machine (fires under sustained
burn, stays quiet on blips and near-empty windows, clears on
recovery, announces through the flight recorder), tenant-table
bounding, and the /sloz document shape.
"""
from __future__ import annotations

import pytest

from language_detector_tpu import flightrec, slo, telemetry
from language_detector_tpu.slo import (BREACH_BURN, MAX_TENANTS,
                                       OVERFLOW_TENANT, SLOW_FACTOR,
                                       SloEngine, parse_spec)


class FakeClock:
    """Injectable monotonic clock: window expiry and alert transitions
    run against controlled time."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, sec):
        self.t += sec


def _engine(spec="p99_ms=50,err_pct=1,window_sec=100", clock=None,
            min_events=1):
    return SloEngine(parse_spec(spec), clock=clock or FakeClock(),
                     min_events=min_events)


# -- spec parsing ------------------------------------------------------------


def test_parse_spec_full():
    s = parse_spec("p99_ms=50,err_pct=0.5,window_sec=300")
    assert s.percentile == 99.0
    assert s.target_ms == 50.0
    assert s.err_pct == 0.5
    assert s.window_sec == 300.0
    assert s.as_dict()["slow_window_sec"] == 300.0 * SLOW_FACTOR


def test_parse_spec_percentile_variants():
    assert parse_spec("p95_ms=20").percentile == 95.0
    assert parse_spec("p50_ms=5").target_ms == 5.0
    assert parse_spec("p99.9_ms=80").percentile == 99.9
    # error-budget-only spec: no latency target
    s = parse_spec("err_pct=2")
    assert s.target_ms is None and s.err_pct == 2.0


def test_parse_spec_malformed_entries_skipped(caplog):
    s = parse_spec("p99_ms=50,bogus,xyz=1,err_pct=nope,window_sec=-5")
    assert s is not None                     # the valid entry survives
    assert s.target_ms == 50.0
    assert s.err_pct == 1.0                  # default kept
    assert s.window_sec == 300.0             # negative rejected


def test_parse_spec_disabled():
    assert parse_spec(None) is None
    assert parse_spec("") is None
    assert parse_spec("   ") is None
    # a spec with no valid entry disables rather than defaulting
    assert parse_spec("garbage,more=junk") is None


# -- window math vs scalar oracle --------------------------------------------


def test_window_slis_match_scalar_oracle():
    clk = FakeClock()
    eng = _engine("p99_ms=50,err_pct=10,window_sec=100", clock=clk)
    lats = [1.0, 2.0, 5.0, 10.0, 20.0, 40.0, 60.0, 80.0, 3.0, 7.0]
    statuses = [200] * 8 + [500, 200]
    for lat, st in zip(lats, statuses):
        eng.observe("acme", st, lat)
        clk.advance(0.5)
    snap = eng.snapshot()
    fast = snap["fleet"]["fast"]
    # oracle: bad = 5xx or latency over the 50ms target (not shed)
    bad = sum(1 for lat, st in zip(lats, statuses)
              if st >= 500 or lat > 50.0)
    assert fast["count"] == len(lats)
    assert fast["bad"] == bad == 3
    assert fast["err_ratio"] == pytest.approx(bad / len(lats), abs=1e-6)
    assert fast["mean_ms"] == pytest.approx(sum(lats) / len(lats),
                                            abs=0.01)
    # percentile estimates land inside their log-bucket neighborhood
    assert fast["p50_ms"] == pytest.approx(7.0, rel=1.0)
    assert 40.0 <= fast["p99_ms"] <= 160.0
    # burn = err_ratio / (err_pct/100)
    assert fast["burn_rate"] == pytest.approx((bad / len(lats)) / 0.10,
                                              abs=1e-3)
    # the tenant window saw the same traffic
    assert snap["tenants"]["acme"]["fast"]["count"] == len(lats)
    assert snap["observed"] == len(lats)


def test_shed_tracked_but_does_not_burn():
    clk = FakeClock()
    eng = _engine("p99_ms=10,err_pct=1,window_sec=100", clock=clk)
    telemetry.REGISTRY.reset()
    try:
        # a shed answered 429 in 500ms: way over target, but sheds are
        # overload protection working — they never burn budget
        eng.observe("acme", 429, 500.0, shed=True)
        eng.observe("acme", 200, 1.0)
        snap = eng.snapshot()["fleet"]["fast"]
        assert snap["count"] == 2
        assert snap["shed"] == 1
        assert snap["bad"] == 0
        assert eng.stats()["burn_fast"] == 0.0
        reg = telemetry.REGISTRY
        assert reg.counter_value("ldt_slo_events_total",
                                 result="shed") == 1
        assert reg.counter_value("ldt_slo_events_total",
                                 result="good") == 1
    finally:
        telemetry.REGISTRY.reset()


def test_window_ages_out_on_fake_clock():
    clk = FakeClock()
    eng = _engine("p99_ms=50,err_pct=1,window_sec=100", clock=clk)
    for _ in range(10):
        eng.observe("acme", 200, 1.0)
    assert eng.snapshot()["fleet"]["fast"]["count"] == 10
    clk.advance(101.0)                       # past the fast window
    snap = eng.snapshot()["fleet"]
    assert snap["fast"]["count"] == 0
    # the 12x slow window still holds the history
    assert snap["slow"]["count"] == 10
    clk.advance(100.0 * SLOW_FACTOR)
    assert eng.snapshot()["fleet"]["slow"]["count"] == 0


# -- burn-rate alert state machine -------------------------------------------


def test_alert_fires_and_clears(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_FLIGHTREC_DIR", str(tmp_path))
    monkeypatch.setattr(flightrec, "RECORDER", None)
    rec = flightrec.init_from_env(role="slo-test")
    telemetry.REGISTRY.reset()
    clk = FakeClock()
    eng = _engine("p99_ms=50,err_pct=1,window_sec=100", clock=clk,
                  min_events=4)
    try:
        # sustained 100% errors: both windows burn far over 1.0
        for _ in range(8):
            eng.observe("acme", 500, 1.0)
            clk.advance(1.0)
        st = eng.stats()
        assert st["alert"] == 1
        assert st["breaches_total"] == 1
        assert st["burn_fast"] >= BREACH_BURN
        assert st["burn_slow"] >= BREACH_BURN
        assert st["budget_remaining"] == 0.0
        assert telemetry.REGISTRY.counter_value(
            "ldt_slo_breaches_total") == 1
        snap = eng.snapshot()
        assert snap["alert"]["state"] == "breach"
        assert snap["alert"]["since_sec"] >= 0
        # recovery: the error traffic stops and the fast window ages
        clk.advance(101.0)
        for _ in range(8):
            eng.observe("acme", 200, 1.0)
        st = eng.stats()
        assert st["alert"] == 0
        assert st["breaches_total"] == 1     # no re-fire
        assert eng.snapshot()["alert"]["state"] == "ok"
        evs = [e["ev"] for e in flightrec.read_ring(rec.path)["events"]]
        assert "slo_breach" in evs
        assert "slo_recovered" in evs
        assert evs.index("slo_breach") < evs.index("slo_recovered")
    finally:
        rec.close()
        monkeypatch.setattr(flightrec, "RECORDER", None)
        telemetry.REGISTRY.reset()


def test_alert_needs_min_events():
    clk = FakeClock()
    eng = _engine("p99_ms=50,err_pct=1,window_sec=100", clock=clk,
                  min_events=10)
    for _ in range(9):                       # one short of the floor
        eng.observe("acme", 500, 1.0)
    assert eng.stats()["alert"] == 0
    eng.observe("acme", 500, 1.0)            # the 10th event
    assert eng.stats()["alert"] == 1


def test_blip_does_not_fire_without_slow_burn():
    """The multi-window rule: a brand-new error burst whose slow
    window is still diluted by hours of good traffic cannot page."""
    clk = FakeClock()
    eng = _engine("p99_ms=50,err_pct=1,window_sec=100", clock=clk,
                  min_events=1)
    # a long healthy history fills the slow window
    for _ in range(600):
        eng.observe("acme", 200, 1.0)
        clk.advance(1.0)
    # a short blip: fast window burns, slow window barely moves
    for _ in range(3):
        eng.observe("acme", 500, 1.0)
    st = eng.stats()
    assert st["burn_fast"] >= BREACH_BURN
    assert st["burn_slow"] < BREACH_BURN
    assert st["alert"] == 0


def test_recovery_visible_without_traffic():
    """stats() runs the state machine too: after the fast window ages
    out, the alert clears even though no new request arrived."""
    telemetry.REGISTRY.reset()
    clk = FakeClock()
    eng = _engine("p99_ms=50,err_pct=1,window_sec=100", clock=clk,
                  min_events=1)
    try:
        for _ in range(4):
            eng.observe("acme", 500, 1.0)
        assert eng.stats()["alert"] == 1
        clk.advance(101.0)                   # fast window empties
        assert eng.stats()["alert"] == 0
    finally:
        telemetry.REGISTRY.reset()


# -- tenant bounding ---------------------------------------------------------


def test_tenant_table_bounded():
    clk = FakeClock()
    eng = _engine(clock=clk)
    for i in range(MAX_TENANTS + 20):
        eng.observe(f"tenant-{i}", 200, 1.0)
    snap = eng.snapshot()
    assert len(snap["tenants"]) == MAX_TENANTS + 1
    assert OVERFLOW_TENANT in snap["tenants"]
    assert snap["tenants"][OVERFLOW_TENANT]["fast"]["count"] == 20


def test_default_tenant():
    eng = _engine(clock=FakeClock())
    eng.observe(None, 200, 1.0)
    assert "default" in eng.snapshot()["tenants"]


# -- module wiring -----------------------------------------------------------


def test_init_from_env_and_sloz(monkeypatch):
    monkeypatch.setattr(slo, "ENGINE", None)
    monkeypatch.setenv("LDT_SLO", "")
    assert slo.init_from_env() is None
    doc = slo.sloz()
    assert doc["enabled"] is False and "hint" in doc
    assert slo.stats() is None
    monkeypatch.setenv("LDT_SLO", "p99_ms=50,err_pct=0.5")
    try:
        eng = slo.init_from_env()
        assert eng is not None
        assert slo.init_from_env() is eng    # idempotent
        doc = slo.sloz()
        assert doc["enabled"] is True
        assert doc["spec"]["target_ms"] == 50.0
        assert doc["alert"]["state"] == "ok"
    finally:
        slo.reset_for_tests()


def test_module_observe_reads_trace(monkeypatch):
    telemetry.REGISTRY.reset()
    clk = FakeClock()
    eng = _engine("p99_ms=50,err_pct=1,window_sec=100", clock=clk)
    monkeypatch.setattr(slo, "ENGINE", eng)
    try:
        tr = telemetry.Trace()
        tr.tenant = "acme"
        slo.observe(tr, {"status": 200}, 3.0)
        snap = eng.snapshot()
        assert snap["tenants"]["acme"]["fast"]["count"] == 1
    finally:
        monkeypatch.setattr(slo, "ENGINE", None)
        telemetry.REGISTRY.reset()
