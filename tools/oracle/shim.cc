// extern "C" shim over the reference CLD2 build, used ONLY by parity tests
// (tests/test_oracle_parity.py) through ctypes. Exposes the hash functions,
// the script-span scanner, and full-document detection so every layer of the
// TPU reimplementation can be validated against the original behavior.

#include <string.h>
#include <stdlib.h>

#include "integral_types.h"
#include "cldutil_shared.h"
#include "getonescriptspan.h"
#include "lang_script.h"
#include "compact_lang_det.h"
#include "encodings.h"

using namespace CLD2;

extern "C" {

// ---- hash parity ----------------------------------------------------------
// buf must have >=1 byte before pos and >=3 bytes after pos+len (the
// reference hashers read the pre/post byte for space indicators and
// overshoot up to 3 bytes).
unsigned int o_quadhash(const char* buf, int pos, int len) {
  return QuadHashV2(buf + pos, len);
}
unsigned long long o_octahash(const char* buf, int pos, int len) {
  return OctaHash40(buf + pos, len);
}
unsigned int o_bihash(const char* buf, int pos, int len) {
  return BiHashV2(buf + pos, len);
}
unsigned long long o_pairhash(unsigned long long a, unsigned long long b) {
  return PairHash(a, b);
}

// ---- script-span scanner parity ------------------------------------------
void* o_scanner_new(const char* text, int len, int is_plain_text) {
  return new ScriptScanner(text, len, is_plain_text != 0);
}
// Returns 1 and fills out/out_len/out_script while spans remain, else 0.
// out must hold >= 40960+8 bytes. Lowercased span text.
int o_scanner_next(void* handle, char* out, int* out_len, int* out_script) {
  ScriptScanner* ss = static_cast<ScriptScanner*>(handle);
  LangSpan span;
  if (!ss->GetOneScriptSpanLower(&span)) return 0;
  memcpy(out, span.text, span.text_bytes + 4);
  *out_len = span.text_bytes;
  *out_script = static_cast<int>(span.ulscript);
  return 1;
}
void o_scanner_free(void* handle) {
  delete static_cast<ScriptScanner*>(handle);
}

// ---- full-document detection parity --------------------------------------
// Returns summary language id; fills top-3 languages/percents/scores.
int o_detect(const char* text, int len, int is_plain_text, int flags,
             int* lang3, int* percent3, double* score3,
             int* text_bytes, int* is_reliable) {
  Language language3[3];
  int pct3[3];
  double ns3[3];
  int tb = 0;
  bool rel = false;
  CLDHints hints = {NULL, NULL, UNKNOWN_ENCODING, UNKNOWN_LANGUAGE};
  Language summary = ExtDetectLanguageSummary(
      text, len, is_plain_text != 0, &hints, flags,
      language3, pct3, ns3, NULL, &tb, &rel);
  for (int i = 0; i < 3; ++i) {
    lang3[i] = static_cast<int>(language3[i]);
    percent3[i] = pct3[i];
    score3[i] = ns3[i];
  }
  *text_bytes = tb;
  *is_reliable = rel ? 1 : 0;
  return static_cast<int>(summary);
}

const char* o_lang_code(int lang) {
  return LanguageCode(static_cast<Language>(lang));
}

}  // extern "C"

extern "C" {

// ---- hinted detection parity ---------------------------------------------
// Same as o_detect but with explicit CLDHints fields (NULL/empty = unset).
int o_detect_hints(const char* text, int len, int is_plain_text, int flags,
                   const char* content_language_hint, const char* tld_hint,
                   int encoding_hint, int language_hint,
                   int* lang3, int* percent3, double* score3,
                   int* text_bytes, int* is_reliable) {
  Language language3[3];
  int pct3[3];
  double ns3[3];
  int tb = 0;
  bool rel = false;
  CLDHints hints;
  hints.content_language_hint =
      (content_language_hint && content_language_hint[0]) ?
      content_language_hint : NULL;
  hints.tld_hint = (tld_hint && tld_hint[0]) ? tld_hint : NULL;
  hints.encoding_hint = encoding_hint;
  hints.language_hint = static_cast<Language>(language_hint);
  Language summary = ExtDetectLanguageSummary(
      text, len, is_plain_text != 0, &hints, flags,
      language3, pct3, ns3, NULL, &tb, &rel);
  for (int i = 0; i < 3; ++i) {
    lang3[i] = static_cast<int>(language3[i]);
    percent3[i] = pct3[i];
    score3[i] = ns3[i];
  }
  *text_bytes = tb;
  *is_reliable = rel ? 1 : 0;
  return static_cast<int>(summary);
}

}  // extern "C"

extern "C" {

// ---- result-chunk vector parity ------------------------------------------
// Fills up to max_chunks (offset, bytes, lang) triples; returns the count.
int o_detect_vector(const char* text, int len, int is_plain_text, int flags,
                    int* offsets, int* bytes, int* langs, int max_chunks) {
  Language language3[3];
  int pct3[3];
  double ns3[3];
  int tb = 0;
  bool rel = false;
  CLDHints hints = {NULL, NULL, UNKNOWN_ENCODING, UNKNOWN_LANGUAGE};
  ResultChunkVector vec;
  ExtDetectLanguageSummary(text, len, is_plain_text != 0, &hints, flags,
                           language3, pct3, ns3, &vec, &tb, &rel);
  int n = static_cast<int>(vec.size());
  if (n > max_chunks) n = max_chunks;
  for (int i = 0; i < n; ++i) {
    offsets[i] = static_cast<int>(vec[i].offset);
    bytes[i] = static_cast<int>(vec[i].bytes);
    langs[i] = static_cast<int>(vec[i].lang1);
  }
  return n;
}

}  // extern "C"
