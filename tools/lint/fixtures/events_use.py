"""Fixture: event emission sites for the event-registry analyzer."""


def record(flightrec):
    flightrec.emit_event("fix_used", role="test")
    flightrec.emit_event("fix_undoc", role="test")
    flightrec.emit_event("fix_rogue", role="test")  # never declared
