"""Runtime config plane: guarded apply of mutable knobs with
SLO-watched probation and auto-rollback.

POST /configz on either front's metrics port stages a batch of mutable
knob overrides (validated against the knobs registry's type/bound/
mrange contract), applies it under a probation window, and watches the
SLO engine's fast-window burn rate: a burn >= 1.0 before the window
elapses auto-rolls the batch back to the prior overrides. Every edge
is journaled to the flight recorder (config_staged / config_applied /
config_committed / config_rolled_back) and counted in
ldt_config_applies_total, so a rollback is reconstructible after the
fact.

The plane is a declared state machine (tools/lint/fsm_registry.py
"config-plane") and its apply/crash interleavings are model-checked
(tools/lint/model_check.py "config-apply"):

    IDLE -> STAGED -> PROBATION -> COMMITTED
                 \\            \\-> ROLLED_BACK -> STAGED (next push)
                  \\-> IDLE (validation refused)

Probation progress is driven by tick(): the fronts call it from
telemetry.finish_request (per completed request) and from every GET
/configz (the fleet's canary poll), so a probation window expires even
on an idle member. The clock and the burn source are injectable for
the model checker — production uses time.monotonic and the SLO
engine's fast burn.
"""
from __future__ import annotations

import json
import logging
import time

from . import flightrec, knobs, telemetry
from .locks import make_lock

_log = logging.getLogger(__name__)

CONFIG_IDLE = 0
CONFIG_STAGED = 1
CONFIG_PROBATION = 2
CONFIG_COMMITTED = 3
CONFIG_ROLLED_BACK = 4

STATE_NAMES = {
    CONFIG_IDLE: "idle",
    CONFIG_STAGED: "staged",
    CONFIG_PROBATION: "probation",
    CONFIG_COMMITTED: "committed",
    CONFIG_ROLLED_BACK: "rolled_back",
}

# the auto-rollback trigger: fast-window error-budget burn at or past
# this during probation reverts the apply (1.0 = burning exactly the
# declared budget)
ROLLBACK_BURN = 1.0


def _slo_fast_burn() -> float | None:
    """Production burn source: the SLO engine's fast-window burn rate,
    None when the engine is off (probation then commits on time
    alone)."""
    from . import slo
    s = slo.stats()
    if s is None:
        return None
    return float(s.get("burn_fast", 0.0))


class ConfigPlane:
    """One process's config-apply state machine (thread-safe)."""

    def __init__(self, clock=time.monotonic, burn_source=_slo_fast_burn):
        self._lock = make_lock("configplane.plane")
        self.clock = clock
        self.burn_source = burn_source
        self.state = CONFIG_IDLE
        self.generation = 0            # last COMMITTED generation
        self.staged_generation = 0     # generation of the in-flight batch
        self.staged: dict | None = None
        self.staged_probation_sec = 0.0
        self.prior: dict | None = None  # raw override map pre-apply
        self.probation_deadline = 0.0
        self.peak_burn = 0.0
        self.last_error: str | None = None
        self.last_rollback: dict | None = None

    # -- guarded FSM writes (fsm_registry "config-plane") -------------

    def mark_staged(self) -> None:
        if self.state == CONFIG_IDLE:
            self.state = CONFIG_STAGED
        elif self.state == CONFIG_COMMITTED:
            self.state = CONFIG_STAGED
        elif self.state == CONFIG_ROLLED_BACK:
            self.state = CONFIG_STAGED

    def mark_idle(self) -> None:
        if self.state == CONFIG_STAGED:
            self.state = CONFIG_IDLE

    def mark_probation(self) -> None:
        if self.state == CONFIG_STAGED:
            self.state = CONFIG_PROBATION

    def mark_committed(self) -> None:
        if self.state == CONFIG_PROBATION:
            self.state = CONFIG_COMMITTED

    def mark_rolled_back(self) -> None:
        if self.state == CONFIG_PROBATION:
            self.state = CONFIG_ROLLED_BACK

    # -- apply path ---------------------------------------------------

    def push(self, updates: dict, probation_sec: float | None = None,
             generation: int | None = None) -> dict:
        """Stage + apply one override batch. Returns the post-apply
        snapshot; on refusal the snapshot carries an "error" key and
        nothing was applied. `generation` stamps an externally
        coordinated generation (the fleet fan-out); local pushes
        auto-increment. probation_sec <= 0 commits immediately (used to
        fan a canary-proven config out to the rest of the fleet)."""
        if probation_sec is None:
            probation_sec = knobs.get_float(
                "LDT_CONFIG_PROBATION_SEC") or 0.0
        with self._lock:
            if self.state == CONFIG_PROBATION:
                snap = self._snapshot_locked()
                snap["error"] = "a config probation is already in flight"
                return snap
            self.staged = dict(updates)
            self.staged_probation_sec = float(probation_sec)
            self.staged_generation = (int(generation) if generation
                                      is not None
                                      else self.generation + 1)
            self.mark_staged()
            flightrec.emit_event(
                "config_staged", generation=self.staged_generation,
                knobs=",".join(sorted(self.staged)))
            return self._apply_locked()

    def _apply_locked(self) -> dict:
        self.prior = knobs.current()["overrides"]
        try:
            knobs.apply_overrides(self.staged or {})
        except ValueError as e:
            self.last_error = str(e)
            telemetry.REGISTRY.counter_inc(
                "ldt_config_applies_total", result="refused")
            _log.warning("configz: apply refused — %s", e)
            self.mark_idle()
            snap = self._snapshot_locked()
            snap["error"] = self.last_error
            return snap
        self.last_error = None
        self.peak_burn = 0.0
        self.probation_deadline = (self.clock()
                                   + self.staged_probation_sec)
        self.mark_probation()
        telemetry.REGISTRY.counter_inc(
            "ldt_config_applies_total", result="applied")
        flightrec.emit_event(
            "config_applied", generation=self.staged_generation,
            probation_sec=self.staged_probation_sec)
        if self.staged_probation_sec <= 0:
            self._commit_locked()
        return self._snapshot_locked()

    def tick(self, now: float | None = None) -> None:
        """Advance a probation: roll back on burn, commit on time.
        Called per completed request and per GET /configz — cheap when
        nothing is in probation."""
        with self._lock:
            if self.state != CONFIG_PROBATION:
                return
            burn = None
            try:
                burn = self.burn_source()
            except Exception:  # a sick burn source must not wedge
                pass           # probation: the window still times out
            if burn is not None and burn > self.peak_burn:
                self.peak_burn = burn
            if burn is not None and burn >= ROLLBACK_BURN:
                self._rollback_locked(
                    f"slo fast burn {burn:.2f} >= {ROLLBACK_BURN:g} "
                    f"during probation")
                return
            if (now if now is not None else self.clock()) \
                    >= self.probation_deadline:
                self._commit_locked()

    def _commit_locked(self) -> None:
        self.mark_committed()
        self.generation = self.staged_generation
        telemetry.REGISTRY.counter_inc(
            "ldt_config_applies_total", result="committed")
        flightrec.emit_event("config_committed",
                             generation=self.generation)
        _log.info("configz: generation %d committed", self.generation)
        self.staged = None
        self.prior = None

    def _rollback_locked(self, reason: str) -> None:
        knobs.clear_overrides()
        if self.prior:
            # the prior overrides were live, so they re-validate
            knobs.apply_overrides(self.prior)
        self.last_rollback = {
            "generation": self.staged_generation,
            "reason": reason,
            "peak_burn": round(self.peak_burn, 4),
            "values": dict(self.staged or {}),
        }
        self.mark_rolled_back()
        telemetry.REGISTRY.counter_inc(
            "ldt_config_applies_total", result="rolled_back")
        flightrec.emit_event(
            "config_rolled_back", generation=self.staged_generation,
            reason=reason)
        _log.warning("configz: generation %d rolled back — %s",
                     self.staged_generation, reason)
        self.staged = None
        self.prior = None

    # -- observability ------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        cur = knobs.current()
        remaining = 0.0
        if self.state == CONFIG_PROBATION:
            remaining = max(0.0, self.probation_deadline - self.clock())
        return {
            "state": STATE_NAMES.get(self.state, "?"),
            "generation": self.generation,
            "staged_generation": self.staged_generation,
            "override_version": cur["version"],
            "values": cur["values"],
            "overrides": cur["overrides"],
            "probation_remaining_sec": round(remaining, 3),
            "peak_burn": round(self.peak_burn, 4),
            "last_error": self.last_error,
            "last_rollback": self.last_rollback,
        }


# -- process singleton + front-facing helpers -------------------------

PLANE: ConfigPlane | None = None
_MODULE_LOCK = make_lock("configplane.module")


def get_plane() -> ConfigPlane:
    global PLANE
    p = PLANE
    if p is None:
        with _MODULE_LOCK:
            if PLANE is None:
                PLANE = ConfigPlane()
            p = PLANE
    return p


def maybe_tick() -> None:
    """Hot-path probation driver: one module-attribute check when no
    plane exists (no POST /configz ever landed)."""
    p = PLANE
    if p is not None:
        p.tick()


def stats() -> dict | None:
    """Config section for /debug/vars and the gauge renderers; None
    until the plane exists (gauges then render generation 0)."""
    p = PLANE
    return p.snapshot() if p is not None else None


def handle_get() -> dict:
    """GET /configz body (also drives probation forward — the fleet's
    canary poll rides this)."""
    p = get_plane()
    p.tick()
    return p.snapshot()


def handle_post(body: bytes) -> tuple[int, dict]:
    """POST /configz: {"set": {knob: value|null}, "probation_sec": s?,
    "generation": g?} -> (http status, response dict). Shared by both
    fronts so apply semantics cannot drift."""
    try:
        req = json.loads(body or b"{}")
        if not isinstance(req, dict):
            raise ValueError("body must be a JSON object")
        updates = req.get("set")
        if not isinstance(updates, dict) or not updates:
            raise ValueError('body must carry a non-empty "set" object')
        probation = req.get("probation_sec")
        if probation is not None:
            probation = float(probation)
        generation = req.get("generation")
        if generation is not None:
            generation = int(generation)
    except (ValueError, json.JSONDecodeError) as e:
        return 400, {"error": f"bad /configz request: {e}"}
    snap = get_plane().push(updates, probation_sec=probation,
                            generation=generation)
    if "error" in snap:
        status = 409 if "in flight" in snap["error"] else 400
        return status, snap
    return 200, snap


def reset_for_tests() -> None:
    global PLANE
    PLANE = None
    knobs.clear_overrides()
