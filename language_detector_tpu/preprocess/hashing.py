"""N-gram fingerprint functions, vectorized with numpy.

Bit-for-bit compatible with the reference hashes (cldutil_shared.cc:107-386):
the scoring tables in the artifact are keyed by these exact fingerprints, so
parity is mandatory. All functions take a span byte buffer plus arrays of
(pos, len) and return fingerprints for every gram at once.

Buffer contract (getonescriptspan.cc:678,1016-1021): spans start with one
space and end with "   \\0", so pos-1 and pos+len are always readable and
32-bit loads may overshoot up to 3 bytes past a gram.
"""
from __future__ import annotations

import numpy as np

_PRE_SPACE = np.uint32(0x00004444)   # cldutil_shared.cc:41
_POST_SPACE = np.uint32(0x44440000)  # cldutil_shared.cc:42

# Little-endian masks for 0..24 bytes picked up as uint32s (kWordMask0)
_WORD_MASK = np.array([0xFFFFFFFF, 0x000000FF, 0x0000FFFF, 0x00FFFFFF],
                      dtype=np.uint32)


def _load32(buf: np.ndarray, pos: np.ndarray) -> np.ndarray:
    """Unaligned little-endian 32-bit load at each pos (port.h semantics)."""
    b = buf.astype(np.uint32)
    return (b[pos] | (b[pos + 1] << np.uint32(8)) |
            (b[pos + 2] << np.uint32(16)) | (b[pos + 3] << np.uint32(24)))


def _prepost(buf: np.ndarray, pos: np.ndarray, length: np.ndarray) -> np.ndarray:
    """Word-boundary indicator bits from surrounding spaces."""
    pre = np.where(buf[pos - 1] == 0x20, _PRE_SPACE, np.uint32(0))
    post = np.where(buf[pos + length] == 0x20, _POST_SPACE, np.uint32(0))
    return pre | post


def quad_hash_v2(buf: np.ndarray, pos: np.ndarray,
                 length: np.ndarray) -> np.ndarray:
    """QuadHashV2 (cldutil_shared.cc:196): 1-12 bytes -> 32-bit fingerprint."""
    pos = np.asarray(pos, dtype=np.int64)
    length = np.asarray(length, dtype=np.int64)
    prepost = _prepost(buf, pos, length)
    mask = _WORD_MASK[length & 3]

    w0_a = _load32(buf, pos) & mask                    # 1..4 bytes
    w0_a ^= w0_a >> np.uint32(3)

    w0_b = _load32(buf, pos)                           # 5..8 bytes
    w0_b ^= w0_b >> np.uint32(3)
    w1_b = _load32(buf, pos + 4) & mask
    w1_b ^= w1_b << np.uint32(4)

    w1_c = _load32(buf, pos + 4)                       # 9..12 bytes
    w1_c ^= w1_c << np.uint32(4)
    w2_c = _load32(buf, pos + 8) & mask
    w2_c ^= w2_c << np.uint32(2)

    h4 = w0_a ^ prepost
    h8 = (w0_b ^ prepost) + w1_b
    h12 = (w0_b ^ prepost) + w1_c + w2_c
    out = np.where(length <= 4, h4, np.where(length <= 8, h8, h12))
    return np.where(length == 0, np.uint32(0), out)


# Per-4-byte-group mixing for OctaHash40 (cldutil_shared.cc:234-333):
# group g of the word is xor-shifted by these (negative = left shift).
_OCTA_SHIFTS = (3, -4, -2, 8, 4, 6)


def octa_hash40(buf: np.ndarray, pos: np.ndarray,
                length: np.ndarray) -> np.ndarray:
    """OctaHash40 (cldutil_shared.cc:348): 1-24 bytes -> 40-bit fingerprint.

    Low 32ish bits are the mixed word; bits 32-39 are a byte-sum checksum.
    Accumulation is 64-bit (the reference uses uint64 intermediates).
    """
    pos = np.asarray(pos, dtype=np.int64)
    length = np.asarray(length, dtype=np.int64)
    n = len(pos)
    prepost = _prepost(buf, pos, length).astype(np.uint64)
    mask = _WORD_MASK[length & 3].astype(np.uint64)
    ngroups = ((length - 1) >> 2).clip(0, 5)  # switch arm; >=21 bytes cap

    word0 = np.zeros(n, dtype=np.uint64)
    csum = np.zeros(n, dtype=np.uint64)
    for g, shift in enumerate(_OCTA_SHIFTS):
        active = ngroups >= g
        last = ngroups == g
        # Groups beyond the gram are discarded; clip their loads so short
        # test buffers without the full span tail pad stay in bounds.
        gpos = np.minimum(pos + 4 * g, len(buf) - 4)
        w = _load32(buf, gpos).astype(np.uint64)
        w = np.where(last, w & mask, w)
        csum = np.where(active, csum + w, csum)
        if shift > 0:
            mixed = w ^ (w >> np.uint64(shift))
        else:
            mixed = w ^ (w << np.uint64(-shift))
        word0 = np.where(active, word0 + mixed, word0)

    csum = csum + (csum >> np.uint64(17))
    csum = csum + (csum >> np.uint64(9))
    csum = (csum & np.uint64(0xFF)) << np.uint64(32)
    out = (word0 ^ prepost) + csum
    return np.where(length == 0, np.uint64(0), out)


def bi_hash_v2(buf: np.ndarray, pos: np.ndarray,
               length: np.ndarray) -> np.ndarray:
    """BiHashV2 (cldutil_shared.cc:107): CJK bigram, 1-8 bytes, no pre/post."""
    pos = np.asarray(pos, dtype=np.int64)
    length = np.asarray(length, dtype=np.int64)
    mask = _WORD_MASK[length & 3]

    w0_a = _load32(buf, pos) & mask
    w0_a ^= w0_a >> np.uint32(3)

    w0_b = _load32(buf, pos)
    w0_b ^= w0_b >> np.uint32(3)
    w1_b = _load32(buf, pos + 4) & mask
    w1_b ^= w1_b << np.uint32(18)

    out = np.where(length <= 4, w0_a, w0_b + w1_b)
    return np.where(length == 0, np.uint32(0), out)


def pair_hash(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """PairHash (cldutil_shared.cc:384): rotate(A,13) + B for word pairs."""
    a = np.asarray(a, dtype=np.uint64)
    b = np.asarray(b, dtype=np.uint64)
    return ((a >> np.uint64(13)) | (a << np.uint64(51))) + b


def quad_subscript_key(fp: np.ndarray, keymask: int,
                       bucketcount: int) -> tuple[np.ndarray, np.ndarray]:
    """32-bit FP -> (bucket subscript, key) (cldutil_shared.h:380-386)."""
    fp = np.asarray(fp, dtype=np.uint32)
    sub = (fp + (fp >> np.uint32(12))) & np.uint32(bucketcount - 1)
    return sub, fp & np.uint32(keymask)


def octa_subscript_key(fp: np.ndarray, keymask: int,
                       bucketcount: int) -> tuple[np.ndarray, np.ndarray]:
    """40-bit FP -> (bucket subscript, key) (cldutil_shared.h:389-397)."""
    fp = np.asarray(fp, dtype=np.uint64)
    sub = ((fp + (fp >> np.uint64(12))) &
           np.uint64(bucketcount - 1)).astype(np.uint32)
    key = (fp >> np.uint64(4)).astype(np.uint32) & np.uint32(keymask)
    return sub, key
